"""Launcher CLI — multi-process / multi-host bootstrap.

Analog of the reference's ``epl-launch``
(epl/utils/launcher.py:25-203): the reference synthesizes TF_CONFIG and
CUDA_VISIBLE_DEVICES per process, tails logs, kills stragglers and
retries once (:125-188).  The TPU-native equivalents:

  * cluster bootstrap is `jax.distributed.initialize` (coordinator
    address + process count + process id) — `init_distributed()` wraps it
    with env-var fallbacks (the launcher exports them per process);
  * local multi-process testing (the reference's 2-worker launcher test,
    tests/Makefile:12-13) spawns N processes on CPU with a shared
    coordinator;
  * straggler kill + single retry semantics are preserved.

Console entry: ``epl-tpu-launch --num_workers 2 -- python train.py``.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time
from typing import List, Optional

from easyparallellibrary_tpu.utils.logging import get_logger


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None):
  """Initialize multi-host JAX from args or EPL_LAUNCH_* env vars."""
  import jax
  coordinator_address = coordinator_address or os.environ.get(
      "EPL_COORDINATOR_ADDRESS")
  num_processes = num_processes or int(os.environ.get(
      "EPL_NUM_PROCESSES", "0")) or None
  process_id = process_id if process_id is not None else (
      int(os.environ["EPL_PROCESS_ID"])
      if "EPL_PROCESS_ID" in os.environ else None)
  if coordinator_address is None:
    get_logger().info("no coordinator configured; single-process run")
    return
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes,
      process_id=process_id,
      local_device_ids=local_device_ids)


def _free_port() -> int:
  with socket.socket() as s:
    s.bind(("", 0))
    return s.getsockname()[1]


def launch_local(num_workers: int, command: List[str],
                 retries: int = 1, log_dir: str = "",
                 extra_env: Optional[dict] = None) -> int:
  """Spawn `num_workers` local processes with distributed env wired up.

  Returns the exit code (0 = all workers succeeded).  On any worker
  failure, the remaining workers are killed and the whole job is retried
  up to `retries` times (reference launcher.py:168-188).
  """
  for attempt in range(retries + 1):
    port = _free_port()
    procs = []
    logs = []
    for rank in range(num_workers):
      env = dict(os.environ)
      env.update(extra_env or {})
      env["EPL_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
      env["EPL_NUM_PROCESSES"] = str(num_workers)
      env["EPL_PROCESS_ID"] = str(rank)
      stdout = None
      if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        logf = open(os.path.join(log_dir, f"worker_{rank}.log"), "w")
        logs.append(logf)
        stdout = logf
      procs.append(subprocess.Popen(
          command, env=env, stdout=stdout,
          stderr=subprocess.STDOUT if stdout else None))
    failed = False
    while procs:
      alive = []
      for p in procs:
        code = p.poll()
        if code is None:
          alive.append(p)
        elif code != 0:
          failed = True
      if failed:
        for p in alive:
          p.kill()  # kill stragglers (reference behavior)
        alive = []
      procs = alive
      if procs:
        time.sleep(0.2)
    for logf in logs:
      logf.close()
    if not failed:
      return 0
    get_logger().warning("worker failed (attempt %d/%d)", attempt + 1,
                         retries + 1)
  return 1


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      prog="epl-tpu-launch",
      description="Launch a multi-process training job "
                  "(reference: epl-launch)")
  parser.add_argument("--num_workers", type=int, default=1)
  parser.add_argument("--machine_rank", type=int, default=0,
                      help="rank of this machine (multi-host)")
  parser.add_argument("--coordinator", default="",
                      help="host:port of process 0 (multi-host)")
  parser.add_argument("--log_dir", default="")
  parser.add_argument("--retries", type=int, default=1)
  parser.add_argument("command", nargs=argparse.REMAINDER,
                      help="-- python train.py ...")
  args = parser.parse_args(argv)
  command = [c for c in args.command if c != "--"]
  if not command:
    parser.error("no command given; usage: epl-tpu-launch -- python ...")
  if args.coordinator:
    # Multi-host: this process IS one worker; export env and exec.
    os.environ["EPL_COORDINATOR_ADDRESS"] = args.coordinator
    os.environ["EPL_NUM_PROCESSES"] = str(args.num_workers)
    os.environ["EPL_PROCESS_ID"] = str(args.machine_rank)
    return subprocess.call(command)
  return launch_local(args.num_workers, command, retries=args.retries,
                      log_dir=args.log_dir)


if __name__ == "__main__":
  sys.exit(main())
