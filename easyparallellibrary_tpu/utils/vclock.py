"""Ambient virtual clock — the simulator's time seam.

Every policy object in the serving stack already takes an injectable
clock (``FCFSScheduler(clock=...)``, ``ServingStats(clock=...)``,
``Router(clock=...)``, ``ReplicaHealth(clock=...)``); the stragglers
were the *defaults* on observability objects built from config
(``slo.ensure_configured`` constructs an ``SLOMonitor`` and a
``DiagnosticCapture`` without threading a clock through).  This module
closes that gap: those defaults now route through :func:`monotonic` /
:func:`wall`, which pass straight to :mod:`time` until a simulation
calls :func:`install`.

The contract is deliberately minimal — two zero-argument callables and
a process-global install/reset pair — because the point is replay
determinism, not a scheduling framework: the discrete-event engine in
``easyparallellibrary_tpu/sim`` owns the virtual timeline and installs
itself here for the duration of an episode so that *config-built*
policy objects (which never saw a ``clock=`` kwarg) still read
simulated time.  Installation is idempotent per episode; always pair
with :func:`reset` (``try/finally``) so a crashed sim cannot leak a
frozen clock into live serving.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

# Process-global overrides.  ``None`` → passthrough to the real clocks.
_monotonic: Optional[Callable[[], float]] = None
_wall: Optional[Callable[[], float]] = None


def monotonic() -> float:
  """Monotonic seconds — ``time.monotonic`` unless a sim is installed."""
  fn = _monotonic
  return fn() if fn is not None else time.monotonic()


def wall() -> float:
  """Wall-clock seconds — ``time.time`` unless a sim is installed."""
  fn = _wall
  return fn() if fn is not None else time.time()


def installed() -> bool:
  """True while a virtual clock is installed (sim episode in flight)."""
  return _monotonic is not None or _wall is not None


def install(monotonic_fn: Optional[Callable[[], float]] = None,
            wall_fn: Optional[Callable[[], float]] = None) -> None:
  """Install virtual time sources.

  ``monotonic_fn`` backs :func:`monotonic`; ``wall_fn`` backs
  :func:`wall` and defaults to ``monotonic_fn`` (a simulated episode
  has one timeline — wall-stamped artifacts like slo_events then carry
  virtual seconds, which is what makes them replayable)."""
  global _monotonic, _wall
  _monotonic = monotonic_fn
  _wall = wall_fn if wall_fn is not None else monotonic_fn


def reset() -> None:
  """Drop any installed virtual clock (return to real time)."""
  global _monotonic, _wall
  _monotonic = None
  _wall = None
