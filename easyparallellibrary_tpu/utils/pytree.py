"""Pytree helpers (size accounting, path utilities).

Plays the role of the reference's ``epl/utils/common.py`` helpers, but for
pytrees instead of TF graph names.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def leaf_bytes(leaf) -> int:
  shape = getattr(leaf, "shape", ())
  dtype = getattr(leaf, "dtype", np.dtype("float32"))
  return int(np.prod(shape or (1,))) * jnp.dtype(dtype).itemsize


def tree_bytes(tree) -> int:
  return sum(leaf_bytes(l) for l in jax.tree_util.tree_leaves(tree))


def tree_param_count(tree) -> int:
  return sum(int(np.prod(getattr(l, "shape", ()) or (1,)))
             for l in jax.tree_util.tree_leaves(tree))


def path_str(path) -> str:
  """Render a jax key path as 'a/b/c'."""
  parts = []
  for p in path:
    if hasattr(p, "key"):
      parts.append(str(p.key))
    elif hasattr(p, "idx"):
      parts.append(str(p.idx))
    elif hasattr(p, "name"):
      parts.append(str(p.name))
    else:
      parts.append(str(p))
  return "/".join(parts)


def tree_paths_and_leaves(tree) -> List[Tuple[str, Any]]:
  flat, _ = jax.tree_util.tree_flatten_with_path(tree)
  return [(path_str(path), leaf) for path, leaf in flat]


def tree_map_with_path_str(fn: Callable[[str, Any], Any], tree):
  return jax.tree_util.tree_map_with_path(
      lambda path, leaf: fn(path_str(path), leaf), tree)


def split_micro_batches(batch, num_micro_batch: int):
  """[B, ...] -> [M, B/M, ...] on every leaf (micro-batch slicing shared
  by gradient accumulation and the pipeline schedules)."""
  def reshape(x):
    b = x.shape[0]
    if b % num_micro_batch != 0:
      raise ValueError(
          f"batch {b} not divisible by num_micro_batch {num_micro_batch}")
    return x.reshape((num_micro_batch, b // num_micro_batch) + x.shape[1:])
  return jax.tree_util.tree_map(reshape, batch)
