"""Logging setup (reference uses tf_logging throughout)."""

import logging
import os

_LOGGER = None


def get_logger() -> logging.Logger:
  global _LOGGER
  if _LOGGER is None:
    logger = logging.getLogger("epl_tpu")
    if not logger.handlers:
      handler = logging.StreamHandler()
      handler.setFormatter(logging.Formatter(
          "[epl-tpu %(levelname)s %(asctime)s] %(message)s", "%H:%M:%S"))
      logger.addHandler(handler)
    logger.setLevel(os.environ.get("EPL_LOG_LEVEL", "INFO"))
    logger.propagate = False
    _LOGGER = logger
  return _LOGGER
