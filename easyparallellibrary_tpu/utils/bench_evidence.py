"""Machine-readable benchmark evidence log.

The driver captures the official perf artifact by running ``bench.py``
once at the end of a round — but the remote-relay TPU backend can wedge
for hours, and has done so at capture time in both previous rounds,
recording 0.0 MFU while healthy-window measurements existed only as
prose in BASELINE.md.  This module fixes that asymmetry: every
successful hardware measurement made during a round appends a full raw
record (per-step wall times, null round-trip, config, timestamp) to
``BENCH_EVIDENCE.json`` at the repo root, and ``bench.py`` falls back to
the most recent auditable record — never to an unverifiable prose
number — when the backend is unreachable at capture time.

Reference analog: none (BASELINE.md mandate; the reference publishes no
numeric baselines at all — SURVEY.md §6).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

_DEFAULT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "BENCH_EVIDENCE.json")


def evidence_path() -> str:
  return os.environ.get("EPL_BENCH_EVIDENCE", _DEFAULT_PATH)


def run_context(sim: bool = False, **extra: Any) -> Dict[str, Any]:
  """The uniform context block every evidence writer stamps:
  ``host_cores`` (the honesty tag behind every "scaling" claim on a
  shared box) and ``provenance`` — ``"sim"`` for numbers produced by
  the cost-card simulator, ``"hardware"`` for measured ones.  A
  sim-derived record can then never be mistaken for a measurement:
  consumers (bench.py fallback, sim/replica.py calibration) filter on
  the tag, and :func:`append_record` back-fills it for writers that
  predate the tag — which also means an OLD record without the key is
  exactly as trustworthy as one stamped "hardware", because that is
  what it would have been stamped.  ``extra`` keys ride along
  (e.g. ``backend=...``)."""
  ctx: Dict[str, Any] = {"host_cores": os.cpu_count() or 1,
                         "provenance": "sim" if sim else "hardware"}
  ctx.update(extra)
  return ctx


def load_records(path: Optional[str] = None) -> List[Dict[str, Any]]:
  path = path or evidence_path()
  try:
    with open(path) as f:
      data = json.load(f)
  except (OSError, ValueError):
    return []
  return data.get("records", []) if isinstance(data, dict) else []


def _preserve_corrupt(path: str) -> None:
  """If `path` exists but does not parse, move it aside instead of
  letting a fresh write erase earlier (possibly recoverable) evidence."""
  if not os.path.exists(path):
    return
  try:
    with open(path) as f:
      json.load(f)
  except ValueError:
    os.replace(path, f"{path}.corrupt-{int(time.time())}")
  except OSError:
    pass


def append_record(record: Dict[str, Any],
                  path: Optional[str] = None) -> Dict[str, Any]:
  """Validate ``record`` against the evidence schema (below), then
  append it; atomic-rename write so a crash mid-dump cannot corrupt
  earlier evidence.  Raises ``ValueError`` listing every schema error —
  the ONE door every writer (the benchmarks via ``benchmarks/
  _evidence.py``, ``bench.py`` directly) goes through, so ``make
  perf-gate`` (which refuses malformed records) can never meet a
  ledger entry this process wrote and cannot trust."""
  path = path or evidence_path()
  _preserve_corrupt(path)
  records = load_records(path)
  record = dict(record)
  record.setdefault("unix_time", time.time())
  record.setdefault("utc", time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()))
  # Uniform honesty tags (run_context): a writer that did not stamp
  # them gets the truthful defaults — this process's core count, and
  # "hardware" (a sim writer MUST tag itself via run_context(sim=True);
  # the simulator's own writers all do).
  for key, val in run_context().items():
    record.setdefault(key, val)
  errors = validate_record(record)
  if errors:
    raise ValueError(
        f"malformed BENCH_EVIDENCE record for "
        f"{record.get('metric')!r}: " + "; ".join(errors)
        + " (schema: utils/bench_evidence.py validate_record)")
  records.append(record)
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    json.dump({"records": records}, f, indent=1)
  os.replace(tmp, path)
  return record


def latest_record(metric: str,
                  path: Optional[str] = None) -> Optional[Dict[str, Any]]:
  """Most recent record for `metric` (highest unix_time wins)."""
  matches = [r for r in load_records(path) if r.get("metric") == metric]
  if not matches:
    return None
  return max(matches, key=lambda r: r.get("unix_time", 0))


# --------------------------------------------------------- record schema

# Keys with fixed meaning; everything else in a record is metrics
# payload.  A record's shape is name (``metric``) / ts (``unix_time`` +
# ``utc``) / context (``config`` + the backend tags) / metrics (a
# numeric ``value`` and/or payload keys) — the schema ``make perf-gate``
# enforces before trusting a record (benchmarks/_evidence.py is the
# shared writer that validates at write time).
_NAME_KEY = "metric"
_TS_KEYS = ("unix_time", "utc")
_CONTEXT_KEYS = ("config", "backend", "device", "device_kind",
                 "host_cores", "provenance")
_HEADLINE_KEYS = ("value", "unit")


def validate_record(rec: Any) -> List[str]:
  """Schema errors for one evidence record ([] = valid).

  Required: a non-empty string ``metric`` (the name), a numeric
  ``unix_time`` (the ts), and a metrics payload — either a numeric
  ``value`` or at least one payload key beyond the name/ts/context/
  headline sets.  ``config`` (the context), when present, must be an
  object; ``value``, when present, must be numeric or null (null is the
  honest "measurement unavailable" bench.py emits).  The perf gate
  REFUSES malformed records instead of silently skipping them — an
  unreadable ledger entry must fail loudly, not vanish from the
  budget's view."""
  if not isinstance(rec, dict):
    return ["record is not a JSON object"]
  errs: List[str] = []
  name = rec.get(_NAME_KEY)
  if not isinstance(name, str) or not name:
    errs.append("missing/invalid 'metric' (the record's name)")
  ts = rec.get("unix_time")
  if not isinstance(ts, (int, float)) or isinstance(ts, bool):
    errs.append("missing/invalid 'unix_time' (the record's ts)")
  ctx = rec.get("config")
  if ctx is not None and not isinstance(ctx, dict):
    errs.append("'config' (the record's context) must be an object")
  value = rec.get("value")
  if value is not None and (isinstance(value, bool)
                            or not isinstance(value, (int, float))):
    errs.append("'value' must be numeric or null")
  reserved = set((_NAME_KEY,) + _TS_KEYS + _CONTEXT_KEYS + _HEADLINE_KEYS)
  has_payload = (isinstance(value, (int, float))
                 and not isinstance(value, bool)) or any(
      k not in reserved for k in rec)
  if not has_payload:
    errs.append("no metrics payload: need a numeric 'value' or at "
                "least one payload key")
  return errs
