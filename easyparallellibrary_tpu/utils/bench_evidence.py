"""Machine-readable benchmark evidence log.

The driver captures the official perf artifact by running ``bench.py``
once at the end of a round — but the remote-relay TPU backend can wedge
for hours, and has done so at capture time in both previous rounds,
recording 0.0 MFU while healthy-window measurements existed only as
prose in BASELINE.md.  This module fixes that asymmetry: every
successful hardware measurement made during a round appends a full raw
record (per-step wall times, null round-trip, config, timestamp) to
``BENCH_EVIDENCE.json`` at the repo root, and ``bench.py`` falls back to
the most recent auditable record — never to an unverifiable prose
number — when the backend is unreachable at capture time.

Reference analog: none (BASELINE.md mandate; the reference publishes no
numeric baselines at all — SURVEY.md §6).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

_DEFAULT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "BENCH_EVIDENCE.json")


def evidence_path() -> str:
  return os.environ.get("EPL_BENCH_EVIDENCE", _DEFAULT_PATH)


def load_records(path: Optional[str] = None) -> List[Dict[str, Any]]:
  path = path or evidence_path()
  try:
    with open(path) as f:
      data = json.load(f)
  except (OSError, ValueError):
    return []
  return data.get("records", []) if isinstance(data, dict) else []


def _preserve_corrupt(path: str) -> None:
  """If `path` exists but does not parse, move it aside instead of
  letting a fresh write erase earlier (possibly recoverable) evidence."""
  if not os.path.exists(path):
    return
  try:
    with open(path) as f:
      json.load(f)
  except ValueError:
    os.replace(path, f"{path}.corrupt-{int(time.time())}")
  except OSError:
    pass


def append_record(record: Dict[str, Any],
                  path: Optional[str] = None) -> None:
  """Append one measurement record; atomic-rename write so a crash
  mid-dump cannot corrupt earlier evidence."""
  path = path or evidence_path()
  _preserve_corrupt(path)
  records = load_records(path)
  record = dict(record)
  record.setdefault("unix_time", time.time())
  record.setdefault("utc", time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()))
  records.append(record)
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    json.dump({"records": records}, f, indent=1)
  os.replace(tmp, path)


def latest_record(metric: str,
                  path: Optional[str] = None) -> Optional[Dict[str, Any]]:
  """Most recent record for `metric` (highest unix_time wins)."""
  matches = [r for r in load_records(path) if r.get("metric") == metric]
  if not matches:
    return None
  return max(matches, key=lambda r: r.get("unix_time", 0))
