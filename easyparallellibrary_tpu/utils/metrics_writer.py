"""Metric logging to durable files.

The reference re-points TF summaries at merged tensors so TensorBoard
sees global values (epl/parallel/hooks.py:593-664) and optionally reports
to the PAI platform (epl/utils/metric.py).  Here metrics are plain
dicts; this writer appends them as JSONL (universally parseable, and
TensorBoard's JSONL/CSV ingestion or a notebook can plot them) with
leader-only writes.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import jax


class MetricsWriter:
  def __init__(self, path: str, flush_every: int = 1):
    self.path = path
    self.flush_every = max(1, flush_every)
    self._file = None
    self._since_flush = 0
    if jax.process_index() == 0:
      os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
      self._file = open(path, "a")

  def write(self, step: int, metrics: Dict[str, Any]):
    if self._file is None:
      return
    record = {"step": int(step), "time": time.time()}
    for k, v in metrics.items():
      try:
        record[k] = float(v)
      except (TypeError, ValueError):
        record[k] = str(v)
    self._file.write(json.dumps(record) + "\n")
    self._since_flush += 1
    if self._since_flush >= self.flush_every:
      self._file.flush()
      self._since_flush = 0

  def close(self):
    if self._file is not None:
      self._file.close()
      self._file = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
