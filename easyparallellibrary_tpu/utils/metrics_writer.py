"""Metric logging to durable files.

The reference re-points TF summaries at merged tensors so TensorBoard
sees global values (epl/parallel/hooks.py:593-664) and optionally reports
to the PAI platform (epl/utils/metric.py).  Here metrics are plain dicts
with two sinks sharing one interface (``write(step, metrics)``):

* :class:`MetricsWriter` — JSONL (universally parseable; the default).
* :class:`TensorBoardWriter` — TF event files a stock TensorBoard
  renders (the reference's summary integration, minus the graph-surgery
  re-pointing: metrics handed in are already merged global values from
  parallel/metrics.py).  Backed by tensorboardX when available; an
  optional dependency, gated at construction.

Both are leader-only (process 0) in multi-process runs, matching the
reference's first-constructor-writes rule (epl/parallel/hooks.py:542),
and both BUFFER raw (possibly device-resident) values: the host sync the
``float()`` conversion forces happens only at flush boundaries, so
``flush_every=N`` keeps the training loop's async dispatch intact
between flushes (a per-step sync on the relay backend costs a full
round-trip).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Tuple

import jax
import numpy as np


def _coerce_metric(v: Any) -> Any:
  """Flush-time coercion of one buffered metric value.

  Scalars (python numbers, 0-d / 1-element device arrays) become
  ``float``.  Multi-element arrays fail ``float()`` — those get a
  compact ``{shape, dtype, mean}`` summary instead of a multi-kilobyte
  ``str()`` repr dumped into the JSONL (a [1024, 1024] grad-norm debug
  tensor is one line of metadata, not a megabyte of digits).  Anything
  else (strings, arbitrary objects) still falls back to ``str``.
  """
  try:
    return float(v)
  except (TypeError, ValueError):
    pass
  if getattr(v, "shape", None) is not None and \
      getattr(v, "dtype", None) is not None:
    try:
      host = np.asarray(v)
      mean = float(np.mean(host.astype(np.float64))) \
          if host.size else None
    except (TypeError, ValueError):  # non-numeric dtype
      mean = None
    return {"shape": [int(d) for d in v.shape], "dtype": str(v.dtype),
            "mean": mean}
  return str(v)


class _LeaderSink:
  """Shared sink core: leader gating, buffering, flush cadence, and
  numeric-vs-text coercion.  Subclasses implement `_emit(step, wall_time,
  record)` plus IO flush/close."""

  def __init__(self, flush_every: int = 1):
    self.flush_every = max(1, flush_every)
    self._buf: List[Tuple[int, float, Dict[str, Any]]] = []
    self._active = jax.process_index() == 0

  def write(self, step: int, metrics: Dict[str, Any]):
    if not self._active:
      return
    # Raw values (device arrays included) are buffered; conversion —
    # and the device sync it forces — waits for the flush boundary.
    self._buf.append((int(step), time.time(), dict(metrics)))
    if len(self._buf) >= self.flush_every:
      self.flush()

  def flush(self):
    if not self._active:
      return
    for step, wall, metrics in self._buf:
      record = {k: _coerce_metric(v) for k, v in metrics.items()}
      self._emit(step, wall, record)
    self._buf = []
    self._flush_io()

  def close(self):
    if self._active:
      self.flush()
      self._close_io()
      self._active = False

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()

  # -- subclass hooks --
  def _emit(self, step: int, wall_time: float, record: Dict[str, Any]):
    raise NotImplementedError

  def _flush_io(self):
    pass

  def _close_io(self):
    pass


class MetricsWriter(_LeaderSink):
  """JSONL sink: one {"step", "time", **metrics} object per line."""

  def __init__(self, path: str, flush_every: int = 1):
    super().__init__(flush_every)
    self.path = path
    self._file = None
    if self._active:
      os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
      self._file = open(path, "a")

  def _emit(self, step, wall_time, record):
    self._file.write(json.dumps({"step": step, "time": wall_time,
                                 **record}) + "\n")

  def _flush_io(self):
    if self._file is not None:
      self._file.flush()

  def _close_io(self):
    if self._file is not None:
      self._file.close()
      self._file = None


class TensorBoardWriter(_LeaderSink):
  """TensorBoard event-file sink (same interface as MetricsWriter).

  Numeric metrics become scalar summaries; non-numeric values become
  text summaries.  Requires ``tensorboardX`` (present in typical TPU
  images; raises with guidance when absent so a configured sink never
  silently drops metrics).
  """

  def __init__(self, logdir: str, flush_every: int = 1):
    super().__init__(flush_every)
    self.logdir = logdir
    self._writer = None
    if self._active:
      try:
        from tensorboardX import SummaryWriter
      except ImportError as e:
        raise ImportError(
            "TensorBoardWriter needs the optional tensorboardX package; "
            "pip install tensorboardX, or use the JSONL MetricsWriter"
        ) from e
      os.makedirs(logdir, exist_ok=True)
      self._writer = SummaryWriter(logdir=logdir)

  def _emit(self, step, wall_time, record):
    for k, v in record.items():
      if isinstance(v, float):
        self._writer.add_scalar(k, v, step, walltime=wall_time)
      else:
        self._writer.add_text(k, str(v), step, walltime=wall_time)

  def _flush_io(self):
    if self._writer is not None:
      self._writer.flush()

  def _close_io(self):
    if self._writer is not None:
      self._writer.close()
      self._writer = None
