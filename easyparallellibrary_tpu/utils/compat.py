"""JAX API compatibility layer for the manual-sharding surface.

The framework targets the modern manual-sharding API (``jax.shard_map``
with ``axis_names=``/``check_vma=``, ``jax.sharding.get_abstract_mesh``,
``lax.axis_size``), but must also run on older jax releases where the
same machinery lives under ``jax.experimental.shard_map.shard_map`` with
``auto=``/``check_rep=`` and no abstract-mesh introspection.  Every
module that enters a manual region goes through these wrappers instead
of touching the jax surface directly, so the old/new split lives in
exactly one file.

No behavior differences are papered over: both APIs lower to the same
manual-mesh partitioning; only spelling differs.  ``check`` maps to
``check_vma`` (new) / ``check_rep`` (old) — the engines disable it for
the same reason either way (per-device branch divergence is intentional).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

_NEW_SHARD_MAP = hasattr(jax, "shard_map")


def shard_map(f, mesh, in_specs, out_specs,
              manual_axes: Optional[frozenset] = None,
              check: bool = False):
  """Manual-map ``f`` over ``mesh``.

  ``manual_axes``: axes the body is manual over (None = all mesh axes —
  the full-manual default both APIs share).  Partial-manual regions pass
  a subset; the remaining axes stay auto (GSPMD) inside the body.
  """
  if _NEW_SHARD_MAP:
    kwargs = {}
    if manual_axes is not None:
      kwargs["axis_names"] = frozenset(manual_axes)
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check, **kwargs)
  from jax.experimental.shard_map import shard_map as _shard_map
  kwargs = {}
  if manual_axes is not None:
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    # Size-1 auto axes are promoted to manual: semantically identical
    # (nothing shards over them, unmentioned in_specs dims stay
    # replicated) and it keeps the region FULL-manual whenever possible,
    # which the old SPMD partitioner handles robustly.  Genuinely live
    # auto axes are a hard stop here: the old partitioner either rejects
    # the region's axis_index (PartitionId: Unimplemented) or CHECK-
    # aborts the process on its collective-permute/all-to-all — a clean
    # error beats both.
    live_auto = sorted(a for a in mesh.axis_names
                       if a not in manual_axes and sizes.get(a, 1) > 1)
    if live_auto:
      raise NotImplementedError(
          f"partial-manual shard_map with live auto axes {live_auto} "
          f"(manual over {sorted(manual_axes)}) is not supported by this "
          "jax/XLA version's SPMD partitioner; upgrade jax, or lay the "
          "mesh out so the non-manual axes have size 1")
  return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=check, **kwargs)


def axis_size(axis_name: str) -> int:
  """Size of a named mesh axis from inside a manual region."""
  if hasattr(lax, "axis_size"):
    return lax.axis_size(axis_name)
  # Old-jax spelling: psum of the literal 1 is special-cased to the
  # concrete axis size (no collective is lowered).
  return lax.psum(1, axis_name)


def ambient_manual_axes() -> frozenset:
  """Mesh axes that are Manual in the ambient shard_map region (empty
  outside one).  On old jax there is no abstract-mesh introspection;
  the bound-axis environment is the equivalent signal (vmap-bound axis
  names are included, which is the conservative answer for every caller:
  a named axis that cannot take a global sharding constraint or a nested
  manual region either way)."""
  get_abstract_mesh = getattr(jax.sharding, "get_abstract_mesh", None)
  if get_abstract_mesh is not None:
    return frozenset(
        getattr(get_abstract_mesh(), "manual_axes", ()) or ())
  try:
    if not jax.core.nonempty_axis_env_DO_NOT_USE():
      return frozenset()
    names = jax.core.unsafe_get_axis_names_DO_NOT_USE()
    return frozenset(n for n in names if isinstance(n, str))
  except Exception:
    return frozenset()
