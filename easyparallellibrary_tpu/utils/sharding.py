"""The one true sharding-constraint helper.

`jax.lax.with_sharding_constraint` with a bare `PartitionSpec` requires
an ambient mesh context (`jax.set_mesh`); without one it raises — and a
silent try/except would turn every activation constraint in the
framework into a no-op (GSPMD propagation from param/input shardings
hides this numerically, but layout control is lost).  This helper binds
the Env's mesh into a `NamedSharding` explicitly, so constraints work in
any jit context without global mesh state.

Returns `x` unchanged only when no mesh exists yet (e.g. models used
standalone before `epl.init`), or inside `shard_map` bodies where global
shardings do not apply.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from easyparallellibrary_tpu.env import Env

UNCONSTRAINED = P.UNCONSTRAINED


def constrain(x, spec: P):
  env = Env.get()
  cluster = env.cluster
  if cluster is None or cluster._mesh is None:
    return x
  try:
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(cluster.mesh, spec))
  except (ValueError, RuntimeError):
    # e.g. inside shard_map (per-shard values), or rank mismatch from a
    # caller that will constrain later.
    return x
