"""The one true sharding-constraint helper.

`jax.lax.with_sharding_constraint` with a bare `PartitionSpec` requires
an ambient mesh context (`jax.set_mesh`); without one it raises — and a
silent try/except would turn every activation constraint in the
framework into a no-op (GSPMD propagation from param/input shardings
hides this numerically, but layout control is lost).  This helper binds
the Env's mesh into a `NamedSharding` explicitly, so constraints work in
any jit context without global mesh state.

Returns `x` unchanged only when no mesh exists yet (e.g. models used
standalone before `epl.init`), or inside `shard_map` bodies where global
shardings do not apply.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.utils.logging import get_logger

UNCONSTRAINED = P.UNCONSTRAINED


def manual_axes() -> frozenset:
  """Mesh axes that are Manual in the ambient shard_map region (empty
  outside one).  The single compatibility shim for the abstract-mesh
  API — consult this, not jax.sharding directly."""
  from easyparallellibrary_tpu.utils.compat import ambient_manual_axes
  return ambient_manual_axes()


_warned_sites = set()


def constrain(x, spec: P):
  env = Env.get()
  cluster = env.cluster
  if cluster is None or cluster._mesh is None:
    return x
  # Caller bugs must surface, not silently no-op: rank mismatches and
  # unknown axis names raise here (NamedSharding validates axis names).
  if len(spec) > getattr(x, "ndim", len(spec)):
    raise ValueError(
        f"sharding spec {spec} has more entries than value rank {x.ndim}")
  # Inside shard_map bodies mesh axes are Manual: a constraint naming one
  # is an error at lowering time (too late for the except below).  Strip
  # manual axes from the spec — per-shard values are already placed on
  # them — and keep any non-manual remainder (partial-manual shard_map).
  manual = manual_axes()
  if manual:
    def clean(entry):
      if entry is None or entry is P.UNCONSTRAINED:
        return entry
      if isinstance(entry, (tuple, list)):
        kept = tuple(a for a in entry if a not in manual)
        return kept if kept else None
      return None if entry in manual else entry

    spec = P(*(clean(e) for e in spec))
    if all(e is None or e is P.UNCONSTRAINED for e in spec):
      return x
  sharding = NamedSharding(cluster.mesh, spec)
  try:
    return jax.lax.with_sharding_constraint(x, sharding)
  except (ValueError, RuntimeError) as e:
    # Expected only inside shard_map bodies (per-shard values reject
    # global shardings).  Log once per site so genuine swallowed errors
    # are visible.
    key = (str(spec), getattr(x, "ndim", None), type(e).__name__)
    if key not in _warned_sites:
      _warned_sites.add(key)
      get_logger().debug("sharding constraint %s skipped: %s", spec, e)
    return x
