"""Retry with exponential backoff for transient IO errors.

Checkpoint shards and input records live on network filesystems in
production (GCS fuse, NFS); both fail transiently under load.  The
reference's recovery story for these is kill-and-retry of the whole
worker (SURVEY §5.3) — here the retry happens at the call site instead,
bounded by ``resilience.io_retries`` / ``resilience.io_retry_backoff_s``
so a dead filesystem still surfaces as the original exception, with the
attempt history logged.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple, Type

from easyparallellibrary_tpu.utils.logging import get_logger

# Exceptions worth retrying by default: OSError covers IOError, network
# filesystem hiccups, and interrupted syscalls.  Never retry programming
# errors (TypeError/KeyError) — those reproduce identically.
TRANSIENT_EXCEPTIONS: Tuple[Type[BaseException], ...] = (OSError,)

# OSError subclasses that reproduce deterministically — retrying them
# only delays the real error.  Honored when the caller uses the default
# exception set; pass `exceptions=` explicitly to retry these too.
PERMANENT_IO_EXCEPTIONS: Tuple[Type[BaseException], ...] = (
    FileNotFoundError, IsADirectoryError, NotADirectoryError,
    PermissionError)


def retry_call(fn: Callable[..., Any],
               *args,
               retries: Optional[int] = None,
               backoff_s: Optional[float] = None,
               max_backoff_s: float = 2.0,
               jitter: float = 0.0,
               exceptions: Tuple[Type[BaseException], ...] = (),
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               what: str = "",
               **kwargs) -> Any:
  """Call ``fn(*args, **kwargs)``, retrying transient failures.

  ``retries`` is the number of RE-tries after the first attempt
  (``retries=0`` means one attempt, no retry); defaults to the active
  config's ``resilience.io_retries``.  Backoff doubles each attempt,
  capped at ``max_backoff_s``.  ``jitter`` stretches each sleep by a
  uniformly random factor in ``[1, 1 + jitter]`` — RPC retries against
  a shared replica (serving/transport.py) must decorrelate, or every
  caller that timed out together retries together and the thundering
  herd re-times-out together.  ``on_retry(attempt, exc)`` is invoked
  before each sleep — callers use it to count retries into metrics.
  The final failure re-raises the last exception unchanged.
  """
  if retries is None or backoff_s is None:
    from easyparallellibrary_tpu.env import Env
    res = Env.get().config.resilience
    if retries is None:
      retries = res.io_retries
    if backoff_s is None:
      backoff_s = res.io_retry_backoff_s
  if jitter < 0:
    raise ValueError(f"jitter must be >= 0: {jitter}")
  default_set = not exceptions
  exceptions = exceptions or TRANSIENT_EXCEPTIONS
  delay = max(0.0, backoff_s)
  for attempt in range(retries + 1):
    try:
      return fn(*args, **kwargs)
    except exceptions as e:
      if default_set and isinstance(e, PERMANENT_IO_EXCEPTIONS):
        raise
      if attempt >= retries:
        raise
      sleep_s = delay
      if delay and jitter:
        import random
        sleep_s = delay * (1.0 + random.uniform(0.0, jitter))
      get_logger().warning(
          "transient failure%s (attempt %d/%d): %s — retrying in %.2fs",
          f" in {what}" if what else "", attempt + 1, retries + 1, e,
          sleep_s)
      if on_retry is not None:
        on_retry(attempt + 1, e)
      if sleep_s:
        time.sleep(sleep_s)
      delay = min(delay * 2 if delay else 0.0, max_backoff_s)
  raise AssertionError("unreachable")  # pragma: no cover


def retrying(what: str = "", **retry_kwargs) -> Callable[[Callable], Callable]:
  """Decorator form of :func:`retry_call`."""

  def deco(fn: Callable) -> Callable:
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
      return retry_call(fn, *args, what=what or fn.__name__,
                        **retry_kwargs, **kwargs)

    return wrapped

  return deco
