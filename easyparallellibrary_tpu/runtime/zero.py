"""ZeRO — optimizer-state (and gradient) sharding over the data axis.

TPU-native redesign of the reference's ZeRO v0/v1
(epl/runtime/zero.py): the reference round-robins whole variables across
data-parallel workers (`group_list`, :88-127), has the owner apply the
update, then chains serialized broadcasts of updated weights (:129-167).
On TPU none of that choreography is written by hand: ZeRO is a *sharding
decision* — optimizer-state leaves get an extra `data`-axis sharding on a
dimension GSPMD can split, and XLA lowers the update into
reduce-scatter(grads) → local apply → all-gather(params) automatically,
which is exactly the ZeRO-1 dataflow.

Levels (reference epl/config.py:129-137):
  * v0 — shard optimizer states only (GSPMD sharding decision, below).
  * v1 — v0 + gradients: :func:`make_zero1_train_step` runs the step
    inside shard_map and spells the ZeRO-1 dataflow out explicitly —
    reduce-scatter(grads) → owner applies its shard → all-gather(params)
    — matching the reference's reduce-to-owner + broadcast choreography
    (epl/runtime/zero.py:178-190, :129-167) with XLA collectives.
  * v2 — not implemented (the reference declares it unimplemented too).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from easyparallellibrary_tpu import constants
from easyparallellibrary_tpu.utils.logging import get_logger


def zero_owner_dim(shape, taken, data_size: int):
  """THE ZeRO owner-dim rule: first dimension that is not already
  sharded (``taken[dim]`` falsy) and divisible by ``data_size``, or
  ``None`` when the leaf stays replicated (reference keeps remainder
  vars on worker 0, epl/runtime/zero.py:105-115).

  Single source of truth shared by :func:`_shard_leaf_spec` (the
  v0/v1 optimizer-state layout) and the engines'
  ``pipeline_smap.zero1_grad_layout`` (the grad reduce-scatter layout) —
  the two MUST agree or the scattered grads land misaligned with the
  owner's optimizer shard and GSPMD reshards between them.
  """
  if not shape or data_size <= 1:
    return None
  for dim, size in enumerate(shape):
    if not taken[dim] and size % data_size == 0 and size >= data_size:
      return dim
  return None


def _shard_leaf_spec(abstract_leaf, spec: P, data_size: int) -> P:
  """Add `data` to the first unsharded, divisible dimension of the spec."""
  shape = getattr(abstract_leaf, "shape", ())
  entries = list(spec) + [None] * (len(shape) - len(spec))
  dim = zero_owner_dim(shape, [e is not None for e in entries], data_size)
  if dim is None:
    return spec  # nothing shardable; stays replicated
  entries[dim] = constants.DATA_AXIS
  return P(*entries)


def shard_opt_state(abstract_state, shardings, mesh: Mesh, level: str):
  """Re-shard the `opt_state` subtree of a TrainState's shardings.

  `abstract_state` is the eval_shape'd state; `shardings` the NamedSharding
  pytree derived from param metadata.  Only `opt_state` leaves are touched:
  params keep their layout (ZeRO-1 semantics — v2/v3 param sharding is out
  of scope, as in the reference).
  """
  if level not in (constants.ZERO_V0, constants.ZERO_V1):
    raise ValueError(f"Unsupported zero.level {level!r}")
  data_size = int(np.prod([s for n, s in zip(mesh.axis_names,
                                             mesh.devices.shape)
                           if n == constants.DATA_AXIS]))
  if data_size <= 1:
    get_logger().warning("zero.level=%s requested but data axis is size 1; "
                         "optimizer state stays unsharded", level)
    return shardings

  if not hasattr(abstract_state, "opt_state"):
    raise ValueError("shard_opt_state expects a TrainState-like object "
                     "with an opt_state field")

  def reshard(abstract_leaf, sharding):
    spec = sharding.spec if isinstance(sharding, NamedSharding) else P()
    new_spec = _shard_leaf_spec(abstract_leaf, spec, data_size)
    return NamedSharding(mesh, new_spec)

  # Unbox metadata on the abstract side so leaves align with shardings.
  import flax.linen as nn
  abstract_opt = nn.unbox(abstract_state.opt_state)
  new_opt_shardings = jax.tree_util.tree_map(
      reshard, abstract_opt, shardings.opt_state)
  return shardings.replace(opt_state=new_opt_shardings)


# --------------------------------------------------------------------------
# Explicit ZeRO-1: reduce-scatter grads to owners, local apply, all-gather.
# --------------------------------------------------------------------------

def _zero1_dim(shape, dp: int):
  """The dimension a leaf is owner-sharded on, or None when it stays
  replicated (the analog of the reference keeping remainder vars on
  worker 0, epl/runtime/zero.py:105-115).  Derived from
  `_shard_leaf_spec` so the shard_map body and the state layouts built by
  `create_sharded_train_state(zero_level=...)` can never disagree."""
  import types
  spec = _shard_leaf_spec(
      types.SimpleNamespace(shape=tuple(shape)), P(), dp)
  for d, entry in enumerate(spec):
    if entry == constants.DATA_AXIS:
      return d
  return None


def _assert_elementwise_tx(tx, params) -> None:
  """Reject optimizers whose update at one position depends on other
  positions (other leaves OR other slices of the same leaf).

  The explicit ZeRO-1 step hands ``tx.update`` 1/dp *slices* of each leaf,
  so any cross-position coupling — ``clip_by_global_norm`` across leaves,
  ``clip_by_block_rms``/factored adafactor statistics within a leaf —
  would be computed over the local shard only and silently diverge from
  the unsharded optimizer.  The reference enforces its analogous
  constraints structurally (epl/runtime/zero.py:60-75); optax transforms
  are opaque closures, so the check is behavioral: on a probe tree with
  the REAL param structure (so structure-keyed transforms like
  ``optax.masked`` probe correctly) but uniform [128, 128] leaves,
  perturb one element of the first and last leaves and require every
  other position's update to be unchanged.  The probe size matters:
  optax's factored RMS statistics (adafactor /
  ``scale_by_factored_rms``) only factor leaves whose dims reach
  ``min_dim_size_to_factor`` (128), so a smaller probe would pass
  adafactor as elementwise while real-size leaves couple positions.
  128x128 fp32 leaves keep the probe cheap while tripping every
  size-gated transform at its default threshold.  A probe that cannot
  run (exotic shape-dependent transform) logs a warning instead of
  blocking — the guard is advisory, coupling it can SEE is a hard error.
  """
  shape = (128, 128)
  probe_p = jax.tree_util.tree_map(
      lambda _: jnp.ones(shape, jnp.float32), params)
  g_base = jax.tree_util.tree_map(
      lambda _: jnp.full(shape, 0.5, jnp.float32), probe_p)
  leaves, treedef = jax.tree_util.tree_flatten(g_base)
  # Large perturbation so norm/rms-dependent rescaling is unmistakable.
  # Perturb first AND last leaves so structure-keyed transforms
  # (optax.masked) that only touch later leaves are still exercised.
  pert_idx = sorted({0, len(leaves) - 1})
  pert_leaves = [l.at[0, 0].set(1e3) if i in pert_idx else l
                 for i, l in enumerate(leaves)]
  g_pert = jax.tree_util.tree_unflatten(treedef, pert_leaves)
  try:
    state = tx.init(probe_p)
    u_base, s_base = tx.update(g_base, state, probe_p)
    u_pert, s_pert = tx.update(g_pert, state, probe_p)
  except Exception as e:  # probe infrastructure failure, not a verdict
    get_logger().warning(
        "explicit ZeRO-1 could not verify the optimizer is elementwise "
        "(probe failed: %s); proceeding — ensure no cross-leaf/cross-"
        "slice transforms (clip_by_global_norm, clip_by_block_rms, "
        "factored adafactor) are in the chain", e)
    return
  mask0 = np.ones(shape, bool)
  mask0[0, 0] = False

  def differs(a, b, masked):
    a, b = np.asarray(a), np.asarray(b)
    if masked and a.shape == shape:
      a, b = a[mask0], b[mask0]
    return not np.allclose(a, b, rtol=1e-5, atol=1e-7)

  ub = jax.tree_util.tree_leaves(u_base)
  up = jax.tree_util.tree_leaves(u_pert)
  coupled = any(differs(a, b, i in pert_idx)
                for i, (a, b) in enumerate(zip(ub, up)))
  # Scale-invariant optimizers (adam) normalize a uniform clip rescale
  # OUT of the first-step update, but the new optimizer STATE still sees
  # the rescaled gradients everywhere — check it too.  State leaves that
  # track the perturbed position legitimately differ at [0, 0] only, so
  # probe-shaped state leaves are compared off that position.
  sb = jax.tree_util.tree_leaves(s_base)
  sp = jax.tree_util.tree_leaves(s_pert)
  coupled = coupled or any(
      differs(a, b, np.asarray(a).shape == shape)
      for a, b in zip(sb, sp))
  if coupled:
    raise ValueError(
        "explicit ZeRO-1 requires an elementwise optimizer: this optax "
        "transform couples positions (e.g. optax.clip_by_global_norm "
        "across leaves, clip_by_block_rms or factored adafactor "
        "statistics within a leaf), so applying it "
        "to per-owner 1/dp shards would compute the coupling over local "
        "slices only.  Either drop the coupled transform, or use GSPMD "
        "optimizer-state sharding (zero.level='v0') where the update "
        "sees full-size gradients.")


def make_zero1_train_step(loss_fn: Callable, mesh: Mesh) -> Callable:
  """Explicit ZeRO-1 train step: `(state, batch, rng) -> (state, metrics)`.

  Inside shard_map over the data axis:

    1. per-shard gradients (full-size, like plain DP),
    2. ``psum_scatter`` each divisible gradient leaf — every worker
       receives only the 1/dp slice it owns (reference: reduce grads to
       the owning worker, epl/runtime/zero.py:178-190),
    3. the owner applies the optimizer update on its param/opt-state
       slice (optimizer must be elementwise — adam/adamw/sgd; global-norm
       transforms would need the full tree),
    4. ``all_gather`` rebuilds the replicated params (reference's chained
       broadcasts, :129-167 — here one fused collective).

  Gradient + optimizer memory for sharded leaves is 1/dp per device by
  construction, not by XLA's liveness choices.  Build the state with
  ``create_sharded_train_state(..., zero_level="v1")`` — the explicit
  step shards leaves on the same first-divisible dim that
  ``shard_opt_state`` uses, so the layouts line up.
  """
  dp_axes = {constants.DATA_AXIS}
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  for name, size in sizes.items():
    if name not in dp_axes and size > 1:
      raise ValueError(
          f"explicit ZeRO-1 supports pure data parallelism; mesh axis "
          f"{name!r} has size {size} (compose GSPMD zero.level=v0 with "
          f"hybrid meshes instead)")
  dp = sizes.get(constants.DATA_AXIS, 1)

  def sharded_step(state, batch, rng):
    import optax
    (loss, aux), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params, batch, rng)
    idx = jax.lax.axis_index(constants.DATA_AXIS)

    def scatter(g):
      d = _zero1_dim(g.shape, dp)
      if d is not None:
        return jax.lax.psum_scatter(
            g, constants.DATA_AXIS, scatter_dimension=d, tiled=True) / dp
      return jax.lax.pmean(g, constants.DATA_AXIS)

    def slice_own(p):
      d = _zero1_dim(p.shape, dp)
      if d is not None:
        block = p.shape[d] // dp
        return jax.lax.dynamic_slice_in_dim(p, idx * block, block, axis=d)
      return p

    grads_own = jax.tree_util.tree_map(scatter, grads)
    params_own = jax.tree_util.tree_map(slice_own, state.params)
    updates, new_opt = state.tx.update(grads_own, state.opt_state,
                                       params_own)
    new_params_own = optax.apply_updates(params_own, updates)

    def gather(ps, p_old):
      d = _zero1_dim(p_old.shape, dp)
      if d is not None:
        return jax.lax.all_gather(ps, constants.DATA_AXIS, axis=d,
                                  tiled=True)
      return ps

    new_params = jax.tree_util.tree_map(gather, new_params_own,
                                        state.params)
    new_state = state.replace(step=state.step + 1, params=new_params,
                              opt_state=new_opt)
    from easyparallellibrary_tpu.parallel.metrics import merge_shard_metrics
    metrics = {"loss": jax.lax.pmean(loss, constants.DATA_AXIS)}
    if aux:
      metrics.update(merge_shard_metrics(
          jax.tree_util.tree_map(jnp.asarray, aux)))
    return new_state, metrics

  def state_specs(state):
    import flax.linen as nn

    def opt_spec(leaf):
      return _shard_leaf_spec(leaf, P(), dp)

    specs = jax.tree_util.tree_map(lambda _: P(), nn.unbox(state))
    return specs.replace(opt_state=jax.tree_util.tree_map(
        opt_spec, nn.unbox(state.opt_state)))

  compiled = {}

  def step(state, batch, rng):
    if "fn" not in compiled:
      import flax.linen as nn
      _assert_elementwise_tx(state.tx, nn.meta.unbox(state.params))
      in_state_specs = state_specs(jax.eval_shape(lambda s: s, state))
      from easyparallellibrary_tpu.utils.compat import shard_map
      mapped = shard_map(
          sharded_step, mesh=mesh,
          in_specs=(in_state_specs, P(constants.DATA_AXIS), P()),
          out_specs=(in_state_specs, P()),
          check=False)
      compiled["fn"] = jax.jit(mapped, donate_argnums=(0,))
      step.jitted = compiled["fn"]
    return compiled["fn"](state, batch, rng)

  return step
