"""ZeRO — optimizer-state (and gradient) sharding over the data axis.

TPU-native redesign of the reference's ZeRO v0/v1
(epl/runtime/zero.py): the reference round-robins whole variables across
data-parallel workers (`group_list`, :88-127), has the owner apply the
update, then chains serialized broadcasts of updated weights (:129-167).
On TPU none of that choreography is written by hand: ZeRO is a *sharding
decision* — optimizer-state leaves get an extra `data`-axis sharding on a
dimension GSPMD can split, and XLA lowers the update into
reduce-scatter(grads) → local apply → all-gather(params) automatically,
which is exactly the ZeRO-1 dataflow.

Levels (reference epl/config.py:129-137):
  * v0 — shard optimizer states only.
  * v1 — v0 + gradients: the train step additionally reduce-scatters
    gradients explicitly when running inside a shard_map region; under
    plain GSPMD jit the partitioner already fuses this, so v1 ≡ v0 there.
  * v2 — not implemented (the reference declares it unimplemented too).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from easyparallellibrary_tpu import constants
from easyparallellibrary_tpu.utils.logging import get_logger


def _shard_leaf_spec(abstract_leaf, spec: P, data_size: int) -> P:
  """Add `data` to the first unsharded, divisible dimension of the spec."""
  shape = getattr(abstract_leaf, "shape", ())
  if not shape or data_size <= 1:
    return spec
  entries = list(spec) + [None] * (len(shape) - len(spec))
  for dim, size in enumerate(shape):
    current = entries[dim]
    if current is None and size % data_size == 0 and size >= data_size:
      entries[dim] = constants.DATA_AXIS
      return P(*entries)
    if current is not None:
      # Already sharded (e.g. tensor-parallel dim) — try combining data
      # on top only if evenly divisible by both.
      continue
  return spec  # nothing shardable; stays replicated (reference keeps
               # remainder vars on worker 0, epl/runtime/zero.py:105-115)


def shard_opt_state(abstract_state, shardings, mesh: Mesh, level: str):
  """Re-shard the `opt_state` subtree of a TrainState's shardings.

  `abstract_state` is the eval_shape'd state; `shardings` the NamedSharding
  pytree derived from param metadata.  Only `opt_state` leaves are touched:
  params keep their layout (ZeRO-1 semantics — v2/v3 param sharding is out
  of scope, as in the reference).
  """
  if level not in (constants.ZERO_V0, constants.ZERO_V1):
    raise ValueError(f"Unsupported zero.level {level!r}")
  data_size = int(np.prod([s for n, s in zip(mesh.axis_names,
                                             mesh.devices.shape)
                           if n == constants.DATA_AXIS]))
  if data_size <= 1:
    get_logger().warning("zero.level=%s requested but data axis is size 1; "
                         "optimizer state stays unsharded", level)
    return shardings

  if not hasattr(abstract_state, "opt_state"):
    raise ValueError("shard_opt_state expects a TrainState-like object "
                     "with an opt_state field")

  def reshard(abstract_leaf, sharding):
    spec = sharding.spec if isinstance(sharding, NamedSharding) else P()
    new_spec = _shard_leaf_spec(abstract_leaf, spec, data_size)
    return NamedSharding(mesh, new_spec)

  # Unbox metadata on the abstract side so leaves align with shardings.
  import flax.linen as nn
  abstract_opt = nn.unbox(abstract_state.opt_state)
  new_opt_shardings = jax.tree_util.tree_map(
      reshard, abstract_opt, shardings.opt_state)
  return shardings.replace(opt_state=new_opt_shardings)
