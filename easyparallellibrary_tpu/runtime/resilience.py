"""Anomaly sentinel, rollback, and step watchdog — the resilience layer.

The reference recovers from every fault the same way: the scheduler
kills the job and restarts it from the last checkpoint (SURVEY §5.3).
This module gives `fit` (runtime/loop.py) graded responses instead,
in the spirit of Varuna's train-through-faults design (Athlur et al.,
EuroSys'22):

* **skip** — an in-jit finite check on loss/grads suppresses a bad
  update via ``jnp.where`` (no host sync, no program split: the guard
  lives inside the single jitted step).  Under bf16 there is no loss
  scale to catch a NaN, so this is the only per-step line of defense;
  with fp16 AMP it composes with ``DynamicLossScale`` (which keeps
  owning the scale backoff).
* **rollback** — consecutive bad steps are counted ON-DEVICE in
  :class:`SentinelState`; the host reads the counter once per
  ``max_bad_steps`` window and, past the threshold, restores the newest
  valid checkpoint (optionally backing off the LR) instead of letting
  the run diverge.
* **watchdog** — :class:`StepWatchdog` logs diagnostics when one loop
  iteration (data fetch + step dispatch) exceeds a wall-clock deadline.

Knobs: the ``resilience.*`` config group (docs/robustness.md).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from easyparallellibrary_tpu.utils.logging import get_logger

# Consecutive rollbacks without a clean window in between: after this
# many the fault is clearly not transient and fit() fails loudly rather
# than replaying the same window forever.
MAX_CONSECUTIVE_ROLLBACKS = 3


def sentinel_enabled(config=None) -> bool:
  """Whether the in-jit anomaly guard is active (``resilience.sentinel``,
  implied by ``resilience.max_bad_steps > 0``)."""
  if config is None:
    from easyparallellibrary_tpu.env import Env
    config = Env.get().config
  return bool(config.resilience.sentinel
              or config.resilience.max_bad_steps > 0)


class SentinelState(struct.PyTreeNode):
  """On-device anomaly counters carried in the train state.

  ``bad_consecutive`` resets to zero on every finite step; crossing
  ``resilience.max_bad_steps`` is what triggers the host-side rollback.
  ``bad_total`` only grows — the run-lifetime ``bad_steps_total``
  metric.
  """
  bad_consecutive: jnp.ndarray
  bad_total: jnp.ndarray

  @classmethod
  def create(cls) -> "SentinelState":
    return cls(bad_consecutive=jnp.zeros((), jnp.int32),
               bad_total=jnp.zeros((), jnp.int32))

  def update(self, finite) -> "SentinelState":
    bad = (~finite).astype(jnp.int32)
    return self.replace(
        bad_consecutive=jnp.where(finite, 0, self.bad_consecutive + 1),
        bad_total=self.bad_total + bad)


def attach_sentinel(state):
  """Give a TrainState its sentinel counters (idempotent)."""
  if getattr(state, "sentinel", None) is not None:
    return state
  return state.replace(sentinel=SentinelState.create())


def finite_check(loss, grads=None) -> jnp.ndarray:
  """Scalar bool: loss (and grads, when given) are all finite.  Traced
  inside the step — works under bf16 where no loss scale exists."""
  from easyparallellibrary_tpu.runtime import amp as amp_lib
  ok = jnp.all(jnp.isfinite(jnp.asarray(loss, jnp.float32)))
  if grads is not None:
    ok = ok & amp_lib.all_finite(grads)
  return ok


def select_state(finite, updated, previous):
  """Pick `updated` on a finite step, `previous` otherwise, leafwise via
  ``jnp.where`` over the WHOLE state (params, opt_state, step, and any
  extra fields like model_state) — a true no-op step with no host branch
  (the AMP skip's mechanism, generalized).  Fields with their own
  update-on-overflow semantics (the AMP loss scale) must be re-set by
  the caller afterwards."""
  return jax.tree_util.tree_map(
      lambda a, b: jnp.where(finite, a, b), updated, previous)


def sentinel_metrics(sentinel: "SentinelState", finite) -> Dict[str, Any]:
  """The metric surface of the guard: stays device-resident — emitting
  these adds no host sync (the metrics writer floats them at its flush
  boundary)."""
  return {"bad_steps": sentinel.bad_consecutive,
          "bad_steps_total": sentinel.bad_total,
          "update_skipped": (~finite).astype(jnp.float32)}


def guard_step(step_fn: Callable) -> Callable:
  """Wrap any ``(state, batch, rng) -> (state, metrics)`` step with the
  anomaly sentinel.

  The wrapper runs `step_fn`, finite-checks the returned loss AND the
  updated params (a NaN gradient poisons the params it touched, so the
  post-update check catches it without seeing the grads), and on a bad
  step keeps the previous params/opt_state/step wholesale.  Everything
  happens inside the same trace — the jitted step stays ONE program and
  gains no host sync.  Use :func:`trainer.build_train_step` instead when
  you want the check on the raw grads before the apply.

  The state must carry sentinel counters (:func:`attach_sentinel`).
  """

  def guarded(state, batch, rng):
    if getattr(state, "sentinel", None) is None:
      raise ValueError(
          "guard_step requires sentinel counters in the train state; "
          "wrap it with resilience.attach_sentinel(state) first")
    new_state, metrics = step_fn(state, batch, rng)
    finite = finite_check(metrics.get("loss", jnp.float32(0.0)),
                          new_state.params)
    out = select_state(finite, new_state, state)
    sentinel = state.sentinel.update(finite)
    out = out.replace(sentinel=sentinel)
    return out, {**metrics, **sentinel_metrics(sentinel, finite)}

  return guarded


# ------------------------------------------------------------- rollback --


def backoff_learning_rate(opt_state, factor: float) -> Tuple[Any, bool]:
  """Scale the optimizer's learning rate by `factor`, when reachable.

  Works for optimizers built with ``optax.inject_hyperparams`` (the
  state then carries a ``hyperparams`` dict); plain optax chains bake
  the LR into closures, which cannot be rewritten post-hoc — those
  return ``(opt_state, False)`` and the caller logs that the backoff
  was skipped.
  """
  hp = getattr(opt_state, "hyperparams", None)
  if isinstance(hp, dict) and "learning_rate" in hp:
    new_hp = dict(hp)
    new_hp["learning_rate"] = new_hp["learning_rate"] * factor
    if hasattr(opt_state, "_replace"):        # NamedTuple state
      return opt_state._replace(hyperparams=new_hp), True
    return opt_state.replace(hyperparams=new_hp), True
  if isinstance(opt_state, tuple):
    out, applied = [], False
    for part in opt_state:
      if not applied:
        part, applied = backoff_learning_rate(part, factor)
      out.append(part)
    if applied:
      # Rebuild preserving the container type (optax states are
      # NamedTuples, whose constructor takes positional fields).
      if hasattr(opt_state, "_fields"):
        return type(opt_state)(*out), True
      return tuple(out), True
  return opt_state, False


# ------------------------------------------------------------- watchdog --


class StepWatchdog:
  """Deadline monitor for training-loop iterations.

  ``arm(step)`` before the iteration, ``disarm()`` after; if the
  deadline passes first, diagnostics are logged (and
  ``on_timeout(step)`` called) — the step is NOT interrupted, matching
  the observability-only role: a wedged input pipeline or a
  pathological recompile shows up in the log with a step number instead
  of as silence.

  One long-lived daemon monitor thread waits on a condition variable;
  ``arm``/``disarm`` just update the deadline under the lock, so the
  per-step cost is a lock acquire + notify, with no thread
  creation/teardown in the hot loop.

  Note: step dispatch is async — `fit` hands the device its work and
  moves on, so a slow DEVICE step surfaces at the next host sync (metric
  flush / checkpoint), which this deadline then covers.  A hung
  ``next(data)`` or a recompile is caught immediately.
  """

  def __init__(self, timeout_s: float,
               on_timeout: Optional[Callable[[int], None]] = None,
               knob: str = "resilience.step_timeout_s"):
    self.timeout_s = timeout_s
    self.on_timeout = on_timeout
    # Which config knob set this deadline — named in the timeout log so
    # a serving watchdog (serving.resilience.step_timeout_s) reads
    # differently from the training one.
    self.knob = knob
    self.timeouts_fired = 0
    self._cond = threading.Condition()
    self._deadline: Optional[float] = None
    self._step = -1
    self._closed = False
    self._thread: Optional[threading.Thread] = None

  def _ensure_thread(self):
    if self._thread is None or not self._thread.is_alive():
      self._thread = threading.Thread(target=self._run,
                                      name="epl-step-watchdog",
                                      daemon=True)
      self._thread.start()

  def arm(self, step: int):
    import time
    with self._cond:
      self._deadline = time.monotonic() + self.timeout_s
      self._step = step
      self._ensure_thread()
      self._cond.notify()

  def disarm(self):
    with self._cond:
      self._deadline = None
      self._cond.notify()

  def _run(self):
    import time
    while True:
      with self._cond:
        if self._closed:
          return
        if self._deadline is None:
          self._cond.wait()
          continue
        remaining = self._deadline - time.monotonic()
        if remaining > 0:
          self._cond.wait(remaining)
          continue
        step, self._deadline = self._step, None  # fire once per arm
      self._fire(step)

  def _fire(self, step: int):
    # Monitor-thread write, host-loop readers (the router's health
    # beats read timeouts_fired between sweeps): `+=` is not
    # GIL-atomic, so the counter shares the condition's lock like
    # every other cross-thread field of this class.
    with self._cond:
      self.timeouts_fired += 1
    # Instant event from the monitor thread (its own trace track): the
    # wedged window shows up IN the timeline next to whatever phase
    # span never closed.
    from easyparallellibrary_tpu.observability import trace as trace_lib
    trace_lib.get_tracer().instant(
        "resilience/watchdog_timeout", cat="resilience",
        track="resilience/watchdog",
        args={"step": step, "timeout_s": self.timeout_s})
    log = get_logger()
    try:
      devices = len(jax.devices())
    except Exception:  # pragma: no cover - backend teardown race
      devices = -1
    log.warning(
        "watchdog: step %d exceeded the %.1fs deadline "
        "(%s); %d device(s) visible. Likely "
        "causes: stalled input pipeline, XLA recompile, or a wedged "
        "collective. Dumping thread stacks to stderr.",
        step, self.timeout_s, self.knob, devices)
    try:
      import faulthandler
      faulthandler.dump_traceback(all_threads=True)
    except Exception:  # pragma: no cover
      pass
    if self.on_timeout is not None:
      self.on_timeout(step)

  def close(self):
    with self._cond:
      self._closed = True
      self._deadline = None
      self._cond.notify()
    if self._thread is not None:
      self._thread.join(timeout=1.0)

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
