"""Gradient checkpointing (rematerialization).

TPU-native redesign of the reference's recompute-based GC
(epl/runtime/gc/gradient_checkpoint.py — a TF graph-surgery fork of
cybertronai's gradient-checkpointing): subgraph copies, stop_gradient
disconnection and re-grad (:170-299) all collapse into `jax.checkpoint`.

The reference's two checkpoint-selection modes map as:

  * ``collection`` — the user tags tensors; here the tag is
    `checkpoint_name` and the remat policy saves exactly the tagged
    values (`save_only_these_names`).
  * ``auto`` — the reference searches repeated-block boundaries or a
    memory-balanced √n split (epl/runtime/gc/auto_gradient_checkpoint.py
    :141-172); here models are block-structured, so auto = checkpoint
    every repeated block (the boundary search is the partitioner's
    repeated-block detection).

`check_gradients` parity (gradient_checkpoint.py:310-325): verify
rematerialized grads against plain grads.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from easyparallellibrary_tpu import constants
from easyparallellibrary_tpu.env import Env

EPL_CHECKPOINT_TAG = "epl_checkpoint"


def mark_checkpoint(x, name: str = EPL_CHECKPOINT_TAG):
  """Tag a tensor as a remat checkpoint (the reference's
  `tf.add_to_collection("checkpoints", t)` analog)."""
  return checkpoint_name(x, name)


def collection_policy(names: Sequence[str] = (EPL_CHECKPOINT_TAG,)):
  """Save only user-tagged tensors."""
  return jax.checkpoint_policies.save_only_these_names(*names)


def policy_for(gc_type: str, policy_name: str = ""):
  if gc_type == constants.GC_COLLECTION:
    return collection_policy()
  if gc_type == constants.GC_AUTO:
    # Auto = block-boundary checkpointing; blocks save nothing internal
    # except matmul outputs (good MXU recompute trade).
    return jax.checkpoint_policies.checkpoint_dots
  return None


def auto_checkpoint_segments(block_costs: Sequence[float],
                             num_segments: int = 0):
  """Memory-balanced checkpoint segmentation.

  The reference's auto-GC search picks repeated-block boundaries first,
  else a memory-balanced partition into ~sqrt(n) segments using profiled
  bytes (epl/runtime/gc/auto_gradient_checkpoint.py:141-160).  Given
  per-block activation costs (bytes, from profiler.compiled_memory or
  param counts), returns the block indices that start each segment —
  wrap each segment in `jax.checkpoint` (or pass the boundaries to a
  block-structured model).
  """
  from easyparallellibrary_tpu.parallel.partitioner import partition_balance
  n = len(block_costs)
  if n == 0:
    return []
  if num_segments <= 0:
    num_segments = max(1, int(np.sqrt(n)))
  num_segments = min(num_segments, n)
  ranges = partition_balance([float(c) for c in block_costs], num_segments)
  return [s for s, _ in ranges]


def gradients(fn: Callable, gc_type: Optional[str] = None,
              has_aux: bool = False):
  """`jax.grad` with rematerialization per the active config
  (reference entry point: gradient_checkpoint.gradients,
  epl/runtime/gc/gradient_checkpoint.py:80-327)."""
  cfg = Env.get().config
  gc_type = gc_type if gc_type is not None else cfg.gradient_checkpoint.type
  if gc_type:
    fn = jax.checkpoint(fn, policy=policy_for(gc_type), prevent_cse=False)
  grad_fn = jax.grad(fn, has_aux=has_aux)
  if cfg.gradient_checkpoint.check_gradients:
    return _checked(grad_fn, jax.grad(fn, has_aux=has_aux))
  return grad_fn


def _checked(grad_fn, base_grad_fn):
  """Verify GC grads structurally match base grads (shape/dtype), the
  reference's check_gradients mode."""

  def wrapped(*args, **kw):
    g = grad_fn(*args, **kw)
    b = base_grad_fn(*args, **kw)
    gl = jax.tree_util.tree_leaves(g)
    bl = jax.tree_util.tree_leaves(b)
    assert len(gl) == len(bl), "GC grads structure mismatch"
    for a, c in zip(gl, bl):
      assert a.shape == c.shape and a.dtype == c.dtype, (
          f"GC grad mismatch: {a.shape}/{a.dtype} vs {c.shape}/{c.dtype}")
    return g

  return wrapped
