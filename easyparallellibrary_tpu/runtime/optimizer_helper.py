"""Grouped optimizer apply — bound peak memory of the weight update.

Analog of the reference's ``apply_grad_group``
(epl/runtime/optimizer_helper.py:75-128): gradients are split into
``optimizer.num_apply_group`` weight-balanced groups and applied one
group at a time, serialized.  On GPU the reference serializes with
control deps; here `jax.lax.optimization_barrier` between groups keeps
XLA from fusing them back into one peak.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax
import numpy as np

from easyparallellibrary_tpu.parallel.partitioner import partition_balance
from easyparallellibrary_tpu.utils.pytree import leaf_bytes


def _group_leaves(tree, num_groups: int) -> List[List[int]]:
  leaves = jax.tree_util.tree_leaves(tree)
  if num_groups >= len(leaves):
    return [[i] for i in range(len(leaves))]
  weights = [float(leaf_bytes(l)) for l in leaves]
  ranges = partition_balance(weights, num_groups)
  return [list(range(s, e)) for s, e in ranges]


def apply_grad_group(tx, params, grads, opt_state, num_apply_group: int):
  """Apply `tx` in `num_apply_group` serialized slices of the param tree.

  Returns (new_params, new_opt_state).  Note: correct for optimizers whose
  per-leaf update depends only on that leaf's state (Adam/SGD/AdamW-mask —
  the reference has the same constraint); global-norm optimizers must use
  group count 1.
  """
  import optax
  if num_apply_group <= 1:
    updates, new_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), new_state

  flat_params, treedef = jax.tree_util.tree_flatten(params)
  flat_grads, grads_def = jax.tree_util.tree_flatten(grads)
  groups = _group_leaves(params, num_apply_group)

  # One tx.update per group, serialized: each group's gradient inputs pass
  # through an optimization barrier that depends on the previous group's
  # result, so the calls cannot be CSE'd or overlapped, and dead-code
  # elimination trims each call to its group's leaves.  Peak memory is one
  # group's update tensors, not all of them.
  new_flat = list(flat_params)
  barrier_token = None
  new_state = None
  for gi, group in enumerate(groups):
    g_leaves = flat_grads
    if barrier_token is not None:
      chained = jax.lax.optimization_barrier(
          tuple(flat_grads) + (barrier_token,))
      g_leaves = list(chained[:-1])
    grads_g = jax.tree_util.tree_unflatten(grads_def, g_leaves)
    updates_g, state_g = tx.update(grads_g, opt_state, params)
    flat_updates = jax.tree_util.tree_leaves(updates_g)
    for i in group:
      new_flat[i] = flat_params[i] + flat_updates[i]
    barrier_token = new_flat[group[-1]]
    if gi == len(groups) - 1:
      # Only the final call's opt state is consumed; earlier calls' state
      # outputs are dead and DCE'd.
      new_state = state_g

  new_params = jax.tree_util.tree_unflatten(treedef, new_flat)
  return new_params, new_state
