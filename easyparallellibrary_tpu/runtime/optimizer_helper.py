"""Grouped optimizer apply — bound peak memory of the weight update.

Analog of the reference's ``apply_grad_group``
(epl/runtime/optimizer_helper.py:75-128): gradients are split into
``optimizer.num_apply_group`` weight-balanced groups and applied one
group at a time, serialized.  On GPU the reference serializes with
control deps; here `jax.lax.optimization_barrier` between groups keeps
XLA from fusing them back into one peak.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax
import numpy as np

from easyparallellibrary_tpu.parallel.partitioner import partition_balance
from easyparallellibrary_tpu.utils.pytree import leaf_bytes


def _group_leaves(tree, num_groups: int) -> List[List[int]]:
  leaves = jax.tree_util.tree_leaves(tree)
  if num_groups >= len(leaves):
    return [[i] for i in range(len(leaves))]
  weights = [float(leaf_bytes(l)) for l in leaves]
  ranges = partition_balance(weights, num_groups)
  return [list(range(s, e)) for s, e in ranges]


def apply_grad_group(tx, params, grads, opt_state, num_apply_group: int):
  """Apply `tx` in `num_apply_group` serialized slices of the param tree.

  Returns (new_params, new_opt_state).  Note: correct for optimizers whose
  per-leaf update depends only on that leaf's state (Adam/SGD/AdamW-mask —
  the reference has the same constraint); global-norm optimizers must use
  group count 1.
  """
  if num_apply_group <= 1:
    updates, new_state = tx.update(grads, opt_state, params)
    import optax
    return optax.apply_updates(params, updates), new_state

  import optax
  flat_params, treedef = jax.tree_util.tree_flatten(params)
  flat_grads = jax.tree_util.tree_leaves(grads)
  groups = _group_leaves(params, num_apply_group)

  # Run the full update once to get new opt state (leafwise it equals the
  # grouped result for per-leaf optimizers), then rebuild params group by
  # group with barriers so XLA materializes one group at a time.
  updates, new_state = tx.update(grads, opt_state, params)
  flat_updates = jax.tree_util.tree_leaves(updates)

  new_flat = list(flat_params)
  barrier_token = None
  for group in groups:
    group_updates = [flat_updates[i] for i in group]
    if barrier_token is not None:
      # Serialize: this group's inputs wait on the previous group.
      group_updates = list(jax.lax.optimization_barrier(
          tuple(group_updates) + (barrier_token,)))[:-1]
    for gi, i in enumerate(group):
      new_flat[i] = flat_params[i] + group_updates[gi]
    barrier_token = new_flat[group[-1]]

  new_params = jax.tree_util.tree_unflatten(treedef, new_flat)
  return new_params, new_state
