"""Grouped optimizer apply — bound peak memory of the weight update.

Analog of the reference's ``apply_grad_group``
(epl/runtime/optimizer_helper.py:75-128): gradients are split into
``optimizer.num_apply_group`` weight-balanced groups and applied one
group at a time, serialized.  On GPU the reference serializes with
control deps; here `jax.lax.optimization_barrier` between groups keeps
XLA from fusing them back into one peak.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax
import numpy as np

from easyparallellibrary_tpu.parallel.partitioner import partition_balance
from easyparallellibrary_tpu.utils.pytree import leaf_bytes


def _group_leaves(tree, num_groups: int) -> List[List[int]]:
  leaves = jax.tree_util.tree_leaves(tree)
  if num_groups >= len(leaves):
    return [[i] for i in range(len(leaves))]
  weights = [float(leaf_bytes(l)) for l in leaves]
  ranges = partition_balance(weights, num_groups)
  return [list(range(s, e)) for s, e in ranges]


def apply_grad_group(tx, params, grads, opt_state, num_apply_group: int):
  """Apply `tx` in `num_apply_group` serialized slices of the param tree.

  Returns (new_params, new_opt_state).  Note: correct for optimizers whose
  per-leaf update depends only on that leaf's state (Adam/SGD/AdamW-mask —
  the reference has the same constraint); global-norm optimizers must use
  group count 1.
  """
  import optax
  if num_apply_group <= 1:
    updates, new_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), new_state

  flat_params, treedef = jax.tree_util.tree_flatten(params)
  flat_grads, grads_def = jax.tree_util.tree_flatten(grads)
  groups = _group_leaves(params, num_apply_group)
  state_owner = _match_state_leaves_to_groups(params, opt_state, groups)

  # One tx.update per group, serialized: each group's gradient inputs pass
  # through an optimization barrier that depends on the previous group's
  # result, so the calls cannot be CSE'd or overlapped.  Each consumed
  # output — the group's param updates AND the state leaves owned by the
  # group (mu/nu mirrors matched by path+shape) — comes from that group's
  # call, so dead-code elimination trims every call to its group's
  # leaves: total FLOPs stay ~one full update and peak memory is one
  # group's update tensors, not all of them (verified by the FLOP-ratio
  # test in tests/test_runtime_features.py).
  new_flat = list(flat_params)
  state_paths, state_def = jax.tree_util.tree_flatten(opt_state)
  new_state_flat = [None] * len(state_paths)
  barrier_token = None
  for gi, group in enumerate(groups):
    g_leaves = flat_grads
    if barrier_token is not None:
      chained = jax.lax.optimization_barrier(
          tuple(flat_grads) + (barrier_token,))
      g_leaves = list(chained[:-1])
    grads_g = jax.tree_util.tree_unflatten(grads_def, g_leaves)
    updates_g, state_g = tx.update(grads_g, opt_state, params)
    flat_updates = jax.tree_util.tree_leaves(updates_g)
    flat_state_g = jax.tree_util.tree_leaves(state_g)
    for i in group:
      new_flat[i] = flat_params[i] + flat_updates[i]
    for j, owner in enumerate(state_owner):
      if owner == gi or (owner is None and gi == len(groups) - 1):
        # Unmatched leaves (shared scalars like Adam's count) come from
        # the last call.
        new_state_flat[j] = flat_state_g[j]
    barrier_token = new_flat[group[-1]]

  new_params = jax.tree_util.tree_unflatten(treedef, new_flat)
  new_state = jax.tree_util.tree_unflatten(state_def, new_state_flat)
  return new_params, new_state


def _match_state_leaves_to_groups(params, opt_state, groups):
  """Assign each optimizer-state leaf to the group of the param it
  mirrors (matched by key-path suffix + shape, which covers Adam-family
  mu/nu/trace trees); None = shared (e.g. the step count)."""

  def key_tuple(path):
    out = []
    for k in path:
      out.append(getattr(k, "key", getattr(k, "idx", None)) or str(k))
    return tuple(out)

  param_items = jax.tree_util.tree_flatten_with_path(params)[0]
  param_keys = [key_tuple(p) for p, _ in param_items]
  param_shapes = [np.shape(l) for _, l in param_items]
  group_of_param = {}
  for gi, group in enumerate(groups):
    for i in group:
      group_of_param[i] = gi

  owners = []
  for path, leaf in jax.tree_util.tree_flatten_with_path(opt_state)[0]:
    kt = key_tuple(path)
    shape = np.shape(leaf)
    owner = None
    best_len = 0
    for i, pk in enumerate(param_keys):
      # Longest (most specific) suffix wins: a top-level "kernel" must
      # not steal ownership of a nested ".../layer/kernel" state leaf.
      if len(pk) > best_len and shape == param_shapes[i] \
          and len(kt) >= len(pk) and kt[-len(pk):] == pk:
        owner = group_of_param[i]
        best_len = len(pk)
    owners.append(owner)
  return owners
