"""High-level training loop — `fit` with checkpointing, profiling, and
auto-resume.

The reference's users get this from MonitoredTrainingSession + hooks
(checkpoint saver hook, logging hooks, profiler hooks — all intercepted
in epl/parallel/hooks.py:279-472); here it is an explicit, composable
loop over the already-parallelized step function.  Restart-after-failure
is checkpoint-based: `fit` resumes from the newest checkpoint in
`checkpoint_dir` (the failure-recovery story the reference lacks beyond
kill-and-retry, SURVEY §5.3).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.registry import split_namespaces
from easyparallellibrary_tpu.profiler.profiler import StepProfiler
from easyparallellibrary_tpu.runtime import resilience as resilience_lib
from easyparallellibrary_tpu.runtime import saver
from easyparallellibrary_tpu.utils.logging import get_logger
from easyparallellibrary_tpu.utils.retry import (
    PERMANENT_IO_EXCEPTIONS, TRANSIENT_EXCEPTIONS)

def _accepts_start_step(factory: Callable) -> bool:
  """Whether a data factory declares a `start_step` parameter (the
  opt-in contract for resuming the input stream mid-epoch).  Only an
  explicitly named parameter opts in — a bare ``**kwargs`` does not, so
  pre-existing factories keep being called with no arguments."""
  import inspect
  try:
    params = inspect.signature(factory).parameters
  except (TypeError, ValueError):
    return False
  return "start_step" in params


def fit(step_fn: Callable,
        state,
        data: Iterable[Any],
        *,
        num_steps: int,
        rng=None,
        checkpoint_dir: str = "",
        checkpoint_every: int = 0,
        log_every: int = 50,
        profiler: Optional[StepProfiler] = None,
        shardings=None,
        checkpoint_on_preemption: bool = True,
        metrics_writer=None):
  """Run `num_steps` of `step_fn(state, batch, rng) -> (state, metrics)`.

  `data` yields batches (already global/sharded — see io.DevicePrefetcher).
  For more steps than one pass of `data`, pass a re-iterable (a list, or a
  zero-arg factory returning a fresh iterator) — one-shot iterators cannot
  be rewound.  A factory may instead accept a `start_step` keyword: fit
  then calls `data(start_step=N)` when resuming from a checkpoint at step
  N (and `start_step=0` on epoch restarts), so the factory can resume the
  INPUT stream mid-epoch too — e.g. by passing
  ``RecordReader(..., skip_records=(N * records_per_step) % shard_records)``
  (the modulo matters: an interrupted run that already wrapped an epoch
  must not skip past the end of the stream — fit restarts epochs exactly
  at exhaustion, so the in-epoch offset is the full position).  This is
  the input-position half of checkpoint/resume; the reference gets it
  from TF's dataset checkpointing.  The rng is folded with the step index
  each
  step, so stochastic layers (dropout) get fresh randomness.
  Returns (state, last_metrics).
  """
  log = get_logger()
  config = Env.get().config
  res = config.resilience
  obs = config.observability
  tracer = trace_lib.ensure_configured(config)
  # Device-truth introspection (observability/device.py): with
  # observability.device.enabled the first dispatched step's compiled
  # program is captured into a train/fit_step cost card (flops, wire
  # bytes, static HBM plan, donation-verified) and the HBM gauges ride
  # the periodic log cadence.
  from easyparallellibrary_tpu.observability import device as device_lib
  introspector = device_lib.ensure_configured(config)
  fit_step_captured = False
  rng = rng if rng is not None else jax.random.PRNGKey(0)
  start_step = int(state.step) if hasattr(state, "step") else 0

  # Never silently unlogged (observability.metrics_jsonl): with a
  # checkpoint dir and no explicit writer, build the leader-only JSONL
  # sink under the checkpoint dir behind the namespaced registry.  An
  # explicitly passed metrics_writer keeps its legacy flat keys.
  own_registry = None
  if metrics_writer is None and checkpoint_dir and obs.metrics_jsonl:
    from easyparallellibrary_tpu.observability.registry import (
        MetricRegistry)
    from easyparallellibrary_tpu.utils.metrics_writer import MetricsWriter
    # Flushing float()s buffered device values (a host sync), so the
    # period must stay > 1 even when periodic logging is off.
    own_registry = MetricRegistry(MetricsWriter(
        os.path.join(checkpoint_dir, "metrics.jsonl"),
        flush_every=log_every if log_every > 0 else 50))

  def _ckpt_tree(st):
    # Full training state: resuming with fresh optimizer moments would
    # silently change the trajectory (Adam bias-correction restarts).
    return {"params": st.params, "opt_state": st.opt_state}

  def _ckpt_shardings():
    if shardings is None:
      return None
    return {"params": shardings.params, "opt_state": shardings.opt_state}

  if checkpoint_dir:
    # One validated restore pass (validation sha256-reads every shard —
    # scanning via latest_step first would do all of that twice).  The
    # rare waste is restoring a checkpoint no newer than the live state
    # and discarding it.
    try:
      restored, rstep = saver.restore_checkpoint(
          checkpoint_dir, target=_ckpt_tree(state),
          shardings=_ckpt_shardings())
    except saver.NoValidCheckpointError as e:
      # Checkpoints exist but every one failed validation: silently
      # retraining from step 0 would throw the whole run away.  This
      # needs an operator (inspect the *.corrupt dirs, delete the root
      # to really start over).
      raise RuntimeError(
          f"refusing to start fresh: {checkpoint_dir!r} contains "
          f"checkpoints but none validate ({e})") from e
    except FileNotFoundError as e:
      if saver.has_quarantined(checkpoint_dir):
        # Only *.corrupt dirs remain (e.g. a restart right after the
        # refusal below quarantined everything): still not a fresh run.
        raise RuntimeError(
            f"refusing to start fresh: {checkpoint_dir!r} holds only "
            f"quarantined (*.corrupt) checkpoints; inspect or clear "
            f"them to really start over") from e
      restored, rstep = None, None  # fresh run
    if jax.process_count() > 1:
      # Each process validated the chain independently; a transient read
      # error on one host can make it fall back further than the others
      # (or find nothing).  Silent divergence at the first collective is
      # the worst outcome — compare the restored step against the
      # leader's and fail loudly on mismatch.
      import numpy as _np
      from jax.experimental import multihost_utils
      mine = -1 if rstep is None else int(rstep)
      agreed = int(multihost_utils.broadcast_one_to_all(_np.int32(mine)))
      if agreed != mine:
        raise RuntimeError(
            f"multi-host resume disagreement: leader restored step "
            f"{agreed} but process {jax.process_index()} restored "
            f"{mine} from {checkpoint_dir!r} — refusing to train on "
            f"diverged states")
    if restored is not None and rstep is not None \
        and int(rstep) > start_step:
      rstep = int(rstep)
      log.info("resuming from %s at step %d", checkpoint_dir, rstep)
      state = state.replace(params=restored["params"],
                            opt_state=restored["opt_state"], step=rstep)
      start_step = rstep

  # Preemption handling (beyond the reference's kill-and-retry, SURVEY
  # §5.3): on SIGTERM, finish the in-flight step, checkpoint, and exit so
  # the scheduler can requeue and `fit` resumes from the checkpoint.
  preempted = {"flag": False}
  prev_handler = None
  handler_installed = False
  if checkpoint_on_preemption and checkpoint_dir:
    def _on_sigterm(signum, frame):
      preempted["flag"] = True
    try:
      prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
      handler_installed = True
    except ValueError:  # not the main thread
      prev_handler = None

  def _make_iter(at_step: int):
    if not callable(data):
      return iter(data)
    if _accepts_start_step(data):
      return iter(data(start_step=at_step))
    return iter(data())

  io_retries = {"n": 0}

  def _next_with_retry(it):
    """`next(it)` with transient-IO retry (resilience.io_retries).

    StopIteration from a CLEAN first attempt propagates — that is the
    epoch boundary.  StopIteration raised on a RETRY attempt means the
    iterator was a generator that died on the original error (an
    exhausted generator raises StopIteration forever after), so the
    original exception is re-raised instead of being mistaken for an
    epoch end and silently restarting the stream.
    """
    try:
      return next(it)
    except TRANSIENT_EXCEPTIONS as e:
      if res.io_retries <= 0 or isinstance(e, PERMANENT_IO_EXCEPTIONS):
        raise
      delay, last = res.io_retry_backoff_s, e
      for attempt in range(res.io_retries):
        log.warning("transient data-iterator failure (attempt %d/%d): %s "
                    "— retrying in %.2fs", attempt + 1,
                    res.io_retries + 1, last, delay)
        io_retries["n"] += 1
        if delay:
          time.sleep(delay)
        delay = min(delay * 2 if delay else 0.0, 2.0)
        try:
          return next(it)
        except StopIteration:
          raise last from None
        except TRANSIENT_EXCEPTIONS as e2:
          if isinstance(e2, PERMANENT_IO_EXCEPTIONS):
            raise  # deterministic error surfaced mid-retry: stop here
          last = e2
      raise last

  watchdog = None
  if res.step_timeout_s > 0:
    watchdog = resilience_lib.StepWatchdog(res.step_timeout_s)

  # Anomaly escalation: the sentinel counts consecutive bad steps
  # ON-DEVICE (runtime/resilience.py); the host reads the counter once
  # per max_bad_steps window — amortized, never per step, so the guard
  # adds no per-step sync.  Past the threshold: roll back to the newest
  # valid checkpoint (resilience.rollback) or fail fast.
  check_every = res.max_bad_steps if res.max_bad_steps > 0 else 0
  # `trigger` is the step index at which the last rollback fired: only
  # surviving PAST that point counts as progress and resets the
  # consecutive-rollback counter — a clean replayed prefix must not,
  # or a deterministic fault far from the checkpoint would defeat the
  # MAX_CONSECUTIVE_ROLLBACKS give-up and replay the same window forever.
  rollbacks = {"total": 0, "consecutive": 0, "trigger": -1}
  # Totals already forwarded to the profiler's note_bad_step/note_retry
  # counters (both StepProfiler and FlopsProfiler expose them).
  fed = {"bad": 0, "retries": 0}

  def _rollback(state, bad, at_step):
    log.error(
        "anomaly sentinel: %d consecutive non-finite steps at step %d — "
        "rolling back to the newest valid checkpoint", bad, at_step + 1)
    from easyparallellibrary_tpu.runtime import amp as amp_lib
    bad_params = amp_lib.nonfinite_report(state.params)
    if bad_params:
      # The jnp.where skip normally keeps params clean; non-finite live
      # params here mean the poison predates the sentinel (or it was
      # enabled mid-run) — name the tensors for the post-mortem.
      log.error("non-finite live params at rollback: %s", bad_params)
    if not checkpoint_dir:
      raise RuntimeError(
          "anomaly rollback requires checkpoint_dir; pass one to fit() "
          "or set resilience.max_bad_steps=0")
    try:
      restored, rstep = saver.restore_checkpoint(
          checkpoint_dir, target=_ckpt_tree(state),
          shardings=_ckpt_shardings())
    except FileNotFoundError as e:
      raise RuntimeError(
          f"anomaly rollback at step {at_step + 1} failed: no valid "
          f"checkpoint under {checkpoint_dir!r}") from e
    rstep = int(rstep) if rstep is not None else 0
    state = state.replace(params=restored["params"],
                          opt_state=restored["opt_state"], step=rstep)
    if getattr(state, "sentinel", None) is not None:
      state = state.replace(sentinel=resilience_lib.SentinelState.create())
    if res.rollback_lr_backoff < 1.0:
      # The restore just reset opt_state to the checkpoint's LR, so the
      # factor must COMPOUND over consecutive rollbacks to the same
      # checkpoint or repeat rollbacks would all run at the same LR.
      factor = res.rollback_lr_backoff ** rollbacks["consecutive"]
      new_opt, applied = resilience_lib.backoff_learning_rate(
          state.opt_state, factor)
      if applied:
        state = state.replace(opt_state=new_opt)
        log.warning("rollback: learning rate backed off by %.3g "
                    "(rollback #%d since last progress)", factor,
                    rollbacks["consecutive"])
      else:
        log.warning(
            "resilience.rollback_lr_backoff=%.3g requested but the "
            "optimizer state does not expose a learning_rate "
            "hyperparameter (build it with optax.inject_hyperparams); "
            "continuing without backoff", res.rollback_lr_backoff)
    log.warning("rolled back to step %d; replaying", rstep)
    return state

  it = _make_iter(start_step)
  metrics: Dict[str, Any] = {}
  step_idx = start_step
  try:
    while step_idx < num_steps:
      if preempted["flag"]:
        log.warning("preemption signal received: checkpointing at step %d "
                    "and exiting", step_idx)
        saver.save_checkpoint(checkpoint_dir, _ckpt_tree(state),
                              step=step_idx)
        raise SystemExit(0)
      if watchdog is not None:
        watchdog.arm(step_idx)
      # One sampling decision per step: every train/* phase span below
      # gates on it, so a sampled step keeps its FULL phase set even
      # when a phase only runs some steps (host sync on log boundaries).
      step_rec = tracer.sample_tick("train")
      with tracer.span("train/data_next", cat="train", track="train",
                       record=step_rec):
        try:
          batch = _next_with_retry(it)
        except StopIteration:
          if step_idx == start_step and start_step > 0:
            # The resumed stream produced nothing: almost always a
            # skip_records that overran the shard (missing the modulo in
            # the recipe above) — restarting at record 0 would silently
            # train on a different data order than the uninterrupted run.
            log.warning(
                "data factory resumed at start_step=%d yielded no "
                "batches; restarting the stream from its beginning.  If "
                "the factory skips records, skip (start_step * "
                "records_per_step) MODULO the shard's record count.",
                start_step)
          # Epoch boundary: restart the stream from its beginning.
          it = _make_iter(0)
          try:
            batch = _next_with_retry(it)
          except StopIteration:
            raise RuntimeError(
                "data iterator exhausted and could not be restarted; "
                "pass a re-iterable (list) or a zero-arg iterator "
                "factory to fit() for multi-epoch runs") from None
      step_specs = None
      if introspector is not None and not fit_step_captured:
        # Abstract specs BEFORE the dispatch — a donating step's inputs
        # must still exist when described (shapes/dtypes only).
        step_specs = device_lib.specs_of(
            (state, batch, jax.random.fold_in(rng, step_idx)))
      # The span measures DISPATCH (async): device time surfaces at the
      # next host sync, which the flush/log spans below then cover.
      with tracer.span("train/step_dispatch", cat="train", track="train",
                       record=step_rec):
        state, metrics = step_fn(state, batch,
                                 jax.random.fold_in(rng, step_idx))
      if step_specs is not None:
        # Warmup cost card for the fit step (capture_twin is defensive:
        # a step_fn without the AOT surface — a plain function, a chaos
        # wrapper — degrades to a logged skip).  parallelize() wrappers
        # expose the underlying jit as `.jitted` (same arg signature —
        # the wrapper passes straight through).
        fit_step_captured = True
        introspector.capture_twin("train/fit_step",
                                  getattr(step_fn, "jitted", step_fn),
                                  step_specs, compile_count=1)
        if own_registry is not None:
          introspector.publish_hbm(step_idx + 1, registry=own_registry)
      if watchdog is not None:
        watchdog.disarm()
      if check_every and (step_idx + 1) % check_every == 0 \
          and "bad_steps" in metrics:
        # epl-lint: disable=host-sync — the sentinel's designed read: one
        # sync per max_bad_steps window, amortized, never per step
        bad = int(metrics["bad_steps"])
        if profiler is not None and hasattr(profiler, "note_bad_step") \
            and "bad_steps_total" in metrics:
          # epl-lint: disable=host-sync — same amortized window as the
          # bad_steps read above; no additional per-step sync
          total_bad = int(metrics["bad_steps_total"])
          if total_bad > fed["bad"]:
            profiler.note_bad_step(total_bad - fed["bad"])
          fed["bad"] = total_bad
        if bad >= res.max_bad_steps:
          tracer.instant(
              "resilience/sentinel_escalation", cat="resilience",
              track="train",
              args={"bad_steps": bad, "step": step_idx + 1,
                    "action": "rollback" if res.rollback else "raise"})
          if not res.rollback:
            raise RuntimeError(
                f"{bad} consecutive non-finite steps at step "
                f"{step_idx + 1} (resilience.max_bad_steps="
                f"{res.max_bad_steps}, rollback off)")
          rollbacks["total"] += 1
          rollbacks["consecutive"] += 1
          if rollbacks["consecutive"] > \
              resilience_lib.MAX_CONSECUTIVE_ROLLBACKS:
            raise RuntimeError(
                f"{rollbacks['consecutive']} rollbacks without a clean "
                f"window in between — the anomaly is not transient; "
                f"giving up at step {step_idx + 1}")
          with tracer.span("resilience/rollback", cat="resilience",
                           track="train"):
            state = _rollback(state, bad, step_idx)
          fed["bad"] = 0  # the sentinel counters were reset with the state
          rollbacks["trigger"] = step_idx
          step_idx = int(state.step)
          it = _make_iter(step_idx)
          continue  # the bad window is not checkpointed or logged
        if step_idx > rollbacks["trigger"]:
          rollbacks["consecutive"] = 0
      if profiler is not None:
        profiler.tick()
        if hasattr(profiler, "note_retry") and io_retries["n"] > \
            fed["retries"]:
          profiler.note_retry(io_retries["n"] - fed["retries"])
          fed["retries"] = io_retries["n"]
      out = metrics
      if io_retries["n"] or rollbacks["total"]:
        out = {**metrics, "io_retries": io_retries["n"],
               "rollbacks": rollbacks["total"]}
      if metrics_writer is not None:
        # Metrics arriving here are already merged global values
        # (parallel/metrics.py) — the writer is a pure sink, matching the
        # reference's summaries-over-merged-tensors contract
        # (epl/parallel/hooks.py:593-664).  Writers buffer raw device
        # values; construct them with flush_every=N so the host sync only
        # happens every N steps and async dispatch survives.  Host-side
        # resilience counters ride along when active.  (Legacy flat
        # keys; the auto-built registry below uses the namespaced
        # schema, observability/registry.py.)
        with tracer.span("train/metrics_flush", cat="train",
                         track="train", record=step_rec):
          metrics_writer.write(step_idx + 1, out)
      elif own_registry is not None:
        with tracer.span("train/metrics_flush", cat="train",
                         track="train", record=step_rec):
          own_registry.publish_many(step_idx + 1, split_namespaces(out))
      if (introspector is not None and own_registry is not None
          and log_every and (step_idx + 1) % log_every == 0):
        # HBM watermark gauges on the periodic log cadence (the
        # training twin of the serving engine's stats-cadence sample).
        introspector.publish_hbm(step_idx + 1, registry=own_registry)
      if log_every and (step_idx + 1) % log_every == 0:
        # float(loss) is the loop's periodic host sync point.
        with tracer.span("train/host_sync", cat="train", track="train",
                         record=step_rec):
          loss = metrics.get("loss")
          # epl-lint: disable=host-sync — the loop's ONE designated
          # periodic sync point (log_every boundary), wrapped in the
          # train/host_sync span precisely because it syncs
          loss_text = f"{float(loss):.5f}" if loss is not None else "n/a"
          log.info("step %d: loss %s", step_idx + 1, loss_text)
      if (checkpoint_dir and checkpoint_every
          and (step_idx + 1) % checkpoint_every == 0):
        saver.save_checkpoint(checkpoint_dir, _ckpt_tree(state),
                              step=step_idx + 1)
      step_idx += 1
  except KeyboardInterrupt:
    if checkpoint_on_preemption and checkpoint_dir:
      log.warning("KeyboardInterrupt: saving final checkpoint at step %d",
                  step_idx)
      try:
        saver.save_checkpoint(checkpoint_dir, _ckpt_tree(state),
                              step=step_idx)
      except Exception as e:
        # An interrupt landing mid-step can leave donated buffers behind;
        # a failed best-effort save must not mask the interrupt itself.
        log.error("final checkpoint on interrupt failed: %s", e)
    raise
  finally:
    # Restore the caller's SIGTERM disposition on EVERY exit path — an
    # exception escaping step_fn must not leave fit's handler installed
    # for the rest of the process.
    if handler_installed and prev_handler is not None:
      signal.signal(signal.SIGTERM, prev_handler)
    if watchdog is not None:
      watchdog.close()
    if own_registry is not None:
      try:
        if profiler is not None and hasattr(profiler, "publish"):
          # End-of-run StepProfiler rollup joins the same schema.
          profiler.publish(own_registry, step_idx)
        own_registry.close()
      except Exception as e:  # must not mask the real exit
        log.error("metrics flush on exit failed: %s", e)
    if tracer.enabled:
      # Export on EVERY exit path: the trace matters most when the run
      # died ("what happened between step 400 and the rollback").
      path = obs.trace_path or (os.path.join(checkpoint_dir, "trace.json")
                                if checkpoint_dir else "")
      if path:
        try:
          tracer.export(path)
        except Exception as e:  # must not mask the real exit
          log.error("trace export to %s failed: %s", path, e)
  if profiler is not None and profiler.summary():
    log.info("training profile: %s", profiler.summary())
  return state, metrics


def evaluate(eval_fn: Callable,
             state,
             data: Iterable[Any],
             *,
             max_batches: int = 0,
             rng=None) -> Dict[str, float]:
  """Average `eval_fn(state, batch, rng) -> metrics` over `data`
  (the reference's Estimator-evaluate role, epl/parallel/hooks.py:906-984;
  metric merging across replicas is implicit under GSPMD)."""
  rng = rng if rng is not None else jax.random.PRNGKey(0)
  totals: Dict[str, float] = {}
  count = 0
  for i, batch in enumerate(data):
    if max_batches and i >= max_batches:
      break
    metrics = eval_fn(state, batch, rng)
    for k, v in metrics.items():
      totals[k] = totals.get(k, 0.0) + float(v)
    count += 1
  return {k: v / max(count, 1) for k, v in totals.items()}


def train_and_evaluate(step_fn: Callable, eval_fn: Callable, state,
                       train_data: Iterable[Any],
                       eval_data: Iterable[Any], *,
                       num_steps: int, eval_every: int,
                       max_eval_batches: int = 0, **fit_kwargs):
  """Interleave training with periodic evaluation (Estimator
  train_and_evaluate parity)."""
  log = get_logger()
  done = int(state.step) if hasattr(state, "step") else 0
  metrics = {}
  while done < num_steps:
    target = min(done + eval_every, num_steps)
    state, metrics = fit(step_fn, state, train_data, num_steps=target,
                         **fit_kwargs)
    done = target
    eval_metrics = evaluate(eval_fn, state, eval_data,
                            max_batches=max_eval_batches)
    log.info("eval @ step %d: %s", done, eval_metrics)
    metrics = {**metrics, **{f"eval_{k}": v
                             for k, v in eval_metrics.items()}}
  return state, metrics
