"""High-level training loop — `fit` with checkpointing, profiling, and
auto-resume.

The reference's users get this from MonitoredTrainingSession + hooks
(checkpoint saver hook, logging hooks, profiler hooks — all intercepted
in epl/parallel/hooks.py:279-472); here it is an explicit, composable
loop over the already-parallelized step function.  Restart-after-failure
is checkpoint-based: `fit` resumes from the newest checkpoint in
`checkpoint_dir` (the failure-recovery story the reference lacks beyond
kill-and-retry, SURVEY §5.3).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax

from easyparallellibrary_tpu.profiler.profiler import StepProfiler
from easyparallellibrary_tpu.runtime import saver
from easyparallellibrary_tpu.utils.logging import get_logger


def fit(step_fn: Callable,
        state,
        data: Iterable[Any],
        *,
        num_steps: int,
        rng=None,
        checkpoint_dir: str = "",
        checkpoint_every: int = 0,
        log_every: int = 50,
        profiler: Optional[StepProfiler] = None,
        shardings=None,
        checkpoint_on_preemption: bool = True):
  """Run `num_steps` of `step_fn(state, batch, rng) -> (state, metrics)`.

  `data` yields batches (already global/sharded — see io.DevicePrefetcher).
  For more steps than one pass of `data`, pass a re-iterable (a list, or a
  zero-arg factory returning a fresh iterator) — one-shot iterators cannot
  be rewound.  The rng is folded with the step index each step, so
  stochastic layers (dropout) get fresh randomness.
  Returns (state, last_metrics).
  """
  log = get_logger()
  rng = rng if rng is not None else jax.random.PRNGKey(0)
  start_step = int(state.step) if hasattr(state, "step") else 0

  def _ckpt_tree(st):
    # Full training state: resuming with fresh optimizer moments would
    # silently change the trajectory (Adam bias-correction restarts).
    return {"params": st.params, "opt_state": st.opt_state}

  def _ckpt_shardings():
    if shardings is None:
      return None
    return {"params": shardings.params, "opt_state": shardings.opt_state}

  if checkpoint_dir:
    last = saver.latest_step(checkpoint_dir)
    if last is not None and last > start_step:
      log.info("resuming from %s at step %d", checkpoint_dir, last)
      restored, _ = saver.restore_checkpoint(
          checkpoint_dir, target=_ckpt_tree(state),
          shardings=_ckpt_shardings())
      state = state.replace(params=restored["params"],
                            opt_state=restored["opt_state"], step=last)
      start_step = last

  # Preemption handling (beyond the reference's kill-and-retry, SURVEY
  # §5.3): on SIGTERM, finish the in-flight step, checkpoint, and exit so
  # the scheduler can requeue and `fit` resumes from the checkpoint.
  preempted = {"flag": False}
  prev_handler = None
  if checkpoint_on_preemption and checkpoint_dir:
    def _on_sigterm(signum, frame):
      preempted["flag"] = True
    try:
      prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread
      prev_handler = None

  it = iter(data() if callable(data) else data)
  metrics: Dict[str, Any] = {}
  for step_idx in range(start_step, num_steps):
    if preempted["flag"]:
      log.warning("preemption signal received: checkpointing at step %d "
                  "and exiting", step_idx)
      saver.save_checkpoint(checkpoint_dir, _ckpt_tree(state),
                            step=step_idx)
      if prev_handler is not None:
        signal.signal(signal.SIGTERM, prev_handler)
      raise SystemExit(0)
    try:
      batch = next(it)
    except StopIteration:
      it = iter(data() if callable(data) else data)
      try:
        batch = next(it)
      except StopIteration:
        raise RuntimeError(
            "data iterator exhausted and could not be restarted; pass a "
            "re-iterable (list) or a zero-arg iterator factory to fit() "
            "for multi-epoch runs") from None
    state, metrics = step_fn(state, batch,
                             jax.random.fold_in(rng, step_idx))
    if profiler is not None:
      profiler.tick()
    if log_every and (step_idx + 1) % log_every == 0:
      loss = metrics.get("loss")
      log.info("step %d: loss %s", step_idx + 1,
               f"{float(loss):.5f}" if loss is not None else "n/a")
    if (checkpoint_dir and checkpoint_every
        and (step_idx + 1) % checkpoint_every == 0):
      saver.save_checkpoint(checkpoint_dir, _ckpt_tree(state),
                            step=step_idx + 1)
  if prev_handler is not None:
    signal.signal(signal.SIGTERM, prev_handler)
  if profiler is not None and profiler.summary():
    log.info("training profile: %s", profiler.summary())
  return state, metrics


def evaluate(eval_fn: Callable,
             state,
             data: Iterable[Any],
             *,
             max_batches: int = 0,
             rng=None) -> Dict[str, float]:
  """Average `eval_fn(state, batch, rng) -> metrics` over `data`
  (the reference's Estimator-evaluate role, epl/parallel/hooks.py:906-984;
  metric merging across replicas is implicit under GSPMD)."""
  rng = rng if rng is not None else jax.random.PRNGKey(0)
  totals: Dict[str, float] = {}
  count = 0
  for i, batch in enumerate(data):
    if max_batches and i >= max_batches:
      break
    metrics = eval_fn(state, batch, rng)
    for k, v in metrics.items():
      totals[k] = totals.get(k, 0.0) + float(v)
    count += 1
  return {k: v / max(count, 1) for k, v in totals.items()}


def train_and_evaluate(step_fn: Callable, eval_fn: Callable, state,
                       train_data: Iterable[Any],
                       eval_data: Iterable[Any], *,
                       num_steps: int, eval_every: int,
                       max_eval_batches: int = 0, **fit_kwargs):
  """Interleave training with periodic evaluation (Estimator
  train_and_evaluate parity)."""
  log = get_logger()
  done = int(state.step) if hasattr(state, "step") else 0
  metrics = {}
  while done < num_steps:
    target = min(done + eval_every, num_steps)
    state, metrics = fit(step_fn, state, train_data, num_steps=target,
                         **fit_kwargs)
    done = target
    eval_metrics = evaluate(eval_fn, state, eval_data,
                            max_batches=max_eval_batches)
    log.info("eval @ step %d: %s", done, eval_metrics)
    metrics = {**metrics, **{f"eval_{k}": v
                             for k, v in eval_metrics.items()}}
  return state, metrics
