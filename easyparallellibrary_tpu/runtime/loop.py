"""High-level training loop — `fit` with checkpointing, profiling, and
auto-resume.

The reference's users get this from MonitoredTrainingSession + hooks
(checkpoint saver hook, logging hooks, profiler hooks — all intercepted
in epl/parallel/hooks.py:279-472); here it is an explicit, composable
loop over the already-parallelized step function.  Restart-after-failure
is checkpoint-based: `fit` resumes from the newest checkpoint in
`checkpoint_dir` (the failure-recovery story the reference lacks beyond
kill-and-retry, SURVEY §5.3).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax

from easyparallellibrary_tpu.profiler.profiler import StepProfiler
from easyparallellibrary_tpu.runtime import saver
from easyparallellibrary_tpu.utils.logging import get_logger


def _accepts_start_step(factory: Callable) -> bool:
  """Whether a data factory declares a `start_step` parameter (the
  opt-in contract for resuming the input stream mid-epoch).  Only an
  explicitly named parameter opts in — a bare ``**kwargs`` does not, so
  pre-existing factories keep being called with no arguments."""
  import inspect
  try:
    params = inspect.signature(factory).parameters
  except (TypeError, ValueError):
    return False
  return "start_step" in params


def fit(step_fn: Callable,
        state,
        data: Iterable[Any],
        *,
        num_steps: int,
        rng=None,
        checkpoint_dir: str = "",
        checkpoint_every: int = 0,
        log_every: int = 50,
        profiler: Optional[StepProfiler] = None,
        shardings=None,
        checkpoint_on_preemption: bool = True,
        metrics_writer=None):
  """Run `num_steps` of `step_fn(state, batch, rng) -> (state, metrics)`.

  `data` yields batches (already global/sharded — see io.DevicePrefetcher).
  For more steps than one pass of `data`, pass a re-iterable (a list, or a
  zero-arg factory returning a fresh iterator) — one-shot iterators cannot
  be rewound.  A factory may instead accept a `start_step` keyword: fit
  then calls `data(start_step=N)` when resuming from a checkpoint at step
  N (and `start_step=0` on epoch restarts), so the factory can resume the
  INPUT stream mid-epoch too — e.g. by passing
  ``RecordReader(..., skip_records=(N * records_per_step) % shard_records)``
  (the modulo matters: an interrupted run that already wrapped an epoch
  must not skip past the end of the stream — fit restarts epochs exactly
  at exhaustion, so the in-epoch offset is the full position).  This is
  the input-position half of checkpoint/resume; the reference gets it
  from TF's dataset checkpointing.  The rng is folded with the step index
  each
  step, so stochastic layers (dropout) get fresh randomness.
  Returns (state, last_metrics).
  """
  log = get_logger()
  rng = rng if rng is not None else jax.random.PRNGKey(0)
  start_step = int(state.step) if hasattr(state, "step") else 0

  def _ckpt_tree(st):
    # Full training state: resuming with fresh optimizer moments would
    # silently change the trajectory (Adam bias-correction restarts).
    return {"params": st.params, "opt_state": st.opt_state}

  def _ckpt_shardings():
    if shardings is None:
      return None
    return {"params": shardings.params, "opt_state": shardings.opt_state}

  if checkpoint_dir:
    last = saver.latest_step(checkpoint_dir)
    if last is not None and last > start_step:
      log.info("resuming from %s at step %d", checkpoint_dir, last)
      restored, _ = saver.restore_checkpoint(
          checkpoint_dir, target=_ckpt_tree(state),
          shardings=_ckpt_shardings())
      state = state.replace(params=restored["params"],
                            opt_state=restored["opt_state"], step=last)
      start_step = last

  # Preemption handling (beyond the reference's kill-and-retry, SURVEY
  # §5.3): on SIGTERM, finish the in-flight step, checkpoint, and exit so
  # the scheduler can requeue and `fit` resumes from the checkpoint.
  preempted = {"flag": False}
  prev_handler = None
  if checkpoint_on_preemption and checkpoint_dir:
    def _on_sigterm(signum, frame):
      preempted["flag"] = True
    try:
      prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread
      prev_handler = None

  def _make_iter(at_step: int):
    if not callable(data):
      return iter(data)
    if _accepts_start_step(data):
      return iter(data(start_step=at_step))
    return iter(data())

  it = _make_iter(start_step)
  metrics: Dict[str, Any] = {}
  for step_idx in range(start_step, num_steps):
    if preempted["flag"]:
      log.warning("preemption signal received: checkpointing at step %d "
                  "and exiting", step_idx)
      saver.save_checkpoint(checkpoint_dir, _ckpt_tree(state),
                            step=step_idx)
      if prev_handler is not None:
        signal.signal(signal.SIGTERM, prev_handler)
      raise SystemExit(0)
    try:
      batch = next(it)
    except StopIteration:
      if step_idx == start_step and start_step > 0:
        # The resumed stream produced nothing: almost always a
        # skip_records that overran the shard (missing the modulo in the
        # recipe above) — restarting at record 0 would silently train on
        # a different data order than the uninterrupted run.
        log.warning(
            "data factory resumed at start_step=%d yielded no batches; "
            "restarting the stream from its beginning.  If the factory "
            "skips records, skip (start_step * records_per_step) MODULO "
            "the shard's record count.", start_step)
      # Epoch boundary: restart the stream from its beginning.
      it = _make_iter(0)
      try:
        batch = next(it)
      except StopIteration:
        raise RuntimeError(
            "data iterator exhausted and could not be restarted; pass a "
            "re-iterable (list) or a zero-arg iterator factory to fit() "
            "for multi-epoch runs") from None
    state, metrics = step_fn(state, batch,
                             jax.random.fold_in(rng, step_idx))
    if profiler is not None:
      profiler.tick()
    if metrics_writer is not None:
      # Metrics arriving here are already merged global values
      # (parallel/metrics.py) — the writer is a pure sink, matching the
      # reference's summaries-over-merged-tensors contract
      # (epl/parallel/hooks.py:593-664).  Writers buffer raw device
      # values; construct them with flush_every=N so the host sync only
      # happens every N steps and async dispatch survives.
      metrics_writer.write(step_idx + 1, metrics)
    if log_every and (step_idx + 1) % log_every == 0:
      loss = metrics.get("loss")
      log.info("step %d: loss %s", step_idx + 1,
               f"{float(loss):.5f}" if loss is not None else "n/a")
    if (checkpoint_dir and checkpoint_every
        and (step_idx + 1) % checkpoint_every == 0):
      saver.save_checkpoint(checkpoint_dir, _ckpt_tree(state),
                            step=step_idx + 1)
  if prev_handler is not None:
    signal.signal(signal.SIGTERM, prev_handler)
  if profiler is not None and profiler.summary():
    log.info("training profile: %s", profiler.summary())
  return state, metrics


def evaluate(eval_fn: Callable,
             state,
             data: Iterable[Any],
             *,
             max_batches: int = 0,
             rng=None) -> Dict[str, float]:
  """Average `eval_fn(state, batch, rng) -> metrics` over `data`
  (the reference's Estimator-evaluate role, epl/parallel/hooks.py:906-984;
  metric merging across replicas is implicit under GSPMD)."""
  rng = rng if rng is not None else jax.random.PRNGKey(0)
  totals: Dict[str, float] = {}
  count = 0
  for i, batch in enumerate(data):
    if max_batches and i >= max_batches:
      break
    metrics = eval_fn(state, batch, rng)
    for k, v in metrics.items():
      totals[k] = totals.get(k, 0.0) + float(v)
    count += 1
  return {k: v / max(count, 1) for k, v in totals.items()}


def train_and_evaluate(step_fn: Callable, eval_fn: Callable, state,
                       train_data: Iterable[Any],
                       eval_data: Iterable[Any], *,
                       num_steps: int, eval_every: int,
                       max_eval_batches: int = 0, **fit_kwargs):
  """Interleave training with periodic evaluation (Estimator
  train_and_evaluate parity)."""
  log = get_logger()
  done = int(state.step) if hasattr(state, "step") else 0
  metrics = {}
  while done < num_steps:
    target = min(done + eval_every, num_steps)
    state, metrics = fit(step_fn, state, train_data, num_steps=target,
                         **fit_kwargs)
    done = target
    eval_metrics = evaluate(eval_fn, state, eval_data,
                            max_batches=max_eval_batches)
    log.info("eval @ step %d: %s", done, eval_metrics)
    metrics = {**metrics, **{f"eval_{k}": v
                             for k, v in eval_metrics.items()}}
  return state, metrics
