"""Config-driven train-step assembly — the orchestration layer.

This is the role of the reference's ``Parallel.do_parallelism``
(epl/parallel/parallel.py:211-231): read the `Config` and compose the
requested runtime features around the user's loss function, in the same
order the reference applies its passes — offload → micro-batching →
gradient aggregation → (scale/unscale) → apply — except here each pass is
a function wrapper instead of a graph rewrite.

Composition:
  * gradient accumulation when ``pipeline.num_micro_batch > 1`` without
    pipeline stages (reference gating: gradient_accumulation.py:40-50),
  * dynamic/fixed loss scaling when ``amp.level`` is set with an fp16
    policy (bf16 needs none),
  * remat per ``gradient_checkpoint.type``,
  * grouped optimizer apply per ``optimizer.num_apply_group``,
  * ZeRO + offload act on the *shardings* (see zero.py / offload.py) and
    are applied by `create_sharded_train_state` / `offload_to_host`,
  * metric-merge collections folded into returned metrics.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from easyparallellibrary_tpu import constants
from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.parallel.api import TrainState
from easyparallellibrary_tpu.runtime import amp as amp_lib
from easyparallellibrary_tpu.runtime.gradient_accumulation import (
    accumulate_gradients,
)
from easyparallellibrary_tpu.runtime import resilience as resilience_lib
from easyparallellibrary_tpu.runtime.optimizer_helper import apply_grad_group


class AmpTrainState(TrainState):
  """TrainState carrying a loss-scale (fp16 training)."""
  loss_scale: Any = None


def build_train_step(loss_fn: Optional[Callable] = None,
                     config=None,
                     use_loss_scale: Optional[bool] = None,
                     grad_fn: Optional[Callable] = None,
                     num_apply_group: Optional[int] = None) -> Callable:
  """Compose the configured runtime features around
  `loss_fn(params, batch, rng) -> (loss, aux)`.

  Alternatively pass `grad_fn(params, batch, rng, loss_scale=None) ->
  ((loss, aux), grads)` for paths that compute gradients manually (the
  1F1B pipeline schedule); it must honor `loss_scale` by seeding its
  backward with it and returning UNSCALED grads (inf/nan preserved for
  the finite check).  Micro-batch accumulation is skipped for a custom
  grad_fn (such paths own their micro-batching); loss scaling, overflow
  skipping, and grouped apply still compose around it.

  Returns `step(state, batch, rng) -> (state, metrics)`, ready for
  `parallel.api.parallelize`.
  """
  if (loss_fn is None) == (grad_fn is None):
    raise ValueError("pass exactly one of loss_fn / grad_fn")
  cfg = config if config is not None else Env.get().config

  ga_steps = 1
  if grad_fn is None and cfg.pipeline.num_micro_batch > 1 \
      and cfg.pipeline.num_stages <= 1:
    # Micro-batching without pipeline = gradient accumulation (the
    # reference applies the same rule, gradient_accumulation.py:40-50).
    ga_steps = cfg.pipeline.num_micro_batch

  scaled = use_loss_scale if use_loss_scale is not None else (
      cfg.amp.level and cfg.amp.loss_scale not in ("", "none", "0"))
  if num_apply_group is None:
    num_apply_group = cfg.optimizer.num_apply_group

  def _apply(state, grads):
    if num_apply_group > 1:
      new_params, new_opt = apply_grad_group(
          state.tx, state.params, grads, state.opt_state, num_apply_group)
      return state.replace(step=state.step + 1, params=new_params,
                           opt_state=new_opt)
    return state.apply_gradients(grads=grads)

  def step(state, batch, rng):
    if grad_fn is not None:
      (loss, aux), grads = grad_fn(
          state.params, batch, rng,
          loss_scale=state.loss_scale.scale if scaled else None)
    else:
      if scaled:
        g_fn = amp_lib.scaled_value_and_grad(
            loss_fn, state.loss_scale.scale, has_aux=True)
      else:
        g_fn = jax.value_and_grad(loss_fn, has_aux=True)
      g_fn = accumulate_gradients(g_fn, ga_steps)
      (loss, aux), grads = g_fn(state.params, batch, rng)

    # Whether the anomaly sentinel rides this step is a structural fact
    # of the state (resilience.attach_sentinel / create_train_state), so
    # the branch resolves at trace time — one compiled program either way.
    sentinel_on = getattr(state, "sentinel", None) is not None
    if scaled or sentinel_on:
      grads_finite = amp_lib.all_finite(grads)
      # The sentinel also screens the LOSS: under bf16 (no loss scale) a
      # NaN can surface in the loss with grads masked finite, and that
      # step must not advance the optimizer either.
      finite = grads_finite & resilience_lib.finite_check(loss) \
          if sentinel_on else grads_finite
      # Run the update, then select the OLD state wholesale on a bad
      # step — a true no-op (the reference conditionally skips the
      # apply, loss_scale.py:44-51; applying zeroed grads would still
      # run weight decay and advance optimizer moments).
      updated = _apply(state, grads)
      state = resilience_lib.select_state(finite, updated, state)
      metrics = {"loss": loss,
                 "grads_finite": finite.astype(jnp.float32)}
      if scaled:
        # The dynamic scale keeps its own contract: backoff is keyed on
        # gradient overflow alone (a NaN loss is the sentinel's call,
        # not a reason to shrink the scale).
        state = state.replace(
            loss_scale=state.loss_scale.update(grads_finite))
        metrics["loss_scale"] = state.loss_scale.scale
      if sentinel_on:
        sentinel = state.sentinel.update(finite)
        state = state.replace(sentinel=sentinel)
        metrics.update(resilience_lib.sentinel_metrics(sentinel, finite))
    else:
      state = _apply(state, grads)
      metrics = {"loss": loss}
    if aux:
      metrics.update(aux)
    return state, metrics

  return step


def create_train_state(apply_fn, params, tx, config=None):
  """TrainState factory honoring the AMP and resilience configs."""
  cfg = config if config is not None else Env.get().config
  extra = {}
  if resilience_lib.sentinel_enabled(cfg):
    extra["sentinel"] = resilience_lib.SentinelState.create()
  if cfg.amp.level and cfg.amp.loss_scale not in ("", "none", "0"):
    if cfg.amp.loss_scale == "dynamic":
      scale = amp_lib.DynamicLossScale.create()
    else:
      scale = amp_lib.fixed_loss_scale(float(cfg.amp.loss_scale))
    return AmpTrainState.create(apply_fn=apply_fn, params=params, tx=tx,
                                loss_scale=scale, **extra)
  return TrainState.create(apply_fn=apply_fn, params=params, tx=tx, **extra)
