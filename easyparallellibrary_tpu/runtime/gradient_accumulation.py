"""Gradient accumulation — large effective batch without pipeline.

TPU-native redesign of the reference's GA
(epl/runtime/gradient_accumulation.py): the reference keeps accumulator
variables + an iteration counter and gates `apply` with a `cond` every n
session runs (:90-136), because its unit of work is one `session.run`.
Here one jitted step owns the whole accumulation: the batch is split into
``num_micro_batch`` slices and reduced with `lax.scan` — the optimizer
applies exactly once per step, no counter, no slot-clearing ops, and XLA
overlaps the micro-batch pipelines.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def accumulate_gradients(grad_fn: Callable, num_micro_batch: int):
  """Wrap `grad_fn(params, batch, rng) -> ((loss, aux), grads)` to average
  over micro-batch slices of the leading batch dim."""
  if num_micro_batch <= 1:
    return grad_fn

  def accumulated(params, batch, rng):
    from easyparallellibrary_tpu.utils.pytree import split_micro_batches
    micro = split_micro_batches(batch, num_micro_batch)

    def body(carry, inp):
      i, mb = inp
      (loss_sum, aux_sum, grads_sum) = carry
      # Distinct rng per micro-batch: reusing one rng would give identical
      # dropout masks across slices, diverging from full-batch semantics.
      mb_rng = None if rng is None else jax.random.fold_in(rng, i)
      (loss, aux), grads = grad_fn(params, mb, mb_rng)
      grads_sum = jax.tree_util.tree_map(jnp.add, grads_sum, grads)
      aux_sum = jax.tree_util.tree_map(jnp.add, aux_sum, aux)
      return (loss_sum + loss, aux_sum, grads_sum), None

    # Zero carries from abstract shapes — every micro-batch (including the
    # first) goes through the scan, so aux metrics cover all of them.
    first = jax.tree_util.tree_map(lambda x: x[0], micro)
    (l_s, aux_s), g_s = jax.eval_shape(grad_fn, params, first, rng)
    zeros = lambda t: jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), t)
    carry0 = (jnp.zeros(l_s.shape, l_s.dtype), zeros(aux_s), zeros(g_s))
    (loss_sum, aux_sum, grads_sum), _ = jax.lax.scan(
        body, carry0, (jnp.arange(num_micro_batch), micro))
    inv = 1.0 / num_micro_batch
    scale = lambda t: jax.tree_util.tree_map(
        lambda x: x * jnp.asarray(inv, x.dtype), t)
    return (loss_sum * inv, scale(aux_sum)), scale(grads_sum)

  return accumulated
