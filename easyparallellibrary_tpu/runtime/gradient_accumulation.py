"""Gradient accumulation — large effective batch without pipeline.

TPU-native redesign of the reference's GA
(epl/runtime/gradient_accumulation.py): the reference keeps accumulator
variables + an iteration counter and gates `apply` with a `cond` every n
session runs (:90-136), because its unit of work is one `session.run`.
Here one jitted step owns the whole accumulation: the batch is split into
``num_micro_batch`` slices and reduced with `lax.scan` — the optimizer
applies exactly once per step, no counter, no slot-clearing ops, and XLA
overlaps the micro-batch pipelines.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def accumulate_gradients(grad_fn: Callable, num_micro_batch: int):
  """Wrap `grad_fn(params, batch, rng) -> ((loss, aux), grads)` to average
  over micro-batch slices of the leading batch dim."""
  if num_micro_batch <= 1:
    return grad_fn

  def split(batch):
    def reshape(x):
      b = x.shape[0]
      if b % num_micro_batch != 0:
        raise ValueError(
            f"batch {b} not divisible by num_micro_batch {num_micro_batch}")
      return x.reshape((num_micro_batch, b // num_micro_batch) + x.shape[1:])
    return jax.tree_util.tree_map(reshape, batch)

  def accumulated(params, batch, rng):
    micro = split(batch)

    def body(carry, mb):
      (loss_sum, aux_sum, grads_sum) = carry
      (loss, aux), grads = grad_fn(params, mb, rng)
      grads_sum = jax.tree_util.tree_map(jnp.add, grads_sum, grads)
      aux_sum = jax.tree_util.tree_map(jnp.add, aux_sum, aux)
      return (loss_sum + loss, aux_sum, grads_sum), None

    # Peek shapes with the first micro-batch to build zero carries.
    first = jax.tree_util.tree_map(lambda x: x[0], micro)
    (l0, aux0), g0 = grad_fn(params, first, rng)
    zero_aux = jax.tree_util.tree_map(jnp.zeros_like, aux0)
    zero_g = jax.tree_util.tree_map(jnp.zeros_like, g0)
    rest = jax.tree_util.tree_map(lambda x: x[1:], micro)
    (loss_sum, aux_sum, grads_sum), _ = jax.lax.scan(
        body, (l0, zero_aux, g0), rest)
    inv = 1.0 / num_micro_batch
    scale = lambda t: jax.tree_util.tree_map(
        lambda x: x * jnp.asarray(inv, x.dtype), t)
    return (loss_sum * inv, scale(aux_sum)), scale(grads_sum)

  return accumulated
