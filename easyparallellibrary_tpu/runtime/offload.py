"""Host-DRAM offload of parameters / optimizer state.

TPU-native analog of the reference's CPU weight offload
(`offload.level = "v0"`, epl/parallel/graph_editor.py:727-751, which pins
variables to `/device:CPU`): on TPU, arrays are placed in the chip's host
memory via sharding ``memory_kind="pinned_host"``; XLA streams them to
HBM around the ops that need them.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding

from easyparallellibrary_tpu.utils.logging import get_logger

HOST_MEMORY_KIND = "pinned_host"
DEVICE_MEMORY_KIND = "device"


def _supports_memory_kind(sharding: NamedSharding, kind: str) -> bool:
  try:
    sharding.with_memory_kind(kind)
    return True
  except Exception:
    return False


def offload_to_host(shardings, what: str = "opt_state"):
  """Retarget a TrainState shardings pytree so `opt_state` (and optionally
  `params`) live in host memory.

  `what`: "opt_state" (reference v0 semantics: weights stay, optimizer
  state offloads best on TPU) | "params" | "all".
  """
  def to_host(s):
    if isinstance(s, NamedSharding) and _supports_memory_kind(
        s, HOST_MEMORY_KIND):
      return s.with_memory_kind(HOST_MEMORY_KIND)
    return s

  if not hasattr(shardings, "opt_state"):
    return jax.tree_util.tree_map(
        to_host, shardings,
        is_leaf=lambda x: isinstance(x, NamedSharding))

  new = shardings
  if what in ("opt_state", "all"):
    new = new.replace(opt_state=jax.tree_util.tree_map(
        to_host, new.opt_state,
        is_leaf=lambda x: isinstance(x, NamedSharding)))
  if what in ("params", "all"):
    new = new.replace(params=jax.tree_util.tree_map(
        to_host, new.params,
        is_leaf=lambda x: isinstance(x, NamedSharding)))
  probe = jax.tree_util.tree_leaves(
      new, is_leaf=lambda x: isinstance(x, NamedSharding))
  if probe and not _supports_memory_kind(probe[0], HOST_MEMORY_KIND):
    get_logger().warning(
        "offload requested but this backend has no %s memory; shardings "
        "unchanged", HOST_MEMORY_KIND)
  return new
