"""Sharded checkpoint save/restore with resharding-at-load.

TPU-native analog of the reference's saver stack (epl/runtime/saver.py):

  * ``MemoryEfficientBuilder`` (:145-207) — save ops sharded into ≤50 MB
    buckets with serialized IO to bound host memory → here the leaf
    arrays are bucketed by the same bound and written one bucket at a
    time (`.npz` shards + a JSON index).
  * ``ShardingLoader`` (:46-128) — restore with a variable→checkpoint
    assign-map and per-variable begin/size slices → `restore_checkpoint`
    takes `assign_map` (regex rename) and slices loaded tensors to the
    target shape with per-leaf offsets.
  * save-only-on-leader semantics (reference hooks.py:542-590: only the
    first constructor saves) → only process 0 writes; every process can
    restore (resharding onto the live mesh is a `device_put` with the
    target shardings — GSPMD's version of the reference's slice-based
    reshard).

An orbax-backed path is available for production multi-host async
checkpointing (`use_orbax=True`); the native format keeps the framework
dependency-free and transparent.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from easyparallellibrary_tpu import constants
from easyparallellibrary_tpu.utils.logging import get_logger
from easyparallellibrary_tpu.utils.pytree import (
    path_str, tree_paths_and_leaves)

INDEX_FILE = "index.json"


def _unbox(tree):
  import flax.linen as nn
  return nn.unbox(tree)


def _is_box(x) -> bool:
  import flax.linen as nn
  return isinstance(x, nn.meta.AxisMetadata)


def _boxed_paths_and_leaves(tree):
  """Like tree_paths_and_leaves but stops at metadata boxes, so padded
  params can be recognized (paths are identical either way — boxes sit
  exactly at leaf positions)."""
  flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=_is_box)
  return [(path_str(path), leaf) for path, leaf in flat]


def _logical_shape(leaf) -> Optional[Tuple[int, ...]]:
  """The attested unpadded shape of a PaddedPartitioned leaf, when it
  differs from the stored value's shape (ops/layers.py)."""
  ls = getattr(leaf, "logical_shape", None)
  if ls is None:
    return None
  value = leaf.unbox() if _is_box(leaf) else leaf
  return tuple(ls) if tuple(ls) != tuple(value.shape) else None


def _rebox_like(template, tree):
  """Put restored values back inside the template's metadata boxes, so a
  restored tree is a drop-in replacement for live (boxed) params."""
  import flax.linen as nn
  is_box = lambda x: isinstance(x, nn.meta.AxisMetadata)
  flat_t, tdef = jax.tree_util.tree_flatten(template, is_leaf=is_box)
  flat_v = jax.tree_util.tree_leaves(tree)
  out = [t.replace_boxed(v) if is_box(t) else v
         for t, v in zip(flat_t, flat_v)]
  return jax.tree_util.tree_unflatten(tdef, out)


def save_checkpoint(directory: str, tree, step: Optional[int] = None,
                    shard_mb: Optional[int] = None) -> str:
  """Write `tree` under `directory` (leader process only).

  Returns the checkpoint path.  Leaves are fetched and written bucket by
  bucket (≤ `shard_mb`, default 50 MB — reference saver.py:148) so host
  memory stays bounded.

  Multi-host: EVERY process must call this (arrays sharded across hosts
  are all-gathered collectively); only process 0 writes, and all
  processes synchronize before returning so a follow-up restore cannot
  race the write.
  """
  multihost = jax.process_count() > 1
  is_leader = jax.process_index() == 0
  shard_mb = shard_mb or constants.DEFAULT_SAVE_SHARD_MB
  limit = shard_mb * 1024 * 1024
  if is_leader:
    os.makedirs(directory, exist_ok=True)

  flat = _boxed_paths_and_leaves(tree)
  index: Dict[str, Any] = {"step": step, "leaves": {}, "shards": []}
  bucket: List[Tuple[str, Any]] = []
  bucket_bytes = 0
  shard_id = 0

  def fetch(leaf) -> np.ndarray:
    if multihost and isinstance(leaf, jax.Array) and \
        not leaf.is_fully_addressable:
      # Collective: every process participates in gathering the global
      # value; only the leader keeps it.
      from jax.experimental import multihost_utils
      return np.asarray(multihost_utils.process_allgather(
          leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))

  def flush():
    nonlocal bucket, bucket_bytes, shard_id
    if not bucket:
      return
    fname = f"shard_{shard_id:05d}.npz"
    arrays = {}
    for path, leaf in bucket:
      logical = _logical_shape(leaf)
      host = fetch(leaf.unbox() if _is_box(leaf) else leaf)
      if logical is not None:
        # Layout portability (reference ShardingLoader role,
        # epl/runtime/saver.py:46-128): pad regions are attested zeros —
        # checkpoints always store LOGICAL shapes, so a load under a
        # different model-axis size or tensor_split setting re-pads to
        # whatever that layout needs.
        host = host[tuple(slice(0, l) for l in logical)]
      arrays[path] = host
      index["leaves"][path] = {
          "shard": fname, "shape": list(host.shape),
          "dtype": str(host.dtype)}
    if is_leader:
      np.savez(os.path.join(directory, fname), **arrays)
    index["shards"].append(fname)
    shard_id += 1
    bucket, bucket_bytes = [], 0

  for path, leaf in flat:
    # Size from the unboxed value: metadata boxes expose no shape/dtype,
    # and a 4-byte default would put everything in one bucket, defeating
    # the host-memory bound.
    value = leaf.unbox() if _is_box(leaf) else leaf
    nbytes = int(np.prod(getattr(value, "shape", ()) or (1,))) * \
        jnp.dtype(getattr(value, "dtype", jnp.float32)).itemsize
    if bucket and bucket_bytes + nbytes > limit:
      flush()
    bucket.append((path, leaf))
    bucket_bytes += nbytes
  flush()

  if is_leader:
    with open(os.path.join(directory, INDEX_FILE), "w") as f:
      json.dump(index, f, indent=1)
    get_logger().info("saved checkpoint: %s (%d leaves, %d shards)",
                      directory, len(index["leaves"]), shard_id)
  if multihost:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(f"epl_save_{directory}")
  return directory


def _apply_assign_map(path: str, assign_map: Optional[Dict[str, str]]
                      ) -> str:
  """Regex rename, first match wins (reference ShardingLoader assign-map,
  saver.py:46-90)."""
  if not assign_map:
    return path
  for pattern, repl in assign_map.items():
    new, n = re.subn(pattern, repl, path)
    if n:
      return new
  return path


def _slice_to_shape(value: np.ndarray, shape: Tuple[int, ...],
                    offsets: Optional[Tuple[int, ...]] = None,
                    logical_shape: Optional[Tuple[int, ...]] = None
                    ) -> np.ndarray:
  """begin/size slicing at load (reference saver.py:91-128); with
  `logical_shape` (target is a PaddedPartitioned param attesting that
  shape) a stored value matching the logical shape exactly is zero-padded
  up to the target — the re-padding half of layout portability.  Padding
  may only fabricate regions known to be zero, so a stored value that
  does NOT cover the whole logical region is a hard error, never silently
  zero-filled."""
  if tuple(value.shape) == tuple(shape):
    return value
  if len(value.shape) != len(shape):
    raise ValueError(f"rank mismatch restoring {value.shape} -> {shape}")
  if logical_shape is not None and any(
      v < s for v, s in zip(value.shape, shape)):
    if tuple(value.shape) != tuple(logical_shape):
      raise ValueError(
          f"stored shape {tuple(value.shape)} does not match the target's "
          f"attested logical shape {tuple(logical_shape)}; refusing to "
          f"zero-pad into the logical region (padded target {tuple(shape)})")
    pad = [(0, max(0, s - v)) for v, s in zip(value.shape, shape)]
    value = np.pad(value, pad)
    if tuple(value.shape) == tuple(shape):
      return value
  offsets = offsets or (0,) * len(shape)
  slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
  if any(o + s > v for o, s, v in zip(offsets, shape, value.shape)):
    raise ValueError(
        f"slice {offsets}+{shape} out of bounds for stored {value.shape}")
  return value[slices]


def restore_checkpoint(directory: str,
                       target=None,
                       shardings=None,
                       assign_map: Optional[Dict[str, str]] = None,
                       slice_offsets: Optional[Dict[str, Tuple[int, ...]]]
                       = None):
  """Restore a checkpoint.

  * `target` (optional) — a pytree giving structure/shapes; loaded values
    are sliced to each leaf's shape (resharding-at-load) and the result
    has `target`'s treedef.  Without it, returns {path: array}.
  * `shardings` — matching pytree of NamedShardings; loaded values are
    `device_put` onto them (the GSPMD reshard).
  * `assign_map` — {regex: replacement} applied to *target* paths to find
    the checkpoint name.
  """
  with open(os.path.join(directory, INDEX_FILE)) as f:
    index = json.load(f)

  cache: Dict[str, Any] = {}

  def load_leaf(ckpt_path: str) -> np.ndarray:
    info = index["leaves"].get(ckpt_path)
    if info is None:
      raise KeyError(
          f"checkpoint {directory} has no tensor '{ckpt_path}'; "
          f"available: {sorted(index['leaves'])[:8]}...")
    shard = info["shard"]
    if shard not in cache:
      cache[shard] = np.load(os.path.join(directory, shard))
    return cache[shard][ckpt_path]

  if target is None:
    out = {p: load_leaf(p) for p in index["leaves"]}
    return out, index.get("step")

  flat_boxed, _ = jax.tree_util.tree_flatten_with_path(
      target, is_leaf=_is_box)
  target_unboxed = _unbox(target)
  flat, treedef = jax.tree_util.tree_flatten_with_path(target_unboxed)
  new_leaves = []
  for (path, leaf), (_, boxed) in zip(flat, flat_boxed):
    pstr = path_str(path)
    ckpt_name = _apply_assign_map(pstr, assign_map)
    value = load_leaf(ckpt_name)
    offs = (slice_offsets or {}).get(pstr)
    value = _slice_to_shape(
        value, tuple(np.shape(leaf)), offs,
        logical_shape=_logical_shape(boxed))
    value = value.astype(np.asarray(leaf).dtype
                         if not hasattr(leaf, "dtype") else leaf.dtype)
    new_leaves.append(value)
  restored = jax.tree_util.tree_unflatten(treedef, new_leaves)

  if shardings is not None:
    flat_shard = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    flat_restored = jax.tree_util.tree_leaves(restored)
    placed = [jax.device_put(v, s)
              for v, s in zip(flat_restored, flat_shard)]
    restored = jax.tree_util.tree_unflatten(treedef, placed)
  # Match the target's boxing so restored params drop into a TrainState.
  restored = _rebox_like(target, restored)
  return restored, index.get("step")


def latest_step(directory: str) -> Optional[int]:
  try:
    with open(os.path.join(directory, INDEX_FILE)) as f:
      return json.load(f).get("step")
  except FileNotFoundError:
    return None


# ----------------------------------------------------------------- orbax --

def save_checkpoint_orbax(directory: str, tree, step: int = 0):
  """Production multi-host async-capable path via orbax (optional)."""
  import orbax.checkpoint as ocp
  ckptr = ocp.StandardCheckpointer()
  path = os.path.join(os.path.abspath(directory), f"step_{step}")
  ckptr.save(path, _unbox(tree))
  ckptr.wait_until_finished()
  return path


def restore_checkpoint_orbax(directory: str, step: int, target=None):
  import orbax.checkpoint as ocp
  ckptr = ocp.StandardCheckpointer()
  path = os.path.join(os.path.abspath(directory), f"step_{step}")
  if target is not None:
    return ckptr.restore(path, _unbox(target))
  return ckptr.restore(path)
