"""Sharded checkpoint save/restore with resharding-at-load.

TPU-native analog of the reference's saver stack (epl/runtime/saver.py):

  * ``MemoryEfficientBuilder`` (:145-207) — save ops sharded into ≤50 MB
    buckets with serialized IO to bound host memory → here the leaf
    arrays are bucketed by the same bound and written one bucket at a
    time (`.npz` shards + a JSON index).
  * ``ShardingLoader`` (:46-128) — restore with a variable→checkpoint
    assign-map and per-variable begin/size slices → `restore_checkpoint`
    takes `assign_map` (regex rename) and slices loaded tensors to the
    target shape with per-leaf offsets.
  * save-only-on-leader semantics (reference hooks.py:542-590: only the
    first constructor saves) → only process 0 writes; every process can
    restore (resharding onto the live mesh is a `device_put` with the
    target shardings — GSPMD's version of the reference's slice-based
    reshard).

An orbax-backed path is available for production multi-host async
checkpointing (`use_orbax=True`); the native format keeps the framework
dependency-free and transparent.

Crash consistency (docs/robustness.md): each checkpoint is one
``step_N`` directory under the checkpoint root.  The save stages into
``step_N.tmp`` — shards with per-shard sha256 checksums recorded in the
index, the index itself written via temp-file + ``os.replace``, all
fsynced — then commits with an atomic directory rename, so a crash at
ANY point leaves either the previous committed checkpoints untouched or
a ``.tmp`` dir the chain scan ignores (CheckFreq-style semantics, Mohan
et al. FAST'21).  ``restore_checkpoint``/``latest_step`` validate
checksums and fall back down the chain to the newest VALID checkpoint,
quarantining corrupt ones as ``step_N.corrupt``; ``keep_last`` bounds
retention.  A directory containing ``index.json`` directly (the pre-
chain flat layout) is still restored as a single checkpoint.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from easyparallellibrary_tpu import constants
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.utils.logging import get_logger
from easyparallellibrary_tpu.utils.pytree import (
    path_str, tree_paths_and_leaves)

INDEX_FILE = "index.json"
TMP_SUFFIX = ".tmp"
CORRUPT_SUFFIX = ".corrupt"
_STEP_DIR_RE = re.compile(r"^step_(\d+)$")


class NoValidCheckpointError(FileNotFoundError):
  """Candidates existed but every one failed validation.  Distinct from
  a plain FileNotFoundError (empty/missing directory — a fresh run) so
  callers can fail loudly instead of silently restarting from step 0."""


def _step_dir_name(step: int) -> str:
  return f"step_{step:08d}"


def _sha256_file(path: str) -> str:
  h = hashlib.sha256()
  with open(path, "rb") as f:
    for chunk in iter(lambda: f.read(1 << 20), b""):
      h.update(chunk)
  return h.hexdigest()


def _fsync_path(path: str, is_dir: bool = False):
  """Best-effort fsync of a file or directory entry (directory fsync is
  what makes the rename-commit durable on POSIX)."""
  try:
    fd = os.open(path, os.O_RDONLY | (os.O_DIRECTORY if is_dir else 0))
  except (OSError, AttributeError):  # pragma: no cover - platform specific
    return
  try:
    os.fsync(fd)
  except OSError:  # pragma: no cover
    pass
  finally:
    os.close(fd)


def _write_index(directory: str, index: Dict[str, Any]):
  """Write index.json via temp-file + atomic replace: a crash mid-write
  can never leave a truncated JSON shadowing the shard files."""
  final = os.path.join(directory, INDEX_FILE)
  tmp = final + TMP_SUFFIX
  with open(tmp, "w") as f:
    json.dump(index, f, indent=1)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, final)


def params_fingerprint(index: Dict[str, Any]) -> str:
  """Deterministic content fingerprint of a checkpoint from its index:
  sha256 over the sorted leaf records (path, stored shape, dtype) plus
  each leaf's covering shard checksum — i.e. tree structure + geometry
  + a per-shard sha256 rollup in one digest.

  Recorded in ``index.json`` at save time (``"params_fingerprint"``)
  and recomputed by :func:`verify_checkpoint`, so a hand-edited or
  mix-and-matched index (leaves of one save over shards of another —
  per-shard checksums alone cannot catch that) is rejected with a
  clear reason.  The rollout validator (serving/rollout.py) also uses
  it as the checkpoint's identity: two directories with the same
  fingerprint serve bit-identical params."""
  h = hashlib.sha256()
  shard_digest: Dict[str, str] = {}
  for entry in index.get("shards", []):
    if isinstance(entry, dict):
      shard_digest[str(entry.get("file", ""))] = str(
          entry.get("sha256") or "")
  for path in sorted(index.get("leaves", {})):
    info = index["leaves"][path]
    h.update(path.encode())
    h.update(repr(tuple(info.get("shape", ()))).encode())
    h.update(str(info.get("dtype", "")).encode())
    h.update(shard_digest.get(str(info.get("shard", "")), "").encode())
    h.update(b"\x00")
  return h.hexdigest()


def checkpoint_fingerprint(directory: str) -> Tuple[str, int]:
  """``(fingerprint, step)`` of the newest VALID checkpoint under
  ``directory`` — the recorded index fingerprint when present, else
  computed from the index (pre-fingerprint saves).  Walks the same
  checksum-validated chain as every other reader, so the identity
  describes the checkpoint a restore would actually load."""
  for path in _walk_valid_checkpoints(directory):
    with open(os.path.join(path, INDEX_FILE)) as f:
      index = json.load(f)
    fp = index.get("params_fingerprint") or params_fingerprint(index)
    return str(fp), int(index.get("step") or 0)
  raise FileNotFoundError(f"no valid checkpoint under {directory!r}")


def _candidate_dirs(directory: str) -> List[str]:
  """Checkpoint candidates, newest first.

  ``step_N`` children form the fallback chain; staging (``.tmp``) and
  quarantined (``.corrupt``) dirs are never candidates.  A directory
  holding ``index.json`` itself is also a candidate — a committed step
  dir, or the legacy flat layout.  A flat checkpoint can COEXIST with
  step dirs (a pre-chain run upgraded and kept checkpointing into the
  same root), so it is ranked into the chain by its recorded step, never
  allowed to shadow newer step dirs.
  """
  try:
    names = os.listdir(directory)
  except (FileNotFoundError, NotADirectoryError):
    return []
  ranked: List[Tuple[int, str]] = []
  for name in names:
    m = _STEP_DIR_RE.match(name)
    if m and os.path.isdir(os.path.join(directory, name)):
      ranked.append((int(m.group(1)), os.path.join(directory, name)))
  if INDEX_FILE in names:
    if not ranked:
      return [directory]
    try:
      with open(os.path.join(directory, INDEX_FILE)) as f:
        flat_step = json.load(f).get("step")
      flat_step = int(flat_step) if flat_step is not None else -1
    except (OSError, ValueError, TypeError):
      flat_step = -1  # unparsable: last resort in the chain
    ranked.append((flat_step, directory))
  return [p for _, p in sorted(ranked, key=lambda t: t[0], reverse=True)]


def has_quarantined(directory: str) -> bool:
  """Whether the checkpoint root holds quarantined (``*.corrupt``)
  checkpoints — evidence that data WAS here and rotted, which callers
  should surface before deciding to train from scratch."""
  try:
    return any(CORRUPT_SUFFIX in name for name in os.listdir(directory))
  except (FileNotFoundError, NotADirectoryError):
    return False


def verify_checkpoint(path: str) -> Tuple[bool, str]:
  """Validate one checkpoint dir: index parses, every shard exists, and
  recorded sizes/sha256 checksums match.  Returns (ok, reason)."""
  try:
    with open(os.path.join(path, INDEX_FILE)) as f:
      index = json.load(f)
  except FileNotFoundError:
    return False, "missing index.json"
  except (json.JSONDecodeError, OSError, UnicodeDecodeError, ValueError) as e:
    return False, f"unparsable index.json ({e})"
  if not isinstance(index, dict) or "leaves" not in index:
    return False, "malformed index.json (no leaves)"
  try:
    for entry in index.get("shards", []):
      if isinstance(entry, str):  # pre-checksum index format
        fname, nbytes, digest = entry, None, None
      else:
        fname = entry.get("file", "")
        nbytes, digest = entry.get("bytes"), entry.get("sha256")
      fpath = os.path.join(path, fname)
      if not os.path.isfile(fpath):
        return False, f"missing shard {fname}"
      if nbytes is not None and os.path.getsize(fpath) != nbytes:
        return False, (f"shard {fname}: size {os.path.getsize(fpath)} != "
                       f"recorded {nbytes} (truncated?)")
      if digest is not None:
        # Retry transient read errors before declaring the shard bad — a
        # network-filesystem blip must not get a VALID checkpoint
        # quarantined (FileNotFoundError stays permanent: a vanished
        # shard IS invalid).
        from easyparallellibrary_tpu.utils.retry import retry_call
        if retry_call(_sha256_file, fpath,
                      what=f"checksum read {fname}") != digest:
          return False, f"shard {fname}: sha256 mismatch (corrupted)"
  except OSError as e:
    # A shard vanishing mid-verify (another process quarantined or
    # retention-pruned the dir under us) is just another way for the
    # candidate to be invalid — the chain must fall back, not crash.
    return False, f"shard disappeared during validation ({e})"
  recorded = index.get("params_fingerprint")
  if recorded is not None and recorded != params_fingerprint(index):
    # The per-shard checksums above prove each shard matches ITS index
    # entry; the fingerprint proves the index entries belong together —
    # a leaves table edited (or mixed with another save's shard list)
    # after the fact fails here, not as a wrong-weights decode.
    return False, "params fingerprint mismatch (index edited or mixed)"
  return True, ""


def _quarantine(path: str):
  """Rename a corrupt checkpoint dir out of the chain (leader only;
  best-effort — a failed rename just leaves it to be skipped again)."""
  if jax.process_index() != 0:
    return
  target = path + CORRUPT_SUFFIX
  n = 0
  while os.path.exists(target):
    n += 1
    target = f"{path}{CORRUPT_SUFFIX}.{n}"
  try:
    os.replace(path, target)
    get_logger().warning("quarantined corrupt checkpoint %s -> %s",
                         path, target)
    trace_lib.get_tracer().instant(
        "checkpoint/quarantine", cat="checkpoint", track="checkpoint",
        args={"path": path})
  except OSError as e:  # pragma: no cover - racing cleanup
    get_logger().warning("could not quarantine %s: %s", path, e)


def _apply_retention(directory: str, keep_last: int):
  """Delete committed checkpoints beyond the newest `keep_last`, plus any
  stale staging dirs a crashed save left behind (leader only)."""
  if jax.process_index() != 0:
    return
  try:
    names = os.listdir(directory)
  except FileNotFoundError:
    return
  for name in names:
    if name.endswith(TMP_SUFFIX) and _STEP_DIR_RE.match(
        name[:-len(TMP_SUFFIX)]):
      shutil.rmtree(os.path.join(directory, name), ignore_errors=True)
      get_logger().info("removed stale checkpoint staging dir %s", name)
  if keep_last <= 0:
    return
  for path in _candidate_dirs(directory)[keep_last:]:
    if path == directory:
      # The root itself can be a (legacy flat) candidate — retention
      # must never rmtree the checkpoint root out from under the chain.
      continue
    shutil.rmtree(path, ignore_errors=True)
    get_logger().info("retention (keep_last=%d): removed %s",
                      keep_last, path)


def _unbox(tree):
  import flax.linen as nn
  return nn.unbox(tree)


def _is_box(x) -> bool:
  import flax.linen as nn
  return isinstance(x, nn.meta.AxisMetadata)


def _boxed_paths_and_leaves(tree):
  """Like tree_paths_and_leaves but stops at metadata boxes, so padded
  params can be recognized (paths are identical either way — boxes sit
  exactly at leaf positions)."""
  flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=_is_box)
  return [(path_str(path), leaf) for path, leaf in flat]


def _logical_shape(leaf) -> Optional[Tuple[int, ...]]:
  """The attested unpadded shape of a PaddedPartitioned leaf, when it
  differs from the stored value's shape (ops/layers.py)."""
  ls = getattr(leaf, "logical_shape", None)
  if ls is None:
    return None
  value = leaf.unbox() if _is_box(leaf) else leaf
  return tuple(ls) if tuple(ls) != tuple(value.shape) else None


def _rebox_like(template, tree):
  """Put restored values back inside the template's metadata boxes, so a
  restored tree is a drop-in replacement for live (boxed) params."""
  import flax.linen as nn
  is_box = lambda x: isinstance(x, nn.meta.AxisMetadata)
  flat_t, tdef = jax.tree_util.tree_flatten(template, is_leaf=is_box)
  flat_v = jax.tree_util.tree_leaves(tree)
  out = [t.replace_boxed(v) if is_box(t) else v
         for t, v in zip(flat_t, flat_v)]
  return jax.tree_util.tree_unflatten(tdef, out)


def save_checkpoint(directory: str, tree, step: Optional[int] = None,
                    shard_mb: Optional[int] = None,
                    keep_last: Optional[int] = None,
                    atomic: Optional[bool] = None) -> str:
  """Write `tree` as checkpoint ``directory/step_N`` (leader process
  writes).

  Returns the committed checkpoint path.  Leaves are fetched and written
  bucket by bucket (≤ `shard_mb`, default 50 MB — reference saver.py:148)
  so host memory stays bounded.  With `atomic` (default
  ``resilience.atomic_checkpoints``) the whole checkpoint is staged in
  ``step_N.tmp`` — per-shard sha256 checksums in the index, everything
  fsynced — and committed by one directory rename, so a crash mid-save
  never shadows an older valid checkpoint.  `keep_last` (default
  ``resilience.keep_last``; 0 = keep all) prunes older committed
  checkpoints after the commit.

  Multi-host: EVERY process must call this (arrays sharded across hosts
  are all-gathered collectively); only process 0 writes, and all
  processes synchronize before returning so a follow-up restore cannot
  race the write.
  """
  from easyparallellibrary_tpu.env import Env
  from easyparallellibrary_tpu.utils.retry import retry_call
  res = Env.get().config.resilience
  if atomic is None:
    atomic = res.atomic_checkpoints
  if keep_last is None:
    keep_last = res.keep_last
  multihost = jax.process_count() > 1
  is_leader = jax.process_index() == 0
  shard_mb = shard_mb or constants.DEFAULT_SAVE_SHARD_MB
  limit = shard_mb * 1024 * 1024

  step_num = 0 if step is None else int(step)
  final_dir = os.path.join(directory, _step_dir_name(step_num))
  write_dir = final_dir + TMP_SUFFIX if atomic else final_dir
  if is_leader:
    os.makedirs(directory, exist_ok=True)
    if os.path.isdir(write_dir):
      shutil.rmtree(write_dir)
    os.makedirs(write_dir)

  flat = _boxed_paths_and_leaves(tree)
  index: Dict[str, Any] = {"step": step, "format": 2, "leaves": {},
                           "shards": []}
  bucket: List[Tuple[str, Any]] = []
  bucket_bytes = 0
  shard_id = 0

  def fetch(leaf) -> np.ndarray:
    if multihost and isinstance(leaf, jax.Array) and \
        not leaf.is_fully_addressable:
      # Collective: every process participates in gathering the global
      # value; only the leader keeps it.
      from jax.experimental import multihost_utils
      return np.asarray(multihost_utils.process_allgather(
          leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))

  def flush():
    nonlocal bucket, bucket_bytes, shard_id
    if not bucket:
      return
    fname = f"shard_{shard_id:05d}.npz"
    arrays = {}
    for path, leaf in bucket:
      logical = _logical_shape(leaf)
      host = fetch(leaf.unbox() if _is_box(leaf) else leaf)
      if logical is not None:
        # Layout portability (reference ShardingLoader role,
        # epl/runtime/saver.py:46-128): pad regions are attested zeros —
        # checkpoints always store LOGICAL shapes, so a load under a
        # different model-axis size or tensor_split setting re-pads to
        # whatever that layout needs.
        host = host[tuple(slice(0, l) for l in logical)]
      arrays[path] = host
      index["leaves"][path] = {
          "shard": fname, "shape": list(host.shape),
          "dtype": str(host.dtype)}
    if is_leader:
      fpath = os.path.join(write_dir, fname)
      retry_call(lambda: np.savez(fpath, **arrays),
                 what=f"checkpoint shard write {fname}")
      _fsync_path(fpath)
      # Checksum over the bytes actually on disk: what verification will
      # re-read is exactly what was hashed.
      index["shards"].append({"file": fname,
                              "bytes": os.path.getsize(fpath),
                              "sha256": _sha256_file(fpath)})
    shard_id += 1
    bucket, bucket_bytes = [], 0

  tracer = trace_lib.get_tracer()
  # Staging (leaf fetch + shard writes + index, all in step_N.tmp) vs
  # commit (the atomic rename) as separate spans: the trace shows
  # whether a slow checkpoint spent its time in device->host IO or in
  # the filesystem's rename/fsync path.
  with tracer.span("checkpoint/stage", cat="checkpoint",
                   track="checkpoint", args={"step": step_num}):
    for path, leaf in flat:
      # Size from the unboxed value: metadata boxes expose no
      # shape/dtype, and a 4-byte default would put everything in one
      # bucket, defeating the host-memory bound.
      value = leaf.unbox() if _is_box(leaf) else leaf
      nbytes = int(np.prod(getattr(value, "shape", ()) or (1,))) * \
          jnp.dtype(getattr(value, "dtype", jnp.float32)).itemsize
      if bucket and bucket_bytes + nbytes > limit:
        flush()
      bucket.append((path, leaf))
      bucket_bytes += nbytes
    flush()
    if is_leader:
      index["params_fingerprint"] = params_fingerprint(index)
      retry_call(lambda: _write_index(write_dir, index),
                 what="checkpoint index write")
      _fsync_path(write_dir, is_dir=True)

  with tracer.span("checkpoint/commit", cat="checkpoint",
                   track="checkpoint", args={"step": step_num}):
    if is_leader:
      if atomic:
        # Commit: one atomic rename.  Everything inside is already
        # fsynced, so after the parent-dir fsync the checkpoint either
        # exists whole or not at all.
        if os.path.isdir(final_dir):
          shutil.rmtree(final_dir)
        os.replace(write_dir, final_dir)
      _fsync_path(directory, is_dir=True)
      get_logger().info("saved checkpoint: %s (%d leaves, %d shards)",
                        final_dir, len(index["leaves"]), shard_id)
      _apply_retention(directory, keep_last)
  if multihost:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(f"epl_save_{directory}_{step_num}")
  return final_dir


def _apply_assign_map(path: str, assign_map: Optional[Dict[str, str]]
                      ) -> str:
  """Regex rename, first match wins (reference ShardingLoader assign-map,
  saver.py:46-90)."""
  if not assign_map:
    return path
  for pattern, repl in assign_map.items():
    new, n = re.subn(pattern, repl, path)
    if n:
      return new
  return path


def _slice_to_shape(value: np.ndarray, shape: Tuple[int, ...],
                    offsets: Optional[Tuple[int, ...]] = None,
                    logical_shape: Optional[Tuple[int, ...]] = None
                    ) -> np.ndarray:
  """begin/size slicing at load (reference saver.py:91-128); with
  `logical_shape` (target is a PaddedPartitioned param attesting that
  shape) a stored value matching the logical shape exactly is zero-padded
  up to the target — the re-padding half of layout portability.  Padding
  may only fabricate regions known to be zero, so a stored value that
  does NOT cover the whole logical region is a hard error, never silently
  zero-filled."""
  if tuple(value.shape) == tuple(shape):
    return value
  if len(value.shape) != len(shape):
    raise ValueError(f"rank mismatch restoring {value.shape} -> {shape}")
  if logical_shape is not None and any(
      v < s for v, s in zip(value.shape, shape)):
    if tuple(value.shape) != tuple(logical_shape):
      raise ValueError(
          f"stored shape {tuple(value.shape)} does not match the target's "
          f"attested logical shape {tuple(logical_shape)}; refusing to "
          f"zero-pad into the logical region (padded target {tuple(shape)})")
    pad = [(0, max(0, s - v)) for v, s in zip(value.shape, shape)]
    value = np.pad(value, pad)
    if tuple(value.shape) == tuple(shape):
      return value
  offsets = offsets or (0,) * len(shape)
  slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
  if any(o + s > v for o, s, v in zip(offsets, shape, value.shape)):
    raise ValueError(
        f"slice {offsets}+{shape} out of bounds for stored {value.shape}")
  return value[slices]


def _walk_valid_checkpoints(directory: str):
  """Yield checksum-VALID checkpoint candidates under `directory`,
  newest first — THE fallback-chain protocol, shared by
  :func:`restore_checkpoint`, :func:`restore_params` and
  :func:`latest_step` so its semantics (warn, quarantine, fall back)
  cannot drift between them.  Raises FileNotFoundError when no
  candidate exists at all; raises :class:`NoValidCheckpointError` after
  the final yield if the consumer exhausts the chain (candidates
  existed but every one failed validation)."""
  candidates = _candidate_dirs(directory)
  if not candidates:
    raise FileNotFoundError(
        f"no checkpoint found under {directory!r} (no index.json and no "
        f"step_N subdirectories)")
  log = get_logger()
  for path in candidates:
    ok, reason = verify_checkpoint(path)
    if ok:
      yield path
      continue
    log.warning("checkpoint %s failed validation (%s); falling back to "
                "the previous checkpoint", path, reason)
    if path != directory:
      _quarantine(path)
  raise NoValidCheckpointError(
      f"no VALID checkpoint under {directory!r}: all {len(candidates)} "
      f"candidate(s) failed validation (corrupt ones quarantined as "
      f"*{CORRUPT_SUFFIX})")


def restore_checkpoint(directory: str,
                       target=None,
                       shardings=None,
                       assign_map: Optional[Dict[str, str]] = None,
                       slice_offsets: Optional[Dict[str, Tuple[int, ...]]]
                       = None):
  """Restore the newest VALID checkpoint under `directory`.

  `directory` is either one checkpoint (contains ``index.json``) or a
  checkpoint root (contains ``step_N`` dirs).  For a root, candidates
  are checksum-verified newest-first; corrupt ones are quarantined with
  a warning and the restore falls back down the chain — a crash or
  bit-rot in the newest checkpoint costs at most ``checkpoint_every``
  steps of progress, never the run.

  * `target` (optional) — a pytree giving structure/shapes; loaded values
    are sliced to each leaf's shape (resharding-at-load) and the result
    has `target`'s treedef.  Without it, returns {path: array}.
  * `shardings` — matching pytree of NamedShardings; loaded values are
    `device_put` onto them (the GSPMD reshard).
  * `assign_map` — {regex: replacement} applied to *target* paths to find
    the checkpoint name.

  Returns ``(tree, step)`` with `step` taken from the checkpoint
  actually restored (callers must not assume it is the newest on disk).
  """
  with trace_lib.get_tracer().span("checkpoint/restore",
                                   cat="checkpoint", track="checkpoint"):
    for path in _walk_valid_checkpoints(directory):
      return _restore_from(path, target, shardings, assign_map,
                           slice_offsets)


def _restore_from(directory: str,
                  target=None,
                  shardings=None,
                  assign_map: Optional[Dict[str, str]] = None,
                  slice_offsets: Optional[Dict[str, Tuple[int, ...]]]
                  = None,
                  leaf_filter=None):
  """Restore one already-validated checkpoint directory.  ``leaf_filter``
  (no-target mode only) restricts which leaves load — shards holding
  only filtered-out leaves are never opened (restore_params' reason not
  to touch optimizer state)."""
  from easyparallellibrary_tpu.utils.retry import retry_call
  with open(os.path.join(directory, INDEX_FILE)) as f:
    index = json.load(f)

  cache: Dict[str, Any] = {}

  def load_leaf(ckpt_path: str) -> np.ndarray:
    info = index["leaves"].get(ckpt_path)
    if info is None:
      raise KeyError(
          f"checkpoint {directory} has no tensor '{ckpt_path}'; "
          f"available: {sorted(index['leaves'])[:8]}...")
    shard = info["shard"]
    if shard not in cache:
      spath = os.path.join(directory, shard)
      cache[shard] = retry_call(lambda: np.load(spath),
                                what=f"checkpoint shard read {shard}")
    return cache[shard][ckpt_path]

  if target is None:
    out = {p: load_leaf(p) for p in index["leaves"]
           if leaf_filter is None or leaf_filter(p)}
    return out, index.get("step")

  flat_boxed, _ = jax.tree_util.tree_flatten_with_path(
      target, is_leaf=_is_box)
  target_unboxed = _unbox(target)
  flat, treedef = jax.tree_util.tree_flatten_with_path(target_unboxed)
  new_leaves = []
  for (path, leaf), (_, boxed) in zip(flat, flat_boxed):
    pstr = path_str(path)
    ckpt_name = _apply_assign_map(pstr, assign_map)
    value = load_leaf(ckpt_name)
    offs = (slice_offsets or {}).get(pstr)
    value = _slice_to_shape(
        value, tuple(np.shape(leaf)), offs,
        logical_shape=_logical_shape(boxed))
    value = value.astype(np.asarray(leaf).dtype
                         if not hasattr(leaf, "dtype") else leaf.dtype)
    new_leaves.append(value)
  restored = jax.tree_util.tree_unflatten(treedef, new_leaves)

  if shardings is not None:
    flat_shard = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    flat_restored = jax.tree_util.tree_leaves(restored)
    placed = [jax.device_put(v, s)
              for v, s in zip(flat_restored, flat_shard)]
    restored = jax.tree_util.tree_unflatten(treedef, placed)
  # Match the target's boxing so restored params drop into a TrainState.
  restored = _rebox_like(target, restored)
  return restored, index.get("step")


def peek_leaf_shapes(directory: str
                     ) -> Tuple[Dict[str, Tuple[int, ...]], int]:
  """Leaf-name → stored shape of the newest VALID checkpoint, from its
  index alone — no shard data is read.

  Walks the same checksum-validated newest-first chain as
  :func:`restore_checkpoint` (corrupt candidates quarantined and
  skipped), so the shapes describe the checkpoint a subsequent restore
  would actually load.  Serving uses this to validate a draft model's
  compatibility (vocabulary width, serving/speculative/drafter.py)
  BEFORE paying for the restore — a shape mismatch then fails in
  milliseconds with an actionable message instead of a pytree error
  mid-load.  Returns ``({path: shape}, step)``; raises
  ``FileNotFoundError`` when no valid checkpoint exists.
  """
  for path in _walk_valid_checkpoints(directory):
    with open(os.path.join(path, INDEX_FILE)) as f:
      index = json.load(f)
    shapes = {p: tuple(info.get("shape", ()))
              for p, info in index["leaves"].items()}
    return shapes, int(index.get("step", 0))
  raise FileNotFoundError(f"no valid checkpoint under {directory!r}")


def restore_params(directory: str,
                   target=None,
                   shardings=None,
                   assign_map: Optional[Dict[str, str]] = None):
  """Params-only restore for serving (docs/serving.md).

  Walks the same checksum-validated newest-first fallback chain as
  :func:`restore_checkpoint` — corrupt candidates are quarantined and
  skipped — but loads ONLY the model parameters: optimizer moments,
  step counters and sentinel state are never read off disk, so serving a
  checkpoint does not construct (or pay host memory for) a TrainState.

  Works on both checkpoint flavors: a full TrainState checkpoint (leaves
  under ``params/`` — the training loop's layout) and a bare params-tree
  checkpoint; the ``params/`` prefix is detected from the index and
  applied automatically.  ``target`` should be a params pytree (e.g.
  ``model.init(...)["params"]`` or an ``eval_shape`` of it);
  ``shardings`` a matching pytree of NamedShardings to place onto the
  serving mesh.  Explicit ``assign_map`` patterns win over the automatic
  prefix and must map to full checkpoint names.  Without ``target``,
  returns the raw ``{path: array}`` dict of just the params leaves
  (prefix stripped).

  Returns ``(params, step)``.
  """
  prefix = "params/"
  for path in _walk_valid_checkpoints(directory):
    with open(os.path.join(path, INDEX_FILE)) as f:
      leaves = json.load(f).get("leaves", {})
    prefixed = any(p.startswith(prefix) for p in leaves)
    if target is None:
      keep = (lambda p: p.startswith(prefix)) if prefixed else None
      tree, step = _restore_from(path, leaf_filter=keep)
      if prefixed:
        tree = {p[len(prefix):]: v for p, v in tree.items()}
      return tree, step
    amap = dict(assign_map) if assign_map else {}
    if prefixed:
      # Applied last (first match wins): explicit entries already name
      # full checkpoint paths.
      amap.setdefault("^", prefix)
    return _restore_from(path, target, shardings, amap)


def latest_step(directory: str) -> Optional[int]:
  """Step of the newest VALID checkpoint under `directory` (a checkpoint
  root or a single checkpoint dir), or None.

  Validation matches :func:`restore_checkpoint` — the same fallback
  chain (:func:`_walk_valid_checkpoints`) — so the step returned here is
  one the restore will actually succeed on.  Corrupt/unparsable
  candidates are logged, quarantined, and skipped instead of crashing
  the resume path.
  """
  try:
    for path in _walk_valid_checkpoints(directory):
      try:
        with open(os.path.join(path, INDEX_FILE)) as f:
          return json.load(f).get("step")
      except (OSError, ValueError):  # pragma: no cover - raced deletion
        continue
  except (FileNotFoundError, NoValidCheckpointError):
    return None
  return None


# ----------------------------------------------------------------- orbax --

def save_checkpoint_orbax(directory: str, tree, step: int = 0):
  """Production multi-host async-capable path via orbax (optional)."""
  import orbax.checkpoint as ocp
  ckptr = ocp.StandardCheckpointer()
  path = os.path.join(os.path.abspath(directory), f"step_{step}")
  ckptr.save(path, _unbox(tree))
  ckptr.wait_until_finished()
  return path


def restore_checkpoint_orbax(directory: str, step: int, target=None):
  import orbax.checkpoint as ocp
  ckptr = ocp.StandardCheckpointer()
  path = os.path.join(os.path.abspath(directory), f"step_{step}")
  if target is not None:
    return ckptr.restore(path, _unbox(target))
  return ckptr.restore(path)
