"""Mixed precision — dtype policy + loss scaling.

TPU-native redesign of the reference's AMP O1
(epl/runtime/amp/auto_mixed_precision.py): the reference rewrites the TF
graph with allow/deny/gray/clear op lists and 4 propagation passes
(:282-415) because TF1 has no dtype policy.  In JAX the policy is simply
the dtypes the model computes in (`GPTConfig.dtype = bfloat16`, fp32
params) — XLA keeps MXU matmuls in bf16 natively, so the graph rewrite
has no role.

Loss scaling (reference epl/runtime/amp/loss_scale.py): bf16 has fp32's
exponent range so TPU training needs no scale; the dynamic scale is kept
for fp16 parity and for numerically fragile models — scale the loss,
unscale grads, skip the update on non-finite grads, grow/backoff the
scale (the reference's conditional apply + update, loss_scale.py:44-51).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct


@dataclasses.dataclass(frozen=True)
class Policy:
  """Dtype policy (the role of the reference's O1 conversion lists)."""
  param_dtype: Any = jnp.float32
  compute_dtype: Any = jnp.bfloat16
  output_dtype: Any = jnp.float32

  def _cast(self, tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x).astype(dtype)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x, tree)

  def cast_to_compute(self, tree):
    return self._cast(tree, self.compute_dtype)

  def wrap_apply(self, fn: Callable) -> Callable:
    """O1 for arbitrary modules: cast float params (first arg) and float
    inputs to the compute dtype around ``fn``, cast float outputs back to
    ``output_dtype`` — the effect of the reference's graph rewrite
    (epl/runtime/amp/auto_mixed_precision.py:174-191) without the rewrite
    (most flax layers follow their input dtype when ``dtype=None``)."""

    def wrapped(params, *args, **kw):
      out = fn(self.cast_to_compute(params),
               *self._cast(args, self.compute_dtype), **kw)
      return self._cast(out, self.output_dtype)

    return wrapped


_COMPUTE_DTYPES = {"bf16": jnp.bfloat16, "fp16": jnp.float16}


def policy_from_config(config=None) -> Optional[Policy]:
  """The active dtype policy, or None when ``amp.level`` is off/O0."""
  from easyparallellibrary_tpu import constants
  from easyparallellibrary_tpu.env import Env
  cfg = config if config is not None else Env.get().config
  if cfg.amp.level != constants.AMP_O1:
    return None
  return Policy(compute_dtype=_COMPUTE_DTYPES[cfg.amp.compute_dtype])


def resolve_model_dtypes(model_cfg, config=None):
  """Apply ``amp.level="O1"`` to a bundled model's dataclass config:
  swap its ``dtype`` (compute) to the policy compute dtype, keep
  ``param_dtype`` — so the config knob, not each model's constructor
  argument, decides mixed precision (VERDICT round-1 item 8)."""
  policy = policy_from_config(config)
  if policy is None or not hasattr(model_cfg, "dtype"):
    return model_cfg
  return dataclasses.replace(model_cfg, dtype=policy.compute_dtype)


class DynamicLossScale(struct.PyTreeNode):
  """State for dynamic loss scaling (reference loss_scale_tf.py fork of
  TF r1.15 LossScale)."""
  scale: jnp.ndarray
  growth_interval: int = struct.field(pytree_node=False, default=2000)
  growth_factor: float = struct.field(pytree_node=False, default=2.0)
  backoff_factor: float = struct.field(pytree_node=False, default=0.5)
  counter: jnp.ndarray = struct.field(
      default_factory=lambda: jnp.zeros((), jnp.int32))

  @classmethod
  def create(cls, initial_scale: float = 2.0 ** 15, **kw):
    return cls(scale=jnp.float32(initial_scale), **kw)

  def update(self, grads_finite) -> "DynamicLossScale":
    grow = (self.counter + 1) >= self.growth_interval
    new_scale = jnp.where(
        grads_finite,
        jnp.where(grow, self.scale * self.growth_factor, self.scale),
        self.scale * self.backoff_factor)
    new_scale = jnp.clip(new_scale, 1.0, 2.0 ** 24)
    new_counter = jnp.where(grads_finite & ~grow, self.counter + 1,
                            jnp.zeros((), jnp.int32))
    return self.replace(scale=new_scale, counter=new_counter)


def fixed_loss_scale(value: float) -> DynamicLossScale:
  """A scale that never changes (reference fixed loss scale)."""
  return DynamicLossScale(scale=jnp.float32(value),
                          growth_factor=1.0, backoff_factor=1.0,
                          growth_interval=2 ** 30)


def all_finite(tree) -> jnp.ndarray:
  """Scalar bool: every floating leaf of `tree` is finite.  Shared by
  the loss-scale skip and the resilience sentinel
  (runtime/resilience.py) — one definition of "bad step" for both."""
  leaves = [jnp.all(jnp.isfinite(l)) for l in jax.tree_util.tree_leaves(tree)
            if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
  if not leaves:
    return jnp.bool_(True)
  return jnp.stack(leaves).all()


def nonfinite_report(tree, max_entries: int = 8) -> "dict[str, int]":
  """{path: nonfinite_count} for the offending leaves of a HOST tree —
  the diagnostic logged when the sentinel escalates to a rollback, so
  the log names which tensors went bad instead of just 'NaN somewhere'.
  Forces a device sync; for post-mortem use, never the hot path."""
  import numpy as np
  from easyparallellibrary_tpu.utils.pytree import tree_paths_and_leaves
  report = {}
  for path, leaf in tree_paths_and_leaves(tree):
    arr = np.asarray(jax.device_get(leaf))
    if not np.issubdtype(arr.dtype, np.floating):
      continue
    bad = int(np.size(arr) - np.count_nonzero(np.isfinite(arr)))
    if bad:
      report[path] = bad
      if len(report) >= max_entries:
        break
  return report


def scaled_value_and_grad(loss_fn: Callable, scale: jnp.ndarray,
                          has_aux: bool = True):
  """value_and_grad with loss scaling: scale before grad, unscale after
  (reference: hooks.py:137-172 scale_loss/unscale_grads)."""

  def scaled_loss(*args, **kw):
    out = loss_fn(*args, **kw)
    if has_aux:
      loss, aux = out
      return loss * scale.astype(loss.dtype), aux
    return out * scale.astype(out.dtype)

  def wrapped(*args, **kw):
    if has_aux:
      (loss, aux), grads = jax.value_and_grad(
          scaled_loss, has_aux=True)(*args, **kw)
    else:
      loss, grads = jax.value_and_grad(scaled_loss)(*args, **kw)
      aux = {}
    inv = (1.0 / scale)
    grads = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
    return (loss / scale.astype(loss.dtype), aux), grads

  return wrapped
