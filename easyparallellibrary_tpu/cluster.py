"""Cluster: device enumeration, layouts, and mesh construction.

TPU-native analog of the reference's ``epl/cluster.py``: instead of parsing
``TF_CONFIG`` and slicing a GPU grid into per-taskgraph ``VirtualDevice``
lists (reference :36-100, :133-143), we enumerate ``jax.devices()`` and build
a single named :class:`jax.sharding.Mesh` over the logical axes
``(stage, data, seq, expert, model)``.  Pipeline stages are a mesh axis, not
separate device groups — XLA partitions one program over the whole mesh.

Layout policies mirror the reference's (``AllLayout`` :108, ``AutoLayout``
:146, ``SpecificLayout`` :162, ``AwareRowLayout`` :169):

  * ``auto``     — data-parallel size inferred as
                   total_devices / (stage*model*seq*expert), the analog of
                   replicas = total / Σ per-stage device_count
                   (reference epl/cluster.py:150-159).
  * ``all``      — everything on one data axis (pure DP).
  * ``specific`` — user-provided mesh shape (``cluster.mesh_shape`` config).
  * topology awareness (the ``AwareRowLayout`` role) comes from
    ``jax.experimental.mesh_utils.create_device_mesh``, which orders TPU
    devices so the innermost axes ride the shortest ICI hops.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from easyparallellibrary_tpu import constants
from easyparallellibrary_tpu.env import Env


class VirtualDevice:
  """The devices backing one taskgraph / pipeline stage.

  Parity object for the reference's ``VirtualDevice``
  (epl/cluster.py:36-100); in this framework it is introspection metadata —
  placement is done by XLA from the mesh, not by assigning device strings.
  """

  def __init__(self, stage_index: int, devices: Sequence[jax.Device]):
    self.stage_index = stage_index
    self.devices = list(devices)

  @property
  def num_devices(self) -> int:
    return len(self.devices)

  def __repr__(self):
    return (f"VirtualDevice(stage={self.stage_index}, "
            f"devices={[getattr(d, 'id', d) for d in self.devices]})")


def _build_device_array(devices: List[jax.Device],
                        shape: Sequence[int],
                        prefer_intra_node: bool) -> np.ndarray:
  """Arrange devices into a mesh-shaped ndarray.

  On TPU, delegate to ``mesh_utils.create_device_mesh`` for ICI-topology-aware
  placement (the reference's AwareRowLayout host-reordering role,
  epl/cluster.py:193-241).  On CPU/virtual platforms fall back to row-major
  reshape; with ``prefer_intra_node`` the innermost axes vary fastest within
  a process, mirroring ``device_place_prefer_intra_node``
  (epl/cluster.py:137).
  """
  shape = tuple(shape)
  n = math.prod(shape)
  if n != len(devices):
    raise ValueError(f"Mesh shape {shape} needs {n} devices, "
                     f"have {len(devices)}")
  platform = devices[0].platform if devices else "cpu"
  if platform == "tpu" and n > 1:
    try:
      from jax.experimental import mesh_utils
      return mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:  # pragma: no cover - topology helpers can be picky
      pass
  order = sorted(devices, key=lambda d: (d.process_index, d.id)) \
      if prefer_intra_node else list(devices)
  return np.array(order, dtype=object).reshape(shape)


class Layout:
  """Base layout: computes the per-axis mesh sizes (reference Layout :244)."""

  name = "base"

  def axis_sizes(self, cluster: "Cluster",
                 requested: Dict[str, int]) -> Dict[str, int]:
    raise NotImplementedError


class AllLayout(Layout):
  """All devices on the data axis — pure DP (reference AllLayout :108)."""

  name = "all"

  def axis_sizes(self, cluster, requested):
    sizes = {axis: 1 for axis in constants.MESH_AXES}
    sizes[constants.DATA_AXIS] = cluster.num_devices
    return sizes


class AutoLayout(Layout):
  """Infer data-parallel size from leftover devices.

  Reference: replicas = total_devices / Σ per-stage device_count
  (epl/cluster.py:150-159).  Here: data = total / (stage*seq*expert*model).
  """

  name = "auto"

  def axis_sizes(self, cluster, requested):
    sizes = {axis: int(requested.get(axis, 1)) for axis in constants.MESH_AXES}
    fixed = math.prod(
        sizes[a] for a in constants.MESH_AXES if a != constants.DATA_AXIS)
    total = cluster.num_devices
    if total % fixed != 0:
      raise ValueError(
          f"Cannot lay out mesh: {total} devices not divisible by "
          f"stage*seq*expert*model = {fixed} "
          f"(requested {requested})")
    inferred = total // fixed
    explicit = requested.get(constants.DATA_AXIS, 0)
    sizes[constants.DATA_AXIS] = explicit if explicit else inferred
    if math.prod(sizes.values()) != total:
      raise ValueError(
          f"Mesh sizes {sizes} do not cover {total} devices")
    return sizes


class SpecificLayout(Layout):
  """Exact user-provided shape (reference SpecificLayout :162).

  Parsed from ``cluster.mesh_shape`` config, e.g. ``"stage:2,data:2,model:2"``.
  """

  name = "specific"

  def __init__(self, spec: str):
    self.sizes = {axis: 1 for axis in constants.MESH_AXES}
    for part in spec.split(","):
      if not part.strip():
        continue
      axis, _, num = part.partition(":")
      axis = axis.strip()
      if axis not in self.sizes:
        raise ValueError(f"Unknown mesh axis '{axis}' in mesh_shape spec "
                         f"{spec!r}; valid: {constants.MESH_AXES}")
      self.sizes[axis] = int(num)

  def axis_sizes(self, cluster, requested):
    if math.prod(self.sizes.values()) != cluster.num_devices:
      raise ValueError(
          f"mesh_shape {self.sizes} does not match device count "
          f"{cluster.num_devices}")
    for axis, size in requested.items():
      if size > 1 and self.sizes.get(axis, 1) != size:
        raise ValueError(
            f"cluster.mesh_shape sets {axis}={self.sizes.get(axis, 1)} but "
            f"the recorded strategy scopes require {axis}={size}; make the "
            f"explicit shape consistent with the annotations")
    return dict(self.sizes)


_LAYOUTS = {"all": AllLayout, "auto": AutoLayout}


class Cluster:
  """Device pool + mesh factory (reference Cluster, epl/cluster.py:293).

  The reference parses TF_CONFIG and starts a TF server; here multi-host
  bootstrap is `jax.distributed.initialize` (done by the launcher CLI) and
  the global device list already spans all hosts.
  """

  def __init__(self,
               devices: Optional[List[jax.Device]] = None,
               layout: str | Layout = "auto"):
    self.devices = list(devices) if devices is not None else jax.devices()
    self.process_index = getattr(jax, "process_index", lambda: 0)()
    self.process_count = getattr(jax, "process_count", lambda: 1)()
    config = Env.get().config
    spec = config.cluster.mesh_shape
    if spec:
      self.layout: Layout = SpecificLayout(spec)
    elif isinstance(layout, Layout):
      self.layout = layout
    else:
      self.layout = _LAYOUTS[layout]()
    self._mesh: Optional[Mesh] = None
    self.virtual_devices: List[VirtualDevice] = []

  @property
  def num_devices(self) -> int:
    return len(self.devices)

  @property
  def devices_per_process(self) -> int:
    return max(1, self.num_devices // max(1, self.process_count))

  def build_mesh(self, **requested: int) -> Mesh:
    """Build the 5-axis mesh; size-1 axes are free.

    ``requested`` gives sizes for non-data axes (e.g. ``stage=2, model=4``);
    the layout infers the rest.
    """
    sizes = self.layout.axis_sizes(self, requested)
    shape = tuple(sizes[a] for a in constants.MESH_AXES)
    prefer_intra = Env.get().config.cluster.device_place_prefer_intra_node
    dev_array = _build_device_array(self.devices, shape, prefer_intra)
    self._mesh = Mesh(dev_array, constants.MESH_AXES)
    # Per-stage virtual devices for introspection/parity.
    num_stages = sizes[constants.STAGE_AXIS]
    self.virtual_devices = [
        VirtualDevice(i, dev_array[i].reshape(-1).tolist())
        for i in range(num_stages)
    ]
    return self._mesh

  @property
  def mesh(self) -> Mesh:
    if self._mesh is None:
      self.build_mesh()
    return self._mesh

  @property
  def built_mesh(self) -> Optional[Mesh]:
    """The mesh if :meth:`build_mesh` has run, else None — the
    observe-without-forcing accessor (``mesh`` force-builds) for
    components that only want to ADOPT an existing cluster layout,
    e.g. the serving engine's ambient-mesh resolution."""
    return self._mesh

  def axis_size(self, axis: str) -> int:
    return dict(zip(self.mesh.axis_names, self.mesh.devices.shape))[axis]

  def __repr__(self):
    shape = None if self._mesh is None else dict(
        zip(self._mesh.axis_names, self._mesh.devices.shape))
    return (f"Cluster(num_devices={self.num_devices}, "
            f"processes={self.process_count}, layout={self.layout.name!r}, "
            f"mesh={shape})")
