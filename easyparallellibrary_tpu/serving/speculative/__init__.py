"""Speculative decoding — the serving engine's fourth component.

Drafters guess the next ``k`` tokens of each decode slot (drafter.py),
the fused step scores all of them in its one model call (they ride the
``[num_slots, chunk]`` positions plain decode wastes — verification is
nearly free), and the verifier keeps each slot's accepted prefix plus
one correction/bonus token with on-device cursor rollback (verify.py).
Greedy output stays bit-exact; sampled output keeps its distribution
(Leviathan et al. rejection sampling).  See docs/serving.md
"Speculative decoding".
"""

from easyparallellibrary_tpu.serving.speculative.drafter import (
    Drafter, DraftModelDrafter, NgramDrafter, ngram_propose,
)
from easyparallellibrary_tpu.serving.speculative.verify import (
    verify_tokens,
)

__all__ = [
    "Drafter", "DraftModelDrafter", "NgramDrafter", "ngram_propose",
    "verify_tokens",
]
