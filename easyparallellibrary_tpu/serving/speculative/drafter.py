"""Drafters: cheap token proposers for speculative decoding.

A drafter fills the chunk positions the fused serving step wastes on
plain decode with *guesses* at the next ``k`` tokens of each slot; the
step then scores all of them in its one model call and the verifier
(verify.py) keeps the accepted prefix.  Two designs ship:

* :class:`NgramDrafter` — prompt-lookup decoding: propose the
  continuation of the most recent earlier occurrence of the request's
  own trailing n-gram.  Pure host work, no weights, no device state —
  the zero-cost drafter for repetitive text (code, retrieval, chat
  templates).
* :class:`DraftModelDrafter` — a small GPT (same vocabulary, any
  depth/width) greedily rolled ``k`` tokens ahead per slot in ONE jitted
  call against its own slot KV cache.  The draft cache mirrors the
  target's admission/prefill/rollback life exactly: it consumes the same
  step plan the engine does, and after verification its cursors are
  overwritten with the engine's rolled-back cursors — cursor values are
  "committed tokens resident in cache", identical on both sides, so no
  cache rewrite is ever needed.

Both propose deterministically (a point-mass proposal); verify.py's
rejection-sampling acceptance stays exactly distribution-preserving for
that case (accept with prob ``p(d)``, residual excludes ``d``).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from easyparallellibrary_tpu.serving._capabilities import (
    check_draft_compatible)


def ngram_propose(history: np.ndarray, k: int, ngram_max: int,
                  ngram_min: int) -> np.ndarray:
  """Prompt-lookup proposal: up to ``k`` continuation tokens of the most
  recent earlier occurrence of ``history``'s trailing n-gram.

  Longest suffix first (``ngram_max`` down to ``ngram_min``): a longer
  match is stronger evidence the continuation repeats.  Among equal-n
  matches the most recent wins (locally repetitive text beats a stale
  early match).  Returns an empty array when nothing matches — the slot
  simply decodes non-speculatively this step.
  """
  history = np.asarray(history).reshape(-1)
  L = len(history)
  for n in range(min(ngram_max, L - 1), ngram_min - 1, -1):
    suffix = history[L - n:]
    # Windows over history[:-1]: every match start i <= L-1-n has at
    # least one continuation token, and the suffix's own occurrence at
    # L-n is excluded.
    windows = np.lib.stride_tricks.sliding_window_view(history[:L - 1], n)
    hits = np.nonzero((windows == suffix).all(axis=1))[0]
    if hits.size:
      start = int(hits[-1]) + n
      return history[start:start + k].astype(np.int32)
  return np.zeros((0,), np.int32)


class Drafter:
  """Interface the engine drives (serving/engine.py).

  ``k`` is the maximum drafts per slot per step; the engine validates
  ``k + 1 <= prefill_chunk`` at bind time.  Lifecycle per engine
  iteration: ``propose(plan, histories)`` BEFORE the fused step (the
  plan's token block is still draft-free), then ``observe_commit(
  new_cursors)`` after it (the engine's verified, rolled-back cursor
  vector — the only rollback a drafter with device state needs).
  """

  k: int = 0

  def bind(self, engine) -> None:
    """Called once from the engine's constructor with the engine itself;
    drafters with device state allocate against the engine's slot/chunk
    geometry and mesh here."""

  def propose(self, plan, histories: Dict[int, np.ndarray]
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Return ``(draft_tokens [N, k] int32, num_draft [N] int32)`` for
    the step described by ``plan`` (num_draft[slot] <=
    plan.draft_cap[slot]).  ``histories`` maps each draft-eligible slot
    to its committed tokens (prompt + generated)."""
    raise NotImplementedError

  def observe_commit(self, new_cursors) -> None:
    """Engine hook after verification; ``new_cursors`` is the engine's
    post-rollback cursor vector (committed cache-resident tokens per
    slot)."""

  def observe_skip(self, plan) -> None:
    """Engine hook when a step runs WITHOUT drafting (the resilience
    degradation ladder's spec_off level skips draft compute outright —
    serving/resilience.py).  Stateless drafters ignore it; drafters
    with device state may mark their mirror stale.  Skipping can only
    cost acceptance rate after recovery, never correctness: the
    verifier judges every later draft against the target's own
    distribution."""


class NgramDrafter(Drafter):
  """Model-free prompt-lookup drafter (:func:`ngram_propose` per slot).

  ``lookback`` bounds the history scanned per step (the trailing window
  most likely to repeat): without it, a long-context request would pay
  an O(history) host-side scan per decode step on the serving hot path.
  0 = unbounded.
  """

  def __init__(self, k: int = 4, ngram_max: int = 4, ngram_min: int = 1,
               lookback: int = 512):
    if not 1 <= ngram_min <= ngram_max:
      raise ValueError(f"need 1 <= ngram_min <= ngram_max; got "
                       f"ngram_min={ngram_min}, ngram_max={ngram_max}")
    if lookback < 0:
      raise ValueError(f"lookback must be >= 0 (0 = unbounded): "
                       f"{lookback}")
    self.k = int(k)
    self.ngram_max = int(ngram_max)
    self.ngram_min = int(ngram_min)
    self.lookback = int(lookback)

  def propose(self, plan, histories):
    from easyparallellibrary_tpu.observability import trace as trace_lib
    # draft_cap is per-SLOT in both plan kinds (the paged plan's tokens
    # are a flat [token_budget] batch, so tokens.shape[0] is not N).
    N = plan.draft_cap.shape[0]
    toks = np.zeros((N, self.k), np.int32)
    counts = np.zeros((N,), np.int32)
    with trace_lib.get_tracer().span("ngram_propose", cat="serving",
                                     track="serving"):
      for slot, hist in histories.items():
        cap = int(plan.draft_cap[slot])
        if cap <= 0:
          continue
        if self.lookback:
          hist = hist[-self.lookback:]
        cont = ngram_propose(hist, min(cap, self.k), self.ngram_max,
                             self.ngram_min)
        counts[slot] = len(cont)
        toks[slot, :len(cont)] = cont
    return toks, counts


class DraftModelDrafter(Drafter):
  """Greedy draft-model drafter with its own slot KV cache.

  ``model``/``params`` are a small GPT sharing the target's vocabulary
  (checked at bind via ``_capabilities.check_draft_compatible``).  One
  jitted call per engine iteration first MIRRORS the step plan through
  the draft model (the same ``[num_slots, chunk]`` block the target
  sees: prefill chunks keep the draft cache in lockstep, decode slots'
  last committed token seeds the rollout), then greedily rolls ``k``
  tokens ahead per slot.  The draft cache buffer is donated, so the
  drafter's steady-state footprint is exactly one (small) cache.
  """

  def __init__(self, model, params, k: int = 4, mesh=None):
    self.k = int(k)
    self.model = model
    self.params = params
    self.mesh = mesh
    self._kv = None
    self._cursors = None
    self._fn = None
    # Paged-engine mirror (set at bind): the draft model keeps its OWN
    # paged pools but reads the ENGINE's block tables — block indices
    # depend only on positions, which are identical on both sides, so
    # one host allocation serves both caches.
    self._paged = False
    # Set at bind (observability/device.py cost-card capture).  The
    # attempt flag is one-shot: a FAILED capture must not re-pay the
    # AOT lower+compile on every subsequent propose() (capture_twin
    # stores no card on failure — it logs once and degrades).
    self._introspector = None
    self._twin_label = "serving/drafter"
    self._card_attempted = False

  @classmethod
  def from_checkpoint(cls, directory: str, model, *, k: int = 4,
                      target=None, shardings=None, mesh=None):
    """Restore draft params off the PR-2 checksum-validated fallback
    chain (``runtime.saver.restore_params``) and wrap them as a drafter.

    The checkpoint's embedding shape is validated against ``model.cfg``
    from the index alone (``saver.peek_leaf_shapes``) BEFORE any shard
    is read, so a wrong-vocabulary draft checkpoint fails in
    milliseconds with an actionable message instead of a tree-structure
    error mid-restore.  Without ``target`` a template is built by
    ``model.init`` (cheap for a drafter-sized GPT).
    """
    from easyparallellibrary_tpu.runtime import saver
    leaves, _ = saver.peek_leaf_shapes(directory)
    for path, shape in leaves.items():
      name = path[len("params/"):] if path.startswith("params/") else path
      if name == "wte/embedding" and shape and \
          shape[0] != model.cfg.vocab_size:
        raise ValueError(
            f"draft checkpoint {directory!r} holds a vocab-{shape[0]} "
            f"embedding but the draft config says vocab_size="
            f"{model.cfg.vocab_size}; speculative verification needs the "
            f"target's vocabulary — restore a checkpoint trained on the "
            f"same tokenizer")
    if target is None:
      target = model.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 4), jnp.int32))["params"]
    params, _ = saver.restore_params(directory, target=target,
                                     shardings=shardings)
    return cls(model, params, k=k, mesh=mesh)

  def bind(self, engine):
    from easyparallellibrary_tpu.observability import device as device_lib
    from easyparallellibrary_tpu.serving import kv_cache as kv_lib
    check_draft_compatible(engine.model.cfg, self.model.cfg)
    # Device-truth introspection (observability/device.py): the draft
    # rollout is a compiled twin like the fused step — its cost card is
    # captured at the first propose() with that call's abstract specs.
    self._introspector = device_lib.get_introspector()
    self._twin_label = f"{engine._track_prefix}/drafter"
    mesh = self.mesh if self.mesh is not None else engine.mesh
    self._paged = bool(getattr(engine, "paged", False))
    if self._paged:
      import dataclasses
      # The mirror pool is addressed exclusively through the ENGINE's
      # block tables (target max_seq_len / block_size wide), so its
      # capacity/geometry validation must use the TARGET's sequence
      # length — a draft model legitimately padded LONGER than the
      # target (check_draft_compatible permits and even advises it)
      # must not inflate the blocks-per-slot requirement.  Only the
      # draft's head geometry shapes the pool.
      mirror_cfg = dataclasses.replace(
          self.model.cfg, max_seq_len=engine.model.cfg.max_seq_len)
      self._kv = kv_lib.allocate_paged_kv_cache(
          mirror_cfg, engine.num_blocks, engine.block_size, mesh)
      self._fn = self._build_paged_draft_fn(engine)
    else:
      self._kv, self._cursors = kv_lib.allocate_kv_cache(
          self.model.cfg, engine.num_slots, engine.chunk, mesh)
      self._fn = self._build_draft_fn(engine.chunk)

  def _build_draft_fn(self, chunk: int):
    from easyparallellibrary_tpu.models.gpt import slot_step_logits
    model, K, C = self.model, self.k, chunk

    def draft(params, kv, cursors, tokens, num_valid, reset):
      cursors = jnp.where(reset, 0, cursors)
      # Mirror the engine's chunk: writes the same prefill K/V the
      # target wrote, and scores decode slots' last committed token.
      logits, kv = slot_step_logits(model, params, kv, tokens, cursors)
      last = jnp.take_along_axis(
          logits, jnp.clip(num_valid - 1, 0, C - 1)[:, None, None],
          axis=1)[:, 0]
      toks = [jnp.argmax(last, axis=-1).astype(jnp.int32)]
      cur = cursors + num_valid
      for _ in range(1, K):
        lg, kv = slot_step_logits(model, params, kv, toks[-1][:, None],
                                  cur)
        toks.append(jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32))
        cur = cur + 1
      # Write-only feed of the final draft: its K/V must be cache-
      # resident too — if every draft is accepted the rolled-back cursor
      # covers its position, and a later step would attend garbage
      # there (the logits of this call are dead code XLA prunes).
      _, kv = slot_step_logits(model, params, kv, toks[-1][:, None], cur)
      return jnp.stack(toks, axis=1), kv

    return jax.jit(draft, donate_argnums=(1,))

  def _build_paged_draft_fn(self, engine):
    """Paged twin of :meth:`_build_draft_fn`: mirror the engine's FLAT
    plan through the draft model (same tokens, slots, positions and
    block tables — prefill chunks keep the mirror pools in lockstep),
    then greedily roll ``k`` tokens ahead per drafting slot with
    one-token-per-slot flat batches at consecutive positions.  Rollout
    positions past the virtual length clamp to the null block inside
    ``paged_step_logits``, so overshoot (a slot near its budget) costs
    acceptance, never correctness.  No cursors anywhere: rollback is
    implicit in next step's host-planned positions."""
    from easyparallellibrary_tpu.models.gpt import paged_step_logits
    model, K = self.model, self.k
    N = engine.num_slots
    T = engine.token_budget
    impl = engine._paged_impl

    def draft(params, kv, tokens, slot_ids, positions, valid, tables,
              last_idx, drafting):
      li = jnp.clip(last_idx, 0, T - 1)
      logits, kv = paged_step_logits(model, params, kv, tokens, slot_ids,
                                     positions, valid, tables, impl=impl)
      last = jnp.take(logits, li, axis=0)                 # [N, V]
      toks = [jnp.argmax(last, axis=-1).astype(jnp.int32)]
      sid = jnp.arange(N, dtype=jnp.int32)
      pos0 = jnp.take(positions, li, axis=0) + 1          # first draft pos
      for j in range(1, K):
        lg, kv = paged_step_logits(model, params, kv, toks[-1], sid,
                                   pos0 + (j - 1), drafting, tables,
                                   impl=impl)
        toks.append(jnp.argmax(lg, axis=-1).astype(jnp.int32))
      # Write-only feed of the final draft (same contract as the slot
      # layout: full acceptance must leave no K/V hole).
      _, kv = paged_step_logits(model, params, kv, toks[-1], sid,
                                pos0 + (K - 1), drafting, tables,
                                impl=impl)
      return jnp.stack(toks, axis=1), kv

    return jax.jit(draft, donate_argnums=(1,))

  def propose(self, plan, histories):
    from easyparallellibrary_tpu.observability import trace as trace_lib
    if self._fn is None:
      raise RuntimeError("DraftModelDrafter.propose before bind(): the "
                         "engine binds drafters in its constructor")
    with trace_lib.get_tracer().span("draft_model_forward", cat="serving",
                                     track="serving"):
      if self._paged:
        last_idx = (plan.base_idx + plan.num_valid - 1).astype(np.int32)
        draft_args = (
            self.params, self._kv, plan.tokens, plan.slot_ids,
            plan.positions, plan.valid, plan.block_tables, last_idx,
            plan.draft_cap > 0)
      else:
        draft_args = (self.params, self._kv, self._cursors,
                      plan.tokens, plan.num_valid, plan.reset)
      if self._introspector is not None and not self._card_attempted:
        from easyparallellibrary_tpu.observability import (
            device as device_lib)
        # Capture BEFORE the call: the cache buffer is donated, and the
        # specs must describe arguments that still exist (abstract
        # shapes only — nothing is read or transferred).  Exactly one
        # attempt, success or not (the engine/fit captures follow the
        # same one-shot rule).
        self._card_attempted = True
        self._introspector.capture_twin(
            self._twin_label, self._fn, device_lib.specs_of(draft_args),
            compile_count=1, meta={"k": self.k})
      toks, self._kv = self._fn(*draft_args)
      # The drafter's one designated fetch — explicit, like the
      # engine's token fetch, so the serving loop stays legal under
      # jax.transfer_guard_device_to_host("disallow").
      toks = jax.device_get(toks)
    counts = np.minimum(plan.draft_cap, self.k).astype(np.int32)
    return toks, counts

  def observe_commit(self, new_cursors):
    # Cursor values are "committed tokens resident in cache" — identical
    # for draft and target caches, so adopting the engine's rolled-back
    # vector IS the draft-side rollback (rejected-draft K/V beyond it is
    # masked, then overwritten, exactly like chunked-prefill garbage).
    # Paged mirror: there are no cursors — next step's host-planned
    # positions ARE the rollback — so there is nothing to adopt.
    if not self._paged:
      self._cursors = new_cursors

  def observe_skip(self, plan):
    # A skipped step (resilience spec_off window) means the mirror cache
    # missed this step's K/V writes: positions the engine committed
    # during the window hold garbage on the draft side until the slot is
    # reused.  That can only depress acceptance after recovery — the
    # target's verification still judges every draft — so no repair pass
    # is attempted on the serving hot path.
    pass
