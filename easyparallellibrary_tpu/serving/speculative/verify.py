"""Batched speculative verification: per-slot accept/rollback, in-jit.

One call decides, for every slot at once, how many of its ``k`` drafted
tokens the target model keeps and what the one guaranteed extra token is
— greedy exact-match acceptance or Leviathan et al.'s rejection-sampling
acceptance, both with static shapes so the fused serving step never
recompiles as draft lengths vary.

Contract (drafters here are deterministic — prompt-lookup or greedy
draft-model — i.e. a point-mass proposal ``q = onehot(d)``):

* **greedy** (``temperature <= 0``): draft ``d_j`` is accepted while it
  equals ``argmax`` of the target logits at its position; the token at
  the first mismatch is the argmax itself (the correction), and when all
  drafts survive the bonus token is the argmax after them.  Committed
  ids are therefore bit-identical to non-speculative greedy decode.
* **sampled**: draft ``d_j`` is accepted with probability
  ``min(1, p_j(d_j) / q_j(d_j)) = p_j(d_j)`` where ``p_j`` is the
  target distribution AFTER the request's temperature/top-k/top-p
  filters (``engine.filtered_logits`` — the same distribution
  ``sample_token_slots`` draws from).  On rejection the committed token
  samples the residual ``norm(max(p_j - q_j, 0))`` — ``p_j`` with the
  rejected id removed; with all drafts accepted the bonus samples
  ``p_k``.  Per position the emitted token is distributed exactly as
  ``p_j`` (accept: ``p(d)``; reject then residual:
  ``(1 - p(d)) * p(y) / (1 - p(d))``), so speculation preserves the
  sampling distribution while changing the bitstream.

Randomness rides the per-request PRNG streams folded by **committed
token index** (scheduler contract): the decision for committed index
``t`` derives from ``fold_in(request_key, t)`` — independent of slot,
iteration, or how many drafts rode along.  A slot with ``num_draft == 0``
uses the plain committed-index fold for its sample, so requests served
without drafts (speculation off per-request, or an empty proposal)
reproduce the non-speculative engine's sample stream bit-exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from easyparallellibrary_tpu.serving.engine import filtered_logits

# Salts separating the acceptance-uniform and residual/bonus-sample
# streams derived from one committed-index fold.  The PLAIN fold (no
# salt) is reserved for the num_draft == 0 sample so that path stays
# bit-identical to the non-speculative engine.
_ACCEPT_SALT = 0x5bec
_SAMPLE_SALT = 0xd4a7


def verify_tokens(target_logits, draft_tokens, num_draft, keys, tok_index,
                  temperature, top_k, top_p):
  """Accept/rollback for one fused step, vectorized over slots.

  ``target_logits`` f32 ``[N, K+1, V]`` — row ``j`` is the target
  distribution (pre-filter logits) for the token FOLLOWING draft ``j``'s
  predecessor, i.e. the distribution draft ``j`` is judged against;
  row ``K`` (== row ``num_draft``) feeds the bonus token.
  ``draft_tokens`` int32 ``[N, K]``; ``num_draft`` int32 ``[N]`` in
  ``[0, K]`` (rows beyond a slot's count are ignored).  ``keys`` uint32
  ``[N, 2]`` per-request PRNG keys, ``tok_index`` int32 ``[N]`` tokens
  committed so far; ``temperature``/``top_k``/``top_p`` per-slot
  sampling knobs with ``sample_token_slots`` semantics.

  Returns ``(committed [N, K+1] int32, n_committed [N] int32,
  accepted [N] int32)`` with ``n_committed = accepted + 1``: the
  accepted draft prefix plus one correction/bonus token.  Only the first
  ``n_committed`` entries of each row are meaningful.
  """
  N, K1, V = target_logits.shape
  K = K1 - 1
  rep = lambda a: jnp.repeat(a, K1, axis=0)
  filt = filtered_logits(
      target_logits.reshape(N * K1, V), rep(temperature), rep(top_k),
      rep(top_p)).reshape(N, K1, V)
  probs = jax.nn.softmax(filt, axis=-1)
  greedy_tok = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)

  # One fold per committed token index this step could produce.
  idx = tok_index[:, None] + jnp.arange(K1)[None]          # [N, K+1]
  fold_grid = jax.vmap(jax.vmap(jax.random.fold_in, in_axes=(None, 0)),
                       in_axes=(0, 0))
  folded = fold_grid(keys, idx)                            # [N, K+1, 2]
  accept_u = jax.vmap(jax.vmap(
      lambda k_: jax.random.uniform(
          jax.random.fold_in(k_, _ACCEPT_SALT))))(folded)  # [N, K+1]

  p_draft = jnp.take_along_axis(
      probs[:, :K], draft_tokens[:, :, None], axis=-1)[..., 0]
  greedy_mode = temperature <= 0
  ok = jnp.where(greedy_mode[:, None],
                 draft_tokens == greedy_tok[:, :K],
                 accept_u[:, :K] < p_draft)
  ok = ok & (jnp.arange(K)[None] < num_draft[:, None])
  # Longest accepted PREFIX: a rejection voids everything after it (the
  # later drafts were conditioned on the rejected token).
  prefix = jnp.cumprod(ok.astype(jnp.int32), axis=1)
  accepted = jnp.sum(prefix, axis=1).astype(jnp.int32)

  # The guaranteed extra token at draft index a = accepted: bonus from
  # p_a when every draft survived, else the residual at the rejection.
  a = accepted
  fin_filt = jnp.take_along_axis(filt, a[:, None, None], axis=1)[:, 0]
  fin_greedy = jnp.take_along_axis(greedy_tok, a[:, None], axis=1)[:, 0]
  rej_tok = jnp.take_along_axis(
      draft_tokens, jnp.clip(a, 0, K - 1)[:, None], axis=1)[:, 0]
  is_bonus = a == num_draft
  resid = jnp.where(jax.nn.one_hot(rej_tok, V, dtype=bool),
                    -jnp.inf, fin_filt)
  # Degenerate residual (the filtered support was exactly the rejected
  # token — reachable only through float roundoff on an accept
  # probability of 1): fall back to the filtered distribution rather
  # than sampling uniformly over filtered-out ids.
  resid_ok = jnp.any(resid > jnp.asarray(-1e29, resid.dtype), axis=-1,
                     keepdims=True)
  resid = jnp.where(resid_ok, resid, fin_filt)
  fin_logits = jnp.where(is_bonus[:, None], fin_filt, resid)

  fold_a = jnp.take_along_axis(folded, a[:, None, None], axis=1)[:, 0]
  salted = jax.vmap(
      lambda k_: jax.random.fold_in(k_, _SAMPLE_SALT))(fold_a)
  samp_keys = jnp.where((num_draft == 0)[:, None], fold_a, salted)
  sampled = jax.vmap(jax.random.categorical)(samp_keys, fin_logits)
  fin = jnp.where(greedy_mode, fin_greedy,
                  sampled.astype(jnp.int32)).astype(jnp.int32)

  pad_drafts = jnp.concatenate(
      [draft_tokens.astype(jnp.int32), jnp.zeros((N, 1), jnp.int32)],
      axis=1)
  committed = jnp.where(jnp.arange(K1)[None] < a[:, None],
                        pad_drafts, fin[:, None])
  return committed, accepted + 1, accepted
