"""One serving replica: an engine plus the host-side plumbing the
router needs to treat it as a fleet member.

A replica is a :class:`~easyparallellibrary_tpu.serving.engine.
ContinuousBatchingEngine` with its own scheduler, KV cache, compiled
fused step, watchdog and :class:`~easyparallellibrary_tpu.profiler.
serving.ServingStats` — replicas share NOTHING but the params source
(the same sharded arrays; params are read-only in serving, so N engines
can hold the same reference).  On top of the engine this class adds:

* **heartbeat material** — every :meth:`step` returns normally or
  raises; the router converts the former into a health beat carrying
  the live signals the step already produced on the host (cumulative
  watchdog-timeout and bad-step counters, the ITL EWMA) and the latter
  into ``mark_down`` + failover.  The replica itself holds no health
  state — policy lives in :class:`serving.resilience.ReplicaHealth`,
  mechanics here.
* **load signals** — ``queue_depth`` / ``num_active`` / ``load`` for
  least-loaded dispatch (the same occupancy/queue gauges the engine
  already publishes through the metric registry).
* **a per-replica metric namespace** — the engine's ``serving/*``
  registry records are re-rooted to ``serving/replica<i>/*`` via a thin
  proxy, so one registry shows every replica side by side plus the
  router's ``serving/fleet/*`` rollup (docs/observability.md).
* **migration endpoints** — :meth:`snapshot_requests` /
  :meth:`restore_request` / :meth:`evacuate` delegate to the engine's
  bit-exact prefix-replay machinery (scheduler.snapshot_requests).

Thread-hosting note: the router drives replicas synchronously (one
``step()`` sweep per router step) — deterministic, test-friendly, and
faithful to the failure modes that matter (a step that raises models a
dead process: its HOST state is what a control plane could recover from
a request journal; a step that stalls models a hung device).  Nothing
here holds state that would prevent moving a replica behind a thread or
process boundary later — the snapshot currency is already serializable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from easyparallellibrary_tpu.serving.engine import ContinuousBatchingEngine
from easyparallellibrary_tpu.serving.scheduler import (
    FinishedRequest, Request)


class _ReplicaRegistry:
  """Registry proxy re-rooting ``serving`` → ``serving/replica<i>``.

  The engine and its ServingStats publish under the ``serving``
  namespace unconditionally; wrapping the registry (instead of teaching
  them a prefix parameter) keeps every existing producer untouched while
  per-replica records land under their own sub-namespace — the schema
  already allows sub-namespaces (observability/registry.py)."""

  def __init__(self, inner, index: int):
    self._inner = inner
    self._prefix = f"serving/replica{index}"

  def publish(self, step: int, metrics, namespace: str = "train"):
    if namespace == "serving":
      namespace = self._prefix
    elif namespace.startswith("serving/"):
      namespace = self._prefix + namespace[len("serving"):]
    self._inner.publish(step, metrics, namespace)

  def __getattr__(self, name):
    return getattr(self._inner, name)


class EngineReplica:
  """One fleet member: engine + stats + migration endpoints.

  ``engine_kwargs`` pass through to :class:`ContinuousBatchingEngine`
  (num_slots, prefill_chunk, drafter, resilience, paged, ...).  A
  ``stats`` object is always attached (built here when the caller
  passes none) — the router's health beats and the fleet rollup read
  it.  ``registry`` (optional) is wrapped per-replica; pass the SAME
  registry to every replica and the router.
  """

  def __init__(self, index: int, model, params, *, mesh=None,
               registry=None, config=None, stats=None, **engine_kwargs):
    self.index = index
    if stats is None and engine_kwargs.get("stats") is None:
      from easyparallellibrary_tpu.profiler.serving import ServingStats
      stats = ServingStats()
    if stats is not None:
      engine_kwargs["stats"] = stats
    # Per-replica Perfetto tracks (serving/replica<i>/slot N) so a
    # failed-over request's flow arc visibly crosses replica tracks
    # instead of two replicas' slot 0 sharing one row.
    engine_kwargs.setdefault("track_prefix", f"serving/replica{index}")
    self.engine = ContinuousBatchingEngine(
        model, params, mesh=mesh, config=config,
        registry=(_ReplicaRegistry(registry, index)
                  if registry is not None else None),
        **engine_kwargs)
    self.stats = self.engine.stats
    self.steps = 0

  # ------------------------------------------------------------- serving

  def submit(self, request: Request) -> bool:
    return self.engine.submit(request)

  def cancel(self, uid: Any) -> bool:
    return self.engine.cancel(uid)

  def step(self) -> List[FinishedRequest]:
    """One engine iteration (cheap when idle).  Raises whatever the
    engine raises — the router treats an escaping exception as this
    replica dying mid-step."""
    fins = self.engine.step()
    self.steps += 1
    return fins

  @property
  def has_work(self) -> bool:
    return self.engine.has_work

  @property
  def finished(self) -> Dict[Any, FinishedRequest]:
    return self.engine.finished

  # -------------------------------------------------------- load signals

  @property
  def queue_depth(self) -> int:
    return self.engine.scheduler.queue_depth

  @property
  def num_active(self) -> int:
    return self.engine.scheduler.num_active

  @property
  def num_slots(self) -> int:
    return self.engine.num_slots

  @property
  def load(self) -> int:
    """Requests this replica is responsible for (active + queued) — the
    least-loaded dispatch key."""
    return self.num_active + self.queue_depth

  # ------------------------------------------------------ health signals

  @property
  def watchdog_timeouts(self) -> int:
    return self.stats.watchdog_timeouts if self.stats is not None else 0

  @property
  def bad_steps(self) -> int:
    return self.stats.bad_steps if self.stats is not None else 0

  @property
  def itl_ewma_s(self) -> float:
    return self.stats.itl_ewma_s if self.stats is not None else 0.0

  # ---------------------------------------------------------- migration

  def snapshot_requests(self) -> List[Dict[str, Any]]:
    return self.engine.snapshot_requests()

  def restore_request(self, snap: Dict[str, Any],
                      front: bool = False) -> Any:
    return self.engine.restore_request(snap, front=front)

  def evacuate(self) -> List[Dict[str, Any]]:
    return self.engine.evacuate()

  # ----------------------------------------------------------- lifecycle

  def close(self):
    self.engine.close()

  def __repr__(self):
    return (f"EngineReplica({self.index}, active={self.num_active}, "
            f"queued={self.queue_depth})")
