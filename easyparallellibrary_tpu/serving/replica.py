"""One serving replica: an engine plus the host-side plumbing the
router needs to treat it as a fleet member.

A replica is a :class:`~easyparallellibrary_tpu.serving.engine.
ContinuousBatchingEngine` with its own scheduler, KV cache, compiled
fused step, watchdog and :class:`~easyparallellibrary_tpu.profiler.
serving.ServingStats` — replicas share NOTHING but the params source
(the same sharded arrays; params are read-only in serving, so N engines
can hold the same reference).  On top of the engine this class adds:

* **heartbeat material** — every :meth:`step` returns normally or
  raises; the router converts the former into a health beat carrying
  the live signals the step already produced on the host (cumulative
  watchdog-timeout and bad-step counters, the ITL EWMA) and the latter
  into ``mark_down`` + failover.  The replica itself holds no health
  state — policy lives in :class:`serving.resilience.ReplicaHealth`,
  mechanics here.
* **load signals** — ``queue_depth`` / ``num_active`` / ``load`` for
  least-loaded dispatch (the same occupancy/queue gauges the engine
  already publishes through the metric registry).
* **a per-replica metric namespace** — the engine's ``serving/*``
  registry records are re-rooted to ``serving/replica<i>/*`` via a thin
  proxy, so one registry shows every replica side by side plus the
  router's ``serving/fleet/*`` rollup (docs/observability.md).
* **migration endpoints** — :meth:`snapshot_requests` /
  :meth:`restore_request` / :meth:`evacuate` delegate to the engine's
  bit-exact prefix-replay machinery (scheduler.snapshot_requests).

Hosting note: by default the router drives replicas in-process and
synchronously (one ``step()`` sweep per router step — deterministic and
test-friendly), via :class:`serving.transport.InprocTransport`.  With
``serving.router.transport = "process"`` the SAME class runs inside a
spawned worker process that owns its own JAX runtime — the
:func:`replica_worker_main` serve loop at the bottom of this module
answers the parent's :class:`serving.transport.ProcessTransport` over a
length-prefixed-JSON socketpair, which is the real fault domain: a
SIGKILL takes exactly one replica's memory, and failover recovers from
the router-side journal, not from this process.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.serving.engine import ContinuousBatchingEngine
from easyparallellibrary_tpu.serving.scheduler import (
    FinishedRequest, Request)


class _ReplicaRegistry:
  """Registry proxy re-rooting ``serving`` → ``serving/replica<i>``.

  The engine and its ServingStats publish under the ``serving``
  namespace unconditionally; wrapping the registry (instead of teaching
  them a prefix parameter) keeps every existing producer untouched while
  per-replica records land under their own sub-namespace — the schema
  already allows sub-namespaces (observability/registry.py)."""

  def __init__(self, inner, index: int):
    self._inner = inner
    self._prefix = f"serving/replica{index}"

  def publish(self, step: int, metrics, namespace: str = "train"):
    if namespace == "serving":
      namespace = self._prefix
    elif namespace.startswith("serving/"):
      namespace = self._prefix + namespace[len("serving"):]
    self._inner.publish(step, metrics, namespace)

  def __getattr__(self, name):
    return getattr(self._inner, name)


class EngineReplica:
  """One fleet member: engine + stats + migration endpoints.

  ``engine_kwargs`` pass through to :class:`ContinuousBatchingEngine`
  (num_slots, prefill_chunk, drafter, resilience, paged, ...).  A
  ``stats`` object is always attached (built here when the caller
  passes none) — the router's health beats and the fleet rollup read
  it.  ``registry`` (optional) is wrapped per-replica; pass the SAME
  registry to every replica and the router.
  """

  def __init__(self, index: int, model, params, *, mesh=None,
               registry=None, config=None, stats=None, **engine_kwargs):
    self.index = index
    if stats is None and engine_kwargs.get("stats") is None:
      from easyparallellibrary_tpu.profiler.serving import ServingStats
      stats = ServingStats()
    if stats is not None:
      engine_kwargs["stats"] = stats
    # Per-replica Perfetto tracks (serving/replica<i>/slot N) so a
    # failed-over request's flow arc visibly crosses replica tracks
    # instead of two replicas' slot 0 sharing one row.
    engine_kwargs.setdefault("track_prefix", f"serving/replica{index}")
    self.engine = ContinuousBatchingEngine(
        model, params, mesh=mesh, config=config,
        registry=(_ReplicaRegistry(registry, index)
                  if registry is not None else None),
        **engine_kwargs)
    self.stats = self.engine.stats
    self.steps = 0

  # ------------------------------------------------------------- serving

  def submit(self, request: Request) -> bool:
    return self.engine.submit(request)

  def cancel(self, uid: Any) -> bool:
    return self.engine.cancel(uid)

  def step(self) -> List[FinishedRequest]:
    """One engine iteration (cheap when idle).  Raises whatever the
    engine raises — the router treats an escaping exception as this
    replica dying mid-step."""
    fins = self.engine.step()
    self.steps += 1
    return fins

  @property
  def has_work(self) -> bool:
    return self.engine.has_work

  @property
  def scheduler(self):
    """The engine's scheduler — the subscriber-list hook point
    (``on_admit``/``on_first_token``/``on_tokens``/``on_finish``) the
    router's stream fanout and the sim fleet both attach to."""
    return self.engine.scheduler

  @property
  def finished(self) -> Dict[Any, FinishedRequest]:
    return self.engine.finished

  # -------------------------------------------------------- load signals

  @property
  def queue_depth(self) -> int:
    return self.engine.scheduler.queue_depth

  @property
  def num_active(self) -> int:
    return self.engine.scheduler.num_active

  @property
  def num_slots(self) -> int:
    return self.engine.num_slots

  @property
  def load(self) -> int:
    """Requests this replica is responsible for (active + queued) — the
    least-loaded dispatch key."""
    return self.num_active + self.queue_depth

  @property
  def checkpoint_version(self) -> int:
    """The checkpoint version this replica's params came from
    (blue/green rollout, serving/rollout.py; 0 pre-rollout).  The
    router reads it for version-aware dispatch and version-gated
    failover placement."""
    return self.engine.checkpoint_version

  # ------------------------------------------------------ health signals

  @property
  def watchdog_timeouts(self) -> int:
    return self.stats.watchdog_timeouts if self.stats is not None else 0

  @property
  def bad_steps(self) -> int:
    return self.stats.bad_steps if self.stats is not None else 0

  @property
  def itl_ewma_s(self) -> float:
    return self.stats.itl_ewma_s if self.stats is not None else 0.0

  # ---------------------------------------------------------- migration

  def snapshot_requests(self) -> List[Dict[str, Any]]:
    return self.engine.snapshot_requests()

  def restore_request(self, snap: Dict[str, Any],
                      front: bool = False) -> Any:
    return self.engine.restore_request(snap, front=front)

  def evacuate(self) -> List[Dict[str, Any]]:
    return self.engine.evacuate()

  # ----------------------------------------------------------- lifecycle

  def close(self):
    self.engine.close()

  def __repr__(self):
    return (f"EngineReplica({self.index}, active={self.num_active}, "
            f"queued={self.queue_depth})")


# ---------------------------------------------------------- worker main --
#
# `python -m easyparallellibrary_tpu.serving.replica --worker-fd N` is
# the child half of serving/transport.py's ProcessTransport: a spawned
# process owning its own JAX runtime, answering length-prefixed JSON
# frames over the socketpair fd it inherited.  Pure host plumbing — the
# engine underneath is byte-for-byte the in-process one.


def _install_pdeathsig() -> None:
  """Ask Linux to SIGKILL this worker the instant its parent dies
  (PR_SET_PDEATHSIG) — the kernel-level half of orphan prevention; the
  pipe-EOF exit below is the portable half."""
  try:
    import ctypes
    libc = ctypes.CDLL("libc.so.6", use_errno=True)
    PR_SET_PDEATHSIG = 1
    libc.prctl(PR_SET_PDEATHSIG, 9)  # SIGKILL
  except Exception:  # pragma: no cover - non-Linux / no libc
    pass


class _WorkerServer:
  """Dispatch loop state for one worker process."""

  def __init__(self, sock):
    from easyparallellibrary_tpu.serving import transport as transport_lib
    self._t = transport_lib
    self.sock = sock
    self.reader = transport_lib.FrameReader(sock)
    self.replica: Optional[EngineReplica] = None
    self._first_tokens: List[Any] = []
    # Cross-process trace harvest (docs/observability.md "Distributed
    # tracing"): when the parent's config enables the tracer, this
    # child records into its OWN ring and the parent drains it in
    # bounded chunks riding step replies, plus a final flush on the
    # shutdown/evacuate paths.  0 bytes = harvest off.
    self.tracer: Optional[Any] = None
    self._harvest_bytes = 0
    # Idempotency dedup: uid -> recorded reply result.  A submit or
    # restore retried after an ambiguous timeout (the reply was lost
    # AFTER this process applied the call) returns the recorded
    # verdict instead of admitting the request twice.
    self._applied: Dict[Any, Dict[str, Any]] = {}

  # ------------------------------------------------------------- handlers

  def _beat(self) -> Dict[str, Any]:
    rep = self.replica
    if rep is None:
      return {}
    try:
      compiles = int(rep.engine._step_fn._cache_size())
    except Exception:
      compiles = 0
    beat = {
        "watchdog_timeouts": int(rep.watchdog_timeouts),
        "bad_steps": int(rep.bad_steps),
        "itl_ewma_s": float(rep.itl_ewma_s),
        "queue_depth": int(rep.queue_depth),
        "num_active": int(rep.num_active),
        "num_slots": int(rep.num_slots),
        "load": int(rep.load),
        "has_work": bool(rep.has_work),
        "compiles": compiles,
        "checkpoint_version": int(rep.checkpoint_version),
        "pid": os.getpid(),
    }
    if self.tracer is not None and self.tracer.enabled:
      # The parent pairs this with its send/recv perf_counter_ns stamps
      # to estimate the cross-process clock offset (midpoint method) —
      # every reply is a fresh sample, re-sampled on the heartbeat
      # cadence parent-side.
      beat["trace_now_us"] = self.tracer.now_us()
    return beat

  def do_init(self, p: Dict[str, Any]) -> Dict[str, Any]:
    wire = int(p.get("wire_version", -1))
    if wire != self._t.WIRE_VERSION:
      raise ValueError(
          f"wire version mismatch: parent speaks v{wire}, this worker "
          f"speaks v{self._t.WIRE_VERSION} — parent and child must run "
          f"the same build")
    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
      # Mirrors tests/conftest.py: the image's sitecustomize can latch
      # the TPU plugin before env vars are honored; backends are not
      # initialized yet, so the config override still wins.
      jax.config.update("jax_platforms", "cpu")
    import easyparallellibrary_tpu as epl
    config = epl.Config(p.get("config") or {})
    epl.init(config)
    # The parent's observability config crossed the wire inside the
    # init frame: configure this child's OWN tracer ring from it, so
    # child-side spans exist for the parent to harvest.  flow_id rides
    # every Request snapshot (scheduler wire shape v2+), so the spans
    # recorded here join the SAME request flow the parent started.
    tracer = trace_lib.ensure_configured(config)
    obs = config.observability
    if tracer.enabled and obs.harvest.enabled:
      self.tracer = tracer
      self._harvest_bytes = int(obs.harvest.max_bytes_per_sweep)
    fn, kwargs = self._t.resolve_factory(p["factory"])
    model, params = fn(**kwargs)
    checkpoint = p.get("checkpoint")
    if checkpoint:
      # Blue/green rollout (serving/rollout.py): this child serves a
      # SPECIFIC checkpoint, not the factory's params.  restore_params
      # walks the checksum-validated chain and verifies the stored
      # params fingerprint/geometry against the factory tree, so a
      # half-written or mismatched checkpoint fails the init RPC with a
      # clear error instead of an XLA shape crash mid-decode.
      from easyparallellibrary_tpu.runtime.saver import restore_params
      params, _ = restore_params(checkpoint, target=params)
    self.replica = EngineReplica(
        int(p.get("index", 0)), model, params, config=config,
        **(p.get("engine_kwargs") or {}))
    self.replica.engine.scheduler.on_first_token.append(
        self._first_tokens.append)
    return {"pid": os.getpid(),
            "platform": jax.devices()[0].platform}

  def do_submit(self, p: Dict[str, Any]) -> Dict[str, Any]:
    req = Request.restore(p["snap"])
    if req.uid in self._applied:
      return self._applied[req.uid]
    accepted = self.replica.submit(req)
    result: Dict[str, Any] = {"accepted": bool(accepted)}
    if not accepted:
      fin = self.replica.finished.get(req.uid)
      if fin is not None:
        result["finished"] = self._t.encode_finished(fin)
    self._applied[req.uid] = result
    return result

  def do_restore(self, p: Dict[str, Any]) -> Dict[str, Any]:
    uid = p["snap"]["request"]["uid"]
    if uid in self._applied and self._applied[uid].get("restored"):
      return self._applied[uid]
    self.replica.restore_request(p["snap"], front=bool(p.get("front")))
    result = {"accepted": True, "restored": True, "uid": uid}
    self._applied[uid] = result
    return result

  def do_cancel(self, p: Dict[str, Any]) -> Dict[str, Any]:
    return {"cancelled": bool(self.replica.cancel(p["uid"]))}

  def do_step(self, p: Dict[str, Any]) -> Dict[str, Any]:
    acked = {uid: int(n) for uid, n in p.get("acked", ())}
    fins = self.replica.step()
    progress = []
    order = []
    for uid, gen in self.replica.engine.scheduler.progress():
      order.append(uid)
      start = min(acked.get(uid, 0), len(gen))
      progress.append([uid, start, [int(t) for t in gen[start:]]])
    # A finished request frees its dedup slot — uids may be reused
    # across episodes, and the dedup map must not grow unboundedly.
    for fin in fins:
      self._applied.pop(fin.uid, None)
    # Shed verdicts free at the NEXT step: the parent is synchronous —
    # by the time it sends a step, every earlier submit's retry loop
    # has resolved — so the retry window is over, and keeping the
    # verdict would permanently reject a legitimately reused uid (and
    # leak one entry per shed under sustained overload).
    for uid in [u for u, v in self._applied.items()
                if not v.get("accepted")]:
      self._applied.pop(uid, None)
    # Drain IN PLACE: the scheduler hook holds this exact list object.
    first = list(self._first_tokens)
    self._first_tokens.clear()
    out = {"finished": [self._t.encode_finished(f) for f in fins],
           "progress": progress, "order": order, "first": first}
    if self._harvest_bytes:
      # Incremental trace harvest piggybacks on the step reply, bounded
      # bytes per sweep so it can never stall dispatch; the ring
      # remainder rides later sweeps or the final flush.
      chunk = self.tracer.drain_wire(self._harvest_bytes)
      if chunk["events"]:
        out["trace"] = chunk
    return out

  def do_snapshot(self, p: Dict[str, Any]) -> Dict[str, Any]:
    return {"snaps": self.replica.snapshot_requests()}

  def do_evacuate(self, p: Dict[str, Any]) -> Dict[str, Any]:
    snaps = self.replica.evacuate()
    for snap in snaps:
      self._applied.pop(snap["request"]["uid"], None)
    result: Dict[str, Any] = {"snaps": snaps}
    # A graceful evacuation usually precedes a fence: flush the whole
    # ring now so a drained replica's spans all reach the merged trace.
    chunk = self._final_flush()
    if chunk is not None:
      result["trace"] = chunk
    return result

  def do_stats(self, p: Dict[str, Any]) -> Dict[str, Any]:
    stats = self.replica.stats
    return {"stats": stats.state_dict() if stats is not None else None}

  def do_ping(self, p: Dict[str, Any]) -> Dict[str, Any]:
    return {"pong": True}

  def do_harvest(self, p: Dict[str, Any]) -> Dict[str, Any]:
    """Explicit low-priority harvest sweep: drain up to ``max_bytes``
    of the tracer ring (the configured sweep bound when unspecified;
    ``drain=True`` empties it)."""
    if self.tracer is None:
      return {"done": True}
    if p.get("drain"):
      max_bytes = None
    else:
      max_bytes = int(p.get("max_bytes") or self._harvest_bytes or 65536)
    chunk = self.tracer.drain_wire(max_bytes)
    out: Dict[str, Any] = {"done": not self.tracer.pending}
    if chunk["events"]:
      out["trace"] = chunk
    return out

  def _final_flush(self) -> Optional[Dict[str, Any]]:
    """The whole ring remainder, for the shutdown/evacuate replies —
    a cleanly exiting worker loses nothing (the satellite bugfix: child
    replicas used to exit without exporting a single span)."""
    if self.tracer is None:
      return None
    chunk = self.tracer.drain_wire(None)
    return chunk if chunk["events"] else None

  # ----------------------------------------------------------- serve loop

  def serve(self) -> int:
    handlers = {
        "init": self.do_init, "submit": self.do_submit,
        "restore": self.do_restore, "cancel": self.do_cancel,
        "step": self.do_step, "snapshot": self.do_snapshot,
        "evacuate": self.do_evacuate, "stats": self.do_stats,
        "ping": self.do_ping, "harvest": self.do_harvest,
    }
    while True:
      try:
        frame = self.reader.read(None)
      except self._t.ReplicaDeadError:
        # Parent gone (pipe EOF): exit now rather than orphan — the
        # prctl death signal is the backstop, this is the portable path.
        # Best-effort final trace flush: the socket is usually fully
        # dead here, but a parent that only shut down its write side
        # can still receive the ring remainder.
        chunk = self._final_flush()
        if chunk is not None:
          try:
            self._t.send_frame(self.sock, {
                "id": None, "m": "trace_flush", "ok": True,
                "result": {"trace": chunk}, "beat": self._beat()})
          except OSError:
            pass
        break
      rid, method = frame.get("id"), frame.get("m")
      if method == "shutdown":
        # Clean exit loses no trace events: the shutdown reply carries
        # the whole ring remainder (the parent's close() ingests it
        # before reaping this process).
        result: Dict[str, Any] = {}
        chunk = self._final_flush()
        if chunk is not None:
          result["trace"] = chunk
        self._reply(rid, method, {"ok": True, "result": result})
        break
      handler = handlers.get(method)
      try:
        if handler is None:
          raise ValueError(f"unknown transport method {method!r}")
        result = handler(frame.get("p") or {})
        self._reply(rid, method, {"ok": True, "result": result})
      except Exception as e:  # noqa: BLE001 — report, don't die: the
        # parent decides whether an error is fatal (its router treats a
        # step error as replica death and evacuates gracefully).
        self._reply(rid, method,
                    {"ok": False, "error": str(e),
                     "etype": type(e).__name__})
    if self.replica is not None:
      self.replica.close()
    return 0

  def _reply(self, rid, method, body: Dict[str, Any]) -> None:
    body["id"] = rid
    body["m"] = method
    body["beat"] = self._beat()
    try:
      self._t.send_frame(self.sock, body)
    except OSError:
      raise self._t.ReplicaDeadError("parent went away mid-reply")


def replica_worker_main(fd: int) -> int:
  """Entry point for the spawned replica worker (transport child)."""
  _install_pdeathsig()
  import socket as socket_lib
  sock = socket_lib.socket(fileno=fd)
  try:
    return _WorkerServer(sock).serve()
  finally:
    try:
      sock.close()
    except OSError:
      pass


if __name__ == "__main__":
  import argparse
  parser = argparse.ArgumentParser(
      description="serving replica worker (spawned by ProcessTransport; "
                  "not a user-facing CLI)")
  parser.add_argument("--worker-fd", type=int, required=True)
  raise SystemExit(replica_worker_main(parser.parse_args().worker_fd))
