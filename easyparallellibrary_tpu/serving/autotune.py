"""Engine-level SLO actuator: breaches move the knobs the stack
already has, between steps, under the compile-once constraint.

PR 9's :class:`~easyparallellibrary_tpu.observability.slo.SLOMonitor`
closed the sensing half of ROADMAP item 5 — TTFT/ITL/burn-rate rules
evaluate live and ``add_listener`` exposes every breach — but until now
a human read the breach log while the engine kept degrading.  This
module is the acting half at ENGINE scope (fleet scope lives in
serving/autoscale.py): an :class:`EngineAutotuner` subscribes to the
monitor and walks its own small ladder of DATA-valued knobs:

========  ============  ==============================================
level     name          knobs applied (all host-side plan data)
========  ============  ==============================================
0         normal        baseline — every clamp released
1         spec_trim     speculation-k clamped to half the drafter's k,
                        floored at 1 (draft compute shrinks but never
                        stops here; greedy exactness holds)
2         budget_tight  speculation off, per-step prefill budget
                        clamped to ``budget_chunks * prefill_chunk``,
                        admission-ladder floor pinned at spec_off
3         slot_cap      plus effective concurrency clamped to half the
                        batch cap (bounded below by ``min_slots``) —
                        fewer resident slots, faster steps, ITL recovers
========  ============  ==============================================

Every knob is data the scheduler reads while planning the NEXT step
(``tune_spec_k`` / ``tune_budget`` / ``tune_slot_cap``,
scheduler.py; ``floor_level``, resilience.py) — shapes of the compiled
fused step never change, so actuation can never cost a recompile.
Geometry (num_slots, chunk, paged pool size) is deliberately NOT a
knob here: geometry changes go through the router's drain + warm
rebuild path, never a live reshape.

Escalation is immediate on a breach event (one level per breached
step), and continues one level per ``hold_steps`` while a matching
stream STAYS breached (a breach event fires only on the transition —
sustained overload is a stream that never recovers, polled via
:meth:`SLOMonitor.breached_streams`).  Recovery is hysteretic,
mirroring PR 6's admission ladder: one level per clean ``hold_steps``
window, so the climb down is staged.  A STALE breach — a stream wedged
"breached" whose records stopped flowing (e.g. a burn stream on an
idle engine, which is silent rather than healthy) — stops counting as
pressure after ``10 * hold_steps`` event-free steps, so it can never
pin the engine slow forever.

Every actuation is emitted three ways at once: a ``serving/actuation``
trace instant (+ ``serving/autotune_level`` counter track), an
``slo_events.jsonl`` line via :meth:`SLOMonitor.note_actuation` (the
stream ``report.py --follow`` renders), and the ``autotune_level`` /
``autotune_actuations`` keys on the engine's per-step registry record —
so the chaos harness can pin "actuator fires, stream stays bit-exact
for non-shed requests, zero recompiles" (``make chaos-heal``).

Pure host policy — no jax imports, unit-testable with a duck-typed
engine (tests/test_serving_autoscale.py).  Knobs:
``serving.autotune.*`` (docs/robustness.md "Self-healing fleet").
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.utils.logging import get_logger

# Tune-ladder levels, in escalation order (index = level number carried
# by metrics and actuation payloads).
TUNE_LEVELS = ("normal", "spec_trim", "budget_tight", "slot_cap")


class EngineAutotuner:
  """Breach-driven knob ladder for ONE engine (module docstring).

  ``engine`` duck-types :class:`ContinuousBatchingEngine`: the tuner
  reads ``scheduler`` (tune_* fields), ``chunk``, ``_admission``
  (ladder floor), ``_twin_label`` / ``_track_prefix`` (breach
  attribution) and ``registry``/``stats`` presence is irrelevant.
  ``monitor`` may be None (config enabled the tuner but SLO monitoring
  is off) — the tuner then never hears a breach and stays at level 0.

  Threading: breach callbacks may arrive from the watchdog's monitor
  thread (``note_event``), so :meth:`_on_breach` only RECORDS the
  breach under a lock; knobs move exclusively in :meth:`on_step`, which
  the engine calls at the top of each host iteration — strictly between
  fused-step dispatches.
  """

  def __init__(self, engine, monitor, config=None):
    conf = (config if config is not None
            else Env.get().config).serving.autotune
    self.engine = engine
    self.monitor = monitor
    self.hold_steps = conf.hold_steps
    self.max_level = min(conf.max_level, len(TUNE_LEVELS) - 1)
    self.min_slots = conf.min_slots
    self.budget_chunks = conf.budget_chunks
    self.level = 0
    self.actuations = 0
    self.breaches_heard = 0
    sched = engine.scheduler
    self._base_spec_k = sched.spec_k
    self._base_cap = min(sched.num_slots, sched.max_batch)
    # Engine step index of the last actuation OR matching breach — the
    # hold window (recovery AND sustained-pressure escalation) restarts
    # from whichever is later.
    self._hold_from: Optional[int] = None
    # Step of the last sign of LIFE from a matching breach: a breach
    # event, or a breached stream whose record count grew (the monitor
    # only fires events on transitions; slo.BreachPressure owns that
    # invariant).  A breached stream silent past stale_steps is stale,
    # not pressure (docstring).
    self._last_heard_step: Optional[int] = None
    from easyparallellibrary_tpu.observability.slo import BreachPressure
    self._probe = BreachPressure(
        monitor, lambda _rule, key: self._matches({"metric": key}))
    self.stale_steps = 10 * self.hold_steps
    self._lock = threading.Lock()
    self._pending_rule: Optional[str] = None
    if monitor is not None:
      # Weak: the ambient monitor outlives engines; a discarded engine
      # (and its tuner) must stay collectible.
      monitor.add_listener(self._on_breach, weak=True)
    else:
      get_logger().warning(
          "serving.autotune.enabled without observability.slo.enabled: "
          "the autotuner has no breach source and will never actuate")
    get_logger().info(
        "engine autotuner: max level %s, hold %d steps, budget clamp "
        "%d chunk(s), slot floor %d", TUNE_LEVELS[self.max_level],
        self.hold_steps, self.budget_chunks, self.min_slots)

  # ------------------------------------------------------------ matching

  def _matches(self, payload: Dict[str, Any]) -> bool:
    """Does a breach concern THIS engine?  Engine-attributed events
    (watchdog, recompile) carry the twin label; record-rule breaches
    carry the metric key, matched by this engine's namespace prefix.
    Fleet-scope metrics (``serving/fleet/*``) are the autoscaler's to
    act on — one fleet breach must not tighten every healthy replica
    at once (same reasoning as the xla-capture listener, engine.py)."""
    twin = payload.get("twin")
    if twin is not None:
      return twin == self.engine._twin_label
    metric = str(payload.get("metric", ""))
    if not metric:
      return False
    prefix = getattr(self.engine, "_track_prefix", "serving")
    # Exclusions FIRST — a bare engine's prefix is "serving", which
    # would otherwise swallow both scopes below:
    if metric.startswith("serving/fleet/"):
      return False                 # fleet scope is the autoscaler's
    if metric.startswith("serving/replica"):
      # A replica-scoped stream concerns exactly the replica it names.
      return prefix != "serving" and metric.startswith(prefix + "/")
    # Own namespace, or the plain serving/* keys a registry-less
    # engine publishes whatever its track prefix.
    return (metric.startswith(prefix + "/")
            or metric.startswith("serving/"))

  def _on_breach(self, rule: str, payload: Dict[str, Any]) -> None:
    if not self._matches(payload):
      return
    with self._lock:
      self.breaches_heard += 1
      self._pending_rule = rule

  # ------------------------------------------------------------- ladder

  def _level_knobs(self, level: int) -> Dict[str, int]:
    """The scheduler/admission clamp values one ladder level means.
    Bounds: spec clamp in [0, k], budget clamp >= one chunk, slot cap
    in [min_slots, base cap]; level 0 releases everything."""
    chunk = self.engine.chunk
    if level <= 0:
      return {"tune_spec_k": -1, "tune_budget": 0, "tune_slot_cap": 0,
              "floor_level": 0}
    if level == 1:
      # Trim, never shut off: floored at 1 so a k=1 drafter keeps its
      # draft at the gentlest level (full spec-off is level 2's job);
      # k=0 (no drafter) keeps the clamp a no-op.
      trimmed = max(1, self._base_spec_k // 2) if self._base_spec_k \
          else 0
      return {"tune_spec_k": trimmed, "tune_budget": 0,
              "tune_slot_cap": 0, "floor_level": 0}
    knobs = {"tune_spec_k": 0,
             "tune_budget": self.budget_chunks * chunk,
             "tune_slot_cap": 0, "floor_level": 1}
    if level >= 3:
      knobs["tune_slot_cap"] = max(self.min_slots, self._base_cap // 2)
    return knobs

  def _pressure(self, step: int) -> bool:
    """Is any matching breach stream STILL breached?  (Module
    docstring: sustained overload never re-fires the transition
    event.)  While the breach is alive (records flowing —
    slo.BreachPressure) ``_last_heard_step`` refreshes, so staleness
    only accrues once a wedged stream's records stop."""
    pressured, fresh = self._probe.poll()
    if fresh:
      self._last_heard_step = step
    return pressured

  def on_step(self, step: int) -> None:
    """One host iteration boundary: escalate on a recorded breach
    event, keep climbing one level per hold window under sustained
    pressure, and release one level per clean hold window.  A few int
    compares on the healthy path."""
    with self._lock:
      rule, self._pending_rule = self._pending_rule, None
    if rule is not None:
      self._last_heard_step = step
      self._hold_from = step
      if self.level < self.max_level:
        self._actuate(self.level + 1, rule, step)
      return
    if self.level == 0 or self._hold_from is None:
      return
    pressured = self._pressure(step)   # may refresh _last_heard_step
    if step - self._hold_from < self.hold_steps:
      return
    stale = (self._last_heard_step is None
             or step - self._last_heard_step >= self.stale_steps)
    if pressured and not stale:
      # The breach never recovered: keep tightening, one level per
      # hold window (or hold at max until it clears).
      self._hold_from = step
      if self.level < self.max_level:
        self._actuate(self.level + 1, "sustained", step)
      return
    self._actuate(self.level - 1, "recovered", step)

  def _actuate(self, new_level: int, rule: str, step: int) -> None:
    old_level, self.level = self.level, new_level
    self._hold_from = step          # recovery hold restarts per move
    sched = self.engine.scheduler
    knobs = self._level_knobs(new_level)
    changes: Dict[str, Any] = {}
    for name in ("tune_spec_k", "tune_budget", "tune_slot_cap"):
      old = getattr(sched, name)
      if old != knobs[name]:
        changes[name] = [old, knobs[name]]
        setattr(sched, name, knobs[name])
    admission = getattr(self.engine, "_admission", None)
    if admission is not None and \
        admission.floor_level != knobs["floor_level"]:
      changes["floor_level"] = [admission.floor_level,
                                knobs["floor_level"]]
      admission.floor_level = knobs["floor_level"]
    self.actuations += 1
    payload = {"actuator": "autotune",
               "twin": self.engine._twin_label,
               "from_level": TUNE_LEVELS[old_level],
               "to_level": TUNE_LEVELS[new_level],
               "rule": rule, "knobs": changes}
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/actuation", cat="serving", track="serving",
          args={"actuator": "autotune", "rule": rule,
                "from_level": TUNE_LEVELS[old_level],
                "to_level": TUNE_LEVELS[new_level]})
      tracer.counter("serving/autotune_level", new_level)
    if self.monitor is not None:
      self.monitor.note_actuation("autotune", payload, step=step)
    get_logger().warning(
        "autotune: %s -> %s (rule %s, step %d, knobs %s)",
        TUNE_LEVELS[old_level], TUNE_LEVELS[new_level], rule, step,
        changes)
