"""Unified serving capability guards.

One site answers "can this config serve?" so the engine, the drafters
and any future serving component reject an unsupported composition with
the SAME actionable message — each pointing at the ROADMAP open item
that will lift the limit, instead of three slightly different inline
raises that drift apart (the PR-3 engine carried two of these inline;
speculative decoding would have added a third family).

Everything here is a pure check: no imports of the engine, no device
work, safe to call before any allocation.
"""

from __future__ import annotations

ROADMAP_PP_SERVING = (
    "pipeline-parallel serving is a ROADMAP open item ('Pipeline-parallel "
    "serving'; docs/serving.md 'Current limits')")
ROADMAP_MOE_SERVING = (
    "MoE serving (expert-parallel decode) is a ROADMAP open item "
    "('MoE serving'; docs/serving.md 'Current limits')")
ROADMAP_DRAFT_DISTILL = (
    "training a matched drafter is a ROADMAP follow-up ('draft-model "
    "distillation'; docs/serving.md 'Speculative decoding')")
ROADMAP_PREEMPTION = (
    "priority reorders ADMISSION, and on the paged engine "
    "(serving.paged.enabled) a RUNNING throughput-class slot is "
    "preempted (reason 'preempted') both on block-pool exhaustion and "
    "EAGERLY when a latency-class arrival would otherwise queue "
    "(serving/proactive_preemptions; docs/robustness.md)")

# Finish-reason glossary (docs/robustness.md "Serving resilience"):
#   length      — max_new_tokens reached
#   stop_token  — the request's stop token was generated
#   deadline    — Request.deadline_s / ttft_budget_s expired
#   cancelled   — client cancellation (scheduler/engine .cancel(uid))
#   shed        — rejected at submit by admission control (overload)
#   failed      — quarantined more than serving.resilience.max_requeues
#                 times (persistent bad steps implicating this request)
#   preempted   — paged out mid-flight because the KV block pool ran dry
#                 (paged engine; rides the requeue prefix-replay path, so
#                 unlike the others it names a REQUEUE, not a final
#                 resolution — the request finishes later under one of
#                 the reasons above with its output bit-intact)
FINISH_REASONS = ("length", "stop_token", "deadline", "cancelled",
                  "shed", "failed", "preempted")

# Admission classes: "latency" jumps the FCFS queue, "throughput" rides
# it.  (True preemption of running requests: ROADMAP_PREEMPTION.)
PRIORITIES = ("latency", "throughput")


def check_request_fields(req) -> None:
  """Validate a Request's lifecycle-control fields at submit time, so a
  typo'd priority class or negative deadline fails loudly instead of
  silently never expiring."""
  if req.priority not in PRIORITIES:
    raise ValueError(
        f"request priority must be one of {PRIORITIES}; got "
        f"{req.priority!r} — {ROADMAP_PREEMPTION}")
  if req.deadline_s < 0:
    raise ValueError(f"deadline_s must be >= 0 (0 = none): "
                     f"{req.deadline_s}")
  if req.ttft_budget_s < 0:
    raise ValueError(f"ttft_budget_s must be >= 0 (0 = none): "
                     f"{req.ttft_budget_s}")
  if (req.deadline_s > 0 and req.ttft_budget_s > 0
      and req.ttft_budget_s > req.deadline_s):
    raise ValueError(
        f"ttft_budget_s {req.ttft_budget_s} exceeds deadline_s "
        f"{req.deadline_s}: the first token can never beat a budget "
        f"that outlives the whole request")


def check_servable(cfg, role: str = "the serving engine") -> None:
  """Reject model configs the serving stack cannot run.

  ``cfg`` is a :class:`models.gpt.GPTConfig` (or anything exposing
  ``pipeline_stages`` / ``num_experts``); ``role`` names the component
  doing the rejecting so a draft-model failure reads differently from a
  target-model one.
  """
  if cfg.pipeline_stages > 1:
    raise ValueError(
        f"{role} is single-program (pipeline_stages=1) but got "
        f"pipeline_stages={cfg.pipeline_stages}; restore the checkpoint "
        f"into a non-pipelined config (runtime.saver.restore_params) — "
        f"{ROADMAP_PP_SERVING}")
  if cfg.num_experts > 0:
    raise ValueError(
        f"{role} does not support MoE checkpoints yet "
        f"(num_experts={cfg.num_experts}); restore a dense checkpoint — "
        f"{ROADMAP_MOE_SERVING}")


def check_draft_compatible(target_cfg, draft_cfg) -> None:
  """Reject draft models whose shapes cannot verify against the target.

  The verify step compares token ids, so the two models must share one
  vocabulary; the draft slot cache must cover every committed position a
  request can reach, so the draft ``max_seq_len`` must be at least the
  target's.  Everything else (depth, width, heads) is free to differ —
  that asymmetry is the whole point of a drafter.
  """
  check_servable(draft_cfg, role="a draft model")
  if draft_cfg.vocab_size != target_cfg.vocab_size:
    raise ValueError(
        f"draft model vocab_size {draft_cfg.vocab_size} != target "
        f"vocab_size {target_cfg.vocab_size}: speculative verification "
        f"compares token ids under one vocabulary; use a drafter trained "
        f"on the target tokenizer — {ROADMAP_DRAFT_DISTILL}")
  if draft_cfg.max_seq_len < target_cfg.max_seq_len:
    raise ValueError(
        f"draft model max_seq_len {draft_cfg.max_seq_len} < target "
        f"max_seq_len {target_cfg.max_seq_len}: the draft slot cache "
        f"must cover every position a request can commit (requests are "
        f"admitted against the target's max_seq_len); pad the draft "
        f"config's max_seq_len up to the target's")


def check_draft_fits_chunk(k: int, chunk: int) -> None:
  """The fused step carries each decode slot's last committed token plus
  its ``k`` drafts in one ``chunk``-wide block; reject a drafter the
  step could never schedule."""
  if k < 1:
    raise ValueError(f"speculative draft length k must be >= 1; got {k}")
  if k + 1 > chunk:
    raise ValueError(
        f"speculative draft length k={k} needs prefill_chunk >= k + 1 "
        f"(one chunk holds the last committed token plus the drafts); "
        f"got prefill_chunk {chunk} — raise serving.prefill_chunk or "
        f"lower serving.speculative.k")
