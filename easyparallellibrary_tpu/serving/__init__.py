"""Serving subsystem — continuous-batching inference over the TP mesh.

The fourth runtime mode (train / eval / generate / serve): a slot-based
preallocated KV cache (:mod:`kv_cache`), a host-side FCFS scheduler with
chunked-prefill admission (:mod:`scheduler`), a single-jitted-step
engine that fuses prefill and decode so requests join and leave the
batch every iteration (:mod:`engine`), speculative decoding — drafters
plus batched verification with per-slot accept/rollback riding that
same step (:mod:`speculative`) — and a resilience layer: admission
control with overload shedding, per-request deadlines/cancellation,
and bad-step retry/quarantine (:mod:`resilience`) — plus a replicated
control plane: N engine replicas (:mod:`replica`) behind a
health-checked :class:`Router` with bit-exact failover, graceful
drain/rejoin and prefix-affinity dispatch (:mod:`router`) — and
fleet-wide copy-on-write prefix caching: a content-addressed radix tree
over prompt blocks that maps shared KV by reference at admission and
persists session prefixes across requests (:mod:`prefix_cache`) — and
zero-downtime blue/green checkpoint rollout with an SLO-watched canary
and automatic rollback (:mod:`rollout`).  See docs/serving.md and
docs/robustness.md.
"""

from easyparallellibrary_tpu.serving._capabilities import (
    FINISH_REASONS, PRIORITIES, check_draft_compatible, check_servable,
)
from easyparallellibrary_tpu.serving.engine import (
    ContinuousBatchingEngine, filtered_logits, sample_token_slots,
)
from easyparallellibrary_tpu.serving.resilience import (
    DEGRADE_LEVELS, HEALTH_STATES, AdmissionController, BadStepPolicy,
    ReplicaHealth,
)
from easyparallellibrary_tpu.serving.autoscale import FleetAutoscaler
from easyparallellibrary_tpu.serving.autotune import (
    TUNE_LEVELS, EngineAutotuner,
)
from easyparallellibrary_tpu.serving.replica import EngineReplica
from easyparallellibrary_tpu.serving.rollout import RolloutController
from easyparallellibrary_tpu.serving.router import Router
from easyparallellibrary_tpu.serving.transport import (
    InprocTransport, ProcessTransport, RemoteError, ReplicaDeadError,
    ReplicaTransport, TransportError, TransportTimeout,
)
from easyparallellibrary_tpu.serving.kv_cache import (
    NULL_BLOCK, BlockAllocator, SlotAllocator, allocate_kv_cache,
    allocate_paged_kv_cache, blocks_per_slot, cache_bytes, cache_length,
    default_num_blocks, kv_cache_shardings, paged_cache_bytes,
)
from easyparallellibrary_tpu.serving.prefix_cache import (
    PrefixCache, block_prefix_keys,
)
from easyparallellibrary_tpu.serving.scheduler import (
    FCFSScheduler, FinishedRequest, PagedStepPlan, Request, StepPlan,
)
from easyparallellibrary_tpu.serving.speculative import (
    Drafter, DraftModelDrafter, NgramDrafter, ngram_propose,
    verify_tokens,
)

__all__ = [
    "ContinuousBatchingEngine", "filtered_logits", "sample_token_slots",
    "SlotAllocator", "allocate_kv_cache", "cache_bytes", "cache_length",
    "kv_cache_shardings",
    "NULL_BLOCK", "BlockAllocator", "allocate_paged_kv_cache",
    "blocks_per_slot", "default_num_blocks", "paged_cache_bytes",
    "FCFSScheduler", "FinishedRequest", "PagedStepPlan", "Request",
    "StepPlan",
    "PrefixCache", "block_prefix_keys",
    "check_draft_compatible", "check_servable",
    "AdmissionController", "BadStepPolicy", "DEGRADE_LEVELS",
    "FINISH_REASONS", "PRIORITIES",
    "EngineReplica", "HEALTH_STATES", "ReplicaHealth", "Router",
    "EngineAutotuner", "FleetAutoscaler", "RolloutController",
    "TUNE_LEVELS",
    "InprocTransport", "ProcessTransport", "RemoteError", "ReplicaDeadError",
    "ReplicaTransport", "TransportError", "TransportTimeout",
    "Drafter", "DraftModelDrafter", "NgramDrafter", "ngram_propose",
    "verify_tokens",
]
