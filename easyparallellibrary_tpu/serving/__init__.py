"""Serving subsystem — continuous-batching inference over the TP mesh.

The fourth runtime mode (train / eval / generate / serve): a slot-based
preallocated KV cache (:mod:`kv_cache`), a host-side FCFS scheduler with
chunked-prefill admission (:mod:`scheduler`), and a single-jitted-step
engine that fuses prefill and decode so requests join and leave the
batch every iteration (:mod:`engine`).  See docs/serving.md.
"""

from easyparallellibrary_tpu.serving.engine import (
    ContinuousBatchingEngine, sample_token_slots,
)
from easyparallellibrary_tpu.serving.kv_cache import (
    SlotAllocator, allocate_kv_cache, cache_bytes, cache_length,
    kv_cache_shardings,
)
from easyparallellibrary_tpu.serving.scheduler import (
    FCFSScheduler, FinishedRequest, Request, StepPlan,
)

__all__ = [
    "ContinuousBatchingEngine", "sample_token_slots",
    "SlotAllocator", "allocate_kv_cache", "cache_bytes", "cache_length",
    "kv_cache_shardings",
    "FCFSScheduler", "FinishedRequest", "Request", "StepPlan",
]
