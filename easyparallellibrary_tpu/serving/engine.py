"""Continuous-batching inference engine over the TP mesh.

The fourth runtime mode (train / eval / generate / **serve**): ONE
jitted step — compiled once, shapes never change — fuses

  * prefill of newly admitted requests (their next prompt chunk), and
  * one-token decode of every other active slot

into a single ``[num_slots, chunk]`` model call against the slot KV
cache (kv_cache.py), per-slot cursors selecting each slot's absolute
positions and causal window (models/gpt.py ``slot_cache_attend``).
Requests therefore join and leave the batch every iteration with zero
recompilation — iteration-level batching as in Orca (OSDI'22) — and the
cache + cursor buffers are donated, so the engine's steady-state device
allocation is exactly one cache.

Division of labor: :class:`FCFSScheduler` (scheduler.py) owns all
host-side variability (admission, budgets, retirement, RNG streams);
this module owns the device program and its placement.  Sampling runs
per-slot inside the step (:func:`sample_token_slots` — the traced-
parameter twin of ``sample_logits``) with per-request keys folded by
token index, so a request's sample stream is independent of which slot
or iteration serves it.

Speculative decoding (serving/speculative/) rides the same fused step:
a drafter fills each decode slot's unused chunk positions with ``k``
guessed tokens, the one model call scores all of them (verification is
a prefill-shaped call — nearly free in this step), and in-jit per-slot
accept/rollback commits the accepted prefix plus one correction/bonus
token, rolling cursors back to the last accepted position.  Toggled by
``serving.speculative.*`` / per-request ``Request.speculative``.

Exactness contract: greedy engine output is bit-identical (token ids)
to ``generate(use_cache=True)`` per request — the legacy path stays the
oracle (tests/test_serving.py), including requests admitted mid-flight
and slots reused after retirement.  Greedy SPECULATIVE output keeps the
same contract (exact-match acceptance); sampled speculative output
keeps the sampling distribution, not the bitstream
(tests/test_serving_speculative.py).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.serving import kv_cache as kv_lib
from easyparallellibrary_tpu.serving._capabilities import (
    check_draft_fits_chunk, check_servable)
from easyparallellibrary_tpu.serving.scheduler import (
    FCFSScheduler, FinishedRequest, Request, _slot_track)
from easyparallellibrary_tpu.utils.logging import get_logger


def filtered_logits(logits, temperature, top_k, top_p):
  """Per-row temperature/top-k/top-p filtering with TRACED parameters —
  the distribution half of :func:`sample_token_slots` (same filter
  semantics and order as ``models.gpt.sample_logits``: top-k, then top-p
  over the survivors), shared with speculative verification
  (serving/speculative/verify.py), whose acceptance rule must judge
  drafts against EXACTLY the distribution sampling would draw from.

  ``logits`` [M, V]; ``temperature``/``top_p`` f32 [M]; ``top_k`` int32
  [M] (0 disables).  Returns the scaled, filtered logits [M, V]
  (filtered entries at -1e30); their softmax is the sampling
  distribution at ``temperature > 0``.
  """
  V = logits.shape[-1]
  neg = jnp.asarray(-1e30, logits.dtype)
  t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
  scaled = logits / t.astype(logits.dtype)
  # top-k with a traced k: threshold at the k-th largest value (ties at
  # the threshold survive, exactly like sample_logits' `logits < kth`).
  sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
  kth = jnp.take_along_axis(
      sorted_desc, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=-1)
  k_off = (top_k[:, None] <= 0) | (top_k[:, None] >= V)
  scaled = jnp.where((scaled >= kth) | k_off, scaled, neg)
  # top-p over the survivors: keep entries whose PRECEDING mass is < p
  # (the crossing token survives; the top token always survives).
  sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
  probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
  cum = jnp.cumsum(probs, axis=-1)
  keep_sorted = (cum - probs) < top_p[:, None]
  thresh = jnp.min(jnp.where(keep_sorted, sorted_desc,
                             jnp.asarray(jnp.inf, scaled.dtype)),
                   axis=-1, keepdims=True)
  p_on = top_p[:, None] < 1.0
  return jnp.where(p_on & (scaled < thresh), neg, scaled)


def sample_token_slots(logits, keys, temperature, top_k, top_p):
  """Per-slot sampling with TRACED parameters — the vectorized twin of
  ``models.gpt.sample_logits``, for the serving step where every slot
  carries its own sampling knobs and every value must be an array
  (static per-request values would recompile the fused step per
  parameter combination).  ``temperature<=0`` is greedy.

  ``logits`` [N, V]; ``keys`` uint32 [N, 2] per-slot PRNG keys;
  ``temperature``/``top_p`` f32 [N]; ``top_k`` int32 [N] (0 disables).
  Returns int32 [N] token ids.
  """
  greedy = jnp.argmax(logits, axis=-1)
  scaled = filtered_logits(logits, temperature, top_k, top_p)
  sampled = jax.vmap(jax.random.categorical)(keys, scaled)
  return jnp.where(temperature <= 0, greedy, sampled).astype(jnp.int32)


class ContinuousBatchingEngine:
  """Slot-based continuous-batching decode engine for a (non-pipelined)
  GPT.

  ``params`` may be boxed (flax Partitioned) or plain; with ``mesh``
  they should already live in their sharded layout (e.g. from
  ``create_sharded_train_state`` or ``runtime.saver.restore_params``)
  and the cache is allocated heads-over-TP on the same mesh.  All knobs
  default from the active ``Config``'s ``serving.*`` group.

  Typical drive::

      eng = ContinuousBatchingEngine(model, params, mesh=mesh)
      eng.submit(Request(uid="a", prompt=ids, max_new_tokens=32))
      outputs = eng.run()          # {uid: prompt+generated np.int32}
  """

  def __init__(self, model, params, *, mesh=None,
               num_slots: Optional[int] = None,
               prefill_chunk: Optional[int] = None,
               prefill_token_budget: Optional[int] = None,
               max_batch: Optional[int] = None,
               stop_token: Optional[int] = None,
               donate_cache: Optional[bool] = None,
               drafter=None, speculative: Optional[bool] = None,
               draft_model=None, draft_params=None,
               stats=None, metrics_writer=None, registry=None,
               config=None):
    cfg = model.cfg
    root_config = config if config is not None else Env.get().config
    conf = root_config.serving
    # Reconcile the ambient tracer with observability.* so a config-
    # enabled run traces serving without any wiring at the call site.
    trace_lib.ensure_configured(root_config)
    check_servable(cfg)
    self.model = model
    self.params = params
    self.mesh = mesh
    self.num_slots = num_slots if num_slots is not None else conf.num_slots
    self.chunk = (prefill_chunk if prefill_chunk is not None
                  else conf.prefill_chunk)
    if self.chunk > cfg.max_seq_len:
      raise ValueError(f"prefill_chunk {self.chunk} exceeds max_seq_len "
                       f"{cfg.max_seq_len}")
    budget = (prefill_token_budget if prefill_token_budget is not None
              else conf.prefill_token_budget)
    if budget > 0 and budget < self.chunk:
      raise ValueError(
          f"prefill_token_budget {budget} below prefill_chunk "
          f"{self.chunk}: no admission could ever afford its first chunk")
    self.drafter = self._resolve_drafter(conf, drafter, speculative,
                                         draft_model, draft_params)
    self.scheduler = FCFSScheduler(
        num_slots=self.num_slots, prefill_chunk=self.chunk,
        max_seq_len=cfg.max_seq_len, prefill_token_budget=budget,
        max_batch=max_batch if max_batch is not None else conf.max_batch,
        stop_token=stop_token if stop_token is not None
        else conf.stop_token,
        spec_k=self.drafter.k if self.drafter is not None else 0)
    self.stats = stats
    self.metrics_writer = metrics_writer
    # Optional MetricRegistry (observability/registry.py): per-step
    # records publish under serving/* through the one metric schema.
    self.registry = registry
    if stats is not None:
      self.scheduler.on_admit = stats.note_admitted
      self.scheduler.on_first_token = stats.note_first_token
      self.scheduler.on_finish = lambda fin: stats.note_finished(
          fin.uid, fin.new_tokens)
    self._kv, self._cursors = kv_lib.allocate_kv_cache(
        cfg, self.num_slots, self.chunk, mesh)
    # Perfetto track name per slot (the scheduler's lifecycle spans and
    # the engine's per-step spans must land on the same track);
    # precomputed so the per-step tracing loop does no string work.
    self._slot_tracks = [_slot_track(i) for i in range(self.num_slots)]
    self._steps = 0
    donate = conf.donate_cache if donate_cache is None else donate_cache
    if self.drafter is not None:
      self.drafter.bind(self)
      self._step_fn = self._build_spec_step(donate)
    else:
      self._step_fn = self._build_step(donate)
    get_logger().info(
        "serving engine: %d slots x chunk %d (cache %.1f MB, %s), "
        "prefill budget %s, max batch %d, speculation %s",
        self.num_slots, self.chunk,
        kv_lib.cache_bytes(cfg, self.num_slots, self.chunk) / 1e6,
        "mesh-sharded" if mesh is not None else "single-program",
        budget or "uncapped", self.scheduler.max_batch,
        f"{type(self.drafter).__name__}(k={self.drafter.k})"
        if self.drafter is not None else "off")

  def _resolve_drafter(self, conf, drafter, speculative, draft_model,
                       draft_params):
    """``speculative=False`` wins over everything (an explicit opt-out
    must be trustworthy even when a drafter object was constructed);
    otherwise an explicit ``drafter`` wins, and ``serving.speculative.*``
    decides the rest (``speculative=True`` overrides its ``enabled``).
    Any resolved drafter must fit the fused step's chunk
    (k + 1 <= prefill_chunk)."""
    from easyparallellibrary_tpu.serving.speculative import (
        DraftModelDrafter, NgramDrafter)
    if speculative is False:
      return None
    spec = conf.speculative
    if drafter is None and (spec.enabled or speculative):
      if spec.kind == "ngram":
        drafter = NgramDrafter(k=spec.k, ngram_max=spec.ngram_max,
                               ngram_min=spec.ngram_min)
      else:  # "draft_model" (config validation rejects anything else)
        if draft_model is None or draft_params is None:
          raise ValueError(
              "serving.speculative.kind='draft_model' needs the drafter's "
              "weights: pass draft_model=/draft_params= (e.g. via "
              "DraftModelDrafter.from_checkpoint) or a drafter= instance")
        drafter = DraftModelDrafter(draft_model, draft_params, k=spec.k)
    if drafter is not None:
      check_draft_fits_chunk(drafter.k, self.chunk)
    return drafter

  # ----------------------------------------------------------- device step

  def _jit_step(self, step, donate: bool, n_rep_in: int, n_rep_out: int):
    """jit a fused step with the engine's donation/placement discipline:
    cache + cursors donated (argnums 1, 2), everything after them
    replicated when a mesh is attached."""
    jit_kwargs: Dict[str, Any] = {}
    if donate:
      jit_kwargs["donate_argnums"] = (1, 2)   # cache + cursors
    if self.mesh is not None:
      from easyparallellibrary_tpu.parallel.api import state_shardings
      kv_sh, cur_sh = kv_lib.kv_cache_shardings(self.model.cfg, self.mesh)
      param_sh = state_shardings(self.params, self.mesh)
      rep = cur_sh
      jit_kwargs["in_shardings"] = (
          (param_sh, kv_sh, cur_sh) + (rep,) * n_rep_in)
      jit_kwargs["out_shardings"] = (rep,) * n_rep_out + (kv_sh, cur_sh)
    return jax.jit(step, **jit_kwargs)

  def _build_step(self, donate: bool):
    from easyparallellibrary_tpu.models.gpt import slot_step_logits
    model = self.model
    C = self.chunk

    def step(params, kv, cursors, tokens, num_valid, reset, keys,
             tok_index, temperature, top_k, top_p):
      cursors = jnp.where(reset, 0, cursors)
      logits, kv = slot_step_logits(model, params, kv, tokens, cursors)
      # Each slot's next-token logits sit at its LAST live chunk
      # position; idle slots (num_valid=0) read position 0 — garbage the
      # scheduler never consumes.
      last = jnp.take_along_axis(
          logits, jnp.clip(num_valid - 1, 0, C - 1)[:, None, None],
          axis=1)[:, 0]
      step_keys = jax.vmap(jax.random.fold_in)(keys, tok_index)
      nxt = sample_token_slots(last.astype(jnp.float32), step_keys,
                               temperature, top_k, top_p)
      return nxt, kv, cursors + num_valid

    return self._jit_step(step, donate, n_rep_in=8, n_rep_out=1)

  def _build_spec_step(self, donate: bool):
    """The speculative twin of :meth:`_build_step`: the SAME single
    model call (drafts ride the chunk positions plain decode wastes, so
    verification adds no model compute), followed by in-jit per-slot
    accept/rollback (serving/speculative/verify.py).  Shapes are static
    in ``k_max = drafter.k``; per-slot draft length is data
    (``num_draft``), so joins/leaves/short proposals never recompile.
    """
    from easyparallellibrary_tpu.models.gpt import slot_step_logits
    from easyparallellibrary_tpu.serving.speculative.verify import (
        verify_tokens)
    model = self.model
    C = self.chunk
    K = self.drafter.k

    def step(params, kv, cursors, tokens, num_valid, num_draft, reset,
             keys, tok_index, temperature, top_k, top_p):
      cursors = jnp.where(reset, 0, cursors)
      logits, kv = slot_step_logits(model, params, kv, tokens, cursors)
      # base = non-draft tokens fed (prefill grant, or 1 for decode);
      # position base-1+j's logits are the target distribution for
      # draft j, and base-1+num_draft's feed the bonus token.  With
      # num_draft=0 row 0 is exactly the legacy step's `last` gather.
      base = num_valid - num_draft
      pos = jnp.clip(base[:, None] - 1 + jnp.arange(K + 1)[None],
                     0, C - 1)
      tgt = jnp.take_along_axis(
          logits, pos[:, :, None], axis=1).astype(jnp.float32)
      dpos = jnp.clip(base[:, None] + jnp.arange(K)[None], 0, C - 1)
      drafts = jnp.take_along_axis(tokens, dpos, axis=1)
      committed, n_committed, accepted = verify_tokens(
          tgt, drafts, num_draft, keys, tok_index, temperature, top_k,
          top_p)
      # Rollback is pure cursor math: the cache keeps K/V for the fed
      # non-draft tokens plus the accepted prefix; rejected-draft K/V
      # beyond the new cursor is masked and later overwritten, exactly
      # like chunked-prefill garbage.
      return committed, n_committed, kv, cursors + base + accepted

    return self._jit_step(step, donate, n_rep_in=9, n_rep_out=2)

  # ------------------------------------------------------------ host loop

  def submit(self, request: Request):
    if self.stats is not None:
      self.stats.note_submitted(request.uid)
    self.scheduler.submit(request)

  @property
  def has_work(self) -> bool:
    return self.scheduler.has_work

  def _trace_slot_spans(self, tracer, plan, t0_us: float, t1_us: float,
                        num_draft=None, n_committed=None):
    """Per-slot timeline spans for one fused step: the single device
    call covers every active slot, so each slot's prefill / decode /
    speculate span shares its bounds and nests inside the request
    lifecycle span opened at admission (scheduler._admit).  Speculating
    slots carry drafted/accepted counts in their span args.  Host
    values only — never called with device arrays."""
    if not tracer.enabled:
      return
    for slot in np.nonzero(plan.num_valid)[0]:
      slot = int(slot)
      track = self._slot_tracks[slot]
      if plan.prefilling[slot]:
        tracer.span_at("prefill", t0_us, t1_us, cat="serving",
                       track=track,
                       args={"tokens": int(plan.num_valid[slot])})
      elif num_draft is not None and int(num_draft[slot]) > 0:
        tracer.span_at(
            "speculate", t0_us, t1_us, cat="serving", track=track,
            args={"drafted": int(num_draft[slot]),
                  "accepted": int(n_committed[slot]) - 1})
      else:
        tracer.span_at("decode", t0_us, t1_us, cat="serving",
                       track=track,
                       args={"tok_index": int(plan.tok_index[slot])})

  def step(self) -> List[FinishedRequest]:
    """One engine iteration: plan -> [draft ->] fused device step ->
    commit.  Returns the requests that retired this iteration (empty
    when idle)."""
    tracer = trace_lib.get_tracer()
    with tracer.span("serving/plan", cat="serving", track="serving"):
      plan = self.scheduler.plan_step()
    if plan is None:
      return []
    t0 = time.monotonic()
    drafted = accepted = 0
    if self.drafter is not None:
      # Propose BEFORE the token block gains drafts: the draft model's
      # mirror call needs the same plan the target sees.
      with tracer.span("serving/draft", cat="serving", track="serving"):
        histories = self.scheduler.slot_histories(plan)
        draft_tokens, num_draft = self.drafter.propose(plan, histories)
        num_draft = np.minimum(
            np.asarray(num_draft, np.int32), plan.draft_cap)
        for slot in np.nonzero(num_draft)[0]:
          nd = int(num_draft[slot])
          plan.tokens[slot, 1:1 + nd] = draft_tokens[slot, :nd]
      t0_us = tracer.now_us()
      committed, n_committed, self._kv, self._cursors = self._step_fn(
          self.params, self._kv, self._cursors, plan.tokens,
          plan.num_valid + num_draft, num_draft, plan.reset, plan.keys,
          plan.tok_index, plan.temperature, plan.top_k, plan.top_p)
      committed = np.asarray(committed)
      n_committed = np.asarray(n_committed)
      t1_us = tracer.now_us()
      tracer.span_at("serving/device_step", t0_us, t1_us, cat="serving",
                     track="serving")
      self._trace_slot_spans(tracer, plan, t0_us, t1_us,
                             num_draft, n_committed)
      with tracer.span("serving/commit", cat="serving", track="serving"):
        finished = self.scheduler.commit(committed, n_committed)
        self.drafter.observe_commit(self._cursors)
      speculated = num_draft > 0
      drafted = int(num_draft.sum())
      accepted = int((n_committed[speculated] - 1).sum())
    else:
      t0_us = tracer.now_us()
      nxt, self._kv, self._cursors = self._step_fn(
          self.params, self._kv, self._cursors, plan.tokens,
          plan.num_valid, plan.reset, plan.keys, plan.tok_index,
          plan.temperature, plan.top_k, plan.top_p)
      nxt = np.asarray(nxt)
      t1_us = tracer.now_us()
      tracer.span_at("serving/device_step", t0_us, t1_us, cat="serving",
                     track="serving")
      self._trace_slot_spans(tracer, plan, t0_us, t1_us)
      with tracer.span("serving/commit", cat="serving", track="serving"):
        finished = self.scheduler.commit(nxt)
    self._steps += 1
    dt = time.monotonic() - t0
    if tracer.enabled:
      tracer.counter("serving/active_slots", plan.active_slots)
      if drafted:
        tracer.counter("serving/drafted_tokens", drafted)
        tracer.counter("serving/accepted_tokens", accepted)
    if self.stats is not None:
      self.stats.note_step(
          active_slots=plan.active_slots, num_slots=self.num_slots,
          prefill_tokens=plan.prefill_tokens,
          decode_tokens=plan.decode_tokens, step_time_s=dt,
          drafted_tokens=drafted, accepted_tokens=accepted)
    if self.metrics_writer is not None or self.registry is not None:
      record = {
          "active_slots": plan.active_slots,
          "slot_occupancy": plan.active_slots / self.num_slots,
          "prefill_tokens": plan.prefill_tokens,
          "decode_tokens": plan.decode_tokens,
          "step_time_s": dt,
      }
      if self.drafter is not None:
        record["drafted_tokens"] = drafted
        record["accepted_tokens"] = accepted
      if self.metrics_writer is not None:
        # Legacy flat keys (pre-registry callers depend on them).
        self.metrics_writer.write(self._steps, record)
      if self.registry is not None:
        self.registry.publish(self._steps, record, "serving")
    return finished

  def run(self, max_steps: Optional[int] = None
          ) -> Dict[Any, np.ndarray]:
    """Drive until the queue drains (or ``max_steps``); returns
    ``{uid: prompt+generated}`` for every request finished during the
    call."""
    out: Dict[Any, np.ndarray] = {}
    steps = 0
    while self.has_work and (max_steps is None or steps < max_steps):
      for fin in self.step():
        out[fin.uid] = fin.tokens
      steps += 1
    if self.registry is not None and self.stats is not None:
      # End-of-drive rollup (tokens/s, TTFT/ITL percentiles, occupancy,
      # speculation counters) under the serving/* namespace.
      self.stats.publish(self.registry, self._steps)
    return out
