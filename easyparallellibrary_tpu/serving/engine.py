"""Continuous-batching inference engine over the TP mesh.

The fourth runtime mode (train / eval / generate / **serve**): ONE
jitted step — compiled once, shapes never change — fuses

  * prefill of newly admitted requests (their next prompt chunk), and
  * one-token decode of every other active slot

into a single ``[num_slots, chunk]`` model call against the slot KV
cache (kv_cache.py), per-slot cursors selecting each slot's absolute
positions and causal window (models/gpt.py ``slot_cache_attend``).
Requests therefore join and leave the batch every iteration with zero
recompilation — iteration-level batching as in Orca (OSDI'22) — and the
cache + cursor buffers are donated, so the engine's steady-state device
allocation is exactly one cache.

Division of labor: :class:`FCFSScheduler` (scheduler.py) owns all
host-side variability (admission, budgets, retirement, RNG streams);
this module owns the device program and its placement.  Sampling runs
per-slot inside the step (:func:`sample_token_slots` — the traced-
parameter twin of ``sample_logits``) with per-request keys folded by
token index, so a request's sample stream is independent of which slot
or iteration serves it.

Exactness contract: greedy engine output is bit-identical (token ids)
to ``generate(use_cache=True)`` per request — the legacy path stays the
oracle (tests/test_serving.py), including requests admitted mid-flight
and slots reused after retirement.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.serving import kv_cache as kv_lib
from easyparallellibrary_tpu.serving.scheduler import (
    FCFSScheduler, FinishedRequest, Request)
from easyparallellibrary_tpu.utils.logging import get_logger


def sample_token_slots(logits, keys, temperature, top_k, top_p):
  """Per-slot sampling with TRACED parameters — the vectorized twin of
  ``models.gpt.sample_logits`` (same filter semantics and order: top-k,
  then top-p over the survivors; ``temperature<=0`` is greedy), for the
  serving step where every slot carries its own sampling knobs and every
  value must be an array (static per-request values would recompile the
  fused step per parameter combination).

  ``logits`` [N, V]; ``keys`` uint32 [N, 2] per-slot PRNG keys;
  ``temperature``/``top_p`` f32 [N]; ``top_k`` int32 [N] (0 disables).
  Returns int32 [N] token ids.
  """
  V = logits.shape[-1]
  greedy = jnp.argmax(logits, axis=-1)
  neg = jnp.asarray(-1e30, logits.dtype)
  t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
  scaled = logits / t.astype(logits.dtype)
  # top-k with a traced k: threshold at the k-th largest value (ties at
  # the threshold survive, exactly like sample_logits' `logits < kth`).
  sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
  kth = jnp.take_along_axis(
      sorted_desc, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=-1)
  k_off = (top_k[:, None] <= 0) | (top_k[:, None] >= V)
  scaled = jnp.where((scaled >= kth) | k_off, scaled, neg)
  # top-p over the survivors: keep entries whose PRECEDING mass is < p
  # (the crossing token survives; the top token always survives).
  sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
  probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
  cum = jnp.cumsum(probs, axis=-1)
  keep_sorted = (cum - probs) < top_p[:, None]
  thresh = jnp.min(jnp.where(keep_sorted, sorted_desc,
                             jnp.asarray(jnp.inf, scaled.dtype)),
                   axis=-1, keepdims=True)
  p_on = top_p[:, None] < 1.0
  scaled = jnp.where(p_on & (scaled < thresh), neg, scaled)
  sampled = jax.vmap(jax.random.categorical)(keys, scaled)
  return jnp.where(temperature <= 0, greedy, sampled).astype(jnp.int32)


class ContinuousBatchingEngine:
  """Slot-based continuous-batching decode engine for a (non-pipelined)
  GPT.

  ``params`` may be boxed (flax Partitioned) or plain; with ``mesh``
  they should already live in their sharded layout (e.g. from
  ``create_sharded_train_state`` or ``runtime.saver.restore_params``)
  and the cache is allocated heads-over-TP on the same mesh.  All knobs
  default from the active ``Config``'s ``serving.*`` group.

  Typical drive::

      eng = ContinuousBatchingEngine(model, params, mesh=mesh)
      eng.submit(Request(uid="a", prompt=ids, max_new_tokens=32))
      outputs = eng.run()          # {uid: prompt+generated np.int32}
  """

  def __init__(self, model, params, *, mesh=None,
               num_slots: Optional[int] = None,
               prefill_chunk: Optional[int] = None,
               prefill_token_budget: Optional[int] = None,
               max_batch: Optional[int] = None,
               stop_token: Optional[int] = None,
               donate_cache: Optional[bool] = None,
               stats=None, metrics_writer=None,
               config=None):
    cfg = model.cfg
    conf = (config if config is not None else Env.get().config).serving
    if cfg.pipeline_stages > 1:
      raise ValueError(
          "the serving engine is single-program (pipeline_stages=1); "
          "restore the checkpoint into a non-pipelined config "
          "(runtime.saver.restore_params) — see docs/serving.md")
    if cfg.num_experts > 0:
      raise ValueError("serving MoE checkpoints is not supported yet "
                       "(ROADMAP open item)")
    self.model = model
    self.params = params
    self.mesh = mesh
    self.num_slots = num_slots if num_slots is not None else conf.num_slots
    self.chunk = (prefill_chunk if prefill_chunk is not None
                  else conf.prefill_chunk)
    if self.chunk > cfg.max_seq_len:
      raise ValueError(f"prefill_chunk {self.chunk} exceeds max_seq_len "
                       f"{cfg.max_seq_len}")
    budget = (prefill_token_budget if prefill_token_budget is not None
              else conf.prefill_token_budget)
    if budget > 0 and budget < self.chunk:
      raise ValueError(
          f"prefill_token_budget {budget} below prefill_chunk "
          f"{self.chunk}: no admission could ever afford its first chunk")
    self.scheduler = FCFSScheduler(
        num_slots=self.num_slots, prefill_chunk=self.chunk,
        max_seq_len=cfg.max_seq_len, prefill_token_budget=budget,
        max_batch=max_batch if max_batch is not None else conf.max_batch,
        stop_token=stop_token if stop_token is not None
        else conf.stop_token)
    self.stats = stats
    self.metrics_writer = metrics_writer
    if stats is not None:
      self.scheduler.on_admit = stats.note_admitted
      self.scheduler.on_first_token = stats.note_first_token
      self.scheduler.on_finish = lambda fin: stats.note_finished(
          fin.uid, fin.new_tokens)
    self._kv, self._cursors = kv_lib.allocate_kv_cache(
        cfg, self.num_slots, self.chunk, mesh)
    self._steps = 0
    donate = conf.donate_cache if donate_cache is None else donate_cache
    self._step_fn = self._build_step(donate)
    get_logger().info(
        "serving engine: %d slots x chunk %d (cache %.1f MB, %s), "
        "prefill budget %s, max batch %d", self.num_slots, self.chunk,
        kv_lib.cache_bytes(cfg, self.num_slots, self.chunk) / 1e6,
        "mesh-sharded" if mesh is not None else "single-program",
        budget or "uncapped", self.scheduler.max_batch)

  # ----------------------------------------------------------- device step

  def _build_step(self, donate: bool):
    model = self.model
    C = self.chunk

    def step(params, kv, cursors, tokens, num_valid, reset, keys,
             tok_index, temperature, top_k, top_p):
      cursors = jnp.where(reset, 0, cursors)
      logits, mut = model.apply(
          {"params": params, "cache": kv}, tokens, decode=True,
          slot_cursors=cursors, mutable=["cache"])
      # Each slot's next-token logits sit at its LAST live chunk
      # position; idle slots (num_valid=0) read position 0 — garbage the
      # scheduler never consumes.
      last = jnp.take_along_axis(
          logits, jnp.clip(num_valid - 1, 0, C - 1)[:, None, None],
          axis=1)[:, 0]
      step_keys = jax.vmap(jax.random.fold_in)(keys, tok_index)
      nxt = sample_token_slots(last.astype(jnp.float32), step_keys,
                               temperature, top_k, top_p)
      return nxt, mut["cache"], cursors + num_valid

    jit_kwargs: Dict[str, Any] = {}
    if donate:
      jit_kwargs["donate_argnums"] = (1, 2)   # cache + cursors
    if self.mesh is not None:
      from easyparallellibrary_tpu.parallel.api import state_shardings
      kv_sh, cur_sh = kv_lib.kv_cache_shardings(model.cfg, self.mesh)
      param_sh = state_shardings(self.params, self.mesh)
      rep = cur_sh
      jit_kwargs["in_shardings"] = (
          param_sh, kv_sh, cur_sh, rep, rep, rep, rep, rep, rep, rep, rep)
      jit_kwargs["out_shardings"] = (rep, kv_sh, cur_sh)
    return jax.jit(step, **jit_kwargs)

  # ------------------------------------------------------------ host loop

  def submit(self, request: Request):
    if self.stats is not None:
      self.stats.note_submitted(request.uid)
    self.scheduler.submit(request)

  @property
  def has_work(self) -> bool:
    return self.scheduler.has_work

  def step(self) -> List[FinishedRequest]:
    """One engine iteration: plan -> fused device step -> commit.
    Returns the requests that retired this iteration (empty when idle)."""
    plan = self.scheduler.plan_step()
    if plan is None:
      return []
    t0 = time.monotonic()
    nxt, self._kv, self._cursors = self._step_fn(
        self.params, self._kv, self._cursors, plan.tokens,
        plan.num_valid, plan.reset, plan.keys, plan.tok_index,
        plan.temperature, plan.top_k, plan.top_p)
    finished = self.scheduler.commit(np.asarray(nxt))
    self._steps += 1
    dt = time.monotonic() - t0
    if self.stats is not None:
      self.stats.note_step(
          active_slots=plan.active_slots, num_slots=self.num_slots,
          prefill_tokens=plan.prefill_tokens,
          decode_tokens=plan.decode_tokens, step_time_s=dt)
    if self.metrics_writer is not None:
      self.metrics_writer.write(self._steps, {
          "active_slots": plan.active_slots,
          "slot_occupancy": plan.active_slots / self.num_slots,
          "prefill_tokens": plan.prefill_tokens,
          "decode_tokens": plan.decode_tokens,
          "step_time_s": dt,
      })
    return finished

  def run(self, max_steps: Optional[int] = None
          ) -> Dict[Any, np.ndarray]:
    """Drive until the queue drains (or ``max_steps``); returns
    ``{uid: prompt+generated}`` for every request finished during the
    call."""
    out: Dict[Any, np.ndarray] = {}
    steps = 0
    while self.has_work and (max_steps is None or steps < max_steps):
      for fin in self.step():
        out[fin.uid] = fin.tokens
      steps += 1
    return out
