"""Continuous-batching inference engine over the TP mesh.

The fourth runtime mode (train / eval / generate / **serve**): ONE
jitted step — compiled once, shapes never change — fuses

  * prefill of newly admitted requests (their next prompt chunk), and
  * one-token decode of every other active slot

into a single ``[num_slots, chunk]`` model call against the slot KV
cache (kv_cache.py), per-slot cursors selecting each slot's absolute
positions and causal window (models/gpt.py ``slot_cache_attend``).
Requests therefore join and leave the batch every iteration with zero
recompilation — iteration-level batching as in Orca (OSDI'22) — and the
cache + cursor buffers are donated, so the engine's steady-state device
allocation is exactly one cache.

Division of labor: :class:`FCFSScheduler` (scheduler.py) owns all
host-side variability (admission, budgets, retirement, RNG streams);
:mod:`serving.resilience` owns fault/overload POLICY (admission
control, the degradation ladder, retry-vs-quarantine); this module owns
the device program, its placement, and the mechanics that policy drives.
Sampling runs per-slot inside the step (:func:`sample_token_slots` —
the traced-parameter twin of ``sample_logits``) with per-request keys
folded by token index, so a request's sample stream is independent of
which slot or iteration serves it.

Speculative decoding (serving/speculative/) rides the same fused step:
a drafter fills each decode slot's unused chunk positions with ``k``
guessed tokens, the one model call scores all of them (verification is
a prefill-shaped call — nearly free in this step), and in-jit per-slot
accept/rollback commits the accepted prefix plus one correction/bonus
token, rolling cursors back to the last accepted position.  Toggled by
``serving.speculative.*`` / per-request ``Request.speculative``.

Resilience (``serving.resilience.*``; docs/robustness.md): with the
group enabled, the fused step additionally returns a per-slot
finiteness verdict on exactly the logit rows the commit consumes — the
PR-2 sentinel pattern, in-trace, zero extra host syncs (the verdict
rides the step's own token fetch) — and gates each slot's cursor
advance on it, so a bad step never moves device state.  The host side
then simply replans: the retry re-feeds identical tokens (exact by
construction), persistent offenders are requeued with their committed
prefix (scheduler.requeue_slot — replay through chunked prefill
rebuilds KV and cursors bit-exactly), and hopeless ones are failed.
Overload is answered at submit (bounded queue + shedding) and by the
degradation ladder (speculation off -> prefill budget tightened ->
shed), never by touching admitted requests' outputs.

Exactness contract: greedy engine output is bit-identical (token ids)
to ``generate(use_cache=True)`` per request — the legacy path stays the
oracle (tests/test_serving.py), including requests admitted mid-flight,
slots reused after retirement, retried/requeued slots, and degradation
transitions (tests/test_serving_resilience.py).  Greedy SPECULATIVE
output keeps the same contract (exact-match acceptance); sampled
speculative output keeps the sampling distribution, not the bitstream
(tests/test_serving_speculative.py).
"""

from __future__ import annotations

import os
import time
import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import device as device_lib
from easyparallellibrary_tpu.observability import slo as slo_lib
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.registry import (
    SERVING_NAMESPACE, MetricRegistry)
from easyparallellibrary_tpu.serving import kv_cache as kv_lib
from easyparallellibrary_tpu.serving._capabilities import (
    check_draft_fits_chunk, check_servable)
from easyparallellibrary_tpu.serving.resilience import (
    AdmissionController, BadStepPolicy, DEGRADE_LEVELS)
from easyparallellibrary_tpu.serving.scheduler import (
    FCFSScheduler, FinishedRequest, Request, _slot_track)
from easyparallellibrary_tpu.utils.logging import get_logger

# Periodic ServingStats rollup cadence (engine steps): per-step records
# carry only step-local gauges, so the TTFT/ITL percentile SLO rules
# would otherwise only ever see a rollup at the END of a run() drive —
# and never for router-driven replicas, which step() forever.  The
# rollup is O(sample cap) thanks to the stats reservoirs.
_STATS_PUBLISH_EVERY = 50


def filtered_logits(logits, temperature, top_k, top_p):
  """Per-row temperature/top-k/top-p filtering with TRACED parameters —
  the distribution half of :func:`sample_token_slots` (same filter
  semantics and order as ``models.gpt.sample_logits``: top-k, then top-p
  over the survivors), shared with speculative verification
  (serving/speculative/verify.py), whose acceptance rule must judge
  drafts against EXACTLY the distribution sampling would draw from.

  ``logits`` [M, V]; ``temperature``/``top_p`` f32 [M]; ``top_k`` int32
  [M] (0 disables).  Returns the scaled, filtered logits [M, V]
  (filtered entries at -1e30); their softmax is the sampling
  distribution at ``temperature > 0``.
  """
  V = logits.shape[-1]
  neg = jnp.asarray(-1e30, logits.dtype)
  t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
  scaled = logits / t.astype(logits.dtype)
  # top-k with a traced k: threshold at the k-th largest value (ties at
  # the threshold survive, exactly like sample_logits' `logits < kth`).
  sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
  kth = jnp.take_along_axis(
      sorted_desc, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=-1)
  k_off = (top_k[:, None] <= 0) | (top_k[:, None] >= V)
  scaled = jnp.where((scaled >= kth) | k_off, scaled, neg)
  # top-p over the survivors: keep entries whose PRECEDING mass is < p
  # (the crossing token survives; the top token always survives).
  sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
  probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
  cum = jnp.cumsum(probs, axis=-1)
  keep_sorted = (cum - probs) < top_p[:, None]
  thresh = jnp.min(jnp.where(keep_sorted, sorted_desc,
                             jnp.asarray(jnp.inf, scaled.dtype)),
                   axis=-1, keepdims=True)
  p_on = top_p[:, None] < 1.0
  return jnp.where(p_on & (scaled < thresh), neg, scaled)


def sample_token_slots(logits, keys, temperature, top_k, top_p):
  """Per-slot sampling with TRACED parameters — the vectorized twin of
  ``models.gpt.sample_logits``, for the serving step where every slot
  carries its own sampling knobs and every value must be an array
  (static per-request values would recompile the fused step per
  parameter combination).  ``temperature<=0`` is greedy.

  ``logits`` [N, V]; ``keys`` uint32 [N, 2] per-slot PRNG keys;
  ``temperature``/``top_p`` f32 [N]; ``top_k`` int32 [N] (0 disables).
  Returns int32 [N] token ids.
  """
  greedy = jnp.argmax(logits, axis=-1)
  scaled = filtered_logits(logits, temperature, top_k, top_p)
  sampled = jax.vmap(jax.random.categorical)(keys, scaled)
  return jnp.where(temperature <= 0, greedy, sampled).astype(jnp.int32)


def _resolve_mesh(mesh):
  """The engine's placement mesh: the caller's, else the ambient Env
  mesh when one has been BUILT (never force-building one).

  This closes the fit->engine recompile interplay (ROADMAP item 1
  "First"; NOTES.md): once any component builds the cluster mesh (fit's
  setup does), ``utils.sharding.constrain`` binds every activation
  constraint inside the fused step to ``NamedSharding(mesh, ...)`` —
  so the step's OUTPUTS come back committed to that mesh even when the
  engine was constructed meshless, while its first-call inputs (a fresh
  meshless cache) were uncommitted single-device arrays.  Call 2's
  donated inputs then carry a different sharding signature than call
  1's and the step recompiles exactly once.  Adopting the ambient mesh
  makes allocation, in_shardings and out_shardings agree from the first
  call (replicated specs degrade gracefully on a 1-device mesh), so the
  compile-once contract holds in any construction order.
  """
  if mesh is not None:
    return mesh
  cluster = getattr(Env.get(), "cluster", None)
  if cluster is not None:
    # built_mesh observes without forcing a build (Cluster.mesh would
    # force one) — a truly meshless run must stay meshless.
    return getattr(cluster, "built_mesh", None)
  return None


class ContinuousBatchingEngine:
  """Slot-based continuous-batching decode engine for a (non-pipelined)
  GPT.

  ``params`` may be boxed (flax Partitioned) or plain; with ``mesh``
  they should already live in their sharded layout (e.g. from
  ``create_sharded_train_state`` or ``runtime.saver.restore_params``)
  and the cache is allocated heads-over-TP on the same mesh.  All knobs
  default from the active ``Config``'s ``serving.*`` group.

  Typical drive::

      eng = ContinuousBatchingEngine(model, params, mesh=mesh)
      eng.submit(Request(uid="a", prompt=ids, max_new_tokens=32))
      outputs = eng.run()          # {uid: prompt+generated np.int32}
      eng.finished["a"].finish_reason   # why each request ended

  ``submit`` returns False when admission control sheds the request
  (``serving.resilience.queue_limit``); the shed record still lands in
  ``engine.finished`` with reason ``"shed"``.
  """

  def __init__(self, model, params, *, mesh=None,
               num_slots: Optional[int] = None,
               prefill_chunk: Optional[int] = None,
               prefill_token_budget: Optional[int] = None,
               max_batch: Optional[int] = None,
               stop_token: Optional[int] = None,
               donate_cache: Optional[bool] = None,
               drafter=None, speculative: Optional[bool] = None,
               draft_model=None, draft_params=None,
               resilience: Optional[bool] = None,
               paged: Optional[bool] = None,
               block_size: Optional[int] = None,
               num_blocks: Optional[int] = None,
               token_budget: Optional[int] = None,
               prefix_cache: Optional[bool] = None,
               stats=None, metrics_writer=None, registry=None,
               config=None, track_prefix: Optional[str] = None,
               checkpoint_version: int = 0):
    cfg = model.cfg
    root_config = config if config is not None else Env.get().config
    conf = root_config.serving
    # Reconcile the ambient tracer AND the ambient SLO monitor with
    # observability.* so a config-enabled run traces and monitors
    # serving without any wiring at the call site.
    trace_lib.ensure_configured(root_config)
    self._slo = slo_lib.ensure_configured(root_config)
    # Device-truth introspection (observability/device.py): warmup
    # capture of every compiled twin's cost/memory analysis, HBM
    # watermark gauges on the stats cadence, and the per-site measured
    # collective-bytes feed.  None when observability.device is off —
    # every hook below is then a cheap attribute test.
    self._introspector = device_lib.ensure_configured(root_config)
    self._pending_step_specs = None
    self._capture_xla = root_config.observability.slo.capture_xla
    self._pending_xla_dir: Optional[str] = None
    check_servable(cfg)
    # Perfetto track namespace for this engine's per-slot timelines
    # (replicas pass serving/replica<i>; docs/observability.md).
    self._track_prefix = track_prefix or "serving"
    # This engine's twin label in breach payloads — the exact-match key
    # that routes engine-attributed anomalies (recompile, watchdog)
    # back to THIS engine and no other (e.g. the xla-capture listener
    # on a shared ambient monitor must not arm every replica).
    self._twin_label = f"{self._track_prefix}/fused_step"
    self.model = model
    self.params = params
    self.mesh = _resolve_mesh(mesh)
    # The checkpoint version these params came from (blue/green rollout,
    # serving/rollout.py): scopes the prefix cache's keys and makes the
    # scheduler refuse cross-version restore replays.  0 = pre-rollout
    # default; the rollout controller stamps green replicas with N+1.
    self.checkpoint_version = int(checkpoint_version)
    self.num_slots = num_slots if num_slots is not None else conf.num_slots
    self.chunk = (prefill_chunk if prefill_chunk is not None
                  else conf.prefill_chunk)
    if self.chunk > cfg.max_seq_len:
      raise ValueError(f"prefill_chunk {self.chunk} exceeds max_seq_len "
                       f"{cfg.max_seq_len}")
    budget = (prefill_token_budget if prefill_token_budget is not None
              else conf.prefill_token_budget)
    if budget > 0 and budget < self.chunk:
      raise ValueError(
          f"prefill_token_budget {budget} below prefill_chunk "
          f"{self.chunk}: no admission could ever afford its first chunk")
    # Paged mode (serving.paged.*; docs/serving.md "Paged KV cache"):
    # token-flat fused step over a block-table cache — decode cost
    # scales with scheduled tokens, concurrency with blocks, not with
    # num_slots * max_seq_len.
    pconf = conf.paged
    self.paged = paged if paged is not None else pconf.enabled
    eff_batch = max_batch if max_batch is not None else conf.max_batch
    if self.paged:
      self.block_size = (block_size if block_size is not None
                         else pconf.block_size)
      mb = kv_lib.blocks_per_slot(cfg, self.block_size)
      self.num_blocks = (num_blocks if num_blocks is not None
                         else pconf.num_blocks)
      if self.num_blocks <= 0:
        self.num_blocks = kv_lib.default_num_blocks(cfg, self.num_slots,
                                                    self.block_size)
      self.token_budget = (token_budget if token_budget is not None
                           else pconf.token_budget)
      if self.token_budget <= 0:
        # Auto: every decode slot's guaranteed token plus two prefill
        # chunks of admission headroom per step.
        self.token_budget = self.num_slots + 2 * self.chunk
      # Resolve the attend implementation ONCE (kernels/paged_attention
      # dispatch rule: Pallas on TPU, the bit-exact jnp reference
      # elsewhere) so the jitted step never consults the environment.
      from easyparallellibrary_tpu.kernels.paged_attention import (
          default_paged_impl)
      self._paged_impl = default_paged_impl()
    else:
      self.block_size = self.num_blocks = self.token_budget = 0
      self._paged_impl = None
    # Copy-on-write prefix caching (serving.prefix_cache.*;
    # docs/serving.md "Prefix caching"): radix-tree block reuse over
    # the paged pool — the scheduler rejects it without paged mode.
    pc_conf = conf.prefix_cache
    self.prefix_caching = (prefix_cache if prefix_cache is not None
                           else pc_conf.enabled)
    self.drafter = self._resolve_drafter(conf, drafter, speculative,
                                         draft_model, draft_params)
    self.scheduler = FCFSScheduler(
        num_slots=self.num_slots, prefill_chunk=self.chunk,
        max_seq_len=cfg.max_seq_len, prefill_token_budget=budget,
        max_batch=eff_batch,
        stop_token=stop_token if stop_token is not None
        else conf.stop_token,
        spec_k=self.drafter.k if self.drafter is not None else 0,
        block_size=self.block_size, num_blocks=self.num_blocks,
        token_budget=self.token_budget,
        track_prefix=self._track_prefix,
        prefix_cache=self.prefix_caching,
        prefix_session_ttl_s=pc_conf.session_ttl_s,
        prefix_max_cached_blocks=pc_conf.max_cached_blocks,
        checkpoint_version=self.checkpoint_version)
    res_conf = conf.resilience
    self._resilient = (resilience if resilience is not None
                       else res_conf.enabled)
    self.stats = stats
    if self._resilient and self.stats is None:
      # The degradation ladder reads measured ITL from ServingStats;
      # auto-build one rather than silently losing that signal.
      from easyparallellibrary_tpu.profiler.serving import ServingStats
      self.stats = ServingStats(finished_limit=conf.finished_limit)
    self.metrics_writer = metrics_writer
    # Optional MetricRegistry (observability/registry.py): per-step
    # records publish under serving/* through the one metric schema.
    self.registry = registry
    # Finish records by uid (reasons incl. shed/deadline/cancelled) —
    # bounded to the most recent serving.finished_limit entries (0 =
    # keep all; a long-running server must bound this or grow host
    # memory linearly with requests served).
    self.finished: Dict[Any, FinishedRequest] = {}
    self._finished_limit = conf.finished_limit
    self.scheduler.on_finish.append(self._record_finished)
    if self.stats is not None:
      stats_obj = self.stats
      self.scheduler.on_admit.append(stats_obj.note_admitted)
      self.scheduler.on_first_token.append(stats_obj.note_first_token)
      self.scheduler.on_finish.append(
          lambda fin: stats_obj.note_finished(fin.uid, fin.new_tokens,
                                              fin.finish_reason))
    self._admission: Optional[AdmissionController] = None
    self._bad_policy: Optional[BadStepPolicy] = None
    self._watchdog = None
    if self._resilient:
      self._admission = AdmissionController(
          queue_limit=res_conf.queue_limit,
          itl_slo_s=res_conf.itl_slo_s,
          degrade_queue_frac=res_conf.degrade_queue_frac,
          on_transition=self._on_degrade_transition)
      self._bad_policy = BadStepPolicy(
          max_step_retries=res_conf.max_step_retries,
          max_requeues=res_conf.max_requeues)
      if res_conf.step_timeout_s > 0:
        from easyparallellibrary_tpu.runtime.resilience import StepWatchdog
        # on_timeout binds the STATS and MONITOR objects, not an engine
        # method: the finalizer below pins the watchdog, so a
        # watchdog->engine reference would pin the engine too and the
        # finalizer could never fire.  The monitor raises the hang as a
        # first-class SLO breach (and deep-captures) from the watchdog's
        # monitor thread — both objects are thread-safe.
        stats_obj = self.stats
        slo_obj = self._slo
        twin_label = self._twin_label

        def _on_timeout(step, _stats=stats_obj, _slo=slo_obj,
                        _twin=twin_label):
          if _stats is not None:
            _stats.note_watchdog_timeout()
          if _slo is not None:
            _slo.note_event("watchdog_timeout",
                            {"engine_step": int(step), "twin": _twin},
                            step=int(step))

        self._watchdog = StepWatchdog(
            res_conf.step_timeout_s, on_timeout=_on_timeout,
            knob="serving.resilience.step_timeout_s")
        # The monitor thread's target is a bound watchdog method, so the
        # thread pins the watchdog and never exits without close() — a
        # discarded engine would otherwise leak one live
        # 'epl-step-watchdog' thread per construction (the training
        # loop closes its own watchdog in fit(); the engine must not
        # depend on the caller remembering to).  The finalizer holds
        # the WATCHDOG, not the engine, so the engine stays collectible.
        self._watchdog_finalizer = weakref.finalize(
            self, self._watchdog.close)
    self._drafter_failures = 0
    self._drafter_fail_logged = False
    if self.paged:
      self._kv = kv_lib.allocate_paged_kv_cache(
          cfg, self.num_blocks, self.block_size, self.mesh)
      self._cursors = None
    else:
      self._kv, self._cursors = kv_lib.allocate_kv_cache(
          cfg, self.num_slots, self.chunk, self.mesh)
    # Quarantine hygiene: a poisoned device step leaves non-finite K/V
    # in a bad slot's cache, and slot_cache_attend's V contraction
    # touches every cache row (0 * NaN = NaN), so the poison must be
    # zeroed before the slot is read again.  A freed slot is zeroed
    # whole (its next occupant starts from row 0); a retried slot is
    # zeroed from its committed cursor up — the retry is only
    # guaranteed to rewrite its OWN grant window, which can be smaller
    # than the bad step's (speculation degraded off, drafter fault,
    # prefill budget tightened between steps).  Separate tiny program;
    # dispatched only on bad-step events, compiles once.  The SAME
    # program serves both layouts: dim 0 is slots (contiguous) or pool
    # blocks (paged), dim 1 rows within — the paged host side maps slot
    # block lists to (block mask, per-block start row) and always
    # includes the null block, which a NaN-params step poisons through
    # padding writes.
    self._sanitize_fn = jax.jit(
        lambda kv, mask, start: jax.tree_util.tree_map(
            lambda x: jnp.where(
                mask[:, None, None, None]
                & (jnp.arange(x.shape[1])[None, :, None, None]
                   >= start[:, None, None, None]),
                jnp.zeros((), x.dtype), x), kv),
        donate_argnums=0) if self._resilient else None
    if self._sanitize_fn is not None and self._introspector is not None:
      # The sanitize twin's cost card, captured here (its first real
      # dispatch is a fault — warmup must not wait for one).  Abstract
      # specs only: the live cache is never read.
      rows = self.num_blocks if self.paged else self.num_slots
      self._introspector.capture_twin(
          f"{self._track_prefix}/sanitize", self._sanitize_fn,
          device_lib.specs_of(
              (self._kv, np.zeros((rows,), bool),
               np.zeros((rows,), np.int32))),
          compile_count=1)
    # Perfetto track name per slot (the scheduler's lifecycle spans and
    # the engine's per-step spans must land on the same track);
    # precomputed so the per-step tracing loop does no string work.
    self._slot_tracks = [_slot_track(i, self._track_prefix)
                         for i in range(self.num_slots)]
    self._steps = 0
    donate = conf.donate_cache if donate_cache is None else donate_cache
    if self.drafter is not None:
      self.drafter.bind(self)
      self._step_fn = (self._build_paged_spec_step(donate, self._resilient)
                       if self.paged
                       else self._build_spec_step(donate, self._resilient))
    elif self.paged:
      self._step_fn = self._build_paged_step(donate, self._resilient)
    else:
      self._step_fn = self._build_step(donate, self._resilient)
    # Always-on compile sentinel (observability/slo.py): the compile-
    # once contract moves from test-only to production — any post-
    # warmup recompile of the fused step is detected the step it
    # happens, attributed to the input signature, and raised as a
    # first-class SLO breach + trace instant.  One host int compare per
    # step; the thunk reads the LIVE attribute so chaos wrappers
    # (testing/chaos._StepFnWrapper) that replace _step_fn stay
    # transparent.
    self._compile_sentinel = slo_lib.CompileSentinel(
        self._twin_label,
        lambda: self._step_fn._cache_size(),
        on_recompile=[self._note_recompile])
    if self._slo is not None:
      # The monitor consumes this engine's registry records (it IS a
      # registry sink) and merges this engine's scheduler/allocator
      # summary into diagnostic bundles.  Both hooks hold the engine
      # weakly/idempotently — the ambient monitor outlives engines.
      if self.registry is not None:
        self._slo.attach(self.registry)
      self._slo.add_context_provider(self._capture_context)
      if self._capture_xla:
        self._slo.add_listener(self._arm_xla_capture, weak=True)
    # Engine-level SLO actuator (serving/autotune.py; docs/robustness.md
    # "Self-healing fleet"): breaches move data-valued knobs between
    # steps — speculation-k / prefill-budget / slot-cap clamps and the
    # admission-ladder floor — with hysteretic recovery.  Never a shape:
    # the compile-once contract is the actuator's hard constraint.
    self._autotuner = None
    if conf.autotune.enabled:
      from easyparallellibrary_tpu.serving.autotune import EngineAutotuner
      self._autotuner = EngineAutotuner(self, self._slo,
                                        config=root_config)
    if self.paged:
      layout = (f"paged: {self.num_blocks} x {self.block_size}-token "
                f"blocks, token budget {self.token_budget}, "
                f"{self._paged_impl} attend, "
                f"{kv_lib.paged_cache_bytes(cfg, self.num_blocks, self.block_size) / 1e6:.1f} MB")
    else:
      layout = (f"contiguous slots, "
                f"{kv_lib.cache_bytes(cfg, self.num_slots, self.chunk) / 1e6:.1f} MB")
    get_logger().info(
        "serving engine: %d slots x chunk %d (%s, %s), "
        "prefill budget %s, max batch %d, speculation %s, resilience %s",
        self.num_slots, self.chunk, layout,
        "mesh-sharded" if self.mesh is not None else "single-program",
        budget or "uncapped", self.scheduler.max_batch,
        f"{type(self.drafter).__name__}(k={self.drafter.k})"
        if self.drafter is not None else "off",
        f"on (queue_limit {res_conf.queue_limit or 'unbounded'}, "
        f"itl_slo {res_conf.itl_slo_s or 'off'}, watchdog "
        f"{res_conf.step_timeout_s or 'off'})"
        if self._resilient else "off")

  def _resolve_drafter(self, conf, drafter, speculative, draft_model,
                       draft_params):
    """``speculative=False`` wins over everything (an explicit opt-out
    must be trustworthy even when a drafter object was constructed);
    otherwise an explicit ``drafter`` wins, and ``serving.speculative.*``
    decides the rest (``speculative=True`` overrides its ``enabled``).
    Any resolved drafter must fit the fused step's chunk
    (k + 1 <= prefill_chunk)."""
    from easyparallellibrary_tpu.serving.speculative import (
        DraftModelDrafter, NgramDrafter)
    if speculative is False:
      return None
    spec = conf.speculative
    if drafter is None and (spec.enabled or speculative):
      if spec.kind == "ngram":
        drafter = NgramDrafter(k=spec.k, ngram_max=spec.ngram_max,
                               ngram_min=spec.ngram_min)
      else:  # "draft_model" (config validation rejects anything else)
        if draft_model is None or draft_params is None:
          raise ValueError(
              "serving.speculative.kind='draft_model' needs the drafter's "
              "weights: pass draft_model=/draft_params= (e.g. via "
              "DraftModelDrafter.from_checkpoint) or a drafter= instance")
        drafter = DraftModelDrafter(draft_model, draft_params, k=spec.k)
    if drafter is not None:
      check_draft_fits_chunk(drafter.k, self.chunk)
    return drafter

  # --------------------------------------------------- resilience hooks

  def _on_degrade_transition(self, old: int, new: int, signals):
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/degraded", cat="serving", track="serving",
          args={"from": DEGRADE_LEVELS[old], "to": DEGRADE_LEVELS[new],
                **signals})
      tracer.counter("serving/degraded_level", new)
    if self.stats is not None:
      self.stats.note_degraded(new)

  # -------------------------------------------------- observability hooks

  def _describe_signature(self, plan) -> Dict[str, Any]:
    """Shape/dtype signature of the step's host-side inputs at
    recompile-detection time — built only on the (rare) recompile path
    to attribute the event, never per healthy step."""
    sig: Dict[str, Any] = {"twin": type(plan).__name__,
                           "mesh": self.mesh is not None,
                           "resilient": self._resilient,
                           "paged": self.paged}
    for name, v in vars(plan).items():
      if hasattr(v, "shape"):
        sig[name] = f"{v.dtype}{list(v.shape)}"
    return sig

  def _note_recompile(self, label: str, cache_size: int,
                      new_compiles: int, signature) -> None:
    """CompileSentinel subscriber: surface an unexpected fused-step
    recompile as a trace instant, a stats counter, and a first-class
    SLO breach (which also triggers deep capture when configured)."""
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/recompile", cat="serving", track="serving",
          args={"twin": label, "cache_size": int(cache_size),
                "new_compiles": int(new_compiles),
                "signature": str(signature)[:512]})
    if self.stats is not None:
      self.stats.note_recompile(new_compiles)
    if self._slo is not None:
      self._slo.note_event(
          "unexpected_recompile",
          {"twin": label, "cache_size": int(cache_size),
           "signature": str(signature)[:512]},
          step=self._steps)

  def _capture_context(self) -> Dict[str, Any]:
    """Scheduler/allocator state summary merged into diagnostic bundles
    (observability/slo.py DiagnosticCapture), keyed by this engine's
    track prefix so replicas' summaries land side by side."""
    sched = self.scheduler
    ctx: Dict[str, Any] = {
        "engine_steps": self._steps,
        "queue_depth": sched.queue_depth,
        "num_active": sched.num_active,
        "num_slots": self.num_slots,
        "paged": self.paged,
        "recompiles": self._compile_sentinel.recompiles,
        "active_uids": [str(s.req.uid)
                        for s in sched.active.values()][:32],
    }
    if self._admission is not None:
      ctx["degraded_level"] = self._admission.level
      ctx["shed_total"] = self._admission.shed_total
    if self._autotuner is not None:
      ctx["autotune_level"] = self._autotuner.level
      ctx["autotune_actuations"] = self._autotuner.actuations
    if self._bad_policy is not None:
      ctx.update(self._bad_policy.counters())
    if self.paged:
      ctx.update(kv_blocks_free=sched.kv_blocks_free,
                 kv_blocks_used=sched.kv_blocks_used,
                 kv_fragmentation=sched.kv_fragmentation,
                 preemptions=sched.preemptions,
                 proactive_preemptions=sched.proactive_preemptions)
      if self.prefix_caching:
        ctx.update(prefix_hits=sched.prefix_hits,
                   prefix_misses=sched.prefix_misses,
                   prefix_blocks_reused=sched.prefix_blocks_reused,
                   prefix_evictions=sched.prefix_evictions,
                   prefix_cached_blocks=sched.prefix_cached_blocks)
    out = {self._track_prefix: ctx}
    if self._introspector is not None:
      # Device truth rides every diagnostic bundle: cost cards, live
      # HBM gauges, the per-site measurement store.  The introspector
      # is ambient (shared across replicas), so one "device" key
      # carries the whole picture.
      out["device"] = self._introspector.context()
    return out

  def _note_step_specs(self, step_args) -> None:
    """Snapshot the warmup call's abstract argument specs (shapes and
    dtypes only — donated buffers are never held) so the device
    introspector can capture this twin's cost card AFTER the step
    completes; no-op past warmup or with device observability off."""
    if (self._introspector is not None and self._steps == 0
        and self._pending_step_specs is None
        and not self._introspector.has_card(self._twin_label)):
      self._pending_step_specs = device_lib.specs_of(step_args)

  def _twin_meta(self) -> Dict[str, Any]:
    """Geometry the perf gate normalizes cost-card numbers by: the
    step's token capacity and the KV footprint per request."""
    cfg = self.model.cfg
    if self.paged:
      kv_bytes = kv_lib.paged_cache_bytes(cfg, self.num_blocks,
                                          self.block_size)
      tokens = self.token_budget
    else:
      kv_bytes = kv_lib.cache_bytes(cfg, self.num_slots, self.chunk)
      tokens = self.num_slots * self.chunk
    return {"tokens_per_step": tokens, "kv_cache_bytes": kv_bytes,
            "kv_bytes_per_request": kv_bytes / max(self.num_slots, 1),
            "num_slots": self.num_slots, "paged": self.paged}

  def _arm_xla_capture(self, rule: str, payload: Dict[str, Any]) -> None:
    """Breach listener (observability.slo.capture_xla): arm a
    jax.profiler device capture around the NEXT fused step, written
    under the breach's diagnostic bundle.  Only for breaches the
    payload attributes to THIS engine's twin — the ambient monitor is
    shared, and a fleet-level breach arming a heavy device capture on
    every healthy replica at once would be the anomaly."""
    bundle = payload.get("bundle")
    if bundle and payload.get("twin") == self._twin_label:
      self._pending_xla_dir = os.path.join(bundle, "xla")

  # ----------------------------------------------------------- device step

  def _jit_step(self, step, donate: bool, n_rep_in: int, n_rep_out: int,
                cursors: bool = True):
    """jit a fused step with the engine's donation/placement discipline:
    cache (+ cursors in the contiguous layout) donated, everything after
    them replicated when a mesh is attached.  The paged step has no
    device cursors — positions are host-planned per step — so only the
    cache pools donate (``cursors=False``)."""
    jit_kwargs: Dict[str, Any] = {}
    if donate:
      jit_kwargs["donate_argnums"] = (1, 2) if cursors else (1,)
    if self.mesh is not None:
      from easyparallellibrary_tpu.parallel.api import state_shardings
      kv_sh, cur_sh = kv_lib.kv_cache_shardings(self.model.cfg, self.mesh)
      param_sh = state_shardings(self.params, self.mesh)
      rep = cur_sh
      state_in = (param_sh, kv_sh) + ((cur_sh,) if cursors else ())
      state_out = (kv_sh,) + ((cur_sh,) if cursors else ())
      jit_kwargs["in_shardings"] = state_in + (rep,) * n_rep_in
      jit_kwargs["out_shardings"] = (rep,) * n_rep_out + state_out
    return jax.jit(step, **jit_kwargs)

  def _build_step(self, donate: bool, guard: bool = False):
    from easyparallellibrary_tpu.models.gpt import slot_step_logits
    model = self.model
    C = self.chunk

    def step(params, kv, cursors, tokens, num_valid, reset, keys,
             tok_index, temperature, top_k, top_p):
      cursors = jnp.where(reset, 0, cursors)
      logits, kv = slot_step_logits(model, params, kv, tokens, cursors)
      # Each slot's next-token logits sit at its LAST live chunk
      # position; idle slots (num_valid=0) read position 0 — garbage the
      # scheduler never consumes.
      last = jnp.take_along_axis(
          logits, jnp.clip(num_valid - 1, 0, C - 1)[:, None, None],
          axis=1)[:, 0]
      step_keys = jax.vmap(jax.random.fold_in)(keys, tok_index)
      nxt = sample_token_slots(last.astype(jnp.float32), step_keys,
                               temperature, top_k, top_p)
      if not guard:
        return nxt, kv, cursors + num_valid
      # In-jit finiteness verdict on exactly the rows commit consumes
      # (the PR-2 sentinel pattern): a bad slot's cursor stays put, so
      # its K/V writes beyond the old cursor are unreachable garbage the
      # retry overwrites — device state never advances on a bad step.
      slot_ok = (jnp.all(jnp.isfinite(last), axis=-1)
                 | (num_valid == 0))
      return nxt, slot_ok, kv, jnp.where(slot_ok, cursors + num_valid,
                                         cursors)

    return self._jit_step(step, donate, n_rep_in=8,
                          n_rep_out=2 if guard else 1)

  def _build_spec_step(self, donate: bool, guard: bool = False):
    """The speculative twin of :meth:`_build_step`: the SAME single
    model call (drafts ride the chunk positions plain decode wastes, so
    verification adds no model compute), followed by in-jit per-slot
    accept/rollback (serving/speculative/verify.py).  Shapes are static
    in ``k_max = drafter.k``; per-slot draft length is data
    (``num_draft``), so joins/leaves/short proposals never recompile.
    """
    from easyparallellibrary_tpu.models.gpt import slot_step_logits
    from easyparallellibrary_tpu.serving.speculative.verify import (
        verify_tokens)
    model = self.model
    C = self.chunk
    K = self.drafter.k

    def step(params, kv, cursors, tokens, num_valid, num_draft, reset,
             keys, tok_index, temperature, top_k, top_p):
      cursors = jnp.where(reset, 0, cursors)
      logits, kv = slot_step_logits(model, params, kv, tokens, cursors)
      # base = non-draft tokens fed (prefill grant, or 1 for decode);
      # position base-1+j's logits are the target distribution for
      # draft j, and base-1+num_draft's feed the bonus token.  With
      # num_draft=0 row 0 is exactly the legacy step's `last` gather.
      base = num_valid - num_draft
      pos = jnp.clip(base[:, None] - 1 + jnp.arange(K + 1)[None],
                     0, C - 1)
      tgt = jnp.take_along_axis(
          logits, pos[:, :, None], axis=1).astype(jnp.float32)
      dpos = jnp.clip(base[:, None] + jnp.arange(K)[None], 0, C - 1)
      drafts = jnp.take_along_axis(tokens, dpos, axis=1)
      committed, n_committed, accepted = verify_tokens(
          tgt, drafts, num_draft, keys, tok_index, temperature, top_k,
          top_p)
      # Rollback is pure cursor math: the cache keeps K/V for the fed
      # non-draft tokens plus the accepted prefix; rejected-draft K/V
      # beyond the new cursor is masked and later overwritten, exactly
      # like chunked-prefill garbage.
      if not guard:
        return committed, n_committed, kv, cursors + base + accepted
      # All K+1 target rows of a healthy slot are gathers of real
      # (finite) logit positions, so checking the whole [K+1, V] block
      # is safe and covers every row verification consumed.
      slot_ok = (jnp.all(jnp.isfinite(tgt), axis=(1, 2))
                 | (num_valid == 0))
      new_cursors = jnp.where(slot_ok, cursors + base + accepted,
                              cursors)
      return committed, n_committed, slot_ok, kv, new_cursors

    return self._jit_step(step, donate, n_rep_in=9,
                          n_rep_out=3 if guard else 2)

  def _build_paged_step(self, donate: bool, guard: bool = False):
    """Token-flat fused step over the paged cache: ONE model call scores
    the whole ``[token_budget]`` flat batch (prefill chunks, one-token
    decodes — each position tagged with slot and absolute position) so
    device compute scales with scheduled tokens, not
    ``num_slots * chunk``.  Shapes are static in ``token_budget`` /
    ``num_slots`` / the block-table width; block tables, positions and
    validity are data — joins, leaves and pool reshuffles never
    recompile.  No device cursors: positions are host-planned, so the
    only persistent device state is the donated pool pair."""
    from easyparallellibrary_tpu.models.gpt import paged_step_logits
    model = self.model
    T = self.token_budget
    impl = self._paged_impl

    def step(params, kv, tokens, slot_ids, positions, valid, tables,
             last_idx, active, keys, tok_index, temperature, top_k,
             top_p):
      logits, kv = paged_step_logits(model, params, kv, tokens, slot_ids,
                                     positions, valid, tables, impl=impl)
      # Each slot's next-token logits sit at its LAST scheduled flat
      # position; idle slots read row 0 — garbage the scheduler never
      # consumes (same contract as the slot step's num_valid=0 rows).
      last = jnp.take(logits, jnp.clip(last_idx, 0, T - 1), axis=0)
      step_keys = jax.vmap(jax.random.fold_in)(keys, tok_index)
      nxt = sample_token_slots(last.astype(jnp.float32), step_keys,
                               temperature, top_k, top_p)
      if not guard:
        return nxt, kv
      slot_ok = jnp.all(jnp.isfinite(last), axis=-1) | ~active
      return nxt, slot_ok, kv

    return self._jit_step(step, donate, n_rep_in=12,
                          n_rep_out=2 if guard else 1, cursors=False)

  def _build_paged_spec_step(self, donate: bool, guard: bool = False):
    """The speculative twin of :meth:`_build_paged_step`: drafts ride
    LEFTOVER flat-budget positions (scheduler pass 3) instead of wasted
    chunk columns, the same single model call scores them, and
    verification gathers each slot's K+1 target rows by flat index
    (row 0 at the slot's last real token, rows 1..K at its draft
    positions).  No cursor rollback — the host plans next step's
    positions from the committed count, so rejection is pure
    bookkeeping, and rejected-draft K/V beyond it is masked garbage
    overwritten on the next feed, exactly like chunked-prefill
    garbage."""
    from easyparallellibrary_tpu.models.gpt import paged_step_logits
    from easyparallellibrary_tpu.serving.speculative.verify import (
        verify_tokens)
    model = self.model
    T = self.token_budget
    K = self.drafter.k
    impl = self._paged_impl

    def step(params, kv, tokens, slot_ids, positions, valid, tables,
             base_last, draft_base, num_draft, active, keys, tok_index,
             temperature, top_k, top_p):
      logits, kv = paged_step_logits(model, params, kv, tokens, slot_ids,
                                     positions, valid, tables, impl=impl)
      j = jnp.arange(K + 1)[None]                       # [1, K+1]
      idx = jnp.concatenate(
          [base_last[:, None],
           draft_base[:, None] + jnp.arange(K)[None]], axis=1)
      # Rows past a slot's actual draft count clamp to its own (real,
      # finite) last row: verification masks them anyway, and the guard
      # verdict must never convict a slot on another slot's rows.
      idx = jnp.where(j <= num_draft[:, None], idx, base_last[:, None])
      idx = jnp.clip(idx, 0, T - 1)
      tgt = jnp.take(logits, idx, axis=0).astype(jnp.float32)  # [N,K+1,V]
      dpos = jnp.clip(draft_base[:, None] + jnp.arange(K)[None], 0, T - 1)
      drafts = jnp.take(tokens, dpos, axis=0)
      committed, n_committed, accepted = verify_tokens(
          tgt, drafts, num_draft, keys, tok_index, temperature, top_k,
          top_p)
      if not guard:
        return committed, n_committed, kv
      slot_ok = jnp.all(jnp.isfinite(tgt), axis=(1, 2)) | ~active
      return committed, n_committed, slot_ok, kv

    return self._jit_step(step, donate, n_rep_in=14,
                          n_rep_out=3 if guard else 2, cursors=False)

  # ------------------------------------------------------------ host loop

  def _record_finished(self, fin: FinishedRequest) -> None:
    """Record a resolution in ``finished``, evicting oldest-first past
    ``serving.finished_limit`` (0 = unbounded)."""
    # pop first: re-assigning an existing key would keep its ORIGINAL
    # dict insertion position, so a reused uid's fresh record would be
    # evicted as if it were the oldest.
    self.finished.pop(fin.uid, None)
    self.finished[fin.uid] = fin
    if self._finished_limit > 0:
      while len(self.finished) > self._finished_limit:
        self.finished.pop(next(iter(self.finished)))

  def submit(self, request: Request) -> bool:
    """Enqueue `request`; returns False when admission control sheds it
    (bounded queue full, or the ladder is at its shed level).  Shed
    records land in ``self.finished`` with reason ``"shed"`` and are
    never admitted — the client learns at submit time, not after a
    hopeless queue wait.  Malformed requests raise regardless of load
    (validation must not depend on instantaneous queue depth)."""
    prompt = self.scheduler.validate(request)
    if self._admission is not None and not self.scheduler.has_work:
      # The ladder normally de-escalates inside step(), but an idle
      # engine never steps: if the queue drained without stepping
      # (every queued request cancelled or expired after a shed-level
      # observation), a stale shed level would otherwise reject 100%
      # of traffic forever.  Re-observe with the idle signals first.
      self._apply_degradation()
    if (self._admission is not None
        and self._admission.should_shed(self.scheduler.queue_depth)):
      self._admission.note_shed()
      fin = FinishedRequest(uid=request.uid, tokens=prompt,
                            new_tokens=0, finish_reason="shed")
      self._record_finished(fin)
      if self.stats is not None:
        self.stats.note_shed(request.uid)
      tracer = trace_lib.get_tracer()
      if tracer.enabled:
        tracer.instant(
            "serving/shed", cat="serving", track="serving/requests",
            args={"uid": str(request.uid),
                  "queue_depth": int(self.scheduler.queue_depth),
                  "level": DEGRADE_LEVELS[self._admission.level]})
        if request.flow_id is not None:
          # A router-minted flow must terminate even on a shed — the
          # rejection IS this request's resolution.
          tracer.flow("f", request.flow_id, track="serving/requests",
                      args={"uid": str(request.uid), "reason": "shed"})
      get_logger().warning(
          "shedding request %r at submit (queue %d/%d, level %s)",
          request.uid, self.scheduler.queue_depth,
          self._admission.queue_limit,
          DEGRADE_LEVELS[self._admission.level])
      return False
    if self.stats is not None:
      self.stats.note_submitted(request.uid)
    self.scheduler.submit(request, _prompt=prompt)
    return True

  def cancel(self, uid: Any) -> bool:
    """Client cancellation: retire `uid` wherever it is; the record (and
    any partial output) lands in ``self.finished`` immediately (the
    on_finish hook fires inside this call), and the retirement is also
    returned by the next ``step()``.  Returns False for
    unknown/already-finished uids."""
    return self.scheduler.cancel(uid)

  # ------------------------------------------------- snapshot / migration

  def snapshot_requests(self) -> List[Dict[str, Any]]:
    """Serializable snapshots of every queued + in-flight request
    (scheduler.snapshot_requests) — the failover/drain currency of the
    multi-replica router (serving/router.py): restoring them on another
    engine sharing the params source resumes each stream bit-exactly
    via prefix replay."""
    return self.scheduler.snapshot_requests()

  def restore_request(self, snap: Dict[str, Any],
                      front: bool = False) -> Any:
    """Resubmit a snapshotted request (bit-exact resumption; see
    :meth:`snapshot_requests`).  Bypasses admission control on purpose:
    a migrated request was already admitted by the fleet once — shedding
    it here would double-charge it for the overload verdict."""
    uid = self.scheduler.restore_request(snap, front=front)
    if self.stats is not None:
      # Keep the ORIGINAL submit time (same monotonic clock domain) so
      # the survivor's TTFT sample includes the pre-migration wait.
      self.stats.note_submitted(uid, at=snap.get("submitted_at"))
    return uid

  def evacuate(self) -> List[Dict[str, Any]]:
    """Snapshot and REMOVE every queued + in-flight request (no finish
    records — they finish elsewhere).  The router's failover and
    drain-timeout migration path; the engine stays warm (cache, compiled
    step and watchdog untouched) and can serve again immediately."""
    return self.scheduler.evacuate()

  @property
  def has_work(self) -> bool:
    return self.scheduler.has_work

  def close(self):
    """Release background resources (the hung-step watchdog thread).
    Idempotent; the engine remains usable for stepping afterwards —
    the watchdog simply stops firing.  Also runs automatically when the
    engine is garbage-collected (or at interpreter exit) and on
    ``with`` exit, so un-closed engines never leak monitor threads."""
    if self._watchdog is not None:
      self._watchdog.close()
      self._watchdog = None
      self._watchdog_finalizer.detach()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
    return False

  def _trace_slot_spans(self, tracer, plan, t0_us: float, t1_us: float,
                        num_draft=None, n_committed=None):
    """Per-slot timeline spans for one fused step: the single device
    call covers every active slot, so each slot's prefill / decode /
    speculate span shares its bounds and nests inside the request
    lifecycle span opened at admission (scheduler._admit).  Speculating
    slots carry drafted/accepted counts in their span args.  Host
    values only — never called with device arrays."""
    if not tracer.enabled:
      return
    for slot in np.nonzero(plan.num_valid)[0]:
      slot = int(slot)
      track = self._slot_tracks[slot]
      extra = {}
      if self.paged:
        # Per-request block occupancy in the timeline (report.py rolls
        # this up as each request's peak KV blocks held).
        extra["kv_blocks"] = len(self.scheduler.slot_blocks(slot))
      if plan.prefilling[slot]:
        tracer.span_at("prefill", t0_us, t1_us, cat="serving",
                       track=track,
                       args={"tokens": int(plan.num_valid[slot]), **extra})
      elif num_draft is not None and int(num_draft[slot]) > 0:
        tracer.span_at(
            "speculate", t0_us, t1_us, cat="serving", track=track,
            args={"drafted": int(num_draft[slot]),
                  "accepted": int(n_committed[slot]) - 1, **extra})
      else:
        tracer.span_at("decode", t0_us, t1_us, cat="serving",
                       track=track,
                       args={"tok_index": int(plan.tok_index[slot]),
                             **extra})

  def _apply_degradation(self):
    """Feed the ladder this iteration's post-admission load signals and
    apply its level to the scheduler (speculation gate, budget clamp).
    Occupancy is relative to the EFFECTIVE concurrency cap — with
    max_batch < num_slots the batch saturates below full slot count,
    and budget_tight's occupancy gate must still be reachable."""
    itl = self.stats.itl_ewma_s if self.stats is not None else 0.0
    # The autotuner's slot-cap clamp shrinks effective concurrency;
    # occupancy (and with it budget_tight's gate) is judged against
    # the cap actually in force.
    cap = min(self.num_slots, self.scheduler.effective_max_batch)
    self._admission.observe(
        self.scheduler.queue_depth,
        self.scheduler.num_active / cap, itl)
    self.scheduler.spec_enabled = self._admission.speculation_enabled
    self.scheduler.budget_override = (
        self.chunk if self._admission.budget_tightened else 0)

  def _propose_drafts(self, tracer, plan):
    """Run the drafter for one step, tolerating drafter faults: a
    raising drafter degrades to zero drafts for the step (verification
    would reject garbage anyway — a flaky drafter may cost speed,
    never correctness), and a degraded ladder (spec_off and above)
    skips draft compute outright — the first ballast under overload."""
    # Per-SLOT count — the paged plan's tokens are flat [token_budget],
    # so draft_cap (always [num_slots]) carries N for both plan kinds.
    N = plan.draft_cap.shape[0]
    if not self.scheduler.spec_enabled:
      # getattr: observe_skip postdates the drafter protocol — a
      # duck-typed pre-resilience drafter must not crash the engine the
      # first time the ladder reaches spec_off.
      skip = getattr(self.drafter, "observe_skip", None)
      if skip is not None:
        skip(plan)
      return np.zeros((N,), np.int32)
    with tracer.span("serving/draft", cat="serving", track="serving"):
      try:
        histories = self.scheduler.slot_histories(plan)
        draft_tokens, num_draft = self.drafter.propose(plan, histories)
        # Clip (not minimum): a malformed proposal with a NEGATIVE count
        # must clamp to zero drafts, not ride into the token writes.
        num_draft = np.clip(np.asarray(num_draft, np.int32),
                            0, plan.draft_cap)
        # Inside the try: a propose() that returns malformed shapes
        # without raising fails HERE, and must degrade like any other
        # drafter fault rather than crash the step.
        for slot in np.nonzero(num_draft)[0]:
          nd = int(num_draft[slot])
          if self.paged:
            # Flat layout: drafts land at the slot's reserved draft
            # positions (scheduler pass 3) and flip exactly those
            # entries live; unused reservations stay invalid and write
            # to the null block.
            b = int(plan.draft_base[slot])
            plan.tokens[b:b + nd] = draft_tokens[slot, :nd]
            plan.valid[b:b + nd] = True
          else:
            plan.tokens[slot, 1:1 + nd] = draft_tokens[slot, :nd]
      except Exception as e:  # noqa: BLE001 — any drafter fault degrades
        self._drafter_failures += 1
        if not self._drafter_fail_logged:
          self._drafter_fail_logged = True
          get_logger().warning(
              "drafter %s failed (%s: %s); serving continues without "
              "drafts this step (logged once; see "
              "serving/drafter_failures)", type(self.drafter).__name__,
              type(e).__name__, e)
        if tracer.enabled:
          tracer.instant("serving/drafter_failure", cat="serving",
                         track="serving",
                         args={"error": type(e).__name__})
        # Partial draft writes before the failure are harmless: with
        # zero drafts every decode slot's num_valid stays 1, so the
        # written positions are masked garbage the step never reads.
        return np.zeros((N,), np.int32)
    return num_draft

  def _handle_bad_slots(self, plan, slot_ok: np.ndarray) -> List[int]:
    """Post-commit bad-step policy: update streaks, requeue/fail the
    slots the policy quarantines.  Returns the bad slot list."""
    bad = [int(s) for s in
           np.nonzero(~slot_ok & (plan.num_valid > 0))[0]]
    exercised = {int(s) for s in np.nonzero(plan.num_valid)[0]}
    actions = self._bad_policy.judge(self.scheduler.active, bad,
                                     exercised=exercised)
    if not bad:
      return bad
    tracer = trace_lib.get_tracer()
    retries = sum(1 for a in actions.values() if a == BadStepPolicy.RETRY)
    if tracer.enabled:
      tracer.instant(
          "serving/bad_step", cat="serving", track="serving",
          args={"slots": bad, "retries": retries})
    get_logger().warning(
        "bad device step (non-finite logits) on slot(s) %s: %s", bad,
        {s: a for s, a in actions.items()})
    # Paged: snapshot block lists BEFORE requeue/retire return them to
    # the pool — the rows must be zeroed either way (the next owner of a
    # reused block needs the finiteness invariant to hold).
    blocks_by_slot = ({s: self.scheduler.slot_blocks(s) for s in bad}
                      if self.paged else None)
    slot_starts: Dict[int, int] = {}
    cursors = None
    for slot, action in actions.items():
      freed = action in (BadStepPolicy.REQUEUE, BadStepPolicy.FAIL)
      if action == BadStepPolicy.REQUEUE:
        self.scheduler.requeue_slot(slot, reason="bad_step")
      elif action == BadStepPolicy.FAIL:
        self.scheduler.retire_slot(slot, "failed")
      if self.paged:
        # Paged: zero from the committed watermark up, freed or not.
        # The plan's first scheduled position for the slot IS the
        # watermark — no device fetch needed (positions are
        # host-planned in the paged layout) — and every one of the bad
        # step's writes landed at a scheduled position at or above it.
        # Rows below hold real committed K/V; with prefix sharing live
        # a released prefix block may still be mapped by the radix
        # tree or a sibling slot's table, so zeroing below the
        # watermark would corrupt a HEALTHY request's cache.
        slot_starts[slot] = int(plan.positions[plan.base_idx[slot]])
      elif freed:
        slot_starts[slot] = 0
      else:  # RETRY: zero the bad step's uncommitted writes only.
        if cursors is None:  # host sync on the rare bad-step path only
          cursors = jax.device_get(self._cursors)
        slot_starts[slot] = int(cursors[slot])
    if slot_starts and self.paged:
      self._sanitize_paged(slot_starts, blocks_by_slot)
    elif slot_starts:
      self._sanitize_slots(slot_starts)
    if self.stats is not None:
      # Single source of truth: the policy already counted this event.
      self.stats.sync_bad_step_counters(self._bad_policy.counters())
    return bad

  def _sanitize_slots(self, slot_starts: Dict[int, int]) -> None:
    """Zero poisoned slots' K/V from each slot's start row up
    (slot_cache_attend's finiteness invariant: masking zeroes a stale
    row's softmax probability, but the V contraction still touches every
    cache row and ``0 * NaN = NaN``).  Freed slots pass start 0 (the
    next occupant must see a clean slot); retried slots pass their
    committed cursor (the prefix is real — only the bad step's writes
    above it are suspect, and the retry's grant may not cover them
    all)."""
    mask = np.zeros((self.num_slots,), bool)
    start = np.zeros((self.num_slots,), np.int32)
    for slot, row in slot_starts.items():
      mask[slot] = True
      start[slot] = row
    self._kv = self._sanitize_fn(self._kv, mask, start)

  def _sanitize_paged(self, slot_starts: Dict[int, int],
                      blocks_by_slot: Dict[int, list]) -> None:
    """Paged twin of :meth:`_sanitize_slots`: map each poisoned slot's
    (pre-release) block list to per-block start rows and zero with the
    same jitted program (dim 0 = pool blocks here).  The null block is
    always included — a NaN-params step poisons it through the padding
    writes, and every slot's gather can touch it."""
    bs = self.block_size
    mask = np.zeros((self.num_blocks,), bool)
    start = np.zeros((self.num_blocks,), np.int32)
    mask[kv_lib.NULL_BLOCK] = True
    for slot, pos in slot_starts.items():
      for j, blk in enumerate(blocks_by_slot.get(slot, ())):
        if (j + 1) * bs <= pos:
          continue  # wholly below the committed watermark: rows are real
        row = max(0, pos - j * bs)
        # A block CAN appear twice now that prefix sharing is real
        # (serving/prefix_cache.py) — but only a shared PREFIX block,
        # which sits wholly below every sharer's watermark and is
        # skipped above.  Two bad slots listing one block therefore
        # agree it needs zeroing; keep the LOWEST start defensively.
        start[blk] = row if not mask[blk] else min(start[blk], row)
        mask[blk] = True
    # Zeroed content must never satisfy a future prefix match.  Purely
    # defensive — registration is commit-gated, so a masked
    # (above-watermark) block is never in the tree — but the purge is
    # cheap and makes the invariant unconditional.
    self.scheduler.invalidate_cached_blocks(
        int(b) for b in np.nonzero(mask)[0] if b != kv_lib.NULL_BLOCK)
    self._kv = self._sanitize_fn(self._kv, mask, start)

  def step(self) -> List[FinishedRequest]:
    """One engine iteration: [degrade ->] plan -> [draft ->] fused
    device step -> commit [-> bad-step policy].  Returns the requests
    that retired this iteration (empty when idle), expiries and
    cancellations included."""
    tracer = trace_lib.get_tracer()
    if self._autotuner is not None:
      # Knob moves land HERE — strictly between fused-step dispatches,
      # steering the plan built just below (compile-once: data only).
      self._autotuner.on_step(self._steps)
    with tracer.span("serving/plan", cat="serving", track="serving"):
      plan = self.scheduler.plan_step()
    if self._admission is not None:
      # Observe AFTER admission: the ladder's queue signal is the
      # backlog this step could NOT absorb — a one-shot burst that
      # admission fully drains must not read as overload (it would
      # falsely shed follow-up submits for the hysteresis window).
      # The resulting gates steer the NEXT plan; one step of lag is
      # the price of measuring the right signal.
      self._apply_degradation()
    if plan is None:
      # No device work, but plan-time expiries may have retired
      # requests (e.g. every queued request's deadline passed).
      return self.scheduler.take_finished()
    t0 = time.monotonic()
    if self._watchdog is not None:
      self._watchdog.arm(self._steps)
    xla_ctx = None
    if self._pending_xla_dir is not None:
      # Deep capture armed a device profile for the step AFTER the
      # breach (observability.slo.capture_xla): the anomaly's immediate
      # aftermath is the timeline worth keeping.
      xla_dir, self._pending_xla_dir = self._pending_xla_dir, None
      xla_ctx = tracer.xla_trace(xla_dir)
      xla_ctx.__enter__()
    drafted = accepted = 0
    slot_ok = None
    try:
      if self.drafter is not None:
        # Propose BEFORE the token block gains drafts: the draft
        # model's mirror call needs the same plan the target sees.
        num_draft = self._propose_drafts(tracer, plan)
        t0_us = tracer.now_us()
        if self.paged:
          base_last = (plan.base_idx + plan.num_valid - 1).astype(np.int32)
          step_args = (
              self.params, self._kv, plan.tokens, plan.slot_ids,
              plan.positions, plan.valid, plan.block_tables, base_last,
              plan.draft_base, num_draft, plan.num_valid > 0, plan.keys,
              plan.tok_index, plan.temperature, plan.top_k, plan.top_p)
          self._note_step_specs(step_args)
          out = self._step_fn(*step_args)
          if self._resilient:
            committed, n_committed, ok_dev, self._kv = out
            slot_ok = jax.device_get(ok_dev)
          else:
            committed, n_committed, self._kv = out
        else:
          step_args = (
              self.params, self._kv, self._cursors, plan.tokens,
              plan.num_valid + num_draft, num_draft, plan.reset,
              plan.keys, plan.tok_index, plan.temperature, plan.top_k,
              plan.top_p)
          self._note_step_specs(step_args)
          out = self._step_fn(*step_args)
          if self._resilient:
            committed, n_committed, ok_dev, self._kv, self._cursors = out
            slot_ok = jax.device_get(ok_dev)
          else:
            committed, n_committed, self._kv, self._cursors = out
        # The step's ONE designated token fetch: explicit (device_get),
        # so it stays visible — and legal — under
        # jax.transfer_guard_device_to_host("disallow"); any OTHER
        # device->host crossing in this loop is a bug the guard (and
        # epl-lint's host-sync rule) catches.
        committed = jax.device_get(committed)
        n_committed = jax.device_get(n_committed)
        t1_us = tracer.now_us()
        tracer.span_at("serving/device_step", t0_us, t1_us,
                       cat="serving", track="serving")
        self._trace_slot_spans(tracer, plan, t0_us, t1_us,
                               num_draft, n_committed)
        with tracer.span("serving/commit", cat="serving",
                         track="serving"):
          finished = self.scheduler.commit(committed, n_committed,
                                           slot_ok=slot_ok)
          self.drafter.observe_commit(self._cursors)
        # Stats count only slots whose verdict committed: a bad slot's
        # n_committed is NaN-logit garbage and its drafts are re-spent
        # on the retry — counting them would double/poison the
        # acceptance-rate samples under chaos.
        ok = np.ones(num_draft.shape, bool) if slot_ok is None else slot_ok
        speculated = (num_draft > 0) & ok
        drafted = int(num_draft[ok].sum())
        accepted = int((n_committed[speculated] - 1).sum())
      else:
        t0_us = tracer.now_us()
        if self.paged:
          last_idx = (plan.base_idx + plan.num_valid - 1).astype(np.int32)
          step_args = (
              self.params, self._kv, plan.tokens, plan.slot_ids,
              plan.positions, plan.valid, plan.block_tables, last_idx,
              plan.num_valid > 0, plan.keys, plan.tok_index,
              plan.temperature, plan.top_k, plan.top_p)
          self._note_step_specs(step_args)
          out = self._step_fn(*step_args)
          if self._resilient:
            nxt, ok_dev, self._kv = out
            slot_ok = jax.device_get(ok_dev)
          else:
            nxt, self._kv = out
        else:
          step_args = (
              self.params, self._kv, self._cursors, plan.tokens,
              plan.num_valid, plan.reset, plan.keys, plan.tok_index,
              plan.temperature, plan.top_k, plan.top_p)
          self._note_step_specs(step_args)
          out = self._step_fn(*step_args)
          if self._resilient:
            nxt, ok_dev, self._kv, self._cursors = out
            slot_ok = jax.device_get(ok_dev)
          else:
            nxt, self._kv, self._cursors = out
        # Designated fetch (see the speculative branch above).
        nxt = jax.device_get(nxt)
        t1_us = tracer.now_us()
        tracer.span_at("serving/device_step", t0_us, t1_us,
                       cat="serving", track="serving")
        self._trace_slot_spans(tracer, plan, t0_us, t1_us)
        with tracer.span("serving/commit", cat="serving",
                         track="serving"):
          finished = self.scheduler.commit(nxt, slot_ok=slot_ok)
    finally:
      if self._watchdog is not None:
        self._watchdog.disarm()
      if xla_ctx is not None:
        xla_ctx.__exit__(None, None, None)
    if slot_ok is not None:
      self._handle_bad_slots(plan, slot_ok)
      # Quarantine retirements ("failed") belong to this iteration.
      finished.extend(self.scheduler.take_finished())
    self._steps += 1
    # Compile sentinel: one host int compare per step; the signature
    # thunk only runs on the (rare) recompile path.
    self._compile_sentinel.check(
        signature_fn=lambda: self._describe_signature(plan))
    dt = time.monotonic() - t0
    # Device introspection runs BELOW the dt cut, like every other
    # publish path: the warmup capture's AOT compile and the HBM
    # gauges' per-device memory_stats host RPC must never inflate the
    # step_time_s sample that feeds the ITL EWMA the admission ladder
    # and SLO rules act on.
    if self._pending_step_specs is not None:
      # Warmup cost card (observability/device.py): introspect the twin
      # through the AOT surface with the specs snapshotted above.  The
      # jit call cache is untouched (the sentinel above stays silent —
      # pinned) and no live buffer is read.
      specs, self._pending_step_specs = self._pending_step_specs, None
      self._introspector.capture_twin(
          self._twin_label, self._step_fn, specs,
          compile_count=self._compile_sentinel.cache_size() or 0,
          meta=self._twin_meta())
    if (self._introspector is not None
        and (self._steps == 1
             or self._steps % _STATS_PUBLISH_EVERY == 0)):
      # HBM watermark gauges on the existing stats cadence (plus once
      # right after warmup so short episodes still carry a sample):
      # observability/device/* registry records + Perfetto counters;
      # the SLO monitor sees them through the registry sink (or
      # directly on registry-less engines).
      self._introspector.publish_hbm(self._steps, registry=self.registry,
                                     monitor=self._slo)
    # Throughput/ITL samples count COMMITTED tokens only: a bad slot's
    # planned tokens never committed and the identical work is re-fed
    # next step — counting both would double prefill/decode throughput
    # under chaos (same rule as the drafted/accepted exclusion above).
    if slot_ok is None or bool(slot_ok.all()):
      pf_tokens, dc_tokens = plan.prefill_tokens, plan.decode_tokens
    else:
      ok = (plan.num_valid > 0) & slot_ok
      pf_tokens = int(plan.num_valid[ok & plan.prefilling].sum())
      dc_tokens = int((ok & ~plan.prefilling).sum())
    if tracer.enabled:
      tracer.counter("serving/active_slots", plan.active_slots)
      if self.paged:
        # Block-pool occupancy rides the counter tracks next to
        # active_slots, so Perfetto shows pool pressure against load.
        tracer.counter("serving/kv_blocks_used",
                       self.scheduler.kv_blocks_used)
        tracer.counter("serving/kv_blocks_free",
                       self.scheduler.kv_blocks_free)
        if self.prefix_caching:
          # Prefix-cache effectiveness next to pool pressure: hit/miss
          # and reuse counters plus the tree's resident footprint.
          tracer.counter("serving/prefix_hits",
                         self.scheduler.prefix_hits)
          tracer.counter("serving/prefix_misses",
                         self.scheduler.prefix_misses)
          tracer.counter("serving/prefix_blocks_reused",
                         self.scheduler.prefix_blocks_reused)
          tracer.counter("serving/prefix_evictions",
                         self.scheduler.prefix_evictions)
          tracer.counter("serving/prefix_cached_blocks",
                         self.scheduler.prefix_cached_blocks)
      if drafted:
        tracer.counter("serving/drafted_tokens", drafted)
        tracer.counter("serving/accepted_tokens", accepted)
    if self.stats is not None:
      self.stats.note_step(
          active_slots=plan.active_slots, num_slots=self.num_slots,
          prefill_tokens=pf_tokens,
          decode_tokens=dc_tokens, step_time_s=dt,
          drafted_tokens=drafted, accepted_tokens=accepted)
      if self.paged:
        self.stats.note_blocks(self.scheduler.kv_blocks_free,
                               self.scheduler.kv_blocks_used,
                               self.scheduler.kv_fragmentation,
                               self.scheduler.preemptions,
                               self.scheduler.proactive_preemptions)
        if self.prefix_caching:
          self.stats.note_prefix(self.scheduler.prefix_hits,
                                 self.scheduler.prefix_misses,
                                 self.scheduler.prefix_blocks_reused,
                                 self.scheduler.prefix_evictions,
                                 self.scheduler.prefix_cached_blocks)
    if (self.metrics_writer is not None or self.registry is not None
        or self._slo is not None):
      record = {
          "active_slots": plan.active_slots,
          "slot_occupancy": plan.active_slots / self.num_slots,
          "prefill_tokens": pf_tokens,
          "decode_tokens": dc_tokens,
          "step_time_s": dt,
      }
      if self.paged:
        # The block-pool gauges (ROADMAP item 1 satellite): pool
        # occupancy, internal fragmentation, and preemption count under
        # the serving/* schema.
        record["kv_blocks_free"] = self.scheduler.kv_blocks_free
        record["kv_blocks_used"] = self.scheduler.kv_blocks_used
        record["kv_fragmentation"] = self.scheduler.kv_fragmentation
        record["preemptions"] = self.scheduler.preemptions
        record["proactive_preemptions"] = (
            self.scheduler.proactive_preemptions)
        if self.prefix_caching:
          # Prefix-cache counters under the same serving/* schema
          # (cumulative, like preemptions).
          record["prefix_hits"] = self.scheduler.prefix_hits
          record["prefix_misses"] = self.scheduler.prefix_misses
          record["prefix_blocks_reused"] = (
              self.scheduler.prefix_blocks_reused)
          record["prefix_evictions"] = self.scheduler.prefix_evictions
          record["prefix_cached_blocks"] = (
              self.scheduler.prefix_cached_blocks)
      if self.drafter is not None:
        record["drafted_tokens"] = drafted
        record["accepted_tokens"] = accepted
        record["drafter_failures"] = self._drafter_failures
      if self._resilient:
        record["queue_depth"] = self.scheduler.queue_depth
        record["degraded_level"] = self._admission.level
        record["shed"] = self._admission.shed_total
        record.update(self._bad_policy.counters())
        if self.stats is not None:
          # The cumulative good-counter partner of "shed", so burn-rate
          # rules (bad="shed", good="finished_requests") evaluate on
          # every per-step record — not only on the sparse percentile
          # rollups — and an overloaded engine's own monitor breaches
          # while the overload is still happening.
          record["finished_requests"] = float(
              self.stats.finished_requests)
      if self._autotuner is not None:
        # Actuator evidence rides the existing serving/* schema: the
        # current tune level and cumulative actuation count per step.
        record["autotune_level"] = self._autotuner.level
        record["autotune_actuations"] = self._autotuner.actuations
      if self.metrics_writer is not None:
        # Legacy flat keys (pre-registry callers depend on them).
        self.metrics_writer.write(self._steps, record)
      if self.registry is not None:
        # The SLO monitor rides the registry as a sink (attach above) —
        # publishing once feeds the sinks AND the rules.
        self.registry.publish(self._steps, record, "serving")
      elif self._slo is not None:
        # Registry-less engine: feed the monitor the same namespaced
        # record directly (host scalars only — no added syncs), through
        # the validated schema helper rather than an ad-hoc key literal.
        self._slo.observe(
            self._steps,
            MetricRegistry.namespaced(SERVING_NAMESPACE, record))
    if (self.stats is not None
        and self._steps % _STATS_PUBLISH_EVERY == 0
        and (self.registry is not None or self._slo is not None)):
      # Periodic percentile rollup so latency SLO rules stay LIVE on a
      # long-serving engine (_STATS_PUBLISH_EVERY above).
      if self.registry is not None:
        self.stats.publish(self.registry, self._steps)
      else:
        self._slo.observe(
            self._steps,
            MetricRegistry.namespaced(SERVING_NAMESPACE,
                                      self.stats.summary()))
    return finished

  def run(self, max_steps: Optional[int] = None
          ) -> Dict[Any, np.ndarray]:
    """Drive until the queue drains (or ``max_steps``); returns
    ``{uid: prompt+generated}`` for every request finished during the
    call (finish reasons: ``self.finished[uid].finish_reason``)."""
    out: Dict[Any, np.ndarray] = {}
    steps = 0
    while self.has_work and (max_steps is None or steps < max_steps):
      for fin in self.step():
        out[fin.uid] = fin.tokens
      steps += 1
    if self.registry is not None and self.stats is not None:
      # End-of-drive rollup (tokens/s, TTFT/ITL percentiles, occupancy,
      # speculation + resilience counters) under the serving/* namespace.
      self.stats.publish(self.registry, self._steps)
    return out
