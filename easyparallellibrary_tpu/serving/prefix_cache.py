"""Copy-on-write prefix caching over the paged KV pool — the radix
tree that turns shared prompt prefixes into shared blocks.

Heavy real traffic is dominated by shared prefixes (system prompts,
few-shot templates, multi-turn chats replaying their whole history every
turn), yet a cold admission pays full prefill for all of it.  The paged
cache (serving/kv_cache.py) already exposes the seam this needs: K/V
lives in fixed-size blocks behind per-slot block tables, and a block
whose covering token prefix matches is BIT-IDENTICAL between requests —
K/V content at position p depends only on the params and tokens
``0..p`` — so it can be shared by reference instead of recomputed.

:class:`PrefixCache` is a content-addressed radix tree over full prompt
blocks.  Each node holds one pool block plus the exact token bytes that
filled it; a node's path from the root spells the block-aligned token
prefix whose K/V the block carries.  On admission the scheduler walks
the tree with the request's prefix (:meth:`match`): every matched block
maps into the request's block table by reference (``refcount++`` — no
device copy, no extra compiled program), the prompt cursor jumps past
the matched region, and chunked prefill runs ONLY for the unmatched
tail.  A fully warm prefix therefore skips prefill entirely but for the
final partial block, and TTFT collapses toward a single fused step.

Copy-on-write discipline — why shared blocks are never written:
matching is capped at ``(len(prefix) - 1) // block_size`` FULL blocks,
i.e. strictly before the last prompt token.  The divergent or
partially-filled block is always freshly allocated and rebuilt by
normal chunked prefill (COW-by-recompute: recomputing up to one block
is cheaper than a device-side block copy and keeps the fused step —
and its compile count of 1 — untouched).  Prefill writes then start at
``prompt_pos = matched * block_size`` and decode writes at positions
``>= len(prompt)``, both strictly past every shared block, so a shared
mapping is read-only by construction.  ``NULL_BLOCK`` is never
registered: it is the pool's trash row and its content is garbage by
design (kv_cache.py).

Session persistence: the tree holds its OWN reference on every block it
registers, so a retired request's blocks stay resident after its slot
releases them — turn N+1 of a chat replays its history against warm
blocks.  Residency is bounded two ways: ``session_ttl_s`` expires
entries not touched within the TTL, and ``max_cached_blocks`` caps the
tree's total footprint (LRU beyond it).  Eviction integrates with the
scheduler's exhaustion path (scheduler.py ``_ensure_blocks``):
cached-but-unmapped blocks (tree refcount is the last reference) are
reclaimed via :meth:`evict_for_space` BEFORE any live slot is
preempted, so warm cache never costs a running request its progress.

LRU invariant: every lookup/registration touches its whole root→node
path, deepest node first, so an ancestor is always at least as recent
as its descendants and the LRU front is always a leaf — eviction pops
leaves without tree surgery, and one front-to-back sweep unwinds whole
chains (a parent freed of its last child appears later in the same
sweep, being newer).

The router shares this module's content hashing
(:func:`block_prefix_keys`) so fleet dispatch and local block reuse
agree on what "same prefix" means — a warm prefix routes to the replica
already holding its blocks (docs/serving.md "Prefix caching").
"""

from __future__ import annotations

import time
import zlib
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional

import numpy as np

from easyparallellibrary_tpu.serving.kv_cache import (
    BlockAllocator, NULL_BLOCK)

# Router affinity probes at most this many block-aligned prefix depths
# (deepest first).  A cap keeps the per-submit work and the affinity
# LRU's key fan-out bounded on very long prompts; eight blocks of
# shared prefix is already far past where affinity routing stops
# mattering (the replica either has the template or it does not).
AFFINITY_MAX_BLOCKS = 8

# Distinct crc32 chain seeds so a block-aligned key can never collide
# with a short-prompt fallback key of identical bytes.
_BLOCK_SALT = zlib.crc32(b"epl/prefix/block")
_SHORT_SALT = zlib.crc32(b"epl/prefix/short")


def _version_salt(base: int, version: int) -> int:
  """Fold a checkpoint version into a chain seed.  Version 0 (the
  pre-rollout default) keeps the bare salt, so single-version fleets
  hash byte-identically to every build before versioning existed."""
  if version == 0:
    return base
  return zlib.crc32(np.asarray([version], np.int64).tobytes(), base)


def block_prefix_keys(prompt, block_size: int,
                      max_blocks: int = AFFINITY_MAX_BLOCKS,
                      version: int = 0) -> List[int]:
  """Content keys for every block-aligned prefix depth of ``prompt``,
  shallowest first — the SHARED hashing between the radix tree's block
  granularity and the router's affinity map (router.py).

  ``keys[d-1]`` covers tokens ``[0, d * block_size)``; each key chains
  the previous depth's crc32 (incremental — hashing all depths costs
  one pass over the prefix).  Only FULL blocks strictly before the last
  token get a key, mirroring :meth:`PrefixCache.match`'s cap: a depth
  the tree can never match is a depth not worth routing on.  A prompt
  too short for any full block falls back to one whole-prompt key under
  a distinct salt, preserving exact-duplicate affinity for tiny
  prompts.  Deterministic and process-stable (crc32, not Python's
  salted ``hash``), like every other cross-replica key in serving/.

  ``version`` scopes every key to a checkpoint version (blue/green
  rollout, serving/rollout.py): the same prompt under version N and
  N+1 yields DISJOINT keys, so the router's affinity map never sends a
  green-pinned request to the replica that warmed this prefix under
  blue weights.  Version 0 hashes identically to the unversioned past.
  """
  prompt = np.ascontiguousarray(np.asarray(prompt, np.int32).reshape(-1))
  full = max(0, int(prompt.size) - 1) // block_size if block_size > 0 else 0
  keys: List[int] = []
  crc = _version_salt(_BLOCK_SALT, version)
  for d in range(min(full, max_blocks)):
    crc = zlib.crc32(prompt[d * block_size:(d + 1) * block_size].tobytes(),
                     crc)
    keys.append(crc)
  if not keys:
    keys.append(zlib.crc32(prompt.tobytes(),
                           _version_salt(_SHORT_SALT, version)))
  return keys


class _Node:
  """One cached block: its chained content digest (the child key in its
  parent), the exact tokens that filled it (collision verification),
  the pool block carrying their K/V, and its place in the tree."""

  __slots__ = ("key", "tokens", "block", "parent", "children",
               "last_touch")

  def __init__(self, key: int, tokens: Optional[np.ndarray], block: int,
               parent: "_Node", now: float):
    self.key = key
    self.tokens = tokens
    self.block = block
    self.parent = parent
    self.children: Dict[int, "_Node"] = {}
    self.last_touch = now


class PrefixCache:
  """Content-addressed radix tree over prompt blocks (module docstring).

  Children are keyed by a CHAINED per-block content digest, cached on
  the node at registration time — the same crc32 chain as
  :func:`block_prefix_keys`, so the tree's child key at depth ``d`` IS
  the router's affinity key for that prefix depth.  An admission walk
  therefore hashes each block's tokens once (crc32 straight over the
  int32 buffer — no byte-string key construction, no long-key dict
  hashing) and looks children up by int.  crc32 is not
  collision-free and a collision serving wrong K/V would break the
  bit-exactness contract, so a digest hit is verified against the
  node's stored tokens (one flat ``np.array_equal`` — a memcmp, still
  cheaper than keying the dict by the bytes themselves); a mismatch
  reads as a miss at that depth (match) or stops descent (register —
  the first writer keeps the canonical digest).  The tree owns one
  allocator reference per registered block (dropped on eviction /
  expiry / invalidation); mapping a match into a slot adds the slot's
  own reference on top, so a block is never freed while any table still
  points at it.

  Counters (``hits``/``misses``/``blocks_reused``/``evictions``) are
  cumulative and feed ``ServingStats`` + the ``serving/prefix_*``
  counter tracks (profiler/serving.py, engine.py).
  """

  def __init__(self, allocator: BlockAllocator, block_size: int,
               session_ttl_s: float = 0.0, max_cached_blocks: int = 0,
               clock: Callable[[], float] = time.monotonic,
               version: int = 0):
    if block_size < 1:
      raise ValueError(f"block_size must be >= 1: {block_size}")
    if session_ttl_s < 0:
      raise ValueError(f"session_ttl_s must be >= 0: {session_ttl_s}")
    if max_cached_blocks < 0:
      raise ValueError(
          f"max_cached_blocks must be >= 0: {max_cached_blocks}")
    self.allocator = allocator
    self.block_size = block_size
    self.session_ttl_s = session_ttl_s
    self.max_cached_blocks = max_cached_blocks
    self.clock = clock
    # Checkpoint-version isolation (blue/green rollout): the digest
    # chain is SEEDED with the version-folded salt (exactly
    # block_prefix_keys' seed), so K/V cached under checkpoint N can
    # NEVER satisfy a match under N+1 — identical tokens under
    # different weights are different content (silent wrong-weights
    # reuse would be a correctness bug the moment two versions
    # coexist).  Version 0 keeps the bare salt, digest-identical to
    # the unversioned past.
    self.version = int(version)
    self._chain_seed = _version_salt(_BLOCK_SALT, self.version)
    self._root = _Node(0, None, NULL_BLOCK, None, 0.0)  # sentinel
    # Insertion/touch-ordered node registry: front = least recent.  The
    # deepest-first path-touch discipline (module docstring) keeps the
    # front a leaf, so LRU eviction never needs tree surgery.
    self._lru: "OrderedDict[_Node, None]" = OrderedDict()
    self.hits = 0
    self.misses = 0
    self.blocks_reused = 0
    self.evictions = 0

  @property
  def num_cached_blocks(self) -> int:
    return len(self._lru)

  def _touch_path(self, path: List[_Node], now: float) -> None:
    # Deepest first, so every ancestor ends NEWER than its descendants
    # (the leaf-at-LRU-front invariant).
    for node in reversed(path):
      node.last_touch = now
      self._lru.move_to_end(node)

  def _remove_subtree(self, node: _Node) -> int:
    """Drop ``node`` and every descendant, releasing the tree's block
    references.  Descendants are unlinked too (not re-rooted): their
    content is only addressable through this path."""
    stack, order = [node], []
    while stack:
      n = stack.pop()
      order.append(n)
      stack.extend(n.children.values())
    for n in reversed(order):  # children first, so parents unlink empty
      del n.parent.children[n.key]
      del self._lru[n]
      self.allocator.decref(n.block)
    self.evictions += len(order)
    return len(order)

  # ---------------------------------------------------------------- match

  def match(self, prefix: np.ndarray) -> List[int]:
    """Walk the tree with ``prefix``; return the matched blocks (root
    order), each carrying ONE fresh reference for the caller's block
    table.  Matching is capped strictly before the last token — the
    divergent/partial block is always rebuilt by prefill, never shared
    (COW rule, module docstring).  Counts one hit (any block matched)
    or one miss per call."""
    prefix = np.ascontiguousarray(np.asarray(prefix, np.int32)
                                  .reshape(-1))
    bs = self.block_size
    limit = max(0, int(prefix.size) - 1) // bs
    node, path, crc = self._root, [], self._chain_seed
    for d in range(limit):
      chunk = prefix[d * bs:(d + 1) * bs]
      crc = zlib.crc32(chunk, crc)   # chained digest, no bytes copy
      child = node.children.get(crc)
      if child is None or not np.array_equal(child.tokens, chunk):
        break  # unknown depth, or a crc collision: never serve it
      path.append(child)
      node = child
    if not path:
      self.misses += 1
      return []
    self.hits += 1
    self.blocks_reused += len(path)
    now = self.clock()
    self._touch_path(path, now)
    for n in path:
      self.allocator.incref(n.block)
    return [n.block for n in path]

  # ------------------------------------------------------------- register

  def register(self, tokens: np.ndarray, num_blocks: int,
               blocks: List[int]) -> int:
    """Insert the first ``num_blocks`` full blocks of ``tokens`` (backed
    by ``blocks[:num_blocks]``) into the tree, increffing each NEWLY
    inserted block.  The caller guarantees those blocks hold committed,
    fully-written K/V for exactly those tokens (scheduler.py registers
    at commit watermarks only).  On content collision the EXISTING node
    wins — first writer keeps the canonical block; the duplicate stays
    privately owned by its slot and frees on retirement.  Returns the
    number of new insertions."""
    tokens = np.ascontiguousarray(np.asarray(tokens, np.int32)
                                  .reshape(-1))
    bs = self.block_size
    num_blocks = min(num_blocks, int(tokens.size) // bs, len(blocks))
    node, path, added = self._root, [], 0
    now = self.clock()
    crc = self._chain_seed
    for d in range(num_blocks):
      chunk = tokens[d * bs:(d + 1) * bs]
      crc = zlib.crc32(chunk, crc)   # digest cached on the node below
      child = node.children.get(crc)
      if child is not None and not np.array_equal(child.tokens, chunk):
        # crc collision under this parent: the existing node keeps the
        # canonical digest; the newcomer's blocks stay privately owned
        # by their slot (same first-writer-wins rule as content
        # collisions), and nothing below this depth is addressable.
        break
      if child is None:
        blk = blocks[d]
        if blk == NULL_BLOCK:
          break  # trash row: garbage content, never shareable
        self.allocator.incref(blk)
        child = _Node(crc, chunk.copy(), blk, node, now)
        node.children[crc] = child
        self._lru[child] = None
        added += 1
      path.append(child)
      node = child
    if path:
      self._touch_path(path, now)
    if self.max_cached_blocks > 0:
      self._enforce_budget()
    return added

  def _enforce_budget(self) -> None:
    # Over-budget: shed least-recent leaves regardless of refcount (a
    # still-mapped block just loses its tree entry; the slot's own
    # reference keeps it alive).
    while len(self._lru) > self.max_cached_blocks:
      front = next(iter(self._lru))
      self._remove_subtree(front)

  # ------------------------------------------------------------- eviction

  def evict_for_space(self, need: int) -> int:
    """Free up to ``need`` pool blocks by dropping least-recent cached
    entries whose tree reference is the LAST one (unmapped by any slot
    — dropping them returns the block to the free list immediately).
    Mapped entries are skipped: a shared block must never be freed
    while a table points at it.  One front-to-back sweep suffices — a
    parent freed of its last child is newer than the child, so the
    sweep reaches it afterwards.  Returns blocks actually freed; the
    scheduler tries this BEFORE preempting any live slot."""
    freed = 0
    for node in list(self._lru):
      if freed >= need:
        break
      if node.children or self.allocator.refcount(node.block) != 1:
        continue
      self._remove_subtree(node)
      freed += 1
    return freed

  def expire(self, now: Optional[float] = None) -> int:
    """Drop every entry idle past ``session_ttl_s`` (0 = never).  The
    LRU front is the least-recent node, so expiry pops from the front
    until it meets a live entry — O(expired), not O(tree).  Called by
    the scheduler each plan step."""
    if self.session_ttl_s <= 0 or not self._lru:
      return 0
    now = self.clock() if now is None else now
    deadline = now - self.session_ttl_s
    dropped = 0
    while self._lru:
      front = next(iter(self._lru))
      if front.last_touch > deadline:
        break
      dropped += self._remove_subtree(front)
    return dropped

  def invalidate_blocks(self, blocks: Iterable[int]) -> int:
    """Remove every entry backed by one of ``blocks`` (plus its subtree
    — descendants become unreachable once the path breaks).  The
    resilient engine calls this for blocks its sanitize pass zeroed
    (engine.py ``_handle_bad_slots``): zeroed K/V must never satisfy a
    future match.  Defensive — commit-gated registration means a bad
    step's writes land past every registered block — but cheap
    insurance against serving garbage."""
    bad = set(int(b) for b in blocks)
    if not bad:
      return 0
    doomed = [n for n in self._lru if n.block in bad]
    removed = 0
    for node in doomed:
      if node in self._lru:  # not already gone with an ancestor's subtree
        removed += self._remove_subtree(node)
    return removed

  def clear(self) -> int:
    """Drop everything (tests + engine shutdown): releases every tree
    reference so ``kv_blocks_used`` falls back to the live slots'."""
    removed = 0
    for child in list(self._root.children.values()):
      removed += self._remove_subtree(child)
    return removed

  def __repr__(self):
    return (f"PrefixCache(blocks={self.num_cached_blocks}, "
            f"hits={self.hits}, misses={self.misses}, "
            f"reused={self.blocks_reused}, evictions={self.evictions})")
