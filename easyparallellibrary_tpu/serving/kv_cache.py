"""KV cache layouts for continuous-batching inference — contiguous
slots and the paged block pool.

Two memory plans share this module.  The CONTIGUOUS layout is vLLM's
insight shrunk to one level: instead of allocating a fresh
``[B, max_seq_len, H, hd]`` cache per ``generate()`` call (models/gpt.py
legacy decode), ONE cache of ``num_slots`` request slots is allocated at
engine start and reused for the life of the server.  The PAGED layout
(``serving.paged.*``; docs/serving.md "Paged KV cache") is the full
two-level design: K/V lives in a pool of fixed-size blocks
(:func:`allocate_paged_kv_cache`), each slot owns a grown-on-demand
block list behind an on-device block table, and a host-side
:class:`BlockAllocator` (free list + refcounts) turns retired requests'
worst-case tail reservations into extra concurrent requests.  A slot is the unit of admission: a request owns
exactly one slot from admission to retirement, its write offset tracked
by a per-slot cursor (the cursor *vector* models/gpt.py's
``slot_cache_attend`` consumes).  Eviction is free-list bookkeeping on
the host — no device work: stale K/V left by the previous occupant is
never attendable because the mask only exposes positions the current
request's own tokens have written (see slot_cache_attend's docstring;
tests/test_serving.py asserts the no-leakage property).

Placement: the cache is materialized directly into its sharded layout on
the mesh (same jit-with-out-shardings trick as
``create_sharded_train_state``), heads sharded over the tensor-parallel
``model`` axis so each TP shard holds exactly the head slice its
column-parallel QKV produces — cache reads/writes stay local, and GSPMD
inserts no resharding around the attention.

Layout note: the per-slot length is ``max_seq_len + chunk``
(:func:`cache_length`), one chunk longer than any request can grow.  The
fused step unconditionally writes a full ``chunk``-wide K/V window at
every slot's cursor (static shapes — masking, not shape, expresses
partial validity), so the window must never clamp against the end of the
buffer; ``jax.lax.dynamic_update_slice`` would otherwise shift the write
and corrupt earlier positions.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from easyparallellibrary_tpu import constants

# Pool index of the reserved null/trash block: block tables default-fill
# with it (unallocated table slots resolve there), and the fused step's
# padding-token writes land there.  Never handed out by BlockAllocator;
# its rows are garbage-but-FINITE by construction (they only ever receive
# real projection outputs), which is all slot/paged attention requires of
# unattendable rows — and the resilient engine's sanitize pass zeroes it
# alongside any poisoned slot, since a NaN-params step poisons padding
# writes too.
NULL_BLOCK = 0


def cache_length(cfg, chunk: int) -> int:
  """Per-slot cache length: ``max_seq_len`` plus one chunk of slack so
  the fused step's fixed-width write window never clamps (module
  docstring)."""
  return cfg.max_seq_len + int(chunk)


def kv_spec() -> P:
  """PartitionSpec of one cache leaf ``[num_slots, Lc, H, hd]``: heads
  over the TP axis, slots/positions replicated."""
  return P(None, None, constants.MODEL_AXIS, None)


def kv_cache_shardings(cfg, mesh: Optional[Mesh]):
  """(kv_shardings_pytree, cursor_sharding) matching
  :func:`allocate_kv_cache`'s structure, or (None, None) without a mesh.

  Heads shard over ``model`` only when the cache's head count actually
  divides the axis; otherwise the cache is replicated (a 1-sized or
  absent model axis degrades to replication anyway).
  """
  if mesh is None:
    return None, None
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  tp = sizes.get(constants.MODEL_AXIS, 1)
  spec = kv_spec() if tp > 1 and cfg.num_heads % tp == 0 else P()
  leaf = NamedSharding(mesh, spec)
  kv = {f"block_{i}": {"attn": {"cached_key": leaf, "cached_value": leaf}}
        for i in range(cfg.num_layers)}
  return kv, NamedSharding(mesh, P())


def allocate_kv_cache(cfg, num_slots: int, chunk: int,
                      mesh: Optional[Mesh] = None
                      ) -> Tuple[Dict[str, Any], jax.Array]:
  """Preallocate the slot cache for a GPT config.

  Returns ``(kv, cursors)``: ``kv`` is a pytree shaped exactly like the
  ``"cache"`` collection GPT's slot-mode decode reads/writes
  (``{"block_i": {"attn": {"cached_key"/"cached_value":
  [num_slots, Lc, H, hd]}}}``), ``cursors`` the int32 ``[num_slots]``
  write-offset vector (all zero).  With a mesh, every leaf materializes
  already sharded (jit + out_shardings — no host-memory spike, no
  transfer).
  """
  if num_slots < 1:
    raise ValueError(f"num_slots must be >= 1: {num_slots}")
  if chunk < 1:
    raise ValueError(f"prefill chunk must be >= 1: {chunk}")
  if cfg.d_model % cfg.num_heads:
    raise ValueError(f"d_model {cfg.d_model} must divide into "
                     f"{cfg.num_heads} heads")
  H, hd = cfg.num_heads, cfg.d_model // cfg.num_heads
  Lc = cache_length(cfg, chunk)
  shape = (num_slots, Lc, H, hd)
  kv_shardings, cur_sharding = kv_cache_shardings(cfg, mesh)

  def build():
    leaf = lambda: jnp.zeros(shape, cfg.dtype)
    kv = {f"block_{i}": {"attn": {"cached_key": leaf(),
                                  "cached_value": leaf()}}
          for i in range(cfg.num_layers)}
    return kv, jnp.zeros((num_slots,), jnp.int32)

  if kv_shardings is None:
    # epl-lint: disable=recompile-hazard — allocation-time one-shot:
    # runs once per engine construction (jit materializes the zeros
    # DIRECTLY in their layout, never through a host buffer)
    return jax.jit(build)()
  # epl-lint: disable=recompile-hazard — same one-shot allocation, mesh
  # path (out_shardings places each leaf as it is created)
  return jax.jit(build, out_shardings=(kv_shardings, cur_sharding))()


def cache_bytes(cfg, num_slots: int, chunk: int) -> int:
  """Total cache footprint in bytes (both K and V, all layers) — the
  number the admission knobs trade against HBM."""
  H, hd = cfg.num_heads, cfg.d_model // cfg.num_heads
  per_leaf = num_slots * cache_length(cfg, chunk) * H * hd
  return 2 * cfg.num_layers * per_leaf * jnp.dtype(cfg.dtype).itemsize


# ------------------------------------------------------------ paged cache --


def blocks_per_slot(cfg, block_size: int) -> int:
  """Block-table width: virtual context rows per slot == ``max_seq_len``
  exactly.  ``block_size`` must divide ``max_seq_len``: the paged
  attend's softmax/V reductions then run over the SAME length as the
  ``generate(use_cache=True)`` oracle's cache, which is what keeps the
  paged engine greedy bit-exact (a longer padded length regroups XLA's
  vectorized partial sums — measured 1-ulp drift — even though the tail
  terms are exact zeros)."""
  if block_size < 1:
    raise ValueError(f"block_size must be >= 1: {block_size}")
  if cfg.max_seq_len % block_size:
    raise ValueError(
        f"serving.paged.block_size {block_size} must divide max_seq_len "
        f"{cfg.max_seq_len}: the paged attend's reduction length "
        f"(blocks_per_slot * block_size) must equal the oracle's cache "
        f"length for the greedy bit-exactness contract to hold")
  return cfg.max_seq_len // block_size


def default_num_blocks(cfg, num_slots: int, block_size: int) -> int:
  """Auto pool size: every slot can reach ``max_seq_len`` (plus the null
  block) — byte-parity with the contiguous layout, so enabling paging is
  never a capacity REGRESSION by default.  The memory win is opt-in:
  size ``serving.paged.num_blocks`` below this (or raise ``num_slots``
  above the contiguous budget) and on-demand allocation turns unused
  tail capacity into extra concurrent requests."""
  return num_slots * blocks_per_slot(cfg, block_size) + 1


def allocate_paged_kv_cache(cfg, num_blocks: int, block_size: int,
                            mesh: Optional[Mesh] = None) -> Dict[str, Any]:
  """Preallocate the paged K/V pools for a GPT config.

  Returns the ``"cache"``-collection pytree GPT's paged decode
  reads/writes: ``{"block_i": {"attn": {"cached_key"/"cached_value":
  [num_blocks, block_size, H, hd]}}}``.  Heads sit at the same axis
  index as the slot layout, so :func:`kv_cache_shardings` serves both.
  Block ``NULL_BLOCK`` is the reserved trash block (module constant).
  """
  mb = blocks_per_slot(cfg, block_size)
  if num_blocks < mb + 1:
    raise ValueError(
        f"num_blocks {num_blocks} cannot hold even one full-length "
        f"request: need >= blocks_per_slot + 1 = {mb + 1} (one null "
        f"block plus max_seq_len/block_size per request)")
  if cfg.d_model % cfg.num_heads:
    raise ValueError(f"d_model {cfg.d_model} must divide into "
                     f"{cfg.num_heads} heads")
  H, hd = cfg.num_heads, cfg.d_model // cfg.num_heads
  shape = (num_blocks, block_size, H, hd)
  kv_shardings, _ = kv_cache_shardings(cfg, mesh)

  def build():
    leaf = lambda: jnp.zeros(shape, cfg.dtype)
    return {f"block_{i}": {"attn": {"cached_key": leaf(),
                                    "cached_value": leaf()}}
            for i in range(cfg.num_layers)}

  if kv_shardings is None:
    # epl-lint: disable=recompile-hazard — allocation-time one-shot
    # (see allocate_kv_cache: pool zeros materialize in place, once)
    return jax.jit(build)()
  # epl-lint: disable=recompile-hazard — same one-shot allocation on
  # the mesh path
  return jax.jit(build, out_shardings=kv_shardings)()


def paged_cache_bytes(cfg, num_blocks: int, block_size: int) -> int:
  """Paged-pool footprint in bytes (both K and V, all layers) — the
  paged twin of :func:`cache_bytes`, and the number the long-tail
  benchmark holds fixed while raising concurrency."""
  H, hd = cfg.num_heads, cfg.d_model // cfg.num_heads
  per_leaf = num_blocks * block_size * H * hd
  return 2 * cfg.num_layers * per_leaf * jnp.dtype(cfg.dtype).itemsize


class BlockAllocator:
  """Host-side free-list + refcounts over the paged K/V pool.

  Lowest-free-first (a heap) keeps block assignment deterministic for a
  given request order, mirroring :class:`SlotAllocator`.  Refcounts
  carry the copy-on-write prefix sharing that
  ``serving/prefix_cache.py`` builds on this pool: a block starts at
  refcount 1 (its allocating slot), the radix tree adds one reference
  when it registers the block's content, and every slot that maps the
  block through a prefix match adds another — so a block's count is
  ``owning slot + tree entry + sharers``, and ``decref`` returns it to
  the free list only when the LAST holder lets go.  Shared blocks are
  read-only by construction (matching stops strictly before the first
  divergent/partial block; writes always land past the shared region —
  prefix_cache.py's COW rule), so sharing needs no device copy.  Block
  ``NULL_BLOCK`` is reserved, never allocated and NEVER shared: its
  rows are garbage by design (trash writes land there), so the tree
  refuses to register it.
  """

  def __init__(self, num_blocks: int, block_size: int):
    if num_blocks < 2:
      raise ValueError(f"num_blocks must be >= 2 (one null block plus at "
                       f"least one allocatable): {num_blocks}")
    if block_size < 1:
      raise ValueError(f"block_size must be >= 1: {block_size}")
    self.num_blocks = num_blocks
    self.block_size = block_size
    self._free: List[int] = list(range(1, num_blocks))
    heapq.heapify(self._free)
    self._ref: Dict[int, int] = {}

  @property
  def num_free(self) -> int:
    return len(self._free)

  @property
  def num_used(self) -> int:
    return len(self._ref)

  def alloc(self) -> Optional[int]:
    """Claim the lowest free block at refcount 1, or None when empty."""
    if not self._free:
      return None
    blk = heapq.heappop(self._free)
    self._ref[blk] = 1
    return blk

  def incref(self, block: int) -> None:
    """Add a reference (prefix-cache tree entries and COW prefix
    sharers: serving/prefix_cache.py)."""
    if block not in self._ref:
      raise ValueError(f"block {block} is not allocated")
    self._ref[block] += 1

  def decref(self, block: int) -> None:
    """Drop a reference; the block returns to the free list at zero."""
    if block not in self._ref:
      raise ValueError(f"block {block} is not allocated (double free?)")
    self._ref[block] -= 1
    if self._ref[block] == 0:
      del self._ref[block]
      heapq.heappush(self._free, block)

  def refcount(self, block: int) -> int:
    return self._ref.get(block, 0)

  def fragmentation(self, used_tokens: int) -> float:
    """Internal fragmentation: the fraction of allocated token capacity
    no resident token occupies (last-block slack across slots).  0.0
    when nothing is allocated."""
    cap = self.num_used * self.block_size
    if cap <= 0:
      return 0.0
    return max(0.0, 1.0 - used_tokens / cap)

  def __repr__(self):
    return (f"BlockAllocator(num_blocks={self.num_blocks}, "
            f"block_size={self.block_size}, free={self.num_free}, "
            f"used={self.num_used})")


class SlotAllocator:
  """Host-side free-list over the cache's request slots.

  Lowest-free-first allocation keeps slot assignment deterministic for a
  given request order (exactness tests replay schedules).  Freeing does
  no device work: the cache mask makes stale K/V unreachable, so
  "eviction" is purely returning the slot id to the list.
  """

  def __init__(self, num_slots: int):
    if num_slots < 1:
      raise ValueError(f"num_slots must be >= 1: {num_slots}")
    self.num_slots = num_slots
    self._free: List[int] = list(range(num_slots))
    self._used = set()

  @property
  def num_free(self) -> int:
    return len(self._free)

  def alloc(self) -> Optional[int]:
    """Claim the lowest free slot, or None when full."""
    if not self._free:
      return None
    slot = min(self._free)
    self._free.remove(slot)
    self._used.add(slot)
    return slot

  def free(self, slot: int):
    if slot not in self._used:
      raise ValueError(f"slot {slot} is not allocated (double free?)")
    self._used.remove(slot)
    self._free.append(slot)

  def __repr__(self):
    return (f"SlotAllocator(num_slots={self.num_slots}, "
            f"free={sorted(self._free)})")
