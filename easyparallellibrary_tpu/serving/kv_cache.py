"""Slot-based KV cache for continuous-batching inference.

The serving engine's memory plan is vLLM's insight shrunk to one level:
instead of allocating a fresh ``[B, max_seq_len, H, hd]`` cache per
``generate()`` call (models/gpt.py legacy decode), ONE cache of
``num_slots`` request slots is allocated at engine start and reused for
the life of the server.  A slot is the unit of admission: a request owns
exactly one slot from admission to retirement, its write offset tracked
by a per-slot cursor (the cursor *vector* models/gpt.py's
``slot_cache_attend`` consumes).  Eviction is free-list bookkeeping on
the host — no device work: stale K/V left by the previous occupant is
never attendable because the mask only exposes positions the current
request's own tokens have written (see slot_cache_attend's docstring;
tests/test_serving.py asserts the no-leakage property).

Placement: the cache is materialized directly into its sharded layout on
the mesh (same jit-with-out-shardings trick as
``create_sharded_train_state``), heads sharded over the tensor-parallel
``model`` axis so each TP shard holds exactly the head slice its
column-parallel QKV produces — cache reads/writes stay local, and GSPMD
inserts no resharding around the attention.

Layout note: the per-slot length is ``max_seq_len + chunk``
(:func:`cache_length`), one chunk longer than any request can grow.  The
fused step unconditionally writes a full ``chunk``-wide K/V window at
every slot's cursor (static shapes — masking, not shape, expresses
partial validity), so the window must never clamp against the end of the
buffer; ``jax.lax.dynamic_update_slice`` would otherwise shift the write
and corrupt earlier positions.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from easyparallellibrary_tpu import constants


def cache_length(cfg, chunk: int) -> int:
  """Per-slot cache length: ``max_seq_len`` plus one chunk of slack so
  the fused step's fixed-width write window never clamps (module
  docstring)."""
  return cfg.max_seq_len + int(chunk)


def kv_spec() -> P:
  """PartitionSpec of one cache leaf ``[num_slots, Lc, H, hd]``: heads
  over the TP axis, slots/positions replicated."""
  return P(None, None, constants.MODEL_AXIS, None)


def kv_cache_shardings(cfg, mesh: Optional[Mesh]):
  """(kv_shardings_pytree, cursor_sharding) matching
  :func:`allocate_kv_cache`'s structure, or (None, None) without a mesh.

  Heads shard over ``model`` only when the cache's head count actually
  divides the axis; otherwise the cache is replicated (a 1-sized or
  absent model axis degrades to replication anyway).
  """
  if mesh is None:
    return None, None
  sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
  tp = sizes.get(constants.MODEL_AXIS, 1)
  spec = kv_spec() if tp > 1 and cfg.num_heads % tp == 0 else P()
  leaf = NamedSharding(mesh, spec)
  kv = {f"block_{i}": {"attn": {"cached_key": leaf, "cached_value": leaf}}
        for i in range(cfg.num_layers)}
  return kv, NamedSharding(mesh, P())


def allocate_kv_cache(cfg, num_slots: int, chunk: int,
                      mesh: Optional[Mesh] = None
                      ) -> Tuple[Dict[str, Any], jax.Array]:
  """Preallocate the slot cache for a GPT config.

  Returns ``(kv, cursors)``: ``kv`` is a pytree shaped exactly like the
  ``"cache"`` collection GPT's slot-mode decode reads/writes
  (``{"block_i": {"attn": {"cached_key"/"cached_value":
  [num_slots, Lc, H, hd]}}}``), ``cursors`` the int32 ``[num_slots]``
  write-offset vector (all zero).  With a mesh, every leaf materializes
  already sharded (jit + out_shardings — no host-memory spike, no
  transfer).
  """
  if num_slots < 1:
    raise ValueError(f"num_slots must be >= 1: {num_slots}")
  if chunk < 1:
    raise ValueError(f"prefill chunk must be >= 1: {chunk}")
  if cfg.d_model % cfg.num_heads:
    raise ValueError(f"d_model {cfg.d_model} must divide into "
                     f"{cfg.num_heads} heads")
  H, hd = cfg.num_heads, cfg.d_model // cfg.num_heads
  Lc = cache_length(cfg, chunk)
  shape = (num_slots, Lc, H, hd)
  kv_shardings, cur_sharding = kv_cache_shardings(cfg, mesh)

  def build():
    leaf = lambda: jnp.zeros(shape, cfg.dtype)
    kv = {f"block_{i}": {"attn": {"cached_key": leaf(),
                                  "cached_value": leaf()}}
          for i in range(cfg.num_layers)}
    return kv, jnp.zeros((num_slots,), jnp.int32)

  if kv_shardings is None:
    return jax.jit(build)()
  return jax.jit(build, out_shardings=(kv_shardings, cur_sharding))()


def cache_bytes(cfg, num_slots: int, chunk: int) -> int:
  """Total cache footprint in bytes (both K and V, all layers) — the
  number the admission knobs trade against HBM."""
  H, hd = cfg.num_heads, cfg.d_model // cfg.num_heads
  per_leaf = num_slots * cache_length(cfg, chunk) * H * hd
  return 2 * cfg.num_layers * per_leaf * jnp.dtype(cfg.dtype).itemsize


class SlotAllocator:
  """Host-side free-list over the cache's request slots.

  Lowest-free-first allocation keeps slot assignment deterministic for a
  given request order (exactness tests replay schedules).  Freeing does
  no device work: the cache mask makes stale K/V unreachable, so
  "eviction" is purely returning the slot id to the list.
  """

  def __init__(self, num_slots: int):
    if num_slots < 1:
      raise ValueError(f"num_slots must be >= 1: {num_slots}")
    self.num_slots = num_slots
    self._free: List[int] = list(range(num_slots))
    self._used = set()

  @property
  def num_free(self) -> int:
    return len(self._free)

  def alloc(self) -> Optional[int]:
    """Claim the lowest free slot, or None when full."""
    if not self._free:
      return None
    slot = min(self._free)
    self._free.remove(slot)
    self._used.add(slot)
    return slot

  def free(self, slot: int):
    if slot not in self._used:
      raise ValueError(f"slot {slot} is not allocated (double free?)")
    self._used.remove(slot)
    self._free.append(slot)

  def __repr__(self):
    return (f"SlotAllocator(num_slots={self.num_slots}, "
            f"free={sorted(self._free)})")
