"""Replicated serving control plane: a health-checked router over N
engine replicas with bit-exact failover.

One engine process is a single point of failure — a hung step, a NaN'd
replica or a rolling restart kills every in-flight request it holds.
This module turns the single-engine serving stack into a fleet, in the
Whale/EPL shape the rest of the repo follows: a THIN coordination layer
over unchanged per-device programs.  The engines don't know the router
exists; the router speaks only the host-side currencies the serving
stack already defined — :class:`Request` snapshots (prefix replay),
:class:`ServingStats` signals, registry namespaces.

* **Health tracking** — per-replica
  :class:`~serving.resilience.ReplicaHealth`: heartbeats from each
  completed replica step (carrying the StepWatchdog timeout count, the
  BadStepPolicy counters and the measured ITL EWMA the engine already
  maintains), a healthy → suspect → down state machine, and a circuit
  breaker whose hold-out doubles per trip so a flapping replica is
  parked exponentially longer each round.
* **Bit-exact failover** — when a replica goes down (its step raised,
  or its heartbeat aged out), its queued AND in-flight requests are
  snapshotted (:meth:`FCFSScheduler.snapshot_requests`: prompt +
  committed prefix + lifecycle counters; PRNG state is implicit — the
  stream key derives from seed/uid and folds by committed token index)
  and resubmitted to survivors via the prefix-replay path.  A non-shed
  request therefore finishes with the EXACT greedy stream the
  single-engine oracle produces, no matter which replica dies when —
  and since replay is just a chunked prefill, the survivor's fused step
  never sees a new shape (no failover-induced recompiles).
* **Graceful drain + rejoin** — :meth:`drain` stops routing to a
  replica and gives its active requests ``drain_timeout_s`` to finish;
  leftovers migrate to survivors; :meth:`rejoin` resumes admission with
  the engine still warm (compiled step and cache untouched) — the
  rolling-restart primitive.
* **Dispatch** — prefix-affinity (requests sharing a prompt prefix go
  back to the replica that served it last — warm KV/prefix-cache
  locality) + least-loaded (occupancy/queue gauges), degrading to
  round-robin when a replica's load signals are stale.

Accounting invariants (tests/test_serving_router.py): every submitted
request resolves EXACTLY once in :attr:`Router.finished` — shed at the
router (no routable replica), shed by a replica's admission control, or
finished on exactly one replica (failover moves a request, it never
forks it) — and the fleet rollup (``serving/fleet/*``,
:func:`profiler.serving.fleet_summary`) merges per-replica stats
without double counting.

Everything is driven synchronously: one :meth:`step` sweeps every live
replica (an idle replica's step is just a heartbeat).

**Transports** (serving/transport.py): replicas sit behind the
:class:`ReplicaTransport` seam.  The default ``inproc`` transport hosts
them in this process, byte-for-byte the original behavior; the
``process`` transport hosts each replica in a spawned subprocess owning
its own JAX runtime — the real fault domain.  The router's step is
two-phase (dispatch to every process replica, then collect) so
concurrent children overlap their sweeps, health beats arrive as wire
watermarks, and a dead child's requests are recovered from the
transport's parent-side journal — no RPC to the corpse — and replayed
bit-exactly onto survivors through the same prefix-replay path.

See docs/serving.md "Multi-replica serving" / "Replica transports";
``make chaos-router`` and ``make chaos-proc`` are the acceptance
harnesses.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import slo as slo_lib
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.observability.registry import (
    FLEET_NAMESPACE, MetricRegistry)
from easyparallellibrary_tpu.profiler.serving import fleet_summary
from easyparallellibrary_tpu.serving.prefix_cache import block_prefix_keys
from easyparallellibrary_tpu.serving.replica import EngineReplica
from easyparallellibrary_tpu.serving.resilience import ReplicaHealth
from easyparallellibrary_tpu.serving.scheduler import (
    FinishedRequest, Request, next_flow_id)
from easyparallellibrary_tpu.serving.transport import (
    InprocTransport, ProcessTransport, TransportError)
from easyparallellibrary_tpu.utils.logging import get_logger

# Prefix-affinity routing hashes BLOCK-ALIGNED prefix content — the
# same content keys the prefix cache's radix tree matches at
# (serving/prefix_cache.py block_prefix_keys), one key per full-block
# depth up to AFFINITY_MAX_BLOCKS.  Routing and block reuse thereby
# agree on what "same prefix" means: a request routed on a depth-d key
# lands on the replica whose tree holds exactly those d blocks warm,
# and the deepest matching depth wins (longest shared prefix = most
# prefill skipped).
# Bounded prefix->replica map (LRU): affinity is a locality hint, not
# state — evicting an entry only costs a cold route.
AFFINITY_CAPACITY = 4096


class Router:
  """Health-checked dispatch over N engine replicas (module docstring).

  Typical drive::

      router = Router(model, params, num_replicas=2, mesh=mesh)
      router.submit(Request(uid="a", prompt=ids, max_new_tokens=64))
      outputs = router.run()       # {uid: prompt+generated}
      router.finished["a"].finish_reason
      router.drain(0); router.run()           # rolling restart:
      router.rejoin(0)                        # ...replica 0 warm again

  Every knob defaults from ``serving.router.*``.  ``replicas`` injects
  prebuilt (or duck-typed fake) replicas for tests; otherwise
  ``num_replicas`` engines are built here, sharing ``params`` and
  ``engine_kwargs``.  ``clock`` is injectable for deterministic
  health/drain tests (production leaves it at ``time.monotonic``).
  """

  def __init__(self, model=None, params=None, *, num_replicas=None,
               mesh=None, registry=None, config=None,
               clock=time.monotonic, replicas=None, factory=None,
               replica_factory=None, transport=None, **engine_kwargs):
    root_config = config if config is not None else Env.get().config
    rconf = root_config.serving.router
    self._root_config = root_config
    self._drain_timeout_s = rconf.drain_timeout_s
    self._affinity_enabled = rconf.affinity
    # Affinity keys are block-aligned content hashes (module constant
    # note): the block size comes from the paged config so routing and
    # each replica's prefix cache carve prompts at the same boundaries
    # — even when paging is off, the fixed carve keeps keys stable.
    self._affinity_block = root_config.serving.paged.block_size
    self._heartbeat_s = rconf.heartbeat_s
    self._suspect_after = rconf.suspect_after
    self._down_after = rconf.down_after
    self.clock = clock
    # Ambient SLO monitor (observability/slo.py): the router feeds it
    # the live fleet rollup — every heartbeat interval, and immediately
    # on failover — so TTFT/ITL/shed/availability rules see the fleet
    # as one deployment, not N replica streams after the fact.
    self._slo = slo_lib.ensure_configured(root_config)
    self._last_rollup = clock()
    self.transport = (transport if transport is not None
                      else rconf.transport)
    # Everything add_replica() needs to build one more fleet member —
    # the autoscaler's cold scale-up path.  Injected (test) replica
    # lists carry no recipe, so the fleet cannot grow there — unless
    # the caller supplies `replica_factory` (an ``index -> replica``
    # callable), the seam that lets an injected fleet (the cost-card
    # simulator, scaling tests) grow through the SAME autoscaler code
    # path as a recipe-built one.
    self._replica_spec: Optional[Dict[str, Any]] = None
    self._replica_factory = replica_factory
    if replicas is not None:
      self.replicas: List[EngineReplica] = list(replicas)
      self.transport = "injected"
    else:
      n = num_replicas if num_replicas is not None else rconf.replicas
      if n < 1:
        raise ValueError(f"num_replicas must be >= 1: {n}")
      if self.transport == "process":
        # Process-isolated replicas (serving/transport.py): each child
        # builds (model, params) from `factory` inside its OWN JAX
        # runtime — live arrays never cross the wire, and a SIGKILL
        # takes exactly one replica's memory.
        if factory is None:
          raise ValueError(
              "serving.router.transport='process' needs Router("
              "factory=...): a 'module:attr' spec (or module-level "
              "callable) building (model, params) in the child — live "
              "model/params objects cannot cross a process boundary")
        self.replicas = [
            ProcessTransport(i, factory, config=root_config,
                             engine_kwargs=engine_kwargs)
            for i in range(n)]
      else:
        self.replicas = [
            InprocTransport(i, model, params, mesh=mesh,
                            registry=registry, config=root_config,
                            **engine_kwargs)
            for i in range(n)]
      self._replica_spec = {
          "model": model, "params": params, "mesh": mesh,
          "registry": registry, "factory": factory,
          "engine_kwargs": dict(engine_kwargs)}
    self._itl_slo = root_config.serving.resilience.itl_slo_s
    self.health: List[ReplicaHealth] = [
        self._make_health(i) for i in range(len(self.replicas))]
    # Fleet-wide streamed-token fanout: fn(uid, [tok, ...]) fired per
    # engine iteration as tokens COMMIT (scheduler.on_tokens for
    # in-process replicas, the step reply's progress watermarks for
    # process replicas) — the front door's feed (serving/frontdoor/).
    # Subscribers must dedup by count across failover replays; the
    # replay path pre-seeds the committed prefix, so fresh deltas
    # continue the stream without re-emission.
    self.on_tokens: List[Any] = []
    for rep in self.replicas:
      self._wire_stream(rep)
    # Readiness-driven driver (serving/reactor.py), built lazily; the
    # `serving.router.reactor` knob makes run() drive through it while
    # step() stays the sweep (simulator / test compatibility).
    self._reactor = None
    self._reactor_enabled = bool(rconf.reactor)
    self.registry = registry
    if self._slo is not None and registry is not None:
      self._slo.attach(registry)
    # Fleet-wide resolution record: uid -> FinishedRequest, exactly one
    # entry per resolved request regardless of which replica (or the
    # router itself) resolved it.
    self.finished: Dict[Any, FinishedRequest] = {}
    # uid -> replica index currently responsible (introspection +
    # cancel routing); entries die with their request.
    self.placement: Dict[Any, int] = {}
    # Requests with NOWHERE to run (every replica down): parked
    # snapshots, flushed the moment a replica is routable again — a
    # total outage delays requests, it must not lose them.
    self._parked: List[Dict[str, Any]] = []
    self._affinity: "OrderedDict[int, int]" = OrderedDict()
    self._rr = 0                     # round-robin cursor
    # Blue/green rollout state (serving/rollout.py).  `None` weights =
    # version-blind dispatch, byte-for-byte the pre-rollout behavior;
    # during a rollout the controller sets {version: admission_weight}
    # and _choose splits NEW admissions by a deterministic deficit
    # counter (no RNG — replayable).  `_fleet_version` is the version
    # the steady-state fleet serves: it salts affinity digests so a
    # warm-prefix hint can never route a request onto a replica whose
    # cache was filled by different weights.
    self._version_weights: Optional[Dict[int, float]] = None
    self._version_dispatched: Dict[int, int] = {}
    self._fleet_version = int(engine_kwargs.get("checkpoint_version", 0))
    self._drain_deadline: Dict[int, float] = {}
    self._rejoined_at: Dict[int, float] = {}
    self.steps = 0
    self.submitted_total = 0         # submit() calls (demand signal)
    self.failovers = 0               # replica-down events that migrated
    self.migrated_requests = 0       # snapshots moved (failover + drain)
    self.router_shed = 0             # shed here: no routable replica
    self.probes = 0                  # breaker half-open rejoins
    # Fleet-level SLO actuator (serving/autoscale.py): SLO-burn-driven
    # grow/shrink of the live replica set through drain/rejoin and the
    # add_replica spawn path below.  Acts at step() start only —
    # replica-list mutation mid-sweep is never safe.
    self._autoscaler = None
    if root_config.serving.autoscale.enabled:
      from easyparallellibrary_tpu.serving.autoscale import (
          FleetAutoscaler)
      self._autoscaler = FleetAutoscaler(self, config=root_config)
    # Blue/green checkpoint rollout controller (serving/rollout.py):
    # operator calls router.rollout.begin(checkpoint_dir); all state
    # transitions happen in on_step at sweep boundaries, same contract
    # as the autoscaler.
    self.rollout = None
    if root_config.serving.rollout.enabled:
      from easyparallellibrary_tpu.serving.rollout import (
          RolloutController)
      self.rollout = RolloutController(self, config=root_config)
    get_logger().info(
        "serving router: %d replica(s), suspect/down after %.1fs/%.1fs, "
        "drain timeout %.1fs, affinity %s", len(self.replicas),
        rconf.suspect_after, rconf.down_after, rconf.drain_timeout_s,
        "on" if self._affinity_enabled else "off")

  # ------------------------------------------------------------- health

  def _make_health(self, index: int) -> ReplicaHealth:
    return ReplicaHealth(
        suspect_after=self._suspect_after, down_after=self._down_after,
        heartbeat_s=self._heartbeat_s, itl_slo_s=self._itl_slo,
        clock=self.clock, on_transition=self._make_health_hook(index))

  @property
  def spawn_recipe_available(self) -> bool:
    """True when this router can BUILD new replicas — it constructed
    its own fleet (recipe on hand) or was handed a ``replica_factory``.
    Injected-replica fleets without a factory (tests) cannot grow — and
    the autoscaler's off-thread spawn path keys off this to fall back
    to the synchronous lever."""
    return (self._replica_spec is not None
            or self._replica_factory is not None)

  def build_replica(self, index: Optional[int] = None, *,
                    checkpoint: Optional[str] = None,
                    checkpoint_version: Optional[int] = None,
                    params=None):
    """Construct ONE new replica from the stored recipe WITHOUT
    registering it — the slow half of :meth:`add_replica` (a process
    transport's subprocess spawn + in-child compile), split out so the
    autoscaler can pay it on a background thread while the fleet keeps
    sweeping (ROADMAP item 5 leftover).  The result is invisible to
    routing until :meth:`adopt_replica` lands it on the router thread.

    Thread-safety contract: this method only READS the recipe (and
    spawns); it never touches the replica/health lists.

    ``checkpoint``/``checkpoint_version``/``params`` override the
    recipe for ONE build — the rollout controller's green-spawn lever
    (serving/rollout.py).  A completed rollout instead rewrites the
    recipe itself, so later autoscale spawns and breaker respawns serve
    the new version with no override."""
    if self._replica_spec is None:
      if self._replica_factory is not None:
        if (checkpoint is not None or checkpoint_version is not None
            or params is not None):
          raise RuntimeError(
              "build_replica() overrides (checkpoint/version/params) "
              "are recipe levers; a replica_factory fleet builds "
              "replicas from the factory alone")
        return self._replica_factory(
            len(self.replicas) if index is None else index)
      raise RuntimeError(
          "build_replica() needs a router that built its own replicas; "
          "a fleet constructed from injected replicas carries no "
          "(model, params)/factory recipe to grow from")
    spec = self._replica_spec
    index = len(self.replicas) if index is None else index
    kwargs = dict(spec["engine_kwargs"])
    if checkpoint_version is not None:
      kwargs["checkpoint_version"] = int(checkpoint_version)
    if self.transport == "process":
      ckpt = checkpoint if checkpoint is not None else (
          spec.get("checkpoint"))
      return ProcessTransport(
          index, spec["factory"], config=self._root_config,
          engine_kwargs=kwargs, checkpoint=ckpt)
    return InprocTransport(
        index, spec["model"],
        spec["params"] if params is None else params,
        mesh=spec["mesh"],
        registry=spec["registry"], config=self._root_config,
        **kwargs)

  def adopt_replica(self, rep) -> int:
    """Register a built replica with the fleet (the fast half of
    :meth:`add_replica`): append to the replica/health lists, emit the
    trace instant, flush the parked backlog.  MUST run on the router's
    thread between sweeps — list mutation mid-sweep is never safe."""
    index = len(self.replicas)
    self.replicas.append(rep)
    self.health.append(self._make_health(index))
    self._wire_stream(rep)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/replica_added", cat="serving", track="serving",
          args={"replica": index, "transport": self.transport,
                "pid": getattr(rep, "child_pid", None) or -1})
    get_logger().info("fleet grew: replica %d added (%s transport)",
                      index, self.transport)
    self._flush_parked()
    return index

  def add_replica(self) -> int:
    """Grow the fleet by ONE replica built from the construction recipe
    (same transport, config and engine kwargs as the originals);
    returns its index.  On the process transport this is a REAL
    subprocess spawn — the child builds its own engine and compiles its
    own fused step once, exactly what a capacity add costs.  The parked
    backlog flushes immediately: new capacity must serve, not idle.

    The synchronous operator lever (blocks for the spawn).  The
    autoscaler instead runs :meth:`build_replica` on a background
    thread and :meth:`adopt_replica` at the next sweep, so a cold
    scale-up never stalls the fleet (serving/autoscale.py).  Raises on
    a fleet built from injected replicas (tests) — there is no recipe
    to build from."""
    return self.adopt_replica(self.build_replica())

  def _wire_stream(self, rep) -> None:
    """Attach the router's streamed-token fanout to one replica's hook
    point: the parent-side ``on_tokens`` list for a process transport,
    the scheduler's for an in-process one.  Duck-typed — injected fakes
    without either hook simply don't stream (routing-policy tests)."""
    hook = getattr(rep, "on_tokens", None)
    if hook is None:
      sched = getattr(rep, "scheduler", None)
      hook = getattr(sched, "on_tokens", None) if sched is not None \
          else None
    if hook is not None:
      hook.append(self._emit_tokens)

  def _emit_tokens(self, uid: Any, tokens: List[int]) -> None:
    for fn in self.on_tokens:
      fn(uid, tokens)

  def reactor(self):
    """The readiness-driven driver over this fleet (built lazily,
    serving/reactor.py): per-replica dispatch the moment each previous
    reply lands, so one slow replica no longer gates the sweep.
    ``run()`` drives through it when ``serving.router.reactor`` is on;
    :meth:`step` stays the lock-step sweep either way."""
    if self._reactor is None:
      from easyparallellibrary_tpu.serving.reactor import RouterReactor
      self._reactor = RouterReactor(self, config=self._root_config)
    return self._reactor

  def _make_health_hook(self, index: int):
    def hook(old: str, new: str, reason: str):
      tracer = trace_lib.get_tracer()
      if tracer.enabled:
        tracer.instant(
            "serving/replica_health", cat="serving", track="serving",
            args={"replica": index, "from": old, "to": new,
                  "reason": reason})
    return hook

  def state(self, index: int) -> str:
    return self.health[index].state

  def states(self) -> List[str]:
    return [h.state for h in self.health]

  def _routable(self) -> List[int]:
    return [i for i, h in enumerate(self.health) if h.routable]

  # ----------------------------------------------------------- dispatch

  def _replica_version(self, index: int) -> int:
    """Checkpoint version replica ``index`` serves (0 = unversioned —
    injected test replicas and pre-rollout fleets)."""
    return int(getattr(self.replicas[index], "checkpoint_version", 0)
               or 0)

  def set_version_weights(self,
                          weights: Optional[Dict[int, float]]) -> None:
    """Install per-checkpoint-version admission weights (the rollout
    controller's lever; init comment on ``_version_weights``).  Resets
    the deficit counters so each stage's split is exact from its first
    admission; ``None`` restores version-blind dispatch."""
    if weights is None:
      self._version_weights = None
      self._version_dispatched = {}
      return
    self._version_weights = {int(v): float(w)
                             for v, w in weights.items() if w > 0.0}
    self._version_dispatched = {v: 0 for v in self._version_weights}

  def _pick_version(self, routable: List[int]) -> tuple:
    """Deterministic weighted split of NEW admissions across checkpoint
    versions: pick the version with the largest admission deficit
    (expected share minus actual dispatches — no RNG, so a replayed
    trace splits identically), restricted to versions with a routable
    replica.  Returns ``(version, candidates)``; falls back to the
    whole routable set when no weighted version is live (weights must
    degrade, never shed)."""
    by_ver: Dict[int, List[int]] = {}
    for i in routable:
      by_ver.setdefault(self._replica_version(i), []).append(i)
    weights = {v: w for v, w in self._version_weights.items()
               if v in by_ver}
    if not weights:
      return None, routable
    total = sum(weights.values())
    n = sum(self._version_dispatched.get(v, 0) for v in weights) + 1
    best = max(sorted(weights),
               key=lambda v: (weights[v] / total) * n
               - self._version_dispatched.get(v, 0))
    self._version_dispatched[best] = (
        self._version_dispatched.get(best, 0) + 1)
    return best, by_ver[best]

  def _prefix_keys(self, prompt: np.ndarray,
                   version: Optional[int] = None) -> List[int]:
    """Block-aligned content keys for ``prompt``, shallowest first —
    the SAME hashing the prefix cache's radix tree matches at
    (prefix_cache.block_prefix_keys), so a deep affinity hit predicts a
    deep block-reuse hit on the target replica.  Keys are salted with
    the serving checkpoint version (default: the steady-state fleet's)
    so blue-era affinity entries can never name a green replica."""
    ver = self._fleet_version if version is None else int(version)
    return block_prefix_keys(prompt, self._affinity_block, version=ver)

  def _remember_affinity(self, key: int, index: int) -> None:
    self._affinity.pop(key, None)
    self._affinity[key] = index
    while len(self._affinity) > AFFINITY_CAPACITY:
      self._affinity.popitem(last=False)

  def _choose(self, prompt: np.ndarray) -> tuple:
    """Pick a replica for one request: ``(index, reason)`` with reason
    in {"only", "affinity", "least_loaded", "round_robin"}, or
    ``(None, "no_replica")`` when nothing is routable."""
    now = self.clock()
    for i, h in enumerate(self.health):
      if self.replicas[i].has_work:
        # Only a replica that OWES work can go stale; an idle one's
        # loop isn't running, and absence of beats proves nothing.
        h.observe(now)
      else:
        h.touch(now)
    self._reap(now)
    routable = self._routable()
    if not routable:
      return None, "no_replica"
    version: Optional[int] = None
    if self._version_weights is not None:
      # Rollout in flight: the admission-weight split picks the
      # checkpoint version FIRST, then normal dispatch ranks within it.
      version, routable = self._pick_version(routable)
    if len(routable) == 1:
      return routable[0], "only"
    if any(self.health[i].signals_stale(now) for i in routable):
      # Load numbers of unknown age rank nothing: fall back to fair
      # rotation until fresh beats return.
      self._rr = (self._rr + 1) % len(routable)
      return routable[self._rr], "round_robin"
    if self._affinity_enabled:
      # Deepest matching depth first: the longest shared block-aligned
      # prefix names the replica holding the most of this prompt warm.
      for key in reversed(self._prefix_keys(prompt, version)):
        aff = self._affinity.get(key)
        if (aff is not None and aff in routable
            and self.replicas[aff].load < self.replicas[aff].num_slots):
          # Warm prefix AND spare capacity: locality wins.  A saturated
          # affinity target falls through to least-loaded — affinity is
          # a tiebreak, never a queueing reason.
          return aff, "affinity"
    idx = min(routable, key=lambda i: (self.replicas[i].load, i))
    return idx, "least_loaded"

  def _shed_at_router(self, request: Request, prompt: np.ndarray,
                      tracer) -> bool:
    self.router_shed += 1
    self.finished[request.uid] = FinishedRequest(
        uid=request.uid, tokens=prompt, new_tokens=0,
        finish_reason="shed")
    if tracer.enabled:
      tracer.instant(
          "serving/route", cat="serving", track="serving/requests",
          args={"uid": str(request.uid), "replica": -1,
                "reason": "no_replica"})
      tracer.flow("f", request.flow_id, track="serving/requests",
                  args={"uid": str(request.uid), "reason": "shed"})
    get_logger().warning(
        "router shedding request %r: no routable replica (states %s)",
        request.uid, self.states())
    return False

  def submit(self, request: Request) -> bool:
    """Route and enqueue one request; False when it was shed — by the
    router (no routable replica) or by the chosen replica's admission
    control.  Either way the shed record lands in :attr:`finished` with
    reason ``"shed"``, exactly once.

    A replica that DIES during the submit (a process transport's child
    crashed or timed out mid-call) is failed over on the spot, and the
    request is admitted exactly once regardless of where the call was
    lost: the transport journals the request BEFORE the RPC, so an
    ambiguous submit rides the failover replay to a survivor, and
    child-side uid dedup stops a retried wire call from double
    admitting."""
    prompt = np.asarray(request.prompt, np.int32).reshape(-1)
    # Cumulative demand counter — counts every arrival regardless of
    # outcome (admitted, replica-shed, router-shed), so rate samples
    # over it measure offered load, not accepted load.  The predictive
    # autoscale rule differentiates it (serving/autoscale.py).
    self.submitted_total += 1
    # The trace-context id is minted HERE — the earliest point the
    # request touches the fleet — so its flow arc starts at routing and
    # stays one connected thread through dispatch, admission, any
    # failover, and retirement (docs/observability.md).
    if request.flow_id is None:
      request = dataclasses.replace(request, flow_id=next_flow_id())
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.flow("s", request.flow_id, track="serving/requests",
                  args={"uid": str(request.uid)})
    for _attempt in range(len(self.replicas) + 1):
      idx, reason = self._choose(prompt)
      if idx is None:
        return self._shed_at_router(request, prompt, tracer)
      if tracer.enabled:
        tracer.instant(
            "serving/route", cat="serving", track="serving/requests",
            args={"uid": str(request.uid), "replica": idx,
                  "reason": reason})
      # Pin the request to the checkpoint version it is admitted under:
      # the tag rides every snapshot, so a later failover can only
      # replay it onto a SAME-version survivor (prefix replay across
      # versions is not bit-exact — docs/robustness.md, migration
      # policy complete-in-place).
      version = self._replica_version(idx)
      if request.checkpoint_version != version:
        request = dataclasses.replace(request,
                                      checkpoint_version=version)
      try:
        accepted = self.replicas[idx].submit(request)
      except TransportError as e:
        # ONLY transport failures read as replica death here — a
        # client error (malformed request -> ValueError) propagates to
        # the caller exactly as the engine contract promises, and must
        # never cost a healthy replica (let alone cascade fleet-wide).
        get_logger().error(
            "replica %d died during submit of %r (%s: %s); failing over",
            idx, request.uid, type(e).__name__, e)
        self.health[idx].mark_down(f"submit raised {type(e).__name__}")
        self._failover(idx)
        if request.uid in self.placement or self._parked_uid(request.uid):
          # The transport journaled the ambiguous submit; the failover
          # (or parking) above already owns it — admitted exactly once.
          return True
        continue
      if accepted:
        self.placement[request.uid] = idx
        if self._affinity_enabled:
          # Every depth remembers the placement: a future prompt
          # sharing only a SHALLOWER block-aligned prefix still finds
          # the warm replica through its own deepest common key.  Keys
          # carry the target's version salt, so the hint only ever
          # matches lookups routed to that same version.
          for key in self._prefix_keys(prompt, version):
            self._remember_affinity(key, idx)
      else:
        # The replica's admission control shed it and recorded the
        # resolution in ITS finished map; mirror fleet-side so callers
        # never chase per-replica maps (the replica counted the shed —
        # don't count it again here).
        fin = self.replicas[idx].finished.get(request.uid)
        if fin is not None:
          self.finished[request.uid] = fin
      return accepted
    return self._shed_at_router(request, prompt, tracer)

  def _parked_uid(self, uid: Any) -> bool:
    return any(snap["request"]["uid"] == uid for snap in self._parked)

  def cancel(self, uid: Any) -> bool:
    """Cancel ``uid`` wherever it lives — on its replica, or in the
    parked backlog (a parked request must not silently resurrect on the
    next rejoin after the client abandoned it)."""
    for k, snap in enumerate(self._parked):
      if snap["request"]["uid"] == uid:
        del self._parked[k]
        generated = np.asarray(snap.get("generated", ()), np.int32)
        fin = FinishedRequest(
            uid=uid,
            tokens=np.concatenate([
                np.asarray(snap["request"]["prompt"], np.int32),
                generated]),
            new_tokens=int(generated.size), finish_reason="cancelled")
        self._note_finished(-1, fin)
        tracer = trace_lib.get_tracer()
        flow_id = snap["request"].get("flow_id")
        if tracer.enabled and flow_id is not None:
          # A parked request's cancellation is its resolution — the
          # flow terminates here, not on any replica track.
          tracer.flow("f", flow_id, track="serving/requests",
                      args={"uid": str(uid), "reason": "cancelled"})
        return True
    idx = self.placement.get(uid)
    if idx is not None:
      try:
        return self.replicas[idx].cancel(uid)
      except TransportError as e:
        # The replica died holding the request: fail it over (fence +
        # journal), then cancel it wherever it landed — parked or on a
        # survivor.  A cancellation must never be silently lost to a
        # later failover replay decoding the request to completion.
        get_logger().error(
            "replica %d died during cancel of %r (%s: %s); failing over",
            idx, uid, type(e).__name__, e)
        self.health[idx].mark_down(f"cancel raised {type(e).__name__}")
        self._failover(idx)
        return self.cancel(uid)
    for rep in self.replicas:
      try:
        if rep.cancel(uid):
          return True
      except TransportError:
        continue
    return False

  # --------------------------------------------------------------- step

  def _note_finished(self, index: int, fin: FinishedRequest) -> None:
    self.finished[fin.uid] = fin
    self.placement.pop(fin.uid, None)

  def _sweep_begin(self, now: float) -> None:
    """Control-plane actions at a sweep/cycle boundary — the ONLY
    point the replica list may mutate (autoscaler grow/drain, rollout
    transitions, drain expiry, parked flush).  Shared verbatim by the
    sweep :meth:`step` and the reactor's cycle (serving/reactor.py),
    so both drivers honor the same mutation-safety contract."""
    if self.rollout is not None:
      # Rollout transitions land BEFORE the autoscaler acts: a rollback
      # or cutover this sweep must hold/release the autoscaler before
      # it reads the replica set (serving/rollout.py).
      self.rollout.on_step(now)
    if self._autoscaler is not None:
      # Replica-set actuation happens HERE, before the sweep touches
      # the list — a mid-sweep grow/drain would race the phase loops.
      self._autoscaler.on_step(now)
    self._check_drains(now)
    self._flush_parked()

  def _dispatch_one(self, i: int, now: float) -> bool:
    """Phase-1 dispatch for one replica: post the step frame (process
    transports) or mark it due (in-process replicas compute at
    collect).  Down replicas are probed on the breaker cadence instead.
    Returns True when the replica now owes a :meth:`_collect_one`."""
    rep = self.replicas[i]
    h = self.health[i]
    if h.state == "down":
      if h.can_probe(now):
        self._probe(i)
      return False
    send = getattr(rep, "step_send", None)
    if send is not None:
      try:
        send()
      except Exception as e:  # noqa: BLE001 — dead at dispatch
        self._note_step_death(i, e)
        return False
    return True

  def _collect_one(self, i: int,
                   now: float) -> Optional[List[FinishedRequest]]:
    """Phase-2 collect for one dispatched replica (and run, for
    in-process replicas): retirements, the health beat, breaker
    forgiveness.  Returns None when the replica died collecting (its
    requests already failed over)."""
    rep = self.replicas[i]
    h = self.health[i]
    recv = getattr(rep, "step_recv", None)
    try:
      fins = rep.step() if recv is None else recv()
    except Exception as e:  # noqa: BLE001 — ANY escaping error = dead
      self._note_step_death(i, e)
      return None
    for fin in fins:
      self._note_finished(i, fin)
    wire = getattr(rep, "wire_beat", None)
    if wire:
      # Process replica: the beat dict rode the step reply over the
      # wire; same watermark semantics as the in-process signals.
      h.beat_from_wire(wire)
    else:
      h.beat(watchdog_timeouts=rep.watchdog_timeouts,
             bad_steps=rep.bad_steps, itl_s=rep.itl_ewma_s)
    if h.state == "healthy" and h.trips:
      # Breaker forgiveness: a rejoined replica that survives a full
      # cooldown window clean sheds one trip.
      since = self._rejoined_at.get(i, now)
      if now - since >= h.cooldown_s():
        h.note_stable()
        self._rejoined_at[i] = now
    return fins

  def _sweep_end(self, now: float) -> None:
    """Sweep/cycle epilogue: reap passively-down replicas, advance the
    step counter, publish the rollup on the heartbeat cadence."""
    # A replica that reached "down" without raising (heartbeat aged out
    # at dispatch time between sweeps) is dead weight holding requests —
    # fail it over now.  Replicas that just stepped beat above, so their
    # age is zero and this is a no-op for them.
    self._reap(now)
    self.steps += 1
    # Live fleet rollup on the heartbeat cadence: the registry's sinks
    # (report.py --follow tails the JSONL) and the SLO monitor's rules
    # both see the fleet mid-run, not just at drain.  Raw-sample
    # percentile merging is bounded by the stats' reservoirs
    # (profiler/serving.py), so this stays O(replicas * sample cap).
    if (self.registry is not None or self._slo is not None) and \
        self.clock() - self._last_rollup >= self._heartbeat_s:
      self._publish_rollup()

  def step(self) -> List[FinishedRequest]:
    """One fleet sweep: migrate expired drains, step every live replica
    (collecting retirements and feeding health beats), fail over any
    replica whose step raised or whose heartbeat aged out, and probe
    down replicas whose breaker cooldown elapsed.  Returns this sweep's
    retirements fleet-wide.

    This is the lock-step (sweep-compat) driver — phase 1 dispatches to
    every live replica, phase 2 collects in replica order — kept
    byte-for-byte for the simulator and deterministic tests.  The
    reactor (serving/reactor.py) drives the SAME four pieces
    (``_sweep_begin`` / ``_dispatch_one`` / ``_collect_one`` /
    ``_sweep_end``) readiness-first instead."""
    now = self.clock()
    out: List[FinishedRequest] = []
    self._sweep_begin(now)
    # Phase 1 — dispatch: process transports get their step frame NOW,
    # so concurrent children overlap their sweeps (fleet wall-clock =
    # the slowest child, not the sum); in-process replicas compute at
    # collect time below, preserving the PR-8 execution order exactly.
    stepped: List[int] = []
    for i in range(len(self.replicas)):
      if self._dispatch_one(i, now):
        stepped.append(i)
    # Phase 2 — collect (and run, for in-process replicas), in replica
    # order: retirements, health beats, failover of anything that died.
    for i in stepped:
      fins = self._collect_one(i, now)
      if fins:
        out.extend(fins)
    self._sweep_end(now)
    return out

  def _publish_rollup(self) -> None:
    self._last_rollup = self.clock()
    records = [(FLEET_NAMESPACE, self.fleet_summary())]
    if self.rollout is not None and self.rollout.active:
      # Per-version sub-rollups during a rollout (serving/rollout.py):
      # the SLO monitor's bare-name rules suffix-match these keys, so
      # the canary's evidence streams (``serving/fleet/v<N>/...``)
      # exist exactly while a rollout is in flight, with no new rules.
      for ver, sub in self.rollout.version_rollups().items():
        records.append((f"{FLEET_NAMESPACE}/v{ver}", sub))
    for namespace, rollup in records:
      if self.registry is not None:
        # The SLO monitor rides the registry as a sink (attach at init).
        self.registry.publish(self.steps, rollup, namespace)
      elif self._slo is not None:
        # Registry-less fleet: same validated schema helper the registry
        # path uses — never an ad-hoc key literal (namespaced() validates
        # the root; report.py reads back through the same constant).
        self._slo.observe(self.steps,
                          MetricRegistry.namespaced(namespace, rollup))

  def _reap(self, now: float) -> None:
    """Fail over any down replica still holding requests.  Idempotent —
    a replica already evacuated (its step raised) yields no snapshots
    and is skipped; this catches the passive path, where staleness
    marked it down without an exception ever unwinding."""
    for i, h in enumerate(self.health):
      if h.state == "down" and self.replicas[i].has_work:
        self._failover(i)

  def run(self, max_steps: Optional[int] = None
          ) -> Dict[Any, np.ndarray]:
    """Drive until the fleet drains (or ``max_steps``); returns
    ``{uid: prompt+generated}`` for requests finished during the call.
    Publishes the fleet rollup at the end when a registry is
    attached."""
    out: Dict[Any, np.ndarray] = {}
    steps = 0
    drive = (self.reactor().cycle if self._reactor_enabled
             else self.step)
    while self.has_work and (max_steps is None or steps < max_steps):
      for fin in drive():
        out[fin.uid] = fin.tokens
      steps += 1
      if self._parked_stalled():
        # The parked backlog cannot move (no healthy or suspect target
        # — or none of the pinned version) and no live replica has work
        # of its own to make progress on —
        # return instead of spinning; the backlog is preserved and a
        # later run()/step() resumes it after a breaker probe or an
        # operator rejoin().
        get_logger().warning(
            "router.run(): %d request(s) parked with no routable "
            "replica (states %s); returning — rejoin a replica to "
            "resume", len(self._parked), self.states())
        break
    if self.registry is not None or self._slo is not None:
      self._publish_rollup()
    return out

  def _parked_stalled(self) -> bool:
    """True when the parked backlog cannot move and no live replica has
    work of its own — run()'s (and the reactor's) spin guard."""
    return bool(
        self._parked
        and not any(rep.has_work
                    for i, rep in enumerate(self.replicas)
                    if self.health[i].state != "down")
        and not any(self._eligible_targets(s, self._survivors(-1))
                    for s in self._parked))

  @property
  def has_work(self) -> bool:
    if self._parked:
      return True
    return any(
        rep.has_work for i, rep in enumerate(self.replicas)
        if self.health[i].state != "down")

  # ----------------------------------------------------------- failover

  def _note_step_death(self, index: int, exc: BaseException) -> None:
    """One replica's step (dispatch or collect) raised: mark it down,
    emit the ``serving/replica_down`` incident instant — carrying the
    child's kill signal when the transport reaped one, so PR 9's SLO
    monitor and diagnostic bundles see REAL process incidents — and
    fail its requests over."""
    rep = self.replicas[index]
    sig = getattr(rep, "exit_signal", None)
    sig_name = ""
    if sig:
      try:
        import signal as _signal
        sig_name = _signal.Signals(sig).name
      except (ValueError, ImportError):
        sig_name = str(sig)
    get_logger().error(
        "replica %d died mid-step (%s: %s%s); failing over",
        index, type(exc).__name__, exc,
        f"; child exit signal {sig_name}" if sig_name else "")
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/replica_down", cat="serving", track="serving",
          args={"replica": index, "error": type(exc).__name__,
                "signal": sig_name,
                "pid": getattr(rep, "child_pid", None) or -1})
    self.health[index].mark_down(f"step raised {type(exc).__name__}")
    self._failover(index)

  def _survivors(self, exclude: int) -> List[int]:
    """Failover targets: healthy first; a draining replica is never a
    target (it is trying to empty), a suspect one only as last resort
    (it is alive, just slow — better slow than parked)."""
    healthy = [i for i in self._routable() if i != exclude]
    if healthy:
      return healthy
    return [i for i, h in enumerate(self.health)
            if h.state == "suspect" and i != exclude]

  def _eligible_targets(self, snap: Dict[str, Any],
                        targets: List[int]) -> List[int]:
    """Targets a snapshot may restore onto: all of them for an unpinned
    request, only SAME-version replicas for one pinned to a checkpoint
    version (_place_snapshots docstring)."""
    pinned = snap["request"].get("checkpoint_version")
    if pinned is None:
      return list(targets)
    return [i for i in targets
            if self._replica_version(i) == int(pinned)]

  def _place_snapshots(self, snaps: List[Dict[str, Any]],
                       targets: List[int]) -> int:
    """Distribute snapshots over ``targets`` (least-loaded each time,
    re-ranked as restores land).  Restores go to the queue FRONT in
    reverse snapshot order, so the dead replica's service order is
    preserved on each target.  Returns how many were placed.

    A target that DIES mid-placement must not take the remaining
    snapshots with it ("an outage delays, it never loses"): the dead
    target is dropped and marked down, an AMBIGUOUSLY-applied restore
    (the target's transport journaled it before the wire failed) stays
    placed there — its own failover recovers it, double-placing would
    fork the request — and when no target is left the remainder parks.

    A snapshot pinned to a checkpoint version only places on a
    SAME-version target (migration policy complete-in-place,
    docs/robustness.md): mid-rollout, a dead blue's requests fail over
    to a surviving blue, never green — and with no same-version target
    they park (delayed, never replayed across versions)."""
    placed = 0
    targets = list(targets)
    pending = list(snaps)
    while pending:
      if not targets:
        get_logger().warning(
            "placement ran out of targets: parking %d remaining "
            "request(s)", len(pending))
        self._parked.extend(pending)
        break
      snap = pending[-1]
      eligible = self._eligible_targets(snap, targets)
      if not eligible:
        get_logger().warning(
            "no version-%s target for request %r: parking (cross-"
            "version replay is refused)",
            snap["request"].get("checkpoint_version"),
            snap["request"].get("uid"))
        self._parked.append(pending.pop())
        continue
      idx = min(eligible, key=lambda i: (self.replicas[i].load, i))
      try:
        uid = self.replicas[idx].restore_request(snap, front=True)
      except Exception as e:  # noqa: BLE001 — target died mid-restore
        get_logger().error(
            "replica %d died during restore placement (%s: %s)",
            idx, type(e).__name__, e)
        self.health[idx].mark_down(f"restore raised {type(e).__name__}")
        targets.remove(idx)
        owns = getattr(self.replicas[idx], "owns", None)
        if owns is not None and owns(snap["request"]["uid"]):
          # Ambiguous outcome, journaled on the dead target: fail THAT
          # replica over NOW (fence first, so it cannot also serve the
          # request) — its journal re-places the snapshot on a live
          # survivor or parks it.  Leaving it for a later sweep would
          # strand it: run() does not drive down replicas.
          pending.pop()
          self._failover(idx)
        continue
      pending.pop()
      self.placement[uid] = idx
      placed += 1
    return placed

  def _failover(self, index: int) -> None:
    """Move a down replica's queued + in-flight requests to survivors
    (module docstring: prefix replay makes this bit-exact).  With no
    survivor the snapshots park and flush on the next rejoin — an
    outage delays, it never loses."""
    snaps = self.replicas[index].evacuate()
    for snap in snaps:
      self.placement.pop(snap["request"]["uid"], None)
    if not snaps:
      return
    self.failovers += 1
    self.migrated_requests += len(snaps)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/failover", cat="serving", track="serving",
          args={"replica": index, "requests": len(snaps),
                "reason": self.health[index].down_reason})
    targets = self._survivors(index)
    if not targets:
      get_logger().warning(
          "failover of replica %d found NO survivor: parking %d "
          "request(s) until a replica rejoins", index, len(snaps))
      self._parked.extend(snaps)
      self._note_incident()
      return
    self._place_snapshots(snaps, targets)
    get_logger().warning(
        "replica %d failed over: %d request(s) resumed on replica(s) %s "
        "via prefix replay", index, len(snaps), targets)
    self._note_incident()

  def _note_incident(self) -> None:
    """Publish the fleet rollup IMMEDIATELY (not on the heartbeat
    cadence): a failover must open its SLO breach window — and land in
    the tailed metrics log — at the kill, not up to a heartbeat later."""
    if self.registry is not None or self._slo is not None:
      self._publish_rollup()

  def _flush_parked(self) -> None:
    if not self._parked:
      return
    # Same target preference as failover: healthy, else suspect as a
    # last resort — a parked backlog waiting for a perfect replica is a
    # parked backlog not being served.
    targets = self._survivors(-1)
    if not targets:
      return
    # A version-pinned snapshot with no same-version target stays
    # parked QUIETLY (no per-step churn through _place_snapshots);
    # it moves the moment its version has a live replica again.
    movable = [s for s in self._parked
               if self._eligible_targets(s, targets)]
    if not movable:
      return
    moved = {id(s) for s in movable}
    self._parked = [s for s in self._parked if id(s) not in moved]
    self._place_snapshots(movable, targets)
    get_logger().info("flushed %d parked request(s) onto replica(s) %s",
                      len(movable), targets)

  def _probe(self, index: int) -> None:
    """Half-open breaker probe: the cooldown elapsed, let the replica
    serve again; a relapse re-trips with a doubled hold-out.  A process
    replica's child is respawned first (cold engine: fresh compile,
    empty cache — what a real restart costs); a failed respawn re-arms
    the breaker with its doubled hold-out instead of spawn-storming."""
    if not self._ensure_replica_host(index):
      return
    if self.health[index].rejoin():
      self.probes += 1
      self._rejoined_at[index] = self.clock()
      get_logger().info(
          "probing replica %d back into service (trip %d, next "
          "hold-out %.1fs)", index, self.health[index].trips,
          self.health[index].cooldown_s())

  def _ensure_replica_host(self, index: int) -> bool:
    """(Re)start a transport-hosted replica's process if it is gone;
    True when the replica is usable.  In-process replicas are always
    up (their ``ensure_started`` is a no-op)."""
    rep = self.replicas[index]
    ensure = getattr(rep, "ensure_started", None)
    if ensure is None:
      return True
    try:
      if ensure():
        get_logger().info(
            "replica %d: child respawned (restart %d)", index,
            getattr(rep, "child_restarts", 0))
    except Exception as e:  # noqa: BLE001 — spawn/init failed
      get_logger().error(
          "replica %d: respawn failed (%s: %s); breaker re-armed",
          index, type(e).__name__, e)
      self.health[index].probe_failed(f"respawn {type(e).__name__}")
      return False
    return True

  # ------------------------------------------------------ drain / rejoin

  def drain(self, index: int,
            timeout_s: Optional[float] = None) -> None:
    """Graceful drain (rolling restart, step 1): stop routing to
    ``index``; its active requests get ``timeout_s`` (default
    ``serving.router.drain_timeout_s``) of fleet steps to finish, then
    the leftovers migrate to survivors.  The replica stays unroutable
    (state ``draining``) until :meth:`rejoin`."""
    self.health[index].drain()
    timeout = self._drain_timeout_s if timeout_s is None else timeout_s
    self._drain_deadline[index] = self.clock() + timeout
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/drain", cat="serving", track="serving",
          args={"replica": index, "timeout_s": float(timeout)})

  def _check_drains(self, now: float) -> None:
    for index in list(self._drain_deadline):
      rep = self.replicas[index]
      if not rep.has_work:
        del self._drain_deadline[index]
        continue
      if now < self._drain_deadline[index]:
        continue
      targets = self._survivors(index)
      if targets and not any(
          self._replica_version(t) == self._replica_version(index)
          for t in targets):
        # Complete-in-place (docs/robustness.md): survivors exist but
        # none serves this replica's checkpoint version, so evacuating
        # would only park its (version-pinned) requests — a LIVE
        # draining replica keeps serving them to completion instead.
        self._drain_deadline[index] = now + self._drain_timeout_s
        continue
      del self._drain_deadline[index]
      snaps = rep.evacuate()
      if not snaps:
        continue
      for snap in snaps:
        # _place_snapshots re-points placed uids; parked ones must not
        # keep a stale entry naming the evacuated replica.
        self.placement.pop(snap["request"]["uid"], None)
      self.migrated_requests += len(snaps)
      targets = self._survivors(index)
      tracer = trace_lib.get_tracer()
      if tracer.enabled:
        tracer.instant(
            "serving/drain_migrate", cat="serving", track="serving",
            args={"replica": index, "requests": len(snaps)})
      if targets:
        self._place_snapshots(snaps, targets)
        get_logger().info(
            "drain timeout on replica %d: migrated %d request(s) to %s",
            index, len(snaps), targets)
      else:
        self._parked.extend(snaps)

  def rejoin(self, index: int, force: bool = False) -> bool:
    """Return a drained (or down) replica to service.  An in-process
    replica rejoins warm — its engine, cache and compiled step were
    never torn down; a process replica whose child died is respawned
    (cold) first.  For a down replica the circuit breaker must agree
    (``force=True`` overrides)."""
    h = self.health[index]
    if h.state == "down" and not (force or h.can_probe()):
      return False
    if not self._ensure_replica_host(index):
      return False
    ok = self.health[index].rejoin(force=force)
    if ok:
      self._drain_deadline.pop(index, None)
      self._rejoined_at[index] = self.clock()
      self._flush_parked()
    return ok

  # -------------------------------------------------------- observability

  def router_counters(self) -> Dict[str, float]:
    states = self.states()
    counters = {
        "failovers": float(self.failovers),
        "migrated_requests": float(self.migrated_requests),
        "router_shed": float(self.router_shed),
        "probes": float(self.probes),
        "parked": float(len(self._parked)),
        "replicas_healthy": float(states.count("healthy")),
        "replicas_suspect": float(states.count("suspect")),
        "replicas_down": float(states.count("down")),
        "replicas_draining": float(states.count("draining")),
        # Transport-layer incident counters (serving/transport.py),
        # summed fleet-wide: retried idempotent RPCs, wire deadline
        # misses, and child respawns.  They ride the fleet rollup
        # through MetricRegistry.namespaced like every other counter,
        # so the SLO monitor and diagnostic bundles see real-process
        # incidents with zero new plumbing.  All 0 on inproc fleets.
        "rpc_retries": 0.0,
        "rpc_timeouts": 0.0,
        "child_restarts": 0.0,
    }
    if self._autoscaler is not None:
      # Actuator counters ride the same fleet rollup (scale_ups,
      # scale_downs, autoscale_holds, flap_trips).
      counters.update(self._autoscaler.counters())
    if self.rollout is not None:
      # rollout_* counters (serving/rollout.py) ride the same schema.
      counters.update(self.rollout.counters())
    for rep in self.replicas:
      rpc = getattr(rep, "rpc_counters", None)
      if rpc is None:
        continue
      for key, val in rpc().items():
        counters[key] = counters.get(key, 0.0) + float(val)
    return counters

  def fleet_summary(self) -> Dict[str, float]:
    """One fleet-wide record (profiler.serving.fleet_summary): summed
    rates/counters, percentiles re-ranked over raw per-replica samples,
    plus the router's own counters.  Total fleet sheds =
    ``shed`` (replica admission control) + ``router_shed`` (nothing
    routable)."""
    # Bind each stats ONCE: for a process replica the property is a
    # blocking child RPC — evaluating it in both the filter and the
    # value position would double every rollup's wire traffic.
    stats = [s for s in (rep.stats for rep in self.replicas)
             if s is not None]
    return fleet_summary(stats, self.router_counters())

  def publish(self, registry, step: int) -> None:
    """Publish the rollup under ``serving/fleet/*`` (every replica's own
    records live under ``serving/replica<i>/*`` beside it)."""
    registry.publish(step, self.fleet_summary(), FLEET_NAMESPACE)

  def harvest_traces(self, drain: bool = True) -> int:
    """Pull every process replica's tracer ring remainder into the
    ambient tracer (docs/observability.md "Distributed tracing").  The
    steady-state path needs no call here — bounded chunks ride every
    step reply, and a clean ``close()`` flushes the rest via the
    shutdown reply — but a caller exporting the merged trace while the
    fleet is still up (``make trace-fleet``, the quick pins) drains
    explicitly first.  Returns events harvested; inproc and injected
    replicas (no ``harvest`` endpoint) contribute zero."""
    total = 0
    for rep in self.replicas:
      harvest = getattr(rep, "harvest", None)
      if harvest is None:
        continue
      try:
        total += int(harvest(drain=drain))
      except TransportError:
        continue
    return total

  # ----------------------------------------------------------- lifecycle

  def close(self):
    # Process replicas flush their ring remainder on the shutdown
    # reply, so closing the fleet completes the merged trace.
    for rep in self.replicas:
      rep.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
    return False
