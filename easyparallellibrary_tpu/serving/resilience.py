"""Serving-side resilience: admission control, overload shedding,
degradation, and bad-step recovery policy.

PR 2 gave *training* graded fault responses (runtime/resilience.py:
in-jit sentinel, rollback, watchdog); this module gives the serving
engine the same "unchanged user code, resilient system underneath"
treatment for what production traffic and flaky hardware actually do:

* **Admission control & shedding** — :class:`AdmissionController`, a
  bounded admission queue plus a degradation ladder driven by live load
  signals (queue depth, slot occupancy, measured ITL vs its SLO).
  Pressure is answered in cost order: speculation off first (draft
  compute is pure ballast under overload), then prefill-budget
  tightening (protect decode cadence), then shedding new arrivals at
  submit (reason ``"shed"``) — never by corrupting or abandoning
  admitted work.  Every ladder transition is emitted as a trace instant
  (``serving/degraded``) on the PR-5 tracer and counted.
* **Bad-step policy** — :class:`BadStepPolicy` tracks per-slot
  consecutive bad device steps (the in-jit finiteness verdict the
  guarded fused step returns; engine.py) and decides retry vs
  quarantine: a bad slot's cursor never advanced, so the next plan
  re-feeds identical work (the retry is free and exact); past
  ``max_step_retries`` the request is requeued with its committed
  prefix (scheduler.requeue_slot), and past ``max_requeues`` it is
  failed rather than allowed to poison the batch forever.
* **Hung-step watchdog** — the engine arms a
  :class:`runtime.resilience.StepWatchdog` around each fused-step
  dispatch+fetch when ``serving.resilience.step_timeout_s`` > 0, so a
  wedged device call surfaces in the log/trace with a step number
  instead of as silence.

Everything here is pure host policy — no device work, no jax imports —
so it is unit-testable with a fake clock and adds zero overhead to the
fused step.  Knobs: the ``serving.resilience.*`` config group
(docs/robustness.md "Serving resilience").
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from easyparallellibrary_tpu.utils.logging import get_logger

# Degradation ladder levels, in escalation order.  The index is the
# level number the engine/metrics carry.
DEGRADE_LEVELS = ("normal", "spec_off", "budget_tight", "shed")

# Replica health states (serving/router.py; docs/serving.md
# "Multi-replica serving").  Only "healthy" receives new dispatch;
# "suspect" keeps its in-flight work but is skipped by routing;
# "down" triggers failover of its queued + in-flight requests;
# "draining" is the admin-initiated rolling-restart state (finish or
# migrate within drain_timeout_s, then rejoin warm).
HEALTH_STATES = ("healthy", "suspect", "down", "draining")


class AdmissionController:
  """Bounded admission queue + graceful-degradation ladder.

  ``queue_limit`` bounds how many requests may wait for a slot; a
  submit that finds the queue full is shed immediately (early
  rejection: the client learns NOW instead of after a hopeless wait).
  Below the hard limit, the ladder degrades service quality in cost
  order as pressure builds:

  ========  ============  ==============================================
  level     name          effect (applied by the engine)
  ========  ============  ==============================================
  0         normal        full service
  1         spec_off      speculation disabled (draft compute freed for
                          committed tokens; exactness unaffected)
  2         budget_tight  per-step prefill budget clamped to one chunk
                          (admission slows, decode cadence protected)
  3         shed          new submits rejected (reason ``"shed"``)
  ========  ============  ==============================================

  Level entry thresholds are queue-depth fractions of ``queue_limit``
  (``degrade_queue_frac`` enters level 1, halfway between it and full
  enters level 2, full enters level 3); an ITL measurement above
  ``itl_slo_s`` forces at least level 1 regardless of queue depth.
  Level 2 additionally requires full slot occupancy — tightening the
  prefill budget while slots sit empty would slow the very admissions
  that drain the queue.  De-escalation is hysteretic: a level is left
  only once the queue has drained below HALF its entry threshold, one
  level per observation, so the ladder cannot flap on a noisy boundary
  — except that ``budget_tight`` is also released the moment occupancy
  drops below full (its entry condition), for the same reason it
  requires full occupancy to enter.  An over-SLO ITL holds the ladder
  at ``spec_off`` or above (it floors the target level at 1) but never
  pins the higher levels.

  ``on_transition(old_level, new_level, signals)`` fires on every
  ladder move (the engine hooks the tracer + stats counters in).
  """

  def __init__(self, queue_limit: int = 0, itl_slo_s: float = 0.0,
               degrade_queue_frac: float = 0.5,
               on_transition: Optional[Callable] = None):
    if queue_limit < 0:
      raise ValueError(f"queue_limit must be >= 0 (0 = unbounded): "
                       f"{queue_limit}")
    if not 0.0 < degrade_queue_frac <= 1.0:
      raise ValueError(f"degrade_queue_frac must be in (0, 1]: "
                       f"{degrade_queue_frac}")
    if itl_slo_s < 0:
      raise ValueError(f"itl_slo_s must be >= 0 (0 = off): {itl_slo_s}")
    self.queue_limit = queue_limit
    self.itl_slo_s = itl_slo_s
    self.degrade_queue_frac = degrade_queue_frac
    self.on_transition = on_transition
    self.level = 0
    # External de-escalation floor (the autotuner's ladder-floor knob,
    # serving/autotune.py): while set, the ladder never drops below it
    # — an SLO actuator can pin "at least spec_off" through a breach
    # window without re-deriving the queue signals.  0 = no floor.
    self.floor_level = 0
    self.transitions = 0
    self.shed_total = 0

  # --------------------------------------------------------------- levels

  def _enter_frac(self, level: int) -> float:
    """Queue-depth fraction at which `level` is entered."""
    if level >= 3:
      return 1.0
    if level == 2:
      return (1.0 + self.degrade_queue_frac) / 2.0
    return self.degrade_queue_frac

  def _target_level(self, queue_frac: float, occupancy: float,
                    itl_over: bool) -> int:
    level = 0
    if self.queue_limit > 0:
      if queue_frac >= self._enter_frac(3):
        level = 3
      elif queue_frac >= self._enter_frac(2) and occupancy >= 1.0:
        level = 2
      elif queue_frac >= self._enter_frac(1):
        level = 1
    if itl_over:
      level = max(level, 1)
    return max(level, min(self.floor_level, 3))

  def observe(self, queue_depth: int, occupancy: float,
              itl_s: float = 0.0) -> int:
    """Feed one engine iteration's load signals; returns the (possibly
    new) degradation level.  Escalation is immediate; de-escalation one
    level per call, and only once pressure is well clear (docstring)."""
    queue_frac = (queue_depth / self.queue_limit
                  if self.queue_limit > 0 else 0.0)
    itl_over = bool(self.itl_slo_s > 0 and itl_s > self.itl_slo_s)
    target = self._target_level(queue_frac, occupancy, itl_over)
    new = self.level
    if target > self.level:
      new = target
    elif target < self.level:
      clear = queue_frac < 0.5 * self._enter_frac(self.level)
      if self.level == 2 and occupancy < 1.0:
        # budget_tight's entry condition includes full occupancy; once
        # slots sit free the clamp only slows the admissions that drain
        # the queue, so its release does not wait for queue hysteresis.
        clear = True
      # No extra ITL gate here: an over-SLO ITL floors `target` at 1
      # (so the ladder never drops below spec_off while it holds), but
      # it must not pin levels 2-3 — a stale EWMA on a drained engine
      # (ITL only refreshes on decode steps, which a fully-shedding
      # engine never runs) would otherwise hold the shed level forever.
      if clear:
        new = self.level - 1
    if new != self.level:
      old, self.level = self.level, new
      self.transitions += 1
      get_logger().info(
          "serving degradation: %s -> %s (queue %d/%s, occupancy %.2f, "
          "itl %.4fs vs slo %.4fs)", DEGRADE_LEVELS[old],
          DEGRADE_LEVELS[new], queue_depth, self.queue_limit or "inf",
          occupancy, itl_s, self.itl_slo_s)
      if self.on_transition is not None:
        self.on_transition(old, new, {
            "queue_depth": int(queue_depth),
            "occupancy": float(occupancy), "itl_s": float(itl_s)})
    return self.level

  # ------------------------------------------------------------ admission

  def should_shed(self, queue_depth: int) -> bool:
    """Submit-time verdict: shed when the bounded queue is full or the
    ladder has reached its shed level.  Pure predicate — safe to poll
    for introspection; the caller that actually sheds a request
    records it via :meth:`note_shed`."""
    if self.queue_limit > 0 and queue_depth >= self.queue_limit:
      return True
    return self.level >= 3

  def note_shed(self):
    """Count one actually-shed request (the engine's shed path calls
    this after acting on a True :meth:`should_shed` verdict)."""
    self.shed_total += 1

  @property
  def speculation_enabled(self) -> bool:
    return self.level < 1

  @property
  def budget_tightened(self) -> bool:
    return self.level >= 2


class BadStepPolicy:
  """Retry-then-quarantine policy over per-slot bad-step streaks.

  The guarded fused step (engine.py) returns a per-slot finiteness
  verdict; a bad slot's cursor and host state never advanced, so simply
  replanning retries it exactly.  This class only decides WHEN to stop
  retrying: a slot whose streak exceeds ``max_step_retries`` is
  quarantined (requeue with committed prefix — a fresh slot's replay
  rewrites any poisoned K/V), and a request requeued more than
  ``max_requeues`` times is failed.
  """

  RETRY, REQUEUE, FAIL = "retry", "requeue", "fail"

  def __init__(self, max_step_retries: int = 1, max_requeues: int = 1):
    if max_step_retries < 0 or max_requeues < 0:
      raise ValueError("max_step_retries and max_requeues must be >= 0")
    self.max_step_retries = max_step_retries
    self.max_requeues = max_requeues
    self.bad_steps = 0        # engine steps with >= 1 bad slot
    self.step_retries = 0     # slot-steps replayed in place
    self.requeues = 0
    self.failures = 0

  def judge(self, slot_states: Dict[int, "object"],
            bad_slots: List[int],
            exercised: Optional[set] = None) -> Dict[int, str]:
    """Update streaks for one engine step and return the action per bad
    slot (``retry`` | ``requeue`` | ``fail``).  ``slot_states`` is the
    scheduler's ``active`` map (entries carry ``bad_streak`` and
    ``requeues``); good slots' streaks reset here — but only slots the
    step actually EXERCISED (``exercised``, the plan's num_valid>0 set;
    None = all): a budget-starved slot proved nothing this step, and
    resetting its streak would re-grant a poisoned slot its full retry
    allowance on every starvation interleave, postponing quarantine
    indefinitely."""
    if bad_slots:
      self.bad_steps += 1
    actions: Dict[int, str] = {}
    bad = set(bad_slots)
    for slot, state in slot_states.items():
      if slot not in bad:
        if exercised is None or slot in exercised:
          state.bad_streak = 0
        continue
      state.bad_streak += 1
      if state.bad_streak <= self.max_step_retries:
        self.step_retries += 1
        actions[slot] = self.RETRY
      elif state.requeues < self.max_requeues:
        self.requeues += 1
        actions[slot] = self.REQUEUE
      else:
        self.failures += 1
        actions[slot] = self.FAIL
    return actions

  def counters(self) -> Dict[str, int]:
    return {"bad_steps": self.bad_steps,
            "step_retries": self.step_retries,
            "requeues": self.requeues,
            "failed_requests": self.failures}


class ReplicaHealth:
  """Health state machine + circuit breaker for ONE serving replica.

  The router feeds two signal kinds and reads one state back:

  * :meth:`beat` — the replica's step loop calls it after every
    COMPLETED engine step, carrying the live signals the step already
    has on the host (the StepWatchdog's timeout count, the BadStepPolicy
    streak counters, the measured ITL EWMA).  A beat is the heartbeat;
    its arguments decide whether it is a *clean* one.
  * :meth:`observe` — the router polls it each scheduling round.
    Heartbeat age drives the passive half of the machine: a replica
    silent past ``suspect_after`` seconds is ``suspect`` (no new
    dispatch; its in-flight work keeps running), past ``down_after`` it
    is ``down`` (failover).  A beat carrying a watchdog timeout or an
    over-SLO ITL also marks the replica suspect — it answered, but too
    slowly to trust with new latency-sensitive work.
  * :meth:`mark_down` — the active half: the router calls it when a
    replica's step RAISES (the thread/process died mid-decode).

  Recovery goes through the **circuit breaker**: every trip to ``down``
  counts, and :meth:`can_probe` only opens after a cooldown that
  doubles per trip (capped), so a flapping replica — one that dies,
  rejoins clean, and dies again — is held out exponentially longer each
  round instead of bouncing traffic.  :meth:`rejoin` closes the breaker
  half-open: the replica is routable again, but its next ``mark_down``
  doubles the hold-out rather than restarting the ladder.

  ``drain()`` / ``rejoin()`` implement the rolling-restart path: a
  draining replica is unroutable but healthy; rejoin resumes admission
  warm (the engine and its compiled step were never torn down).

  Pure host policy — injectable ``clock``, no jax, unit-testable with a
  fake clock like the ladder above.  ``on_transition(old, new, reason)``
  fires on every state change (the router hooks tracer instants in).
  """

  def __init__(self, suspect_after: float = 3.0, down_after: float = 10.0,
               heartbeat_s: float = 1.0, itl_slo_s: float = 0.0,
               clock: Callable[[], float] = time.monotonic,
               on_transition: Optional[Callable] = None):
    if not 0 < suspect_after <= down_after:
      raise ValueError(
          f"need 0 < suspect_after <= down_after; got "
          f"suspect_after={suspect_after}, down_after={down_after}")
    if heartbeat_s <= 0:
      raise ValueError(f"heartbeat_s must be > 0: {heartbeat_s}")
    self.suspect_after = suspect_after
    self.down_after = down_after
    self.heartbeat_s = heartbeat_s
    self.itl_slo_s = itl_slo_s
    self.clock = clock
    self.on_transition = on_transition
    self.state = "healthy"
    self.last_beat = clock()
    self.last_clean_beat = self.last_beat
    self.trips = 0              # healthy->down round trips (breaker)
    self.transitions = 0
    self.down_reason = ""
    self._down_since = 0.0
    # Cumulative-counter watermarks: beats carry the stats objects'
    # running totals, and only an INCREASE is a fresh incident — an old
    # timeout must not keep every later beat dirty forever.
    self._last_bad_steps = 0
    self._last_watchdog = 0

  # --------------------------------------------------------------- signals

  def _set_state(self, new: str, reason: str = ""):
    if new == self.state:
      return
    old, self.state = self.state, new
    self.transitions += 1
    if new == "down":
      self.trips += 1
      self._down_since = self.clock()
      self.down_reason = reason
    get_logger().warning(
        "replica health: %s -> %s%s", old, new,
        f" ({reason})" if reason else "")
    if self.on_transition is not None:
      self.on_transition(old, new, reason)

  def beat(self, watchdog_timeouts: int = 0, bad_steps: int = 0,
           itl_s: float = 0.0) -> None:
    """One completed engine step.  ``watchdog_timeouts`` / ``bad_steps``
    are CUMULATIVE counters (the stats objects already hold them);
    deltas are computed here.  A down/draining replica's beats are
    recorded (staleness clears) but never auto-promote — recovery from
    ``down`` goes through :meth:`rejoin`, and ``draining`` is admin
    state."""
    now = self.clock()
    self.last_beat = now
    hung = watchdog_timeouts > self._last_watchdog
    bad = bad_steps > self._last_bad_steps
    self._last_watchdog = max(self._last_watchdog, watchdog_timeouts)
    self._last_bad_steps = max(self._last_bad_steps, bad_steps)
    slow = self.itl_slo_s > 0 and itl_s > self.itl_slo_s
    if hung or bad or slow:
      if self.state == "healthy":
        self._set_state(
            "suspect",
            "watchdog timeout" if hung else
            ("bad device step" if bad else "ITL over SLO"))
      return
    self.last_clean_beat = now
    if self.state == "suspect":
      self._set_state("healthy", "clean beat")

  def beat_from_wire(self, beat: Dict[str, "object"]) -> None:
    """Ingest a transport heartbeat (serving/transport.py): process
    replicas piggyback their watchdog/bad-step WATERMARKS, the ITL EWMA
    and load signals on every RPC reply, and the router feeds the
    health half here.  Same cumulative-counter semantics as
    :meth:`beat` — the dict is just the wire spelling of the in-process
    signals, so the state machine cannot tell (and must not care)
    which side of a process boundary the replica lives on."""
    self.beat(
        watchdog_timeouts=int(beat.get("watchdog_timeouts", 0) or 0),
        bad_steps=int(beat.get("bad_steps", 0) or 0),
        itl_s=float(beat.get("itl_ewma_s", 0.0) or 0.0))

  def touch(self, now: Optional[float] = None) -> None:
    """Reset the heartbeat clock WITHOUT a step.  The router calls this
    for an IDLE replica at dispatch time: an idle replica's loop is not
    running, so absence of beats is not evidence of death — only a
    replica that owes work can go stale.  (Without this, a healthy
    fleet quiet for ``suspect_after`` seconds would shed its first
    request after every lull.)  No state transitions: a suspect set by
    a dirty beat still needs a CLEAN beat to clear."""
    if self.state in ("down", "draining"):
      return
    self.last_beat = self.clock() if now is None else now

  def observe(self, now: Optional[float] = None) -> str:
    """Heartbeat-staleness check; returns the (possibly new) state.
    Draining and down are sticky — staleness never demotes an admin
    state, and only :meth:`rejoin` recovers a down replica."""
    now = self.clock() if now is None else now
    if self.state in ("down", "draining"):
      return self.state
    age = now - self.last_beat
    if age >= self.down_after:
      self._set_state("down", f"no heartbeat for {age:.2f}s")
    elif age >= self.suspect_after and self.state == "healthy":
      self._set_state("suspect", f"heartbeat stale ({age:.2f}s)")
    return self.state

  def mark_down(self, reason: str = "step raised") -> None:
    """Active failure report (the replica's step raised / its host died).
    Trips the breaker immediately."""
    self._set_state("down", reason)

  # ------------------------------------------------------------- lifecycle

  def drain(self) -> None:
    """Admin drain: unroutable, but not a failure — no breaker trip."""
    if self.state != "down":
      self._set_state("draining", "drain requested")

  def cooldown_s(self) -> float:
    """Current breaker hold-out: ``down_after`` doubled per trip, capped
    at 2^6 — a flapping replica waits exponentially longer each round."""
    return self.down_after * (2 ** min(max(self.trips - 1, 0), 6))

  def can_probe(self, now: Optional[float] = None) -> bool:
    """True once a down replica's breaker cooldown has elapsed — the
    router may then :meth:`rejoin` it as a half-open probe."""
    if self.state != "down":
      return False
    now = self.clock() if now is None else now
    return now - self._down_since >= self.cooldown_s()

  def rejoin(self, force: bool = False) -> bool:
    """Return the replica to service (rolling-restart rejoin, or a
    breaker probe).  A down replica rejoins only once :meth:`can_probe`
    allows it (``force=True`` overrides — the operator knows best);
    returns False when the breaker refuses.  The trip count is KEPT —
    a relapse doubles the next hold-out (that is the breaker's whole
    point); it decays only via :meth:`note_stable`."""
    if self.state == "down" and not (force or self.can_probe()):
      return False
    self.last_beat = self.clock()   # fresh grace period, not instant-stale
    self._set_state("healthy", "rejoin")
    return True

  def probe_failed(self, reason: str = "") -> None:
    """A half-open probe could not even START the replica (e.g. a
    process transport's respawn failed).  Re-arm the breaker as if the
    replica had relapsed — trip count up, cooldown window restarted —
    so a host that cannot spawn is backed off exponentially instead of
    spawn-stormed every sweep."""
    if self.state != "down":
      return
    self.trips += 1
    self._down_since = self.clock()
    if reason:
      self.down_reason = reason
    get_logger().warning(
        "replica probe failed%s: breaker re-armed (trip %d, hold-out "
        "%.1fs)", f" ({reason})" if reason else "", self.trips,
        self.cooldown_s())

  def note_stable(self) -> None:
    """Forgive one breaker trip (the router calls this after a rejoined
    replica survives a full cooldown window without incident, so an
    ancient flap does not tax a now-healthy replica forever)."""
    self.trips = max(0, self.trips - 1)

  @property
  def routable(self) -> bool:
    return self.state == "healthy"

  def signals_stale(self, now: Optional[float] = None) -> bool:
    """Load signals older than two heartbeats cannot be trusted for
    least-loaded ranking — dispatch degrades to round-robin."""
    now = self.clock() if now is None else now
    return now - self.last_beat > 2.0 * self.heartbeat_s
