"""Serving-side resilience: admission control, overload shedding,
degradation, and bad-step recovery policy.

PR 2 gave *training* graded fault responses (runtime/resilience.py:
in-jit sentinel, rollback, watchdog); this module gives the serving
engine the same "unchanged user code, resilient system underneath"
treatment for what production traffic and flaky hardware actually do:

* **Admission control & shedding** — :class:`AdmissionController`, a
  bounded admission queue plus a degradation ladder driven by live load
  signals (queue depth, slot occupancy, measured ITL vs its SLO).
  Pressure is answered in cost order: speculation off first (draft
  compute is pure ballast under overload), then prefill-budget
  tightening (protect decode cadence), then shedding new arrivals at
  submit (reason ``"shed"``) — never by corrupting or abandoning
  admitted work.  Every ladder transition is emitted as a trace instant
  (``serving/degraded``) on the PR-5 tracer and counted.
* **Bad-step policy** — :class:`BadStepPolicy` tracks per-slot
  consecutive bad device steps (the in-jit finiteness verdict the
  guarded fused step returns; engine.py) and decides retry vs
  quarantine: a bad slot's cursor never advanced, so the next plan
  re-feeds identical work (the retry is free and exact); past
  ``max_step_retries`` the request is requeued with its committed
  prefix (scheduler.requeue_slot), and past ``max_requeues`` it is
  failed rather than allowed to poison the batch forever.
* **Hung-step watchdog** — the engine arms a
  :class:`runtime.resilience.StepWatchdog` around each fused-step
  dispatch+fetch when ``serving.resilience.step_timeout_s`` > 0, so a
  wedged device call surfaces in the log/trace with a step number
  instead of as silence.

Everything here is pure host policy — no device work, no jax imports —
so it is unit-testable with a fake clock and adds zero overhead to the
fused step.  Knobs: the ``serving.resilience.*`` config group
(docs/robustness.md "Serving resilience").
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from easyparallellibrary_tpu.utils.logging import get_logger

# Degradation ladder levels, in escalation order.  The index is the
# level number the engine/metrics carry.
DEGRADE_LEVELS = ("normal", "spec_off", "budget_tight", "shed")


class AdmissionController:
  """Bounded admission queue + graceful-degradation ladder.

  ``queue_limit`` bounds how many requests may wait for a slot; a
  submit that finds the queue full is shed immediately (early
  rejection: the client learns NOW instead of after a hopeless wait).
  Below the hard limit, the ladder degrades service quality in cost
  order as pressure builds:

  ========  ============  ==============================================
  level     name          effect (applied by the engine)
  ========  ============  ==============================================
  0         normal        full service
  1         spec_off      speculation disabled (draft compute freed for
                          committed tokens; exactness unaffected)
  2         budget_tight  per-step prefill budget clamped to one chunk
                          (admission slows, decode cadence protected)
  3         shed          new submits rejected (reason ``"shed"``)
  ========  ============  ==============================================

  Level entry thresholds are queue-depth fractions of ``queue_limit``
  (``degrade_queue_frac`` enters level 1, halfway between it and full
  enters level 2, full enters level 3); an ITL measurement above
  ``itl_slo_s`` forces at least level 1 regardless of queue depth.
  Level 2 additionally requires full slot occupancy — tightening the
  prefill budget while slots sit empty would slow the very admissions
  that drain the queue.  De-escalation is hysteretic: a level is left
  only once the queue has drained below HALF its entry threshold, one
  level per observation, so the ladder cannot flap on a noisy boundary
  — except that ``budget_tight`` is also released the moment occupancy
  drops below full (its entry condition), for the same reason it
  requires full occupancy to enter.  An over-SLO ITL holds the ladder
  at ``spec_off`` or above (it floors the target level at 1) but never
  pins the higher levels.

  ``on_transition(old_level, new_level, signals)`` fires on every
  ladder move (the engine hooks the tracer + stats counters in).
  """

  def __init__(self, queue_limit: int = 0, itl_slo_s: float = 0.0,
               degrade_queue_frac: float = 0.5,
               on_transition: Optional[Callable] = None):
    if queue_limit < 0:
      raise ValueError(f"queue_limit must be >= 0 (0 = unbounded): "
                       f"{queue_limit}")
    if not 0.0 < degrade_queue_frac <= 1.0:
      raise ValueError(f"degrade_queue_frac must be in (0, 1]: "
                       f"{degrade_queue_frac}")
    if itl_slo_s < 0:
      raise ValueError(f"itl_slo_s must be >= 0 (0 = off): {itl_slo_s}")
    self.queue_limit = queue_limit
    self.itl_slo_s = itl_slo_s
    self.degrade_queue_frac = degrade_queue_frac
    self.on_transition = on_transition
    self.level = 0
    self.transitions = 0
    self.shed_total = 0

  # --------------------------------------------------------------- levels

  def _enter_frac(self, level: int) -> float:
    """Queue-depth fraction at which `level` is entered."""
    if level >= 3:
      return 1.0
    if level == 2:
      return (1.0 + self.degrade_queue_frac) / 2.0
    return self.degrade_queue_frac

  def _target_level(self, queue_frac: float, occupancy: float,
                    itl_over: bool) -> int:
    level = 0
    if self.queue_limit > 0:
      if queue_frac >= self._enter_frac(3):
        level = 3
      elif queue_frac >= self._enter_frac(2) and occupancy >= 1.0:
        level = 2
      elif queue_frac >= self._enter_frac(1):
        level = 1
    if itl_over:
      level = max(level, 1)
    return level

  def observe(self, queue_depth: int, occupancy: float,
              itl_s: float = 0.0) -> int:
    """Feed one engine iteration's load signals; returns the (possibly
    new) degradation level.  Escalation is immediate; de-escalation one
    level per call, and only once pressure is well clear (docstring)."""
    queue_frac = (queue_depth / self.queue_limit
                  if self.queue_limit > 0 else 0.0)
    itl_over = bool(self.itl_slo_s > 0 and itl_s > self.itl_slo_s)
    target = self._target_level(queue_frac, occupancy, itl_over)
    new = self.level
    if target > self.level:
      new = target
    elif target < self.level:
      clear = queue_frac < 0.5 * self._enter_frac(self.level)
      if self.level == 2 and occupancy < 1.0:
        # budget_tight's entry condition includes full occupancy; once
        # slots sit free the clamp only slows the admissions that drain
        # the queue, so its release does not wait for queue hysteresis.
        clear = True
      # No extra ITL gate here: an over-SLO ITL floors `target` at 1
      # (so the ladder never drops below spec_off while it holds), but
      # it must not pin levels 2-3 — a stale EWMA on a drained engine
      # (ITL only refreshes on decode steps, which a fully-shedding
      # engine never runs) would otherwise hold the shed level forever.
      if clear:
        new = self.level - 1
    if new != self.level:
      old, self.level = self.level, new
      self.transitions += 1
      get_logger().info(
          "serving degradation: %s -> %s (queue %d/%s, occupancy %.2f, "
          "itl %.4fs vs slo %.4fs)", DEGRADE_LEVELS[old],
          DEGRADE_LEVELS[new], queue_depth, self.queue_limit or "inf",
          occupancy, itl_s, self.itl_slo_s)
      if self.on_transition is not None:
        self.on_transition(old, new, {
            "queue_depth": int(queue_depth),
            "occupancy": float(occupancy), "itl_s": float(itl_s)})
    return self.level

  # ------------------------------------------------------------ admission

  def should_shed(self, queue_depth: int) -> bool:
    """Submit-time verdict: shed when the bounded queue is full or the
    ladder has reached its shed level.  Pure predicate — safe to poll
    for introspection; the caller that actually sheds a request
    records it via :meth:`note_shed`."""
    if self.queue_limit > 0 and queue_depth >= self.queue_limit:
      return True
    return self.level >= 3

  def note_shed(self):
    """Count one actually-shed request (the engine's shed path calls
    this after acting on a True :meth:`should_shed` verdict)."""
    self.shed_total += 1

  @property
  def speculation_enabled(self) -> bool:
    return self.level < 1

  @property
  def budget_tightened(self) -> bool:
    return self.level >= 2


class BadStepPolicy:
  """Retry-then-quarantine policy over per-slot bad-step streaks.

  The guarded fused step (engine.py) returns a per-slot finiteness
  verdict; a bad slot's cursor and host state never advanced, so simply
  replanning retries it exactly.  This class only decides WHEN to stop
  retrying: a slot whose streak exceeds ``max_step_retries`` is
  quarantined (requeue with committed prefix — a fresh slot's replay
  rewrites any poisoned K/V), and a request requeued more than
  ``max_requeues`` times is failed.
  """

  RETRY, REQUEUE, FAIL = "retry", "requeue", "fail"

  def __init__(self, max_step_retries: int = 1, max_requeues: int = 1):
    if max_step_retries < 0 or max_requeues < 0:
      raise ValueError("max_step_retries and max_requeues must be >= 0")
    self.max_step_retries = max_step_retries
    self.max_requeues = max_requeues
    self.bad_steps = 0        # engine steps with >= 1 bad slot
    self.step_retries = 0     # slot-steps replayed in place
    self.requeues = 0
    self.failures = 0

  def judge(self, slot_states: Dict[int, "object"],
            bad_slots: List[int],
            exercised: Optional[set] = None) -> Dict[int, str]:
    """Update streaks for one engine step and return the action per bad
    slot (``retry`` | ``requeue`` | ``fail``).  ``slot_states`` is the
    scheduler's ``active`` map (entries carry ``bad_streak`` and
    ``requeues``); good slots' streaks reset here — but only slots the
    step actually EXERCISED (``exercised``, the plan's num_valid>0 set;
    None = all): a budget-starved slot proved nothing this step, and
    resetting its streak would re-grant a poisoned slot its full retry
    allowance on every starvation interleave, postponing quarantine
    indefinitely."""
    if bad_slots:
      self.bad_steps += 1
    actions: Dict[int, str] = {}
    bad = set(bad_slots)
    for slot, state in slot_states.items():
      if slot not in bad:
        if exercised is None or slot in exercised:
          state.bad_streak = 0
        continue
      state.bad_streak += 1
      if state.bad_streak <= self.max_step_retries:
        self.step_retries += 1
        actions[slot] = self.RETRY
      elif state.requeues < self.max_requeues:
        self.requeues += 1
        actions[slot] = self.REQUEUE
      else:
        self.failures += 1
        actions[slot] = self.FAIL
    return actions

  def counters(self) -> Dict[str, int]:
    return {"bad_steps": self.bad_steps,
            "step_retries": self.step_retries,
            "requeues": self.requeues,
            "failed_requests": self.failures}
