"""Replica transports: the fault boundary between the router and its
replicas.

PR 8's control plane was honest that its fault injection is simulated —
replicas were thread-hosted in one synchronous loop, so a "kill" was a
raised exception and a "hang" shared the host's GIL.  This module makes
the fault domain real.  :class:`ReplicaTransport` is the surface the
:class:`~easyparallellibrary_tpu.serving.router.Router` already speaks
(submit / cancel / step / snapshot / restore / evacuate / drain signals
/ health beats / load signals / finished records), with two
implementations:

* :class:`InprocTransport` — today's
  :class:`~easyparallellibrary_tpu.serving.replica.EngineReplica`
  behind the transport interface.  The default, and byte-for-byte
  behavior-preserving: it IS an ``EngineReplica`` (subclass), adding
  only no-op transport affordances.
* :class:`ProcessTransport` — the replica lives in a **spawned
  subprocess that owns its own JAX runtime** (the unit at which real
  failures occur: a SIGKILL takes exactly one replica's memory, an OOM
  kills one process, a wedged device call stalls one child).  Parent
  and child speak length-prefixed JSON frames over a ``socketpair``.

The wire currency already exists: :meth:`Request.snapshot` /
:meth:`Request.restore` is the versioned serializable request form,
``FinishedRequest`` and the scheduler's migration snapshots are plain
dicts.  The transport layer is defensive end to end:

* **Per-call deadlines** with jittered exponential backoff
  (:func:`utils.retry.retry_call`) on idempotent calls.  ``submit`` /
  ``restore_request`` are made idempotent by child-side **uid dedup**:
  a retry after an ambiguous timeout (reply lost after the child
  applied the call) returns the recorded verdict instead of admitting
  twice.  ``step`` is never retried — it is not idempotent; a step
  whose reply times out **condemns** the replica (fenced with SIGKILL
  at evacuation, so a stalled child can never double-serve requests
  the fleet has already failed over).
* **Heartbeats over the wire** — every reply piggybacks a beat dict
  carrying the child's cumulative watchdog/bad-step watermarks, the
  ITL EWMA, load signals and the fused-step compile count; the router
  feeds it into the existing :class:`ReplicaHealth` machine
  (:meth:`ReplicaHealth.beat_from_wire`).
* **Child liveness** — ``waitpid`` (``Popen.poll``) plus pipe-EOF
  detection map a dead child to an immediate
  :class:`ReplicaDeadError`; the router treats it like any step
  exception: mark down, fail over.
* **Orphan reaping** — every spawned child is registered with an
  ``atexit`` reaper (a dead router never leaks children) and sets
  ``prctl(PR_SET_PDEATHSIG, SIGKILL)`` where available, so even a
  SIGKILLed parent takes its children down.
* **Crash-consistent failover** — the parent keeps a **snapshot
  journal**: each admitted request's spec (versioned snapshot) plus
  its last committed token watermark, advanced from step replies with
  cumulative acked-count resync (a lost reply is healed by the next
  reply's suffix — tokens are never double-committed because the child
  always resends from the watermark the parent last acked).  On child
  death ``evacuate()`` needs no RPC to the corpse: it fences the
  child (SIGKILL) and synthesizes scheduler-format snapshots from the
  journal, which the router replays bit-exactly onto survivors through
  the existing prefix-replay path.

Knobs: ``serving.router.transport`` (``"inproc"`` | ``"process"``),
``rpc_timeout_s`` / ``rpc_retries`` / ``rpc_backoff_s`` /
``spawn_timeout_s`` (docs/serving.md "Replica transports";
``make chaos-proc`` is the acceptance harness).
"""

from __future__ import annotations

import atexit
import importlib
import itertools
import json
import os
import signal as _signal
import socket
import struct
import subprocess
import sys
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.serving.replica import EngineReplica
from easyparallellibrary_tpu.serving.scheduler import (
    FinishedRequest, Request)
from easyparallellibrary_tpu.utils.logging import get_logger
from easyparallellibrary_tpu.utils.retry import retry_call

# Wire protocol version, checked at child init — a parent/child build
# mismatch must fail loudly at spawn, not corrupt a journal mid-flight.
WIRE_VERSION = 1

_LEN = struct.Struct(">I")
_MAX_FRAME = 256 * 1024 * 1024


class TransportError(RuntimeError):
  """Base class for transport-layer failures."""


class ReplicaDeadError(TransportError):
  """The child process is gone (waitpid reaped it / the socket hit
  EOF) or has been condemned — the router must fail its requests over
  via the parent-side journal."""


class TransportTimeout(TransportError):
  """One RPC exceeded its wire deadline.  Idempotent calls retry with
  jittered backoff; a ``step`` timeout condemns the replica instead
  (the call is not idempotent)."""


class RemoteError(TransportError):
  """The child REPLIED with an application error (``ok: false``) — an
  UNambiguous outcome: the call was received and did not apply.  Carries
  the remote exception's type name so callers can translate client
  errors (a remote ``ValueError`` for a malformed request must surface
  as a ``ValueError``, never as replica death)."""

  def __init__(self, message: str, etype: str = ""):
    super().__init__(message)
    self.etype = etype


# ------------------------------------------------------------- framing --


def send_frame(sock: socket.socket, obj: Any) -> None:
  """Write one length-prefixed JSON frame (4-byte big-endian length +
  UTF-8 payload)."""
  payload = json.dumps(obj).encode("utf-8")
  sock.sendall(_LEN.pack(len(payload)) + payload)


class FrameReader:
  """Incremental frame reader that survives deadlines mid-frame.

  Partial bytes stay buffered across calls, so a timeout between (or
  inside) frames never desynchronizes the stream — the next ``read``
  resumes exactly where the wire left off."""

  def __init__(self, sock: socket.socket):
    self.sock = sock
    self.buf = b""

  def read(self, timeout: Optional[float] = None) -> Any:
    """Next frame as a decoded object; ``timeout`` is a per-call
    deadline in seconds (None blocks forever).  Raises
    :class:`TransportTimeout` on deadline, :class:`ReplicaDeadError`
    on EOF."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
      if len(self.buf) >= _LEN.size:
        (n,) = _LEN.unpack_from(self.buf)
        if n > _MAX_FRAME:
          raise TransportError(f"frame length {n} exceeds limit")
        if len(self.buf) >= _LEN.size + n:
          payload = self.buf[_LEN.size:_LEN.size + n]
          self.buf = self.buf[_LEN.size + n:]
          return json.loads(payload.decode("utf-8"))
      if deadline is None:
        self.sock.settimeout(None)
      else:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          raise TransportTimeout("rpc deadline exceeded")
        self.sock.settimeout(remaining)
      try:
        chunk = self.sock.recv(1 << 16)
      except socket.timeout as e:
        raise TransportTimeout("rpc deadline exceeded") from e
      except OSError as e:
        raise ReplicaDeadError(f"socket error: {e}") from e
      if not chunk:
        raise ReplicaDeadError("peer closed the socket (pipe EOF)")
      self.buf += chunk


# -------------------------------------------------- wire (de)serializers --


def encode_finished(fin: FinishedRequest) -> Dict[str, Any]:
  return {"uid": fin.uid,
          "tokens": [int(t) for t in np.asarray(fin.tokens).reshape(-1)],
          "new_tokens": int(fin.new_tokens),
          "finish_reason": fin.finish_reason}


def decode_finished(d: Dict[str, Any]) -> FinishedRequest:
  return FinishedRequest(
      uid=d["uid"], tokens=np.asarray(d["tokens"], np.int32),
      new_tokens=int(d["new_tokens"]), finish_reason=d["finish_reason"])


def resolve_factory(factory) -> Tuple[Callable, Dict[str, Any]]:
  """Resolve a replica factory spec to ``(callable, kwargs)``.

  A spec is ``"module:attr"``, ``{"fn": "module:attr", "kwargs":
  {...}}``, or a module-level callable (serialized by reference).  The
  callable runs IN THE CHILD and returns ``(model, params)`` — the
  child owns its JAX runtime, so live arrays never cross the wire and
  parent/child params are bit-identical by construction (same factory,
  same seed, same backend)."""
  kwargs: Dict[str, Any] = {}
  if isinstance(factory, dict):
    kwargs = dict(factory.get("kwargs") or {})
    factory = factory["fn"]
  if callable(factory):
    return factory, kwargs
  mod, sep, attr = str(factory).partition(":")
  if not sep:
    raise ValueError(
        f"replica factory must be 'module:attr' (got {factory!r})")
  fn = importlib.import_module(mod)
  for part in attr.split("."):
    fn = getattr(fn, part)
  return fn, kwargs


def factory_spec(factory) -> Dict[str, Any]:
  """Wire form of a factory: ``{"fn": "module:attr", "kwargs": ...}``."""
  if isinstance(factory, dict):
    spec = {"fn": factory["fn"], "kwargs": dict(factory.get("kwargs")
                                                or {})}
  elif callable(factory):
    spec = {"fn": f"{factory.__module__}:{factory.__qualname__}",
            "kwargs": {}}
  else:
    spec = {"fn": str(factory), "kwargs": {}}
  # Fail in the parent, at construction — not in the child, at spawn.
  resolve_factory(spec)
  return spec


# ------------------------------------------------------- orphan reaping --

# Every live child Popen, so a dying router (normal exit, sys.exit, an
# unhandled exception) reaps its fleet: a dead router never leaks
# children.  The belt to the child-side prctl suspenders.
_LIVE_CHILDREN: Dict[int, subprocess.Popen] = {}
_REAPER_INSTALLED = False


def _reap_orphans() -> None:
  for pid, proc in list(_LIVE_CHILDREN.items()):
    try:
      if proc.poll() is None:
        proc.kill()
        proc.wait(timeout=5.0)
    except Exception:  # pragma: no cover - best-effort at interpreter exit
      pass
    _LIVE_CHILDREN.pop(pid, None)


def _register_child(proc: subprocess.Popen) -> None:
  global _REAPER_INSTALLED
  if not _REAPER_INSTALLED:
    atexit.register(_reap_orphans)
    _REAPER_INSTALLED = True
  _LIVE_CHILDREN[proc.pid] = proc


# ----------------------------------------------------------- interface --


class ReplicaTransport:
  """The surface the router drives a replica through.

  Serving: ``submit`` / ``cancel`` / ``step`` (or the pipelined
  ``step_send`` + ``step_recv`` pair, so process replicas overlap their
  sweeps) / ``has_work`` / ``finished``.  Load signals:
  ``queue_depth`` / ``num_active`` / ``num_slots`` / ``load``.  Health:
  ``watchdog_timeouts`` / ``bad_steps`` / ``itl_ewma_s`` /
  ``wire_beat`` / ``alive`` / ``exit_signal`` / ``compile_count``.
  Migration: ``snapshot_requests`` / ``restore_request`` /
  ``evacuate``.  Lifecycle: ``ensure_started`` / ``close``.
  Observability: ``rpc_counters``.

  Implementations are duck-typed (tests inject fakes); this class only
  documents the contract and supplies inert defaults for the
  transport-specific extras."""

  kind = "abstract"
  wire_beat: Optional[Dict[str, Any]] = None
  exit_signal: Optional[int] = None
  child_pid: Optional[int] = None

  @property
  def alive(self) -> bool:
    return True

  def ensure_started(self) -> bool:
    """(Re)start the replica's host if it is gone; True when a restart
    actually happened (the engine state is fresh — compile count resets,
    caches are cold)."""
    return False

  def step_send(self) -> None:
    """Dispatch one step without waiting (pipelining hook; no-op for
    in-process replicas, whose step runs at :meth:`step_recv`)."""

  def step_recv(self) -> List[FinishedRequest]:
    raise NotImplementedError

  def readiness_fd(self) -> Optional[int]:
    """select()-able file descriptor that becomes readable when this
    replica's pipelined step reply lands (the reactor's wait handle,
    serving/reactor.py).  ``None`` = no wire: the replica computes
    synchronously at :meth:`step_recv`, so the reactor treats it as
    ready the moment it is dispatched (the queue-backed shim)."""
    return None

  def step_ready(self) -> bool:
    """True when :meth:`step_recv` would return without blocking on the
    wire.  In-process replicas are always ready (their compute happens
    inside ``step_recv``); the process transport also reports ready
    when the step reply was already drained off the socket by an
    interleaved RPC (submit/cancel mid-cycle) and stashed."""
    return True

  def rpc_counters(self) -> Dict[str, int]:
    return {"rpc_retries": 0, "rpc_timeouts": 0, "child_restarts": 0}


class InprocTransport(EngineReplica, ReplicaTransport):
  """The default transport: PR 8's in-process ``EngineReplica``,
  unchanged (this IS an ``EngineReplica`` — same construction, same
  synchronous step, same memory — so the default fleet is byte-for-byte
  the pre-transport behavior), wearing the transport interface so the
  router can treat every fleet member uniformly.  The inert transport
  affordances (``alive``/``ensure_started``/``step_send``/
  ``rpc_counters``/...) come straight from :class:`ReplicaTransport`'s
  defaults; only the two with real content live here."""

  kind = "inproc"

  def step_recv(self) -> List[FinishedRequest]:
    return self.step()

  @property
  def compile_count(self) -> int:
    try:
      return int(self.engine._step_fn._cache_size())
    except Exception:
      return 0


# ------------------------------------------------------ process transport --


class _JournalEntry:
  """Parent-side recovery record for one admitted request: the
  versioned request snapshot plus the committed-token watermark
  advanced from step replies."""

  __slots__ = ("request", "generated", "submitted_at", "requeues",
               "first_token_emitted")

  def __init__(self, request: Dict[str, Any], submitted_at: float,
               generated: Optional[List[int]] = None, requeues: int = 0,
               first_token_emitted: bool = False):
    self.request = request
    self.generated: List[int] = list(generated or [])
    self.submitted_at = float(submitted_at)
    self.requeues = int(requeues)
    self.first_token_emitted = bool(first_token_emitted)

  def snapshot(self) -> Dict[str, Any]:
    return {"request": self.request,
            "generated": [int(t) for t in self.generated],
            "requeues": self.requeues,
            "first_token_emitted": (self.first_token_emitted
                                    or bool(self.generated)),
            "submitted_at": self.submitted_at}


class ProcessTransport(ReplicaTransport):
  """A replica hosted in a spawned subprocess owning its own JAX
  runtime (module docstring).  ``factory`` builds ``(model, params)``
  in the child; ``engine_kwargs`` must be JSON-serializable and pass
  through to the child's :class:`EngineReplica`."""

  kind = "process"

  def __init__(self, index: int, factory, *, config=None,
               engine_kwargs: Optional[Dict[str, Any]] = None,
               rpc_timeout_s: Optional[float] = None,
               rpc_retries: Optional[int] = None,
               rpc_backoff_s: Optional[float] = None,
               spawn_timeout_s: Optional[float] = None,
               checkpoint: Optional[str] = None,
               start: bool = True):
    from easyparallellibrary_tpu.env import Env
    self.index = index
    self._config = config if config is not None else Env.get().config
    rconf = self._config.serving.router
    self._factory = factory_spec(factory)
    self._engine_kwargs = dict(engine_kwargs or {})
    # Blue/green rollout (serving/rollout.py): when set, the child
    # restores THIS checkpoint over the factory's params at init (the
    # path rides the init frame; a validation failure fails the spawn,
    # never a live request).
    self._checkpoint = checkpoint
    self.rpc_timeout_s = (rpc_timeout_s if rpc_timeout_s is not None
                          else rconf.rpc_timeout_s)
    self.rpc_retries = (rpc_retries if rpc_retries is not None
                        else rconf.rpc_retries)
    self.rpc_backoff_s = (rpc_backoff_s if rpc_backoff_s is not None
                          else rconf.rpc_backoff_s)
    self.spawn_timeout_s = (spawn_timeout_s if spawn_timeout_s is not None
                            else rconf.spawn_timeout_s)
    # Crash-recovery journal: uid -> _JournalEntry, insertion-ordered by
    # admission; _service_order is the child's last reported line order.
    self._journal: "OrderedDict[Any, _JournalEntry]" = OrderedDict()
    self._service_order: List[Any] = []
    self.finished: Dict[Any, FinishedRequest] = {}
    self._finished_backlog: List[FinishedRequest] = []
    self.on_first_token: List[Callable[[Any], None]] = []
    # Parent-side per-iteration token delivery: fn(uid, [tok, ...]) for
    # every journal watermark advance — the child's scheduler commits
    # ride the step reply's `progress` suffixes, so the wire already
    # carries them; this fans the FRESH tokens (beyond what the parent
    # had) out exactly once, mirroring how `first` -> on_first_token.
    self.on_tokens: List[Callable[[Any, List[int]], None]] = []
    self.wire_beat: Optional[Dict[str, Any]] = None
    self.exit_signal: Optional[int] = None
    self.rpc_retries_total = 0
    self.rpc_timeouts_total = 0
    self.child_restarts = 0
    # Cross-process trace harvest + clock alignment (docs/
    # observability.md "Distributed tracing").  Every reply's beat can
    # carry the child tracer's clock; paired with the parent-side
    # send/recv perf_counter_ns stamps per rid it yields an NTP-style
    # midpoint offset estimate.  The best (smallest-RTT) sample wins
    # within a heartbeat-cadence resync window.
    obs = self._config.observability
    self._harvest_on = bool(obs.enabled and obs.harvest.enabled)
    self._harvest_final_timeout_s = float(obs.harvest.final_timeout_s)
    self.trace_events_harvested = 0
    self._send_ns: Dict[Any, int] = {}
    self._clock_offset_us: Optional[float] = None
    self._clock_rtt_ns: Optional[int] = None
    self._clock_at = 0.0
    self._clock_resync_s = max(float(rconf.heartbeat_s), 0.1)
    self.last_spawn_s = 0.0     # spawn-to-ready wall time (start())
    self._proc: Optional[subprocess.Popen] = None
    self._sock: Optional[socket.socket] = None
    self._reader: Optional[FrameReader] = None
    self._seq = itertools.count(1)
    self._pending: Dict[int, Dict[str, Any]] = {}
    self._inflight_step: Optional[int] = None
    self._condemned = False
    self._stats_cache = None
    if start:
      self.start()

  # ------------------------------------------------------------ lifecycle

  @property
  def child_pid(self) -> Optional[int]:
    return self._proc.pid if self._proc is not None else None

  @property
  def alive(self) -> bool:
    """Usable for RPC: a live child, an open socket, and no
    condemnation (a step timeout condemns — the child may be stalled
    mid-step and must be fenced, never spoken to again)."""
    if self._condemned or self._proc is None or self._sock is None:
      return False
    if self._proc.poll() is not None:
      self._note_exit()
      return False
    return True

  def _note_exit(self) -> None:
    if self._proc is not None and self._proc.returncode is not None:
      rc = self._proc.returncode
      self.exit_signal = -rc if rc < 0 else None
      _LIVE_CHILDREN.pop(self._proc.pid, None)

  def start(self) -> None:
    """Spawn the child, hand it the socketpair end, and block until its
    engine is built (``ready``).  The child process is registered with
    the atexit reaper before anything can fail past the spawn.
    ``last_spawn_s`` records the spawn-to-ready wall time — the cold
    capacity cost the autoscaler's scale-up actuation pays
    (serving/autoscale.py), surfaced so operators can weigh warm rejoin
    against cold spawn from evidence."""
    if self.alive:
      return
    t_spawn = time.monotonic()
    parent_sock, child_sock = socket.socketpair()
    try:
      env = dict(os.environ)
      # The child resolves the package the same way the parent did,
      # even when running from a source checkout that is not installed.
      pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
          os.path.abspath(__file__))))
      env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
      # `-c` rather than `-m`: runpy would re-execute replica.py as
      # __main__ after serving/__init__ already imported it, and warn.
      worker_cmd = (
          "from easyparallellibrary_tpu.serving.replica import "
          f"replica_worker_main; raise SystemExit(replica_worker_main("
          f"{child_sock.fileno()}))")
      self._proc = subprocess.Popen(
          [sys.executable, "-c", worker_cmd],
          pass_fds=(child_sock.fileno(),), env=env, close_fds=True)
    except Exception:
      parent_sock.close()
      raise
    finally:
      child_sock.close()
    _register_child(self._proc)
    self._sock = parent_sock
    self._reader = FrameReader(parent_sock)
    self._pending.clear()
    self._inflight_step = None
    self._condemned = False
    self.exit_signal = None
    self.wire_beat = None
    self._seq = itertools.count(1)
    # A fresh child is a fresh tracer timebase: the old offset (and the
    # min-RTT gate that protects it) must not survive a respawn.
    self._send_ns.clear()
    self._clock_offset_us = None
    self._clock_rtt_ns = None
    self._clock_at = 0.0
    try:
      init_id = self._post("init", {
          "wire_version": WIRE_VERSION,
          "index": int(self.index),
          "factory": self._factory,
          "engine_kwargs": self._engine_kwargs,
          "config": self._config.to_dict(),
          "checkpoint": self._checkpoint,
      })
      reply = self._wait(init_id, timeout=self.spawn_timeout_s)
    except Exception:
      # A child that failed init (version mismatch, factory error,
      # spawn deadline) must not linger half-born: fence before raising.
      self._fence()
      raise
    info = reply.get("result") or {}
    self.last_spawn_s = time.monotonic() - t_spawn
    if (self.wire_beat or {}).get("trace_now_us") is not None:
      # Handshake clock sample: the init reply's RTT spans the whole
      # engine build (useless for a midpoint estimate), so take one
      # tight ping now — _ingest pairs its send/recv stamps with the
      # beat's child clock and seeds the offset.
      try:
        self._call("ping", {}, retry=False, condemn=False,
                   timeout=min(self.rpc_timeout_s, 5.0))
      except TransportError:
        pass
    get_logger().info(
        "replica %d: process transport up (pid %d, backend %s, "
        "spawn %.1fs)", self.index, self._proc.pid,
        info.get("platform", "?"), self.last_spawn_s)

  def ensure_started(self) -> bool:
    """Respawn a dead/condemned child (breaker probe, operator rejoin).
    The fresh engine is cold: compile count resets, the KV cache is
    empty — exactly what a real process restart costs.  Requests the
    journal still owns (placed here, never failed over) are replayed
    into the fresh child in service order, so a respawn resumes its own
    backlog bit-exactly instead of stranding it."""
    if self.alive:
      return False
    self._fence()
    self.start()
    self.child_restarts += 1
    for entry in self._iter_journal():
      self._call("restore", {"snap": entry.snapshot(), "front": False})
    return True

  def _fence(self) -> None:
    """Make the child inert: SIGKILL if still running (a condemned or
    stalled child must never race the fleet for requests the journal is
    about to fail over), reap the pid, close the wire."""
    if self._proc is not None:
      if self._proc.poll() is None:
        try:
          self._proc.kill()
        except OSError:  # pragma: no cover - already gone
          pass
        try:
          self._proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:  # pragma: no cover
          pass
      self._note_exit()
    if self._sock is not None:
      try:
        self._sock.close()
      except OSError:  # pragma: no cover
        pass
      self._sock = None
      self._reader = None
    self._condemned = True
    # The corpse will never flush again: close whatever spans its
    # harvested ring left open, at its last rebased timestamp, so the
    # merged trace stays schema-valid and shows the work ENDING here.
    pid = self.child_pid
    if pid is not None:
      tracer = trace_lib.get_tracer()
      if tracer.enabled:
        tracer.close_remote(
            pid, reason="killed" if self.exit_signal else "lost")

  def kill(self, sig: int = _signal.SIGKILL) -> None:
    """Deliver ``sig`` to the child (the chaos harness's real-process
    fault injection rides this; see testing/chaos.py ProcessKiller)."""
    if self._proc is not None and self._proc.poll() is None:
      os.kill(self._proc.pid, sig)

  def close(self):
    if self.alive:
      try:
        sid = self._post("shutdown", {})
        self._wait(sid, timeout=min(5.0, self.rpc_timeout_s))
        self._proc.wait(timeout=5.0)
      except (TransportError, subprocess.TimeoutExpired):
        pass
    self._fence()

  # ------------------------------------------------------------- rpc core

  def _mark_dead(self) -> None:
    self._condemned = True
    if self._proc is not None and self._proc.poll() is not None:
      self._note_exit()

  def _post(self, method: str, params: Dict[str, Any]) -> int:
    if self._sock is None or self._condemned:
      raise ReplicaDeadError(f"replica {self.index}: transport closed")
    rid = next(self._seq)
    try:
      # Bound the send too (FrameReader leaves the last per-read
      # timeout on the shared socket, and a child that will not drain
      # its receive buffer for a full deadline is a dead replica, not
      # a reason to block the router forever).
      self._sock.settimeout(self.rpc_timeout_s)
      send_frame(self._sock, {"id": rid, "m": method, "p": params})
    except OSError as e:  # socket.timeout included
      self._mark_dead()
      raise ReplicaDeadError(
          f"replica {self.index}: send failed ({e})") from e
    # Clock-offset raw material: the reply pairs this send stamp with
    # its receive stamp (bounded: abandoned rids are evicted oldest
    # first — their replies will never arrive).
    self._send_ns[rid] = time.perf_counter_ns()
    while len(self._send_ns) > 256:
      self._send_ns.pop(next(iter(self._send_ns)))
    return rid

  def _read_frame(self, timeout: Optional[float]) -> Dict[str, Any]:
    # Seam for wire-level chaos (testing/chaos.py ReplyDropper).
    return self._reader.read(timeout)

  def _wait(self, rid: int, timeout: Optional[float] = None
            ) -> Dict[str, Any]:
    if rid in self._pending:
      frame = self._pending.pop(rid)
      self._prune_pending()
      return self._check(frame)
    deadline = time.monotonic() + (self.rpc_timeout_s
                                   if timeout is None else timeout)
    while True:
      remaining = deadline - time.monotonic()
      if remaining <= 0:
        self.rpc_timeouts_total += 1
        raise TransportTimeout(
            f"replica {self.index}: rpc {rid} timed out")
      try:
        frame = self._read_frame(remaining)
      except TransportTimeout:
        self.rpc_timeouts_total += 1
        raise
      except ReplicaDeadError:
        self._mark_dead()
        raise
      self._ingest(frame)
      if frame.get("id") == rid:
        self._prune_pending()
        return self._check(frame)
      self._pending[frame["id"]] = frame

  def _prune_pending(self) -> None:
    """Drop stashed replies no one will ever wait on again.  The router
    is single-threaded, so the only rid that can still be awaited after
    a ``_wait`` returns is the pipelined in-flight step; everything
    else belongs to abandoned (timed-out, retried) calls whose content
    ``_ingest`` already applied — keeping the frames would leak."""
    for k in [k for k in self._pending if k != self._inflight_step]:
      del self._pending[k]

  def _check(self, frame: Dict[str, Any]) -> Dict[str, Any]:
    if not frame.get("ok", False):
      etype = frame.get("etype", "error")
      raise RemoteError(
          f"replica {self.index}: remote {etype}: "
          f"{frame.get('error', '?')}", etype=etype)
    return frame

  def _update_clock(self, send_ns: Optional[int], recv_ns: int,
                    child_now_us: Optional[float]) -> None:
    """NTP-style midpoint offset estimate: the child's tracer clock at
    ``child_now_us`` corresponds to roughly the midpoint of this RPC's
    send/recv ``perf_counter_ns`` pair, so
    ``parent_ts ≈ child_ts + offset``.  The error bound is RTT/2:
    prefer the smallest-RTT sample, re-opening acceptance on the
    heartbeat cadence (``serving.router.heartbeat_s``) so the estimate
    tracks long-run drift without letting a step-inflated RTT (the
    reply that waited on a whole engine step) wreck a tight one."""
    if send_ns is None or child_now_us is None:
      return
    tracer = trace_lib.get_tracer()
    if not tracer.enabled:
      return
    rtt = recv_ns - send_ns
    now = time.monotonic()
    stale = now - self._clock_at >= self._clock_resync_s
    if self._clock_rtt_ns is not None and rtt >= self._clock_rtt_ns \
        and not (stale and rtt <= 4 * self._clock_rtt_ns):
      return
    self._clock_offset_us = (tracer.at_us((send_ns + recv_ns) // 2)
                             - float(child_now_us))
    self._clock_rtt_ns = rtt
    self._clock_at = now

  def _harvest_ingest(self, result: Any) -> None:
    """Merge a reply's piggybacked trace chunk into the ambient tracer
    (exactly once — `_ingest` is the single funnel every received frame
    passes through)."""
    chunk = result.get("trace") if isinstance(result, dict) else None
    if not chunk:
      return
    tracer = trace_lib.get_tracer()
    if not tracer.enabled or self._clock_offset_us is None:
      return
    pid = self.child_pid or int((self.wire_beat or {}).get("pid") or 0)
    if not pid:
      return
    self.trace_events_harvested += tracer.ingest_remote(
        pid, chunk.get("events") or (),
        offset_us=self._clock_offset_us,
        label=f"replica{self.index} worker (pid {pid})")

  def _ingest(self, frame: Dict[str, Any]) -> None:
    """Apply a reply's side-band content exactly once, whether it is
    the awaited reply or a stale one that surfaced while waiting for a
    different id (the lost-reply recovery path: a late step reply still
    advances the journal watermark and still surfaces its finishes).
    Side-band now includes the distributed-tracing material: every
    beat's child-clock sample feeds the offset estimate, and any
    reply — step piggyback, explicit harvest, evacuate/shutdown final
    flush, or the worker's unsolicited EOF flush — may carry a trace
    chunk."""
    recv_ns = time.perf_counter_ns()
    send_ns = self._send_ns.pop(frame.get("id"), None)
    beat = frame.get("beat")
    if beat:
      self.wire_beat = beat
      self._update_clock(send_ns, recv_ns, beat.get("trace_now_us"))
    if not frame.get("ok", False):
      return
    result = frame.get("result") or {}
    self._harvest_ingest(result)
    if frame.get("m") != "step":
      return
    for uid, start, tokens in result.get("progress", ()):
      entry = self._journal.get(uid)
      if entry is None:
        continue
      # Cumulative-watermark resync: the child sends the suffix from
      # the count the parent last acked; overlap overwrites (the
      # stream is deterministic, so overlapping tokens are identical).
      prev = len(entry.generated)
      entry.generated[start:] = [int(t) for t in tokens]
      if self.on_tokens and len(entry.generated) > prev:
        # Stream delivery exactly once: only the tokens beyond what the
        # journal already held are fresh — a stale frame's overlap
        # re-applied above never re-fires (deterministic stream).
        fresh = list(entry.generated[prev:])
        for cb in self.on_tokens:
          cb(uid, fresh)
    order = result.get("order")
    if order is not None:
      self._service_order = list(order)
    fins = [decode_finished(d) for d in result.get("finished", ())]
    for fin in fins:
      self._journal.pop(fin.uid, None)
      self.finished[fin.uid] = fin
    self._finished_backlog.extend(fins)
    for uid in result.get("first", ()):
      for cb in self.on_first_token:
        cb(uid)

  def _call(self, method: str, params: Dict[str, Any], *,
            retry: bool = True, timeout: Optional[float] = None,
            condemn: bool = True) -> Dict[str, Any]:
    """One request/reply exchange.  ``retry=True`` (idempotent calls
    only) rides utils.retry with jittered exponential backoff; the
    final timeout condemns the replica (``condemn=True``) — an
    unresponsive child must be fenced, not trusted with half-applied
    state.  Pass ``condemn=False`` for best-effort observability polls
    whose deadline miss must NEVER cost a healthy replica its life."""

    def once():
      rid = self._post(method, params)
      return self._wait(rid, timeout=timeout)

    def note(attempt, exc):
      self.rpc_retries_total += 1

    try:
      if not retry or self.rpc_retries <= 0:
        return once()
      return retry_call(once, retries=self.rpc_retries,
                        backoff_s=self.rpc_backoff_s,
                        max_backoff_s=max(self.rpc_backoff_s * 8, 1.0),
                        jitter=0.25, exceptions=(TransportTimeout,),
                        on_retry=note, what=f"replica {self.index} {method}")
    except TransportTimeout as e:
      if not condemn:
        raise
      self._condemned = True
      raise ReplicaDeadError(
          f"replica {self.index}: {method} exhausted its deadline "
          f"({self.rpc_timeout_s:.1f}s x {self.rpc_retries + 1}); "
          f"condemned for fencing") from e

  # -------------------------------------------------------------- serving

  def submit(self, request: Request) -> bool:
    """Journal-then-send: the request spec is journaled BEFORE the RPC,
    so an ambiguous outcome (timeout, child death mid-call) is always
    recoverable — failover replays the journal entry, and the child's
    uid dedup guarantees a retried or replayed submit admits once."""
    snap = request.snapshot()
    uid = request.uid
    self._journal[uid] = _JournalEntry(snap, time.monotonic())
    try:
      reply = self._call("submit", {"snap": snap})
    except RemoteError as e:
      # The child REPLIED with an error: unambiguously not admitted —
      # the journal must not resurrect it later.  A remote client
      # error (malformed request) surfaces as the client exception the
      # engine contract promises, never as replica death.
      self._journal.pop(uid, None)
      if e.etype == "ValueError":
        raise ValueError(str(e)) from e
      raise
    result = reply.get("result") or {}
    accepted = bool(result.get("accepted"))
    if not accepted:
      self._journal.pop(uid, None)
      fin = result.get("finished")
      if fin is not None:
        self.finished[uid] = decode_finished(fin)
    return accepted

  def cancel(self, uid: Any) -> bool:
    if not self.alive:
      entry = self._journal.pop(uid, None)
      if entry is None:
        return False
      generated = np.asarray(entry.generated, np.int32)
      fin = FinishedRequest(
          uid=uid,
          tokens=np.concatenate([
              np.asarray(entry.request["prompt"], np.int32), generated]),
          new_tokens=int(generated.size), finish_reason="cancelled")
      self.finished[uid] = fin
      self._finished_backlog.append(fin)
      return True
    reply = self._call("cancel", {"uid": uid})
    return bool((reply.get("result") or {}).get("cancelled"))

  def _acked(self) -> List[List[Any]]:
    return [[uid, len(entry.generated)]
            for uid, entry in self._journal.items()]

  def step_send(self) -> None:
    """Dispatch one step (pipelined: the router sends to every process
    replica, then collects — concurrent children overlap their sweeps).
    The request carries the journal's acked watermarks so the child
    knows exactly which token suffix the parent still needs."""
    if self._inflight_step is not None:
      return
    self._inflight_step = self._post("step", {"acked": self._acked()})

  def readiness_fd(self) -> Optional[int]:
    """The transport socket's fd while a step is in flight — readable
    exactly when the child's reply (or any side-band frame) lands, which
    is the reactor's dispatch-the-moment-it-answers signal."""
    if self._inflight_step is None or self._sock is None \
        or self._condemned:
      return None
    try:
      return self._sock.fileno()
    except OSError:
      return None

  def step_ready(self) -> bool:
    """True when the pipelined step reply is already stashed (an
    interleaved submit/cancel drained it off the socket while waiting
    for its own reply) — the socket will never poll readable for it, so
    the reactor must collect it directly."""
    return (self._inflight_step is not None
            and self._inflight_step in self._pending)

  def step_recv(self) -> List[FinishedRequest]:
    """Collect the pipelined step.  NEVER retried: a step is not
    idempotent, so a timeout condemns the replica — the journal (not a
    second RPC) is the recovery path, and the condemned child is fenced
    with SIGKILL at evacuation so it cannot double-serve."""
    rid, self._inflight_step = self._inflight_step, None
    if rid is None:
      rid = self._post("step", {"acked": self._acked()})
    try:
      self._wait(rid)
    except TransportTimeout as e:
      self._condemned = True
      raise ReplicaDeadError(
          f"replica {self.index}: step reply missed its "
          f"{self.rpc_timeout_s:.1f}s deadline; condemned for fencing"
      ) from e
    fins, self._finished_backlog = self._finished_backlog, []
    return fins

  def step(self) -> List[FinishedRequest]:
    self.step_send()
    return self.step_recv()

  @property
  def has_work(self) -> bool:
    if not self.alive:
      return bool(self._journal)
    beat = self.wire_beat or {}
    return bool(beat.get("has_work")) or bool(self._journal)

  # --------------------------------------------------------- load signals

  def _beat_get(self, key: str, default=0):
    beat = self.wire_beat or {}
    return beat.get(key, default)

  @property
  def queue_depth(self) -> int:
    return int(self._beat_get("queue_depth"))

  @property
  def num_active(self) -> int:
    return int(self._beat_get("num_active"))

  @property
  def num_slots(self) -> int:
    return int(self._beat_get("num_slots",
                              self._engine_kwargs.get("num_slots", 1)))

  @property
  def load(self) -> int:
    if not self.alive:
      return len(self._journal)
    return int(self._beat_get("load", len(self._journal)))

  # ------------------------------------------------------- health signals

  @property
  def watchdog_timeouts(self) -> int:
    return int(self._beat_get("watchdog_timeouts"))

  @property
  def bad_steps(self) -> int:
    return int(self._beat_get("bad_steps"))

  @property
  def itl_ewma_s(self) -> float:
    return float(self._beat_get("itl_ewma_s", 0.0))

  @property
  def compile_count(self) -> int:
    return int(self._beat_get("compiles"))

  @property
  def checkpoint_version(self) -> int:
    """This replica's checkpoint version, from the last wire beat
    (falling back to the engine kwargs the child was spawned with —
    correct before the first beat arrives, same pattern as
    ``num_slots``)."""
    return int(self._beat_get(
        "checkpoint_version",
        self._engine_kwargs.get("checkpoint_version", 0)))

  def rpc_counters(self) -> Dict[str, int]:
    return {"rpc_retries": int(self.rpc_retries_total),
            "rpc_timeouts": int(self.rpc_timeouts_total),
            "child_restarts": int(self.child_restarts),
            "trace_events_harvested": int(self.trace_events_harvested)}

  def harvest(self, drain: bool = True) -> int:
    """Pull the child's tracer ring into the ambient tracer via the
    explicit low-priority ``harvest`` RPC (each reply stays within the
    configured sweep byte bound; ``drain=True`` loops until the ring is
    dry or ``observability.harvest.final_timeout_s`` elapses).  Best
    effort: a deadline miss is an observability gap, never a death
    sentence for a healthy replica.  Returns the events harvested."""
    if not self.alive or not self._harvest_on:
      return 0
    before = self.trace_events_harvested
    deadline = time.monotonic() + self._harvest_final_timeout_s
    while True:
      try:
        reply = self._call("harvest", {}, retry=False, condemn=False,
                           timeout=min(self.rpc_timeout_s, 5.0))
      except TransportError:
        break
      result = reply.get("result") or {}
      if not drain or result.get("done") or not result.get("trace"):
        break
      if time.monotonic() >= deadline:
        break
    return self.trace_events_harvested - before

  @property
  def stats(self):
    """Fleet-rollup stats: fetched from the child on demand and loaded
    into a parent-side ServingStats twin; the last good snapshot is
    served when the child is unreachable (a dead replica's history
    still belongs in the rollup)."""
    if self.alive:
      try:
        # condemn=False: a slow metrics reply is an observability miss,
        # never a death sentence for a healthy replica.
        reply = self._call("stats", {}, retry=False, condemn=False,
                           timeout=min(self.rpc_timeout_s, 5.0))
        state = (reply.get("result") or {}).get("stats")
        if state is not None:
          from easyparallellibrary_tpu.profiler.serving import ServingStats
          if self._stats_cache is None:
            self._stats_cache = ServingStats()
          self._stats_cache.load_state(state)
      except TransportError:
        pass
    return self._stats_cache

  # ------------------------------------------------------------ migration

  def snapshot_requests(self) -> List[Dict[str, Any]]:
    if self.alive:
      reply = self._call("snapshot", {})
      return list((reply.get("result") or {}).get("snaps", ()))
    return [e.snapshot() for e in self._iter_journal()]

  def owns(self, uid: Any) -> bool:
    """True when this transport's journal holds ``uid`` — i.e. an
    ambiguously-applied call left the request HERE to recover (the
    router uses this to avoid double-placing a snapshot whose restore
    timed out but may have landed)."""
    return uid in self._journal

  def restore_request(self, snap: Dict[str, Any],
                      front: bool = False) -> Any:
    uid = snap["request"]["uid"]
    pinned = snap["request"].get("checkpoint_version")
    if pinned is not None and int(pinned) != self.checkpoint_version:
      # Refused BEFORE journaling: a cross-version snapshot must never
      # enter this replica's recovery journal (the child would reject
      # the replay anyway — the scheduler enforces the same policy —
      # but the parent-side check keeps the refusal unambiguous and
      # free of wire traffic).
      raise ValueError(
          f"cross-version restore refused: request {uid!r} is pinned to "
          f"checkpoint version {int(pinned)} but replica {self.index} "
          f"serves version {self.checkpoint_version} — prefix replay "
          f"across versions is not bit-exact (docs/robustness.md)")
    self._journal[uid] = _JournalEntry(
        snap["request"], snap.get("submitted_at", time.monotonic()),
        generated=snap.get("generated"),
        requeues=snap.get("requeues", 0),
        first_token_emitted=snap.get("first_token_emitted", False))
    try:
      self._call("restore", {"snap": snap, "front": bool(front)})
    except RemoteError:
      # Unambiguous rejection: the snapshot is still the caller's to
      # re-place — a stale journal entry here would double-serve it.
      self._journal.pop(uid, None)
      raise
    return uid

  def _iter_journal(self) -> List[_JournalEntry]:
    """Journal entries in the child's last reported service order
    (requests never seen in a reply keep submit order, at the back)."""
    ordered: List[_JournalEntry] = []
    seen = set()
    for uid in self._service_order:
      entry = self._journal.get(uid)
      if entry is not None and uid not in seen:
        ordered.append(entry)
        seen.add(uid)
    for uid, entry in self._journal.items():
      if uid not in seen:
        ordered.append(entry)
    return ordered

  def evacuate(self) -> List[Dict[str, Any]]:
    """Snapshot + remove every queued/in-flight request.  Graceful RPC
    while the child is responsive (exact scheduler snapshots); on a
    dead, condemned or unresponsive child: **fence** (SIGKILL — a
    stalled child must not keep decoding requests the fleet is about
    to re-place) and synthesize snapshots from the journal — no RPC to
    the corpse, bit-exact by prefix replay from the last committed
    watermark."""
    if self.alive:
      try:
        reply = self._call("evacuate", {}, retry=False)
        snaps = list((reply.get("result") or {}).get("snaps", ()))
        for snap in snaps:
          self._journal.pop(snap["request"]["uid"], None)
        # Anything the journal still holds was resolved child-side in
        # replies we already ingested; nothing else to recover.
        return snaps
      except TransportError:
        pass
    self._fence()
    snaps = [e.snapshot() for e in self._iter_journal()]
    self._journal.clear()
    self._service_order = []
    if snaps:
      get_logger().warning(
          "replica %d: child fenced%s; recovered %d request(s) from the "
          "parent-side journal", self.index,
          (f" (exit signal {self.exit_signal})"
           if self.exit_signal else ""), len(snaps))
    return snaps

  def __repr__(self):
    return (f"ProcessTransport({self.index}, pid={self.child_pid}, "
            f"alive={self.alive}, journal={len(self._journal)})")
