"""Fleet-level SLO actuator: burn-rate breaches resize the live
replica set through the router's existing levers.

The serving stack already owns every mechanism this policy needs:
graceful :meth:`Router.drain` / warm :meth:`Router.rejoin` (PR 8), a
real process-spawn path behind :class:`ProcessTransport` (PR 11, now
exposed as :meth:`Router.add_replica`), and the
:class:`~easyparallellibrary_tpu.observability.slo.SLOMonitor`'s
burn-rate rules that already prove a breach is sustained (fast AND slow
window).  This module is only the policy that connects them:

* **grow** — on a sustained SLO burn (any :class:`BurnRateRule` breach,
  plus any rule named in ``serving.autoscale.rules``), add one replica:
  a replica the autoscaler ITSELF previously drained rejoins WARM
  (compiled step and cache intact — the cheapest capacity in the
  fleet; an OPERATOR-drained replica is maintenance in progress and is
  never silently reverted), else a new replica is built cold through
  :meth:`Router.add_replica` (a REAL subprocess spawn on the process
  transport — synchronous, like every router action: the sweep blocks
  for the spawn, the same cost the breaker's respawn probe already
  pays; an off-thread spawn with the replica unroutable until ready is
  the ROADMAP follow-up);
* **shrink** — once the error budget has recovered (no relevant breach
  for ``scale_down_cooldown_s``), gracefully :meth:`drain` the
  youngest-added live replica back out, never below ``min_replicas``;
* **flap breaker** — a scale-up that lands inside ``flap_window_s`` of
  a scale-down is a flap: each trip DOUBLES the scale-up hold-out
  (capped at 2^6, decaying one trip per clean window) — the same
  doubling-hold-out shape as PR 8's replica circuit breaker, so an
  oscillating load curve converges to a steady set instead of paying a
  cold spawn per wave.

Actuations move only the replica SET — never a live engine's geometry —
so every stream stays bit-exact and every replica's compile count stays
1 (a cold spawn compiles its own step once, exactly like any restart).
Each action emits a ``serving/actuation`` trace instant, an
``slo_events.jsonl`` line (:meth:`SLOMonitor.note_actuation`), and the
``scale_ups`` / ``scale_downs`` / ``autoscale_holds`` / ``flap_trips``
counters on the ``serving/fleet/*`` rollup (published immediately, not
on the heartbeat cadence — an actuation opens its evidence window at
the action).

Pure host policy — injectable clock (the router's), no jax; unit tests
drive it with fake replicas and a fake clock
(tests/test_serving_autoscale.py).  Knobs: ``serving.autoscale.*``
(docs/robustness.md "Self-healing fleet").
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.utils.logging import get_logger

# Flap hold-out doubling cap: 2^6 (mirrors ReplicaHealth.cooldown_s).
_MAX_FLAP_DOUBLINGS = 6


class FleetAutoscaler:
  """SLO-burn-driven replica-set policy for one Router (module
  docstring).  Built by the router when ``serving.autoscale.enabled``;
  the router calls :meth:`on_step` at the top of every fleet sweep —
  replica-list mutation is only safe between sweeps.

  Threading mirrors the autotuner: breach callbacks may arrive from a
  watchdog thread, so the listener only RECORDS under a lock and every
  action happens in :meth:`on_step` on the router's thread.
  """

  def __init__(self, router, config=None):
    conf = (config if config is not None
            else Env.get().config).serving.autoscale
    self.router = router
    self.clock = router.clock
    self.min_replicas = conf.min_replicas
    self.max_replicas = conf.max_replicas
    self.scale_up_cooldown_s = conf.scale_up_cooldown_s
    self.scale_down_cooldown_s = conf.scale_down_cooldown_s
    self.flap_window_s = conf.flap_window_s
    self._rules = set(conf.rules)
    self.scale_ups = 0
    self.scale_downs = 0
    self.holds = 0              # actions suppressed by cooldown/hold-out
    self.flap_trips = 0
    self.spawn_failures = 0
    # Replica indices this policy currently OWNS (spawned or rejoined
    # into service); shrink only ever drains from this set, and a
    # drain moves the entry to _parked (eligible for warm rejoin) —
    # the operator's base fleet is never the autoscaler's to take
    # below its provisioned size, and an OPERATOR-drained replica is
    # never its rejoin target.
    self._added: List[int] = []
    self._parked: List[int] = []
    self._last_up_t: Optional[float] = None
    self._last_down_t: Optional[float] = None
    self._flap_decay_t: Optional[float] = None
    self._lock = threading.Lock()
    self._pending_rule: Optional[str] = None
    self._last_breach_t: Optional[float] = None
    monitor = router._slo
    from easyparallellibrary_tpu.observability.slo import BreachPressure
    self._probe = BreachPressure(
        monitor, lambda rule, _key: rule in self._relevant_rules())
    if monitor is not None:
      monitor.add_listener(self._on_breach, weak=True)
    else:
      get_logger().warning(
          "serving.autoscale.enabled without observability.slo.enabled: "
          "the autoscaler has no burn signal and will never actuate")
    if len(router.replicas) >= self.max_replicas:
      get_logger().warning(
          "serving.autoscale.max_replicas (%d) <= current fleet size "
          "(%d): every scale-up will be held — raise max_replicas if "
          "the fleet should grow under burn", self.max_replicas,
          len(router.replicas))
    get_logger().info(
        "fleet autoscaler: %d..%d replicas, up/down cooldown "
        "%.1fs/%.1fs, flap window %.1fs, extra rules %s",
        self.min_replicas, self.max_replicas, self.scale_up_cooldown_s,
        self.scale_down_cooldown_s, self.flap_window_s,
        sorted(self._rules) or "(burn rules only)")

  # ----------------------------------------------------------- listening

  def _on_breach(self, rule: str, payload: Dict[str, Any]) -> None:
    """Record a relevant breach.  Burn-rate breaches (payload carries
    the window burns) always qualify — the rule itself proved the burn
    is sustained across fast AND slow windows; threshold rules only
    when named in ``serving.autoscale.rules``."""
    if "fast_burn" not in payload and rule not in self._rules:
      return
    with self._lock:
      self._pending_rule = rule
      self._last_breach_t = self.clock()

  # ------------------------------------------------------------- policy

  def _live(self) -> List[int]:
    """Replica indices serving or able to serve (healthy + suspect);
    draining and down replicas are capacity already removed."""
    return [i for i, h in enumerate(self.router.health)
            if h.state in ("healthy", "suspect")]

  def _relevant_rules(self) -> set:
    monitor = self.router._slo
    if monitor is None:
      return set(self._rules)
    from easyparallellibrary_tpu.observability.slo import BurnRateRule
    return ({r.name for r in monitor.rules
             if isinstance(r, BurnRateRule)} | self._rules)

  def _pressure(self) -> bool:
    """Is any relevant breach stream STILL breached?  A breach event
    fires only on the transition; an overload one replica-add did not
    absorb looks like a burn stream that never recovers, so sustained
    pressure is polled (slo.BreachPressure owns the liveness
    invariant).  While the breach is alive ``_last_breach_t``
    refreshes, so the quiet-window gates below never read a live burn
    as recovered; a wedged stream whose records stopped flowing lets
    the timestamp age out."""
    pressured, fresh = self._probe.poll()
    if fresh:
      with self._lock:
        self._last_breach_t = self.clock()
    return pressured

  def scale_up_holdout_s(self) -> float:
    """Current scale-up hold-out: the base cooldown doubled per flap
    trip (capped) — PR 8's breaker shape applied to capacity."""
    return self.scale_up_cooldown_s * (
        2 ** min(self.flap_trips, _MAX_FLAP_DOUBLINGS))

  def on_step(self, now: Optional[float] = None) -> None:
    """One fleet-sweep boundary: act on a recorded breach (grow), or on
    a recovered budget (shrink), honoring bounds/cooldowns/hold-outs."""
    now = self.clock() if now is None else now
    if self._parked:
      # A parked claim is valid only while the drain THIS policy
      # started is still in effect: the moment a parked replica leaves
      # "draining" through any other path (an operator rejoined it,
      # or it died), the claim is void — otherwise a LATER operator
      # maintenance drain of the same index would read as ours and a
      # breach could silently revert it.
      self._parked = [i for i in self._parked
                      if self.router.health[i].state == "draining"]
    with self._lock:
      rule, self._pending_rule = self._pending_rule, None
    if rule is not None:
      self._maybe_scale_up(rule, now)
      return
    # _pressure() refreshes _last_breach_t while the breached streams'
    # records keep flowing — a live sustained burn keeps the quiet
    # window open; a wedged-silent stream lets it close (stale escape).
    pressured = self._pressure()
    with self._lock:
      last_breach_t = self._last_breach_t
    if (pressured and last_breach_t is not None
        and now - last_breach_t < self.scale_down_cooldown_s):
      # Sustained burn one add did not absorb: keep growing, one
      # replica per hold-out window (the checks here pre-gate so the
      # holds counter only counts suppressed breach EVENTS).
      if (len(self._live()) < self.max_replicas
          and (self._last_up_t is None
               or now - self._last_up_t >= self.scale_up_holdout_s())):
        self._maybe_scale_up("sustained", now)
      return
    # Flap-trip decay: a full clean window without any scaling action
    # forgives one trip (ReplicaHealth.note_stable's analogue).
    if self.flap_trips:
      quiet = max(self._last_up_t or 0.0, self._last_down_t or 0.0,
                  self._flap_decay_t or 0.0)
      if now - quiet >= self.flap_window_s:
        self.flap_trips -= 1
        self._flap_decay_t = now   # one forgiveness per clean window
    if not self._added or last_breach_t is None:
      # Nothing autoscaler-owned in service: the operator's base set
      # is never drained — min_replicas is a floor, not a target.
      return
    quiet_since = max(
        last_breach_t, self._last_up_t or 0.0, self._last_down_t or 0.0)
    if now - quiet_since >= self.scale_down_cooldown_s:
      self._maybe_scale_down(now)

  def _maybe_scale_up(self, rule: str, now: float) -> None:
    live = self._live()
    if len(live) >= self.max_replicas:
      self.holds += 1
      return
    if (self._last_up_t is not None
        and now - self._last_up_t < self.scale_up_holdout_s()):
      self.holds += 1
      return
    flapped = (self._last_down_t is not None
               and now - self._last_down_t < self.flap_window_s)
    router = self.router
    # Cheapest capacity first: a replica THIS policy drained rejoins
    # WARM.  Operator-drained replicas are maintenance in progress —
    # reverting one on a breach would silently undo a rolling restart.
    parked = [i for i in self._parked
              if router.health[i].state == "draining"]
    if parked:
      index = parked[-1]
      if not router.rejoin(index):
        self.holds += 1
        return
      self._parked.remove(index)
      action = "rejoin"
    else:
      try:
        index = router.add_replica()
      except Exception as e:  # noqa: BLE001 — a failed spawn must not
        self.spawn_failures += 1          # take the control plane down
        get_logger().error(
            "autoscale: replica spawn failed (%s: %s); holding",
            type(e).__name__, e)
        # Stamp AFTER the failed attempt (same rule as the success
        # path): a spawn that blocked until spawn_timeout_s must buy a
        # full cooldown of actual serving before the retry, not an
        # immediate back-to-back doomed attempt.
        self._last_up_t = self.clock()
        return
      action = "spawn"
    if index not in self._added:
      # Autoscaler-owned capacity (spawned OR rejoined into service):
      # exactly the set shrink may later drain back out.
      self._added.append(index)
    if flapped:
      # Growing right after shrinking — and only when the grow actually
      # LANDED: the load is oscillating around the capacity step, so
      # the next hold-out doubles (a failed spawn is not a flap).
      self.flap_trips = min(self.flap_trips + 1, _MAX_FLAP_DOUBLINGS)
    self.scale_ups += 1
    # Stamp AFTER the action: a cold spawn blocks for seconds, and a
    # cooldown counted from before it would let the very next sweep
    # read the whole spawn as "quiet" and drain the replica right back.
    self._last_up_t = self.clock()
    self._emit("scale_up", action, index, rule)

  def _maybe_scale_down(self, now: float) -> None:
    live = self._live()
    if len(live) <= self.min_replicas:
      return
    # Youngest-added live replica, LIFO — and ONLY autoscaler-owned
    # capacity: if everything it added is already gone (e.g. the
    # spawned replica died), the operator's base set is not a fallback.
    added_live = [i for i in self._added if i in live]
    if not added_live:
      return
    index = added_live[-1]
    self._added.remove(index)
    self._parked.append(index)   # eligible for a future warm rejoin
    self.router.drain(index)
    self.scale_downs += 1
    self._last_down_t = self.clock()
    self._emit("scale_down", "drain", index, "recovered")

  # ------------------------------------------------------------ emission

  def counters(self) -> Dict[str, float]:
    """Fleet-rollup counters (merged into Router.router_counters, so
    they ride the ``serving/fleet/*`` schema with zero new plumbing)."""
    return {"scale_ups": float(self.scale_ups),
            "scale_downs": float(self.scale_downs),
            "autoscale_holds": float(self.holds),
            "flap_trips": float(self.flap_trips)}

  def _emit(self, action: str, mechanism: str, index: int,
            rule: str) -> None:
    router = self.router
    live = len(self._live())
    payload = {"actuator": "autoscale", "action": action,
               "mechanism": mechanism, "replica": int(index),
               "rule": rule, "live_replicas": live,
               "knobs": {"live_replicas":
                         [live - 1 if action == "scale_up" else live + 1,
                          live]}}
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/actuation", cat="serving", track="serving",
          args={"actuator": "autoscale", "action": action,
                "mechanism": mechanism, "replica": int(index),
                "rule": rule, "live_replicas": live})
      tracer.counter("serving/live_replicas", live)
    if router._slo is not None:
      router._slo.note_actuation("autoscale", payload, step=router.steps)
    # Immediate rollup: the actuation's counter evidence lands at the
    # action, not up to a heartbeat later (Router._note_incident's rule).
    router._note_incident()
    get_logger().warning(
        "autoscale: %s replica %d via %s (rule %s) -> %d live "
        "(trips %d, next hold-out %.1fs)", action, index, mechanism,
        rule, live, self.flap_trips, self.scale_up_holdout_s())
