"""Fleet-level SLO actuator: burn-rate breaches resize the live
replica set through the router's existing levers.

The serving stack already owns every mechanism this policy needs:
graceful :meth:`Router.drain` / warm :meth:`Router.rejoin` (PR 8), a
real process-spawn path behind :class:`ProcessTransport` (PR 11, now
exposed as :meth:`Router.add_replica`), and the
:class:`~easyparallellibrary_tpu.observability.slo.SLOMonitor`'s
burn-rate rules that already prove a breach is sustained (fast AND slow
window).  This module is only the policy that connects them:

* **grow** — on a sustained SLO burn (any :class:`BurnRateRule` breach,
  plus any rule named in ``serving.autoscale.rules``), add one replica:
  a replica the autoscaler ITSELF previously drained rejoins WARM
  (compiled step and cache intact — the cheapest capacity in the
  fleet; an OPERATOR-drained replica is maintenance in progress and is
  never silently reverted), else a new replica is built cold
  OFF-THREAD: :meth:`Router.build_replica` (the REAL subprocess spawn
  + in-child compile on the process transport) runs on a background
  spawner thread while the sweep keeps serving, and the finished
  replica is adopted (:meth:`Router.adopt_replica` — appended,
  health-tracked, parked backlog flushed) at the next sweep boundary.
  The replica is UNROUTABLE until adopted (it simply is not in the
  fleet yet), at most one spawn is in flight (further grow impulses
  hold), and a failed spawn counts a ``spawn_failures`` — never a flap
  (a flap trip requires a grow that LANDED).  Fleets without a build
  recipe (injected test replicas) fall back to the synchronous
  :meth:`Router.add_replica` lever;
* **shrink** — once the error budget has recovered (no relevant breach
  for ``scale_down_cooldown_s``), gracefully :meth:`drain` the
  youngest-added live replica back out, never below ``min_replicas``;
* **flap breaker** — a scale-up that lands inside ``flap_window_s`` of
  a scale-down is a flap: each trip DOUBLES the scale-up hold-out
  (capped at 2^6, decaying one trip per clean window) — the same
  doubling-hold-out shape as PR 8's replica circuit breaker, so an
  oscillating load curve converges to a steady set instead of paying a
  cold spawn per wave.

Actuations move only the replica SET — never a live engine's geometry —
so every stream stays bit-exact and every replica's compile count stays
1 (a cold spawn compiles its own step once, exactly like any restart).
Each action emits a ``serving/actuation`` trace instant, an
``slo_events.jsonl`` line (:meth:`SLOMonitor.note_actuation`), and the
``scale_ups`` / ``scale_downs`` / ``autoscale_holds`` / ``flap_trips``
counters on the ``serving/fleet/*`` rollup (published immediately, not
on the heartbeat cadence — an actuation opens its evidence window at
the action).

Pure host policy — injectable clock (the router's), no jax; unit tests
drive it with fake replicas and a fake clock
(tests/test_serving_autoscale.py).  Knobs: ``serving.autoscale.*``
(docs/robustness.md "Self-healing fleet").
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.utils.logging import get_logger

# Flap hold-out doubling cap: 2^6 (mirrors ReplicaHealth.cooldown_s).
_MAX_FLAP_DOUBLINGS = 6


class FleetAutoscaler:
  """SLO-burn-driven replica-set policy for one Router (module
  docstring).  Built by the router when ``serving.autoscale.enabled``;
  the router calls :meth:`on_step` at the top of every fleet sweep —
  replica-list mutation is only safe between sweeps.

  Threading mirrors the autotuner: breach callbacks may arrive from a
  watchdog thread, so the listener only RECORDS under a lock and every
  action happens in :meth:`on_step` on the router's thread.
  """

  def __init__(self, router, config=None):
    conf = (config if config is not None
            else Env.get().config).serving.autoscale
    self.router = router
    self.clock = router.clock
    self.min_replicas = conf.min_replicas
    self.max_replicas = conf.max_replicas
    self.scale_up_cooldown_s = conf.scale_up_cooldown_s
    self.scale_down_cooldown_s = conf.scale_down_cooldown_s
    self.flap_window_s = conf.flap_window_s
    self._rules = set(conf.rules)
    # Deterministic spawn lever (replay/simulation): grow replicas
    # synchronously inside on_step instead of on the spawner thread.
    self.sync_spawn = conf.sync_spawn
    # Predictive scale-up (config comment): differentiate the router's
    # cumulative submit counter over a sliding window and grow when the
    # arrival-rate SLOPE says the burn is coming — before the burn-rate
    # rule can have breached.  slope <= 0 disables the rule.
    self.predictive_window_s = conf.predictive_window_s
    self.predictive_slope = conf.predictive_slope
    self._demand_samples: Deque[Tuple[float, int]] = deque()
    self.predictive_fires = 0
    # First landed grow of this policy's lifetime — the time-to-react
    # evidence `make heal-bench` compares predictive vs reactive on.
    self.first_scale_up_t: Optional[float] = None
    self.scale_ups = 0
    self.scale_downs = 0
    self.holds = 0              # actions suppressed by cooldown/hold-out
    self.flap_trips = 0
    self.spawn_failures = 0
    # Replica indices this policy currently OWNS (spawned or rejoined
    # into service); shrink only ever drains from this set, and a
    # drain moves the entry to _parked (eligible for warm rejoin) —
    # the operator's base fleet is never the autoscaler's to take
    # below its provisioned size, and an OPERATOR-drained replica is
    # never its rejoin target.
    self._added: List[int] = []
    self._parked: List[int] = []
    self._last_up_t: Optional[float] = None
    self._last_down_t: Optional[float] = None
    self._flap_decay_t: Optional[float] = None
    self._lock = threading.Lock()
    self._pending_rule: Optional[str] = None
    self._last_breach_t: Optional[float] = None
    # Off-thread cold spawn (module docstring): at most one in flight;
    # a single LONG-LIVED daemon spawner thread serves build requests
    # and posts outcomes here for the router thread to adopt (or book
    # the failure) at the next on_step.  The thread must outlive every
    # child it spawns: Linux delivers PR_SET_PDEATHSIG when the thread
    # that forked the child EXITS, so a short-lived per-spawn thread
    # would SIGKILL its own replica the moment it finished — and a
    # daemon thread dying only at process exit turns that same signal
    # into exactly the orphan reaping the transport wants.
    self._spawn_thread: Optional[threading.Thread] = None
    self._spawn_queue = None
    self._spawn_busy = False
    self._spawn_outcome: Optional[tuple] = None
    # External hold (serving/rollout.py): while a blue/green rollout is
    # in flight the replica set belongs to the rollout controller —
    # autoscale grow/shrink during a canary would change the capacity
    # the canary's SLO evidence is judging.  In-flight spawn outcomes
    # still LAND while held (a child process must be adopted or
    # reaped), but no new action starts.
    self._hold_reason: Optional[str] = None
    monitor = router._slo
    from easyparallellibrary_tpu.observability.slo import BreachPressure
    self._probe = BreachPressure(
        monitor, lambda rule, _key: rule in self._relevant_rules())
    if monitor is not None:
      monitor.add_listener(self._on_breach, weak=True)
    else:
      get_logger().warning(
          "serving.autoscale.enabled without observability.slo.enabled: "
          "the autoscaler has no burn signal and will never actuate")
    if len(router.replicas) >= self.max_replicas:
      get_logger().warning(
          "serving.autoscale.max_replicas (%d) <= current fleet size "
          "(%d): every scale-up will be held — raise max_replicas if "
          "the fleet should grow under burn", self.max_replicas,
          len(router.replicas))
    get_logger().info(
        "fleet autoscaler: %d..%d replicas, up/down cooldown "
        "%.1fs/%.1fs, flap window %.1fs, extra rules %s",
        self.min_replicas, self.max_replicas, self.scale_up_cooldown_s,
        self.scale_down_cooldown_s, self.flap_window_s,
        sorted(self._rules) or "(burn rules only)")

  # ----------------------------------------------------------- listening

  def _on_breach(self, rule: str, payload: Dict[str, Any]) -> None:
    """Record a relevant breach.  Burn-rate breaches (payload carries
    the window burns) always qualify — the rule itself proved the burn
    is sustained across fast AND slow windows; threshold rules only
    when named in ``serving.autoscale.rules``."""
    if "fast_burn" not in payload and rule not in self._rules:
      return
    with self._lock:
      self._pending_rule = rule
      self._last_breach_t = self.clock()

  # ------------------------------------------------------------- policy

  def _live(self) -> List[int]:
    """Replica indices serving or able to serve (healthy + suspect);
    draining and down replicas are capacity already removed."""
    return [i for i, h in enumerate(self.router.health)
            if h.state in ("healthy", "suspect")]

  def _relevant_rules(self) -> set:
    monitor = self.router._slo
    if monitor is None:
      return set(self._rules)
    from easyparallellibrary_tpu.observability.slo import BurnRateRule
    return ({r.name for r in monitor.rules
             if isinstance(r, BurnRateRule)} | self._rules)

  def _pressure(self) -> bool:
    """Is any relevant breach stream STILL breached?  A breach event
    fires only on the transition; an overload one replica-add did not
    absorb looks like a burn stream that never recovers, so sustained
    pressure is polled (slo.BreachPressure owns the liveness
    invariant).  While the breach is alive ``_last_breach_t``
    refreshes, so the quiet-window gates below never read a live burn
    as recovered; a wedged stream whose records stopped flowing lets
    the timestamp age out."""
    pressured, fresh = self._probe.poll()
    if fresh:
      with self._lock:
        self._last_breach_t = self.clock()
    return pressured

  def _demand_slope(self, now: float) -> Optional[float]:
    """Sample the router's cumulative demand counter and estimate the
    arrival-rate slope (requests/s per second) over the sliding window:
    the late-half rate minus the early-half rate, over half the span.
    Returns None while the rule is off, the window has not filled yet
    (startup must never read as a ramp), or the halves are degenerate.
    Two-half differencing instead of least squares on purpose: it is
    O(1) per sweep, exactly reproducible, and a steady Poisson stream's
    halves agree in expectation — slope ~ 0, so fault-free traffic
    cannot fire the rule."""
    if self.predictive_slope <= 0:
      return None
    count = getattr(self.router, "submitted_total", None)
    if count is None:
      return None
    samples = self._demand_samples
    samples.append((now, int(count)))
    cutoff = now - self.predictive_window_s
    # Keep s[0] as the newest sample at-or-before the cutoff so the
    # retained span always covers the full window.
    while len(samples) >= 2 and samples[1][0] <= cutoff:
      samples.popleft()
    t0, c0 = samples[0]
    span = now - t0
    if span < self.predictive_window_s * 0.95:
      return None
    mid = now - span / 2.0
    tp, cp = min(samples, key=lambda tc: abs(tc[0] - mid))
    if not t0 < tp < now:
      return None
    early = (cp - c0) / (tp - t0)
    late = (count - cp) / (now - tp)
    return (late - early) / (span / 2.0)

  @property
  def spawn_in_flight(self) -> bool:
    """True while an off-thread cold spawn is running or its outcome
    has not yet been landed by :meth:`on_step` — drivers that want the
    scale-up to complete keep sweeping (idle sweeps are heartbeats)
    while this holds."""
    with self._lock:
      return self._spawn_busy or self._spawn_outcome is not None

  def hold(self, reason: str) -> None:
    """Suspend autoscaling actions (init comment on ``_hold_reason``):
    breaches keep being recorded and in-flight spawns still land, but
    no grow/shrink starts until :meth:`release`.  Idempotent."""
    if self._hold_reason is None:
      get_logger().info("autoscale: held (%s)", reason)
    self._hold_reason = reason

  def release(self) -> None:
    """Lift a :meth:`hold`.  Idempotent."""
    if self._hold_reason is not None:
      get_logger().info("autoscale: released (was held: %s)",
                        self._hold_reason)
    self._hold_reason = None

  @property
  def held(self) -> bool:
    return self._hold_reason is not None

  def scale_up_holdout_s(self) -> float:
    """Current scale-up hold-out: the base cooldown doubled per flap
    trip (capped) — PR 8's breaker shape applied to capacity."""
    return self.scale_up_cooldown_s * (
        2 ** min(self.flap_trips, _MAX_FLAP_DOUBLINGS))

  def on_step(self, now: Optional[float] = None) -> None:
    """One fleet-sweep boundary: land any finished off-thread spawn,
    then act on a recorded breach (grow) or on a recovered budget
    (shrink), honoring bounds/cooldowns/hold-outs."""
    now = self.clock() if now is None else now
    # Demand sampling runs every sweep — held or not — so the slope
    # estimate never has a hole exactly where the interesting window is.
    slope = self._demand_slope(now)
    with self._lock:
      outcome, self._spawn_outcome = self._spawn_outcome, None
    if outcome is not None:
      self._finish_spawn(outcome, now)
    if self._parked:
      # A parked claim is valid only while the drain THIS policy
      # started is still in effect: the moment a parked replica leaves
      # "draining" through any other path (an operator rejoined it,
      # or it died), the claim is void — otherwise a LATER operator
      # maintenance drain of the same index would read as ours and a
      # breach could silently revert it.
      self._parked = [i for i in self._parked
                      if self.router.health[i].state == "draining"]
    if self._hold_reason is not None:
      # Held (rollout in flight): the breach event is consumed as a
      # hold — a burn that OUTLIVES the hold re-fires through the
      # sustained-pressure poll once released, so no real overload is
      # lost, only the stale event.
      with self._lock:
        pending, self._pending_rule = self._pending_rule, None
      if pending is not None:
        self.holds += 1
      return
    with self._lock:
      rule, self._pending_rule = self._pending_rule, None
    if rule is not None:
      self._maybe_scale_up(rule, now)
      return
    if (slope is not None and slope >= self.predictive_slope
        and len(self._live()) < self.max_replicas
        and (self._last_up_t is None
             or now - self._last_up_t >= self.scale_up_holdout_s())):
      # Arrival-rate slope says the burn is COMING: grow now, while the
      # spawn still lands before the queue does.  Pre-gated (like the
      # sustained path) so a high slope inside the hold-out window does
      # not spin the holds counter every sweep.
      self.predictive_fires += 1
      self._maybe_scale_up("predictive", now)
      return
    # _pressure() refreshes _last_breach_t while the breached streams'
    # records keep flowing — a live sustained burn keeps the quiet
    # window open; a wedged-silent stream lets it close (stale escape).
    pressured = self._pressure()
    with self._lock:
      last_breach_t = self._last_breach_t
    if (pressured and last_breach_t is not None
        and now - last_breach_t < self.scale_down_cooldown_s):
      # Sustained burn one add did not absorb: keep growing, one
      # replica per hold-out window (the checks here pre-gate so the
      # holds counter only counts suppressed breach EVENTS).
      if (len(self._live()) < self.max_replicas
          and (self._last_up_t is None
               or now - self._last_up_t >= self.scale_up_holdout_s())):
        self._maybe_scale_up("sustained", now)
      return
    # Flap-trip decay: a full clean window without any scaling action
    # forgives one trip (ReplicaHealth.note_stable's analogue).
    if self.flap_trips:
      quiet = max(self._last_up_t or 0.0, self._last_down_t or 0.0,
                  self._flap_decay_t or 0.0)
      if now - quiet >= self.flap_window_s:
        self.flap_trips -= 1
        self._flap_decay_t = now   # one forgiveness per clean window
    if not self._added or last_breach_t is None:
      # Nothing autoscaler-owned in service: the operator's base set
      # is never drained — min_replicas is a floor, not a target.
      return
    quiet_since = max(
        last_breach_t, self._last_up_t or 0.0, self._last_down_t or 0.0)
    if now - quiet_since >= self.scale_down_cooldown_s:
      self._maybe_scale_down(now)

  def _maybe_scale_up(self, rule: str, now: float) -> None:
    with self._lock:
      spawning = self._spawn_busy or self._spawn_outcome is not None
    if spawning:
      # One capacity action in flight: further grow impulses hold until
      # the spawner thread's outcome lands at a sweep boundary.
      self.holds += 1
      return
    live = self._live()
    if len(live) >= self.max_replicas:
      self.holds += 1
      return
    if (self._last_up_t is not None
        and now - self._last_up_t < self.scale_up_holdout_s()):
      self.holds += 1
      return
    router = self.router
    # Cheapest capacity first: a replica THIS policy drained rejoins
    # WARM.  Operator-drained replicas are maintenance in progress —
    # reverting one on a breach would silently undo a rolling restart.
    parked = [i for i in self._parked
              if router.health[i].state == "draining"]
    if parked:
      index = parked[-1]
      if not router.rejoin(index):
        self.holds += 1
        return
      self._parked.remove(index)
      self._land_grow(index, "rejoin", rule, now)
      return
    if (not self.sync_spawn
        and getattr(router, "spawn_recipe_available", False)):
      # Cold spawn OFF the sweep thread (ROADMAP item 5 leftover
      # closed): the subprocess spawn + in-child compile can take
      # seconds, and a synchronous add would stall every live replica
      # for exactly the window the fleet is overloaded.  The new
      # replica is unroutable until adoption lands it at a later
      # sweep.
      self._start_spawn(rule)
      return
    # No build recipe (injected test fleets), or sync_spawn pinned for
    # replay determinism: the synchronous operator lever is the grow
    # path.
    try:
      index = router.add_replica()
    except Exception as e:  # noqa: BLE001 — a failed spawn must not
      self.spawn_failures += 1          # take the control plane down
      get_logger().error(
          "autoscale: replica spawn failed (%s: %s); holding",
          type(e).__name__, e)
      # Stamp AFTER the failed attempt (same rule as the success
      # path): a spawn that blocked until spawn_timeout_s must buy a
      # full cooldown of actual serving before the retry, not an
      # immediate back-to-back doomed attempt.
      self._last_up_t = self.clock()
      return
    self._land_grow(index, "spawn", rule, now)

  def _start_spawn(self, rule: str) -> None:
    """Queue the cold spawn onto the persistent daemon spawner thread
    (init comment on ``_spawn_thread``: the forking thread must outlive
    the child, or PDEATHSIG kills the fresh replica the moment the
    thread exits).  The thread only calls :meth:`Router.build_replica`
    (recipe reads + the subprocess spawn — no router-list mutation) and
    posts the outcome for :meth:`on_step` to land on the router's
    thread."""
    import queue
    with self._lock:
      if self._spawn_thread is None or not self._spawn_thread.is_alive():
        self._spawn_queue = queue.Queue()
        self._spawn_thread = threading.Thread(
            target=self._spawner_loop, name="epl-autoscale-spawner",
            daemon=True)
        self._spawn_thread.start()
      self._spawn_busy = True
    self._spawn_queue.put(rule)
    get_logger().info(
        "autoscale: cold replica spawn started off-thread (rule %s); "
        "fleet keeps sweeping, replica unroutable until ready", rule)

  def _spawner_loop(self) -> None:
    while True:
      rule = self._spawn_queue.get()
      try:
        rep, err = self.router.build_replica(), None
      except Exception as e:  # noqa: BLE001 — posted, booked on_step
        rep, err = None, e
      with self._lock:
        self._spawn_outcome = (rep, err, rule)
        self._spawn_busy = False

  def _finish_spawn(self, outcome, now: float) -> None:
    rep, err, rule = outcome
    if err is not None:
      # A failed spawn is booked exactly like the synchronous path:
      # counted, cooled down — and NEVER a flap (no grow landed).
      self.spawn_failures += 1
      get_logger().error(
          "autoscale: off-thread replica spawn failed (%s: %s); holding",
          type(err).__name__, err)
      self._last_up_t = self.clock()
      return
    index = self.router.adopt_replica(rep)
    self._land_grow(index, "spawn", rule, now)

  def _land_grow(self, index: int, action: str, rule: str,
                 now: float) -> None:
    """Book one grow that LANDED (warm rejoin, sync spawn, or adopted
    off-thread spawn): ownership, flap accounting, cooldown stamp,
    emission."""
    if index not in self._added:
      # Autoscaler-owned capacity (spawned OR rejoined into service):
      # exactly the set shrink may later drain back out.
      self._added.append(index)
    if (self._last_down_t is not None
        and now - self._last_down_t < self.flap_window_s):
      # Growing right after shrinking — and only when the grow actually
      # LANDED: the load is oscillating around the capacity step, so
      # the next hold-out doubles (a failed spawn is not a flap).
      self.flap_trips = min(self.flap_trips + 1, _MAX_FLAP_DOUBLINGS)
    if self.first_scale_up_t is None:
      self.first_scale_up_t = now
    self.scale_ups += 1
    # Stamp AFTER the action: a cold spawn takes seconds, and a
    # cooldown counted from before it would let the very next sweep
    # read the whole spawn as "quiet" and drain the replica right back.
    self._last_up_t = self.clock()
    self._emit("scale_up", action, index, rule)

  def _maybe_scale_down(self, now: float) -> None:
    live = self._live()
    if len(live) <= self.min_replicas:
      return
    # Youngest-added live replica, LIFO — and ONLY autoscaler-owned
    # capacity: if everything it added is already gone (e.g. the
    # spawned replica died), the operator's base set is not a fallback.
    added_live = [i for i in self._added if i in live]
    if not added_live:
      return
    index = added_live[-1]
    self._added.remove(index)
    self._parked.append(index)   # eligible for a future warm rejoin
    self.router.drain(index)
    self.scale_downs += 1
    self._last_down_t = self.clock()
    self._emit("scale_down", "drain", index, "recovered")

  # ------------------------------------------------------------ emission

  def counters(self) -> Dict[str, float]:
    """Fleet-rollup counters (merged into Router.router_counters, so
    they ride the ``serving/fleet/*`` schema with zero new plumbing)."""
    return {"scale_ups": float(self.scale_ups),
            "scale_downs": float(self.scale_downs),
            "autoscale_holds": float(self.holds),
            "flap_trips": float(self.flap_trips),
            "predictive_fires": float(self.predictive_fires)}

  def _emit(self, action: str, mechanism: str, index: int,
            rule: str) -> None:
    router = self.router
    live = len(self._live())
    payload = {"actuator": "autoscale", "action": action,
               "mechanism": mechanism, "replica": int(index),
               "rule": rule, "live_replicas": live,
               "knobs": {"live_replicas":
                         [live - 1 if action == "scale_up" else live + 1,
                          live]}}
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/actuation", cat="serving", track="serving",
          args={"actuator": "autoscale", "action": action,
                "mechanism": mechanism, "replica": int(index),
                "rule": rule, "live_replicas": live})
      tracer.counter("serving/live_replicas", live)
    if router._slo is not None:
      router._slo.note_actuation("autoscale", payload, step=router.steps)
    # Immediate rollup: the actuation's counter evidence lands at the
    # action, not up to a heartbeat later (Router._note_incident's rule).
    router._note_incident()
    get_logger().warning(
        "autoscale: %s replica %d via %s (rule %s) -> %d live "
        "(trips %d, next hold-out %.1fs)", action, index, mechanism,
        rule, live, self.flap_trips, self.scale_up_holdout_s())
