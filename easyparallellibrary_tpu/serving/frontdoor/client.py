"""Stdlib SSE consumer for the front door (server.py) — the client the
tests, chaos suite and benchmark drive the HTTP surface with, so every
equivalence pin exercises the REAL wire (socket, chunking, SSE framing)
rather than in-process shortcuts."""

from __future__ import annotations

import http.client
import json
import socket
from typing import Any, Dict, Iterator, List, Optional, Tuple


def _post(address: Tuple[str, int], body: Dict[str, Any],
          headers: Optional[Dict[str, str]],
          timeout: float) -> http.client.HTTPResponse:
  conn = http.client.HTTPConnection(address[0], address[1],
                                    timeout=timeout)
  hdrs = {"Content-Type": "application/json"}
  if headers:
    hdrs.update(headers)
  conn.request("POST", "/v1/generate", json.dumps(body).encode(), hdrs)
  resp = conn.getresponse()
  resp._frontdoor_conn = conn   # keep the socket alive with the response
  return resp


def stream_generate(address: Tuple[str, int], body: Dict[str, Any],
                    headers: Optional[Dict[str, str]] = None,
                    timeout: float = 60.0
                    ) -> Iterator[Tuple[str, Dict[str, Any]]]:
  """POST /v1/generate and yield ``(event, data)`` pairs as SSE frames
  arrive — ``("token", {"tokens": [...]})`` per engine iteration, then
  one ``("done", {"finish_reason": ..., ...})``.  Raises RuntimeError
  with the server's message on a non-200 response.  Keepalive comments
  are consumed silently."""
  resp = _post(address, body, headers, timeout)
  if resp.status != 200:
    detail = resp.read().decode(errors="replace")
    resp.close()
    raise RuntimeError(f"frontdoor HTTP {resp.status}: {detail}")
  event: Optional[str] = None
  try:
    for raw in resp:
      line = raw.rstrip(b"\r\n").decode()
      if line.startswith(":"):
        continue                       # keepalive comment
      if line.startswith("event:"):
        event = line[len("event:"):].strip()
      elif line.startswith("data:") and event is not None:
        data = json.loads(line[len("data:"):].strip())
        yield event, data
        if event == "done":
          return
        event = None
  finally:
    resp.close()


def generate(address: Tuple[str, int], body: Dict[str, Any],
             headers: Optional[Dict[str, str]] = None,
             timeout: float = 60.0
             ) -> Tuple[List[int], Dict[str, Any]]:
  """Run one request to completion; returns ``(streamed_tokens, done)``
  where ``streamed_tokens`` is every token event's payload concatenated
  in arrival order (the byte-exact-assembly currency of
  tests/test_serving_frontdoor.py)."""
  tokens: List[int] = []
  done: Dict[str, Any] = {}
  for event, data in stream_generate(address, body, headers=headers,
                                     timeout=timeout):
    if event == "token":
      tokens.extend(int(t) for t in data["tokens"])
    elif event == "done":
      done = data
  if not done:
    raise RuntimeError("stream ended without a done event")
  return tokens, done


def healthz(address: Tuple[str, int],
            timeout: float = 10.0) -> Dict[str, Any]:
  conn = http.client.HTTPConnection(address[0], address[1],
                                    timeout=timeout)
  try:
    conn.request("GET", "/healthz")
    resp = conn.getresponse()
    if resp.status != 200:
      raise RuntimeError(f"healthz HTTP {resp.status}")
    return json.loads(resp.read().decode())
  finally:
    conn.close()


def open_raw_stream(address: Tuple[str, int], body: Dict[str, Any],
                    headers: Optional[Dict[str, str]] = None,
                    timeout: float = 60.0) -> socket.socket:
  """Open /v1/generate as a RAW socket and return it after the request
  is written, without reading the response — the chaos suite's handle
  for misbehaving clients (testing/chaos.py SlowReader /
  DisconnectingClient): close it to vanish mid-stream, read one byte an
  hour to strangle the flow."""
  payload = json.dumps(body).encode()
  lines = [f"POST /v1/generate HTTP/1.1",
           f"Host: {address[0]}:{address[1]}",
           "Content-Type: application/json",
           f"Content-Length: {len(payload)}"]
  for k, v in (headers or {}).items():
    lines.append(f"{k}: {v}")
  raw = ("\r\n".join(lines) + "\r\n\r\n").encode() + payload
  sock = socket.create_connection(address, timeout=timeout)
  sock.sendall(raw)
  return sock
