"""Event-driven streaming front door: stdlib HTTP/1.1 + SSE over one
router (docs/serving.md "Front door").

Threading model — the router keeps its single-threaded contract:

* One **driver thread** owns the router exclusively.  It drains a
  command queue (submit/cancel marshalled from HTTP handler threads),
  then drives one cycle — the reactor
  (serving/reactor.py) when ``serving.router.reactor`` is on, the
  ``router.step()`` sweep otherwise.  No router method is ever called
  from a handler thread.
* One **handler thread per connection** (``ThreadingHTTPServer``)
  parses the request, posts a submit command, and then only *reads*
  its own stream's queue and writes SSE frames to its own socket.

Token flow is push, never poll: the router's ``on_tokens`` fanout
(scheduler commit -> transport side-band -> router -> here) lands each
request's freshly committed tokens in its per-connection bounded queue
**on the driver thread, inside the cycle** — the handler thread wakes
and writes the SSE frame while the fleet keeps stepping.

Backpressure is per-flow: the queue holds at most
``serving.frontdoor.stream_buffer`` batches.  A reader too slow to
drain it overflows ONLY its own queue; the overflow marks the stream
and the driver cancels that uid *after* the cycle (never reentrantly
inside scheduler.commit), so one slow phone on a bad link costs one
request — not a batch slot held hostage, and never a neighbour's
tokens.  A second line of defence — ``write_timeout_s`` on the
connection socket — catches the reader whose TCP window closed
entirely.

Cancel-on-disconnect: every SSE write failure (broken pipe, reset,
write timeout) and every keepalive-probe failure posts a cancel
command; the driver runs ``router.cancel(uid)``, which retires the
request with reason ``"cancelled"``, frees its slot and cache blocks,
and finalizes its trace flow — capacity returns to the fleet within
one keepalive interval (``keepalive_s``) even when the client vanishes
without a FIN.

Wire schema (one ``event:``/``data:`` pair per frame, UTF-8 JSON)::

    event: token
    data: {"tokens": [733, 12, ...]}     # one engine iteration's commit

    event: done
    data: {"finish_reason": "length", "new_tokens": 16,
           "truncated": false}

Request headers map onto scheduler fields (the same admission/deadline
machinery every other entry point uses — docs/serving.md has the
table): ``X-Deadline-S`` -> ``deadline_s``, ``X-TTFT-Budget-S`` ->
``ttft_budget_s``, ``X-Priority`` -> ``priority``.

Trace context (docs/observability.md "Distributed tracing"): a W3C
``traceparent`` request header binds the request to the caller's
trace — its trace-id maps onto the scheduler's ``Request.flow_id``,
so the flow arc in the merged Perfetto export starts at the HTTP edge
and the id is recoverable from the caller's trace-id.  When absent,
one is minted.  A malformed header is a 400 (a proxy that mangles
trace context should hear about it, not silently fork a new trace).
The SSE response echoes ``X-Request-Id`` (the request uid) and the
effective ``traceparent``; ``frontdoor/request`` / ``frontdoor/
first_byte`` instants give report.py the client-observed TTFT hop.
"""

from __future__ import annotations

import itertools
import json
import queue
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.serving.scheduler import Request, next_flow_id
from easyparallellibrary_tpu.utils.logging import get_logger

_PRIORITIES = ("throughput", "latency")

# Perfetto flow ids are JSON numbers; keep them inside the 53-bit
# exact-integer range so a round-trip through any JSON tooling cannot
# corrupt the flow binding.
_FLOW_ID_MASK = (1 << 53) - 1


def parse_traceparent(header: str) -> Tuple[str, str, str]:
  """Strictly parse a W3C ``traceparent`` header
  (``00-<32hex trace-id>-<16hex parent-id>-<2hex flags>``); returns
  ``(trace_id, parent_id, flags)`` or raises ``ValueError`` (the front
  door maps that to a 400)."""
  parts = header.strip().split("-")
  if len(parts) != 4:
    raise ValueError(
        f"malformed traceparent (want version-traceid-parentid-flags): "
        f"{header!r}")
  version, trace_id, parent_id, flags = parts
  hexdigits = "0123456789abcdef"

  def _hex(field: str, value: str, width: int) -> str:
    if len(value) != width or any(c not in hexdigits for c in value):
      raise ValueError(f"malformed traceparent: {field} must be "
                       f"{width} lowercase hex chars: {value!r}")
    return value

  _hex("version", version, 2)
  if version == "ff":
    raise ValueError("malformed traceparent: version 'ff' is invalid")
  _hex("trace-id", trace_id, 32)
  if trace_id == "0" * 32:
    raise ValueError("malformed traceparent: trace-id must be non-zero")
  _hex("parent-id", parent_id, 16)
  if parent_id == "0" * 16:
    raise ValueError("malformed traceparent: parent-id must be non-zero")
  _hex("flags", flags, 2)
  return trace_id, parent_id, flags


def mint_traceparent(flow_id: int) -> str:
  """A fresh ``traceparent`` carrying ``flow_id`` as its trace-id, for
  requests that arrive without one — the caller can correlate the SSE
  response's echoed header with the exported trace's flow id."""
  return f"00-{flow_id:032x}-{flow_id & ((1 << 64) - 1):016x}-01"


def flow_id_from_trace_id(trace_id: str) -> int:
  """Map a 128-bit W3C trace-id onto a Perfetto-safe flow id (low 53
  bits; collision odds at serving-fleet scale are negligible)."""
  return int(trace_id, 16) & _FLOW_ID_MASK


class _StreamState:
  """Per-connection stream plumbing: the bounded token queue the driver
  pushes into and the handler drains, plus the terminal record.  Tokens
  are only ever pushed BEFORE ``final`` is set, so a handler that sees
  ``final`` with an empty queue has streamed everything."""

  __slots__ = ("uid", "prompt_len", "queue", "pushed", "overflow",
               "admitted", "accepted", "error", "final")

  def __init__(self, uid: Any, prompt_len: int, buffer: int):
    self.uid = uid
    self.prompt_len = prompt_len
    self.queue: "queue.Queue[List[int]]" = queue.Queue(maxsize=buffer)
    self.pushed = 0            # generated tokens enqueued so far
    self.overflow = False
    self.admitted = threading.Event()
    self.accepted = False
    self.error: Optional[str] = None
    self.final: Optional[Dict[str, Any]] = None


class FrontDoor:
  """The serving fleet's streaming HTTP entry point (module docstring).

  ``with FrontDoor(router) as fd:`` binds ``serving.frontdoor.host`` /
  ``.port`` (port 0 = ephemeral; read the bound one off
  ``fd.address``), starts the HTTP listener and the router driver
  thread, and serves until ``close()``.  The router must not be driven
  by anyone else while the front door owns it."""

  def __init__(self, router, config=None):
    root = config if config is not None else router._root_config
    fconf = root.serving.frontdoor
    self.router = router
    self._reactor_enabled = bool(root.serving.router.reactor)
    self.stream_buffer = int(fconf.stream_buffer)
    self.write_timeout_s = float(fconf.write_timeout_s)
    self.keepalive_s = float(fconf.keepalive_s)
    self._streams: Dict[Any, _StreamState] = {}
    self._streams_lock = threading.Lock()
    self._commands: "queue.Queue[Tuple[Any, ...]]" = queue.Queue()
    self._overflow_cancels: List[Any] = []   # driver-thread local
    self._kick = False                       # cycle once though idle
    self._uid_counter = itertools.count()
    self._stop = threading.Event()
    self._driver: Optional[threading.Thread] = None
    self._server_thread: Optional[threading.Thread] = None
    # Observable counters (benchmarks/frontdoor_bench.py).
    self.streamed_events = 0   # token batches pushed to stream queues
    self.overflow_sheds = 0    # slow-reader flows cancelled on overflow
    self.disconnect_cancels = 0
    router.on_tokens.append(self._on_tokens)
    front_door = self

    class _Handler(BaseHTTPRequestHandler):
      protocol_version = "HTTP/1.1"

      def log_message(self, fmt, *args):   # noqa: N802 (stdlib name)
        get_logger().debug("frontdoor http: " + fmt, *args)

      def do_GET(self):                    # noqa: N802
        front_door._handle_get(self)

      def do_POST(self):                   # noqa: N802
        front_door._handle_post(self)

    self._httpd = ThreadingHTTPServer(
        (str(fconf.host), int(fconf.port)), _Handler)
    self._httpd.daemon_threads = True
    self.address: Tuple[str, int] = self._httpd.server_address[:2]

  # ------------------------------------------------------------ lifecycle

  def start(self) -> "FrontDoor":
    self._driver = threading.Thread(
        target=self._drive, name="frontdoor-driver", daemon=True)
    self._driver.start()
    self._server_thread = threading.Thread(
        target=self._httpd.serve_forever, name="frontdoor-http",
        kwargs={"poll_interval": 0.05}, daemon=True)
    self._server_thread.start()
    return self

  def close(self) -> None:
    self._stop.set()
    self._httpd.shutdown()
    self._httpd.server_close()
    for t in (self._server_thread, self._driver):
      if t is not None:
        t.join(timeout=5.0)

  def __enter__(self) -> "FrontDoor":
    return self.start()

  def __exit__(self, *exc) -> None:
    self.close()

  @property
  def url(self) -> str:
    return f"http://{self.address[0]}:{self.address[1]}"

  # ------------------------------------------------------ driver thread

  def _drive(self) -> None:
    """The router's single owner: commands, then one cycle, repeat."""
    r = self.router
    drive = (r.reactor().cycle if self._reactor_enabled else r.step)
    while not self._stop.is_set():
      busy = r.has_work
      try:
        cmd = self._commands.get(timeout=0.0 if busy else 0.05)
      except queue.Empty:
        cmd = None
      while cmd is not None:
        self._handle_command(cmd)
        try:
          cmd = self._commands.get_nowait()
        except queue.Empty:
          cmd = None
      if not r.has_work and not self._kick:
        continue
      self._kick = False
      try:
        fins = drive()
      except Exception:
        get_logger().exception("frontdoor driver: cycle raised")
        continue
      for fin in fins:
        self._finalize(fin)
      if self._overflow_cancels:
        # Post-cycle, never inside scheduler.commit: cancelling
        # reentrantly from the on_tokens callback would mutate the
        # batch mid-commit.
        for uid in self._overflow_cancels:
          with self._streams_lock:
            self.overflow_sheds += 1
          r.cancel(uid)
        self._overflow_cancels = []

  def _handle_command(self, cmd: Tuple[Any, ...]) -> None:
    r = self.router
    kind = cmd[0]
    if kind == "submit":
      _, request, stream = cmd
      with self._streams_lock:
        self._streams[request.uid] = stream
      try:
        stream.accepted = r.submit(request)
      except ValueError as e:
        stream.error = str(e)
        stream.accepted = False
        with self._streams_lock:
          self._streams.pop(request.uid, None)
      else:
        if not stream.accepted:
          # Shed at admission: the resolution is already in
          # router.finished — surface it as the stream's done event.
          fin = r.finished.get(request.uid)
          if fin is not None:
            self._finalize(fin)
      stream.admitted.set()
    elif kind == "cancel":
      _, uid = cmd
      with self._streams_lock:
        stream = self._streams.pop(uid, None)
      if stream is not None and stream.final is None:
        stream.final = {"finish_reason": "cancelled",
                        "new_tokens": stream.pushed, "truncated": False}
      with self._streams_lock:
        self.disconnect_cancels += 1
      # Retires with reason "cancelled" wherever the request lives
      # (active slot, queue, parked backlog); slot + blocks free now,
      # the fin rides the next cycle into router.finished — kick one
      # even if this was the fleet's last request (an idle step is
      # cheap and it's what surfaces the retirement fleet-side).
      r.cancel(uid)
      self._kick = True

  def _on_tokens(self, uid: Any, toks: List[int]) -> None:
    """Router on_tokens fanout -> this stream's bounded queue (driver
    thread, inside the cycle)."""
    with self._streams_lock:
      stream = self._streams.get(uid)
    if stream is None or stream.final is not None or stream.overflow:
      return
    try:
      stream.queue.put_nowait(list(toks))
      stream.pushed += len(toks)
      with self._streams_lock:
        self.streamed_events += 1
    except queue.Full:
      # Slow reader: bound ITS buffer, shed ITS flow — after the cycle.
      stream.overflow = True
      self._overflow_cancels.append(uid)

  def _finalize(self, fin) -> None:
    with self._streams_lock:
      stream = self._streams.pop(fin.uid, None)
    if stream is None or stream.final is not None:
      return
    # Backfill anything committed but not yet pushed (e.g. tokens a
    # failover replayed, or the final commit of a finish that retired
    # before its on_tokens landed) so the stream byte-assembles to
    # exactly fin.tokens.
    generated = [int(t) for t in
                 np.asarray(fin.tokens).reshape(-1)[stream.prompt_len:]]
    backfill = generated[stream.pushed:]
    truncated = False
    if backfill:
      try:
        stream.queue.put_nowait(backfill)
        stream.pushed += len(backfill)
        with self._streams_lock:
          self.streamed_events += 1
      except queue.Full:
        truncated = True   # overflowed reader: already being shed
    stream.final = {"finish_reason": fin.finish_reason,
                    "new_tokens": int(fin.new_tokens),
                    "truncated": truncated}

  # ----------------------------------------------------- handler threads

  def _handle_get(self, h: BaseHTTPRequestHandler) -> None:
    if h.path != "/healthz":
      self._send_error(h, 404, "unknown path (POST /v1/generate)")
      return
    body = json.dumps({
        "states": list(self.router.states()),
        "steps": int(self.router.steps),
    }).encode()
    h.send_response(200)
    h.send_header("Content-Type", "application/json")
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    h.wfile.write(body)

  def _handle_post(self, h: BaseHTTPRequestHandler) -> None:
    if h.path != "/v1/generate":
      self._send_error(h, 404, "unknown path (POST /v1/generate)")
      return
    try:
      request, prompt_len, traceparent = self._parse_request(h)
    except ValueError as e:
      self._send_error(h, 400, str(e))
      return
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      # Client-arrival mark for the hop breakdown (report.py): the gap
      # to the router's serving/submit instant is front-door ingress,
      # the gap from the engine's first token to frontdoor/first_byte
      # is wire + stream delivery.
      tracer.instant("frontdoor/request", cat="serving",
                     track="frontdoor",
                     args={"uid": str(request.uid),
                           "flow": int(request.flow_id)})
    stream = _StreamState(request.uid, prompt_len, self.stream_buffer)
    self._commands.put(("submit", request, stream))
    if not stream.admitted.wait(timeout=60.0):
      self._send_error(h, 503, "router driver unresponsive")
      return
    if stream.error is not None:
      self._send_error(h, 400, stream.error)
      return
    self._stream_sse(h, stream, traceparent)

  def _parse_request(self, h: BaseHTTPRequestHandler
                     ) -> Tuple[Request, int, str]:
    length = int(h.headers.get("Content-Length", 0) or 0)
    raw = h.rfile.read(length) if length else b""
    try:
      body = json.loads(raw.decode() or "{}")
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
      raise ValueError(f"body is not JSON: {e}")
    if not isinstance(body, dict):
      raise ValueError("body must be a JSON object")
    prompt = body.get("prompt")
    if (not isinstance(prompt, list) or not prompt
        or not all(isinstance(t, int) for t in prompt)):
      raise ValueError('"prompt" must be a non-empty list of token ids')
    uid = body.get("uid")
    if uid is None:
      uid = f"fd-{next(self._uid_counter)}"

    def _num(source: str, name: str, raw_val: Any, cast, default):
      if raw_val is None:
        return default
      try:
        return cast(raw_val)
      except (TypeError, ValueError):
        raise ValueError(f"{source} {name!r} must be a number: {raw_val!r}")

    # Header mapping (docs/serving.md "Front door"): headers win over
    # body fields — proxies inject policy without rewriting payloads.
    deadline_s = _num("header", "X-Deadline-S",
                      h.headers.get("X-Deadline-S"), float,
                      _num("field", "deadline_s", body.get("deadline_s"),
                           float, 0.0))
    ttft_budget_s = _num("header", "X-TTFT-Budget-S",
                         h.headers.get("X-TTFT-Budget-S"), float,
                         _num("field", "ttft_budget_s",
                              body.get("ttft_budget_s"), float, 0.0))
    priority = h.headers.get("X-Priority", body.get("priority",
                                                    "throughput"))
    if priority not in _PRIORITIES:
      raise ValueError(f'priority must be one of {_PRIORITIES}: '
                       f'{priority!r}')
    # Trace-context propagation: bind the caller's trace-id onto the
    # request's flow id (mint both when the header is absent), so the
    # scheduler's flow events — including the child replicas' harvested
    # ones — connect back to the HTTP edge.
    header_tp = h.headers.get("traceparent")
    if header_tp is not None:
      trace_id, _parent_id, _flags = parse_traceparent(header_tp)
      flow_id = flow_id_from_trace_id(trace_id) or next_flow_id()
      traceparent = header_tp.strip()
    else:
      flow_id = next_flow_id()
      traceparent = mint_traceparent(flow_id)
    request = Request(
        uid=uid,
        prompt=np.asarray(prompt, np.int32),
        max_new_tokens=_num("field", "max_new_tokens",
                            body.get("max_new_tokens"), int, 16),
        temperature=_num("field", "temperature",
                         body.get("temperature"), float, 0.0),
        top_k=_num("field", "top_k", body.get("top_k"), int, 0),
        top_p=_num("field", "top_p", body.get("top_p"), float, 1.0),
        stop_token=_num("field", "stop_token",
                        body.get("stop_token"), int, -1),
        seed=_num("field", "seed", body.get("seed"), int, None),
        deadline_s=deadline_s,
        ttft_budget_s=ttft_budget_s,
        priority=priority,
        flow_id=flow_id)
    return request, len(prompt), traceparent

  def _stream_sse(self, h: BaseHTTPRequestHandler,
                  stream: _StreamState,
                  traceparent: Optional[str] = None) -> None:
    h.send_response(200)
    h.send_header("Content-Type", "text/event-stream")
    h.send_header("Cache-Control", "no-store")
    h.send_header("Connection", "close")
    # Trace-context echo: the uid correlates a client log line with the
    # trace/report, the traceparent hands back the effective trace-id
    # (the minted one when the request arrived without).
    h.send_header("X-Request-Id", str(stream.uid))
    if traceparent:
      h.send_header("traceparent", traceparent)
    h.end_headers()
    h.close_connection = True
    # Second backpressure line: a reader whose TCP window stays shut
    # past write_timeout_s reads as gone (the bounded queue is the
    # first line — it trips before the kernel buffers fill in most
    # slow-reader shapes).
    h.connection.settimeout(self.write_timeout_s)
    last_write = time.monotonic()
    tracer = trace_lib.get_tracer()
    first_byte_pending = tracer.enabled

    def _mark_first_byte():
      nonlocal first_byte_pending
      if first_byte_pending:
        # Client-observed TTFT endpoint: the first payload frame left
        # this process (post-flush), everything upstream included.
        tracer.instant("frontdoor/first_byte", cat="serving",
                       track="frontdoor", args={"uid": str(stream.uid)})
        first_byte_pending = False

    try:
      while True:
        if stream.final is not None and stream.queue.empty():
          payload = json.dumps(stream.final)
          h.wfile.write(f"event: done\ndata: {payload}\n\n".encode())
          h.wfile.flush()
          _mark_first_byte()
          return
        try:
          batch = stream.queue.get(timeout=0.05)
        except queue.Empty:
          if time.monotonic() - last_write >= self.keepalive_s:
            # Probe: surfaces a vanished client (no FIN) as a write
            # error within one keepalive interval.
            h.wfile.write(b": keepalive\n\n")
            h.wfile.flush()
            last_write = time.monotonic()
          continue
        payload = json.dumps({"tokens": batch})
        h.wfile.write(f"event: token\ndata: {payload}\n\n".encode())
        h.wfile.flush()
        _mark_first_byte()
        last_write = time.monotonic()
    except (BrokenPipeError, ConnectionResetError, socket.timeout,
            OSError):
      # Client gone (or unwritable past write_timeout_s): free its
      # slot and blocks NOW rather than decoding to a dead socket.
      self._commands.put(("cancel", stream.uid))

  @staticmethod
  def _send_error(h: BaseHTTPRequestHandler, code: int,
                  message: str) -> None:
    body = json.dumps({"error": message}).encode()
    try:
      h.send_response(code)
      h.send_header("Content-Type", "application/json")
      h.send_header("Content-Length", str(len(body)))
      h.end_headers()
      h.wfile.write(body)
    except OSError:
      pass
