"""Streaming HTTP front door for the serving fleet (docs/serving.md
"Front door").

``FrontDoor`` (server.py) owns a router on a single driver thread and
exposes ``POST /v1/generate`` with Server-Sent-Events token streaming:
tokens surface per engine iteration as they commit (the scheduler's
``on_tokens`` hook — never by polling ``finished``), a slow reader
bounds its own buffer and sheds/cancels only its own flow, and a client
that disconnects mid-stream cancels its request (slot and cache blocks
freed, flow trace finalized).  ``client.py`` is the stdlib SSE consumer
the tests, benchmarks and chaos suite drive it with.  Stdlib only — no
new dependencies.
"""

from easyparallellibrary_tpu.serving.frontdoor.server import FrontDoor
from easyparallellibrary_tpu.serving.frontdoor.client import (
    generate, healthz, open_raw_stream, stream_generate)

__all__ = ["FrontDoor", "generate", "healthz", "open_raw_stream",
           "stream_generate"]
