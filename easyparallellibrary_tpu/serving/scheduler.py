"""Host-side request scheduling for the continuous-batching engine.

Iteration-level (continuous) batching as in Orca (OSDI'22): the
scheduler re-forms the working set EVERY engine step, so requests join
the moment a slot frees and leave the moment they finish — no
batch-formation wait, no decode steps wasted running finished requests
to a batch-wide horizon.  The device program never changes shape; all of
the variability lives here, in which tokens each slot is fed.

Responsibilities (and nothing else — device work lives in engine.py):

* FCFS admission, gated by free slots, a configurable concurrent-batch
  cap (``max_batch``) and a per-iteration prefill-token budget that
  bounds how much prompt work any single step may carry
  (Sarathi-style chunked prefill: long prompts stream through the fused
  step ``prefill_chunk`` tokens at a time, so admission never stalls
  decode latency for more than one chunk).  ``latency``-class requests
  jump the FCFS order (:attr:`Request.priority`).
* Per-request decode state: prompt cursor, generated tokens, per-request
  RNG stream (a dedicated PRNGKey folded with the token index — two
  requests with the same seed reproduce the same sample stream no
  matter which slots or iterations they ride).
* Retirement: per-request ``max_new_tokens`` and optional stop-token,
  plus the hard ``max_seq_len`` capacity guard (checked at submit), and
  the lifecycle-control reasons — per-request deadlines / TTFT budgets
  (``deadline``), client cancellation (``cancelled``), overload
  rejection (``shed``, engine-side) and quarantine overflow
  (``failed``).  The full glossary lives in
  ``serving._capabilities.FINISH_REASONS`` / docs/robustness.md.
* Requeue: :meth:`requeue_slot` returns a mid-flight request to the
  FRONT of the queue with its committed prefix intact — on readmission
  the prompt AND the already-generated tokens replay through chunked
  prefill into a fresh slot, which reproduces the exact decode state
  (same KV content, same cursors-as-committed-token-count, same
  ``tok_index`` RNG fold), so a quarantined request's final output is
  bit-identical to an undisturbed run.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import zlib
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

import jax
import numpy as np

from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.serving._capabilities import (
    check_request_fields)
from easyparallellibrary_tpu.utils.logging import get_logger


def _slot_track(slot: int, prefix: str = "serving") -> str:
  """Perfetto track name for one KV-cache slot — every request served by
  this slot renders its lifecycle span here (docs/observability.md).
  Replicas pass their own prefix (``serving/replica<i>``) so the fleet's
  tracks stay distinct and a failed-over request's flow arc visibly
  crosses replica tracks."""
  return f"{prefix}/slot {slot}"


# Flow-context ids (Perfetto flow events; docs/observability.md
# "Request-flow correlation"): one id per request lifetime, minted at
# the FIRST submit the request reaches — the router when there is one,
# the scheduler otherwise — and carried through snapshot/restore so a
# failed-over request keeps its flow across replicas.  Process-unique
# is all a trace needs; minting is unconditional (a plain int) so
# enabling the tracer mid-run never sees id-less requests.
_FLOW_IDS = itertools.count(1)


def next_flow_id() -> int:
  return next(_FLOW_IDS)


# Request.snapshot() wire-format version: bump on ANY field change and
# keep a reader for every prior version — snapshots cross process
# boundaries (transport RPC, crash journals) where writer and reader
# can be different builds.  v2 added ``checkpoint_version`` (blue/green
# rollout, serving/rollout.py); v1 snapshots read with it defaulted to
# None ("any version" — pre-rollout fleets have exactly one).
SNAPSHOT_VERSION = 2


def _request_key(req: "Request") -> np.ndarray:
  """The request's private PRNG stream key.  Deterministic in
  ``seed``/``uid`` and stable across processes (crc32, not Python's
  per-process-salted hash()), so a request migrated to another replica
  — or a restarted server — reproduces the identical sample stream."""
  if req.seed is not None:
    seed = req.seed
  else:
    seed = zlib.crc32(str(req.uid).encode())
  return np.asarray(jax.random.PRNGKey(seed))


@dataclasses.dataclass
class Request:
  """One generation request.

  ``prompt`` is a 1-D int32 token array (non-empty — the model
  conditions the first new token on it, exactly like ``generate()``).
  ``temperature<=0`` is greedy; ``top_k``/``top_p`` mirror
  ``sample_logits`` semantics per slot.  ``stop_token < 0`` disables
  stop-token retirement; when hit, the stop token IS included in the
  output (the caller sees why the request ended).  ``seed`` starts the
  request's private RNG stream (defaults to a hash of ``uid``).
  ``speculative`` toggles speculative decoding per request: None
  follows the engine (a drafter is configured or not), False opts this
  request out (it then keeps the engine's non-speculative sample stream
  bit-exactly), True is a no-op on an engine without a drafter.

  Lifecycle control (docs/robustness.md "Serving resilience"):
  ``deadline_s`` retires the request with reason ``"deadline"`` once
  that many seconds have passed since submit, wherever it is (queued,
  prefilling, decoding; partial output is returned).  ``ttft_budget_s``
  is the stricter first-token bound: expire unless the first token was
  produced within the budget.  Both are 0 = off.  ``priority`` is
  ``"throughput"`` (FCFS) or ``"latency"`` (admitted ahead of queued
  throughput-class requests).

  ``flow_id`` is the request's trace-context id (Perfetto flow events;
  docs/observability.md "Request-flow correlation") — minted
  automatically at the first submit (router or scheduler) and carried
  through snapshot/restore, so callers never set it.

  ``checkpoint_version`` pins the request to the weights it started
  decoding under (docs/robustness.md "Blue/green rollout"): stamped by
  the router at dispatch time from the chosen replica's version and
  carried through snapshot/restore, so the failover journal can refuse
  to replay it onto a replica of a DIFFERENT version — prefix replay
  across checkpoints is not bit-exact.  ``None`` means "any version"
  (single-version fleets, pre-rollout snapshots); callers never set it.
  """
  uid: Any
  prompt: np.ndarray
  max_new_tokens: int
  temperature: float = 0.0
  top_k: int = 0
  top_p: float = 1.0
  stop_token: int = -1
  seed: Optional[int] = None
  speculative: Optional[bool] = None
  deadline_s: float = 0.0
  ttft_budget_s: float = 0.0
  priority: str = "throughput"
  flow_id: Optional[int] = None
  checkpoint_version: Optional[int] = None

  def snapshot(self) -> Dict[str, Any]:
    """JSON-serializable snapshot of the request spec (the immutable
    half of cross-replica migration; the scheduler adds the mutable
    half — committed prefix + lifecycle counters — in
    :meth:`FCFSScheduler.snapshot_requests`).  The PRNG state needs no
    field of its own: the stream key derives deterministically from
    ``seed``/``uid`` (:func:`_request_key`) and is folded by committed
    token index, so prompt + generated prefix IS the full sampler
    state.

    The dict is **versioned** (``"v": 2``): snapshots cross process
    boundaries (serving/transport.py ships them to worker processes and
    journals them for crash recovery), so a future field change must
    bump the version and keep a reader for every prior one —
    :meth:`restore` rejects unknown versions with a clear error instead
    of mis-restoring, and tests/golden/request_snapshot_v{1,2}.json pin
    the exact shapes.  v2 added ``checkpoint_version``; a v1 snapshot
    reads with it defaulted to None."""
    return {
        "v": SNAPSHOT_VERSION,
        "uid": self.uid,
        "prompt": [int(t) for t in np.asarray(self.prompt).reshape(-1)],
        "max_new_tokens": int(self.max_new_tokens),
        "temperature": float(self.temperature),
        "top_k": int(self.top_k),
        "top_p": float(self.top_p),
        "stop_token": int(self.stop_token),
        "seed": None if self.seed is None else int(self.seed),
        "speculative": self.speculative,
        "deadline_s": float(self.deadline_s),
        "ttft_budget_s": float(self.ttft_budget_s),
        "priority": self.priority,
        "flow_id": None if self.flow_id is None else int(self.flow_id),
        "checkpoint_version": (None if self.checkpoint_version is None
                               else int(self.checkpoint_version)),
    }

  @classmethod
  def restore(cls, snap: Dict[str, Any]) -> "Request":
    """Inverse of :meth:`snapshot` (tolerates a JSON round trip).
    Pre-versioning snapshots (no ``"v"`` key) read as v1 — the v1 field
    set with ``checkpoint_version`` absent; a v1 snapshot restores with
    it defaulted to None ("any version").  An UNKNOWN (newer) version
    is rejected loudly, because silently dropping or misreading a field
    would break cross-process failover bit-exactness in the quietest
    possible way."""
    snap = dict(snap)
    version = snap.pop("v", 1)
    if not 1 <= version <= SNAPSHOT_VERSION:
      raise ValueError(
          f"unsupported request snapshot version {version!r}: this build "
          f"reads v1..v{SNAPSHOT_VERSION} (a newer writer must not feed "
          f"an older reader across the failover wire — upgrade the "
          f"reader or re-snapshot with a v{SNAPSHOT_VERSION} writer)")
    snap.setdefault("checkpoint_version", None)
    snap["prompt"] = np.asarray(snap["prompt"], np.int32)
    return cls(**snap)


@dataclasses.dataclass
class FinishedRequest:
  uid: Any
  tokens: np.ndarray          # prompt + generated (stop token included)
  new_tokens: int
  finish_reason: str          # serving._capabilities.FINISH_REASONS


@dataclasses.dataclass
class StepPlan:
  """Device-ready arrays for one fused engine step (all [N] or [N, C])."""
  tokens: np.ndarray          # int32 [N, C] token chunk per slot
  num_valid: np.ndarray       # int32 [N]   live tokens in the chunk
  reset: np.ndarray           # bool  [N]   zero the cursor (fresh slot)
  keys: np.ndarray            # uint32 [N, 2] per-request PRNG keys
  tok_index: np.ndarray       # int32 [N]   tokens generated so far
  temperature: np.ndarray     # f32   [N]
  top_k: np.ndarray           # int32 [N]
  top_p: np.ndarray           # f32   [N]
  draft_cap: np.ndarray       # int32 [N] max speculative drafts this step
  prefilling: np.ndarray      # bool  [N]   this step's grant is prompt work
  prefill_tokens: int         # scheduled prompt tokens this step
  decode_tokens: int          # scheduled decode tokens this step
  active_slots: int


@dataclasses.dataclass
class PagedStepPlan:
  """Device-ready arrays for one token-flat fused step over the paged
  cache (serving/engine.py paged mode).  Flat arrays are [T] —
  ``T = token_budget``, one entry per scheduled position, each tagged
  with its slot and absolute position; per-slot arrays are [N] and share
  :class:`StepPlan`'s semantics so ``commit()`` consumes both plan kinds
  unchanged (``num_valid`` counts a slot's REAL tokens this step — its
  prefill grant, or 1 for decode — never reserved draft positions)."""
  tokens: np.ndarray          # int32 [T]  flat token batch
  slot_ids: np.ndarray        # int32 [T]  owning slot per position
  positions: np.ndarray       # int32 [T]  absolute position per token
  valid: np.ndarray           # bool  [T]  live entry (drafts flip late)
  block_tables: np.ndarray    # int32 [N, MB] per-slot block tables
  base_idx: np.ndarray        # int32 [N]  slot's first flat index
  draft_base: np.ndarray      # int32 [N]  slot's first draft flat index
  num_valid: np.ndarray       # int32 [N]  real tokens scheduled (no drafts)
  draft_cap: np.ndarray       # int32 [N]  reserved draft positions
  prefilling: np.ndarray      # bool  [N]
  keys: np.ndarray            # uint32 [N, 2]
  tok_index: np.ndarray       # int32 [N]
  temperature: np.ndarray     # f32   [N]
  top_k: np.ndarray           # int32 [N]
  top_p: np.ndarray           # f32   [N]
  prefill_tokens: int
  decode_tokens: int
  scheduled_tokens: int       # live flat positions (diagnostics)
  active_slots: int


class _SlotState:
  """Host mirror of one occupied slot.

  ``prefix`` is what chunked prefill feeds: the prompt for a fresh
  request, prompt + already-committed tokens for a requeued one (the
  replay that reconstructs the slot's KV/cursor state exactly).
  """

  __slots__ = ("req", "slot", "prompt_pos", "generated", "key", "prefix",
               "submitted_at", "admitted_at", "first_token_at",
               "first_token_emitted", "requeues", "bad_streak",
               "admit_seq", "reg_blocks")

  def __init__(self, req: Request, slot: int, submitted_at: float,
               now: float, carried: Optional["_SlotState"] = None,
               admit_seq: int = 0):
    self.req = req
    self.slot = slot
    self.prompt_pos = 0                    # prefix tokens already fed
    self.submitted_at = submitted_at
    self.admitted_at = now
    self.bad_streak = 0                    # consecutive bad-step hits
    # Monotonic admission sequence (preemption eligibility: a slot may
    # only page out strictly-younger same-priority slots, so two
    # starving slots can never preempt each other in a cycle).  A
    # requeued request gets a FRESH seq on readmission — it re-enters as
    # the youngest and cannot immediately steal back its old blocks.
    self.admit_seq = admit_seq
    # Leading blocks already registered in (or mapped from) the prefix
    # cache — the commit-time registration watermark, so the tree walk
    # only runs when a new full block completes.
    self.reg_blocks = 0
    if carried is not None:
      self.generated: List[int] = carried.generated
      self.key = carried.key
      self.first_token_at = carried.first_token_at
      self.first_token_emitted = carried.first_token_emitted
      self.requeues = carried.requeues
      self.prefix = np.concatenate(
          [req.prompt, np.asarray(self.generated, np.int32)])
    else:
      self.generated = []
      self.key = _request_key(req)
      self.first_token_at: Optional[float] = None
      self.first_token_emitted = False
      self.requeues = 0
      self.prefix = req.prompt

  @property
  def prefilling(self) -> bool:
    return self.prompt_pos < len(self.prefix)


class _Pending:
  """Queue entry: a not-yet-admitted request, optionally carrying the
  slot state of a requeued one (its committed prefix replays through
  prefill on readmission)."""

  __slots__ = ("req", "submitted_at", "carried")

  def __init__(self, req: Request, submitted_at: float,
               carried: Optional[_SlotState] = None):
    self.req = req
    self.submitted_at = submitted_at
    self.carried = carried

  @property
  def prefix_len(self) -> int:
    if self.carried is not None:
      return len(self.req.prompt) + len(self.carried.generated)
    return len(self.req.prompt)

  # Read-through to the wrapped request, so queue introspection
  # (`sched.pending[0].uid`) reads the same as before entries carried
  # submit timestamps.
  @property
  def uid(self):
    return self.req.uid

  @property
  def prompt(self):
    return self.req.prompt

  @property
  def priority(self) -> str:
    return self.req.priority


class FCFSScheduler:
  """First-come-first-served continuous-batching scheduler.

  ``plan_step()`` builds the next fused-step inputs (expiring dead
  requests, then admitting new ones as slots and budget allow);
  ``commit(next_tokens)`` folds the step's sampled tokens back into
  per-request state and returns the requests that retired.  The engine
  owns the device half of the loop.

  The ``on_admit`` / ``on_first_token`` / ``on_finish`` hooks are LISTS
  of subscribers (append, don't assign) so stats, resilience and user
  callbacks compose without clobbering each other.

  ``clock`` is injectable for deterministic deadline tests; production
  callers leave it at ``time.monotonic``.
  """

  def __init__(self, num_slots: int, prefill_chunk: int,
               max_seq_len: int, prefill_token_budget: int = 0,
               max_batch: int = 0, stop_token: int = -1,
               spec_k: int = 0, clock: Callable[[], float] = time.monotonic,
               block_size: int = 0, num_blocks: int = 0,
               token_budget: int = 0, track_prefix: str = "serving",
               prefix_cache: bool = False,
               prefix_session_ttl_s: float = 0.0,
               prefix_max_cached_blocks: int = 0,
               checkpoint_version: int = 0):
    from easyparallellibrary_tpu.serving.kv_cache import (
        BlockAllocator, SlotAllocator)
    from easyparallellibrary_tpu.serving.prefix_cache import PrefixCache
    if prefill_chunk < 1:
      raise ValueError(f"prefill_chunk must be >= 1: {prefill_chunk}")
    if prefill_token_budget < 0 or max_batch < 0:
      raise ValueError("prefill_token_budget and max_batch must be >= 0")
    if spec_k < 0:
      raise ValueError(f"spec_k must be >= 0: {spec_k}")
    self.num_slots = num_slots
    self.chunk = prefill_chunk
    self.max_seq_len = max_seq_len
    # The checkpoint version this scheduler's engine serves
    # (docs/robustness.md "Blue/green rollout"): restore_request refuses
    # a snapshot pinned to a DIFFERENT version — prefix replay across
    # weights is not bit-exact — and the prefix cache keys its radix
    # tree on it so a warm block from checkpoint N is never reused to
    # skip prefill under N+1.  0 is the pre-rollout default.
    self.checkpoint_version = int(checkpoint_version)
    # Paged mode (block_size > 0): plan_step builds token-flat
    # PagedStepPlans against a block-table cache; the per-slot K/V
    # region becomes a grown-on-demand block list and pool exhaustion
    # preempts instead of raising (engine: serving.paged.*).
    self.paged = block_size > 0
    if self.paged:
      if max_seq_len % block_size:
        raise ValueError(f"block_size {block_size} must divide "
                         f"max_seq_len {max_seq_len}")
      if token_budget < 1:
        raise ValueError(f"token_budget must be >= 1 in paged mode: "
                         f"{token_budget}")
      eff_batch = min(num_slots, max_batch if max_batch > 0 else num_slots)
      if token_budget < eff_batch:
        raise ValueError(
            f"token_budget {token_budget} below the concurrent-batch cap "
            f"{eff_batch}: a step could not hand every decoding slot its "
            f"one guaranteed token")
      self.block_size = block_size
      self.token_budget = token_budget
      self._mb = max_seq_len // block_size
      self.block_allocator = BlockAllocator(num_blocks, block_size)
      self._slot_blocks: Dict[int, List[int]] = {}
      self._tables = np.zeros((num_slots, self._mb), np.int32)
      self.preemptions = 0
      # Eager evictions at admission so a latency-class arrival never
      # queues behind a throughput slot's blocks (ROADMAP item 5
      # leftover; _preempt_for_latency_admission).
      self.proactive_preemptions = 0
      # Copy-on-write prefix caching (serving/prefix_cache.py): a radix
      # tree over committed prompt blocks.  Admission maps matched
      # blocks by reference and skips their prefill; retirement leaves
      # blocks pinned under the TTL/LRU budget (session persistence).
      self.prefix_cache = (
          PrefixCache(self.block_allocator, block_size,
                      session_ttl_s=prefix_session_ttl_s,
                      max_cached_blocks=prefix_max_cached_blocks,
                      clock=clock, version=self.checkpoint_version)
          if prefix_cache else None)
    else:
      if prefix_cache:
        raise ValueError(
            "prefix caching shares KV at block granularity and therefore "
            "requires the paged cache: enable serving.paged (block_size "
            "> 0) alongside serving.prefix_cache")
      self.block_size = 0
      self.token_budget = 0
      self.block_allocator = None
      self.prefix_cache = None
    self._admit_seq = 0
    # Max speculative drafts per decode slot per step (0 = engine has no
    # drafter); per-request Request.speculative=False opts out, and the
    # engine's degradation ladder flips `spec_enabled` off under load.
    self.spec_k = spec_k
    self.spec_enabled = True
    # 0 = uncapped: every prefilling slot gets a full chunk each step.
    self.prefill_token_budget = prefill_token_budget
    # Temporary degradation override (engine resilience): when > 0 the
    # effective per-step budget is min(budget or inf, override).
    self.budget_override = 0
    # Autotuner clamps (serving/autotune.py) — all DATA-valued: they
    # steer host-side planning/admission only, so moving them between
    # steps never changes a fused-step shape.  tune_budget (>0) joins
    # the budget min above; tune_slot_cap (>0) caps effective
    # concurrency below max_batch; tune_spec_k (>=0) caps per-slot
    # draft length below spec_k (0 = no drafts planned).
    self.tune_budget = 0
    self.tune_slot_cap = 0
    self.tune_spec_k = -1
    self.max_batch = max_batch if max_batch > 0 else num_slots
    self.default_stop_token = stop_token
    self.clock = clock
    # Slot-track namespace for this scheduler's lifecycle spans
    # (replicas pass serving/replica<i> so fleet tracks stay distinct).
    self.track_prefix = track_prefix
    self.allocator = SlotAllocator(num_slots)
    self.pending: Deque[_Pending] = deque()
    # Count of queued latency-class entries, maintained at every
    # pending mutation: _next_pending_index early-outs to O(1) FCFS
    # when none is queued (the common case — an overload queue of
    # throughput requests must not pay an O(depth) scan per admission).
    self._latency_pending = 0
    # Same O(1) discipline for lifecycle deadlines: counts of queued /
    # active requests carrying a deadline or TTFT budget, so expire()
    # (called every plan_step) skips its queue scan and active-slot
    # sweep outright when no request has one — the default.
    self._deadline_pending = 0
    self._deadline_active = 0
    self.active: Dict[int, _SlotState] = {}   # slot -> state
    self._admit_order: List[int] = []         # slots, admission order
    self._plan: Optional[StepPlan] = None
    self._finished_buffer: List[FinishedRequest] = []
    self.on_admit: List[Callable[[Any], None]] = []      # fn(uid)
    self.on_first_token: List[Callable[[Any], None]] = []  # fn(uid)
    self.on_finish: List[Callable[[FinishedRequest], None]] = []
    # Per-iteration token delivery: fn(uid, [tok, ...]) with the tokens
    # THIS commit() appended for that request, fired the moment they
    # commit (before any retirement they trigger) — the streaming front
    # door's feed (serving/frontdoor/), so it never polls `finished`.
    self.on_tokens: List[Callable[[Any, List[int]], None]] = []

  def _effective_budget(self) -> int:
    # Branches, not a list build: this runs twice per engine step on
    # the host hot path.
    budget = self.prefill_token_budget
    if self.budget_override > 0 and \
        (budget == 0 or self.budget_override < budget):
      budget = self.budget_override
    if self.tune_budget > 0 and (budget == 0 or self.tune_budget < budget):
      budget = self.tune_budget
    return budget

  @property
  def effective_max_batch(self) -> int:
    """Concurrency cap after the autotuner's slot-cap clamp (admission
    reads this; ``max_batch`` stays the configured baseline)."""
    if self.tune_slot_cap > 0:
      return min(self.max_batch, self.tune_slot_cap)
    return self.max_batch

  @property
  def effective_spec_k(self) -> int:
    """Per-slot draft cap after the autotuner's speculation clamp."""
    if self.tune_spec_k >= 0:
      return min(self.spec_k, self.tune_spec_k)
    return self.spec_k

  # ---------------------------------------------------------------- queue

  def validate(self, req: Request) -> np.ndarray:
    """Raise on a malformed request (mirrors ``generate()``'s argument
    validation so a request the engine accepts can always run); returns
    the normalized prompt.  The engine also calls this BEFORE its shed
    verdict, so a malformed request fails loudly regardless of load
    instead of being silently recorded as ``"shed"``."""
    prompt = np.asarray(req.prompt, np.int32).reshape(-1)
    if prompt.size == 0:
      raise ValueError("request needs a non-empty prompt (at least a BOS "
                       "token) — same contract as generate()")
    if req.max_new_tokens < 1:
      raise ValueError(f"max_new_tokens must be >= 1: {req.max_new_tokens}")
    total = prompt.size + req.max_new_tokens
    if total > self.max_seq_len:
      raise ValueError(f"prompt + new tokens ({total}) exceeds "
                       f"max_seq_len {self.max_seq_len}")
    if not 0.0 < req.top_p <= 1.0:
      raise ValueError(f"top_p must be in (0, 1]: {req.top_p}")
    if req.top_k < 0:
      raise ValueError(f"top_k must be >= 0: {req.top_k}")
    check_request_fields(req)
    return prompt

  def submit(self, req: Request, _prompt: Optional[np.ndarray] = None):
    """Validate and enqueue (FCFS).  ``_prompt`` lets the engine pass
    the normalized prompt from its own pre-shed ``validate`` call so an
    accepted submit validates exactly once."""
    prompt = self.validate(req) if _prompt is None else _prompt
    req = dataclasses.replace(req, prompt=prompt)
    if req.stop_token < 0 and self.default_stop_token >= 0:
      req = dataclasses.replace(req, stop_token=self.default_stop_token)
    # Flow-context id: minted here unless an upstream router already
    # did (its id wins — the flow must span the WHOLE dispatch arc).
    minted = req.flow_id is None
    if minted:
      req = dataclasses.replace(req, flow_id=next_flow_id())
    self.pending.append(_Pending(req, self.clock()))
    self._latency_pending += req.priority == "latency"
    self._deadline_pending += self._has_deadline(req)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:  # args dicts are not free; skip them when off
      tracer.instant(
          "serving/submit", cat="serving", track="serving/requests",
          args={"uid": str(req.uid), "prompt_tokens": int(prompt.size),
                "max_new_tokens": int(req.max_new_tokens)})
      # The minter starts the flow; a router-minted flow already has
      # its "s" — this submit is one step of its arc.
      tracer.flow("s" if minted else "t", req.flow_id,
                  track="serving/requests", args={"uid": str(req.uid)})

  @property
  def has_work(self) -> bool:
    return bool(self.pending or self.active)

  @property
  def num_active(self) -> int:
    return len(self.active)

  @property
  def queue_depth(self) -> int:
    return len(self.pending)

  def take_finished(self) -> List[FinishedRequest]:
    """Drain retirements accumulated since the last call (commit-time
    retirements plus plan-time expiries and out-of-band cancellations)."""
    out, self._finished_buffer = self._finished_buffer, []
    return out

  # ------------------------------------------------------ lifecycle ctl

  def _finish_unadmitted(self, entry: _Pending, reason: str):
    """Retire a request straight out of the queue (expiry/cancel before
    a slot was ever granted — or after a requeue)."""
    generated = (entry.carried.generated if entry.carried is not None
                 else [])
    fin = FinishedRequest(
        uid=entry.req.uid,
        tokens=np.concatenate(
            [entry.req.prompt, np.asarray(generated, np.int32)]),
        new_tokens=len(generated),
        finish_reason=reason)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          f"serving/{reason}", cat="serving", track="serving/requests",
          args={"uid": str(entry.req.uid), "where": "queue"})
      if entry.req.flow_id is not None:
        # Queue-side retirement terminates the flow too — every started
        # flow must reach an "f" (validate_trace).
        tracer.flow("f", entry.req.flow_id, track="serving/requests",
                    args={"uid": str(entry.req.uid), "reason": reason})
    self._finished_buffer.append(fin)
    for fn in self.on_finish:
      fn(fin)
    return fin

  @staticmethod
  def _has_deadline(req: Request) -> bool:
    return req.deadline_s > 0 or req.ttft_budget_s > 0

  def _expired(self, req: Request, submitted_at: float, now: float,
               first_token: bool) -> bool:
    waited = now - submitted_at
    if req.deadline_s > 0 and waited >= req.deadline_s:
      return True
    return (req.ttft_budget_s > 0 and not first_token
            and waited >= req.ttft_budget_s)

  def expire(self, now: Optional[float] = None) -> int:
    """Retire every queued or active request whose deadline / TTFT
    budget has passed (finish reason ``"deadline"``).  Called by
    ``plan_step`` each iteration; callable standalone.  O(1) when no
    queued/active request carries a deadline (the ``_deadline_*``
    counters).  Returns how many requests expired."""
    now = self.clock() if now is None else now
    expired = 0
    if self.pending and self._deadline_pending:
      keep: Deque[_Pending] = deque()
      for entry in self.pending:
        first = (entry.carried.first_token_emitted
                 if entry.carried is not None else False)
        if self._expired(entry.req, entry.submitted_at, now, first):
          # _expired is True only for a deadline-carrying request, so
          # the unconditional decrement is exact.
          self._latency_pending -= entry.req.priority == "latency"
          self._deadline_pending -= 1
          self._finish_unadmitted(entry, "deadline")
          expired += 1
        else:
          keep.append(entry)
      self.pending = keep
    if not self._deadline_active:
      return expired
    for slot in list(self._admit_order):
      state = self.active.get(slot)
      if state is None:
        continue
      if self._expired(state.req, state.submitted_at, now,
                       state.first_token_emitted):
        self._retire(state, "deadline")
        expired += 1
    return expired

  def cancel(self, uid: Any) -> bool:
    """Client cancellation: retire `uid` wherever it is (queued or
    active) with finish reason ``"cancelled"``.  Returns False when the
    request is unknown (already finished, or never submitted)."""
    for i, entry in enumerate(self.pending):
      if entry.req.uid == uid:
        del self.pending[i]
        self._latency_pending -= entry.req.priority == "latency"
        self._deadline_pending -= self._has_deadline(entry.req)
        self._finish_unadmitted(entry, "cancelled")
        return True
    for slot, state in list(self.active.items()):
      if state.req.uid == uid:
        self._retire(state, "cancelled")
        return True
    return False

  def requeue_slot(self, slot: int, reason: str = "bad_step"
                   ) -> Optional[Any]:
    """Quarantine: evict `slot`'s request back to the FRONT of the queue
    with its committed prefix intact (module docstring) — the engine's
    bad-step recovery uses this to stop one poisoned slot from wedging
    the batch.  Returns the requeued uid, or None for an empty slot."""
    state = self.active.get(slot)
    if state is None:
      return None
    del self.active[slot]
    self._admit_order.remove(slot)
    self.allocator.free(slot)
    self._release_blocks(slot)
    self._deadline_active -= self._has_deadline(state.req)
    state.requeues += 1
    state.bad_streak = 0
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      if state.req.flow_id is not None:
        # Flow step INSIDE the closing span, so the arc anchors on this
        # occupancy before jumping to the request's next slot.
        tracer.flow("t", state.req.flow_id,
                    track=_slot_track(slot, self.track_prefix),
                    args={"uid": str(state.req.uid), "reason": reason})
      tracer.end(
          f"request {state.req.uid}", cat="serving.request",
          track=_slot_track(slot, self.track_prefix),
          args={"finish_reason": "requeued",
                "new_tokens": int(len(state.generated))})
      tracer.instant(
          "serving/requeue", cat="serving", track="serving/requests",
          args={"uid": str(state.req.uid), "slot": int(slot),
                "reason": reason,
                "committed_prefix": int(len(state.req.prompt)
                                        + len(state.generated))})
    self.pending.appendleft(
        _Pending(state.req, state.submitted_at, carried=state))
    self._latency_pending += state.req.priority == "latency"
    self._deadline_pending += self._has_deadline(state.req)
    return state.req.uid

  def retire_slot(self, slot: int, reason: str) -> Optional[FinishedRequest]:
    """Force-retire an active slot with an explicit finish reason (the
    engine's quarantine-overflow path: reason ``"failed"``)."""
    state = self.active.get(slot)
    if state is None:
      return None
    return self._retire(state, reason)

  # ------------------------------------------------- snapshot / migration

  @staticmethod
  def _snapshot_state(req: Request, generated: List[int], requeues: int,
                      first_token_emitted: bool,
                      submitted_at: float) -> Dict[str, Any]:
    return {
        "request": req.snapshot(),
        "generated": [int(t) for t in generated],
        "requeues": int(requeues),
        "first_token_emitted": bool(first_token_emitted),
        "submitted_at": float(submitted_at),
    }

  def snapshot_requests(self) -> List[Dict[str, Any]]:
    """Serializable snapshots of every IN-FLIGHT and queued request, in
    service order (active slots by admission order, then the queue
    front-to-back).  Each snapshot carries the request spec
    (:meth:`Request.snapshot`) plus the mutable half — committed
    generated prefix, requeue count, first-token flag, submit time —
    which is everything bit-exact resumption needs: restoring on ANY
    scheduler against the same params source replays prompt + prefix
    through chunked prefill, reconstructing KV, cursors and the
    ``tok_index`` PRNG fold exactly (module docstring: the requeue
    contract, here made cross-replica).  Read-only — the scheduler is
    untouched; pair with :meth:`evacuate` to also remove them."""
    snaps = []
    for slot in self._admit_order:
      s = self.active[slot]
      snaps.append(self._snapshot_state(
          s.req, s.generated, s.requeues, s.first_token_emitted,
          s.submitted_at))
    for entry in self.pending:
      c = entry.carried
      snaps.append(self._snapshot_state(
          entry.req, c.generated if c is not None else [],
          c.requeues if c is not None else 0,
          c.first_token_emitted if c is not None else False,
          entry.submitted_at))
    return snaps

  def progress(self) -> List[Any]:
    """``[(uid, generated_token_list)]`` for every live request, in
    service order (active slots by admission order, then the queue) —
    the committed-token watermark stream a transport worker reports so
    the router-side crash journal can replay bit-exactly
    (serving/transport.py).  Lives beside :meth:`snapshot_requests`
    because it walks the identical structure — the wire layer must
    never reach into scheduler internals for it."""
    out: List[Any] = []
    for slot in self._admit_order:
      state = self.active[slot]
      out.append((state.req.uid, state.generated))
    for entry in self.pending:
      carried = entry.carried
      out.append((entry.req.uid,
                  carried.generated if carried is not None else []))
    return out

  def restore_request(self, snap: Dict[str, Any],
                      front: bool = False) -> Any:
    """Resubmit a snapshotted request (queued here, replayed through
    chunked prefill on admission — the committed prefix and sample
    stream resume bit-exactly).  ``front=True`` preserves the migrated
    request's place in line (failover resubmits in REVERSE snapshot
    order so the head of the dead replica's line stays the head here).
    Returns the restored uid.

    A snapshot pinned to a DIFFERENT checkpoint version is REFUSED
    (docs/robustness.md "Blue/green rollout"): replaying its committed
    prefix under other weights would silently fork the sample stream —
    the router places it on a same-version survivor or parks it."""
    pinned = snap["request"].get("checkpoint_version")
    if pinned is not None and int(pinned) != self.checkpoint_version:
      raise ValueError(
          f"cross-version restore refused: request "
          f"{snap['request'].get('uid')!r} is pinned to checkpoint "
          f"version {int(pinned)} but this replica serves version "
          f"{self.checkpoint_version} — prefix replay across versions "
          f"is not bit-exact (migration policy is complete-in-place; "
          f"docs/robustness.md)")
    req = Request.restore(snap["request"])
    req = dataclasses.replace(req, prompt=self.validate(req))
    restored_flow = req.flow_id is not None
    if not restored_flow:  # pre-flow snapshot: start a fresh flow here
      req = dataclasses.replace(req, flow_id=next_flow_id())
    submitted_at = float(snap["submitted_at"])
    generated = [int(t) for t in snap.get("generated", ())]
    carried = None
    if generated or snap.get("requeues") or snap.get("first_token_emitted"):
      # Rebuild the carried per-request state a requeue would have kept:
      # the slot number is a placeholder (never read off a carried
      # state) and the PRNG key re-derives from seed/uid — identical by
      # _request_key's determinism.
      carried = _SlotState(req, -1, submitted_at, self.clock())
      carried.generated = generated
      carried.requeues = int(snap.get("requeues", 0))
      carried.first_token_emitted = bool(snap.get("first_token_emitted"))
      carried.prefix = np.concatenate(
          [req.prompt, np.asarray(generated, np.int32)])
    entry = _Pending(req, submitted_at, carried=carried)
    if front:
      self.pending.appendleft(entry)
    else:
      self.pending.append(entry)
    self._latency_pending += req.priority == "latency"
    self._deadline_pending += self._has_deadline(req)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(
          "serving/restore", cat="serving", track="serving/requests",
          args={"uid": str(req.uid),
                "committed_prefix": int(len(req.prompt) + len(generated))})
      tracer.flow("t" if restored_flow else "s", req.flow_id,
                  track="serving/requests",
                  args={"uid": str(req.uid), "reason": "restored"})
    return req.uid

  def evacuate(self) -> List[Dict[str, Any]]:
    """Snapshot EVERY queued + in-flight request, then remove them all
    without finish records (they will finish elsewhere — failover and
    drain-timeout migration; router.py).  Slots, blocks and lifecycle
    counters are released exactly as a requeue releases them; each
    active request's trace span ends with reason ``"migrated"`` (like
    ``"requeued"``/``"preempted"``, it names a move, not a final
    resolution).  Call between steps only — never with a plan in
    flight."""
    snaps = self.snapshot_requests()
    tracer = trace_lib.get_tracer()
    for slot in list(self._admit_order):
      state = self.active.pop(slot)
      self._admit_order.remove(slot)
      self.allocator.free(slot)
      self._release_blocks(slot)
      self._deadline_active -= self._has_deadline(state.req)
      if tracer.enabled:
        if state.req.flow_id is not None:
          tracer.flow("t", state.req.flow_id,
                      track=_slot_track(slot, self.track_prefix),
                      args={"uid": str(state.req.uid),
                            "reason": "migrated"})
        tracer.end(
            f"request {state.req.uid}", cat="serving.request",
            track=_slot_track(slot, self.track_prefix),
            args={"finish_reason": "migrated",
                  "new_tokens": int(len(state.generated))})
    self.pending.clear()
    self._latency_pending = 0
    self._deadline_pending = 0
    self._plan = None
    return snaps

  # ----------------------------------------------------------------- plan

  def _next_pending_index(self) -> int:
    """Admission order: the oldest ``latency``-class request if any is
    queued (priority admission), else the queue head (FCFS).  O(1)
    unless a latency-class entry is actually queued."""
    if self._latency_pending == 0:
      return 0
    for i, entry in enumerate(self.pending):
      if entry.req.priority == "latency":
        return i
    return 0

  def _admit(self) -> None:
    """Admit pending requests while slots, the batch cap and the prefill
    budget allow — ``latency``-class first, then FCFS.  The budget is
    charged for each admission's first chunk so one step never admits
    more prefill work than it can schedule — an admitted-but-starved
    request would hold a slot while contributing nothing."""
    budget_cap = self._effective_budget()
    batch_cap = self.effective_max_batch   # hoisted: loop-invariant
    budget_left = budget_cap
    if budget_left > 0:
      # Already-active prefill slots have first claim on the budget.
      budget_left -= sum(
          min(self.chunk, len(s.prefix) - s.prompt_pos)
          for s in self.active.values() if s.prefilling)
    while self.pending:
      idx = self._next_pending_index()
      entry = self.pending[idx]
      first_chunk = min(self.chunk, entry.prefix_len)
      if budget_cap > 0 and budget_left < first_chunk:
        break
      if (self.allocator.num_free == 0
          or len(self.active) >= batch_cap):
        # Capacity-blocked.  Proactive latency-class preemption (paged
        # engine): a latency arrival next in line evicts the youngest
        # throughput slot holding blocks NOW rather than queueing until
        # a retirement or pool exhaustion frees capacity.  The budget
        # check above ran first — evicting for an admission this step
        # cannot afford would burn the victim's progress for nothing.
        if not (self.paged and self._latency_pending
                and entry.req.priority == "latency"):
          break
        if self._preempt_for_latency_admission() is None:
          break
        # The victim re-entered the queue at its front; the latency
        # entry's index may have shifted — re-resolve it.
        idx = self._next_pending_index()
        entry = self.pending[idx]
      budget_left -= first_chunk
      del self.pending[idx]
      self._latency_pending -= entry.req.priority == "latency"
      self._deadline_pending -= self._has_deadline(entry.req)
      req = entry.req
      slot = self.allocator.alloc()
      self._admit_seq += 1
      state = _SlotState(req, slot, entry.submitted_at, self.clock(),
                         carried=entry.carried,
                         admit_seq=self._admit_seq)
      self.active[slot] = state
      self._deadline_active += self._has_deadline(req)
      self._admit_order.append(slot)
      # Warm admission (serving/prefix_cache.py): walk the radix tree
      # with the request's prefix (prompt, plus the committed replay
      # for a requeued one).  Matched blocks map into the table by
      # reference — each already carries one fresh refcount from
      # match() — and the prompt cursor jumps past them, so chunked
      # prefill only ever feeds the unmatched tail.  The match cap
      # (strictly before the last prefix token) guarantees prompt_pos
      # stays short of len(prefix): the slot still runs at least one
      # prefill step, keeping first-token emission on its normal path.
      reused = 0
      if self.paged and self.prefix_cache is not None:
        matched = self.prefix_cache.match(state.prefix)
        if matched:
          blocks = self._slot_blocks.setdefault(slot, [])
          for blk in matched:
            self._tables[slot, len(blocks)] = blk
            blocks.append(blk)
          reused = len(matched)
          state.prompt_pos = reused * self.block_size
          state.reg_blocks = reused
      # The request's lifecycle span opens on its slot's track and stays
      # open until _retire — every per-step prefill/decode span the
      # engine records for this slot nests inside it, so one Perfetto
      # track row reads as the request's complete timeline.
      tracer = trace_lib.get_tracer()
      if tracer.enabled:
        args = {"uid": str(req.uid),
                "prompt_tokens": int(len(req.prompt)),
                "max_new_tokens": int(req.max_new_tokens)}
        if reused:
          args["prefix_blocks_reused"] = int(reused)
        if state.requeues:
          args["requeues"] = int(state.requeues)
        tracer.begin(f"request {req.uid}", cat="serving.request",
                     track=_slot_track(slot, self.track_prefix),
                     args=args)
        if req.flow_id is not None:
          # Flow step just inside the freshly opened span: the arc
          # lands on this slot's track for this occupancy.
          tracer.flow("t", req.flow_id,
                      track=_slot_track(slot, self.track_prefix),
                      args={"uid": str(req.uid)})
      if state.requeues == 0:
        for fn in self.on_admit:
          fn(req.uid)

  # -------------------------------------------------- paged block planning

  def _resident_tokens(self, state: _SlotState) -> int:
    """Tokens whose K/V are valid-resident in the slot's blocks — the
    host mirror of the contiguous engine's device cursor.  During
    prefill this is the fed prefix; after it, the decode input token's
    position is always ``len(prompt) + len(generated) - 1`` (a requeued
    replay's generated prefix is both inside ``prefix`` AND in
    ``generated``, which this accounting absorbs)."""
    if state.prefilling:
      return state.prompt_pos
    return len(state.req.prompt) + len(state.generated) - 1

  def slot_blocks(self, slot: int) -> List[int]:
    """The slot's current block list (engine sanitize + tests)."""
    return list(self._slot_blocks.get(slot, ()))

  @property
  def kv_blocks_free(self) -> int:
    return self.block_allocator.num_free if self.paged else 0

  @property
  def kv_blocks_used(self) -> int:
    return self.block_allocator.num_used if self.paged else 0

  @property
  def kv_fragmentation(self) -> float:
    """Fraction of allocated block capacity no resident token occupies
    (last-block slack + preallocated draft headroom)."""
    if not self.paged:
      return 0.0
    used_tokens = sum(self._resident_tokens(s)
                      for s in self.active.values())
    return self.block_allocator.fragmentation(used_tokens)

  def _release_blocks(self, slot: int) -> None:
    if not self.paged:
      return
    for blk in self._slot_blocks.pop(slot, ()):  # noqa: B909
      self.block_allocator.decref(blk)
    self._tables[slot] = 0

  # --------------------------------------------------- prefix-cache interop

  @property
  def prefix_hits(self) -> int:
    return self.prefix_cache.hits if self.prefix_cache is not None else 0

  @property
  def prefix_misses(self) -> int:
    return self.prefix_cache.misses if self.prefix_cache is not None else 0

  @property
  def prefix_blocks_reused(self) -> int:
    return (self.prefix_cache.blocks_reused
            if self.prefix_cache is not None else 0)

  @property
  def prefix_evictions(self) -> int:
    return (self.prefix_cache.evictions
            if self.prefix_cache is not None else 0)

  @property
  def prefix_cached_blocks(self) -> int:
    return (self.prefix_cache.num_cached_blocks
            if self.prefix_cache is not None else 0)

  def invalidate_cached_blocks(self, blocks) -> int:
    """Purge ``blocks`` from the prefix cache (engine sanitize: zeroed
    K/V must never satisfy a future match).  No-op without a cache."""
    if self.prefix_cache is None:
      return 0
    return self.prefix_cache.invalidate_blocks(blocks)

  def _register_cached(self, state: _SlotState) -> None:
    """Register ``state``'s newly COMPLETED full blocks in the prefix
    tree — called at commit watermarks (prefill advance, decode block
    boundaries) and at retirement (session persistence).  Only blocks
    strictly below the committed-K/V watermark register, so a tree
    entry always describes fully-written, commit-gated content; the
    partial tail block (and any position a bad step may have scribbled
    on) stays private to the slot."""
    upto = self._resident_tokens(state)
    n = upto // self.block_size
    blocks = self._slot_blocks.get(state.slot)
    if blocks is not None:
      n = min(n, len(blocks))
    else:
      n = 0
    if n <= state.reg_blocks:
      return
    if state.prefilling:
      tokens = state.prefix  # covers [0, prompt_pos) — exactly what fed
    else:
      tokens = np.concatenate(
          [state.req.prompt, np.asarray(state.generated, np.int32)])
    self.prefix_cache.register(tokens, n, blocks)
    state.reg_blocks = n

  def _preemption_victim(self, req_rank, excluded: set) -> Optional[int]:
    """Shared eligibility rule for BOTH preemption paths (pool
    exhaustion and proactive latency admission).  Victim choice: lowest
    priority class first, then the youngest admission — the
    least-progress slot loses.  A victim must rank strictly below the
    requester (``(is_latency, -admit_seq)`` ordering, so two starving
    peers can never preempt each other in a cycle), must not be in
    ``excluded`` (the requester itself, or slots already holding
    scheduled work in the plan being built — their in-flight writes
    would race the reallocated blocks), and must actually hold blocks
    (a blockless victim frees nothing: evicting it would requeue a
    request — burning its queue position — without refilling the
    pool)."""
    best = None
    best_rank = None
    for slot, state in self.active.items():
      if slot in excluded:
        continue
      if not self._slot_blocks.get(slot):
        continue
      rank = (state.req.priority == "latency", -state.admit_seq)
      if rank >= req_rank:
        continue  # only strictly lower-priority-or-younger slots
      if best is None or rank < best_rank:
        best, best_rank = slot, rank
    return best

  def _preempt_for_blocks(self, requester: int,
                          scheduled: set) -> Optional[int]:
    """Page out one victim to refill the pool (satellite of ROADMAP
    item 1: exhaustion preempts instead of raising).  Eligibility:
    :meth:`_preemption_victim`.  Returns the victim slot or None."""
    req_state = self.active.get(requester)
    if req_state is None:
      return None
    req_rank = (req_state.req.priority == "latency", -req_state.admit_seq)
    best = self._preemption_victim(req_rank, scheduled | {requester})
    if best is None:
      return None
    uid = self.active[best].req.uid
    self.preemptions += 1
    get_logger().warning(
        "KV block pool exhausted: preempting slot %d (uid %r) to refill "
        "it; the request replays its committed prefix on readmission",
        best, uid)
    self.requeue_slot(best, reason="preempted")
    return best

  def _preempt_for_latency_admission(self) -> Optional[int]:
    """Proactive latency-class preemption (ROADMAP item 5 leftover):
    when a ``latency``-priority request is next in line but admission is
    capacity-blocked (no free slot, or the batch cap is full), evict the
    youngest throughput-class slot holding blocks NOW — eagerly, at
    admission — instead of making the latency request wait for a natural
    retirement or the pool to run dry.  Same eligibility rules as
    exhaustion preemption (admission-seq ordering — an older
    latency-class slot is never evicted for a younger latency arrival —
    and draft headroom still never preempts: that rule lives in
    ``_ensure_blocks(preempt=False)``, untouched here).  Returns the
    victim slot or None; counted separately as
    ``proactive_preemptions``."""
    # The would-be admission's rank: strictly younger than every active
    # slot, latency class — so exactly the throughput-class actives are
    # eligible, youngest first.
    req_rank = (True, -(self._admit_seq + 1))
    best = self._preemption_victim(req_rank, set())
    if best is None:
      return None
    uid = self.active[best].req.uid
    self.proactive_preemptions += 1
    get_logger().info(
        "proactive preemption: evicting throughput slot %d (uid %r) to "
        "admit a latency-class request; the victim replays its committed "
        "prefix on readmission", best, uid)
    self.requeue_slot(best, reason="preempted")
    return best

  def _ensure_blocks(self, slot: int, num_tokens: int, scheduled: set,
                     preempt: bool = True) -> int:
    """Grow ``slot``'s block list to cover ``num_tokens`` positions,
    preempting victims when the pool runs dry (``preempt=False`` for
    optional work — speculative draft headroom must never evict a
    request's committed K/V).  Returns the number of positions actually
    covered (callers shrink their grant to it — a short allocation
    starves the slot for a step, never corrupts)."""
    blocks = self._slot_blocks.setdefault(slot, [])
    need = min((num_tokens + self.block_size - 1) // self.block_size,
               self._mb)
    while len(blocks) < need:
      blk = self.block_allocator.alloc()
      if blk is None:
        # Reclamation order on a dry pool: cached-but-unmapped prefix
        # blocks first (pure cache — dropping them costs a future
        # admission some prefill, never a live request its progress),
        # preemption only once the tree has nothing evictable.  A
        # preempted victim's released blocks may themselves become
        # tree-only references, which the NEXT iteration's eviction
        # pass then reclaims.
        if (self.prefix_cache is not None
            and self.prefix_cache.evict_for_space(
                need - len(blocks)) > 0):
          continue
        if not preempt or self._preempt_for_blocks(slot, scheduled) is None:
          break
        continue
      self._tables[slot, len(blocks)] = blk
      blocks.append(blk)
    return min(len(blocks) * self.block_size, self.max_seq_len)

  def _plan_flat(self) -> Optional[PagedStepPlan]:
    """Token-budget planning: the paged twin of the slot-block half of
    :meth:`plan_step`.  Three passes over admission order fill the flat
    batch: (1) every decoding slot gets its one guaranteed token (ITL
    protection — ``token_budget >= max_batch`` is validated so this pass
    never starves), (2) prefill chunks stream in while the flat budget
    and the prefill-token budget allow, (3) leftover budget is reserved
    for speculative drafts (drafts ride spare capacity here, exactly as
    they ride wasted chunk positions in the slot engine).  Block
    coverage is ensured per grant; a dry pool preempts the youngest
    lowest-priority slot, and a still-short allocation shrinks the grant
    (the slot resumes next step)."""
    if not self.active:
      self._plan = None
      return None
    T, N, MB = self.token_budget, self.num_slots, self._mb
    plan = PagedStepPlan(
        tokens=np.zeros((T,), np.int32),
        slot_ids=np.zeros((T,), np.int32),
        positions=np.zeros((T,), np.int32),
        valid=np.zeros((T,), bool),
        block_tables=np.zeros((N, MB), np.int32),
        base_idx=np.zeros((N,), np.int32),
        draft_base=np.zeros((N,), np.int32),
        num_valid=np.zeros((N,), np.int32),
        draft_cap=np.zeros((N,), np.int32),
        prefilling=np.zeros((N,), bool),
        keys=np.zeros((N, 2), np.uint32),
        tok_index=np.zeros((N,), np.int32),
        temperature=np.zeros((N,), np.float32),
        top_k=np.zeros((N,), np.int32),
        top_p=np.ones((N,), np.float32),
        prefill_tokens=0, decode_tokens=0, scheduled_tokens=0,
        active_slots=len(self.active))
    budget = self._effective_budget()
    pos = 0
    scheduled: set = set()
    # Pass 1: decode slots — one guaranteed token each.
    for slot in list(self._admit_order):
      state = self.active.get(slot)
      if state is None or state.prefilling:
        continue
      dec_pos = self._resident_tokens(state)
      if self._ensure_blocks(slot, dec_pos + 1, scheduled) < dec_pos + 1:
        continue  # pool exhausted with no eligible victim: starve a step
      state = self.active.get(slot)
      if state is None:
        continue  # defensive: a preemption cascade evicted this slot
      plan.base_idx[slot] = pos
      plan.tokens[pos] = state.generated[-1]
      plan.slot_ids[pos] = slot
      plan.positions[pos] = dec_pos
      plan.valid[pos] = True
      plan.num_valid[slot] = 1
      plan.decode_tokens += 1
      pos += 1
      scheduled.add(slot)
    # Pass 2: prefill chunks under both budgets.
    for slot in list(self._admit_order):
      state = self.active.get(slot)
      if state is None or not state.prefilling or pos >= T:
        continue
      remaining = len(state.prefix) - state.prompt_pos
      grant = min(self.chunk, remaining, T - pos)
      if budget > 0:
        grant = min(grant, max(budget - plan.prefill_tokens, 0))
      if grant <= 0:
        continue
      covered = self._ensure_blocks(slot, state.prompt_pos + grant,
                                    scheduled)
      grant = min(grant, covered - state.prompt_pos)
      state = self.active.get(slot)
      if state is None or grant <= 0:
        continue
      chunk = state.prefix[state.prompt_pos:state.prompt_pos + grant]
      plan.base_idx[slot] = pos
      plan.tokens[pos:pos + grant] = chunk
      plan.slot_ids[pos:pos + grant] = slot
      plan.positions[pos:pos + grant] = np.arange(
          state.prompt_pos, state.prompt_pos + grant)
      plan.valid[pos:pos + grant] = True
      plan.num_valid[slot] = grant
      plan.prefilling[slot] = True
      plan.prefill_tokens += grant
      pos += grant
      scheduled.add(slot)
    # Pass 3: speculative draft reservations ride the leftover budget.
    spec_k = self.effective_spec_k
    if spec_k > 0 and self.spec_enabled:
      for slot in list(self._admit_order):
        state = self.active.get(slot)
        if (state is None or state.prefilling
            or plan.num_valid[slot] != 1 or pos >= T
            or state.req.speculative is False):
          continue
        remaining = state.req.max_new_tokens - len(state.generated)
        cap = max(0, min(spec_k, remaining - 1, T - pos))
        if cap <= 0:
          continue
        dec_pos = int(plan.positions[plan.base_idx[slot]])
        # Draft headroom is OPTIONAL work: never preempt for it — a dry
        # pool just shrinks the draft cap (drafts ride spare capacity).
        covered = self._ensure_blocks(slot, dec_pos + 1 + cap, scheduled,
                                      preempt=False)
        cap = max(0, min(cap, covered - 1 - dec_pos))
        if cap <= 0 or self.active.get(slot) is None:
          continue
        plan.draft_base[slot] = pos
        plan.slot_ids[pos:pos + cap] = slot
        plan.positions[pos:pos + cap] = np.arange(dec_pos + 1,
                                                  dec_pos + 1 + cap)
        # valid stays False: the engine flips exactly the positions the
        # drafter fills (serving/engine.py _propose_drafts).
        plan.draft_cap[slot] = cap
        pos += cap
    # Per-slot sampling state for every slot with scheduled work.
    for slot in self._admit_order:
      state = self.active.get(slot)
      if state is None or plan.num_valid[slot] == 0:
        continue
      req = state.req
      plan.keys[slot] = state.key
      plan.tok_index[slot] = len(state.generated)
      plan.temperature[slot] = req.temperature
      plan.top_k[slot] = req.top_k
      plan.top_p[slot] = req.top_p
    plan.scheduled_tokens = pos
    plan.block_tables = self._tables.copy()
    if pos == 0:
      # Every active slot starved (pool exhausted, budget zero): no
      # device work this iteration.
      self._plan = None
      return None
    self._plan = plan
    return plan

  def plan_step(self) -> Optional[StepPlan]:
    """Build the next fused step's inputs, or None when idle.

    Order: expire dead requests, admit (priority first, then FCFS),
    then grant tokens.  Budgeting: decode slots always get their one
    token (decode latency is the metric continuous batching protects);
    prefill chunks are granted FCFS in admission order until the
    per-step budget runs out — a starved prefill slot simply carries
    ``num_valid=0`` this step and resumes next step.
    """
    self.expire()
    if self.prefix_cache is not None:
      # Session TTL sweep before admission, so an expired session can
      # never satisfy this iteration's matches.  O(expired) — the
      # cache's LRU front is its least-recent entry.
      self.prefix_cache.expire()
    self._admit()
    if self.paged:
      return self._plan_flat()
    if not self.active:
      self._plan = None
      return None
    N, C = self.num_slots, self.chunk
    plan = StepPlan(
        tokens=np.zeros((N, C), np.int32),
        num_valid=np.zeros((N,), np.int32),
        reset=np.zeros((N,), bool),
        keys=np.zeros((N, 2), np.uint32),
        tok_index=np.zeros((N,), np.int32),
        temperature=np.zeros((N,), np.float32),
        top_k=np.zeros((N,), np.int32),
        top_p=np.ones((N,), np.float32),
        draft_cap=np.zeros((N,), np.int32),
        prefilling=np.zeros((N,), bool),
        prefill_tokens=0, decode_tokens=0,
        active_slots=len(self.active))
    budget = self._effective_budget()
    spec_k = self.effective_spec_k        # hoisted: loop-invariant
    for slot in self._admit_order:
      state = self.active.get(slot)
      if state is None:
        continue
      req = state.req
      plan.keys[slot] = state.key
      plan.tok_index[slot] = len(state.generated)
      plan.temperature[slot] = req.temperature
      plan.top_k[slot] = req.top_k
      plan.top_p[slot] = req.top_p
      # Nothing fed yet (fresh slot, or a requeued request starting its
      # replay): zero the cursor before this step's writes.
      plan.reset[slot] = state.prompt_pos == 0
      if state.prefilling:
        remaining = len(state.prefix) - state.prompt_pos
        grant = min(C, remaining)
        if budget > 0:
          grant = min(grant, max(budget - plan.prefill_tokens, 0))
        if grant == 0:
          continue  # budget-starved this step; resumes next step
        chunk = state.prefix[state.prompt_pos:state.prompt_pos + grant]
        plan.tokens[slot, :grant] = chunk
        plan.num_valid[slot] = grant
        plan.prefilling[slot] = True
        plan.prefill_tokens += grant
      else:
        plan.tokens[slot, 0] = state.generated[-1]
        plan.num_valid[slot] = 1
        plan.decode_tokens += 1
        if (spec_k > 0 and self.spec_enabled
            and req.speculative is not False):
          # Drafting past the request's remaining budget is pure waste:
          # at most (remaining - 1) drafts can commit alongside the
          # step's guaranteed token.
          remaining = req.max_new_tokens - len(state.generated)
          plan.draft_cap[slot] = max(0, min(spec_k, remaining - 1))
    self._plan = plan
    return plan

  def slot_histories(self, plan: StepPlan) -> Dict[int, np.ndarray]:
    """Committed tokens (prompt + generated) per draft-eligible slot of
    ``plan`` — the context drafters propose from."""
    out: Dict[int, np.ndarray] = {}
    for slot, state in self.active.items():
      if plan.draft_cap[slot] > 0:
        out[slot] = np.concatenate(
            [state.req.prompt,
             np.asarray(state.generated, np.int32)])
    return out

  # --------------------------------------------------------------- commit

  def _emit_tokens(self, uid: Any, fresh: List[int]) -> None:
    """Fan one request's just-committed tokens out to the ``on_tokens``
    subscribers — always BEFORE any retirement those tokens trigger, so
    a streaming consumer sees every token ahead of the finish event."""
    for fn in self.on_tokens:
      fn(uid, fresh)

  def _retire(self, state: _SlotState, reason: str) -> FinishedRequest:
    slot = state.slot
    del self.active[slot]
    self._admit_order.remove(slot)
    self.allocator.free(slot)
    # Session KV persistence: register the retiring request's completed
    # blocks BEFORE releasing the slot's references, so a multi-turn
    # follow-up (its next prompt = this conversation's full history)
    # admits warm.  The tree's own references keep the blocks resident
    # under its TTL/LRU budget.  A quarantine-overflow retirement
    # ("failed") never registers — its device state is untrusted.
    if self.prefix_cache is not None and reason != "failed":
      self._register_cached(state)
    self._release_blocks(slot)
    self._deadline_active -= self._has_deadline(state.req)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      if state.req.flow_id is not None:
        tracer.flow("f", state.req.flow_id,
                    track=_slot_track(slot, self.track_prefix),
                    args={"uid": str(state.req.uid), "reason": reason})
      tracer.end(
          f"request {state.req.uid}", cat="serving.request",
          track=_slot_track(slot, self.track_prefix),
          args={"finish_reason": reason,
                "new_tokens": int(len(state.generated))})
    fin = FinishedRequest(
        uid=state.req.uid,
        tokens=np.concatenate(
            [state.req.prompt,
             np.asarray(state.generated, np.int32)]),
        new_tokens=len(state.generated),
        finish_reason=reason)
    self._finished_buffer.append(fin)
    for fn in self.on_finish:
      fn(fin)
    return fin

  def commit(self, next_tokens: np.ndarray,
             num_committed: Optional[np.ndarray] = None,
             slot_ok: Optional[np.ndarray] = None
             ) -> List[FinishedRequest]:
    """Fold one step's committed tokens back into request state; returns
    this iteration's retirements (commit-time plus any buffered
    plan-time expiries).  ``next_tokens`` is ``[N]`` (one sampled token
    per slot, the non-speculative step) or ``[N, K+1]`` with
    ``num_committed [N]`` (speculative verification: accepted drafts
    plus the correction/bonus token).  ``slot_ok`` (bool [N], engine
    resilience) marks slots whose device step was judged bad — those are
    skipped WHOLESALE (no prefix advance, no token commit), which makes
    the next ``plan_step`` re-feed the identical work: the cursor never
    moved, so the replay is the retry.  A slot's tokens only count when
    its prompt is fully consumed — mid-prefill samples are positions
    whose "next token" is still dictated by the prompt.  Multi-token
    commits apply stop-token and ``max_new_tokens`` checks PER TOKEN in
    commit order, so a stop token appearing mid-draft retires the
    request and discards the rest of its accepted drafts."""
    if self._plan is None:
      raise RuntimeError("commit() without a preceding plan_step()")
    plan, self._plan = self._plan, None
    tokens = np.asarray(next_tokens)
    if tokens.ndim == 1:
      tokens = tokens[:, None]
    if num_committed is None:
      num_committed = np.ones((tokens.shape[0],), np.int32)
    now = self.clock()
    for slot in list(self._admit_order):
      state = self.active.get(slot)
      if state is None or plan.num_valid[slot] == 0:
        continue
      if slot_ok is not None and not slot_ok[slot]:
        continue  # bad step: state untouched — next plan retries exactly
      req = state.req
      if state.prefilling:
        state.prompt_pos += int(plan.num_valid[slot])
        if state.prefilling:
          # More prompt to feed; discard the sample — but the chunk
          # just committed may have COMPLETED full blocks: register
          # them now so a concurrent same-prefix admission already
          # shares them mid-prefill.
          if self.prefix_cache is not None:
            self._register_cached(state)
          continue
        if not state.first_token_emitted:
          state.first_token_emitted = True
          state.first_token_at = now
          tracer = trace_lib.get_tracer()
          if tracer.enabled:
            tracer.instant(
                "serving/first_token", cat="serving",
                track=_slot_track(slot, self.track_prefix),
                args={"uid": str(req.uid)})
          for fn in self.on_first_token:
            fn(req.uid)
        # A requeued replay commits this sample too: the last prefix
        # position's logits ARE the distribution for new token number
        # len(generated) — identical to the undisturbed decode step
        # (tok_index fold included), so the stream continues bit-exactly.
      fresh: List[int] = []
      retired = False
      for j in range(int(num_committed[slot])):
        tok = int(tokens[slot, j])
        state.generated.append(tok)
        fresh.append(tok)
        if req.stop_token >= 0 and tok == req.stop_token:
          if self.on_tokens:
            self._emit_tokens(req.uid, fresh)
          self._retire(state, "stop_token")
          retired = True
          break
        if len(state.generated) >= req.max_new_tokens:
          if self.on_tokens:
            self._emit_tokens(req.uid, fresh)
          self._retire(state, "length")
          retired = True
          break
      if not retired and fresh and self.on_tokens:
        self._emit_tokens(req.uid, fresh)
      # Decode watermark registration: committed tokens may have pushed
      # the written-K/V frontier across a block boundary — register the
      # freshly completed block(s).  A retirement above already
      # registered via _retire; `is state` guards the stale reference.
      if self.prefix_cache is not None and self.active.get(slot) is state:
        self._register_cached(state)
    return self.take_finished()
