"""Host-side request scheduling for the continuous-batching engine.

Iteration-level (continuous) batching as in Orca (OSDI'22): the
scheduler re-forms the working set EVERY engine step, so requests join
the moment a slot frees and leave the moment they finish — no
batch-formation wait, no decode steps wasted running finished requests
to a batch-wide horizon.  The device program never changes shape; all of
the variability lives here, in which tokens each slot is fed.

Responsibilities (and nothing else — device work lives in engine.py):

* FCFS admission, gated by free slots, a configurable concurrent-batch
  cap (``max_batch``) and a per-iteration prefill-token budget that
  bounds how much prompt work any single step may carry
  (Sarathi-style chunked prefill: long prompts stream through the fused
  step ``prefill_chunk`` tokens at a time, so admission never stalls
  decode latency for more than one chunk).
* Per-request decode state: prompt cursor, generated tokens, per-request
  RNG stream (a dedicated PRNGKey folded with the token index — two
  requests with the same seed reproduce the same sample stream no
  matter which slots or iterations they ride).
* Retirement: per-request ``max_new_tokens`` and optional stop-token,
  plus the hard ``max_seq_len`` capacity guard (checked at submit).
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from collections import deque
from typing import Any, Deque, Dict, List, Optional

import jax
import numpy as np

from easyparallellibrary_tpu.observability import trace as trace_lib


def _slot_track(slot: int) -> str:
  """Perfetto track name for one KV-cache slot — every request served by
  this slot renders its lifecycle span here (docs/observability.md)."""
  return f"serving/slot {slot}"


@dataclasses.dataclass
class Request:
  """One generation request.

  ``prompt`` is a 1-D int32 token array (non-empty — the model
  conditions the first new token on it, exactly like ``generate()``).
  ``temperature<=0`` is greedy; ``top_k``/``top_p`` mirror
  ``sample_logits`` semantics per slot.  ``stop_token < 0`` disables
  stop-token retirement; when hit, the stop token IS included in the
  output (the caller sees why the request ended).  ``seed`` starts the
  request's private RNG stream (defaults to a hash of ``uid``).
  ``speculative`` toggles speculative decoding per request: None
  follows the engine (a drafter is configured or not), False opts this
  request out (it then keeps the engine's non-speculative sample stream
  bit-exactly), True is a no-op on an engine without a drafter.
  """
  uid: Any
  prompt: np.ndarray
  max_new_tokens: int
  temperature: float = 0.0
  top_k: int = 0
  top_p: float = 1.0
  stop_token: int = -1
  seed: Optional[int] = None
  speculative: Optional[bool] = None


@dataclasses.dataclass
class FinishedRequest:
  uid: Any
  tokens: np.ndarray          # prompt + generated (stop token included)
  new_tokens: int
  finish_reason: str          # "length" | "stop_token"


@dataclasses.dataclass
class StepPlan:
  """Device-ready arrays for one fused engine step (all [N] or [N, C])."""
  tokens: np.ndarray          # int32 [N, C] token chunk per slot
  num_valid: np.ndarray       # int32 [N]   live tokens in the chunk
  reset: np.ndarray           # bool  [N]   zero the cursor (fresh slot)
  keys: np.ndarray            # uint32 [N, 2] per-request PRNG keys
  tok_index: np.ndarray       # int32 [N]   tokens generated so far
  temperature: np.ndarray     # f32   [N]
  top_k: np.ndarray           # int32 [N]
  top_p: np.ndarray           # f32   [N]
  draft_cap: np.ndarray       # int32 [N] max speculative drafts this step
  prefilling: np.ndarray      # bool  [N]   this step's grant is prompt work
  prefill_tokens: int         # scheduled prompt tokens this step
  decode_tokens: int          # scheduled decode tokens this step
  active_slots: int


class _SlotState:
  """Host mirror of one occupied slot."""

  __slots__ = ("req", "slot", "prompt_pos", "generated", "key",
               "admitted_at", "first_token_at")

  def __init__(self, req: Request, slot: int):
    self.req = req
    self.slot = slot
    self.prompt_pos = 0                    # prompt tokens already fed
    self.generated: List[int] = []
    if req.seed is not None:
      seed = req.seed
    else:
      # Stable across processes (Python's hash() is salted per process,
      # which would make a restarted server sample different streams
      # for the same uid).
      seed = zlib.crc32(str(req.uid).encode())
    self.key = np.asarray(jax.random.PRNGKey(seed))
    self.admitted_at = time.monotonic()
    self.first_token_at: Optional[float] = None

  @property
  def prefilling(self) -> bool:
    return self.prompt_pos < len(self.req.prompt)


class FCFSScheduler:
  """First-come-first-served continuous-batching scheduler.

  ``plan_step()`` builds the next fused-step inputs (admitting new
  requests as slots and budget allow); ``commit(next_tokens)`` folds the
  step's sampled tokens back into per-request state and returns the
  requests that retired.  The engine owns the device half of the loop.
  """

  def __init__(self, num_slots: int, prefill_chunk: int,
               max_seq_len: int, prefill_token_budget: int = 0,
               max_batch: int = 0, stop_token: int = -1,
               spec_k: int = 0):
    from easyparallellibrary_tpu.serving.kv_cache import SlotAllocator
    if prefill_chunk < 1:
      raise ValueError(f"prefill_chunk must be >= 1: {prefill_chunk}")
    if prefill_token_budget < 0 or max_batch < 0:
      raise ValueError("prefill_token_budget and max_batch must be >= 0")
    if spec_k < 0:
      raise ValueError(f"spec_k must be >= 0: {spec_k}")
    self.num_slots = num_slots
    self.chunk = prefill_chunk
    self.max_seq_len = max_seq_len
    # Max speculative drafts per decode slot per step (0 = engine has no
    # drafter); per-request Request.speculative=False opts out.
    self.spec_k = spec_k
    # 0 = uncapped: every prefilling slot gets a full chunk each step.
    self.prefill_token_budget = prefill_token_budget
    self.max_batch = max_batch if max_batch > 0 else num_slots
    self.default_stop_token = stop_token
    self.allocator = SlotAllocator(num_slots)
    self.pending: Deque[Request] = deque()
    self.active: Dict[int, _SlotState] = {}   # slot -> state
    self._admit_order: List[int] = []         # slots, admission order
    self._plan: Optional[StepPlan] = None
    self.on_admit = None                      # hooks: fn(uid)
    self.on_first_token = None                # fn(uid)
    self.on_finish = None                     # fn(FinishedRequest)

  # ---------------------------------------------------------------- queue

  def submit(self, req: Request):
    """Validate and enqueue (FCFS).  Mirrors ``generate()``'s argument
    validation so a request the engine accepts can always run."""
    prompt = np.asarray(req.prompt, np.int32).reshape(-1)
    if prompt.size == 0:
      raise ValueError("request needs a non-empty prompt (at least a BOS "
                       "token) — same contract as generate()")
    if req.max_new_tokens < 1:
      raise ValueError(f"max_new_tokens must be >= 1: {req.max_new_tokens}")
    total = prompt.size + req.max_new_tokens
    if total > self.max_seq_len:
      raise ValueError(f"prompt + new tokens ({total}) exceeds "
                       f"max_seq_len {self.max_seq_len}")
    if not 0.0 < req.top_p <= 1.0:
      raise ValueError(f"top_p must be in (0, 1]: {req.top_p}")
    if req.top_k < 0:
      raise ValueError(f"top_k must be >= 0: {req.top_k}")
    req = dataclasses.replace(req, prompt=prompt)
    if req.stop_token < 0 and self.default_stop_token >= 0:
      req = dataclasses.replace(req, stop_token=self.default_stop_token)
    self.pending.append(req)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:  # args dicts are not free; skip them when off
      tracer.instant(
          "serving/submit", cat="serving", track="serving/requests",
          args={"uid": str(req.uid), "prompt_tokens": int(prompt.size),
                "max_new_tokens": int(req.max_new_tokens)})

  @property
  def has_work(self) -> bool:
    return bool(self.pending or self.active)

  @property
  def num_active(self) -> int:
    return len(self.active)

  # ----------------------------------------------------------------- plan

  def _admit(self) -> None:
    """Admit pending requests FCFS while slots, the batch cap and the
    prefill budget allow.  The budget is charged for each admission's
    first chunk so one step never admits more prefill work than it can
    schedule — an admitted-but-starved request would hold a slot while
    contributing nothing."""
    budget_left = self.prefill_token_budget
    if budget_left > 0:
      # Already-active prefill slots have first claim on the budget.
      budget_left -= sum(
          min(self.chunk, len(s.req.prompt) - s.prompt_pos)
          for s in self.active.values() if s.prefilling)
    while (self.pending and self.allocator.num_free > 0
           and len(self.active) < self.max_batch):
      first_chunk = min(self.chunk, len(self.pending[0].prompt))
      if self.prefill_token_budget > 0 and budget_left < first_chunk:
        break
      budget_left -= first_chunk
      req = self.pending.popleft()
      slot = self.allocator.alloc()
      self.active[slot] = _SlotState(req, slot)
      self._admit_order.append(slot)
      # The request's lifecycle span opens on its slot's track and stays
      # open until _retire — every per-step prefill/decode span the
      # engine records for this slot nests inside it, so one Perfetto
      # track row reads as the request's complete timeline.
      tracer = trace_lib.get_tracer()
      if tracer.enabled:
        tracer.begin(
            f"request {req.uid}", cat="serving.request",
            track=_slot_track(slot),
            args={"uid": str(req.uid),
                  "prompt_tokens": int(len(req.prompt)),
                  "max_new_tokens": int(req.max_new_tokens)})
      if self.on_admit:
        self.on_admit(req.uid)

  def plan_step(self) -> Optional[StepPlan]:
    """Build the next fused step's inputs, or None when idle.

    Budgeting: decode slots always get their one token (decode latency
    is the metric continuous batching protects); prefill chunks are
    granted FCFS in admission order until the per-step budget runs out —
    a starved prefill slot simply carries ``num_valid=0`` this step and
    resumes next step.
    """
    self._admit()
    if not self.active:
      self._plan = None
      return None
    N, C = self.num_slots, self.chunk
    plan = StepPlan(
        tokens=np.zeros((N, C), np.int32),
        num_valid=np.zeros((N,), np.int32),
        reset=np.zeros((N,), bool),
        keys=np.zeros((N, 2), np.uint32),
        tok_index=np.zeros((N,), np.int32),
        temperature=np.zeros((N,), np.float32),
        top_k=np.zeros((N,), np.int32),
        top_p=np.ones((N,), np.float32),
        draft_cap=np.zeros((N,), np.int32),
        prefilling=np.zeros((N,), bool),
        prefill_tokens=0, decode_tokens=0,
        active_slots=len(self.active))
    budget = self.prefill_token_budget
    for slot in self._admit_order:
      state = self.active.get(slot)
      if state is None:
        continue
      req = state.req
      plan.keys[slot] = state.key
      plan.tok_index[slot] = len(state.generated)
      plan.temperature[slot] = req.temperature
      plan.top_k[slot] = req.top_k
      plan.top_p[slot] = req.top_p
      plan.reset[slot] = state.prompt_pos == 0 and not state.generated
      if state.prefilling:
        remaining = len(req.prompt) - state.prompt_pos
        grant = min(C, remaining)
        if budget > 0:
          grant = min(grant, max(budget - plan.prefill_tokens, 0))
        if grant == 0:
          continue  # budget-starved this step; resumes next step
        chunk = req.prompt[state.prompt_pos:state.prompt_pos + grant]
        plan.tokens[slot, :grant] = chunk
        plan.num_valid[slot] = grant
        plan.prefilling[slot] = True
        plan.prefill_tokens += grant
      else:
        plan.tokens[slot, 0] = state.generated[-1]
        plan.num_valid[slot] = 1
        plan.decode_tokens += 1
        if self.spec_k > 0 and req.speculative is not False:
          # Drafting past the request's remaining budget is pure waste:
          # at most (remaining - 1) drafts can commit alongside the
          # step's guaranteed token.
          remaining = req.max_new_tokens - len(state.generated)
          plan.draft_cap[slot] = max(0, min(self.spec_k, remaining - 1))
    self._plan = plan
    return plan

  def slot_histories(self, plan: StepPlan) -> Dict[int, np.ndarray]:
    """Committed tokens (prompt + generated) per draft-eligible slot of
    ``plan`` — the context drafters propose from."""
    out: Dict[int, np.ndarray] = {}
    for slot, state in self.active.items():
      if plan.draft_cap[slot] > 0:
        out[slot] = np.concatenate(
            [state.req.prompt,
             np.asarray(state.generated, np.int32)])
    return out

  # --------------------------------------------------------------- commit

  def _retire(self, state: _SlotState, reason: str) -> FinishedRequest:
    slot = state.slot
    del self.active[slot]
    self._admit_order.remove(slot)
    self.allocator.free(slot)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.end(
          f"request {state.req.uid}", cat="serving.request",
          track=_slot_track(slot),
          args={"finish_reason": reason,
                "new_tokens": int(len(state.generated))})
    fin = FinishedRequest(
        uid=state.req.uid,
        tokens=np.concatenate(
            [state.req.prompt,
             np.asarray(state.generated, np.int32)]),
        new_tokens=len(state.generated),
        finish_reason=reason)
    if self.on_finish:
      self.on_finish(fin)
    return fin

  def commit(self, next_tokens: np.ndarray,
             num_committed: Optional[np.ndarray] = None
             ) -> List[FinishedRequest]:
    """Fold one step's committed tokens back into request state; returns
    retirements.  ``next_tokens`` is ``[N]`` (one sampled token per
    slot, the non-speculative step) or ``[N, K+1]`` with
    ``num_committed [N]`` (speculative verification: accepted drafts
    plus the correction/bonus token).  A slot's tokens only count when
    its prompt is fully consumed — mid-prefill samples are positions
    whose "next token" is still dictated by the prompt.  Multi-token
    commits apply stop-token and ``max_new_tokens`` checks PER TOKEN in
    commit order, so a stop token appearing mid-draft retires the
    request and discards the rest of its accepted drafts."""
    if self._plan is None:
      raise RuntimeError("commit() without a preceding plan_step()")
    plan, self._plan = self._plan, None
    tokens = np.asarray(next_tokens)
    if tokens.ndim == 1:
      tokens = tokens[:, None]
    if num_committed is None:
      num_committed = np.ones((tokens.shape[0],), np.int32)
    finished: List[FinishedRequest] = []
    now = time.monotonic()
    for slot in list(self._admit_order):
      state = self.active.get(slot)
      if state is None or plan.num_valid[slot] == 0:
        continue
      req = state.req
      if state.prefilling:
        state.prompt_pos += int(plan.num_valid[slot])
        if state.prefilling:
          continue  # more prompt to feed; discard the sample
        state.first_token_at = now
        tracer = trace_lib.get_tracer()
        if tracer.enabled:
          tracer.instant(
              "serving/first_token", cat="serving",
              track=_slot_track(slot), args={"uid": str(req.uid)})
        if self.on_first_token:
          self.on_first_token(req.uid)
      for j in range(int(num_committed[slot])):
        tok = int(tokens[slot, j])
        state.generated.append(tok)
        if req.stop_token >= 0 and tok == req.stop_token:
          finished.append(self._retire(state, "stop_token"))
          break
        if len(state.generated) >= req.max_new_tokens:
          finished.append(self._retire(state, "length"))
          break
    return finished
