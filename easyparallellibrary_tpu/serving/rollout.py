"""Zero-downtime blue/green checkpoint rollout with an SLO-watched
canary and automatic rollback.

A fleet serving checkpoint N ("blue") moves to checkpoint N+1
("green") without dropping a request and without trusting the new
weights until they have carried real traffic:

1. **Validate** — ``begin(checkpoint_dir)`` walks the checksum chain
   (:func:`~runtime.saver.checkpoint_fingerprint`: every shard's
   sha256 plus the index's params fingerprint) and, when the serving
   params live in this process, checks the stored leaf geometry
   against them (:func:`~runtime.saver.peek_leaf_shapes`) — a wrong
   checkpoint fails in milliseconds, before any replica is spawned.
2. **Spawn green** — one new replica per live blue is built from the
   router's construction recipe pointed at the new checkpoint, OFF the
   sweep thread (the autoscaler's spawner pattern: a long-lived daemon
   thread builds, :meth:`Router.adopt_replica` lands each at a sweep
   boundary).  Capacity only ever GROWS here — the live set never dips
   below ``serving.rollout.min_replicas`` because blue keeps serving
   untouched until cutover.
3. **Canary** — admission weight shifts green-ward in stages:
   ``canary_frac`` of NEW requests first (the router's deterministic
   deficit split, :meth:`Router.set_version_weights`), watched for
   ``canary_hold_s`` through the existing
   :class:`~observability.slo.SLOMonitor` via per-version breach
   streams — the router publishes ``serving/fleet/v<N>/*`` sub-rollups
   while a rollout is active, and bare-name SLO rules suffix-match
   them with no new rule plumbing.  A canary-scoped breach (or a green
   replica death, or a green spawn failure) triggers **automatic
   rollback**: green is drained, blue admission weights are restored,
   and the fleet is bit-exactly the never-rolled fleet.  A clean hold
   cuts admission fully over to green.
4. **Drain blue** — after cutover, blue replicas drain gracefully:
   in-flight blue requests COMPLETE IN PLACE on the weights that
   started them (migration policy: prefix replay across checkpoint
   versions is not bit-exact, so every request is pinned to the
   version it was admitted under and restore/evacuate refuse
   cross-version replay — a mid-rollout SIGKILL of a blue replica
   fails over to a surviving blue, never green).  Once blue is empty
   the recipe is rewritten (later autoscale spawns and breaker
   respawns build green), ``Router._fleet_version`` advances, and the
   rollout retires.

Every transition is emitted three ways: a ``serving/rollout`` trace
instant, an :meth:`SLOMonitor.note_actuation` line in
``slo_events.jsonl``, and the ``serving/fleet/rollout_*`` counters on
the fleet rollup (published immediately, not on the heartbeat
cadence).

While a rollout is in flight the autoscaler is HELD
(:meth:`FleetAutoscaler.hold`): grow/shrink mid-canary would change
the capacity the canary's SLO evidence is judging.

Pure host policy — injectable clock (the router's), driven from
:meth:`Router.step` at sweep boundaries exactly like the autoscaler.
Knobs: ``serving.rollout.*`` (docs/robustness.md "Blue/green
rollout"); ``make chaos-rollout`` and ``make rollout-bench`` are the
acceptance harnesses.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from easyparallellibrary_tpu.env import Env
from easyparallellibrary_tpu.observability import trace as trace_lib
from easyparallellibrary_tpu.profiler.serving import fleet_summary
from easyparallellibrary_tpu.utils.logging import get_logger

PARAMS_PREFIX = "params/"


class RolloutController:
  """Blue/green rollout state machine for one Router (module
  docstring).  Built by the router when ``serving.rollout.enabled``;
  the operator calls :meth:`begin` between sweeps, and every state
  transition happens in :meth:`on_step` on the router's thread.

  States: ``idle`` → ``spawning`` → ``canary`` → ``draining_blue`` →
  ``idle`` (completed), with ``rolling_back`` → ``idle`` reachable
  from ``spawning`` (spawn failure/timeout) and ``canary``
  (canary-scoped SLO breach, green replica death).
  """

  def __init__(self, router, config=None):
    conf = (config if config is not None
            else Env.get().config).serving.rollout
    self.router = router
    self.clock = router.clock
    self.canary_frac = conf.canary_frac
    self.canary_hold_s = conf.canary_hold_s
    self.min_replicas = conf.min_replicas
    self.spawn_timeout_s = conf.spawn_timeout_s
    self.drain_timeout_s = conf.drain_timeout_s
    self._rules = set(conf.rules)
    self.state = "idle"
    self.started = 0
    self.completed = 0
    self.rollbacks = 0
    self.spawn_failures = 0
    # One rollout's working set (valid while state != idle).
    self._checkpoint: Optional[str] = None
    self._blue_version = 0
    self._green_version = 0
    self._blue: List[int] = []        # replica indices serving blue
    self._green: List[int] = []       # adopted green replica indices
    self._target_greens = 0
    self._begin_t = 0.0
    self._canary_t = 0.0
    self._green_params = None         # inproc: loaded on spawner thread
    # Off-thread green spawns — the autoscaler's spawner-thread shape
    # (serving/autoscale.py init comment: the forking thread must
    # outlive every child it spawns, or PDEATHSIG reaps the fresh
    # replica the moment the thread exits).
    self._lock = threading.Lock()
    self._spawn_thread: Optional[threading.Thread] = None
    self._spawn_queue = None
    self._outcomes: List[tuple] = []
    if router._slo is None:
      get_logger().warning(
          "serving.rollout.enabled without observability.slo.enabled: "
          "the canary has no breach signal — a bad checkpoint will "
          "cut over after canary_hold_s unchallenged")
    get_logger().info(
        "rollout controller: canary %.0f%% for %.1fs, floor %d "
        "replica(s), spawn timeout %.1fs", 100.0 * self.canary_frac,
        self.canary_hold_s, self.min_replicas, self.spawn_timeout_s)

  # ------------------------------------------------------------ operator

  @property
  def active(self) -> bool:
    return self.state != "idle"

  def begin(self, checkpoint_dir: str) -> int:
    """Start a rollout to the newest valid checkpoint under
    ``checkpoint_dir``.  Validates BEFORE any replica exists (module
    docstring step 1) and raises on a bad checkpoint — a rollout that
    cannot even validate never touches the fleet.  Returns the green
    checkpoint version.  Must be called between sweeps on the router's
    thread (same contract as every replica-list mutation)."""
    if self.state != "idle":
      raise RuntimeError(
          f"rollout already in flight (state {self.state!r}); one "
          f"checkpoint transition at a time")
    router = self.router
    if not router.spawn_recipe_available:
      raise RuntimeError(
          "rollout needs a router that built its own replicas; an "
          "injected-replica fleet carries no recipe to spawn green "
          "from")
    from easyparallellibrary_tpu.runtime.saver import (
        checkpoint_fingerprint, peek_leaf_shapes)
    # Checksum chain: index parses, shards exist, sizes + sha256 match,
    # and the recorded params fingerprint recomputes — all before a
    # single green replica is paid for.
    fingerprint, ckpt_step = checkpoint_fingerprint(checkpoint_dir)
    shapes, _ = peek_leaf_shapes(checkpoint_dir)
    params = router._replica_spec.get("params")
    if params is not None:
      self._check_geometry(shapes, params, checkpoint_dir)
    blue_live = [i for i, h in enumerate(router.health)
                 if h.state in ("healthy", "suspect")]
    if len(blue_live) < self.min_replicas:
      raise RuntimeError(
          f"rollout refused: {len(blue_live)} live replica(s) is "
          f"already below serving.rollout.min_replicas="
          f"{self.min_replicas}")
    self._checkpoint = checkpoint_dir
    self._blue_version = router._fleet_version
    self._green_version = self._blue_version + 1
    self._blue = blue_live
    self._green = []
    self._green_params = None
    self._target_greens = max(len(blue_live), self.min_replicas)
    self._begin_t = self.clock()
    self.started += 1
    self.state = "spawning"
    if router._autoscaler is not None:
      # The replica set belongs to this rollout until it retires —
      # autoscale grow/shrink mid-canary would change the capacity the
      # canary's SLO evidence is judging.
      router._autoscaler.hold("rollout in flight")
    self._emit("begin", checkpoint=checkpoint_dir,
               checkpoint_step=int(ckpt_step),
               fingerprint=fingerprint[:16],
               greens_to_spawn=self._target_greens)
    self._start_spawns()
    return self._green_version

  def _check_geometry(self, shapes: Dict[str, tuple], params,
                      checkpoint_dir: str) -> None:
    """Stored leaf geometry vs the serving params tree: every live leaf
    must exist in the checkpoint with a restorable shape (equal, or
    larger-and-sliceable — saver._slice_to_shape's contract covers
    padded saves).  Mirrors what restore_params would discover
    mid-load, but fails here in milliseconds with the leaf named."""
    from easyparallellibrary_tpu.runtime import saver as saver_lib
    prefixed = any(p.startswith(PARAMS_PREFIX) for p in shapes)
    stored = {(p[len(PARAMS_PREFIX):] if prefixed else p): tuple(s)
              for p, s in shapes.items()
              if not prefixed or p.startswith(PARAMS_PREFIX)}
    for path, leaf in saver_lib._boxed_paths_and_leaves(params):
      want = stored.get(path)
      if want is None:
        raise ValueError(
            f"rollout validation failed: serving leaf {path!r} is "
            f"missing from checkpoint {checkpoint_dir!r} — wrong "
            f"model?")
      value = leaf.unbox() if saver_lib._is_box(leaf) else leaf
      got = tuple(value.shape)
      logical = saver_lib._logical_shape(leaf)
      restorable = (want == got or (logical is not None
                                    and want == tuple(logical)))
      if not restorable and len(want) == len(got):
        # A larger stored leaf slices down at load (padded save).
        restorable = all(w >= g for w, g in zip(want, got))
      if not restorable:
        raise ValueError(
            f"rollout validation failed: leaf {path!r} is "
            f"{want} in checkpoint {checkpoint_dir!r} but the "
            f"serving config expects {got} — geometry mismatch")

  # -------------------------------------------------------- green spawns

  def _start_spawns(self) -> None:
    import queue
    with self._lock:
      if self._spawn_thread is None or not self._spawn_thread.is_alive():
        self._spawn_queue = queue.Queue()
        self._spawn_thread = threading.Thread(
            target=self._spawner_loop, name="epl-rollout-spawner",
            daemon=True)
        self._spawn_thread.start()
    for _ in range(self._target_greens):
      self._spawn_queue.put(self._green_version)
    get_logger().info(
        "rollout: spawning %d green replica(s) off-thread (version "
        "%d); blue keeps serving", self._target_greens,
        self._green_version)

  def _spawner_loop(self) -> None:
    while True:
      version = self._spawn_queue.get()
      try:
        rep, err = self._build_green(version), None
      except Exception as e:  # noqa: BLE001 — posted, booked on_step
        rep, err = None, e
      with self._lock:
        self._outcomes.append((rep, err))

  def _build_green(self, version: int):
    """Build ONE green replica (spawner thread; recipe reads only).  A
    process replica's child restores the checkpoint itself
    (transport's ``checkpoint`` init key); an in-process replica gets
    the green params loaded HERE, once, against the recipe's params as
    the target tree — a failed load is a spawn failure, which rolls
    the rollout back."""
    router = self.router
    if router.transport == "process":
      return router.build_replica(checkpoint=self._checkpoint,
                                  checkpoint_version=version)
    if self._green_params is None:
      from easyparallellibrary_tpu.runtime.saver import restore_params
      self._green_params, _ = restore_params(
          self._checkpoint, target=router._replica_spec["params"])
    return router.build_replica(checkpoint_version=version,
                                params=self._green_params)

  # --------------------------------------------------------------- sweep

  def on_step(self, now: Optional[float] = None) -> None:
    """One fleet-sweep boundary: land finished green spawns, then move
    the state machine (module docstring)."""
    if self.state == "idle":
      return
    now = self.clock() if now is None else now
    router = self.router
    with self._lock:
      outcomes, self._outcomes = self._outcomes, []
    for rep, err in outcomes:
      if err is not None:
        self.spawn_failures += 1
        get_logger().error(
            "rollout: green replica spawn failed (%s: %s)",
            type(err).__name__, err)
        self._emit("spawn_failed", error=type(err).__name__)
        if self.state in ("spawning", "canary"):
          self._rollback(f"green spawn failed ({type(err).__name__})",
                         now)
        continue
      if self.state not in ("spawning", "canary"):
        # A spawn landing after rollback began: the replica is not
        # wanted — close it instead of adopting a stray green.
        try:
          rep.close()
        except Exception:  # noqa: BLE001 — best-effort teardown
          pass
        continue
      index = router.adopt_replica(rep)
      self._green.append(index)
      self._emit("green_up", replica=index,
                 greens=len(self._green), target=self._target_greens)
    if self.state == "spawning":
      if len(self._green) >= self._target_greens:
        self._start_canary(now)
      elif now - self._begin_t > self.spawn_timeout_s:
        self.spawn_failures += 1
        self._rollback(
            f"green spawn timed out after {self.spawn_timeout_s:.1f}s "
            f"({len(self._green)}/{self._target_greens} up)", now)
    elif self.state == "canary":
      breach = self._canary_breach()
      dead = [i for i in self._green
              if router.health[i].state == "down"]
      if breach is not None:
        self._rollback(f"canary SLO breach: {breach[0]}@{breach[1]}",
                       now)
      elif dead:
        self._rollback(f"green replica {dead[0]} died during canary",
                       now)
      elif now - self._canary_t >= self.canary_hold_s:
        self._cutover(now)
    elif self.state == "draining_blue":
      if not self._holding_work(self._blue):
        self._complete(now)
    elif self.state == "rolling_back":
      if not self._holding_work(self._green):
        self._finish_rollback(now)

  def _holding_work(self, indices: List[int]) -> bool:
    router = self.router
    return any(router.replicas[i].has_work for i in indices
               if router.health[i].state != "down")

  def _canary_breach(self) -> Optional[tuple]:
    """First live breach on the green version's scoped streams
    (``serving/fleet/v<green>/*``), filtered to
    ``serving.rollout.rules`` when set; None when clean."""
    monitor = self.router._slo
    if monitor is None:
      return None
    scope = f"serving/fleet/v{self._green_version}"
    for rule, key in monitor.breached_streams(scope=scope):
      if not self._rules or rule in self._rules:
        return rule, key
    return None

  # --------------------------------------------------------- transitions

  def _start_canary(self, now: float) -> None:
    self.state = "canary"
    self._canary_t = now
    self.router.set_version_weights({
        self._blue_version: 1.0 - self.canary_frac,
        self._green_version: self.canary_frac})
    self._emit("canary_start", canary_frac=self.canary_frac,
               hold_s=self.canary_hold_s, greens=len(self._green))

  def _cutover(self, now: float) -> None:
    router = self.router
    self.state = "draining_blue"
    router.set_version_weights({self._green_version: 1.0})
    # Graceful blue drain: every in-flight blue request completes IN
    # PLACE on the weights that started it (complete-in-place
    # migration policy); the version pin on each request enforces it
    # even through a blue death — failover targets are blue-only.
    for index in self._blue:
      if router.health[index].state in ("healthy", "suspect"):
        router.drain(index, timeout_s=self.drain_timeout_s)
    self._emit("cutover", drained_blues=len(self._blue))

  def _complete(self, now: float) -> None:
    router = self.router
    # The recipe now builds GREEN: later autoscale spawns and breaker
    # respawns serve the new checkpoint with no override.
    spec = router._replica_spec
    spec["engine_kwargs"]["checkpoint_version"] = self._green_version
    if router.transport == "process":
      spec["checkpoint"] = self._checkpoint
    elif self._green_params is not None:
      spec["params"] = self._green_params
    router._fleet_version = self._green_version
    router.set_version_weights(None)
    self.completed += 1
    self.state = "idle"
    if router._autoscaler is not None:
      router._autoscaler.release()
    self._emit("completed", version=self._green_version,
               duration_s=now - self._begin_t)

  def _rollback(self, reason: str, now: float) -> None:
    """Automatic rollback: blue admission weights restore NOW (green
    stops receiving new requests this very sweep), green drains
    gracefully — its in-flight canary requests complete in place —
    and the fleet is bit-exactly the never-rolled fleet."""
    router = self.router
    get_logger().error("rollout ROLLBACK: %s", reason)
    self.rollbacks += 1
    self.state = "rolling_back"
    # Version-blind dispatch over blue: greens are drained (unroutable)
    # below, so restoring weights to None IS restoring blue's 100%.
    router.set_version_weights(None)
    for index in self._green:
      if router.health[index].state in ("healthy", "suspect"):
        router.drain(index, timeout_s=self.drain_timeout_s)
    self._emit("rollback_start", reason=reason,
               greens_draining=len(self._green))

  def _finish_rollback(self, now: float) -> None:
    router = self.router
    self.state = "idle"
    self._green_params = None
    if router._autoscaler is not None:
      router._autoscaler.release()
    self._emit("rollback_done", blue_version=self._blue_version,
               duration_s=now - self._begin_t)

  # ------------------------------------------------------- observability

  def version_rollups(self) -> Dict[int, Dict[str, float]]:
    """Per-checkpoint-version fleet sub-rollups, for the router to
    publish under ``serving/fleet/v<N>/*`` while a rollout is active —
    the canary's evidence streams (module docstring step 3)."""
    router = self.router
    by_ver: Dict[int, list] = {}
    for i, rep in enumerate(router.replicas):
      if router.health[i].state == "down":
        continue
      by_ver.setdefault(router._replica_version(i), []).append(rep)
    out: Dict[int, Dict[str, float]] = {}
    for ver, reps in by_ver.items():
      stats = [s for s in (r.stats for r in reps) if s is not None]
      if stats:
        out[ver] = fleet_summary(stats)
    return out

  def counters(self) -> Dict[str, float]:
    """Fleet-rollup counters (merged into Router.router_counters —
    the ``serving/fleet/rollout_*`` schema)."""
    return {"rollout_started": float(self.started),
            "rollout_completed": float(self.completed),
            "rollout_rollbacks": float(self.rollbacks),
            "rollout_spawn_failures": float(self.spawn_failures),
            "rollout_active": 1.0 if self.active else 0.0}

  def _emit(self, event: str, **args: Any) -> None:
    """Three-way emission per transition (module docstring): trace
    instant, slo_events line, immediate counter rollup."""
    router = self.router
    payload = {"actuator": "rollout", "transition": event,
               "state": self.state,
               "blue_version": int(self._blue_version),
               "green_version": int(self._green_version)}
    payload.update(args)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant("serving/rollout", cat="serving", track="serving",
                     args=dict(payload))
    if router._slo is not None:
      router._slo.note_actuation("rollout", payload, step=router.steps)
    # Immediate rollup: the transition's counter evidence lands at the
    # transition, not up to a heartbeat later.
    router._note_incident()
    get_logger().info("rollout: %s (state %s, blue v%d, green v%d)",
                      event, self.state, self._blue_version,
                      self._green_version)
