"""Readiness-driven router core: per-replica dispatch the moment each
previous reply lands, instead of the lock-step sweep.

``Router.step()`` is a barrier: phase 1 dispatches a step to every live
replica, phase 2 collects in replica order — so the fleet's cadence is
its slowest member's.  One stalled child (SIGSTOP, a long compile, a
slow host) gates every fast replica behind the sweep barrier even
though the process transport's wire is fully pipelined.  This module
removes the barrier without touching the control plane: a
``selectors``-based event loop over the SAME four pieces the sweep is
built from (``Router._sweep_begin`` / ``_dispatch_one`` /
``_collect_one`` / ``_sweep_end``), re-dispatching each replica the
moment its reply lands.

* **Process replicas** wait on the transport socket
  (:meth:`ReplicaTransport.readiness_fd`): readable = the step reply
  (or a side-band frame) arrived.  A reply that an interleaved RPC
  already drained off the socket (``submit``/``cancel`` mid-cycle
  stashes it) never polls readable — :meth:`step_ready` catches those.
* **In-process replicas** compute synchronously inside ``step_recv``,
  so readiness is a queue-backed shim: dispatch appends the replica to
  a ready deque and collect runs its step — the execution order within
  a cycle stays deterministic (FIFO), which is what keeps the inproc
  N=1 reactor bit-exact with the sweep (tests/test_serving_frontdoor).

One **cycle** = one ``_sweep_begin`` (rollout -> autoscaler -> drains
-> parked flush: the only point the replica list may mutate — the
sweep's exact mutation-safety contract), then readiness-driven
dispatch/collect until every replica has either exhausted its
``serving.router.reactor_max_steps`` quota or run out of work, then
one ``_sweep_end`` (reap + rollup).  A fast replica thus runs up to
``reactor_max_steps`` steps per cycle while a slow peer finishes one —
the fleet's throughput decouples from its slowest member while health,
failover, journal recovery, autoscale and rollout all run UNMODIFIED
(they are the same router methods the sweep calls).

A straggler that never becomes readable is force-collected once its
wire deadline (``serving.router.rpc_timeout_s``) elapses —
``step_recv``'s own deadline/condemn/fence machinery then runs exactly
as it does under the sweep.

``Router.step()`` remains the sweep (the simulator's fixed-dt episode
loop and the golden replay depend on its determinism);
``serving.router.reactor = True`` routes ``Router.run()`` and the
front door's driver (serving/frontdoor/) through this loop.  See
docs/serving.md "Front door".
"""

from __future__ import annotations

import selectors
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from easyparallellibrary_tpu.serving.scheduler import FinishedRequest
from easyparallellibrary_tpu.utils.logging import get_logger


class RouterReactor:
  """Readiness-driven driver over one :class:`~serving.router.Router`
  (module docstring).  Build via ``router.reactor()`` (cached) or
  directly; ``cycle()`` is the reactor's unit of progress — the
  readiness-first analogue of one ``router.step()`` sweep."""

  def __init__(self, router, *, config=None,
               max_steps_per_cycle: Optional[int] = None):
    root = config if config is not None else router._root_config
    rconf = root.serving.router
    self.router = router
    self.max_steps = int(max_steps_per_cycle
                         if max_steps_per_cycle is not None
                         else rconf.reactor_max_steps)
    if self.max_steps < 1:
      raise ValueError(
          f"reactor max_steps_per_cycle must be >= 1: {self.max_steps}")
    self._rpc_timeout_s = float(rconf.rpc_timeout_s)
    self._sel = selectors.DefaultSelector()
    self.cycles = 0
    self.dispatched = 0   # per-replica steps driven (all cycles)
    self.wire_waits = 0   # selector waits that actually blocked

  # ------------------------------------------------------------- cycle

  def cycle(self) -> List[FinishedRequest]:
    """One reactor cycle (module docstring): control-plane actions at
    the boundary, then dispatch/collect each live replica readiness-
    first up to ``max_steps`` steps each.  Returns the cycle's
    retirements fleet-wide — the same contract as ``router.step()``."""
    r = self.router
    r._sweep_begin(r.clock())
    out: List[FinishedRequest] = []
    steps_done: Dict[int, int] = {}
    ready: Deque[int] = deque()          # inproc readiness shim
    inflight: Dict[int, float] = {}      # index -> wire deadline
    registered: Dict[int, Any] = {}      # index -> selector key

    def dispatch(i: int) -> None:
      if not r._dispatch_one(i, r.clock()):
        return
      steps_done[i] = steps_done.get(i, 0) + 1
      self.dispatched += 1
      rep = r.replicas[i]
      getfd = getattr(rep, "readiness_fd", None)
      fd = getfd() if getfd is not None else None
      if fd is None:
        ready.append(i)
      else:
        inflight[i] = time.monotonic() + self._rpc_timeout_s
        try:
          registered[i] = self._sel.register(fd, selectors.EVENT_READ, i)
        except (ValueError, KeyError, OSError):
          # fd unusable (condemned between dispatch and register):
          # fall back to a direct collect, whose own deadline handles
          # the corpse.
          inflight.pop(i, None)
          ready.append(i)

    def collect(i: int) -> None:
      fins = r._collect_one(i, r.clock())
      if fins is None:
        return                     # died collecting; failover already ran
      out.extend(fins)
      rep = r.replicas[i]
      if (steps_done.get(i, 0) < self.max_steps
          and r.health[i].state not in ("down",)
          and getattr(rep, "has_work", False)):
        dispatch(i)

    def unregister(i: int) -> None:
      key = registered.pop(i, None)
      inflight.pop(i, None)
      if key is not None:
        try:
          self._sel.unregister(key.fileobj)
        except (KeyError, ValueError, OSError):
          pass

    for i in range(len(r.replicas)):
      dispatch(i)
    while ready or inflight:
      while ready:
        collect(ready.popleft())
      if not inflight:
        break
      # Replies an interleaved RPC already stashed never poll readable.
      stashed = [i for i in list(inflight)
                 if getattr(r.replicas[i], "step_ready", lambda: True)()]
      for i in stashed:
        unregister(i)
        collect(i)
      if stashed or ready or not inflight:
        continue
      now_w = time.monotonic()
      timeout = max(0.0, min(inflight.values()) - now_w)
      events = self._sel.select(timeout=timeout)
      self.wire_waits += 1
      if events:
        for key, _ in events:
          i = key.data
          if i in inflight:
            unregister(i)
            collect(i)
      else:
        # Deadline stragglers: force the collect — step_recv's own
        # wire deadline condemns/fences exactly as under the sweep.
        overdue = [i for i, dl in inflight.items()
                   if time.monotonic() >= dl]
        for i in overdue:
          unregister(i)
          collect(i)
    r._sweep_end(r.clock())
    self.cycles += 1
    return out

  # --------------------------------------------------------------- run

  def run(self, max_cycles: Optional[int] = None
          ) -> Dict[Any, Any]:
    """Drive cycles until the fleet drains (or ``max_cycles``); returns
    ``{uid: prompt+generated}`` — the same contract as
    ``Router.run()``, which delegates here when
    ``serving.router.reactor`` is on."""
    r = self.router
    out: Dict[Any, Any] = {}
    cycles = 0
    while r.has_work and (max_cycles is None or cycles < max_cycles):
      for fin in self.cycle():
        out[fin.uid] = fin.tokens
      cycles += 1
      if r._parked_stalled():
        get_logger().warning(
            "reactor.run(): %d request(s) parked with no routable "
            "replica (states %s); returning — rejoin a replica to "
            "resume", len(r._parked), r.states())
        break
    if r.registry is not None or r._slo is not None:
      r._publish_rollup()
    return out

  def close(self) -> None:
    try:
      self._sel.close()
    except OSError:
      pass
