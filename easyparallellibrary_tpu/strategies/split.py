"""`split` — the tensor/expert-parallel primitive.

Analog of the reference's ``Split``/``split()``
(epl/strategies/split.py:24,49): layers applied inside a ``split`` scope
shard their weights (and, for MoE, their experts) across ``device_count``
devices — the mesh's ``model`` axis here.  The reference swaps op
implementations via hooks (epl/parallel/hooks.py:710-828); in this
framework the distributed layers in :mod:`easyparallellibrary_tpu.ops`
consult the ambient scope at trace time and apply GSPMD shardings +
collectives themselves — no monkey-patching.

``is_nested`` parity (epl/strategies/split.py:36-46): a split scope opened
while another split is active marks itself nested and does not re-shard.
"""

from __future__ import annotations

from typing import Optional

from easyparallellibrary_tpu.strategies.base import ParallelStrategy


class Split(ParallelStrategy):
  kind = "split"

  def __init__(self, device_count: Optional[int] = None, name: str = ""):
    super().__init__(device_count=device_count, name=name)
    self.is_nested = False


def split(device_count: Optional[int] = None, name: str = "") -> Split:
  """Open a tensor-parallel scope over `device_count` devices
  (None = whole model axis)."""
  return Split(device_count=device_count, name=name)
