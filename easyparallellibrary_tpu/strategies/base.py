"""Strategy scope base class.

Analog of the reference's ``ParallelStrategy`` context-manager
(epl/strategies/parallel_strategy.py:28): entering pushes the strategy onto
the process-global :class:`StrategyContext`; the *defining call site* is the
scope's identity so that re-entering the same ``with`` statement (e.g. a
layer loop calling the model twice, or a module applied once per microbatch
under trace) reuses the same taskgraph rather than minting a new stage
(reference ``_get_stack`` :48-57).
"""

from __future__ import annotations

import traceback
from typing import Optional

from easyparallellibrary_tpu.env import Env


class ParallelStrategy:
  """Context manager recording a parallelism annotation."""

  # Subclasses set this ("replicate" / "split").
  kind = "base"

  def __init__(self, device_count: Optional[int] = None, name: str = ""):
    if device_count is not None and device_count < 1:
      raise ValueError(f"device_count must be >= 1, got {device_count}")
    self.device_count = device_count
    self.name = name
    self.identity = self._call_site_identity()
    # Assigned by StrategyContext when first entered.
    self.index: Optional[int] = None
    self.taskgraph = None

  @staticmethod
  def _call_site_identity() -> str:
    """Identity = the user frames of the defining call stack.

    Mirrors the reference's stack-hash identity
    (epl/strategies/parallel_strategy.py:48-57): frames inside this package
    are skipped so the identity is stable for a given user call site.
    """
    frames = []
    for frame in traceback.extract_stack():
      if "easyparallellibrary_tpu" in (frame.filename or ""):
        continue
      frames.append(f"{frame.filename}:{frame.lineno}")
    return "|".join(frames[-8:])

  def __enter__(self):
    # add_context returns the canonical strategy for this call site, which
    # may be an earlier instance when the scope is re-entered — the `as`
    # binding must see the one that owns the taskgraph.
    return Env.get().strategy_context.add_context(self)

  def __exit__(self, exc_type, exc_value, tb):
    Env.get().strategy_context.remove_context(self)
    return False

  def __repr__(self):
    return (f"{type(self).__name__}(device_count={self.device_count}, "
            f"name={self.name!r}, index={self.index})")
