"""Strategy scope base class.

Analog of the reference's ``ParallelStrategy`` context-manager
(epl/strategies/parallel_strategy.py:28): entering pushes the strategy onto
the process-global :class:`StrategyContext`; the *defining call site* is the
scope's identity so that re-entering the same ``with`` statement (e.g. a
layer loop calling the model twice, or a module applied once per microbatch
under trace) reuses the same taskgraph rather than minting a new stage
(reference ``_get_stack`` :48-57).
"""

from __future__ import annotations

import traceback
from typing import Optional

from easyparallellibrary_tpu.env import Env


class ParallelStrategy:
  """Context manager recording a parallelism annotation."""

  # Subclasses set this ("replicate" / "split").
  kind = "base"

  def __init__(self, device_count: Optional[int] = None, name: str = ""):
    if device_count is not None and device_count < 1:
      raise ValueError(f"device_count must be >= 1, got {device_count}")
    self.device_count = device_count
    self.name = name
    self.identity = self._call_site_identity()
    # Assigned by StrategyContext when first entered.
    self.index: Optional[int] = None
    self.taskgraph = None

  def _call_site_identity(self) -> str:
    """Identity = the source location of the defining `with` statement.

    Plays the role of the reference's call-stack-hash identity
    (epl/strategies/parallel_strategy.py:48-57) with one deliberate
    difference: only the innermost *user* frame is used, not the whole
    stack.  JAX traces the model function several times from different
    outer call paths (eval_shape for shapes, jit for init, jit for the
    train step), so a full-stack identity would mint a fresh pipeline
    stage per trace; the `with` line itself is stable across traces while
    still distinguishing sibling scopes and collapsing loop re-entries.
    """
    # Framework internals are skipped; easyparallellibrary_tpu/models is
    # deliberately NOT skipped — bundled models open scopes and those
    # `with` lines are their identity.
    skip_markers = ("easyparallellibrary_tpu/strategies",
                    "easyparallellibrary_tpu/parallel",
                    "easyparallellibrary_tpu/ir",
                    "easyparallellibrary_tpu/ops",
                    "easyparallellibrary_tpu/runtime",
                    "easyparallellibrary_tpu/__init__",
                    "easyparallellibrary_tpu/env",
                    "site-packages", "dist-packages", "<frozen",
                    "importlib", "/lib/python")
    last_user = None
    for frame in traceback.extract_stack():
      fname = frame.filename or ""
      if any(m in fname for m in skip_markers):
        continue
      last_user = f"{fname}:{frame.lineno}"
    site = last_user or "unknown"
    return f"{site}|{self.kind}|{self.name}|{self.device_count}"

  def __enter__(self):
    # add_context returns the canonical strategy for this call site, which
    # may be an earlier instance when the scope is re-entered — the `as`
    # binding must see the one that owns the taskgraph.
    return Env.get().strategy_context.add_context(self)

  def __exit__(self, exc_type, exc_value, tb):
    Env.get().strategy_context.remove_context(self)
    return False

  def __repr__(self):
    return (f"{type(self).__name__}(device_count={self.device_count}, "
            f"name={self.name!r}, index={self.index})")
