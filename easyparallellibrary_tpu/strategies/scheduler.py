"""Pipeline schedule policies.

The reference encodes schedules as control-dependency edges between
per-(stage, micro-batch) entrance/exit op sets
(epl/strategies/scheduler.py:36-116): ``PreferForward`` is GPipe-like,
``PreferBackward`` is 1F1B-like (bounds live activations), and
``PreferBackwardOptimizer`` additionally interleaves the optimizer apply.

In the SPMD pipeline (parallel/pipeline.py) the *order* of work is fixed
by dataflow — XLA schedules it — so the policies map onto what they
actually bought on GPUs: peak-memory behavior.

  * PreferForward          — keep all micro-batch activations (fastest,
                             GPipe memory profile).
  * PreferBackward         — rematerialize each stage's forward during the
                             backward pass, so live activations stay ~one
                             micro-batch per stage (1F1B memory profile).
  * PreferBackwardOptimizer— PreferBackward + grouped optimizer apply
                             (see runtime/optimizer_helper.py).
"""

from __future__ import annotations

import dataclasses

from easyparallellibrary_tpu import constants


@dataclasses.dataclass(frozen=True)
class Schedule:
  name: str
  remat_stage: bool
  grouped_apply: bool


_SCHEDULES = {
    constants.SCHEDULE_PREFER_FORWARD: Schedule(
        constants.SCHEDULE_PREFER_FORWARD, remat_stage=False,
        grouped_apply=False),
    constants.SCHEDULE_PREFER_BACKWARD: Schedule(
        constants.SCHEDULE_PREFER_BACKWARD, remat_stage=True,
        grouped_apply=False),
    constants.SCHEDULE_PREFER_BACKWARD_OPT: Schedule(
        constants.SCHEDULE_PREFER_BACKWARD_OPT, remat_stage=True,
        grouped_apply=True),
}


def get_scheduler(name: str) -> Schedule:
  """Reference: get_scheduler registry (epl/strategies/scheduler.py:126)."""
  if name not in _SCHEDULES:
    raise ValueError(f"Unknown pipeline schedule {name!r}; "
                     f"one of {sorted(_SCHEDULES)}")
  return _SCHEDULES[name]
