"""Pipeline schedule policies.

The reference encodes schedules as control-dependency edges between
per-(stage, micro-batch) entrance/exit op sets
(epl/strategies/scheduler.py:36-116): ``PreferForward`` is GPipe-like,
``PreferBackward`` is 1F1B-like (bounds live activations), and
``PreferBackwardOptimizer`` additionally interleaves the optimizer apply.

Here the policies select between two genuinely different programs:

  * PreferForward          — GPipe: autodiff through the SPMD pipeline
                             (parallel/pipeline.py); all micro-batch
                             activations live at the fwd/bwd boundary.
  * PreferBackward         — TRUE 1F1B: the manual
                             fwd/bwd-wavefront scan in
                             parallel/schedule_1f1b.py, whose residual
                             ring structurally bounds live stage inputs to
                             min(M, 2S-1) per stage instead of M, with
                             per-stage recompute (matching the reference's
                             free-and-recompute behavior).  Dispatched by
                             models.gpt.make_gpt_train_step.
  * PreferBackwardOptimizer— PreferBackward + grouped optimizer apply
                             (see runtime/optimizer_helper.py).

``remat_stage`` is also consulted by forward-only Pipeline module uses
(eval), where it toggles per-stage checkpointing.

Megatron-style interleaved (virtual-stage) 1F1B: impossible on the
LOCKSTEP engines (a masked chunk costs the same as a live one, so K-way
chunk interleaving has ramp 2(S - 1/K) device-ticks — never better than
plain 1F1B's 2(S-1); requesting it with 1F1B on the vmapped engines
falls back with a warning, and interleave stays the reference's
circular weight placement there).  The per-rank formulation CAN express
it: ``pipeline.engine="smap"`` with ``pipeline_interleave=K > 1``
dispatches the table-driven interleaved engine
(parallel/pipeline_interleaved.py) whose real branches shrink the ramp
to 2(S-1) + (K-1)S one-chunk ticks — see BASELINE.md round 4.
"""

from __future__ import annotations

import dataclasses

from easyparallellibrary_tpu import constants


@dataclasses.dataclass(frozen=True)
class Schedule:
  name: str
  remat_stage: bool
  grouped_apply: bool


_SCHEDULES = {
    constants.SCHEDULE_PREFER_FORWARD: Schedule(
        constants.SCHEDULE_PREFER_FORWARD, remat_stage=False,
        grouped_apply=False),
    constants.SCHEDULE_PREFER_BACKWARD: Schedule(
        constants.SCHEDULE_PREFER_BACKWARD, remat_stage=True,
        grouped_apply=False),
    constants.SCHEDULE_PREFER_BACKWARD_OPT: Schedule(
        constants.SCHEDULE_PREFER_BACKWARD_OPT, remat_stage=True,
        grouped_apply=True),
}


def get_scheduler(name: str) -> Schedule:
  """Reference: get_scheduler registry (epl/strategies/scheduler.py:126)."""
  if name not in _SCHEDULES:
    raise ValueError(f"Unknown pipeline schedule {name!r}; "
                     f"one of {sorted(_SCHEDULES)}")
  return _SCHEDULES[name]
