"""Process-global stack of active strategy scopes.

Analog of the reference's ``StrategyContext``
(epl/strategies/strategy_context.py:26): tracks the stack of entered
scopes, enforces the nesting rules (:34-54), assigns strategy indices
(:81-88), creates one :class:`Taskgraph` per distinct scope call site, and
manages the default strategy (:137-152).
"""

from __future__ import annotations

from typing import List, Optional

from easyparallellibrary_tpu.ir.taskgraph import Taskgraph


class StrategyContext:
  def __init__(self):
    self.stack: List = []           # currently-entered scopes
    self.taskgraphs: List[Taskgraph] = []
    self.default_strategy = None
    self._identity_map = {}         # call-site identity -> strategy

  # -- scope entry/exit ----------------------------------------------------

  def add_context(self, strategy):
    self._check_nesting(strategy)
    if getattr(strategy, "is_nested", False):
      # Nested splits do not open a new taskgraph (reference: nested split
      # does not re-apply op replacement, epl/strategies/split.py:36-46).
      self.stack.append(strategy)
      return strategy
    canonical = self._canonicalize(strategy)
    self.stack.append(canonical)
    return canonical

  def remove_context(self, strategy):
    if not self.stack:
      raise RuntimeError("Strategy scope exited but context stack is empty")
    top = self.stack.pop()
    if top.identity != strategy.identity:
      raise RuntimeError(
          f"Strategy scopes exited out of order: popped {top}, "
          f"expected {strategy}")

  def _check_nesting(self, strategy):
    """Nesting rules (reference epl/strategies/strategy_context.py:34-54)."""
    if not self.stack:
      return
    outer = self.stack[-1]
    if outer.kind == "split":
      if strategy.kind == "split":
        # A re-entrant split is tolerated and marked nested so it does not
        # re-shard (reference epl/strategies/split.py:36-46).
        strategy.is_nested = True
        return
      raise ValueError("Nesting any strategy scope inside a 'split' scope "
                       "is not allowed")
    if outer.kind == strategy.kind:
      raise ValueError(
          f"Nesting a '{strategy.kind}' scope inside another "
          f"'{outer.kind}' scope is not allowed")
    if outer.kind == "replicate" and strategy.kind == "split":
      raise ValueError(
          "Nesting 'split' inside 'replicate' is not allowed; make them "
          "sibling scopes and set config cluster.colocate_split_and_replicate")

  def _canonicalize(self, strategy):
    """Reuse the strategy (and its taskgraph) for a repeated call site.

    Re-entering the same ``with`` statement — a loop over layers, or the
    model function traced again — must not mint a new pipeline stage
    (reference identity hash, epl/strategies/parallel_strategy.py:48-57).
    """
    existing = self._identity_map.get(strategy.identity)
    if existing is not None:
      return existing
    strategy.index = len(self.taskgraphs)
    tg = Taskgraph(index=strategy.index, strategy=strategy)
    strategy.taskgraph = tg
    self.taskgraphs.append(tg)
    self._identity_map[strategy.identity] = strategy
    return strategy

  # -- queries -------------------------------------------------------------

  @property
  def current(self):
    """Innermost active scope, or the default strategy."""
    if self.stack:
      return self.stack[-1]
    return self.default_strategy

  @property
  def identity(self) -> str:
    return "|".join(s.identity for s in self.stack)

  def set_default(self, strategy):
    """Reference: epl.set_default_strategy (epl/__init__.py:53-55)."""
    self.default_strategy = self._canonicalize(strategy) \
        if strategy is not None else None

  def reset(self):
    self.stack = []
    self.taskgraphs = []
    self.default_strategy = None
    self._identity_map = {}
