"""`replicate` — the data-parallel / pipeline-stage primitive.

Analog of the reference's ``Replicate``/``replicate()``
(epl/strategies/replicate.py:24,39): code (model construction or
application) inside a ``replicate`` scope is data-parallel over the mesh's
``data`` axis; *consecutive distinct* ``replicate`` scopes become pipeline
stages (taskgraphs), exactly as in the reference where each new scope call
site starts a new taskgraph.

On TPU, "replication" means: batch sharded on the data axis, params
replicated across it (unless ZeRO shards optimizer state), gradient
all-reduce inserted automatically by GSPMD.
"""

from __future__ import annotations

from typing import Optional

from easyparallellibrary_tpu.strategies.base import ParallelStrategy


class Replicate(ParallelStrategy):
  kind = "replicate"

  def __init__(self, device_count: Optional[int] = None, name: str = ""):
    super().__init__(device_count=1 if device_count is None else device_count,
                     name=name)


def replicate(device_count: Optional[int] = None, name: str = "") -> Replicate:
  """Open a data-parallel scope.

  ``device_count`` is the number of devices each model replica of this
  stage spans (reference semantics); with pipeline, the per-stage device
  count feeds the auto layout (replicas = total / Σ stage device_count,
  epl/cluster.py:150-159).
  """
  return Replicate(device_count=device_count, name=name)
