from easyparallellibrary_tpu.strategies.base import ParallelStrategy
from easyparallellibrary_tpu.strategies.context import StrategyContext
from easyparallellibrary_tpu.strategies.replicate import Replicate, replicate
from easyparallellibrary_tpu.strategies.split import Split, split

__all__ = [
    "ParallelStrategy", "StrategyContext", "Replicate", "replicate",
    "Split", "split",
]
