"""Namespaced metric registry — one schema over every metric producer.

Before this module, each producer shipped its own ad-hoc dict to
whatever sink it happened to hold: ``fit()`` wrote raw step metrics,
the serving engine wrote its own per-step record, ``StepProfiler`` /
``FlopsProfiler`` logged summaries, and resilience counters rode along
as bare keys.  Nothing downstream could tell ``loss`` from
``step_time_s`` from ``tokens_per_s`` without knowing who wrote the
line.

The registry fixes the schema, not the sinks: every metric is published
under one of four namespaces and lands in the existing
``MetricsWriter`` / ``TensorBoardWriter`` (or anything with the same
``write(step, metrics)`` / ``flush()`` / ``close()`` surface) as
``<namespace>/<name>`` keys:

====================  ====================================================
namespace             producers
====================  ====================================================
``train/*``           fit() step metrics, StepProfiler / FlopsProfiler
                      step-time / MFU summaries
``serving/*``         ContinuousBatchingEngine per-step records,
                      ServingStats rollups (tokens/s, TTFT, ITL,
                      occupancy, speculation counters)
``comm/*``            FlopsProfiler collective-traffic counters
                      (comm_gb_per_step, comm_share)
``resilience/*``      sentinel bad-step counters, IO retries, rollbacks,
                      watchdog timeouts
``observability/*``   the observability layer's own device-truth channel
                      (``observability/device/*``: compiled-twin cost
                      cards, HBM watermark gauges —
                      observability/device.py)
====================  ====================================================

Publishing is buffer-friendly: values pass through RAW (device arrays
included) — the sinks already defer the ``float()`` host sync to their
flush boundary, and the registry must not reintroduce a per-step sync.
Sub-namespaces are allowed (``serving/slot0/...``); only the root is
validated.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping

NAMESPACES = ("train", "serving", "comm", "resilience", "observability")

# Well-known sub-namespaces, shared so producers (serving/router.py)
# and consumers (observability/report.py's rollup/--follow readers)
# never restate the literal — epl-lint's metric-schema rule validates
# every literal namespace at publish/namespaced() call sites against
# the roots above.
SERVING_NAMESPACE = "serving"
FLEET_NAMESPACE = "serving/fleet"
# Device-truth channel (observability/device.py): compiled-twin cost
# cards and HBM watermark gauges — what XLA/the runtime report, never
# host-planned quantities (those belong to the producer namespaces).
DEVICE_NAMESPACE = "observability/device"

# The key->namespace rule for producers that accumulate one flat mixed
# metrics dict (fit's step metrics, the profilers' summaries).  Shared
# here so the same key never lands under train/* in one record and
# resilience/* in another; a new counter is added to ONE set and every
# producer routes it identically.
RESILIENCE_KEYS = frozenset(
    ("bad_steps", "bad_steps_total", "update_skipped", "io_retries",
     "rollbacks"))
COMM_KEYS = frozenset(("comm_gb_per_step", "comm_share"))


def split_namespaces(metrics: Mapping[str, Any]
                     ) -> Dict[str, Dict[str, Any]]:
  """Partition a flat metrics dict by the shared key->namespace rule
  (keys not named in a special set are ``train/*``); feed the result to
  :meth:`MetricRegistry.publish_many`."""
  out: Dict[str, Dict[str, Any]] = {"train": {}, "comm": {},
                                    "resilience": {}}
  for k, v in metrics.items():
    if k in RESILIENCE_KEYS:
      out["resilience"][k] = v
    elif k in COMM_KEYS:
      out["comm"][k] = v
    else:
      out["train"][k] = v
  return out


class MetricRegistry:
  """Fan metrics from many producers into shared sinks under one
  namespaced schema.

  ``registry = MetricRegistry(MetricsWriter(path))`` then
  ``registry.publish(step, {"loss": ...}, "train")`` writes
  ``{"train/loss": ...}``.  :meth:`publish_many` merges several
  namespaces into ONE sink record (one JSONL line / one summary step),
  which is how ``fit()`` emits train + resilience metrics per step.
  """

  def __init__(self, *sinks):
    self._sinks: List[Any] = [s for s in sinks if s is not None]
    self._latest: Dict[str, Any] = {}

  def add_sink(self, sink):
    self._sinks.append(sink)
    return sink

  def add_sink_once(self, sink):
    """Idempotent :meth:`add_sink` — the SLO monitor attaches itself to
    whatever registry each serving component holds, and N replicas
    sharing one registry must not multiply every record N ways
    (observability/slo.py)."""
    if sink not in self._sinks:
      self._sinks.append(sink)
    return sink

  @staticmethod
  def namespaced(namespace: str, metrics: Mapping[str, Any]
                 ) -> Dict[str, Any]:
    """Validate `namespace` and prefix every key with it."""
    root = namespace.split("/", 1)[0]
    if root not in NAMESPACES:
      raise ValueError(
          f"unknown metric namespace {namespace!r}; the schema roots are "
          f"{list(NAMESPACES)} (docs/observability.md)")
    return {f"{namespace}/{k}": v for k, v in metrics.items()}

  def publish(self, step: int, metrics: Mapping[str, Any],
              namespace: str = "train"):
    """Publish one producer's metrics under `namespace`."""
    self.publish_many(step, {namespace: metrics})

  def publish_many(self, step: int,
                   by_namespace: Mapping[str, Mapping[str, Any]]):
    """Publish several namespaces as ONE record (empty ones skipped)."""
    record: Dict[str, Any] = {}
    for namespace, metrics in by_namespace.items():
      if metrics:
        record.update(self.namespaced(namespace, metrics))
    if not record:
      return
    self._latest.update(record)
    for sink in self._sinks:
      sink.write(int(step), record)

  def latest(self) -> Dict[str, Any]:
    """Snapshot of the most recently published value per key (raw —
    device values are not floated here)."""
    return dict(self._latest)

  def flush(self):
    for sink in self._sinks:
      sink.flush()

  def close(self):
    """Close the sinks (the registry owns its sinks' lifecycle when the
    caller hands them over at construction, as ``fit()`` does for the
    auto-built JSONL sink)."""
    for sink in self._sinks:
      sink.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
