"""Perf regression gate: device-truth cost-card and benchmark-evidence
invariants pinned in ``perf_budget.json`` (``make perf-gate``).

BENCH_EVIDENCE.json was a write-only ledger: every benchmark appended
evidence and nothing ever READ it, so a PR could double a twin's flops
or regress a pinned episode and no gate noticed until a human re-ran a
benchmark on a quiet box.  This module closes that: a checked-in budget
file pins

* **cost-card invariants** — per compiled twin (the deterministic tiny
  reference geometry :func:`collect_cards` builds), bounds on the
  numbers XLA itself reports at warmup via the device introspector
  (observability/device.py): ``compile_count`` (the compile-once
  contract as a number), ``flops_per_token``, ``kv_bytes_per_request``,
  the static ``peak_hbm_bytes`` plan, and ``donation_verified``.  These
  are COMPILER facts, not wall clocks — they are bit-stable on a noisy
  1-core box, which is exactly why they gate where timing cannot.
* **benchmark-evidence invariants** — selected structural metrics from
  the latest BENCH_EVIDENCE.json record per pinned name (a failover
  episode losing zero requests, the observability overhead staying
  within budget).  Records are validated against the evidence schema
  FIRST (``utils.bench_evidence.validate_record``) and a malformed
  record FAILS the gate — refused, never silently skipped.

Budget entry forms (``perf_budget.json``)::

    {"version": 1,
     "cost_cards": {
       "<twin label>": {"<metric>": {"max": 1.0}            # <= bound
                        | {"min": 1.0}                      # >= bound
                        | {"max": ..., "min": ...}}},
     "bench": [
       {"metric": "<record name>", "path": "kill.orphans_after",
        "op": "<=", "target": 0}]}

Bounds are written pre-inflated (``--write-budget`` applies the
per-metric tolerances below to the measured values), so the check
itself is a plain comparison.  Exit status is CI-shaped: 0 clean, 1 on
any violation, with one ``path: got vs bound`` line each.

Run: ``python -m easyparallellibrary_tpu.observability.perfgate``
(``make perf-gate``; ``make gate`` chains epl-lint first).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from easyparallellibrary_tpu.utils.logging import get_logger

_OPS = {
    "<=": lambda v, t: v <= t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    ">": lambda v, t: v > t,
    "==": lambda v, t: v == t,
}

# Tolerance applied per cost-card metric when GENERATING a budget from
# measured cards (--write-budget): the bound ships pre-inflated so the
# gate is a plain compare.  compile_count and donation_verified are
# exact — a second compile or a lost alias IS the regression.
_CARD_TOLERANCE = {
    "compile_count": 0.0,
    "donation_verified": 0.0,
    "flops_per_token": 0.10,
    "flops": 0.10,
    "kv_bytes_per_request": 0.10,
    "peak_hbm_bytes": 0.25,
}
# Metrics the generated budget pins per twin (when the card carries
# them); max-bounded except donation_verified, which is min-bounded.
_CARD_PINNED = ("compile_count", "flops_per_token", "flops",
                "kv_bytes_per_request", "peak_hbm_bytes")

_DEFAULT_BUDGET = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "perf_budget.json")


def default_budget_path() -> str:
  return os.environ.get("EPL_PERF_BUDGET", _DEFAULT_BUDGET)


def load_budget(path: Optional[str] = None) -> Dict[str, Any]:
  path = path or default_budget_path()
  with open(path, encoding="utf-8") as f:
    doc = json.load(f)
  if not isinstance(doc, dict):
    raise ValueError(f"perf budget {path!r} is not a JSON object")
  return doc


# ------------------------------------------------------ card collection


def collect_cards(twins: Tuple[str, ...] = ("plain", "guarded", "paged")
                  ) -> Dict[str, Dict[str, float]]:
  """Capture cost cards for the canonical reference twins on THIS
  backend: deterministic ``testing.factories.tiny_gpt`` engines, each
  serving one seeded request so warmup capture fires.  Returns
  ``{twin label: flat metrics dict}`` — the measured side the budget's
  ``cost_cards`` section compares against.

  The geometry is pinned (it IS the budget's reference program): any
  change here invalidates the checked-in budget and must regenerate it
  (``--write-budget``)."""
  import numpy as np

  from easyparallellibrary_tpu.observability import device as device_lib
  from easyparallellibrary_tpu.serving import (
      ContinuousBatchingEngine, Request)
  from easyparallellibrary_tpu.testing.factories import tiny_gpt

  previous = device_lib.get_introspector()
  intro = device_lib.install(device_lib.DeviceIntrospector())
  try:
    model, params = tiny_gpt()
    variants = {
        "plain": dict(resilience=False, track_prefix="serving"),
        "guarded": dict(resilience=True,
                        track_prefix="serving/guarded"),
        "paged": dict(resilience=False, paged=True, block_size=8,
                      track_prefix="serving/paged"),
    }
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 64, (5,)).astype(np.int32)
    for name in twins:
      kw = variants[name]
      eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                     prefill_chunk=4, speculative=False,
                                     **kw)
      try:
        eng.submit(Request(uid=f"gate-{name}", prompt=prompt,
                           max_new_tokens=3))
        eng.run()
      finally:
        eng.close()
    return {label: card.metrics()
            for label, card in sorted(intro.cards.items())}
  finally:
    device_lib.install(previous)


# ------------------------------------------------------------ checking


def _check_bound(path: str, value: Any, bound: Dict[str, Any]
                 ) -> List[str]:
  if isinstance(value, bool):
    value = float(value)
  if not isinstance(value, (int, float)):
    return [f"{path}: measured value {value!r} is not numeric"]
  errs = []
  if "max" in bound and value > bound["max"]:
    errs.append(f"{path}: {value:g} exceeds budget max {bound['max']:g}")
  if "min" in bound and value < bound["min"]:
    errs.append(f"{path}: {value:g} below budget min {bound['min']:g}")
  return errs


def check_cost_cards(budget: Dict[str, Any],
                     cards: Dict[str, Dict[str, float]]) -> List[str]:
  """Violations of the budget's ``cost_cards`` section against measured
  cards.  A budgeted twin or metric that was NOT measured is a
  violation — a gate that cannot see a pinned number has not passed
  it."""
  errs: List[str] = []
  for label, pins in (budget.get("cost_cards") or {}).items():
    card = cards.get(label)
    if card is None:
      errs.append(f"cost_cards[{label}]: twin not captured "
                  f"(collection geometry changed?)")
      continue
    for metric, bound in pins.items():
      if metric not in card:
        errs.append(f"cost_cards[{label}].{metric}: metric missing "
                    f"from the captured card")
        continue
      errs.extend(_check_bound(f"cost_cards[{label}].{metric}",
                               card[metric], bound))
  return errs


def _resolve_path(record: Dict[str, Any], dotted: str) -> Any:
  cur: Any = record
  for part in dotted.split("."):
    if not isinstance(cur, dict) or part not in cur:
      return None
    cur = cur[part]
  return cur


def check_bench(budget: Dict[str, Any],
                evidence_path: Optional[str] = None) -> List[str]:
  """Violations of the budget's ``bench`` section against the latest
  BENCH_EVIDENCE.json record per pinned metric.  EVERY record in the
  ledger is schema-validated first; malformed records are refused as
  violations, never silently skipped."""
  from easyparallellibrary_tpu.utils import bench_evidence
  errs: List[str] = []
  records = bench_evidence.load_records(evidence_path)
  for i, rec in enumerate(records):
    for problem in bench_evidence.validate_record(rec):
      errs.append(
          f"bench evidence record #{i} "
          f"({rec.get('metric') if isinstance(rec, dict) else '?'}): "
          f"malformed — {problem}")
  by_name: Dict[str, Dict[str, Any]] = {}
  for rec in records:
    if not isinstance(rec, dict):
      continue
    name = rec.get("metric")
    prev = by_name.get(name)
    if prev is None or (rec.get("unix_time", 0)
                        > prev.get("unix_time", 0)):
      by_name[name] = rec
  for entry in budget.get("bench") or ():
    name, dotted = entry["metric"], entry["path"]
    op, target = entry.get("op", "<="), entry["target"]
    where = f"bench[{name}].{dotted}"
    rec = by_name.get(name)
    if rec is None:
      errs.append(f"{where}: no evidence record named {name!r}")
      continue
    value = _resolve_path(rec, dotted)
    if isinstance(value, bool):
      value = float(value)
    if not isinstance(value, (int, float)):
      errs.append(f"{where}: path missing or non-numeric "
                  f"(got {value!r})")
      continue
    if op not in _OPS:
      errs.append(f"{where}: unknown op {op!r}")
      continue
    if not _OPS[op](value, target):
      errs.append(f"{where}: {value:g} violates '{op} {target:g}'")
  return errs


def run_gate(budget_path: Optional[str] = None,
             evidence_path: Optional[str] = None,
             cards: Optional[Dict[str, Dict[str, float]]] = None
             ) -> List[str]:
  """The whole gate: load the budget, collect (or accept) measured
  cards, check both sections.  Returns every violation."""
  budget = load_budget(budget_path)
  errs: List[str] = []
  if budget.get("cost_cards"):
    if cards is None:
      cards = collect_cards()
    errs.extend(check_cost_cards(budget, cards))
  errs.extend(check_bench(budget, evidence_path))
  return errs


# ----------------------------------------------------------- generation


def generate_budget(cards: Dict[str, Dict[str, float]],
                    bench: Optional[List[Dict[str, Any]]] = None
                    ) -> Dict[str, Any]:
  """A budget document pinning ``cards`` with the standard tolerances
  (the ``--write-budget`` path; the checked-in starter budget was
  produced exactly this way)."""
  cost_cards: Dict[str, Any] = {}
  for label, metrics in sorted(cards.items()):
    pins: Dict[str, Any] = {}
    for metric in _CARD_PINNED:
      if metric not in metrics:
        continue
      tol = _CARD_TOLERANCE.get(metric, 0.25)
      bound = metrics[metric] * (1.0 + tol)
      pins[metric] = {"max": round(bound, 4)}
    if metrics.get("donation_verified") is not None:
      pins["donation_verified"] = {"min": metrics["donation_verified"]}
    cost_cards[label] = pins
  return {
      "version": 1,
      "comment": "Perf budget: cost-card + bench-evidence invariants "
                 "enforced by `make perf-gate` (observability/"
                 "perfgate.py).  Regenerate with --write-budget ONLY "
                 "when a perf change is intentional, and say why in "
                 "the PR.",
      "cost_cards": cost_cards,
      "bench": bench if bench is not None else _DEFAULT_BENCH_PINS,
  }


# Structural (non-wall-clock) evidence pins for the starter budget:
# episodes must keep resolving every request, flagging zero recompiles,
# leaking zero orphans, and closing the self-healing loop.
_DEFAULT_BENCH_PINS: List[Dict[str, Any]] = [
    {"metric": "observability_overhead", "path": "recompiles_flagged",
     "op": "<=", "target": 0},
    {"metric": "observability_overhead", "path": "within_5pct",
     "op": ">=", "target": 1},
    {"metric": "router_failover_process", "path": "kill.orphans_after",
     "op": "<=", "target": 0},
    {"metric": "router_failover_process", "path": "kill.kills",
     "op": ">=", "target": 1},
    {"metric": "self_heal", "path": "self_healing.scale_ups",
     "op": ">=", "target": 1},
    {"metric": "self_heal", "path": "self_healing.slo_recoveries",
     "op": ">=", "target": 1},
]


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      prog="python -m easyparallellibrary_tpu.observability.perfgate",
      description="Perf regression gate over device cost cards and "
                  "BENCH_EVIDENCE.json (perf_budget.json)")
  parser.add_argument("--budget", default=None,
                      help="budget file (default: repo perf_budget.json)")
  parser.add_argument("--evidence", default=None,
                      help="evidence file (default: BENCH_EVIDENCE.json)")
  parser.add_argument("--write-budget", action="store_true",
                      help="regenerate the budget from freshly "
                           "collected cards (tolerances applied) "
                           "instead of checking")
  args = parser.parse_args(argv)
  budget_path = args.budget or default_budget_path()
  if args.write_budget:
    cards = collect_cards()
    doc = generate_budget(cards)
    with open(budget_path, "w", encoding="utf-8") as f:
      json.dump(doc, f, indent=1, sort_keys=False)
      f.write("\n")
    print(f"perf budget written: {budget_path} "
          f"({len(doc['cost_cards'])} twin(s), "
          f"{len(doc['bench'])} bench pin(s))")
    return 0
  violations = run_gate(budget_path, args.evidence)
  if violations:
    print(f"perf-gate: {len(violations)} violation(s):")
    for v in violations:
      print(f"  FAIL {v}")
    return 1
  budget = load_budget(budget_path)
  print(f"perf-gate: OK ({len(budget.get('cost_cards') or {})} twin(s), "
        f"{len(budget.get('bench') or ())} bench pin(s))")
  return 0


if __name__ == "__main__":
  get_logger().setLevel("WARNING")
  sys.exit(main())
