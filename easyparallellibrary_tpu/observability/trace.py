"""Host-side span tracer with Chrome-trace-event / Perfetto JSON export.

The reference's observability story is TF summaries plus RunMetadata
FULL_TRACE capture (epl/parallel/hooks.py:593-664); this repo had
outgrown that with four disjoint half-instruments (StepProfiler,
FlopsProfiler, ServingStats, two metric sinks) none of which could
answer "where did this request's latency go".  The tracer is the one
event substrate they all share:

* **spans** — paired B/E duration events, via the :meth:`Tracer.span`
  context manager (host phases: data-next, step dispatch, checkpoint
  stage/commit) or :meth:`Tracer.span_at` with explicit timestamps
  (per-slot serving timelines, where one fused device step covers many
  requests and the per-slot spans share its start/end);
* **instants** — point events (request submit, first token, sentinel
  escalation, watchdog timeout);
* **counter tracks** — numeric series (active slots, accepted draft
  tokens) Perfetto renders as graphs;
* **flow events** — ``s``/``t``/``f`` phase triplets sharing one
  ``id``, which Perfetto renders as arrows BETWEEN tracks.  The serving
  stack threads one flow per request (``Request.flow_id``, minted at
  router/scheduler submit) through dispatch → admission → every
  migration → retirement, so a request that fails over between replicas
  renders as a single connected arc across the replica tracks instead
  of disconnected span fragments (docs/observability.md "Reading a
  failover trace").

Design constraints, in order:

1. **Zero device syncs on the hot path.**  Nothing here touches a
   ``jax.Array``; timestamps come from ``time.perf_counter_ns`` and
   every argument recorded is already a host value.  The tracer can run
   inside ``jax.transfer_guard_device_to_host("disallow")``.
2. **Bounded memory.**  Events live in a ring buffer
   (``observability.ring_capacity``); a long run keeps the most recent
   window — exactly the window a post-mortem needs ("what happened
   between step 400 and the rollback at 412").
3. **Cheap when off.**  A disabled tracer's ``span()`` returns a
   module-level null context manager: one attribute read and no
   allocation, so instrumentation can stay unconditionally in hot
   loops.
4. **Leader-only export.**  Every process records (cheap), only
   process 0 writes the JSON — the metrics writers' rule
   (epl/parallel/hooks.py:542).

**Distributed tracing** (docs/observability.md "Distributed
tracing"): a process-isolated replica records into its OWN ring; the
parent harvests it over the wire in bounded increments
(:meth:`Tracer.drain_wire` child-side, :meth:`Tracer.ingest_remote`
parent-side) and rebases the child's timestamps into its timebase with
a handshake-estimated clock offset (midpoint of send/recv
``perf_counter_ns`` pairs).  The merged export tags each process's
events with its OS pid, emits per-pid process/track metadata, and
keeps every pid's timeline monotonic after shifting — so one Perfetto
file shows the whole fleet and a request flow arcs across process
boundaries.

The export is standard Chrome trace-event JSON: load it at
``ui.perfetto.dev`` or ``chrome://tracing``.  Device-side XLA timelines
are attached with :meth:`Tracer.xla_trace`, which brackets a
``jax.profiler`` capture with a host span so the two timelines
correlate by wall clock.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple, Union

# Event tuples in the ring: (ph, name, cat, ts_us, tid, args_or_None).
# Dicts are only built at export — the hot path appends one tuple.
_Event = Tuple[str, str, str, float, int, Optional[Dict[str, Any]]]

# Wire event shape for cross-process harvest (JSON-friendly lists):
# [ph, name, cat, ts_us, track_name, args_or_None].  Track NAMES cross
# the wire — tids are tracer-local and get re-assigned per remote pid
# on ingest, so two processes' "serving/slot0" tracks never collide.
_ENC = {"separators": (",", ":"), "default": str}


class _NullSpan:
  """No-op context manager returned by a disabled tracer's ``span()``."""
  __slots__ = ()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    return False


_NULL_SPAN = _NullSpan()


class _Span:
  """Live span handle: records E on exit (always, even on exceptions,
  so an error escaping a phase still closes its span)."""
  __slots__ = ("_tracer", "_name", "_cat", "_tid", "_args")

  def __init__(self, tracer: "Tracer", name: str, cat: str, tid: int,
               args: Optional[Dict[str, Any]] = None):
    self._tracer = tracer
    self._name = name
    self._cat = cat
    self._tid = tid
    self._args = args

  def __enter__(self):
    t = self._tracer
    t._append("B", self._name, self._cat, t.now_us(), self._tid,
              self._args)
    return self

  def __exit__(self, *exc):
    t = self._tracer
    t._append("E", self._name, self._cat, t.now_us(), self._tid, None)
    return False


class Tracer:
  """Ring-buffered host-side span tracer (module docstring).

  ``sample_rate`` in (0, 1] drives deterministic sampling of the
  per-step train-loop phases: fit() makes ONE decision per step with
  :meth:`sample_tick` and gates all of that step's phase spans on it
  (the ``record=`` argument), so a sampled step keeps its FULL phase
  set — including phases only some steps reach (host sync on log
  boundaries) — and a long run can keep per-step phases at, say, 1%
  without losing the request-lifecycle and checkpoint events that are
  always recorded.  A bare ``span(..., sample=True)`` ticks an
  accumulator keyed by its own span name, for standalone call sites
  that sample one recurring span.
  """

  def __init__(self, *, enabled: bool = True, ring_capacity: int = 65536,
               sample_rate: float = 1.0, trace_path: str = ""):
    if ring_capacity < 1:
      raise ValueError(f"ring_capacity must be >= 1: {ring_capacity}")
    if not 0.0 < sample_rate <= 1.0:
      raise ValueError(f"sample_rate must be in (0, 1]: {sample_rate}")
    self.enabled = enabled
    self.ring_capacity = ring_capacity
    self.sample_rate = sample_rate
    self.trace_path = trace_path
    self._events: "deque[_Event]" = deque(maxlen=ring_capacity)
    self._tracks: Dict[str, int] = {"main": 0}
    # The watchdog monitor thread records instants while the main
    # thread records spans, so track registration (two unsynchronized
    # first-uses could claim the same tid) and the append/eviction
    # accounting (`+=` is not GIL-atomic) share one lock.  Event rates
    # are per-step-scale, not per-token, so the cost is noise; the
    # cached track() path stays a lock-free dict read.
    self._lock = threading.Lock()
    self._t0_ns = time.perf_counter_ns()
    self._sample_accs: Dict[str, float] = {}
    # Eviction accounting off the hot path: one int increment per
    # append; `dropped` is derived at read time.
    self._n_appended = 0
    # Harvest accounting: events consumed by drain_wire() are delivered,
    # not dropped.
    self._n_drained = 0
    # Harvested remote rings, keyed by the remote OS pid.  Each store
    # holds its own ring (bounded like the local one), its own track
    # table (track names -> per-pid tids), the display label, and the
    # last rebased timestamp (per-process monotonic clamp: a re-sampled
    # clock offset may move backwards; the merged timeline must not).
    self._remote: Dict[int, Dict[str, Any]] = {}

  # ------------------------------------------------------------- recording

  def now_us(self) -> float:
    """Microseconds since tracer creation (host monotonic clock)."""
    return (time.perf_counter_ns() - self._t0_ns) / 1e3

  def at_us(self, t_ns: int) -> float:
    """A raw ``time.perf_counter_ns`` reading in this tracer's µs
    timebase (clock-offset estimation uses send/recv timestamps taken
    OUTSIDE the tracer)."""
    return (t_ns - self._t0_ns) / 1e3

  def track(self, name: Optional[str]) -> int:
    """tid for a named track (registered on first use; exported as a
    thread-name metadata event so Perfetto labels the row)."""
    if not name:
      return 0
    tid = self._tracks.get(name)
    if tid is None:
      with self._lock:
        tid = self._tracks.get(name)
        if tid is None:
          tid = len(self._tracks)
          self._tracks[name] = tid
    return tid

  @property
  def pending(self) -> int:
    """Events currently buffered in the local ring (the harvest loop's
    'drained dry' signal)."""
    return len(self._events)

  @property
  def dropped(self) -> int:
    """Events evicted by the ring so far (for the export note).
    Events consumed by :meth:`drain_wire` were delivered, not lost."""
    return self._n_appended - self._n_drained - len(self._events)

  def _append(self, ph: str, name: str, cat: str, ts: float, tid: int,
              args: Optional[Dict[str, Any]]):
    with self._lock:
      self._n_appended += 1
      self._events.append((ph, name, cat, ts, tid, args))

  def sample_tick(self, key: str = "") -> bool:
    """Advance the deterministic sampling accumulator for ``key`` and
    return whether this tick records.  fit() calls this once per step
    and gates all of that step's phase spans on the result (``record=``),
    so sampled steps keep their full phase set even for phases a given
    step only sometimes reaches (host sync on log boundaries)."""
    if not self.enabled:
      return False
    if self.sample_rate >= 1.0:
      return True
    acc = self._sample_accs.get(key, 0.0) + self.sample_rate
    if acc < 1.0:
      self._sample_accs[key] = acc
      return False
    self._sample_accs[key] = acc - 1.0
    return True

  def span(self, name: str, cat: str = "", track: Optional[str] = None,
           sample: bool = False, args: Optional[Dict[str, Any]] = None,
           record: bool = True):
    """Context manager recording a B/E pair around the body.
    ``record=False`` returns the null span — for call sites that made a
    per-step sampling decision with :meth:`sample_tick` up front.  With
    ``sample=True`` the span ticks its own name's accumulator instead."""
    if not self.enabled or not record:
      return _NULL_SPAN
    if sample and not self.sample_tick(name):
      return _NULL_SPAN
    return _Span(self, name, cat, self.track(track), args)

  def span_at(self, name: str, t0_us: float, t1_us: float, cat: str = "",
              track: Optional[str] = None,
              args: Optional[Dict[str, Any]] = None):
    """Record a completed span with explicit timestamps — for work whose
    duration is known only after the fact (one fused device step covers
    every serving slot; each slot's span shares its bounds)."""
    if not self.enabled:
      return
    tid = self.track(track) if track else 0
    with self._lock:
      append = self._events.append
      append(("B", name, cat, t0_us, tid, args))
      append(("E", name, cat, t1_us if t1_us >= t0_us else t0_us, tid,
              None))
      self._n_appended += 2

  def begin(self, name: str, cat: str = "", track: Optional[str] = None,
            args: Optional[Dict[str, Any]] = None):
    """Open a long-lived span explicitly (request lifecycle: opened at
    admission, closed at retirement many engine steps later)."""
    if self.enabled:
      self._append("B", name, cat, self.now_us(), self.track(track), args)

  def end(self, name: str, cat: str = "", track: Optional[str] = None,
          args: Optional[Dict[str, Any]] = None):
    """Close a span opened with :meth:`begin` (args merge with the B's
    in trace viewers — retirement reason rides the E)."""
    if self.enabled:
      self._append("E", name, cat, self.now_us(), self.track(track), args)

  def instant(self, name: str, cat: str = "", track: Optional[str] = None,
              args: Optional[Dict[str, Any]] = None):
    if self.enabled:
      self._append("i", name, cat, self.now_us(), self.track(track), args)

  def counter(self, name: str, value: Union[int, float], cat: str = ""):
    """One sample of a numeric counter track (Perfetto draws a graph)."""
    if self.enabled:
      self._append("C", name, cat, self.now_us(), 0, {"value": value})

  def flow(self, phase: str, flow_id: int,
           name: str = "serving/request_flow", cat: str = "serving",
           track: Optional[str] = None, ts: Optional[float] = None,
           args: Optional[Dict[str, Any]] = None):
    """Record one Perfetto flow event: ``phase`` is ``"s"`` (start),
    ``"t"`` (step) or ``"f"`` (finish).  All events of one flow share
    ``flow_id`` (and should share ``name``/``cat`` — viewers match
    flows by category + id); each binds to the enclosing slice on its
    track at ``ts``, and the viewer draws arrows start → steps →
    finish.  The schema contract (:func:`validate_trace`): every
    started flow must be finished, and steps/finishes must follow a
    start."""
    if not self.enabled:
      return
    if phase not in ("s", "t", "f"):
      raise ValueError(f"flow phase must be 's', 't' or 'f': {phase!r}")
    a = dict(args) if args else {}
    a["id"] = int(flow_id)
    self._append(phase, name, cat, self.now_us() if ts is None else ts,
                 self.track(track), a)

  @contextlib.contextmanager
  def xla_trace(self, log_dir: str, name: str = "xla_trace"):
    """Bracket a ``jax.profiler`` device-trace capture with a host span,
    so the XLA timeline (TensorBoard/Perfetto from ``log_dir``) and this
    tracer's host timeline correlate.  The capture runs whether or not
    the tracer is enabled — the span is recorded only when it is."""
    import jax
    from easyparallellibrary_tpu.utils.logging import get_logger
    jax.profiler.start_trace(log_dir)
    t0 = self.now_us()
    try:
      yield
    finally:
      jax.profiler.stop_trace()
      self.span_at(name, t0, self.now_us(), cat="xla",
                   args={"log_dir": os.path.abspath(log_dir)})
      get_logger().info("xla trace written to %s", log_dir)

  # ------------------------------------------- cross-process harvest --

  def drain_wire(self, max_bytes: Optional[int] = None
                 ) -> Dict[str, Any]:
    """Consume the OLDEST ring events into a wire-ready chunk of at
    most ~``max_bytes`` encoded bytes (``None`` = drain everything).
    Called in a worker's serve loop so the parent can harvest the ring
    incrementally; the byte bound keeps one sweep from ever stalling
    dispatch, and whatever does not fit simply rides a later sweep.
    Returns ``{"events": [[ph, name, cat, ts_us, track, args], ...],
    "now_us": <child clock>, "dropped": <ring evictions so far>}``.
    Drained events are delivered, not dropped — :attr:`dropped` only
    counts ring evictions."""
    out: List[List[Any]] = []
    size = 0
    with self._lock:
      rev = {tid: name for name, tid in self._tracks.items()}
      while self._events:
        ph, name, cat, ts, tid, args = self._events[0]
        wire = [ph, name, cat, ts, rev.get(tid, f"track{tid}"), args]
        enc = len(json.dumps(wire, **_ENC))
        if out and max_bytes is not None and size + enc > max_bytes:
          break
        self._events.popleft()
        self._n_drained += 1
        out.append(wire)
        size += enc
        if max_bytes is not None and size >= max_bytes:
          break
    return {"events": out, "now_us": self.now_us(),
            "dropped": self.dropped}

  def ingest_remote(self, pid: int, events: List[List[Any]], *,
                    offset_us: float, label: str = "") -> int:
    """Merge a harvested chunk from a remote process into this tracer.

    ``pid`` is the remote OS pid (the merged export's process key),
    ``offset_us`` the current clock-offset estimate such that
    ``parent_ts ≈ child_ts + offset_us``.  Rebased timestamps are
    clamped per-pid monotonic: the offset is re-estimated over time and
    may step backwards, but a process's own clock never does, so the
    merged timeline must not either.  Remote rings are bounded like the
    local one.  Returns the number of events ingested."""
    if not events:
      return 0
    n = 0
    with self._lock:
      store = self._remote.get(pid)
      if store is None:
        store = {"label": label or f"pid {pid}",
                 "tracks": {},
                 "events": deque(maxlen=self.ring_capacity),
                 "appended": 0,
                 "last_ts": None}
        self._remote[pid] = store
      elif label:
        store["label"] = label
      tracks = store["tracks"]
      for wire in events:
        try:
          ph, name, cat, ts, track, args = wire
        except (TypeError, ValueError):
          continue  # malformed wire event: drop, never poison the ring
        tid = tracks.get(track)
        if tid is None:
          tid = len(tracks)
          tracks[track] = tid
        ts = float(ts) + offset_us
        last = store["last_ts"]
        if last is not None and ts < last:
          ts = last
        store["last_ts"] = ts
        store["events"].append((ph, name, cat, ts, tid, args))
        store["appended"] += 1
        n += 1
    return n

  def close_remote(self, pid: int, reason: str = "lost") -> int:
    """Close every span a remote process left OPEN — a SIGKILLed child
    dies mid-request, so its harvested ring ends in dangling ``B``
    events that would fail schema validation and render as unbounded
    slices.  Synthesizes ``E`` events at the pid's last rebased
    timestamp (LIFO per track, tagged ``{"finish_reason": reason}``),
    so the merged trace shows the victim's work ENDING at death.
    Idempotent; returns the number of spans closed."""
    with self._lock:
      store = self._remote.get(pid)
      if store is None or store["last_ts"] is None:
        return 0
      open_spans: Dict[int, List[Tuple[str, str]]] = {}
      for ph, name, cat, _ts, tid, _args in store["events"]:
        if ph == "B":
          open_spans.setdefault(tid, []).append((name, cat))
        elif ph == "E":
          stack = open_spans.get(tid)
          if stack and stack[-1][0] == name:
            stack.pop()
      n = 0
      for tid, stack in open_spans.items():
        while stack:
          name, cat = stack.pop()
          store["events"].append(
              ("E", name, cat, store["last_ts"], tid,
               {"finish_reason": reason}))
          store["appended"] += 1
          n += 1
      return n

  def remote_summary(self) -> Dict[int, Dict[str, Any]]:
    """Per remote pid: display label, events currently buffered, and
    events evicted from the remote ring (diagnostics + tests)."""
    with self._lock:
      return {pid: {"label": s["label"], "events": len(s["events"]),
                    "dropped": s["appended"] - len(s["events"])}
              for pid, s in self._remote.items()}

  # --------------------------------------------------------------- export

  def events(self) -> List[Dict[str, Any]]:
    """Chrome-trace-event dicts: per-process metadata first (process
    and thread names for the local pid and every harvested remote pid),
    then ALL processes' events merged and sorted by timestamp (spans
    recorded retroactively via :meth:`span_at` land in buffer order,
    not time order; the stable sort restores B-before-E at equal
    timestamps, and each pid's stream is already monotonic so the
    merge preserves per-pid order)."""
    import jax
    pid = jax.process_index()
    with self._lock:  # a concurrent append must not mutate mid-snapshot
      events = list(self._events)
      tracks = sorted(self._tracks.items(), key=lambda kv: kv[1])
      remote = [(rpid, s["label"],
                 sorted(s["tracks"].items(), key=lambda kv: kv[1]),
                 list(s["events"]))
                for rpid, s in sorted(self._remote.items())]
    out: List[Dict[str, Any]] = []
    for name, tid in tracks:
      out.append({"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                  "args": {"name": name}})
      out.append({"ph": "M", "name": "thread_sort_index", "pid": pid,
                  "tid": tid, "args": {"sort_index": tid}})
    for rpid, label, rtracks, _revents in remote:
      out.append({"ph": "M", "name": "process_name", "pid": rpid,
                  "tid": 0, "args": {"name": label}})
      for name, tid in rtracks:
        out.append({"ph": "M", "name": "thread_name", "pid": rpid,
                    "tid": tid, "args": {"name": name}})
        out.append({"ph": "M", "name": "thread_sort_index", "pid": rpid,
                    "tid": tid, "args": {"sort_index": tid}})
    merged = [(e, pid) for e in events]
    for rpid, _label, _rtracks, revents in remote:
      merged.extend((e, rpid) for e in revents)
    for (ph, name, cat, ts, tid, args), epid in sorted(
        merged, key=lambda e: e[0][3]):
      ev: Dict[str, Any] = {"ph": ph, "name": name, "ts": ts,
                            "pid": epid, "tid": tid}
      if cat:
        ev["cat"] = cat
      if ph == "i":
        ev["s"] = "t"
      if ph in ("s", "t", "f") and args is not None and "id" in args:
        # Flow events carry their id top-level (Chrome trace format) and
        # bind to the ENCLOSING slice ("bp": "e") so the arrow anchors
        # on the request span the flow event was recorded inside.
        args = dict(args)
        ev["id"] = args.pop("id")
        ev["bp"] = "e"
        if not args:
          args = None
      if args is not None:
        ev["args"] = args
      out.append(ev)
    return out

  def export(self, path: Optional[str] = None) -> Optional[str]:
    """Write the trace JSON (leader only; non-leaders no-op and return
    None).  Load the file at ``ui.perfetto.dev``."""
    import jax
    from easyparallellibrary_tpu.utils.logging import get_logger
    path = path or self.trace_path
    if not path:
      raise ValueError("no trace path: pass export(path) or set "
                       "observability.trace_path")
    if jax.process_index() != 0:
      return None
    doc = {"traceEvents": self.events(), "displayTimeUnit": "ms"}
    if self.dropped:
      doc["otherData"] = {
          "dropped_events": self.dropped,
          "note": "ring buffer evicted oldest events; raise "
                  "observability.ring_capacity for a longer window"}
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
      json.dump(doc, f)
    os.replace(tmp, path)
    get_logger().info(
        "trace: %d events -> %s (open at ui.perfetto.dev)",
        len(self._events), path)
    return path

  def clear(self):
    with self._lock:
      self._events.clear()
      self._n_appended = 0
      self._n_drained = 0
      self._remote.clear()


# ------------------------------------------------------- global tracer --

# One ambient tracer, like logging: instrumentation sites call
# get_tracer() and stay cheap when it is disabled.  `install()` pins an
# explicit tracer (wins over config); `ensure_configured()` auto-builds
# from the active observability.* config and rebuilds/removes the
# auto-built one when the config changes.
_DISABLED = Tracer(enabled=False, ring_capacity=1)
_tracer: Optional[Tracer] = None
_auto_sig: Optional[Tuple] = None


def get_tracer() -> Tracer:
  """The ambient tracer (never None; a disabled singleton when nothing
  is configured)."""
  return _tracer if _tracer is not None else _DISABLED


def install(tracer: Optional[Tracer]) -> Optional[Tracer]:
  """Pin `tracer` as the ambient tracer (None = uninstall).  An
  explicitly installed tracer wins over config auto-configuration."""
  global _tracer, _auto_sig
  _tracer = tracer
  _auto_sig = None
  return tracer


def reset():
  """Drop any ambient tracer (tests; Env resets do not reach here)."""
  install(None)


def ensure_configured(config=None) -> Tracer:
  """Reconcile the ambient tracer with ``config.observability`` (the
  active Env's config when None): enable/rebuild it when the config asks
  for tracing, drop an auto-built tracer when it no longer does.  An
  explicitly :func:`install`-ed tracer is left alone.  Called by
  ``fit()`` and the serving engine at entry, so setting
  ``observability.enabled`` is all a run needs.

  Only the AMBIENT Env config may tear down or rebuild an existing
  auto-built tracer (both discard the ring).  A component constructed
  with its own explicit config — an engine built mid-fit with serving
  knobs whose observability group is default-off — can enable tracing
  when none exists, but must not silently drop the run's recorded
  events or stop the instrumentation every other site records into."""
  global _tracer, _auto_sig
  if _tracer is not None and _auto_sig is None:
    return _tracer  # explicit install wins
  from easyparallellibrary_tpu.env import Env
  if config is None:
    config = Env.get().config
    ambient = True
  else:
    ambient = config is Env.get().config
  obs = config.observability
  if not obs.enabled:
    if _auto_sig is not None and ambient:
      _tracer = None
      _auto_sig = None
    return get_tracer()
  sig = (obs.ring_capacity, obs.sample_rate, obs.trace_path)
  if _tracer is None:
    _tracer = Tracer(enabled=True, ring_capacity=obs.ring_capacity,
                     sample_rate=obs.sample_rate,
                     trace_path=obs.trace_path)
    _auto_sig = sig
  elif _auto_sig != sig and ambient:
    _tracer = Tracer(enabled=True, ring_capacity=obs.ring_capacity,
                     sample_rate=obs.sample_rate,
                     trace_path=obs.trace_path)
    _auto_sig = sig
  return _tracer


# ----------------------------------------------------- schema validation --

_REQUIRED_KEYS = ("ph", "name", "pid", "tid")


def validate_trace(trace: Union[str, Dict[str, Any], List[Dict[str, Any]]]
                   ) -> List[Dict[str, Any]]:
  """Schema-validate a Chrome-trace JSON export; returns the event list
  or raises ``ValueError`` naming every problem.

  Checks: top-level shape, required keys per event, monotonically
  non-decreasing ``ts`` PER PID (a merged multi-process trace
  interleaves processes whose clocks are only offset-aligned; each
  process's own rebased timeline must still be monotonic), unique
  thread-name metadata per (pid, tid) — a merge bug that emits a pid's
  track table twice corrupts Perfetto's row labels — strict B/E
  pairing per (pid, tid) — every E closes the innermost open B of the
  same name, nothing left open — and the flow schema: every
  ``s``/``t``/``f`` flow event carries an ``id``, steps and finishes
  follow a start of the same id AND bind to it by category (viewers
  match flows by cat + id, so a cross-process arc only connects when
  both sides agree), no second start while a flow is open, and every
  started flow TERMINATES with an ``f`` (a failed-over request must
  reach retirement somewhere — a dangling flow is a lost request).
  (``make trace-demo`` / ``make trace-fleet`` quick tests run this
  over real emitted traces.)
  """
  if isinstance(trace, str):
    with open(trace) as f:
      trace = json.load(f)
  if isinstance(trace, dict):
    if "traceEvents" not in trace:
      raise ValueError("trace JSON object lacks the 'traceEvents' key")
    events = trace["traceEvents"]
  else:
    events = trace
  if not isinstance(events, list):
    raise ValueError(f"traceEvents must be a list; got {type(events)}")
  problems: List[str] = []
  last_ts: Dict[Any, float] = {}
  stacks: Dict[Tuple[Any, Any], List[str]] = {}
  named_tracks: set = set()
  # Open flows: id -> (index of the "s" event, its category).
  flows: Dict[Any, Tuple[int, Any]] = {}
  for i, ev in enumerate(events):
    if not isinstance(ev, dict):
      problems.append(f"event {i}: not an object")
      continue
    missing = [k for k in _REQUIRED_KEYS if k not in ev]
    if missing:
      problems.append(f"event {i}: missing {missing}")
      continue
    ph = ev["ph"]
    pid = ev["pid"]
    if ph == "M":
      if ev["name"] == "thread_name":
        key = (pid, ev["tid"])
        if key in named_tracks:
          problems.append(f"event {i}: duplicate thread_name metadata "
                          f"for pid/tid {key}")
        named_tracks.add(key)
      continue  # metadata events carry no timestamp
    if "ts" not in ev:
      problems.append(f"event {i} ({ph} {ev['name']!r}): missing 'ts'")
      continue
    ts = ev["ts"]
    prev = last_ts.get(pid)
    if prev is not None and ts < prev:
      problems.append(
          f"event {i} ({ph} {ev['name']!r}): ts {ts} < previous {prev} "
          f"on pid {pid} (not monotonic)")
    last_ts[pid] = ts
    if ph in ("s", "t", "f"):
      if "id" not in ev:
        problems.append(f"event {i} ({ph} {ev['name']!r}): flow event "
                        f"missing 'id'")
        continue
      fid = ev["id"]
      if ph == "s":
        if fid in flows:
          problems.append(
              f"event {i}: flow {fid!r} started again while still open "
              f"(previous start at event {flows[fid][0]})")
        flows[fid] = (i, ev.get("cat"))
      elif fid not in flows:
        problems.append(f"event {i}: flow {ph!r} phase for {fid!r} with "
                        f"no open flow start")
      else:
        start_cat = flows[fid][1]
        if ev.get("cat") != start_cat:
          problems.append(
              f"event {i}: flow {ph!r} for {fid!r} on pid {pid} has cat "
              f"{ev.get('cat')!r} but the flow started with "
              f"{start_cat!r} (flows bind by cat + id)")
        if ph == "f":
          del flows[fid]
      continue
    key = (ev["pid"], ev["tid"])
    stack = stacks.setdefault(key, [])
    if ph == "B":
      stack.append(ev["name"])
    elif ph == "E":
      if not stack:
        problems.append(f"event {i}: E {ev['name']!r} with no open B "
                        f"on pid/tid {key}")
      elif stack[-1] != ev["name"]:
        problems.append(
            f"event {i}: E {ev['name']!r} does not close the innermost "
            f"open B {stack[-1]!r} on pid/tid {key}")
        stack.pop()
      else:
        stack.pop()
  for key, stack in stacks.items():
    if stack:
      problems.append(f"unclosed span(s) {stack} on pid/tid {key}")
  for fid, (start_i, _cat) in flows.items():
    problems.append(f"flow {fid!r} (started at event {start_i}) never "
                    f"terminated with an 'f' phase")
  if problems:
    raise ValueError("invalid trace:\n  " + "\n  ".join(problems))
  return events
