"""Device-truth observability: compiled-program introspection, per-site
measured collective bytes, and HBM watermark gauges.

The tracing/SLO stack (observability/trace.py, slo.py) sees the HOST
side of every step: spans bracket ``device_step``, counters track what
the scheduler planned.  What XLA actually compiled — flops, bytes moved
per collective, peak HBM, whether donation really aliased — stayed a
black box, and the overlap planner's measured-bytes input
(``plan_collective_matmul(measured_collective_bytes=...)``, ROADMAP
item 5c) had nothing feeding it.  This module is the measured half of
that loop:

* :class:`CostCard` — one compiled twin's device truth, captured ONCE
  at warmup from the ahead-of-time introspection surface
  (``jit(f).lower(specs).compile()`` → ``cost_analysis()`` /
  ``memory_analysis()``): flops, bytes accessed, per-collective-op wire
  bytes (attributed to registered overlap SITES), the static HBM plan
  (argument/output/temp/alias bytes and their peak-bound sum), and a
  donation-verified flag (``alias_size_in_bytes > 0`` — the compiler's
  own word that the donated buffers really aliased, not just that the
  caller asked).  AOT lowering traces ABSTRACT values
  (:func:`specs_of` ShapeDtypeStructs), so capture never touches live
  buffers, never transfers device->host (transfer-guard clean), and
  never grows the twin's jit call cache — the compile sentinel stays
  silent (pinned in tests/test_observability_device.py).
* **Per-site measured collective bytes** — overlap call sites register
  themselves through :func:`resolve_num_chunks(site=...)
  <easyparallellibrary_tpu.communicators.overlap.resolve_num_chunks>`
  (the planner's site naming, ``parallel.planner.OVERLAP_SITES``); when
  a captured program contains the site's fused collective, its RESULT
  bytes are matched back to the site and converted to ring wire bytes,
  and the next resolution consumes the measurement automatically
  (:func:`measured_collective_bytes`).  The measurement is SITE-scoped
  — never the whole-program aggregate ``FlopsProfiler`` counts — and
  the analytic derivation stays the fallback whenever no measurement
  exists (bit-identical decisions, pinned).
* **HBM watermark gauges** — :meth:`DeviceIntrospector.hbm_gauges`
  reads ``jax.local_devices()[i].memory_stats()`` where the backend
  provides it (TPU/GPU: live + peak + limit, so ``hbm_frac`` feeds the
  ``observability.slo.hbm_frac`` rule), and degrades to the cost
  cards' static plan bound elsewhere (CPU: ``memory_stats() is None``
  — the gauge still reports the compiled twins' worst-case footprint,
  it just cannot see allocator churn).  Sampled on the engine's
  existing stats cadence and published under the
  ``observability/device/*`` registry namespace, as Perfetto counter
  tracks, and into diagnostic bundles.

Ambient wiring mirrors the tracer/monitor contract
(:func:`ensure_configured` reconciles with ``observability.device.*``;
:func:`install` pins an explicit introspector for tests).  Capture is
defensive end to end: introspection describes the program, it must
never take the program down — every capture failure degrades to a
logged skip.  Lint: ``cost_analysis``/``memory_analysis``/
``memory_stats`` calls are allowed HERE (and in profiler/) and nowhere
on the serving/training hot paths — epl-lint's ``device-introspection``
rule enforces the boundary statically (docs/static_analysis.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Dict, List, Mapping, Optional, Tuple

from easyparallellibrary_tpu.utils.logging import get_logger

# Which fused StableHLO op a site's collective lowers to when the
# overlap policy picks the fused program (the form capture can match —
# an already-ringed site shows collective_permutes and stays analytic).
_SITE_FUSED_OP = {
    "all_gather_matmul": "all_gather",
    "matmul_reduce_scatter": "reduce_scatter",
    "reduce_scatter": "reduce_scatter",
}

# A parsed collective matches a site only when its result bytes sit
# within this factor of the site's expected fused result — close enough
# to be the site, far enough to tolerate padding/layout slop.
_MATCH_FACTOR = 1.5


@dataclasses.dataclass
class SiteInfo:
  """One registered overlap site: the analytic signature
  ``resolve_num_chunks`` saw, kept so captured programs can be matched
  back to the site that will consume the measurement."""
  site: str
  kind: str
  axis_n: int
  m: int
  k: int
  n_out: int
  dtype_bytes: int

  def expected_result_bytes(self) -> float:
    """Result-tensor bytes of this site's FUSED collective (what the
    StableHLO text sizes ops by; wire bytes are derived from it)."""
    n = max(self.axis_n, 1)
    if self.kind == "all_gather_matmul":
      return float(n * self.m * self.k * self.dtype_bytes)
    if self.kind == "matmul_reduce_scatter":
      return float(self.m / n * self.n_out * self.dtype_bytes)
    return float(self.m / n * self.k * self.dtype_bytes)

  def wire_bytes_from_result(self, result_bytes: float) -> float:
    """Ring wire bytes implied by a matched fused result: an all_gather
    moves (n-1)/n of its gathered result past each device; a
    reduce_scatter moves (n-1) copies of its scattered block."""
    n = max(self.axis_n, 1)
    if self.kind == "all_gather_matmul":
      return result_bytes * (n - 1) / n
    return result_bytes * (n - 1)


@dataclasses.dataclass
class CostCard:
  """Device truth for one compiled twin, captured at warmup."""
  label: str                       # twin label (serving/fused_step, ...)
  flops: float = 0.0
  bytes_accessed: float = 0.0
  collective_wire_bytes: float = 0.0   # sum over collective ops
  collective_ops: int = 0
  site_bytes: Dict[str, float] = dataclasses.field(default_factory=dict)
  argument_bytes: float = 0.0
  output_bytes: float = 0.0
  temp_bytes: float = 0.0
  alias_bytes: float = 0.0
  generated_code_bytes: float = 0.0
  peak_hbm_bytes: float = 0.0      # static plan bound: args + temp + out
  donation_requested: bool = False
  donation_verified: bool = False
  compile_count: int = 0           # twin's jit cache size at capture
  meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

  def metrics(self) -> Dict[str, float]:
    """Flat numeric view for the registry / perf gate (host floats
    only; ``meta`` numeric entries ride along)."""
    out = {
        "flops": self.flops,
        "bytes_accessed": self.bytes_accessed,
        "collective_wire_bytes": self.collective_wire_bytes,
        "collective_ops": float(self.collective_ops),
        "argument_bytes": self.argument_bytes,
        "output_bytes": self.output_bytes,
        "temp_bytes": self.temp_bytes,
        "alias_bytes": self.alias_bytes,
        "peak_hbm_bytes": self.peak_hbm_bytes,
        "donation_verified": float(self.donation_verified),
        "compile_count": float(self.compile_count),
    }
    for k, v in self.meta.items():
      if isinstance(v, (int, float)) and not isinstance(v, bool):
        out[k] = float(v)
    if self.meta.get("tokens_per_step"):
      out["flops_per_token"] = (
          self.flops / float(self.meta["tokens_per_step"]))
    return out

  def summary(self) -> Dict[str, Any]:
    d = self.metrics()
    d["label"] = self.label
    if self.site_bytes:
      d["site_bytes"] = dict(self.site_bytes)
    return d


def specs_of(args) -> Tuple:
  """ShapeDtypeStruct pytree mirroring ``args`` — the abstract twin the
  AOT capture lowers, so live (possibly donated) buffers are never held
  or read.  Host scalars/arrays pass through unchanged (lowering treats
  them as it would the originals)."""
  import jax

  def spec(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
      return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x

  return jax.tree_util.tree_map(spec, args)


class DeviceIntrospector:
  """Warmup-time compiled-program introspection + the per-site
  measurement store + HBM gauges (module docstring).  Thread-safe: the
  stores are lock-guarded (capture may run on an engine thread while a
  watchdog-triggered bundle reads the summary)."""

  def __init__(self, hbm_gauges: bool = True, site_feed: bool = True,
               cards_path: str = ""):
    self.hbm_gauges_enabled = hbm_gauges
    self.site_feed = site_feed
    self.cards_path = cards_path
    self.cards: Dict[str, CostCard] = {}
    self.captures = 0
    self.capture_failures = 0
    self._sites: Dict[str, SiteInfo] = {}
    self._measured: Dict[str, float] = {}
    self._lock = threading.Lock()
    self._fail_logged: set = set()

  # ------------------------------------------------------------- sites

  def register_site(self, site: str, *, kind: str, axis_n: int, m: int,
                    k: int, n_out: int, dtype_bytes: int) -> None:
    """Record one overlap site's analytic signature (called from
    ``resolve_num_chunks``) so later captures can attribute the site's
    fused collective back to it."""
    if not self.site_feed:
      return
    with self._lock:
      self._sites[site] = SiteInfo(site, kind, int(axis_n), int(m),
                                   int(k), int(n_out), int(dtype_bytes))

  def record_site_bytes(self, site: str, wire_bytes: float) -> None:
    """Store a measured per-step wire-byte figure for ``site`` — the
    value the next ``resolve_num_chunks(site=...)`` consumes in place
    of the analytic derivation."""
    with self._lock:
      self._measured[site] = float(wire_bytes)

  def measured_site_bytes(self, site: str) -> Optional[float]:
    with self._lock:
      return self._measured.get(site)

  def sites(self) -> Dict[str, SiteInfo]:
    with self._lock:
      return dict(self._sites)

  def measured(self) -> Dict[str, float]:
    with self._lock:
      return dict(self._measured)

  def _attribute_sites(self, ops: List[Tuple[str, float]]
                       ) -> Dict[str, float]:
    """Match parsed collective ops to registered sites by expected
    fused-result bytes; claimed ops feed the measurement store.  Sites
    with no plausible match stay unmeasured (analytic fallback) —
    attribution must never guess."""
    with self._lock:
      sites = list(self._sites.values())
    if not sites or not ops:
      return {}
    available = list(ops)
    matched: Dict[str, float] = {}
    for info in sites:
      want_op = _SITE_FUSED_OP.get(info.kind)
      expected = info.expected_result_bytes()
      if want_op is None or expected <= 0:
        continue
      best_i, best_ratio = -1, _MATCH_FACTOR
      for i, (op, result) in enumerate(available):
        if op != want_op or result <= 0:
          continue
        ratio = max(result / expected, expected / result)
        if ratio <= best_ratio:
          best_i, best_ratio = i, ratio
      if best_i < 0:
        continue
      _op, result = available.pop(best_i)
      matched[info.site] = info.wire_bytes_from_result(result)
    if matched:
      with self._lock:
        self._measured.update(matched)
    return matched

  # ----------------------------------------------------------- capture

  def has_card(self, label: str) -> bool:
    with self._lock:
      return label in self.cards

  def card(self, label: str) -> Optional[CostCard]:
    with self._lock:
      return self.cards.get(label)

  def capture_twin(self, label: str, fn, arg_specs,
                   compile_count: Optional[int] = None,
                   meta: Optional[Mapping[str, Any]] = None
                   ) -> Optional[CostCard]:
    """Introspect one compiled twin through the AOT surface and record
    its :class:`CostCard`.  ``fn`` is the twin's ``jax.jit`` wrapper;
    ``arg_specs`` the :func:`specs_of` tree of one real call's
    arguments.  Idempotent per label; never raises (a failed capture
    logs once per label and serving continues)."""
    with self._lock:
      if label in self.cards:
        return self.cards[label]
    try:
      card = self._capture(label, fn, arg_specs, compile_count, meta)
    except Exception as e:  # noqa: BLE001 — introspection must not crash
      self.capture_failures += 1
      if label not in self._fail_logged:
        self._fail_logged.add(label)
        get_logger().warning(
            "device introspection of twin %s failed (%s: %s); cost card "
            "skipped (logged once)", label, type(e).__name__, e)
      return None
    with self._lock:
      self.cards[label] = card
      self.captures += 1
    self._emit(card)
    self._dump_cards()
    return card

  def _capture(self, label, fn, arg_specs, compile_count, meta
               ) -> CostCard:
    from easyparallellibrary_tpu.profiler.flops import collective_op_sizes
    lowered = fn.lower(*arg_specs)
    text = lowered.as_text()
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # some backends return per-computation
      cost = cost[0] if cost else {}
    cost = dict(cost or {})
    mem = compiled.memory_analysis()
    ops = collective_op_sizes(text)
    site_bytes = self._attribute_sites(ops)
    requested = "tf.aliasing_output" in text or "jax.buffer_donor" in text
    card = CostCard(
        label=label,
        flops=float(cost.get("flops", 0.0)),
        bytes_accessed=float(cost.get("bytes accessed", 0.0)),
        collective_ops=len(ops),
        site_bytes=site_bytes,
        donation_requested=requested,
        compile_count=int(compile_count or 0),
        meta=dict(meta or {}))
    if mem is not None:
      card.argument_bytes = float(
          getattr(mem, "argument_size_in_bytes", 0) or 0)
      card.output_bytes = float(
          getattr(mem, "output_size_in_bytes", 0) or 0)
      card.temp_bytes = float(getattr(mem, "temp_size_in_bytes", 0) or 0)
      card.alias_bytes = float(
          getattr(mem, "alias_size_in_bytes", 0) or 0)
      card.generated_code_bytes = float(
          getattr(mem, "generated_code_size_in_bytes", 0) or 0)
      # Static plan bound: everything the program holds at once minus
      # what aliases onto its own inputs (donated buffers are not paid
      # twice) — the compiler's worst case, not allocator truth.
      card.peak_hbm_bytes = max(
          card.argument_bytes + card.temp_bytes + card.output_bytes
          - card.alias_bytes, 0.0)
      card.donation_verified = card.alias_bytes > 0
    else:
      # No memory plan on this backend: the donation flag falls back to
      # the lowered text's aliasing annotation (request == verification
      # is the best this backend can attest).
      card.donation_verified = requested
    # Wire bytes summed over every collective the program holds — the
    # whole-program figure (FlopsProfiler's comm counter analog); the
    # SITE split above is what the overlap planner consumes.
    card.collective_wire_bytes = float(sum(b for _o, b in ops))
    return card

  def _emit(self, card: CostCard) -> None:
    from easyparallellibrary_tpu.observability import trace as trace_lib
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      args = {k: v for k, v in card.summary().items()
              if isinstance(v, (int, float, str))}
      tracer.instant("device/cost_card", cat="device", track="device",
                     args=args)
      tracer.counter("device/twin_flops", card.flops)
      tracer.counter("device/twin_peak_hbm_bytes", card.peak_hbm_bytes)
    get_logger().info(
        "device cost card %s: %.3g flops, %.3g bytes accessed, "
        "%.3g peak HBM (static), %d collective op(s), donation %s",
        card.label, card.flops, card.bytes_accessed, card.peak_hbm_bytes,
        card.collective_ops,
        "verified" if card.donation_verified else
        ("NOT aliased" if card.donation_requested else "not requested"))

  def _dump_cards(self) -> None:
    if not self.cards_path:
      return
    try:
      with self._lock:
        doc = {label: card.summary()
               for label, card in sorted(self.cards.items())}
        doc["sites"] = {s: dataclasses.asdict(i)
                        for s, i in sorted(self._sites.items())}
        doc["measured_site_bytes"] = dict(self._measured)
      tmp = self.cards_path + ".tmp"
      os.makedirs(os.path.dirname(os.path.abspath(self.cards_path)),
                  exist_ok=True)
      with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
      os.replace(tmp, self.cards_path)
    except OSError as e:
      get_logger().warning("device cost-card dump to %s failed: %s",
                           self.cards_path, e)

  # -------------------------------------------------------- HBM gauges

  def hbm_gauges(self) -> Dict[str, Any]:
    """Current HBM watermarks as host floats.  ``memory_stats()``-
    backed where the runtime provides it (live/peak/limit + the
    ``hbm_frac`` the SLO rule consumes); elsewhere the cost cards'
    static plan bound with ``hbm_source = "cost_card"`` (and no frac —
    a bound over no limit is not an occupancy)."""
    import jax
    try:
      devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend, no gauges
      return {}
    in_use = peak = limit = 0.0
    live = False
    for d in devices:
      try:
        stats = d.memory_stats()
      except Exception:  # noqa: BLE001
        stats = None
      if not stats:
        continue
      live = True
      in_use += float(stats.get("bytes_in_use", 0) or 0)
      peak = max(peak, float(stats.get("peak_bytes_in_use", 0) or 0))
      limit += float(stats.get("bytes_limit", 0) or 0)
    if live:
      out = {"hbm_bytes_in_use": in_use, "hbm_peak_bytes": peak,
             "hbm_bytes_limit": limit, "hbm_source": "memory_stats"}
      if limit > 0:
        out["hbm_frac"] = in_use / limit
      return out
    with self._lock:
      bound = max((c.peak_hbm_bytes for c in self.cards.values()),
                  default=0.0)
    if bound <= 0:
      return {}
    return {"hbm_bytes_in_use": bound, "hbm_peak_bytes": bound,
            "hbm_bytes_limit": 0.0, "hbm_source": "cost_card"}

  def publish_hbm(self, step: int, registry=None, monitor=None) -> None:
    """Publish the gauges under ``observability/device/*`` (registry
    when present — the SLO monitor rides it as a sink — else straight
    to the monitor) and as Perfetto counter tracks.  Host floats only;
    a gaugeless backend publishes nothing."""
    if not self.hbm_gauges_enabled:
      return
    gauges = self.hbm_gauges()
    if not gauges:
      return
    from easyparallellibrary_tpu.observability import trace as trace_lib
    from easyparallellibrary_tpu.observability.registry import (
        DEVICE_NAMESPACE, MetricRegistry)
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.counter("device/hbm_bytes_in_use",
                     gauges["hbm_bytes_in_use"])
      tracer.counter("device/hbm_peak_bytes", gauges["hbm_peak_bytes"])
    numeric = {k: v for k, v in gauges.items()
               if isinstance(v, (int, float))}
    if registry is not None:
      registry.publish(step, numeric, DEVICE_NAMESPACE)
    elif monitor is not None:
      monitor.observe(step,
                      MetricRegistry.namespaced(DEVICE_NAMESPACE, numeric))

  # ----------------------------------------------------------- context

  def context(self) -> Dict[str, Any]:
    """Diagnostic-bundle summary (DiagnosticCapture context provider):
    every card plus the live gauges and the site measurement store."""
    with self._lock:
      cards = {label: card.summary()
               for label, card in sorted(self.cards.items())}
      measured = dict(self._measured)
    out: Dict[str, Any] = {"cost_cards": cards}
    if measured:
      out["measured_site_bytes"] = measured
    gauges = self.hbm_gauges()
    if gauges:
      out["hbm"] = gauges
    if self.capture_failures:
      out["capture_failures"] = self.capture_failures
    return out


# --------------------------------------------------- ambient introspector


_introspector: Optional[DeviceIntrospector] = None
_auto_sig: Optional[Tuple] = None


def get_introspector() -> Optional[DeviceIntrospector]:
  """The ambient introspector, or None when device observability is
  off."""
  return _introspector


def install(intro: Optional[DeviceIntrospector]
            ) -> Optional[DeviceIntrospector]:
  """Pin an explicit introspector (None = uninstall); wins over
  config."""
  global _introspector, _auto_sig
  _introspector = intro
  _auto_sig = None
  return intro


def reset():
  """Drop any ambient introspector (tests)."""
  install(None)


def ensure_configured(config=None) -> Optional[DeviceIntrospector]:
  """Reconcile the ambient introspector with
  ``config.observability.device`` — the tracer/monitor contract:
  explicit :func:`install` wins, and only the AMBIENT Env config may
  tear down or rebuild an auto-built instance (rebuilding drops the
  cards and the site measurement store)."""
  global _introspector, _auto_sig
  if _introspector is not None and _auto_sig is None:
    return _introspector  # explicit install wins
  from easyparallellibrary_tpu.env import Env
  if config is None:
    config = Env.get().config
    ambient = True
  else:
    ambient = config is Env.get().config
  dev = config.observability.device
  if not dev.enabled:
    if _auto_sig is not None and ambient:
      _introspector = None
      _auto_sig = None
    return _introspector
  sig = (dev.hbm_gauges, dev.site_feed, dev.cards_path)
  if _introspector is not None and (_auto_sig == sig or not ambient):
    return _introspector
  _introspector = DeviceIntrospector(
      hbm_gauges=dev.hbm_gauges, site_feed=dev.site_feed,
      cards_path=dev.cards_path)
  _auto_sig = sig
  get_logger().info(
      "device introspector: hbm gauges %s, site feed %s, cards -> %s",
      "on" if dev.hbm_gauges else "off",
      "on" if dev.site_feed else "off", dev.cards_path or "(memory only)")
  return _introspector


# Module-level conveniences the overlap policy calls (cheap no-ops when
# device observability is off — the policy must not pay for plumbing).


def measured_collective_bytes(site: str) -> Optional[float]:
  """The measured per-step wire bytes for one overlap site, or None
  when device observability is off or the site is unmeasured — the
  automatic feed behind ``resolve_num_chunks(site=...)`` (analytic
  fallback preserved)."""
  intro = _introspector
  if intro is None:
    return None
  return intro.measured_site_bytes(site)


def register_site(site: str, *, kind: str, axis_n: int, m: int, k: int,
                  n_out: int, dtype_bytes: int) -> None:
  """Register one overlap site's analytic signature with the ambient
  introspector (no-op when off)."""
  intro = _introspector
  if intro is not None:
    intro.register_site(site, kind=kind, axis_n=axis_n, m=m, k=k,
                        n_out=n_out, dtype_bytes=dtype_bytes)
