"""Host-side SLO monitoring, compile sentinel, and anomaly-triggered
deep capture — the layer that turns the PR-5 timelines from something
humans read into signals the system acts on (ROADMAP item 5).

Three connected pieces, all pure host policy (no jax imports on any hot
path; unit-testable with fake clocks like serving/resilience.py):

* :class:`SLOMonitor` — consumes the registry's namespaced records
  (``serving/fleet/*``, ``serving/replica<i>/*``, ``serving/*``,
  ``train/*``) and evaluates declarative rules against them:
  :class:`SLORule` threshold rules (TTFT/ITL percentile targets, replica
  availability) and :class:`BurnRateRule` error-budget burn over
  fast/slow record windows (shed rate, in the multi-window SRE shape —
  a short spike and a slow leak both fire, a momentary blip does not).
  Every breach/recovery is emitted three ways at once: a
  machine-readable line in ``slo_events.jsonl`` (the artifact
  ``report.py --follow`` and future autotuners tail), a ``slo/breach``
  trace instant + ``slo/breaches`` counter track on the ambient tracer,
  and the :meth:`SLOMonitor.add_listener` callbacks (the hook the
  router's health machine and autotuners subscribe to).  The monitor IS
  a registry sink (``write``/``flush``/``close``), so attaching it via
  :meth:`MetricRegistry.add_sink_once` makes every producer's records
  flow through with zero new plumbing.
* :class:`CompileSentinel` — a cache-size watermark per compiled twin
  that turns the tests' ``_cache_size() == 1`` assertion into an
  always-on production check: any post-warmup recompile of a fused step
  is detected the step it happens, attributed to the input signature
  that caused it, and raised as a first-class SLO breach + trace
  instant.  The compile-once invariant is the load-bearing contract of
  every engine twin; silently violating it turns a 2ms decode step into
  a multi-second compile stall.
* :class:`DiagnosticCapture` — on SLO breach, watchdog fire, or
  recompile, atomically dump a bounded diagnostic bundle (the tracer
  ring's tail, the registry's ``latest()`` snapshot, a
  scheduler/allocator state summary from the engine's context
  providers) into a quarantine-style timestamped directory — staged as
  ``<bundle>.tmp`` then renamed, the saver's crash-consistency
  discipline — rate-limited and retention-bounded so a flapping fleet
  cannot fill the disk.

Ambient wiring mirrors the tracer (observability/trace.py): components
call :func:`ensure_configured` at entry and the ``observability.slo.*``
config group decides everything; :func:`install` pins an explicit
monitor for tests.  Knob table: docs/observability.md "SLO monitoring".

Monitoring must never change what it monitors: evaluation is dict/float
arithmetic on values that are ALREADY host scalars — device arrays in a
record are skipped, never floated (floating one would reintroduce the
per-step host sync the registry exists to avoid) — and nothing here
touches the fused step, so the standing contracts (zero added
recompiles, bit-exact streams, ≤5% step overhead) hold with the whole
layer enabled (tests/test_observability_fleet.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Tuple

from easyparallellibrary_tpu.utils import vclock
from easyparallellibrary_tpu.utils.logging import get_logger

_OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
}

# Only values that are already host scalars are evaluated; anything
# array-like (a device value passing through the registry raw) is
# skipped — evaluating it would force the host sync the sinks defer.
_SCALARS = (int, float)


def _is_scalar(v: Any) -> bool:
  if isinstance(v, bool):
    return False
  if isinstance(v, _SCALARS):
    return True
  # numpy scalars quack like floats without being jax.Arrays; a shaped
  # array (np or device) is never evaluated.
  return hasattr(v, "dtype") and getattr(v, "shape", None) == () and \
      not type(v).__module__.startswith("jax")


@dataclasses.dataclass
class SLORule:
  """One threshold SLO: healthy while ``value <op> target`` holds for
  the matched metric.

  ``metric`` is either a full registry key (``serving/fleet/ttft_p99_s``
  — exact match) or a bare metric name (``ttft_p99_s`` — matches ANY
  key whose last path segment equals it: the fleet rollup, every
  ``serving/replica<i>/*`` record and a bare engine's ``serving/*``
  record all evaluate under one rule, each tracked as its own breach
  stream).  ``for_records`` requires that many CONSECUTIVE violating
  observations of one key before the breach fires (debounce for noisy
  percentiles)."""
  name: str
  metric: str
  op: str = "<="
  target: float = 0.0
  for_records: int = 1

  def __post_init__(self):
    if self.op not in _OPS:
      raise ValueError(f"SLORule op must be one of {sorted(_OPS)}: "
                       f"{self.op!r}")
    if self.for_records < 1:
      raise ValueError(f"for_records must be >= 1: {self.for_records}")

  def healthy(self, value: float) -> bool:
    return _OPS[self.op](value, self.target)


@dataclasses.dataclass
class BurnRateRule:
  """Error-budget burn over fast/slow record windows (multi-window
  burn-rate alerting).

  ``bad`` and ``good`` name CUMULATIVE counters (suffix-matched like
  :class:`SLORule.metric`, both under the same key prefix);
  ``objective`` is the promised good fraction (0.99 = at most 1% of
  events may be bad).  Each observation appends the counter pair; the
  burn rate over a window of N records is::

      burn = (Δbad / (Δbad + Δgood)) / (1 - objective)

  i.e. how many times faster than "exactly exhausting the budget" the
  budget is being spent.  A breach fires only when BOTH the fast window
  (catches a spike) and the slow window (proves it is sustained) exceed
  their thresholds — the standard shape that alerts fast on real
  incidents without paging on one bad record."""
  name: str
  bad: str
  good: str
  objective: float = 0.99
  fast_window: int = 5
  slow_window: int = 20
  fast_burn: float = 10.0
  slow_burn: float = 2.0

  def __post_init__(self):
    if not 0.0 <= self.objective < 1.0:
      raise ValueError(f"objective must be in [0, 1): {self.objective}")
    if not 1 <= self.fast_window <= self.slow_window:
      raise ValueError(
          f"need 1 <= fast_window <= slow_window; got "
          f"{self.fast_window}, {self.slow_window}")
    if self.fast_burn <= 0 or self.slow_burn <= 0:
      raise ValueError("burn thresholds must be > 0")

  def burn(self, history: Deque[Tuple[float, float]], window: int
           ) -> Optional[float]:
    """Burn rate over the last ``window`` record intervals, or None when
    the window has not FILLED yet or saw no traffic (no verdict — a
    partial slow window would collapse onto the fast one and let a
    single startup blip page, defeating the both-windows debounce; an
    idle fleet is not healthy OR unhealthy, it is silent)."""
    if len(history) < window + 1:
      return None
    lo = history[len(history) - 1 - window]
    hi = history[-1]
    d_bad = hi[0] - lo[0]
    d_total = d_bad + (hi[1] - lo[1])
    if d_total <= 0:
      return None
    return (d_bad / d_total) / max(1.0 - self.objective, 1e-9)


def _match_keys(metric: str, record: Mapping[str, Any]) -> List[str]:
  """Keys of ``record`` the rule's metric selector matches: exact key
  when the selector contains a ``/``, else any key whose last path
  segment equals it."""
  if "/" in metric:
    return [metric] if metric in record else []
  return [k for k in record if k.rsplit("/", 1)[-1] == metric]


class DiagnosticCapture:
  """Bounded, rate-limited diagnostic-bundle writer (module docstring).

  A bundle is a timestamped directory under ``out_dir``::

      bundle_<unix>_<seq>_<reason>/
        meta.json       # reason, step, wall time, trigger payload
        trace.json      # the tracer ring's tail (last `ring_tail`
                        #   events + track metadata; Perfetto-loadable,
                        #   but truncated spans are expected — it is a
                        #   flight recording, not a validated export)
        registry.json   # MetricRegistry.latest() snapshot (JSON-safe)
        state.json      # engine/scheduler context-provider summaries

  Staged as ``<bundle>.tmp`` then atomically renamed (the saver's
  crash-consistency rule), so a bundle that exists is complete.
  ``min_interval_s`` rate-limits writes and ``limit`` bounds retained
  bundles (oldest deleted first) — a flapping fleet breaching every
  sweep costs one bundle per interval and bounded disk, never a full
  volume.  Thread-safe: the watchdog's monitor thread captures
  concurrently with the host loop."""

  def __init__(self, out_dir: str, limit: int = 8,
               min_interval_s: float = 30.0, ring_tail: int = 2048,
               clock: Callable[[], float] = vclock.monotonic):
    if limit < 1:
      raise ValueError(f"limit must be >= 1: {limit}")
    if min_interval_s < 0 or ring_tail < 1:
      raise ValueError("min_interval_s must be >= 0 and ring_tail >= 1")
    self.out_dir = out_dir
    self.limit = limit
    self.min_interval_s = min_interval_s
    self.ring_tail = ring_tail
    self.clock = clock
    self.captures = 0
    self.suppressed = 0
    self._last: Optional[float] = None
    self._seq = 0
    self._lock = threading.Lock()

  @staticmethod
  def _json_safe(value: Any) -> Any:
    if value is None or isinstance(value, (bool, int, float, str)):
      return value
    if isinstance(value, Mapping):
      return {str(k): DiagnosticCapture._json_safe(v)
              for k, v in value.items()}
    if isinstance(value, (list, tuple)):
      return [DiagnosticCapture._json_safe(v) for v in value]
    try:
      if getattr(value, "shape", None) == ():
        return float(value)  # host/np scalar; rare path, sync is fine
      if hasattr(value, "shape"):
        return {"shape": list(value.shape),
                "dtype": str(getattr(value, "dtype", "?"))}
    except Exception:  # noqa: BLE001 — diagnostics must not raise
      pass
    return repr(value)[:200]

  def capture(self, reason: str, step: Optional[int] = None,
              payload: Optional[Dict[str, Any]] = None,
              context: Optional[Dict[str, Any]] = None,
              tracer=None, registry=None) -> Optional[str]:
    """Write one bundle; returns its path, or None when rate-limited.
    Never raises — a broken disk must not take the serving loop down
    with it (the capture is the diagnosis, not the patient)."""
    with self._lock:
      now = self.clock()
      if self._last is not None and now - self._last < self.min_interval_s:
        self.suppressed += 1
        return None
      self._last = now
      self._seq += 1
      seq = self._seq
    try:
      return self._write(reason, seq, step, payload, context, tracer,
                         registry)
    except Exception as e:  # noqa: BLE001
      get_logger().warning(
          "diagnostic capture for %r failed (%s: %s); serving continues",
          reason, type(e).__name__, e)
      return None

  def _write(self, reason, seq, step, payload, context, tracer,
             registry) -> str:
    slug = re.sub(r"[^A-Za-z0-9_-]+", "_", reason)[:48] or "anomaly"
    name = f"bundle_{int(vclock.wall())}_{seq:04d}_{slug}"
    final = os.path.join(self.out_dir, name)
    tmp = final + ".tmp"
    os.makedirs(tmp, exist_ok=True)

    def dump(fname, obj):
      with open(os.path.join(tmp, fname), "w") as f:
        json.dump(self._json_safe(obj), f, indent=1)

    dump("meta.json", {
        "reason": reason, "step": step, "time": vclock.wall(),
        "payload": payload or {}})
    if tracer is not None and getattr(tracer, "enabled", False):
      events = tracer.events()
      meta = [e for e in events if e.get("ph") == "M"]
      tail = [e for e in events if e.get("ph") != "M"][-self.ring_tail:]
      with open(os.path.join(tmp, "trace.json"), "w") as f:
        json.dump({"traceEvents": meta + tail,
                   "otherData": {"note": "ring tail at capture time; "
                                         "truncated spans expected"}},
                  f)
    if registry is not None:
      dump("registry.json", registry.latest())
    if context:
      dump("state.json", context)
    os.replace(tmp, final)
    self.captures += 1
    self._enforce_retention()
    get_logger().warning("diagnostic bundle captured: %s (reason %s)",
                         final, reason)
    return final

  def _enforce_retention(self):
    try:
      bundles = sorted(
          d for d in os.listdir(self.out_dir)
          if d.startswith("bundle_") and not d.endswith(".tmp"))
    except OSError:
      return
    for stale in bundles[:-self.limit] if len(bundles) > self.limit else []:
      shutil.rmtree(os.path.join(self.out_dir, stale),
                    ignore_errors=True)


class SLOMonitor:
  """Declarative SLO evaluation over registry records (module
  docstring).

  Usage::

      monitor = SLOMonitor([SLORule("ttft_p99", "ttft_p99_s",
                                    "<=", 0.5)],
                           events_path="slo_events.jsonl")
      registry.add_sink_once(monitor)   # records now flow through
      monitor.observe(step, {"serving/fleet/ttft_p99_s": 0.7})  # direct

  Breach state is per (rule, matched key): a fleet-level TTFT breach
  and a single replica's are separate streams with separate recovery.
  ``note_event`` injects first-class breaches that do not come from a
  record (the compile sentinel's recompile, the watchdog's hang).
  """

  def __init__(self, rules: Optional[List[Any]] = None,
               events_path: str = "",
               capture: Optional[DiagnosticCapture] = None,
               wall_clock: Callable[[], float] = vclock.wall,
               history_limit: int = 1024):
    self.rules = list(rules or ())
    names = [r.name for r in self.rules]
    if len(names) != len(set(names)):
      raise ValueError(f"duplicate SLO rule names: {sorted(names)}")
    self.events_path = events_path
    self.capture = capture
    self.wall_clock = wall_clock
    self.breaches = 0          # breach transitions + injected events
    self.recoveries = 0
    self.actuations = 0        # note_actuation records (actuator layer)
    self.listener_errors = 0   # raising listener callbacks, cumulative
    # (rule_name, key) -> {"breached": bool, "streak": int, "hist": deque}
    self._state: Dict[Tuple[str, str], Dict[str, Any]] = {}
    self.events: Deque[Dict[str, Any]] = deque(maxlen=history_limit)
    self._file = None
    self._listeners: List[Callable[[], Optional[Callable]]] = []
    self._context_providers: List[Callable[[], Optional[Callable]]] = []
    self._lock = threading.Lock()
    self._registry = None      # last attached registry, for bundles

  # ------------------------------------------------------------ wiring

  def attach(self, registry) -> None:
    """Route a registry's records through this monitor (idempotent) and
    remember it as the bundle snapshot source."""
    if registry is None:
      return
    registry.add_sink_once(self)
    self._registry = registry

  def add_listener(self, fn: Callable[[str, Dict[str, Any]], None],
                   weak: bool = False) -> None:
    """Subscribe ``fn(rule_name, payload)`` to every breach event.
    ``weak=True`` holds the bound method weakly (an engine subscribing
    must stay collectible — the monitor is ambient and outlives it).

    Listener failures are ISOLATED: a raising callback is caught,
    logged once per listener, counted (:attr:`listener_errors`, plus
    the ``slo/listener_errors`` counter track), and never breaks
    monitoring, the caller's step, or sibling listeners."""
    self._listeners.append({
        "ref": weakref.WeakMethod(fn) if weak else (lambda _f=fn: _f),
        "logged": False})

  def add_context_provider(self, fn: Callable[[], Dict[str, Any]],
                           weak: bool = True) -> None:
    """Register a state-summary callable merged into diagnostic bundles
    (the engine's scheduler/allocator summary).  Weak by default for
    the same lifetime reason as :meth:`add_listener`."""
    self._context_providers.append(
        weakref.WeakMethod(fn) if weak else (lambda _f=fn: _f))

  def _collect(self, refs) -> List[Callable]:
    alive, out = [], []
    for ref in refs:
      fn = ref()
      if fn is not None:
        alive.append(ref)
        out.append(fn)
    refs[:] = alive
    return out

  def status(self) -> Dict[str, str]:
    """Current per-stream state: ``{"rule@key": "breach"|"ok"}``."""
    return {f"{name}@{key}": ("breach" if st["breached"] else "ok")
            for (name, key), st in self._state.items()}

  def breached_streams(self, scope: Optional[str] = None
                       ) -> List[Tuple[str, str]]:
    """Currently-breached ``(rule_name, metric_key)`` streams — the
    live-pressure view actuators poll between steps (a breach EVENT
    fires only on the transition; sustained overload looks like a
    stream that stays breached, serving/autotune.py).

    ``scope`` restricts the view to streams whose metric key lives
    under that namespace prefix (``key == scope`` or starts with
    ``scope + "/"``) — how the rollout controller watches ONLY the
    canary's per-version streams (``serving/fleet/v<N>/...``) while the
    fleet-wide streams keep feeding the autoscaler."""
    out = [(name, key) for (name, key), st in self._state.items()
           if st["breached"]]
    if scope is not None:
      out = [(name, key) for name, key in out
             if key == scope or key.startswith(scope + "/")]
    return out

  def breached_stream_obs(self) -> Dict[Tuple[str, str], int]:
    """Observation counts for the currently-breached streams: how many
    records each has EVER evaluated.  Actuators distinguish a live
    sustained breach (records keep flowing, the count keeps growing —
    hold/escalate mitigation) from a stale wedged one (an idle
    engine's burn stream renders no verdict and the count freezes —
    release mitigation) by watching this grow, since neither case
    re-fires the transition event."""
    return {(name, key): st.get("obs", 0)
            for (name, key), st in self._state.items()
            if st["breached"]}

  # --------------------------------------------------------- evaluation

  def observe(self, step: int, record: Mapping[str, Any]) -> None:
    """Evaluate every rule against one namespaced record.  Cheap: a few
    string/float comparisons per rule; device arrays are skipped (see
    module docstring)."""
    for rule in self.rules:
      if isinstance(rule, BurnRateRule):
        self._observe_burn(rule, step, record)
      else:
        self._observe_threshold(rule, step, record)

  # Registry-sink surface: attaching the monitor via add_sink_once makes
  # every publisher's records flow through observe with no new plumbing.
  def write(self, step: int, record: Mapping[str, Any]) -> None:
    self.observe(step, record)

  def flush(self) -> None:
    with self._lock:
      if self._file is not None:
        self._file.flush()

  def close(self) -> None:
    with self._lock:
      if self._file is not None:
        self._file.close()
        self._file = None

  def _observe_threshold(self, rule: SLORule, step: int,
                         record: Mapping[str, Any]) -> None:
    for key in _match_keys(rule.metric, record):
      value = record[key]
      if not _is_scalar(value):
        continue
      value = float(value)
      st = self._state.setdefault(
          (rule.name, key), {"breached": False, "streak": 0, "obs": 0})
      st["obs"] = st.get("obs", 0) + 1
      if rule.healthy(value):
        st["streak"] = 0
        if st["breached"]:
          st["breached"] = False
          self.recoveries += 1
          self._emit("recover", rule.name, step, {
              "metric": key, "value": value, "target": rule.target,
              "op": rule.op})
        continue
      st["streak"] += 1
      if not st["breached"] and st["streak"] >= rule.for_records:
        st["breached"] = True
        self._breach(rule.name, step, {
            "metric": key, "value": value, "target": rule.target,
            "op": rule.op, "for_records": rule.for_records})

  def _observe_burn(self, rule: BurnRateRule, step: int,
                    record: Mapping[str, Any]) -> None:
    for bad_key in _match_keys(rule.bad, record):
      prefix = bad_key.rsplit("/", 1)[0] if "/" in bad_key else ""
      good_key = (f"{prefix}/{rule.good}" if prefix else rule.good) \
          if "/" not in rule.good else rule.good
      if good_key not in record:
        continue
      bad_v, good_v = record[bad_key], record[good_key]
      if not (_is_scalar(bad_v) and _is_scalar(good_v)):
        continue
      st = self._state.setdefault(
          (rule.name, bad_key),
          {"breached": False, "streak": 0, "obs": 0,
           "hist": deque(maxlen=rule.slow_window + 1)})
      st["obs"] = st.get("obs", 0) + 1
      st["hist"].append((float(bad_v), float(good_v)))
      fast = rule.burn(st["hist"], rule.fast_window)
      slow = rule.burn(st["hist"], rule.slow_window)
      if fast is None or slow is None:
        continue
      burning = fast >= rule.fast_burn and slow >= rule.slow_burn
      if burning and not st["breached"]:
        st["breached"] = True
        self._breach(rule.name, step, {
            "metric": bad_key, "fast_burn": fast, "slow_burn": slow,
            "fast_threshold": rule.fast_burn,
            "slow_threshold": rule.slow_burn,
            "objective": rule.objective})
      elif st["breached"] and fast < rule.fast_burn:
        # Recovery keys off the fast window alone: once the recent burn
        # is back under budget the incident is over — waiting for the
        # slow window to drain would hold the alert long after the fix.
        st["breached"] = False
        self.recoveries += 1
        self._emit("recover", rule.name, step, {
            "metric": bad_key, "fast_burn": fast, "slow_burn": slow})

  # ----------------------------------------------------------- emission

  def note_event(self, name: str, payload: Optional[Dict[str, Any]] = None,
                 step: Optional[int] = None,
                 context: Optional[Dict[str, Any]] = None) -> None:
    """Inject a first-class breach that does not come from a record —
    the compile sentinel's ``unexpected_recompile``, the watchdog's
    ``watchdog_timeout``.  Same three-way emission as a rule breach."""
    self._breach(name, step, dict(payload or {}), context=context)

  def note_actuation(self, name: str,
                     payload: Optional[Dict[str, Any]] = None,
                     step: Optional[int] = None) -> None:
    """Record one self-healing actuation (serving/autotune.py moved a
    knob, serving/autoscale.py resized the replica set) as an
    ``slo_events.jsonl`` line + ``slo/actuation`` trace instant — the
    stream ``report.py --follow`` renders so operators watch the loop
    close.  NOT a breach: no capture, no listener fan-out (an actuator
    reacting to its own actuation would be a feedback loop), and the
    breach counter is untouched."""
    self.actuations += 1
    self._emit("actuation", name, step, dict(payload or {}))

  def _breach(self, name: str, step: Optional[int],
              payload: Dict[str, Any],
              context: Optional[Dict[str, Any]] = None) -> None:
    self.breaches += 1
    # Capture FIRST so the one listener notification (and the jsonl
    # line) already carries the bundle path — notifying before and
    # again after would double-trigger any subscriber that acts per
    # callback (remediation hooks, autotuners).
    if self.capture is not None:
      ctx = dict(context or {})
      for fn in self._collect(self._context_providers):
        try:
          ctx.update(fn() or {})
        except Exception:  # noqa: BLE001
          pass
      from easyparallellibrary_tpu.observability import trace as trace_lib
      bundle = self.capture.capture(
          name, step=step, payload=dict(payload), context=ctx,
          tracer=trace_lib.get_tracer(), registry=self._registry)
      if bundle is not None:
        payload["bundle"] = bundle
    self._emit("breach", name, step, payload)
    self._notify(name, payload)

  def _notify(self, name: str, payload: Dict[str, Any]) -> None:
    """Deliver one breach to every live listener, isolating failures:
    a raising subscriber is caught (the monitor, the engine step and
    every SIBLING listener proceed), logged ONCE per listener (a
    listener broken in a loop must not flood the log), and counted —
    :attr:`listener_errors` plus a ``slo/listener_errors`` counter
    track, so a silently-broken actuator is still visible."""
    alive = []
    errors_before = self.listener_errors
    for entry in self._listeners:
      fn = entry["ref"]()
      if fn is None:
        continue
      alive.append(entry)
      try:
        fn(name, dict(payload))
      except Exception as e:  # noqa: BLE001 — a bad subscriber must not
        self.listener_errors += 1                 # wedge the monitor
        if not entry["logged"]:
          entry["logged"] = True
          get_logger().warning(
              "SLO breach listener %r failed (%s: %s); listener kept, "
              "logged once — see the slo/listener_errors counter",
              getattr(fn, "__qualname__", fn), type(e).__name__, e)
    self._listeners[:] = alive
    if self.listener_errors != errors_before:
      from easyparallellibrary_tpu.observability import trace as trace_lib
      tracer = trace_lib.get_tracer()
      if tracer.enabled:
        tracer.counter("slo/listener_errors", self.listener_errors)

  def _emit(self, event: str, name: str, step: Optional[int],
            payload: Dict[str, Any]) -> None:
    rec = {"time": self.wall_clock(), "event": event, "rule": name,
           "step": step, **payload}
    with self._lock:
      self.events.append(rec)
      if self.events_path:
        if self._file is None:
          parent = os.path.dirname(os.path.abspath(self.events_path))
          os.makedirs(parent, exist_ok=True)
          self._file = open(self.events_path, "a")
        self._file.write(json.dumps(rec) + "\n")
        self._file.flush()
    from easyparallellibrary_tpu.observability import trace as trace_lib
    tracer = trace_lib.get_tracer()
    if tracer.enabled:
      tracer.instant(f"slo/{event}", cat="slo", track="slo",
                     args={"rule": name, "step": step,
                           **{k: v for k, v in payload.items()
                              if isinstance(v, (int, float, str))}})
      if event == "breach":
        tracer.counter("slo/breaches", self.breaches)
    log = get_logger().warning if event == "breach" else get_logger().info
    log("SLO %s: %s %s", event, name,
        {k: v for k, v in payload.items() if k != "bundle"})


class CompileSentinel:
  """Cache-size watermark for one compiled twin (module docstring).

  ``cache_size_fn`` returns the jitted callable's compiled-program
  count (``jax.jit``'s ``_cache_size``; read through a thunk so chaos
  wrappers that replace the step function stay transparent).
  ``expected`` compiles are warmup (1 for every engine twin: shapes are
  static by construction); any growth beyond max(watermark, expected)
  fires ``on_recompile(label, cache_size, new_compiles, signature)``
  with the signature the caller attributes the recompile to.  The check
  is one host int compare per step — cheap enough to be always-on."""

  def __init__(self, label: str, cache_size_fn: Callable[[], int],
               expected: int = 1,
               on_recompile: Optional[List[Callable]] = None):
    if expected < 1:
      raise ValueError(f"expected must be >= 1: {expected}")
    self.label = label
    self.expected = expected
    self.on_recompile: List[Callable] = list(on_recompile or ())
    self.recompiles = 0
    self._cache_size_fn = cache_size_fn
    self._watermark = 0
    self._unreadable_logged = False

  def cache_size(self) -> Optional[int]:
    try:
      return int(self._cache_size_fn())
    except Exception as e:  # noqa: BLE001 — _cache_size is internal API
      if not self._unreadable_logged:
        self._unreadable_logged = True
        get_logger().warning(
            "compile sentinel %s cannot read the jit cache size (%s: "
            "%s); recompile detection disabled for this twin",
            self.label, type(e).__name__, e)
      return None

  def check(self, signature_fn: Optional[Callable[[], Any]] = None
            ) -> int:
    """Observe the current cache size; returns how many NEW unexpected
    compiles happened since the last check (0 on the healthy path).
    ``signature_fn`` is only invoked when a recompile is detected, so
    attribution costs nothing per step."""
    size = self.cache_size()
    if size is None:
      return 0
    baseline = max(self._watermark, self.expected)
    self._watermark = max(self._watermark, size)
    extra = size - baseline
    if extra <= 0:
      return 0
    self.recompiles += extra
    signature = None
    if signature_fn is not None:
      try:
        signature = signature_fn()
      except Exception:  # noqa: BLE001
        signature = "<signature unavailable>"
    get_logger().error(
        "compile sentinel %s: %d unexpected recompile(s) detected "
        "(cache size %d, expected %d) — signature: %s",
        self.label, extra, size, self.expected, signature)
    for fn in self.on_recompile:
      try:
        fn(self.label, size, extra, signature)
      except Exception as e:  # noqa: BLE001
        get_logger().warning("compile-sentinel subscriber failed "
                             "(%s: %s)", type(e).__name__, e)
    return extra


class BreachPressure:
  """Liveness poll over a monitor's breached streams — the one place
  the subtle actuator invariant lives (serving/autotune.py and
  serving/autoscale.py both ride it): a breach EVENT fires only on the
  transition, so sustained overload looks like a stream that stays
  breached, and the only way to tell a LIVE sustained breach (keep
  mitigating) from a stale wedged one (an idle engine's burn stream
  renders no verdict — release mitigation) is whether the breached
  streams' record counts are still growing.

  ``match(rule_name, metric_key)`` selects the streams this probe
  cares about.  :meth:`poll` returns ``(pressured, fresh)``:
  ``pressured`` while any matching stream is breached, ``fresh`` when
  any individual stream's observation count GREW (or a new breached
  stream appeared) since the last poll — the caller refreshes its own
  staleness clock (engine steps, wall time) on ``fresh``.  Freshness
  is judged PER STREAM, never on an aggregate: one stream recovering
  shrinks a sum without a single new record on the wedged survivors,
  and must not read as life."""

  def __init__(self, monitor: Optional[SLOMonitor],
               match: Callable[[str, str], bool]):
    self.monitor = monitor
    self.match = match
    self._counts: Dict[Tuple[str, str], int] = {}

  def poll(self) -> Tuple[bool, bool]:
    if self.monitor is None:
      return False, False
    current = {sk: count for sk, count
               in self.monitor.breached_stream_obs().items()
               if self.match(*sk)}
    if not current:
      self._counts = {}
      return False, False
    fresh = any(count > self._counts.get(sk, -1)
                for sk, count in current.items())
    self._counts = current
    return True, fresh


# ------------------------------------------------------ ambient monitor --

_monitor: Optional[SLOMonitor] = None
_auto_sig: Optional[Tuple] = None


def get_monitor() -> Optional[SLOMonitor]:
  """The ambient SLO monitor, or None when monitoring is off."""
  return _monitor


def install(monitor: Optional[SLOMonitor]) -> Optional[SLOMonitor]:
  """Pin an explicit monitor (None = uninstall); wins over config."""
  global _monitor, _auto_sig
  _monitor = monitor
  _auto_sig = None
  return monitor


def reset():
  """Drop any ambient monitor (tests)."""
  old = _monitor
  install(None)
  if old is not None:
    old.close()


def rules_from_config(slo_conf) -> List[Any]:
  """The declarative rule set the ``observability.slo.*`` knobs
  describe (docs/observability.md "SLO monitoring"); every rule uses
  bare-name metric matching so fleet, per-replica and bare-engine
  records all evaluate."""
  rules: List[Any] = []
  if slo_conf.ttft_p99_s > 0:
    rules.append(SLORule("ttft_p99", "ttft_p99_s", "<=",
                         slo_conf.ttft_p99_s))
  if slo_conf.itl_p99_s > 0:
    rules.append(SLORule("itl_p99", "itl_p99_s", "<=",
                         slo_conf.itl_p99_s))
  if slo_conf.shed_objective > 0:
    rules.append(BurnRateRule(
        "shed_burn", bad="shed", good="finished_requests",
        objective=slo_conf.shed_objective,
        fast_window=slo_conf.fast_window,
        slow_window=slo_conf.slow_window,
        fast_burn=slo_conf.fast_burn, slow_burn=slo_conf.slow_burn))
  if slo_conf.replicas_down:
    # Fleet availability: any replica down is a breach window — the
    # serving/fleet/* rollup carries the per-state counts.
    rules.append(SLORule("replica_down", "replicas_down", "<=", 0.0))
  if slo_conf.hbm_frac > 0:
    # Device-memory headroom: the introspector's HBM gauges
    # (observability/device.py) publish hbm_frac only on backends whose
    # memory_stats() reports a limit, so the rule is inert elsewhere.
    rules.append(SLORule("hbm_high", "hbm_frac", "<=",
                         slo_conf.hbm_frac))
  return rules


def ensure_configured(config=None) -> Optional[SLOMonitor]:
  """Reconcile the ambient monitor with ``config.observability.slo``
  (the active Env's config when None) — the tracer's
  ``ensure_configured`` contract, including the rule that only the
  AMBIENT Env config may tear down or rebuild an auto-built monitor
  (rebuilding drops breach state and closes the events file; a
  component's explicit config can enable monitoring but never discard
  the run's)."""
  global _monitor, _auto_sig
  if _monitor is not None and _auto_sig is None:
    return _monitor  # explicit install wins
  from easyparallellibrary_tpu.env import Env
  if config is None:
    config = Env.get().config
    ambient = True
  else:
    ambient = config is Env.get().config
  slo = config.observability.slo
  if not slo.enabled:
    if _auto_sig is not None and ambient:
      _monitor.close()
      _monitor = None
      _auto_sig = None
    return _monitor
  sig = (slo.events_path, slo.ttft_p99_s, slo.itl_p99_s,
         slo.shed_objective, slo.fast_window, slo.slow_window,
         slo.fast_burn, slo.slow_burn, slo.replicas_down, slo.hbm_frac,
         slo.capture_dir, slo.capture_limit, slo.capture_min_interval_s,
         slo.capture_ring_tail)
  if _monitor is not None and (_auto_sig == sig or not ambient):
    return _monitor
  if _monitor is not None:
    _monitor.close()
  capture = None
  if slo.capture_dir:
    capture = DiagnosticCapture(
        slo.capture_dir, limit=slo.capture_limit,
        min_interval_s=slo.capture_min_interval_s,
        ring_tail=slo.capture_ring_tail)
  _monitor = SLOMonitor(rules_from_config(slo),
                        events_path=slo.events_path, capture=capture)
  _auto_sig = sig
  get_logger().info(
      "SLO monitor: %d rule(s) [%s], events -> %s, deep capture %s",
      len(_monitor.rules),
      ", ".join(r.name for r in _monitor.rules),
      slo.events_path or "(memory only)",
      f"-> {slo.capture_dir}" if capture else "off")
  return _monitor
