"""Latency-breakdown summary over an exported trace.

``python -m easyparallellibrary_tpu.observability.report <trace.json>``
prints, without leaving the terminal for Perfetto:

* a **span table** — per span name: count, total/mean/p50/p99 duration
  and share of the trace's wall clock (where did the run's time go);
* **request timelines** — per serving request: queue wait, prefill
  time/chunks, decode steps, speculation drafted/accepted, TTFT,
  total latency and finish reason (where did THIS request's latency
  go);
* with ``--metrics <metrics.jsonl>``, the **fleet rollup** — the last
  ``serving/fleet/*`` record a multi-replica Router published through
  the registry (tokens/s summed, merged TTFT/ITL percentiles,
  shed/failover counters, replica state counts; docs/serving.md
  "Multi-replica serving").

Reads the Chrome-trace JSON the tracer exports (observability/trace.py)
— and nothing else; the report is a pure function of the artifact, so
it works on traces mailed in from another machine.  Unmatched B/E
events (a ring buffer that wrapped mid-span) are skipped and counted
rather than fatal — post-mortems read partial traces.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from easyparallellibrary_tpu.profiler.serving import percentile


def load_events(path: str) -> List[Dict[str, Any]]:
  with open(path) as f:
    doc = json.load(f)
  return doc["traceEvents"] if isinstance(doc, dict) else doc


def pair_spans(events: List[Dict[str, Any]]
               ) -> Tuple[List[Dict[str, Any]], int]:
  """Match B/E pairs per (pid, tid) into completed spans
  ``{name, cat, ts, dur, tid, args}``; returns (spans, unmatched)."""
  spans: List[Dict[str, Any]] = []
  unmatched = 0
  stacks: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
  for ev in sorted((e for e in events if e.get("ph") in ("B", "E")),
                   key=lambda e: e.get("ts", 0.0)):
    key = (ev.get("pid"), ev.get("tid"))
    stack = stacks.setdefault(key, [])
    if ev["ph"] == "B":
      stack.append(ev)
      continue
    if not stack or stack[-1]["name"] != ev.get("name", stack[-1]["name"]):
      unmatched += 1
      continue
    b = stack.pop()
    args = dict(b.get("args") or {})
    args.update(ev.get("args") or {})
    spans.append({"name": b["name"], "cat": b.get("cat", ""),
                  "ts": b["ts"], "dur": ev["ts"] - b["ts"],
                  "tid": key[1], "args": args})
  unmatched += sum(len(s) for s in stacks.values())
  return spans, unmatched


def span_table(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
  """Aggregate spans by name into count/total/mean/p50/p99 rows,
  sorted by total time descending."""
  by_name: Dict[str, List[float]] = {}
  for sp in spans:
    by_name.setdefault(sp["name"], []).append(sp["dur"])
  rows = []
  for name, durs in by_name.items():
    rows.append({
        "name": name, "count": len(durs), "total_us": sum(durs),
        "mean_us": sum(durs) / len(durs),
        "p50_us": percentile(durs, 50), "p99_us": percentile(durs, 99)})
  rows.sort(key=lambda r: -r["total_us"])
  return rows


def request_timelines(events: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
  """Per-request lifecycle rollup from the serving instrumentation:
  request spans (cat ``serving.request``), the prefill/decode/speculate
  chunk spans nested in them, and the submit/first_token instants —
  plus the resilience events (docs/robustness.md "Serving resilience"):
  per-uid requeue counts, and rows for requests that never reached a
  slot (shed at submit, expired or cancelled in the queue), whose whole
  story is an instant."""
  spans, _ = pair_spans(events)
  submits: Dict[str, float] = {}
  first_tokens: Dict[str, float] = {}
  requeues: Dict[str, int] = {}
  # Requests resolved without ever holding a slot: uid -> (ts, reason).
  unadmitted: Dict[str, Tuple[float, str]] = {}
  for ev in events:
    if ev.get("ph") != "i":
      continue
    uid = (ev.get("args") or {}).get("uid")
    if uid is None:
      continue
    uid = str(uid)
    name = ev.get("name")
    if name == "serving/submit":
      submits[uid] = ev["ts"]
    elif name == "serving/first_token":
      first_tokens[uid] = ev["ts"]
    elif name == "serving/requeue":
      requeues[uid] = requeues.get(uid, 0) + 1
    elif name == "serving/shed":
      unadmitted[uid] = (ev["ts"], "shed")
    elif name in ("serving/deadline", "serving/cancelled"):
      # Emitted only for queue-side retirement (args.where == "queue");
      # slot-side expiry/cancellation ends the request span instead.
      unadmitted[uid] = (ev["ts"], name.split("/", 1)[1])
  requests = []
  for req in (s for s in spans if s["cat"] == "serving.request"):
    uid = str(req["args"].get("uid", req["name"]))
    t0, t1 = req["ts"], req["ts"] + req["dur"]
    inner = [s for s in spans
             if s["tid"] == req["tid"] and s["name"] != req["name"]
             and t0 <= s["ts"] and s["ts"] + s["dur"] <= t1 + 1e-9]
    phase_us = {ph: sum(s["dur"] for s in inner if s["name"] == ph)
                for ph in ("prefill", "decode", "speculate")}
    drafted = sum(s["args"].get("drafted", 0) for s in inner
                  if s["name"] == "speculate")
    accepted = sum(s["args"].get("accepted", 0) for s in inner
                   if s["name"] == "speculate")
    # Paged engine: each per-step span carries the slot's block count
    # (engine._trace_slot_spans); the request's peak is its KV
    # footprint high-water mark in blocks.  0 on a contiguous engine.
    kv_blocks_peak = max(
        (s["args"].get("kv_blocks", 0) for s in inner), default=0)
    submit = submits.get(uid)
    ttft = first_tokens.get(uid)
    requests.append({
        "uid": uid,
        "queue_wait_us": (t0 - submit) if submit is not None else None,
        "admitted_ts_us": t0,
        "total_us": req["dur"],
        "ttft_us": (ttft - (submit if submit is not None else t0))
                   if ttft is not None else None,
        "prefill_us": phase_us["prefill"],
        "prefill_chunks": sum(1 for s in inner if s["name"] == "prefill"),
        "decode_steps": sum(1 for s in inner
                            if s["name"] in ("decode", "speculate")),
        "decode_us": phase_us["decode"] + phase_us["speculate"],
        "drafted": drafted, "accepted": accepted,
        "kv_blocks_peak": kv_blocks_peak,
        "new_tokens": req["args"].get("new_tokens"),
        "finish_reason": req["args"].get("finish_reason"),
        "requeues": requeues.get(uid, 0),
    })
  # A requeued request's queue-side resolution (expiry/cancel) — or a
  # shed — is an instant, not a span end; requests that DID end in a
  # slot already carry their final reason above.
  resolved_in_slot = {r["uid"] for r in requests
                      if r["finish_reason"] not in (None, "requeued")}
  for uid, (ts, reason) in unadmitted.items():
    if uid in resolved_in_slot:
      continue
    submit = submits.get(uid)
    requests.append({
        "uid": uid,
        "queue_wait_us": (ts - submit) if submit is not None else None,
        "admitted_ts_us": ts,
        "total_us": None, "ttft_us": None,
        "prefill_us": 0.0, "prefill_chunks": 0,
        "decode_steps": 0, "decode_us": 0.0,
        "drafted": 0, "accepted": 0, "kv_blocks_peak": 0,
        "new_tokens": None, "finish_reason": reason,
        "requeues": requeues.get(uid, 0),
    })
  requests.sort(key=lambda r: r["admitted_ts_us"])
  return requests


def _fmt_us(us: Optional[float]) -> str:
  if us is None:
    return "-"
  return f"{us / 1e3:.2f}ms" if us >= 1e3 else f"{us:.0f}us"


def fleet_rollup(metrics_path: str) -> Optional[Dict[str, Any]]:
  """The LAST ``serving/fleet/*`` record in a registry-written metrics
  JSONL (one ``{"step", "time", **namespaced_keys}`` object per line),
  with the namespace prefix stripped — or None when the file holds no
  fleet record.  Lenient to trailing partial lines (a live server's
  sink may be mid-write) — post-mortems read partial logs."""
  prefix = "serving/fleet/"
  last: Optional[Dict[str, Any]] = None
  try:
    with open(metrics_path) as f:
      for line in f:
        try:
          rec = json.loads(line)
        except ValueError:
          continue
        if not isinstance(rec, dict):
          continue  # a truncated line can still parse (e.g. a number)
        fleet = {k[len(prefix):]: v for k, v in rec.items()
                 if k.startswith(prefix)}
        if fleet:
          fleet["step"] = rec.get("step")
          last = fleet
  except OSError:
    return None
  return last


def format_fleet(fleet: Dict[str, Any]) -> str:
  """Render one fleet rollup as a compact block (keys grouped:
  throughput / latency / resolution / control plane)."""
  def g(key, default=0.0):
    return fleet.get(key, default)

  lines = [
      f"fleet rollup (step {fleet.get('step', '-')}): "
      f"{g('replicas'):.0f} replica(s) — "
      f"{g('replicas_healthy'):.0f} healthy, "
      f"{g('replicas_suspect'):.0f} suspect, "
      f"{g('replicas_down'):.0f} down, "
      f"{g('replicas_draining'):.0f} draining",
      f"  throughput: {g('tokens_per_s'):.1f} tok/s summed, "
      f"{g('finished_requests'):.0f} finished, "
      f"{g('generated_tokens'):.0f} tokens, "
      f"occupancy {g('slot_occupancy_mean'):.2f}",
      f"  latency:    ttft p50 {g('ttft_p50_s') * 1e3:.1f}ms "
      f"p99 {g('ttft_p99_s') * 1e3:.1f}ms, "
      f"itl p50 {g('itl_p50_s') * 1e3:.2f}ms "
      f"p99 {g('itl_p99_s') * 1e3:.2f}ms (merged raw samples)",
      f"  resolution: shed {g('shed'):.0f} (+{g('router_shed'):.0f} at "
      f"router), deadline {g('deadline_expired'):.0f}, "
      f"cancelled {g('cancelled'):.0f}, failed {g('failed'):.0f}",
      f"  control:    failovers {g('failovers'):.0f}, "
      f"migrated {g('migrated_requests'):.0f}, "
      f"probes {g('probes'):.0f}, parked {g('parked'):.0f}, "
      f"requeues {g('requeues'):.0f}, "
      f"preemptions {g('preemptions'):.0f} "
      f"(+{g('proactive_preemptions'):.0f} proactive)",
  ]
  return "\n".join(lines)


def format_report(events: List[Dict[str, Any]]) -> str:
  spans, unmatched = pair_spans(events)
  lines: List[str] = []
  wall = 0.0
  if spans:
    wall = max(s["ts"] + s["dur"] for s in spans) - \
        min(s["ts"] for s in spans)
  lines.append(f"{len(events)} events, {len(spans)} spans over "
               f"{_fmt_us(wall)} wall clock"
               + (f" ({unmatched} unmatched B/E skipped)"
                  if unmatched else ""))
  lines.append("")
  lines.append(f"{'span':<28}{'count':>7}{'total':>11}{'mean':>10}"
               f"{'p50':>10}{'p99':>10}{'share':>8}")
  for row in span_table(spans):
    share = row["total_us"] / wall if wall else 0.0
    lines.append(
        f"{row['name']:<28}{row['count']:>7}"
        f"{_fmt_us(row['total_us']):>11}{_fmt_us(row['mean_us']):>10}"
        f"{_fmt_us(row['p50_us']):>10}{_fmt_us(row['p99_us']):>10}"
        f"{share:>7.1%}")
  requests = request_timelines(events)
  if requests:
    lines.append("")
    # The blk column (peak KV blocks held) only appears when any request
    # actually ran paged — a contiguous-engine trace keeps its old shape.
    paged = any(r["kv_blocks_peak"] for r in requests)
    lines.append(f"{'request':<12}{'wait':>9}{'ttft':>10}{'prefill':>10}"
                 f"{'chunks':>7}{'decode':>10}{'steps':>6}{'drafted':>8}"
                 f"{'accepted':>9}{'rq':>4}"
                 + (f"{'blk':>5}" if paged else "")
                 + f"{'total':>10}  finish")
    for r in requests:
      lines.append(
          f"{r['uid']:<12}{_fmt_us(r['queue_wait_us']):>9}"
          f"{_fmt_us(r['ttft_us']):>10}{_fmt_us(r['prefill_us']):>10}"
          f"{r['prefill_chunks']:>7}{_fmt_us(r['decode_us']):>10}"
          f"{r['decode_steps']:>6}{r['drafted']:>8}{r['accepted']:>9}"
          f"{r['requeues']:>4}"
          + (f"{r['kv_blocks_peak']:>5}" if paged else "")
          + f"{_fmt_us(r['total_us']):>10}"
          f"  {r['finish_reason'] or '-'}")
  counters = sorted({e["name"] for e in events if e.get("ph") == "C"})
  if counters:
    lines.append("")
    lines.append("counter tracks: " + ", ".join(counters))
  return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      prog="python -m easyparallellibrary_tpu.observability.report",
      description="Latency-breakdown summary of an exported trace "
                  "(observability/trace.py JSON).")
  parser.add_argument("trace", help="path to the exported trace JSON")
  parser.add_argument(
      "--metrics", default=None,
      help="registry metrics JSONL; prints the last serving/fleet/* "
           "rollup a multi-replica Router published")
  args = parser.parse_args(argv)
  print(format_report(load_events(args.trace)))
  if args.metrics is not None:
    fleet = fleet_rollup(args.metrics)
    print()
    if fleet is None:
      print(f"no serving/fleet/* record in {args.metrics}")
    else:
      print(format_fleet(fleet))
  return 0


if __name__ == "__main__":
  sys.exit(main())
