"""Latency-breakdown summary over an exported trace.

``python -m easyparallellibrary_tpu.observability.report <trace.json>``
prints, without leaving the terminal for Perfetto:

* a **span table** — per span name: count, total/mean/p50/p99 duration
  and share of the trace's wall clock (where did the run's time go);
* **request timelines** — per serving request: queue wait, prefill
  time/chunks, decode steps, speculation drafted/accepted, TTFT,
  total latency and finish reason (where did THIS request's latency
  go); merged multi-process traces with front-door instrumentation
  add the hop decomposition — client-observed TTFT, ingress and wire
  columns (docs/observability.md "Distributed tracing");
* with ``--metrics <metrics.jsonl>``, the **fleet rollup** — the last
  ``serving/fleet/*`` record a multi-replica Router published through
  the registry (tokens/s summed, merged TTFT/ITL percentiles,
  shed/failover counters, replica state counts; docs/serving.md
  "Multi-replica serving");
* with ``--follow <metrics.jsonl>``, **tail mode** — re-render the
  fleet rollup and SLO status as records append, so a live
  ``make chaos-router`` run is watched AS the kill and failover happen
  instead of post-mortem.  ``--slo <slo_events.jsonl>`` adds the SLO
  monitor's breach/recovery stream (auto-detected when a sibling
  ``slo_events.jsonl`` exists); Ctrl-C exits cleanly.

Reads the Chrome-trace JSON the tracer exports (observability/trace.py)
— and nothing else; the report is a pure function of the artifact, so
it works on traces mailed in from another machine.  Unmatched B/E
events (a ring buffer that wrapped mid-span) are skipped and counted
rather than fatal — post-mortems read partial traces, and tail mode
reads mid-write files (partial trailing lines are left for the next
poll).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from easyparallellibrary_tpu.observability.registry import FLEET_NAMESPACE
from easyparallellibrary_tpu.profiler.serving import percentile


def load_events(path: str) -> List[Dict[str, Any]]:
  with open(path) as f:
    doc = json.load(f)
  return doc["traceEvents"] if isinstance(doc, dict) else doc


def pair_spans(events: List[Dict[str, Any]]
               ) -> Tuple[List[Dict[str, Any]], int]:
  """Match B/E pairs per (pid, tid) into completed spans
  ``{name, cat, ts, dur, pid, tid, args}``; returns (spans, unmatched).
  Merged multi-process traces (docs/observability.md "Distributed
  tracing") interleave pids, so the pid rides along — timeline
  containment checks must key on (pid, tid), not tid alone."""
  spans: List[Dict[str, Any]] = []
  unmatched = 0
  stacks: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
  for ev in sorted((e for e in events if e.get("ph") in ("B", "E")),
                   key=lambda e: e.get("ts", 0.0)):
    key = (ev.get("pid"), ev.get("tid"))
    stack = stacks.setdefault(key, [])
    if ev["ph"] == "B":
      stack.append(ev)
      continue
    if not stack or stack[-1]["name"] != ev.get("name", stack[-1]["name"]):
      unmatched += 1
      continue
    b = stack.pop()
    args = dict(b.get("args") or {})
    args.update(ev.get("args") or {})
    spans.append({"name": b["name"], "cat": b.get("cat", ""),
                  "ts": b["ts"], "dur": ev["ts"] - b["ts"],
                  "pid": key[0], "tid": key[1], "args": args})
  unmatched += sum(len(s) for s in stacks.values())
  return spans, unmatched


def span_table(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
  """Aggregate spans by name into count/total/mean/p50/p99 rows,
  sorted by total time descending."""
  by_name: Dict[str, List[float]] = {}
  for sp in spans:
    by_name.setdefault(sp["name"], []).append(sp["dur"])
  rows = []
  for name, durs in by_name.items():
    rows.append({
        "name": name, "count": len(durs), "total_us": sum(durs),
        "mean_us": sum(durs) / len(durs),
        "p50_us": percentile(durs, 50), "p99_us": percentile(durs, 99)})
  rows.sort(key=lambda r: -r["total_us"])
  return rows


def request_timelines(events: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
  """Per-request lifecycle rollup from the serving instrumentation:
  request spans (cat ``serving.request``), the prefill/decode/speculate
  chunk spans nested in them, and the submit/first_token instants —
  plus the resilience events (docs/robustness.md "Serving resilience"):
  per-uid requeue counts, and rows for requests that never reached a
  slot (shed at submit, expired or cancelled in the queue), whose whole
  story is an instant.

  On a merged multi-process trace with front-door instrumentation the
  rows also carry the hop decomposition (docs/observability.md
  "Distributed tracing"): ``ingress_us`` (front-door receipt to router
  submit), ``client_ttft_us`` (front-door receipt to first SSE byte —
  the latency the CLIENT observed) and ``wire_us`` (engine first token
  to first SSE byte: harvest-rebased wire + stream-delivery gap; small
  negatives are clock-offset noise and reported as-is)."""
  spans, _ = pair_spans(events)
  submits: Dict[str, float] = {}
  first_tokens: Dict[str, float] = {}
  fd_requests: Dict[str, float] = {}
  fd_first_bytes: Dict[str, float] = {}
  requeues: Dict[str, int] = {}
  # Requests resolved without ever holding a slot: uid -> (ts, reason).
  unadmitted: Dict[str, Tuple[float, str]] = {}
  for ev in events:
    if ev.get("ph") != "i":
      continue
    uid = (ev.get("args") or {}).get("uid")
    if uid is None:
      continue
    uid = str(uid)
    name = ev.get("name")
    if name == "serving/submit":
      submits[uid] = ev["ts"]
    elif name == "serving/first_token":
      first_tokens[uid] = ev["ts"]
    elif name == "serving/requeue":
      requeues[uid] = requeues.get(uid, 0) + 1
    elif name == "frontdoor/request":
      fd_requests[uid] = ev["ts"]
    elif name == "frontdoor/first_byte":
      fd_first_bytes[uid] = ev["ts"]
    elif name == "serving/shed":
      unadmitted[uid] = (ev["ts"], "shed")
    elif name in ("serving/deadline", "serving/cancelled"):
      # Emitted only for queue-side retirement (args.where == "queue");
      # slot-side expiry/cancellation ends the request span instead.
      unadmitted[uid] = (ev["ts"], name.split("/", 1)[1])
  requests = []
  for req in (s for s in spans if s["cat"] == "serving.request"):
    uid = str(req["args"].get("uid", req["name"]))
    t0, t1 = req["ts"], req["ts"] + req["dur"]
    inner = [s for s in spans
             if s["pid"] == req["pid"] and s["tid"] == req["tid"]
             and s["name"] != req["name"]
             and t0 <= s["ts"] and s["ts"] + s["dur"] <= t1 + 1e-9]
    phase_us = {ph: sum(s["dur"] for s in inner if s["name"] == ph)
                for ph in ("prefill", "decode", "speculate")}
    drafted = sum(s["args"].get("drafted", 0) for s in inner
                  if s["name"] == "speculate")
    accepted = sum(s["args"].get("accepted", 0) for s in inner
                   if s["name"] == "speculate")
    # Paged engine: each per-step span carries the slot's block count
    # (engine._trace_slot_spans); the request's peak is its KV
    # footprint high-water mark in blocks.  0 on a contiguous engine.
    kv_blocks_peak = max(
        (s["args"].get("kv_blocks", 0) for s in inner), default=0)
    submit = submits.get(uid)
    ttft = first_tokens.get(uid)
    fd_req = fd_requests.get(uid)
    fd_byte = fd_first_bytes.get(uid)
    requests.append({
        "uid": uid,
        "queue_wait_us": (t0 - submit) if submit is not None else None,
        "ingress_us": (submit - fd_req)
                      if None not in (submit, fd_req) else None,
        "client_ttft_us": (fd_byte - fd_req)
                          if None not in (fd_byte, fd_req) else None,
        "wire_us": (fd_byte - first_tokens[uid])
                   if fd_byte is not None and uid in first_tokens
                   else None,
        "admitted_ts_us": t0,
        "total_us": req["dur"],
        "ttft_us": (ttft - (submit if submit is not None else t0))
                   if ttft is not None else None,
        "prefill_us": phase_us["prefill"],
        "prefill_chunks": sum(1 for s in inner if s["name"] == "prefill"),
        "decode_steps": sum(1 for s in inner
                            if s["name"] in ("decode", "speculate")),
        "decode_us": phase_us["decode"] + phase_us["speculate"],
        "drafted": drafted, "accepted": accepted,
        "kv_blocks_peak": kv_blocks_peak,
        # Blocks mapped by reference from the prefix cache at admission
        # (scheduler._admit stamps the request span).  0 without the
        # cache — the column stays hidden below.
        "blk_reused": req["args"].get("prefix_blocks_reused", 0),
        "new_tokens": req["args"].get("new_tokens"),
        "finish_reason": req["args"].get("finish_reason"),
        "requeues": requeues.get(uid, 0),
    })
  # A requeued request's queue-side resolution (expiry/cancel) — or a
  # shed — is an instant, not a span end; requests that DID end in a
  # slot already carry their final reason above.
  resolved_in_slot = {r["uid"] for r in requests
                      if r["finish_reason"] not in (None, "requeued")}
  for uid, (ts, reason) in unadmitted.items():
    if uid in resolved_in_slot:
      continue
    submit = submits.get(uid)
    fd_req = fd_requests.get(uid)
    requests.append({
        "uid": uid,
        "queue_wait_us": (ts - submit) if submit is not None else None,
        "ingress_us": (submit - fd_req)
                      if None not in (submit, fd_req) else None,
        "client_ttft_us": None, "wire_us": None,
        "admitted_ts_us": ts,
        "total_us": None, "ttft_us": None,
        "prefill_us": 0.0, "prefill_chunks": 0,
        "decode_steps": 0, "decode_us": 0.0,
        "drafted": 0, "accepted": 0, "kv_blocks_peak": 0,
        "blk_reused": 0,
        "new_tokens": None, "finish_reason": reason,
        "requeues": requeues.get(uid, 0),
    })
  requests.sort(key=lambda r: r["admitted_ts_us"])
  return requests


def _fmt_us(us: Optional[float]) -> str:
  if us is None:
    return "-"
  return f"{us / 1e3:.2f}ms" if us >= 1e3 else f"{us:.0f}us"


def fleet_rollup(metrics_path: str) -> Optional[Dict[str, Any]]:
  """The LAST ``serving/fleet/*`` record in a registry-written metrics
  JSONL (one ``{"step", "time", **namespaced_keys}`` object per line),
  with the namespace prefix stripped — or None when the file holds no
  fleet record.  Lenient to trailing partial lines (a live server's
  sink may be mid-write) — post-mortems read partial logs."""
  prefix = FLEET_NAMESPACE + "/"
  last: Optional[Dict[str, Any]] = None
  try:
    with open(metrics_path) as f:
      for line in f:
        try:
          rec = json.loads(line)
        except ValueError:
          continue
        if not isinstance(rec, dict):
          continue  # a truncated line can still parse (e.g. a number)
        fleet = {k[len(prefix):]: v for k, v in rec.items()
                 if k.startswith(prefix)}
        if fleet:
          fleet["step"] = rec.get("step")
          last = fleet
  except OSError:
    return None
  return last


def format_fleet(fleet: Dict[str, Any]) -> str:
  """Render one fleet rollup as a compact block (keys grouped:
  throughput / latency / resolution / control plane)."""
  def g(key, default=0.0):
    return fleet.get(key, default)

  lines = [
      f"fleet rollup (step {fleet.get('step', '-')}): "
      f"{g('replicas'):.0f} replica(s) — "
      f"{g('replicas_healthy'):.0f} healthy, "
      f"{g('replicas_suspect'):.0f} suspect, "
      f"{g('replicas_down'):.0f} down, "
      f"{g('replicas_draining'):.0f} draining",
      f"  throughput: {g('tokens_per_s'):.1f} tok/s summed, "
      f"{g('finished_requests'):.0f} finished, "
      f"{g('generated_tokens'):.0f} tokens, "
      f"occupancy {g('slot_occupancy_mean'):.2f}",
      f"  latency:    ttft p50 {g('ttft_p50_s') * 1e3:.1f}ms "
      f"p99 {g('ttft_p99_s') * 1e3:.1f}ms, "
      f"itl p50 {g('itl_p50_s') * 1e3:.2f}ms "
      f"p99 {g('itl_p99_s') * 1e3:.2f}ms (merged raw samples)",
      f"  resolution: shed {g('shed'):.0f} (+{g('router_shed'):.0f} at "
      f"router), deadline {g('deadline_expired'):.0f}, "
      f"cancelled {g('cancelled'):.0f}, failed {g('failed'):.0f}",
      f"  control:    failovers {g('failovers'):.0f}, "
      f"migrated {g('migrated_requests'):.0f}, "
      f"probes {g('probes'):.0f}, parked {g('parked'):.0f}, "
      f"scale-ups {g('scale_ups'):.0f} "
      f"(-{g('scale_downs'):.0f} down), "
      f"requeues {g('requeues'):.0f}, "
      f"preemptions {g('preemptions'):.0f} "
      f"(+{g('proactive_preemptions'):.0f} proactive), "
      f"recompiles {g('recompiles'):.0f}",
  ]
  return "\n".join(lines)


class FollowState:
  """Incremental tail over a registry metrics JSONL (and optionally the
  SLO monitor's ``slo_events.jsonl``): each :meth:`poll` consumes only
  the bytes appended since the last one — COMPLETE lines only, a
  partial trailing line (the sink may be mid-write) waits for the next
  poll — and returns a rendered status block when anything changed,
  else None.  Pure state machine, no sleeping: :func:`follow` owns the
  loop so tests can drive polls directly."""

  def __init__(self, metrics_path: str, slo_path: Optional[str] = None):
    self.metrics_path = metrics_path
    self.slo_path = slo_path
    self._offsets: Dict[str, int] = {}
    self.records = 0
    self.last_step: Optional[int] = None
    self.last_fleet: Optional[Dict[str, Any]] = None
    self.slo_breaches = 0
    # rule@metric -> last breach/recover event (current stream state;
    # bounded — a follow session is meant to run for days, so it keeps
    # state per RULE STREAM, never per event).
    self.slo_state: Dict[str, Dict[str, Any]] = {}
    # Self-healing actuations (serving/autotune.py / autoscale.py write
    # "actuation" events into the same stream): total count plus the
    # last few, so operators watch the control loop CLOSE — breach,
    # knob moved old->new, recovery — in one panel.  Bounded like
    # slo_state: a days-long follow keeps a tail, never every event.
    self.actuation_count = 0
    self.actuations: Deque[Dict[str, Any]] = deque(maxlen=4)
    self._polls = 0

  def _read_new_lines(self, path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
      with open(path, "rb") as f:
        offset = self._offsets.get(path, 0)
        size = os.fstat(f.fileno()).st_size
        if size < offset:
          # The file shrank: truncated or rotated under us.  Restart
          # from the top rather than seeking past EOF and going
          # permanently silent.
          offset = self._offsets[path] = 0
        f.seek(offset)
        chunk = f.read()
    except OSError:
      return out
    consumed = chunk.rfind(b"\n") + 1  # whole lines only
    if consumed <= 0:
      return out
    self._offsets[path] = self._offsets.get(path, 0) + consumed
    for line in chunk[:consumed].splitlines():
      try:
        rec = json.loads(line)
      except ValueError:
        continue
      if isinstance(rec, dict):
        out.append(rec)
    return out

  def poll(self) -> Optional[str]:
    changed = False
    prefix = FLEET_NAMESPACE + "/"
    for rec in self._read_new_lines(self.metrics_path):
      self.records += 1
      changed = True
      self.last_step = rec.get("step", self.last_step)
      fleet = {k[len(prefix):]: v for k, v in rec.items()
               if k.startswith(prefix)}
      if fleet:
        fleet["step"] = rec.get("step")
        self.last_fleet = fleet
    if self.slo_path:
      for ev in self._read_new_lines(self.slo_path):
        changed = True
        if ev.get("event") == "actuation":
          self.actuation_count += 1
          self.actuations.append(ev)
          continue
        self.slo_breaches += ev.get("event") == "breach"
        key = f"{ev.get('rule', '?')}@{ev.get('metric', '-')}"
        self.slo_state[key] = ev
    self._polls += 1
    if not changed and self._polls > 1:
      return None
    return self.render()

  def render(self) -> str:
    lines = [f"--- {time.strftime('%H:%M:%S')}  {self.records} "
             f"record(s), last step {self.last_step if self.last_step is not None else '-'}"]
    if self.last_fleet is not None:
      lines.append(format_fleet(self.last_fleet))
    else:
      lines.append("(no serving/fleet/* record yet)")
    if self.slo_path:
      if not self.slo_state:
        lines.append("SLO: no events")
      else:
        parts = []
        for key, ev in sorted(self.slo_state.items()):
          state = "BREACH" if ev.get("event") == "breach" else "ok"
          detail = ""
          if "value" in ev:
            detail = f" (value {ev['value']:.4g} vs {ev.get('target')})"
          elif "fast_burn" in ev:
            detail = f" (burn {ev['fast_burn']:.2g}x)"
          parts.append(f"{key}: {state}{detail}")
        lines.append(f"SLO [{self.slo_breaches} breach event(s)]: "
                     + "; ".join(parts))
      if self.actuation_count:
        lines.append(
            f"actuations [{self.actuation_count} total]: "
            + "; ".join(self._fmt_actuation(ev)
                        for ev in self.actuations))
    return "\n".join(lines)

  @staticmethod
  def _fmt_actuation(ev: Dict[str, Any]) -> str:
    """One actuation as ``actor: knob old->new (rule)`` — the knob
    moved, its old and new value, and the breach that triggered it."""
    actor = ev.get("actuator", ev.get("rule", "?"))
    rule = ev.get("rule", "?")
    knobs = ev.get("knobs") or {}
    moves = [f"{k} {v[0]}->{v[1]}" for k, v in sorted(knobs.items())
             if isinstance(v, (list, tuple)) and len(v) == 2]
    if not moves and "from_level" in ev:
      moves = [f"level {ev['from_level']}->{ev['to_level']}"]
    if not moves and "action" in ev:
      moves = [f"{ev['action']} replica {ev.get('replica', '?')}"]
    if not moves and "transition" in ev:
      # Blue/green rollout transitions (serving/rollout.py).
      moves = [f"{ev['transition']} v{ev.get('blue_version', '?')}"
               f"->v{ev.get('green_version', '?')}"]
    return f"{actor}: {', '.join(moves) or ev.get('action', '?')} " \
           f"(rule {rule})"


def follow(metrics_path: str, slo_path: Optional[str] = None,
           interval_s: float = 2.0, max_polls: int = 0,
           out=None) -> FollowState:
  """Tail loop over :class:`FollowState` (``report.py --follow``):
  re-print the fleet rollup + SLO status whenever records append.
  ``max_polls`` bounds the loop (0 = until Ctrl-C); returns the final
  state for callers that inspect it."""
  out = out if out is not None else print
  state = FollowState(metrics_path, slo_path)
  polls = 0
  try:
    while True:
      block = state.poll()
      if block is not None:
        out(block)
      polls += 1
      if max_polls and polls >= max_polls:
        break
      time.sleep(interval_s)
  except KeyboardInterrupt:
    pass
  return state


def format_report(events: List[Dict[str, Any]]) -> str:
  spans, unmatched = pair_spans(events)
  lines: List[str] = []
  wall = 0.0
  if spans:
    wall = max(s["ts"] + s["dur"] for s in spans) - \
        min(s["ts"] for s in spans)
  lines.append(f"{len(events)} events, {len(spans)} spans over "
               f"{_fmt_us(wall)} wall clock"
               + (f" ({unmatched} unmatched B/E skipped)"
                  if unmatched else ""))
  lines.append("")
  lines.append(f"{'span':<28}{'count':>7}{'total':>11}{'mean':>10}"
               f"{'p50':>10}{'p99':>10}{'share':>8}")
  for row in span_table(spans):
    share = row["total_us"] / wall if wall else 0.0
    lines.append(
        f"{row['name']:<28}{row['count']:>7}"
        f"{_fmt_us(row['total_us']):>11}{_fmt_us(row['mean_us']):>10}"
        f"{_fmt_us(row['p50_us']):>10}{_fmt_us(row['p99_us']):>10}"
        f"{share:>7.1%}")
  requests = request_timelines(events)
  if requests:
    lines.append("")
    # The blk column (peak KV blocks held) only appears when any request
    # actually ran paged — a contiguous-engine trace keeps its old shape.
    paged = any(r["kv_blocks_peak"] for r in requests)
    # Same shape-preservation rule for blk-reused: it only appears when
    # the prefix cache actually mapped shared blocks into some request.
    reuse = any(r["blk_reused"] for r in requests)
    # Hop columns (fd-ttft = client-observed TTFT, wire = engine first
    # token -> first SSE byte) only appear when the trace actually
    # carries front-door instants — an engine-only trace keeps its
    # old shape.
    hops = any(r["client_ttft_us"] is not None
               or r["ingress_us"] is not None for r in requests)
    lines.append(f"{'request':<12}{'wait':>9}{'ttft':>10}"
                 + (f"{'fd-ttft':>9}{'ingress':>9}{'wire':>9}"
                    if hops else "")
                 + f"{'prefill':>10}"
                 f"{'chunks':>7}{'decode':>10}{'steps':>6}{'drafted':>8}"
                 f"{'accepted':>9}{'rq':>4}"
                 + (f"{'blk':>5}" if paged else "")
                 + (f"{'blk-reused':>11}" if reuse else "")
                 + f"{'total':>10}  finish")
    for r in requests:
      lines.append(
          f"{r['uid']:<12}{_fmt_us(r['queue_wait_us']):>9}"
          f"{_fmt_us(r['ttft_us']):>10}"
          + (f"{_fmt_us(r['client_ttft_us']):>9}"
             f"{_fmt_us(r['ingress_us']):>9}"
             f"{_fmt_us(r['wire_us']):>9}" if hops else "")
          + f"{_fmt_us(r['prefill_us']):>10}"
          f"{r['prefill_chunks']:>7}{_fmt_us(r['decode_us']):>10}"
          f"{r['decode_steps']:>6}{r['drafted']:>8}{r['accepted']:>9}"
          f"{r['requeues']:>4}"
          + (f"{r['kv_blocks_peak']:>5}" if paged else "")
          + (f"{r['blk_reused']:>11}" if reuse else "")
          + f"{_fmt_us(r['total_us']):>10}"
          f"  {r['finish_reason'] or '-'}")
  counters = sorted({e["name"] for e in events if e.get("ph") == "C"})
  if counters:
    lines.append("")
    lines.append("counter tracks: " + ", ".join(counters))
  return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      prog="python -m easyparallellibrary_tpu.observability.report",
      description="Latency-breakdown summary of an exported trace "
                  "(observability/trace.py JSON).")
  parser.add_argument("trace", nargs="?", default=None,
                      help="path to the exported trace JSON (optional "
                           "with --follow)")
  parser.add_argument(
      "--metrics", default=None,
      help="registry metrics JSONL; prints the last serving/fleet/* "
           "rollup a multi-replica Router published")
  parser.add_argument(
      "--follow", default=None, metavar="METRICS_JSONL",
      help="tail a live registry metrics JSONL: re-render the fleet "
           "rollup and SLO status as records append (Ctrl-C to stop)")
  parser.add_argument(
      "--slo", default=None, metavar="SLO_EVENTS_JSONL",
      help="SLO monitor events JSONL for --follow (default: a sibling "
           "slo_events.jsonl of the followed file, when present)")
  parser.add_argument("--interval", type=float, default=2.0,
                      help="--follow poll interval in seconds")
  parser.add_argument("--max-polls", type=int, default=0,
                      help="stop --follow after N polls (0 = forever)")
  args = parser.parse_args(argv)
  if args.follow is not None:
    slo_path = args.slo
    if slo_path is None:
      sibling = os.path.join(os.path.dirname(os.path.abspath(
          args.follow)), "slo_events.jsonl")
      slo_path = sibling if os.path.exists(sibling) else None
    follow(args.follow, slo_path=slo_path, interval_s=args.interval,
           max_polls=args.max_polls)
    return 0
  if args.trace is None:
    parser.error("a trace path is required unless --follow is given")
  print(format_report(load_events(args.trace)))
  if args.metrics is not None:
    fleet = fleet_rollup(args.metrics)
    print()
    if fleet is None:
      print(f"no serving/fleet/* record in {args.metrics}")
    else:
      print(format_fleet(fleet))
  return 0


if __name__ == "__main__":
  sys.exit(main())
