"""Unified tracing & telemetry (docs/observability.md).

One event substrate for the whole runtime:

* :mod:`trace` — ring-buffered host-side span tracer with Chrome /
  Perfetto JSON export, ``jax.profiler`` capture attachment, and a
  cheap ambient ``get_tracer()`` the training loop, checkpoint path,
  resilience layer, and serving stack all record into;
* :mod:`registry` — the namespaced metric schema (``train/*``,
  ``serving/*``, ``comm/*``, ``resilience/*``) feeding the existing
  ``MetricsWriter`` / ``TensorBoardWriter`` sinks;
* :mod:`report` — ``python -m easyparallellibrary_tpu.observability
  .report <trace>`` latency-breakdown summaries, including per-request
  serving timelines (``--follow`` tails a live metrics JSONL);
* :mod:`slo` — declarative SLO rules over the registry records, the
  always-on compile sentinel, and anomaly-triggered diagnostic-bundle
  capture (``observability.slo.*``);
* :mod:`device` — device-truth introspection: compiled-twin cost cards
  (``Compiled.cost_analysis()``/``memory_analysis()`` at warmup),
  per-site measured collective bytes feeding the overlap planner, and
  HBM watermark gauges (``observability.device.*``);
* :mod:`perfgate` — ``make perf-gate``: cost-card and
  BENCH_EVIDENCE.json invariants pinned in ``perf_budget.json``,
  failing CI-style on regression.

Knobs: the ``observability.*`` config group (enabled / trace_path /
ring_capacity / sample_rate / metrics_jsonl / slo.* / device.*).
"""

from easyparallellibrary_tpu.observability.device import (
    CostCard, DeviceIntrospector, get_introspector,
)
from easyparallellibrary_tpu.observability.registry import (
    NAMESPACES, MetricRegistry, split_namespaces,
)
from easyparallellibrary_tpu.observability.slo import (
    BurnRateRule, CompileSentinel, DiagnosticCapture, SLOMonitor,
    SLORule, get_monitor,
)
from easyparallellibrary_tpu.observability.trace import (
    Tracer, ensure_configured, get_tracer, install, validate_trace,
)

__all__ = [
    "MetricRegistry", "NAMESPACES", "split_namespaces",
    "BurnRateRule", "CompileSentinel", "CostCard", "DeviceIntrospector",
    "DiagnosticCapture", "SLOMonitor", "SLORule", "get_introspector",
    "get_monitor", "Tracer", "ensure_configured", "get_tracer",
    "install", "validate_trace",
]
