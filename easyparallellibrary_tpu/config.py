"""Typed, frozen, environment-overridable configuration.

Mirrors the semantics of the reference's config system
(``epl/config.py``): a nested config object whose every leaf is

  * typed (value coerced / validated against the default's type),
  * settable via environment variable ``EPL_<CATEGORY>_<ATTRIBUTE>``
    (reference: epl/config.py:283-287),
  * overridable by a python dict passed to ``Config(...)`` with dict
    values taking precedence over env vars (reference: epl/config.py:289-299),
  * protected against typos — setting an unknown attribute raises
    (reference: epl/config.py:49-53).

The categories are re-designed for TPU: communication tuning maps to XLA
collective/fusion knobs, offload targets TPU host DRAM, and a new
``sequence`` category covers ring/Ulysses context parallelism which the
reference lacks (SURVEY §5.7).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Tuple

from easyparallellibrary_tpu import constants


def _coerce(value: Any, default: Any, where: str) -> Any:
  """Coerce `value` to the type of `default` (env strings included)."""
  if default is None:
    return value
  typ = type(default)
  if isinstance(value, typ) and not (typ is int and isinstance(value, bool)):
    # bool is a subclass of int; require exact semantics for int fields.
    if typ is bool or not isinstance(value, bool):
      return value
  if typ is bool:
    if isinstance(value, str):
      low = value.strip().lower()
      if low in ("true", "1", "yes", "on"):
        return True
      if low in ("false", "0", "no", "off", ""):
        return False
      raise ValueError(f"{where}: cannot parse bool from {value!r}")
    return bool(value)
  if typ is int:
    return int(value)
  if typ is float:
    return float(value)
  if typ is str:
    return str(value)
  if typ in (list, tuple):
    if isinstance(value, str):
      items = [v for v in value.split(",") if v != ""]
      return typ(items)
    return typ(value)
  raise ValueError(f"{where}: unsupported config type {typ}")


class _Category:
  """One nested config section; subclasses define `_fields`.

  `_fields` maps attribute name → default value.  Precedence when
  constructing: python override > env var > default.
  """

  _fields: Dict[str, Any] = {}
  _name = ""

  def __init__(self, overrides: Dict[str, Any]):
    # Sub-group fields are dotted ("speculative.enabled"); accept the
    # equivalent nested-dict override form {"speculative": {"enabled": 1}}.
    flat: Dict[str, Any] = {}
    for key, value in overrides.items():
      if isinstance(value, dict):
        for sub_key, sub_value in value.items():
          flat[f"{key}.{sub_key}"] = sub_value
      else:
        flat[key] = value
    overrides = flat
    unknown = set(overrides) - set(self._fields)
    if unknown:
      raise ValueError(
          f"Unknown config key(s) {sorted(unknown)} in category "
          f"'{self._name}'. Valid keys: {sorted(self._fields)}")
    for key, default in self._fields.items():
      env_key = (f"{constants.ENV_PREFIX}_{self._name.upper()}_"
                 f"{key.upper().replace('.', '_')}")
      value = default
      if env_key in os.environ:
        value = _coerce(os.environ[env_key], default, env_key)
      if key in overrides:
        value = _coerce(overrides[key], default, f"{self._name}.{key}")
      object.__setattr__(self, key, value)

  def __setattr__(self, key: str, value: Any):
    if key not in self._fields:
      raise AttributeError(
          f"Unknown config key '{self._name}.{key}'. "
          f"Valid keys: {sorted(self._fields)}")
    object.__setattr__(self, key, _coerce(value, self._fields[key],
                                          f"{self._name}.{key}"))

  def to_dict(self) -> Dict[str, Any]:
    return {k: getattr(self, k) for k in self._fields}

  def __repr__(self):
    inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in self._fields)
    return f"{type(self).__name__}({inner})"


class _SubGroup:
  """Attribute view over a category's dotted sub-group fields, so
  ``config.serving.speculative.enabled`` reads/writes the flat
  ``serving`` field ``"speculative.enabled"`` with the category's own
  coercion and unknown-key protection."""

  def __init__(self, category: _Category, prefix: str):
    object.__setattr__(self, "_category", category)
    object.__setattr__(self, "_prefix", prefix)

  def __getattr__(self, key: str) -> Any:
    return getattr(self._category, f"{self._prefix}.{key}")

  def __setattr__(self, key: str, value: Any):
    setattr(self._category, f"{self._prefix}.{key}", value)

  def __repr__(self):
    cat = self._category
    inner = ", ".join(
        f"{k.split('.', 1)[1]}={getattr(cat, k)!r}"
        for k in cat._fields if k.startswith(self._prefix + "."))
    return f"{type(cat).__name__}.{self._prefix}({inner})"


class AutoParallelConfig(_Category):
  """Automatic parallelism (reference: epl/config.py:55-60)."""
  _name = "auto"
  _fields = {
      # Enable automatic pipeline-stage partitioning of a block list.
      "auto_parallel": False,
      # Stage search policy: balance_param | balance_flops | repeated_layers
      # (reference policies: balance-op-num / repeated-layers / heuristic,
      # epl/parallel/planner.py:66-112).
      "stage_policy": "balance_param",
      # Auto tensor-split placement (the reference leaves this TODO,
      # epl/ir/graph.py:124): inside a `split` scope, auto-named sibling
      # Dense layers alternate column -> row (Megatron pairing), so
      # back-to-back projections chain through a sharded activation with
      # a single psum instead of an activation all-gather.  Explicit
      # `parallel=` always wins; numerics are unchanged either way
      # (GSPMD inserts whatever collectives the placement implies).
      # Opt-in: the pairing is positional, so NON-chained auto-named
      # siblings (parallel branches off one input) would trade their
      # free column placement for a psum, and row-mode kernels pad the
      # CONTRACTION dim, so uneven-dim checkpoints saved with the flag
      # off do not load with it on.  Annotate explicitly where it
      # matters.
      "tensor_split": False,
  }


class IOConfig(_Category):
  """Input pipeline (reference: epl/config.py:62-75)."""
  _name = "io"
  _fields = {
      # Shard input files/samples across data-parallel replicas
      # (reference io_slicing: epl/parallel/graph_editor.py:116-215).
      "slicing": False,
      # Allow replicas to get unequal file counts (reference:
      # fetch_slice_objects_proportion_to_local_num_replicas,
      # epl/parallel/graph_editor.py:787-854).
      "unbalanced_io_slicing": False,
      "drop_last_files": False,
      # Host-side prefetch depth for the native loader.
      "prefetch": 2,
      # Number of C++ reader threads (0 = python fallback).
      "num_threads": 4,
  }


class CommunicationConfig(_Category):
  """Collective tuning (reference: epl/config.py:77-101)."""
  _name = "communication"
  _fields = {
      # Number of overlapping "communicators" — on TPU this maps to how many
      # fusion buckets may be in flight concurrently (reference pool:
      # epl/communicators/communication_pool.py:26).
      "num_communicators": constants.DEFAULT_NUM_COMMUNICATORS,
      # Gradient-fusion bucket size in MB (reference: 32 MB,
      # epl/utils/constant.py:82).
      "fusion_threshold_mb": constants.DEFAULT_FUSION_BUCKET_MB,
      "max_splits": constants.DEFAULT_MAX_FUSION_SPLITS,
      # Compress gradients to bf16 for the all-reduce (reference fp16
      # compression + scale: epl/config.py:90-94).
      "compress_dtype": "",          # "" | "bf16" | "fp16"
      "compress_scale": 1.0,
      # Convert sparse grads (embedding scatter) to dense before reduction
      # (reference: sparse_as_dense, epl/parallel/hooks.py:161-167).
      "sparse_as_dense": False,
      # mean | sum across replicas (reference: gradients_reduce_method).
      "gradients_reduce_method": "mean",
      # Latency-hiding collective-matmul (communicators/overlap.py):
      # decompose all_gather->matmul / matmul->reduce_scatter adjacencies
      # into a compute-overlapped ppermute ring.  "auto" consults the
      # planner's analytic crossover (parallel/planner.py:
      # plan_collective_matmul) per site; "on"/"off" force it.  "off"
      # emits exactly the fused programs.
      "overlap": "auto",
      # Ring chunk count for the overlap path (0 = let the policy pick;
      # non-divisors of the axis size round down to the nearest divisor).
      "overlap_chunks": 0,
  }


class PipelineConfig(_Category):
  """Pipeline parallelism (reference: epl/config.py:103-114)."""
  _name = "pipeline"
  _fields = {
      "num_micro_batch": 1,
      # Number of stages when auto-partitioning (reference:
      # pipeline.num_stages consumed by planner, epl/parallel/hooks.py:129-135).
      "num_stages": 1,
      # Schedule policy (reference: epl/strategies/scheduler.py:120-124).
      "strategy": constants.SCHEDULE_PREFER_BACKWARD,
      # Interleaved (circular) pipeline: blocks per stage > 1.
      "num_stages_per_device": 1,
      # Pipeline engine: "" (= "vmap", the lockstep SPMD engines) or
      # "smap" (per-device stage programs under shard_map — real-branch
      # bubbles, stage-resident boundary layers; see
      # parallel/pipeline_smap.py).  The schedule policy above still
      # picks GPipe vs 1F1B order within either engine.
      "engine": "",
  }


class GradientCheckpointConfig(_Category):
  """Rematerialization (reference: epl/config.py:116-127)."""
  _name = "gradient_checkpoint"
  _fields = {
      # "" (off) | "collection" (user-tagged tensors) | "auto"
      "type": "",
      # Stop auto-GC at this taskgraph index (reference:
      # gradient_checkpoint.end_taskgraph).
      "end_taskgraph": -1,
      # Verify checkpointed grads against baseline (reference:
      # check_gradients, epl/runtime/gc/gradient_checkpoint.py:310-325).
      "check_gradients": False,
  }


class ZeroConfig(_Category):
  """Optimizer-state / gradient sharding (reference: epl/config.py:129-138)."""
  _name = "zero"
  _fields = {
      # "" (off) | "v0" (shard optimizer state) | "v1" (+ gradients)
      "level": "",
  }


class OffloadConfig(_Category):
  """Host-DRAM offload (reference: epl/config.py:140-146)."""
  _name = "offload"
  _fields = {
      # "" (off) | "v0" (params+opt state live in TPU host memory)
      "level": "",
  }


class AMPConfig(_Category):
  """Mixed precision (reference: epl/config.py:148-159)."""
  _name = "amp"
  _fields = {
      # "" (off) | "O1" (bf16 compute, fp32 params)
      "level": "",
      # Loss scale: "dynamic" | numeric string (bf16 on TPU usually
      # needs no scaling; kept for fp16 parity, reference
      # epl/runtime/amp/loss_scale.py).
      "loss_scale": "dynamic",
      # Compute dtype under O1: "bf16" (TPU-native) | "fp16".
      "compute_dtype": "bf16",
      "debug_log": False,
  }


class ClusterConfig(_Category):
  """Device layout (reference: epl/config.py:161-172)."""
  _name = "cluster"
  _fields = {
      # Reuse the same devices for split and replicate (DP×TP colocation;
      # reference: colocate_split_and_replicate, epl/config.py:170-171).
      "colocate_split_and_replicate": True,
      # Prefer packing mesh axes within a host before crossing hosts
      # (reference: device_place_prefer_intra_node, epl/cluster.py:137).
      "device_place_prefer_intra_node": True,
      # Explicit mesh shape override, e.g. "stage:2,data:2,model:2".
      "mesh_shape": "",
  }


class OptimizerConfig(_Category):
  """Optimizer apply tuning (reference: epl/config.py:174-179)."""
  _name = "optimizer"
  _fields = {
      # Split the weight-update into N serialized groups to bound peak
      # memory (reference: epl/runtime/optimizer_helper.py:75-128).
      "num_apply_group": 1,
  }


class SequenceConfig(_Category):
  """Sequence/context parallelism — new vs the reference (SURVEY §5.7)."""
  _name = "sequence"
  _fields = {
      # "" (off) | "ring" (ring attention over seq axis) | "ulysses"
      "parallelism": "",
      # Size of the seq mesh axis.
      "axis_size": 1,
      # Block size for blockwise/ring attention; 0 = one block per
      # seq-axis device (finer blocking is opt-in).
      "block_size": 0,
      # "flash" (default): shard_map ring with the Pallas flash kernel
      # per block and a KV-recommunicating backward — O(S/n) live memory
      # per device.  "einsum": global-array formulation (GSPMD-
      # composable; used automatically when num_blocks/block_size asks
      # for finer-than-device blocking).
      "ring_impl": "flash",
      # Same choice for Ulysses' head-sharded attention region: "flash"
      # runs the Pallas kernel per device (no [S, S] scores), "einsum"
      # keeps the pure sharding-constraint formulation.
      "ulysses_impl": "flash",
      # Causal ring block layout: "zigzag" (default — half-chunks i and
      # 2n-1-i on device i) balances the causal mask so every device
      # does uniform half-block work each step, cutting causal ring
      # compute ~2x; measured 1.84x fwd+bwd compiled (dense blocks, CPU
      # mesh) and 1.54x interpret-mode (benchmarks/ring_layout.py,
      # BASELINE.md round 4) — hence the default.  "contiguous" (block i
      # on device i) is the fallback; non-causal rings and odd
      # per-device splits automatically use contiguous behavior, and
      # flash blocks additionally require tileable half-blocks (dense
      # blocks have no tiling bound).  shard_map ring only.
      "ring_layout": "zigzag",
  }


class ResilienceConfig(_Category):
  """Failure recovery — crash-consistent checkpoints, anomaly sentinel,
  IO retry, step watchdog (docs/robustness.md).  New vs the reference,
  whose recovery story is kill-and-retry (SURVEY §5.3)."""
  _name = "resilience"
  _fields = {
      # Stage each checkpoint in a step_N.tmp dir with per-shard sha256
      # checksums, fsync, then atomically rename to commit — a crash
      # mid-save can never shadow the previous good checkpoint
      # (CheckFreq-style crash consistency, Mohan et al. FAST'21).
      "atomic_checkpoints": True,
      # Retain only the newest N committed checkpoints (0 = keep all).
      "keep_last": 0,
      # In-jit anomaly sentinel: finite-check loss/grads every step and
      # suppress the update via jnp.where on a bad step (no extra host
      # sync); consecutive bad steps are counted on-device and surfaced
      # as the `bad_steps` metric.  Implied on when max_bad_steps > 0.
      "sentinel": False,
      # After this many CONSECUTIVE non-finite steps, fit() rolls the
      # training state back to the newest valid checkpoint (0 = never;
      # skip-only).  The host checks the on-device counter once per
      # max_bad_steps window, so the guard stays sync-free per step.
      "max_bad_steps": 0,
      # What to do when max_bad_steps trips: True = restore the last
      # valid checkpoint and replay; False = raise (fail fast).
      "rollback": True,
      # Multiply the learning rate by this factor on each rollback
      # (1.0 = off).  Requires the optimizer to expose its LR via
      # optax.inject_hyperparams; logged and skipped otherwise.
      "rollback_lr_backoff": 1.0,
      # Transient-IO retries (checkpoint shard read/write, record-file
      # open, data-iterator next) and the initial backoff between them.
      "io_retries": 3,
      "io_retry_backoff_s": 0.05,
      # Log diagnostics when one fit() step (data fetch + dispatch)
      # exceeds this wall-clock deadline (0 = off).
      "step_timeout_s": 0.0,
  }


class ServingConfig(_Category):
  """Continuous-batching inference engine (serving/, docs/serving.md).
  New vs the reference, which is training-only (SURVEY §1)."""
  _name = "serving"
  _fields = {
      # Request slots in the preallocated KV cache = max concurrently
      # resident requests.  Cache bytes scale linearly
      # (serving.kv_cache.cache_bytes).
      "num_slots": 8,
      # Token width of the fused step: prefill streams through the
      # engine this many prompt tokens per iteration (Sarathi-style
      # chunked prefill); decode slots use 1 of the positions.  Larger =
      # fewer prefill iterations but more compute per step.
      "prefill_chunk": 16,
      # Per-iteration cap on scheduled prompt tokens across all slots
      # (admission control: decode latency vs prefill throughput).
      # 0 = uncapped.  Must be 0 or >= prefill_chunk.
      "prefill_token_budget": 0,
      # Cap on concurrently active requests (0 = num_slots).
      "max_batch": 0,
      # Default stop-token id for requests that don't set one (-1 = no
      # stop token; requests run to max_new_tokens).
      "stop_token": -1,
      # Donate the cache + cursor buffers to the jitted step (in-place
      # update; steady-state device allocation = one cache).  Turn off
      # only for debugging (keeps every step's input cache alive).
      "donate_cache": True,
      # Retention bound on resolved-request records (engine.finished
      # and the stats' finished per-request traces): keep only the most
      # recent N, evicting oldest-first.  0 = keep all (fine for
      # episodic runs; a long-running server otherwise grows host
      # memory linearly with requests served).  run()'s return value is
      # unaffected — it collects each call's retirements directly.
      "finished_limit": 0,
      # --- paged KV cache + token-flat fused step (serving/kv_cache.py,
      # docs/serving.md "Paged KV cache").  Off by default: the
      # contiguous slot layout stays byte-identical.  On, per-slot K/V
      # lives in fixed-size blocks behind a block table, the fused step
      # becomes a [token_budget] flat batch (decode cost scales with
      # scheduled tokens, not num_slots * chunk), and block-pool
      # exhaustion preempts the youngest lowest-priority slot instead of
      # capping admission at worst-case length.
      "paged.enabled": False,
      # Tokens per KV block.  Must divide max_seq_len (the paged
      # attend's reduction length must equal the oracle's cache length
      # for greedy bit-exactness — kv_cache.blocks_per_slot).
      "paged.block_size": 16,
      # Pool size in blocks (one is reserved as the null block).  0 =
      # auto: num_slots * max_seq_len / block_size + 1 — byte parity
      # with the contiguous layout.  Size it SMALLER (or raise
      # num_slots) to turn unused worst-case tail into extra concurrent
      # requests; must always hold at least one full-length request.
      "paged.num_blocks": 0,
      # Flat positions per fused step (the step's whole compute bill).
      # 0 = auto: num_slots + 2 * prefill_chunk.  Must at least cover
      # every decoding slot's one guaranteed token (>= the effective
      # batch cap); prefill chunks and speculative drafts share the
      # rest.
      "paged.token_budget": 0,
      # --- copy-on-write prefix caching over the paged pool
      # (serving/prefix_cache.py, docs/serving.md "Prefix caching").
      # Requires paged.enabled: admission walks a content-addressed
      # radix tree over full prompt blocks, maps matched blocks by
      # reference (refcount++, no device copy) and prefills only the
      # unmatched tail; retired requests' blocks stay pinned in the
      # tree so multi-turn follow-ups admit warm.  Off by default: with
      # it on, cached blocks keep kv_blocks_used nonzero between
      # requests by design.
      "prefix_cache.enabled": False,
      # Seconds an unused cached entry survives before the per-step
      # expiry sweep drops it (session persistence horizon).  0 = no
      # TTL: entries live until LRU/space eviction reclaims them.
      "prefix_cache.session_ttl_s": 0.0,
      # Cap on tree-resident blocks; beyond it the least-recent entries
      # are shed regardless of sharing.  0 = uncapped (the pool itself
      # still bounds residency: a dry pool evicts unmapped cached
      # blocks before preempting any live slot).
      "prefix_cache.max_cached_blocks": 0,
      # --- speculative decoding (serving/speculative/, docs/serving.md).
      # Draft k tokens per decode slot and verify them in the SAME fused
      # step (the drafts ride chunk positions plain decode wastes), so
      # an accepted draft is a free committed token.  Off by default:
      # speculation changes sampled streams (never their distribution).
      "speculative.enabled": False,
      # Draft tokens per decode slot per step; the fused step needs
      # prefill_chunk >= k + 1 (k drafts + the last committed token).
      "speculative.k": 4,
      # Drafter: "ngram" (prompt-lookup over each request's committed
      # history — no extra weights) or "draft_model" (a small GPT passed
      # to the engine / DraftModelDrafter.from_checkpoint).
      "speculative.kind": "ngram",
      # Longest/shortest history suffix the n-gram drafter matches.
      "speculative.ngram_max": 4,
      "speculative.ngram_min": 1,
      # --- serving resilience (serving/resilience.py,
      # docs/robustness.md "Serving resilience").  Master switch: off
      # keeps the engine's pre-resilience fused step and host loop
      # byte-identical.  On, the fused step gains an in-jit finiteness
      # verdict (no extra host sync — it rides the step's own token
      # fetch) and the host loop gains admission control, deadlines and
      # bad-step recovery.
      "resilience.enabled": False,
      # Bounded admission queue: submits beyond this many waiting
      # requests are shed (finish_reason "shed").  0 = unbounded (no
      # shedding, no queue-driven degradation).
      "resilience.queue_limit": 0,
      # Inter-token-latency SLO: a measured ITL (EWMA of decode-step
      # time, profiler/serving.py) above this forces at least the
      # spec_off degradation level.  0 = off.
      "resilience.itl_slo_s": 0.0,
      # Queue-depth fraction of queue_limit that enters degradation
      # level 1 (spec_off); level 2 enters halfway between it and full,
      # level 3 (shed) at full.  De-escalation at half the entry
      # threshold (hysteresis).
      "resilience.degrade_queue_frac": 0.5,
      # Bad-step recovery: in-place exact retries per slot before the
      # request is quarantined (requeued with its committed prefix),
      # and requeues per request before it is failed.
      "resilience.max_step_retries": 1,
      "resilience.max_requeues": 1,
      # Hung-step watchdog: log + trace when one fused step (dispatch +
      # result fetch) exceeds this wall-clock deadline (0 = off).  The
      # step is not interrupted — observability, like the fit() one.
      "resilience.step_timeout_s": 0.0,
      # --- replicated serving control plane (serving/router.py,
      # docs/serving.md "Multi-replica serving").  N engine replicas —
      # each with its own mesh/engine, sharing nothing but the params
      # source — behind a health-checked Router: bit-exact failover of
      # queued AND in-flight requests via the prefix-replay path,
      # graceful drain + warm rejoin, prefix-affinity + least-loaded
      # dispatch degrading to round-robin on stale signals.
      "router.replicas": 1,
      # Expected heartbeat interval: each completed replica step beats;
      # load signals older than 2x this are considered stale (dispatch
      # degrades to round-robin).
      "router.heartbeat_s": 1.0,
      # Heartbeat age that moves a replica healthy -> suspect (no new
      # dispatch, existing work continues) and suspect -> down (its
      # requests fail over to survivors).  suspect_after <= down_after.
      "router.suspect_after": 3.0,
      "router.down_after": 10.0,
      # Graceful drain: a draining replica gets this long to finish its
      # active requests before the leftovers are migrated to survivors
      # (0 = migrate immediately).
      "router.drain_timeout_s": 30.0,
      # Prefix-affinity dispatch: route requests sharing a prompt prefix
      # to the replica that served it last (warm KV / prefix-cache
      # locality), load permitting.  Off = pure least-loaded.
      "router.affinity": True,
      # --- replica transports (serving/transport.py, docs/serving.md
      # "Replica transports").  "inproc" (default) hosts replicas in
      # the router's process, byte-for-byte the PR-8 behavior;
      # "process" spawns each replica as a subprocess owning its own
      # JAX runtime (the REAL fault domain: SIGKILL-survivable
      # failover via the router-side journal, wire-level timeouts,
      # idempotent retries).  Process mode needs a Router(factory=...)
      # spec ("module:attr" building (model, params) in the child).
      "router.transport": "inproc",
      # Per-RPC wire deadline.  Generous by default — a child's first
      # step carries XLA compilation; chaos tests tighten it.  A STEP
      # that misses the deadline condemns the replica (fenced with
      # SIGKILL at evacuation) because steps are not idempotent.
      "router.rpc_timeout_s": 30.0,
      # Idempotent-call retries (submit/restore/cancel/snapshot) after
      # the first attempt, with jittered exponential backoff from
      # rpc_backoff_s.  Retried submits cannot double-admit: the child
      # dedups by uid.
      "router.rpc_retries": 2,
      "router.rpc_backoff_s": 0.05,
      # Deadline for a spawned child to import JAX, build its engine
      # from the factory, and answer the init frame.
      "router.spawn_timeout_s": 120.0,
      # --- reactor router core (serving/reactor.py, docs/serving.md
      # "Front door").  Readiness-driven dispatch: each live replica
      # gets its next step the moment its previous reply lands
      # (selectors over the process transport's socket; in-process
      # replicas through a queue-backed readiness shim), so one slow
      # replica no longer gates the fleet.  Consumed by router.run()
      # and the front door's driver; router.step() stays the lock-step
      # sweep either way (simulator / replay compatibility).
      "router.reactor": False,
      # Per-replica step quota inside one reactor cycle: a fast replica
      # may run up to this many steps while a slow peer finishes one;
      # control-plane actions (autoscale/rollout/drain/parked flush)
      # still land only at cycle boundaries — the same mutation-safety
      # contract as the sweep.
      "router.reactor_max_steps": 4,
      # --- streaming front door (serving/frontdoor/, docs/serving.md
      # "Front door").  A stdlib HTTP/1.1 server exposing POST
      # /v1/generate with SSE token streaming — tokens surface per
      # engine iteration as they commit (scheduler.on_tokens), never
      # by polling `finished` — plus per-connection backpressure and
      # cancel-on-disconnect wired to the router's cancel(uid).
      "frontdoor.host": "127.0.0.1",
      # 0 = ephemeral: the OS picks a free port; FrontDoor.address
      # reports the bound one (tests and the bench always use this).
      "frontdoor.port": 0,
      # Per-connection bounded buffer, in SSE token events: a slow
      # reader's flow queues up to this many undelivered events, then
      # its request is cancelled (finish_reason "cancelled", SSE
      # `shed` terminal) — backpressure sheds ONLY that flow, never
      # the fleet.
      "frontdoor.stream_buffer": 64,
      # Per-connection socket write deadline: a reader that keeps a
      # write blocked this long is treated as disconnected (its flow
      # cancelled), bounding a handler thread's stall.
      "frontdoor.write_timeout_s": 10.0,
      # SSE keepalive comment cadence while a stream is idle — also
      # the cancel-on-disconnect probe: a dropped client surfaces as
      # the keepalive write failing.
      "frontdoor.keepalive_s": 2.0,
      # --- engine autotuner (serving/autotune.py, docs/robustness.md
      # "Self-healing fleet").  An SLO-breach-driven actuator that moves
      # DATA-VALUED knobs between fused steps — speculation-k clamp,
      # prefill-budget clamp, effective slot cap, degradation-ladder
      # floor — under the compile-once constraint (never a shape).
      # Needs observability.slo.enabled to hear breaches.
      "autotune.enabled": False,
      # Clean engine steps (no matching breach) before the autotuner
      # releases ONE level — hysteretic recovery mirroring the
      # admission ladder, so a stale breach cannot pin the engine slow.
      "autotune.hold_steps": 50,
      # Highest tune level the autotuner may reach (1 = spec_trim,
      # 2 = budget_tight, 3 = slot_cap; see serving/autotune.py).
      "autotune.max_level": 3,
      # Effective-slot-cap floor at the slot_cap level: the autotuner
      # never clamps concurrency below this many slots.
      "autotune.min_slots": 1,
      # Prefill-budget clamp at budget_tight and above, in chunks:
      # effective budget = budget_chunks * prefill_chunk.
      "autotune.budget_chunks": 1,
      # --- fleet autoscaler (serving/autoscale.py, docs/robustness.md
      # "Self-healing fleet").  SLO-burn-driven replica-set policy over
      # the router's existing drain()/rejoin()/add_replica() levers:
      # grow on sustained fast+slow-window burn, shrink via graceful
      # drain once the budget recovers.  Needs observability.slo.enabled.
      "autoscale.enabled": False,
      # Live-replica-set bounds (live = healthy + suspect).
      "autoscale.min_replicas": 1,
      "autoscale.max_replicas": 4,
      # Cooldown after a scale-up before the next one (the base of the
      # flap breaker's doubling hold-out), and the quiet period (no
      # relevant breach) required before a scale-down.
      "autoscale.scale_up_cooldown_s": 5.0,
      "autoscale.scale_down_cooldown_s": 30.0,
      # A scale-up this soon after a scale-down counts as a flap and
      # doubles the scale-up hold-out (trip decay after a clean window),
      # reusing the replica breaker's doubling-hold-out shape.
      "autoscale.flap_window_s": 60.0,
      # Extra SLO rule names (beyond every burn-rate rule, which always
      # actuates) whose breaches trigger scale-up, e.g. "ttft_p99".
      "autoscale.rules": (),
      # Spawn replicas synchronously inside on_step() instead of on the
      # router's spawn thread.  Deterministic (replay/simulation) at the
      # cost of blocking the sweep for the spawn's duration; the async
      # path stays the production default.
      "autoscale.sync_spawn": False,
      # Predictive scale-up (promoted from fleet simulation, see
      # docs/simulator.md): sample the router's cumulative submitted
      # count, estimate the arrival-rate slope over this window as
      # (late-half rate - early-half rate) / (window/2), and scale up
      # BEFORE the burn-rate breach when the slope exceeds the
      # threshold below.  0 slope = rule off (the repo-wide idiom).
      "autoscale.predictive_window_s": 1.0,
      # Arrival-rate slope threshold in requests/s per second.  Tune
      # via `make sim-bench`; must stay high enough that steady
      # fault-free traffic (slope ~ 0) never fires it.
      "autoscale.predictive_slope": 0.0,
      # --- blue/green checkpoint rollout (serving/rollout.py,
      # docs/robustness.md "Blue/green rollout").  A RolloutController
      # on the router ships checkpoint N+1 under live traffic: validate
      # the checkpoint, spawn green replicas off the sweep thread,
      # shift admission weight green-ward in stages (canary fraction
      # first, watched by version-scoped SLO breach streams), then cut
      # over and drain blue complete-in-place — with automatic
      # rollback (drain green, restore blue weights) on any
      # canary-scoped breach or green spawn failure.
      "rollout.enabled": False,
      # Admission-weight fraction routed to green during the canary
      # stage (the rest stays on blue).
      "rollout.canary_frac": 0.1,
      # How long the canary stage must run breach-free before full
      # cutover.
      "rollout.canary_hold_s": 10.0,
      # Blues below this live count are never drained mid-rollout (the
      # fleet's capacity floor while green capacity is still unproven).
      "rollout.min_replicas": 1,
      # Deadline for ALL green replicas to spawn + init; exceeded =
      # rollback (greens drained, blue weights restored).
      "rollout.spawn_timeout_s": 300.0,
      # Graceful-drain window for blue replicas after cutover (their
      # in-flight requests complete in place; leftovers past the
      # window migrate — only ever to a same-version survivor).
      "rollout.drain_timeout_s": 30.0,
      # Extra SLO rule names (beyond every burn-rate rule) whose
      # green-scoped breaches roll the canary back, e.g. "ttft_p99".
      "rollout.rules": (),
  }

  @property
  def speculative(self) -> _SubGroup:
    return _SubGroup(self, "speculative")

  @property
  def paged(self) -> _SubGroup:
    return _SubGroup(self, "paged")

  @property
  def prefix_cache(self) -> _SubGroup:
    return _SubGroup(self, "prefix_cache")

  @property
  def resilience(self) -> _SubGroup:
    return _SubGroup(self, "resilience")

  @property
  def router(self) -> _SubGroup:
    return _SubGroup(self, "router")

  @property
  def frontdoor(self) -> _SubGroup:
    return _SubGroup(self, "frontdoor")

  @property
  def autotune(self) -> _SubGroup:
    return _SubGroup(self, "autotune")

  @property
  def autoscale(self) -> _SubGroup:
    return _SubGroup(self, "autoscale")

  @property
  def rollout(self) -> _SubGroup:
    return _SubGroup(self, "rollout")


class ObservabilityConfig(_Category):
  """Unified tracing & telemetry (observability/, docs/observability.md).
  New vs the reference, whose observability is re-pointed TF summaries
  plus RunMetadata FULL_TRACE capture (epl/parallel/hooks.py:593-664)."""
  _name = "observability"
  _fields = {
      # Master switch for the host-side span tracer: fit() and the
      # serving engine record phase spans / per-request timelines into
      # the ambient tracer (observability.trace.get_tracer()).  Off by
      # default; when off every instrumentation site is a no-op context
      # manager (no allocation, no host sync).
      "enabled": False,
      # Where fit() exports the Chrome-trace / Perfetto JSON at the end
      # of a run ("" = <checkpoint_dir>/trace.json when a checkpoint dir
      # is set, else no auto-export).  Serving callers export explicitly
      # via get_tracer().export(path).  Load at ui.perfetto.dev.
      "trace_path": "",
      # Ring-buffer capacity in EVENTS (a span is two events).  The ring
      # keeps the most recent window and counts what it evicted — a
      # bounded-memory flight recorder, not a full-run archive.
      "ring_capacity": 65536,
      # Sampling for the per-step train-loop phase spans (data-next /
      # step-dispatch / metrics-flush): record every 1/sample_rate-th
      # step's phases.  Request-lifecycle, checkpoint, and resilience
      # events are never sampled.  1.0 records everything.
      "sample_rate": 1.0,
      # When fit() gets a checkpoint_dir but no metrics_writer,
      # auto-construct a leader-only JSONL MetricsWriter at
      # <checkpoint_dir>/metrics.jsonl behind a namespaced
      # MetricRegistry (train/* + resilience/* keys), so runs are never
      # silently unlogged.  An explicitly passed writer always wins.
      "metrics_jsonl": True,
      # --- SLO monitoring & anomaly-triggered deep capture
      # (observability/slo.py, docs/observability.md "SLO monitoring").
      # Master switch: the serving engine and router build/attach the
      # ambient SLOMonitor at entry when on; every breach/recovery is a
      # slo_events.jsonl line + slo/breach trace instant + listener
      # callback.  Off keeps every record path byte-identical.
      "slo.enabled": False,
      # Machine-readable breach/recovery log ("" = memory + trace only).
      "slo.events_path": "",
      # Threshold rules (0 = rule off).  Bare-name metric matching:
      # each target evaluates against the fleet rollup, every
      # serving/replica<i>/* record, AND a bare engine's serving/*
      # records, as separate breach streams.
      "slo.ttft_p99_s": 0.0,
      "slo.itl_p99_s": 0.0,
      # Shed-rate error budget: promised non-shed fraction (e.g. 0.99 =
      # at most 1% of requests may shed; 0 = rule off), evaluated as
      # multi-window burn rates over the last fast_window / slow_window
      # records — both must exceed their thresholds to breach.
      "slo.shed_objective": 0.0,
      "slo.fast_window": 5,
      "slo.slow_window": 20,
      "slo.fast_burn": 10.0,
      "slo.slow_burn": 2.0,
      # Fleet availability rule: any replicas_down > 0 in the fleet
      # rollup is a breach window (the failover acceptance signal).
      "slo.replicas_down": True,
      # Anomaly-triggered deep capture: on breach / watchdog fire /
      # recompile, dump a bounded diagnostic bundle (tracer ring tail,
      # registry snapshot, scheduler state summary) into this dir
      # ("" = capture off), staged + atomically renamed, keeping at
      # most capture_limit bundles and at most one per
      # capture_min_interval_s (a flapping fleet cannot fill the disk).
      "slo.capture_dir": "",
      "slo.capture_limit": 8,
      "slo.capture_min_interval_s": 30.0,
      "slo.capture_ring_tail": 2048,
      # Also arm a jax.profiler device capture around the NEXT fused
      # step after an ENGINE-ATTRIBUTED breach (recompile / watchdog —
      # the payload's twin names the engine; fleet-level rule breaches
      # arm nothing, lest one kill device-profile every healthy
      # replica).  Written under <bundle>/xla.  Off by default: device
      # captures are heavy.
      "slo.capture_xla": False,
      # Breach when any local device's bytes_in_use / bytes_limit
      # exceeds this fraction (0 = rule off).  Fed by the device
      # introspector's HBM gauges (observability/device.py) — only
      # backends whose memory_stats() reports a limit ever produce the
      # hbm_frac metric, so the rule is naturally inert on CPU.
      "slo.hbm_frac": 0.0,
      # --- Device-truth introspection (observability/device.py,
      # docs/observability.md "Device truth").  Master switch: at
      # warmup every compiled twin's cost/memory analysis is captured
      # into a CostCard (flops, wire bytes per overlap site, static HBM
      # plan, donation-verified), HBM watermark gauges ride the serving
      # stats cadence, and measured per-site collective bytes feed the
      # overlap planner automatically.  Off by default: capture pays
      # one extra (AOT) compile per twin at warmup.
      "device.enabled": False,
      # Sample jax.local_devices()[i].memory_stats() (static cost-card
      # bound where unavailable) into observability/device/* gauges +
      # Perfetto counters on the engine's stats cadence.
      "device.hbm_gauges": True,
      # Feed introspector-measured per-SITE collective bytes into
      # communicators.overlap.resolve_num_chunks (analytic fallback
      # preserved; ROADMAP item 5c).
      "device.site_feed": True,
      # Also dump every captured cost card to this JSON path (atomic
      # rewrite per capture; "" = memory only).
      "device.cards_path": "",
      # --- Cross-process trace harvest (docs/observability.md
      # "Distributed tracing").  With observability.enabled on a
      # process-transport fleet, each child replica drains its tracer
      # ring over the wire (bounded chunks piggybacked on step replies
      # + a final flush on clean shutdown/evacuation) and the parent
      # merges the rebased events into one Perfetto export.  Off keeps
      # child rings child-local (the pre-distributed behaviour).
      "harvest.enabled": True,
      # Encoded-byte bound per harvest sweep: one step reply carries at
      # most this many bytes of trace events, so harvest can never
      # stall dispatch; the remainder rides later sweeps.
      "harvest.max_bytes_per_sweep": 65536,
      # Deadline for the explicit full-ring drain (`harvest` RPC loop)
      # used by Router.harvest_traces() and the final flush paths.
      "harvest.final_timeout_s": 5.0,
  }

  @property
  def slo(self) -> _SubGroup:
    return _SubGroup(self, "slo")

  @property
  def device(self) -> _SubGroup:
    return _SubGroup(self, "device")

  @property
  def harvest(self) -> _SubGroup:
    return _SubGroup(self, "harvest")


class SimConfig(_Category):
  """Cost-card fleet simulator (easyparallellibrary_tpu/sim/,
  docs/simulator.md).  Every knob feeds the discrete-event episode
  builder only — nothing here is read by live serving."""
  _name = "sim"
  _fields = {
      # Seed for the simulator's xorshift RNG (arrivals, prompt shapes,
      # fault draws).  Same seed + same config = bit-identical episode.
      "seed": 0,
      # Fleet size for a sweep episode (the replay harness takes its
      # size from the recorded episode instead).
      "replicas": 100,
      # Simulated episode length in virtual seconds.
      "duration_s": 60.0,
      # Arrival trace shape: poisson | zipf | diurnal | overload
      # (sim/arrivals.py; diurnal modulates a Poisson base rate by a
      # day-curve, overload reuses testing/chaos.py's burst shape).
      "trace": "diurnal",
      # Mean arrival rate in requests/s across the whole fleet
      # (0 = derive from the fleet's modeled capacity: ~70% of
      # aggregate decode throughput, so default sweeps run loaded but
      # not saturated).
      "arrival_rate_rps": 0.0,
      # SimReplica step-cost physics, seconds per token.  0 = calibrate
      # from the newest hardware-provenance serving record in
      # BENCH_EVIDENCE.json (sim/replica.py::calibrate); set explicitly
      # to model other hardware from its cost card.
      "prefill_token_cost_s": 0.0,
      "decode_token_cost_s": 0.0,
      # Fixed per-step host overhead (dispatch, bookkeeping) added to
      # every modeled step.
      "step_overhead_s": 5e-5,
      # Fault injector: virtual seconds a simulated spawn takes before
      # the new replica lands (0 = spawns land on the next sweep).
      "spawn_delay_s": 0.0,
  }


class Config:
  """Root configuration (reference: epl/config.py:181).

  Accepts a flat dict with dotted keys (EPL style), e.g.::

      Config({"pipeline.num_micro_batch": 4, "zero.level": "v1"})

  or a nested dict ``{"pipeline": {"num_micro_batch": 4}}``.
  """

  _categories: Tuple[type, ...] = (
      AutoParallelConfig, IOConfig, CommunicationConfig, PipelineConfig,
      GradientCheckpointConfig, ZeroConfig, OffloadConfig, AMPConfig,
      ClusterConfig, OptimizerConfig, SequenceConfig, ResilienceConfig,
      ServingConfig, ObservabilityConfig, SimConfig,
  )

  def __init__(self, param_dict: Dict[str, Any] | None = None):
    by_cat: Dict[str, Dict[str, Any]] = {c._name: {} for c in self._categories}
    for key, value in (param_dict or {}).items():
      if isinstance(value, dict):
        cat, sub = key, value
        if cat not in by_cat:
          raise ValueError(f"Unknown config category '{cat}'")
        by_cat[cat].update(sub)
      else:
        if "." not in key:
          raise ValueError(
              f"Config key '{key}' must be '<category>.<attr>' or a nested "
              f"dict. Categories: {sorted(by_cat)}")
        cat, attr = key.split(".", 1)
        if cat not in by_cat:
          raise ValueError(f"Unknown config category '{cat}' in key '{key}'")
        by_cat[cat][attr] = value
    for cls in self._categories:
      object.__setattr__(self, cls._name, cls(by_cat[cls._name]))
    self.validate()

  def __setattr__(self, key, value):
    raise AttributeError(
        "Config categories are fixed; set leaves like "
        "`config.pipeline.num_micro_batch = 4` instead.")

  def validate(self):
    """Cross-field validation (reference: epl/config.py:301-305)."""
    from easyparallellibrary_tpu.utils.logging import get_logger
    if self.communication.sparse_as_dense:
      # Accepted for API parity but a no-op here: JAX gradients are always
      # dense arrays (the reference converts IndexedSlices,
      # epl/parallel/hooks.py:161-167).  Warn loudly so nobody believes
      # the knob did something (VERDICT round-1 weak item 6).
      get_logger().warning(
          "communication.sparse_as_dense=True has NO effect on TPU: JAX "
          "gradients are always dense; the knob exists only for config "
          "compatibility with the reference.")
    if self.gradient_checkpoint.end_taskgraph != -1:
      get_logger().warning(
          "gradient_checkpoint.end_taskgraph=%s has NO effect: remat is "
          "applied per block/stage (gradient_checkpoint.type, "
          "GPTConfig.remat), not per taskgraph index; the knob exists "
          "only for config compatibility with the reference.",
          self.gradient_checkpoint.end_taskgraph)
    if self.zero.level not in ("", constants.ZERO_V0, constants.ZERO_V1):
      raise ValueError(f"zero.level must be '', 'v0' or 'v1'; "
                       f"got {self.zero.level!r}")
    if self.offload.level not in ("", constants.OFFLOAD_V0):
      raise ValueError(f"offload.level must be '' or 'v0'; "
                       f"got {self.offload.level!r}")
    if self.amp.level not in ("", constants.AMP_O0, constants.AMP_O1):
      raise ValueError(f"amp.level must be '', 'O0' or 'O1'; "
                       f"got {self.amp.level!r}")
    if self.amp.compute_dtype not in ("bf16", "fp16"):
      raise ValueError(f"amp.compute_dtype must be 'bf16' or 'fp16'; "
                       f"got {self.amp.compute_dtype!r}")
    if self.gradient_checkpoint.type not in (
        "", constants.GC_COLLECTION, constants.GC_AUTO):
      raise ValueError("gradient_checkpoint.type must be '', 'collection' "
                       f"or 'auto'; got {self.gradient_checkpoint.type!r}")
    if self.sequence.parallelism not in (
        "", constants.SEQ_PARALLEL_RING, constants.SEQ_PARALLEL_ULYSSES):
      raise ValueError("sequence.parallelism must be '', 'ring' or "
                       f"'ulysses'; got {self.sequence.parallelism!r}")
    if self.sequence.ring_impl not in ("flash", "einsum", "dense"):
      raise ValueError("sequence.ring_impl must be 'flash', 'einsum' or "
                       f"'dense'; got {self.sequence.ring_impl!r}")
    if self.sequence.ulysses_impl not in ("flash", "einsum"):
      raise ValueError("sequence.ulysses_impl must be 'flash' or "
                       f"'einsum'; got {self.sequence.ulysses_impl!r}")
    if self.sequence.ring_layout not in ("contiguous", "zigzag"):
      raise ValueError("sequence.ring_layout must be 'contiguous' or "
                       f"'zigzag'; got {self.sequence.ring_layout!r}")
    if self.pipeline.num_micro_batch < 1:
      raise ValueError("pipeline.num_micro_batch must be >= 1")
    if self.pipeline.num_stages < 1:
      raise ValueError("pipeline.num_stages must be >= 1")
    if self.pipeline.engine not in ("", "vmap", "smap"):
      raise ValueError("pipeline.engine must be '', 'vmap' or 'smap'; "
                       f"got {self.pipeline.engine!r}")
    if self.communication.gradients_reduce_method not in ("mean", "sum"):
      raise ValueError("communication.gradients_reduce_method must be "
                       "'mean' or 'sum'")
    if self.communication.compress_dtype not in ("", "bf16", "fp16"):
      raise ValueError("communication.compress_dtype must be '', 'bf16' "
                       f"or 'fp16'; got {self.communication.compress_dtype!r}")
    if self.communication.overlap not in ("auto", "on", "off"):
      raise ValueError("communication.overlap must be 'auto', 'on' or "
                       f"'off'; got {self.communication.overlap!r}")
    if self.communication.overlap_chunks < 0:
      raise ValueError("communication.overlap_chunks must be >= 0; got "
                       f"{self.communication.overlap_chunks}")
    for field in ("keep_last", "max_bad_steps", "io_retries"):
      if getattr(self.resilience, field) < 0:
        raise ValueError(f"resilience.{field} must be >= 0; got "
                         f"{getattr(self.resilience, field)}")
    for field in ("io_retry_backoff_s", "step_timeout_s"):
      if getattr(self.resilience, field) < 0:
        raise ValueError(f"resilience.{field} must be >= 0; got "
                         f"{getattr(self.resilience, field)}")
    if not 0 < self.resilience.rollback_lr_backoff <= 1:
      raise ValueError("resilience.rollback_lr_backoff must be in (0, 1]; "
                       f"got {self.resilience.rollback_lr_backoff}")
    if self.serving.num_slots < 1:
      raise ValueError(f"serving.num_slots must be >= 1; "
                       f"got {self.serving.num_slots}")
    if self.serving.prefill_chunk < 1:
      raise ValueError(f"serving.prefill_chunk must be >= 1; "
                       f"got {self.serving.prefill_chunk}")
    if self.serving.prefill_token_budget < 0:
      raise ValueError(f"serving.prefill_token_budget must be >= 0; "
                       f"got {self.serving.prefill_token_budget}")
    if 0 < self.serving.prefill_token_budget < self.serving.prefill_chunk:
      raise ValueError(
          "serving.prefill_token_budget must be 0 (uncapped) or >= "
          f"serving.prefill_chunk ({self.serving.prefill_chunk}); a "
          "smaller budget could never afford any request's first chunk; "
          f"got {self.serving.prefill_token_budget}")
    if self.serving.max_batch < 0:
      raise ValueError(f"serving.max_batch must be >= 0; "
                       f"got {self.serving.max_batch}")
    if self.serving.stop_token < -1:
      raise ValueError(f"serving.stop_token must be >= -1; "
                       f"got {self.serving.stop_token}")
    if self.serving.finished_limit < 0:
      raise ValueError(f"serving.finished_limit must be >= 0 (0 = keep "
                       f"all); got {self.serving.finished_limit}")
    paged = self.serving.paged
    if paged.block_size < 1:
      raise ValueError(f"serving.paged.block_size must be >= 1; "
                       f"got {paged.block_size}")
    if paged.num_blocks < 0:
      raise ValueError(f"serving.paged.num_blocks must be >= 0 (0 = "
                       f"auto); got {paged.num_blocks}")
    if paged.token_budget < 0:
      raise ValueError(f"serving.paged.token_budget must be >= 0 (0 = "
                       f"auto); got {paged.token_budget}")
    pcache = self.serving.prefix_cache
    if pcache.enabled and not paged.enabled:
      raise ValueError(
          "serving.prefix_cache.enabled requires serving.paged.enabled: "
          "prefix caching shares KV at the paged layout's block "
          "granularity (engine kwargs can still combine paged=True with "
          "prefix_cache=True explicitly)")
    if pcache.session_ttl_s < 0:
      raise ValueError(f"serving.prefix_cache.session_ttl_s must be >= 0 "
                       f"(0 = no TTL); got {pcache.session_ttl_s}")
    if pcache.max_cached_blocks < 0:
      raise ValueError(f"serving.prefix_cache.max_cached_blocks must be "
                       f">= 0 (0 = uncapped); "
                       f"got {pcache.max_cached_blocks}")
    spec = self.serving.speculative
    if spec.k < 1:
      raise ValueError(
          f"serving.speculative.k must be >= 1; got {spec.k}")
    if spec.kind not in ("ngram", "draft_model"):
      raise ValueError("serving.speculative.kind must be 'ngram' or "
                       f"'draft_model'; got {spec.kind!r}")
    if not 1 <= spec.ngram_min <= spec.ngram_max:
      raise ValueError(
          "serving.speculative needs 1 <= ngram_min <= ngram_max; got "
          f"ngram_min={spec.ngram_min}, ngram_max={spec.ngram_max}")
    if self.observability.ring_capacity < 1:
      raise ValueError(f"observability.ring_capacity must be >= 1; "
                       f"got {self.observability.ring_capacity}")
    if not 0.0 < self.observability.sample_rate <= 1.0:
      raise ValueError(f"observability.sample_rate must be in (0, 1]; "
                       f"got {self.observability.sample_rate}")
    slo = self.observability.slo
    for field in ("ttft_p99_s", "itl_p99_s", "capture_min_interval_s"):
      if getattr(slo, field) < 0:
        raise ValueError(f"observability.slo.{field} must be >= 0 "
                         f"(0 = off); got {getattr(slo, field)}")
    if not 0.0 <= slo.shed_objective < 1.0:
      raise ValueError(
          f"observability.slo.shed_objective must be in [0, 1) (0 = "
          f"rule off); got {slo.shed_objective}")
    if not 1 <= slo.fast_window <= slo.slow_window:
      raise ValueError(
          f"observability.slo needs 1 <= fast_window <= slow_window; "
          f"got fast_window={slo.fast_window}, "
          f"slow_window={slo.slow_window}")
    if slo.fast_burn <= 0 or slo.slow_burn <= 0:
      raise ValueError(
          f"observability.slo burn thresholds must be > 0; got "
          f"fast_burn={slo.fast_burn}, slow_burn={slo.slow_burn}")
    if slo.capture_limit < 1:
      raise ValueError(f"observability.slo.capture_limit must be >= 1; "
                       f"got {slo.capture_limit}")
    if slo.capture_ring_tail < 1:
      raise ValueError(
          f"observability.slo.capture_ring_tail must be >= 1; got "
          f"{slo.capture_ring_tail}")
    if not 0.0 <= slo.hbm_frac < 1.0:
      raise ValueError(
          f"observability.slo.hbm_frac must be in [0, 1) (0 = rule "
          f"off); got {slo.hbm_frac}")
    harvest = self.observability.harvest
    if harvest.max_bytes_per_sweep < 1024:
      raise ValueError(
          f"observability.harvest.max_bytes_per_sweep must be >= 1024 "
          f"(one sweep must fit at least a few events); got "
          f"{harvest.max_bytes_per_sweep}")
    if harvest.final_timeout_s <= 0:
      raise ValueError(
          f"observability.harvest.final_timeout_s must be > 0; got "
          f"{harvest.final_timeout_s}")
    if spec.enabled and spec.k + 1 > self.serving.prefill_chunk:
      raise ValueError(
          f"serving.speculative.k={spec.k} needs serving.prefill_chunk "
          f">= k + 1 (the fused step carries each decode slot's last "
          f"committed token plus its k drafts in one chunk); got "
          f"prefill_chunk {self.serving.prefill_chunk}")
    res = self.serving.resilience
    if res.queue_limit < 0:
      raise ValueError(f"serving.resilience.queue_limit must be >= 0 "
                       f"(0 = unbounded); got {res.queue_limit}")
    if res.itl_slo_s < 0:
      raise ValueError(f"serving.resilience.itl_slo_s must be >= 0 "
                       f"(0 = off); got {res.itl_slo_s}")
    if not 0.0 < res.degrade_queue_frac <= 1.0:
      raise ValueError(
          f"serving.resilience.degrade_queue_frac must be in (0, 1]; "
          f"got {res.degrade_queue_frac}")
    if res.max_step_retries < 0 or res.max_requeues < 0:
      raise ValueError(
          "serving.resilience.max_step_retries and max_requeues must be "
          f">= 0; got {res.max_step_retries}, {res.max_requeues}")
    if res.step_timeout_s < 0:
      raise ValueError(f"serving.resilience.step_timeout_s must be >= 0 "
                       f"(0 = off); got {res.step_timeout_s}")
    router = self.serving.router
    if router.replicas < 1:
      raise ValueError(f"serving.router.replicas must be >= 1; "
                       f"got {router.replicas}")
    if router.heartbeat_s <= 0:
      raise ValueError(f"serving.router.heartbeat_s must be > 0; "
                       f"got {router.heartbeat_s}")
    if not 0 < router.suspect_after <= router.down_after:
      raise ValueError(
          f"serving.router.suspect_after must be > 0 and <= down_after "
          f"(a replica cannot go down before it goes suspect); got "
          f"suspect_after={router.suspect_after}, "
          f"down_after={router.down_after}")
    if router.transport not in ("inproc", "process"):
      raise ValueError(
          f"serving.router.transport must be 'inproc' or 'process'; "
          f"got {router.transport!r}")
    if router.rpc_timeout_s <= 0:
      raise ValueError(f"serving.router.rpc_timeout_s must be > 0; "
                       f"got {router.rpc_timeout_s}")
    if router.rpc_retries < 0:
      raise ValueError(f"serving.router.rpc_retries must be >= 0; "
                       f"got {router.rpc_retries}")
    if router.rpc_backoff_s < 0:
      raise ValueError(f"serving.router.rpc_backoff_s must be >= 0; "
                       f"got {router.rpc_backoff_s}")
    if router.spawn_timeout_s <= 0:
      raise ValueError(f"serving.router.spawn_timeout_s must be > 0; "
                       f"got {router.spawn_timeout_s}")
    if router.reactor_max_steps < 1:
      raise ValueError(f"serving.router.reactor_max_steps must be >= 1; "
                       f"got {router.reactor_max_steps}")
    frontdoor = self.serving.frontdoor
    if not 0 <= frontdoor.port <= 65535:
      raise ValueError(f"serving.frontdoor.port must be in [0, 65535]; "
                       f"got {frontdoor.port}")
    if frontdoor.stream_buffer < 1:
      raise ValueError(f"serving.frontdoor.stream_buffer must be >= 1; "
                       f"got {frontdoor.stream_buffer}")
    if frontdoor.write_timeout_s <= 0:
      raise ValueError(f"serving.frontdoor.write_timeout_s must be > 0; "
                       f"got {frontdoor.write_timeout_s}")
    if frontdoor.keepalive_s <= 0:
      raise ValueError(f"serving.frontdoor.keepalive_s must be > 0; "
                       f"got {frontdoor.keepalive_s}")
    if router.drain_timeout_s < 0:
      raise ValueError(f"serving.router.drain_timeout_s must be >= 0 "
                       f"(0 = migrate immediately); got "
                       f"{router.drain_timeout_s}")
    tune = self.serving.autotune
    if tune.hold_steps < 1:
      raise ValueError(f"serving.autotune.hold_steps must be >= 1; "
                       f"got {tune.hold_steps}")
    if not 0 <= tune.max_level <= 3:
      raise ValueError(f"serving.autotune.max_level must be in [0, 3]; "
                       f"got {tune.max_level}")
    if tune.min_slots < 1:
      raise ValueError(f"serving.autotune.min_slots must be >= 1; "
                       f"got {tune.min_slots}")
    if tune.budget_chunks < 1:
      raise ValueError(
          f"serving.autotune.budget_chunks must be >= 1 (a smaller "
          f"clamp could never afford any request's first chunk); got "
          f"{tune.budget_chunks}")
    scale = self.serving.autoscale
    if not 1 <= scale.min_replicas <= scale.max_replicas:
      raise ValueError(
          f"serving.autoscale needs 1 <= min_replicas <= max_replicas; "
          f"got min_replicas={scale.min_replicas}, "
          f"max_replicas={scale.max_replicas}")
    for field in ("scale_up_cooldown_s", "scale_down_cooldown_s",
                  "flap_window_s", "predictive_slope"):
      if getattr(scale, field) < 0:
        raise ValueError(f"serving.autoscale.{field} must be >= 0; "
                         f"got {getattr(scale, field)}")
    if scale.predictive_window_s <= 0:
      raise ValueError(
          f"serving.autoscale.predictive_window_s must be > 0 (the "
          f"slope estimate divides by it); got "
          f"{scale.predictive_window_s}")
    sim = self.sim
    if sim.replicas < 1:
      raise ValueError(f"sim.replicas must be >= 1; got {sim.replicas}")
    if sim.duration_s <= 0:
      raise ValueError(f"sim.duration_s must be > 0; got {sim.duration_s}")
    if sim.trace not in ("poisson", "zipf", "diurnal", "overload"):
      raise ValueError(
          f"sim.trace must be one of poisson/zipf/diurnal/overload; "
          f"got {sim.trace!r}")
    for field in ("arrival_rate_rps", "prefill_token_cost_s",
                  "decode_token_cost_s", "step_overhead_s",
                  "spawn_delay_s"):
      if getattr(sim, field) < 0:
        raise ValueError(f"sim.{field} must be >= 0; "
                         f"got {getattr(sim, field)}")
    roll = self.serving.rollout
    if not 0.0 < roll.canary_frac <= 1.0:
      raise ValueError(
          f"serving.rollout.canary_frac must be in (0, 1] (a zero "
          f"canary never observes green under load); got "
          f"{roll.canary_frac}")
    if roll.min_replicas < 1:
      raise ValueError(f"serving.rollout.min_replicas must be >= 1; "
                       f"got {roll.min_replicas}")
    for field in ("canary_hold_s", "drain_timeout_s"):
      if getattr(roll, field) < 0:
        raise ValueError(f"serving.rollout.{field} must be >= 0; "
                         f"got {getattr(roll, field)}")
    if roll.spawn_timeout_s <= 0:
      raise ValueError(f"serving.rollout.spawn_timeout_s must be > 0; "
                       f"got {roll.spawn_timeout_s}")

  def to_dict(self) -> Dict[str, Dict[str, Any]]:
    return {c._name: getattr(self, c._name).to_dict()
            for c in self._categories}

  def __repr__(self):
    return f"Config({self.to_dict()})"
