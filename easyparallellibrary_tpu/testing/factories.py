"""Replica factories: deterministic ``(model, params)`` builders that a
:class:`~easyparallellibrary_tpu.serving.transport.ProcessTransport`
child can import by name.

A process-hosted replica owns its own JAX runtime, so live model/params
objects never cross the wire — instead the parent ships a factory spec
(``"module:attr"`` + JSON kwargs) and BOTH sides build from it: the
child for serving, the parent for its bit-exactness oracle.  Factories
must therefore be **deterministic in their kwargs** (fixed PRNG seed,
no ambient state): identical kwargs on the same backend yield
bit-identical params in every process, which is what makes
cross-process failover exactly as bit-exact as the in-process kind.

Used by ``make chaos-proc`` (tests/test_serving_transport.py) and the
process half of ``make router-bench`` (benchmarks/router_failover.py).
"""

from __future__ import annotations

from typing import Tuple


def tiny_gpt(vocab_size: int = 64, num_layers: int = 2,
             num_heads: int = 4, d_model: int = 32, d_ff: int = 64,
             max_seq_len: int = 32, init_len: int = 4,
             seed: int = 0) -> Tuple[object, object]:
  """The chaos/bench workhorse: a tiny fp32 GPT with params initialized
  from ``PRNGKey(seed)`` — small enough that a child process compiles
  its fused step in seconds, big enough that greedy streams are
  non-trivial."""
  import jax
  import jax.numpy as jnp

  from easyparallellibrary_tpu.models import GPT, GPTConfig

  cfg = GPTConfig(vocab_size=vocab_size, num_layers=num_layers,
                  num_heads=num_heads, d_model=d_model, d_ff=d_ff,
                  max_seq_len=max_seq_len, dtype=jnp.float32)
  model = GPT(cfg)
  params = model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, init_len), jnp.int32))["params"]
  return model, params
