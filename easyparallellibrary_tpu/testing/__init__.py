"""Test-support utilities, including the fault-injection harness
(:mod:`easyparallellibrary_tpu.testing.chaos`)."""

from easyparallellibrary_tpu.testing import chaos  # noqa: F401
