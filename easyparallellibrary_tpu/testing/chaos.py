"""Fault-injection harness — the adversary the resilience layer is
tested against.

Every fault class the resilience subsystem claims to survive has an
injector here, so `tests/test_resilience.py` (and `make chaos`) can
exercise the real recovery paths instead of mocking them:

* checkpoint corruption — :func:`corrupt_shard`, :func:`corrupt_index`
  (bit-flip / truncate / delete, after the save committed);
* numeric poison — :class:`NaNInjector` (NaN batches at chosen steps),
  :func:`nan_batch`;
* transient IO — :class:`FlakyIterator` (data `next()` raising
  `IOError` N times before succeeding), :func:`flaky` (same for any
  callable);
* preemption — :class:`SigtermInjector` (deliver SIGTERM to the current
  process mid-`fit`, from inside the data stream).

These mutate real files and deliver real signals; none of them are
imported by library code.
"""

from __future__ import annotations

import os
import signal as _signal
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np


# -------------------------------------------------- checkpoint corruption --


def _shard_files(ckpt_dir: str) -> list:
  names = sorted(f for f in os.listdir(ckpt_dir) if f.endswith(".npz"))
  if not names:
    raise FileNotFoundError(f"no shard files under {ckpt_dir}")
  return names


def corrupt_shard(ckpt_dir: str, shard: int = 0, mode: str = "flip",
                  offset: int = -64) -> str:
  """Damage one committed shard file.  `mode`:

  * ``"flip"`` — XOR a byte at `offset` (bit-rot; size unchanged, so
    only the checksum can catch it),
  * ``"truncate"`` — drop the trailing half (crash mid-write on a
    non-atomic filesystem),
  * ``"delete"`` — remove the file.

  Returns the path of the damaged shard.
  """
  path = os.path.join(ckpt_dir, _shard_files(ckpt_dir)[shard])
  if mode == "delete":
    os.remove(path)
    return path
  size = os.path.getsize(path)
  if mode == "truncate":
    with open(path, "r+b") as f:
      f.truncate(max(1, size // 2))
    return path
  if mode == "flip":
    pos = offset % size
    with open(path, "r+b") as f:
      f.seek(pos)
      byte = f.read(1)
      f.seek(pos)
      f.write(bytes([byte[0] ^ 0xFF]))
    return path
  raise ValueError(f"unknown corruption mode {mode!r}")


def corrupt_index(ckpt_dir: str, mode: str = "truncate") -> str:
  """Damage a checkpoint's ``index.json``: ``"truncate"`` (the classic
  crash-mid-write artifact), ``"garbage"`` (unparsable bytes), or
  ``"delete"``."""
  path = os.path.join(ckpt_dir, "index.json")
  if mode == "delete":
    os.remove(path)
  elif mode == "truncate":
    with open(path, "r+b") as f:
      f.truncate(max(1, os.path.getsize(path) // 3))
  elif mode == "garbage":
    with open(path, "wb") as f:
      f.write(b"\x00not json\xff")
  else:
    raise ValueError(f"unknown corruption mode {mode!r}")
  return path


# ------------------------------------------------------- numeric poison --


def nan_batch(batch):
  """A copy of `batch` with every floating leaf fully NaN."""
  def poison(x):
    arr = np.asarray(x)
    if np.issubdtype(arr.dtype, np.floating):
      return np.full_like(arr, np.nan)
    return x
  return jax.tree_util.tree_map(poison, batch)


class NaNInjector:
  """Wrap a per-step batch source, poisoning chosen steps with NaNs.

  ``batch_fn(step) -> batch`` provides the clean stream; steps listed in
  `bad_steps` come out poisoned.  With ``once=True`` (default) each bad
  step is poisoned only the FIRST time it is drawn — a replay after a
  rollback sees clean data, modeling a transient corruption upstream.
  Use as a `fit` data factory: it accepts ``start_step`` so resume and
  rollback replays line the stream up with the step index.
  """

  def __init__(self, batch_fn: Callable[[int], Any],
               bad_steps: Sequence[int], num_steps: int,
               once: bool = True):
    self.batch_fn = batch_fn
    self.bad_steps = set(bad_steps)
    self.num_steps = num_steps
    self.once = once
    self.poisoned: list = []

  def __call__(self, start_step: int = 0) -> Iterator[Any]:
    def gen():
      for step in range(start_step, self.num_steps):
        batch = self.batch_fn(step)
        if step in self.bad_steps:
          if self.once:
            self.bad_steps.discard(step)
          self.poisoned.append(step)
          batch = nan_batch(batch)
        yield batch
    return gen()


# -------------------------------------------------------- transient IO --


class FlakyIterator:
  """Iterator raising a transient exception `failures` times at position
  `fail_at` before yielding that element — the data-side fault
  `fit`'s retrying `next()` must absorb."""

  def __init__(self, items: Iterable[Any], fail_at: int = 0,
               failures: int = 1,
               exc_factory: Callable[[], BaseException] = lambda:
               IOError("chaos: transient read failure")):
    self._items = list(items)
    self.fail_at = fail_at
    self.failures_left = failures
    self.exc_factory = exc_factory
    self._pos = 0

  def __iter__(self):
    return self

  def __next__(self):
    if self._pos >= len(self._items):
      raise StopIteration
    if self._pos == self.fail_at and self.failures_left > 0:
      self.failures_left -= 1
      raise self.exc_factory()
    item = self._items[self._pos]
    self._pos += 1
    return item


def flaky(fn: Callable, failures: int = 1,
          exc_factory: Callable[[], BaseException] = lambda:
          IOError("chaos: transient failure")) -> Callable:
  """Wrap `fn` to raise a transient exception on its first `failures`
  calls, then behave normally — for driving utils/retry paths."""
  state = {"left": failures}

  def wrapped(*args, **kwargs):
    if state["left"] > 0:
      state["left"] -= 1
      raise exc_factory()
    return fn(*args, **kwargs)

  wrapped.chaos_state = state
  return wrapped


# ---------------------------------------------------------- preemption --


class SigtermInjector:
  """Iterable delivering SIGTERM to the current process when batch
  `at_batch` (0-based) is drawn, then continuing to yield — so `fit`
  observes the preemption flag on its next loop iteration, finishes the
  in-flight step, checkpoints, and exits, exactly like a scheduler
  preemption."""

  def __init__(self, batch: Any, at_batch: int = 3,
               max_batches: int = 10_000):
    self.batch = batch
    self.at_batch = at_batch
    self.max_batches = max_batches
    self._drawn = 0

  def __iter__(self):
    return self

  def __next__(self):
    if self._drawn >= self.max_batches:
      raise StopIteration
    if self._drawn == self.at_batch:
      os.kill(os.getpid(), _signal.SIGTERM)
    self._drawn += 1
    return self.batch
